"""Consumer-group rebalance is at-least-once: moved partitions replay.

Three consumers split a 6-partition log. One leaves mid-stream; the
group rebalances and the survivors absorb its partitions — but commits
are tracked per consumer (reference semantics), so a partition's new
owner starts from ITS OWN last offset and re-reads records the old
owner already processed. No partition is orphaned, nothing is lost, and
the duplicates are the price: exactly the at-least-once contract
consumers must be idempotent against. Role parity:
``examples/infrastructure/consumer_group.py`` (rebalance-on-leave leg).
"""

from happysim_tpu import Entity, Event, Instant, Simulation
from happysim_tpu.components.streaming import ConsumerGroup, EventLog


class NullConsumer(Entity):
    def handle_event(self, event):
        return None


def main() -> dict:
    log = EventLog("log", num_partitions=6)
    group = ConsumerGroup("group", log, rebalance_delay=0.05)
    consumers = {name: NullConsumer(name) for name in ("c1", "c2", "c3")}
    outcome = {}

    class Driver(Entity):
        def handle_event(self, event):
            for name, entity in consumers.items():
                yield from group.join(name, entity)
            yield 0.2  # rebalances settle
            for i in range(30):
                yield from log.append(f"key{i}", i)

            # First wave: everyone polls and commits what they got.
            polled_before = 0
            for name in consumers:
                records = yield from group.poll(name, max_records=100)
                polled_before += len(records)
                commits = {}
                for record in records:
                    commits[record.partition] = max(
                        commits.get(record.partition, 0), record.offset + 1
                    )
                if commits:
                    yield from group.commit(name, commits)

            # c3 crashes out of the group; its partitions must move.
            yield from group.leave("c3")
            yield 0.2
            survivors = {
                name: partitions
                for name, partitions in group.assignments.items()
            }

            # Second wave lands entirely on the survivors.
            for i in range(30, 48):
                yield from log.append(f"key{i}", i)
            polled_after = 0
            for name in ("c1", "c2"):
                records = yield from group.poll(name, max_records=100)
                polled_after += len(records)
            outcome.update(
                polled_before=polled_before,
                polled_after=polled_after,
                survivors=survivors,
                rebalances=group.stats.rebalances,
            )
            return None

    driver = Driver("driver")
    sim = Simulation(
        entities=[log, group, driver, *consumers.values()],
        end_time=Instant.from_seconds(10.0),
    )
    sim.schedule(Event(Instant.Epoch, "go", target=driver))
    sim.run()

    assert outcome["polled_before"] == 30
    new_records = 18
    duplicates = outcome["polled_after"] - new_records
    assert duplicates > 0, "moved partitions replay records (at-least-once)"
    assert duplicates <= 30, outcome
    claimed = sorted(
        partition
        for partitions in outcome["survivors"].values()
        for partition in partitions
    )
    assert claimed == list(range(6)), "no partition orphaned after the leave"
    assert outcome["rebalances"] >= 2
    return {
        "first_wave": outcome["polled_before"],
        "second_wave": outcome["polled_after"],
        "replayed_duplicates": duplicates,
        "survivor_partitions": {
            name: len(partitions) for name, partitions in outcome["survivors"].items()
        },
        "rebalances": outcome["rebalances"],
    }


if __name__ == "__main__":
    print(main())
