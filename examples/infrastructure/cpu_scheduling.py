"""Fair-share vs priority-preemptive CPU scheduling.

Two 50ms tasks on a fair-share CPU interleave quantum-by-quantum and both
finish near 100ms; under priority preemption the high-priority task runs
first and finishes at ~50ms while the low-priority one waits. Role parity:
``examples/infrastructure/cpu_scheduling.py``.
"""

from happysim_tpu import Entity, Event, Instant, Simulation
from happysim_tpu.components.infrastructure import (
    CPUScheduler,
    FairShare,
    PriorityPreemptive,
)


class Worker(Entity):
    def __init__(self, name, cpu, work_s, priority=0):
        super().__init__(name)
        self.cpu = cpu
        self.work_s = work_s
        self.priority = priority
        self.done_at = None

    def handle_event(self, event):
        yield from self.cpu.execute(self.name, cpu_time_s=self.work_s, priority=self.priority)
        self.done_at = self.now.to_seconds()
        return None


def _run(policy, priorities):
    cpu = CPUScheduler("cpu", policy=policy, context_switch_s=0.0)
    workers = [
        Worker(f"w{i}", cpu, work_s=0.05, priority=p) for i, p in enumerate(priorities)
    ]
    sim = Simulation(entities=[cpu, *workers], end_time=Instant.from_seconds(5))
    sim.schedule([Event(Instant.Epoch, "Go", target=w) for w in workers])
    sim.run()
    return [w.done_at for w in workers]


def main() -> dict:
    fair = _run(FairShare(quantum_s=0.01), [0, 0])
    # Interleaved: both tasks straddle the full 100ms window.
    assert min(fair) > 0.05
    assert abs(max(fair) - 0.10) < 5e-3

    pri = _run(PriorityPreemptive(quantum_s=0.01), [0, 10])
    low_done, high_done = pri
    # Strict priority: the high task monopolizes the CPU (modulo at most
    # one quantum the low task grabbed before the preemption kicked in).
    assert high_done < low_done
    assert abs(high_done - 0.05) <= 0.011
    assert 0.085 <= low_done <= 0.111
    return {
        "fair_share_done": [round(x, 3) for x in fair],
        "priority_done": {"high": round(high_done, 3), "low": round(low_done, 3)},
    }


if __name__ == "__main__":
    print(main())
