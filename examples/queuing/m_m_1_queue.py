"""The canonical M/M/1 queue against its closed forms.

Poisson arrivals (lambda=8/s) into a single exponential server
(mu=10/s): utilization rho = 0.8, mean sojourn 1/(mu-lambda) = 0.5s.
Role parity: ``examples/queuing/m_m_1_queue.py`` in the reference.
"""

from happysim_tpu import ExponentialLatency, Instant, Server, Simulation, Sink, Source

LAM, MU = 8.0, 10.0


def main() -> dict:
    sink = Sink("sink")
    server = Server(
        "server", service_time=ExponentialLatency(1.0 / MU, seed=1), downstream=sink
    )
    source = Source.poisson(rate=LAM, target=server, seed=42)
    summary = Simulation(
        sources=[source], entities=[server, sink],
        end_time=Instant.from_seconds(800.0),
    ).run()

    sojourn = sink.latency_stats().mean_s
    utilization = server.busy_seconds / summary.simulated_seconds
    analytic_sojourn = 1.0 / (MU - LAM)
    assert abs(utilization - LAM / MU) < 0.05
    assert abs(sojourn - analytic_sojourn) / analytic_sojourn < 0.3
    return {
        "sojourn_s": round(sojourn, 4),
        "analytic_s": analytic_sojourn,
        "utilization": round(utilization, 3),
        "served": sink.events_received,
    }


if __name__ == "__main__":
    print(main())
