"""Least-outstanding routing beats random routing under heterogeneity.

Two server pools — one slow, one fast — behind either a random router
or a least-connections balancer: load-aware routing cuts tail latency.
Role parity: ``examples/queuing/load_aware_routing.py``.
"""

from happysim_tpu import (
    ExponentialLatency,
    Instant,
    LoadBalancer,
    RandomRouter,
    Server,
    Simulation,
    Sink,
    Source,
)
from happysim_tpu.components.load_balancer import LeastConnections


def run(balanced: bool) -> float:
    sink = Sink("sink")
    fast = Server("fast", service_time=ExponentialLatency(0.05, seed=1), downstream=sink)
    slow = Server("slow", service_time=ExponentialLatency(0.25, seed=2), downstream=sink)
    if balanced:
        router = LoadBalancer("lb", backends=[fast, slow], strategy=LeastConnections())
    else:
        router = RandomRouter("rr", targets=[fast, slow], seed=3)
    source = Source.poisson(rate=6.0, target=router, seed=4)
    Simulation(
        sources=[source], entities=[router, fast, slow, sink],
        end_time=Instant.from_seconds(300.0),
    ).run()
    return sink.latency_stats().p99_s


def main() -> dict:
    random_p99 = run(balanced=False)
    balanced_p99 = run(balanced=True)
    assert balanced_p99 < random_p99
    return {"random_p99_s": round(random_p99, 3), "least_conn_p99_s": round(balanced_p99, 3)}


if __name__ == "__main__":
    print(main())
