"""Watch a queue cross the stability boundary as load ramps.

Arrivals ramp linearly from rho=0.4 to rho=1.3 over two minutes while a
probe samples queue depth. Below saturation depth stays near its
steady-state value; once rho crosses 1, depth stops fluctuating and grows
~linearly — the probe's time series shows the knee. Role parity:
``examples/queuing/increasing_queue_depth.py``.
"""

from happysim_tpu import (
    ExponentialLatency,
    Instant,
    LinearRampProfile,
    Probe,
    Server,
    Simulation,
    Sink,
    Source,
)

MU = 10.0
DURATION = 120.0


def main() -> dict:
    sink = Sink("sink")
    server = Server(
        "srv",
        service_time=ExponentialLatency(1.0 / MU, seed=2),
        downstream=sink,
        queue_capacity=100_000,
    )
    source = Source.with_profile(
        LinearRampProfile(start_rate=4.0, end_rate=13.0, ramp_duration_s=DURATION),
        target=server,
        stop_after=DURATION,
        seed=8,
    )
    depth_probe = Probe.on(server, "queue_depth", interval_s=1.0)
    sim = Simulation(
        sources=[source],
        entities=[server, sink],
        probes=[depth_probe],
        end_time=Instant.from_seconds(DURATION),
    )
    sim.run()

    series = depth_probe.data
    early = series.between(10.0, 40.0)   # rho in [0.47, 0.70]
    late = series.between(100.0, 120.0)  # rho in [1.15, 1.30]
    assert early.max() < 30, "subcritical: depth bounded"
    assert late.mean() > 5 * max(early.mean(), 1.0), "supercritical: depth grows"
    # Monotone-ish growth after the knee: the last samples dominate.
    assert late.max() == series.max()
    return {
        "early_mean_depth": round(early.mean(), 1),
        "late_mean_depth": round(late.mean(), 1),
        "final_depth": int(series.max()),
    }


if __name__ == "__main__":
    print(main())
