"""Retry storms create a metastable overload that outlives its trigger.

A near-saturated server (rho ~ 0.95) takes a 5s outage; clients retry
failed requests. The retry load keeps the system saturated long after
the outage heals — the classic metastable failure state. Role parity:
``examples/queuing/metastable_state.py``.
"""

import random

from happysim_tpu import (
    Client,
    CrashNode,
    ExponentialLatency,
    FaultSchedule,
    FixedRetry,
    Instant,
    Server,
    Simulation,
)

RATE, HORIZON_S = 9.0, 120.0
OUTAGE_AT, OUTAGE_ENDS = 60.0, 65.0


def main() -> dict:
    server = Server(
        "api",
        service_time=ExponentialLatency(0.105, seed=3),  # rho ~ 0.95
        queue_capacity=300,
    )
    client = Client(
        "client",
        target=server,
        timeout=2.0,
        retry_policy=FixedRetry(max_attempts=4, delay_s=0.2),
    )
    faults = FaultSchedule()
    faults.add(CrashNode(entity_name="api", at=OUTAGE_AT, restart_at=OUTAGE_ENDS))

    sim = Simulation(
        entities=[client, server],
        fault_schedule=faults,
        end_time=Instant.from_seconds(HORIZON_S),
    )
    rng = random.Random(5)
    t, requests = 0.0, []
    while t < HORIZON_S:
        t += rng.expovariate(RATE)
        requests.append(client.send_request(at=Instant.from_seconds(t)))
    sim.schedule(requests)
    sim.run()

    stats = client.stats
    # The 5s outage triggers retries; the amplified load persists past
    # the heal — visible as a deep backlog and/or continued timeouts.
    assert stats.retries > 20
    assert server.queue_depth > 10 or stats.failures > 0
    return {
        "requests_sent": stats.requests_sent,
        "retries": stats.retries,
        "timeouts": stats.timeouts,
        "failures": stats.failures,
        "end_queue_depth": server.queue_depth,
    }


if __name__ == "__main__":
    print(main())
