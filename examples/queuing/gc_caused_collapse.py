"""Stop-the-world GC pauses push a near-saturated queue over the edge.

A single-worker service at rho=0.85 is stable (p99 well under 100ms). Add
a 300ms stop-the-world pause every ~50 requests and the arrivals that pile
up during each pause can't fully drain before the next one — p99 latency
blows up to many multiples of the pause itself. Role parity:
``examples/queuing/gc_caused_collapse.py``.
"""

from happysim_tpu import Instant, QueuedResource, Simulation, Sink, Source
from happysim_tpu.components.infrastructure import GarbageCollector, StopTheWorld


class GCService(QueuedResource):
    """Serialized 10ms service; optionally GC-pauses every N requests."""

    def __init__(self, name, downstream, gc=None, gc_every=50):
        super().__init__(name)
        self.downstream = downstream
        self.gc = gc
        self.gc_every = gc_every
        self.handled = 0
        self._busy = False

    def worker_has_capacity(self):
        return not self._busy

    def downstream_entities(self):
        return [self.downstream]

    def handle_queued_event(self, event):
        self._busy = True
        self.handled += 1
        if self.gc is not None and self.handled % self.gc_every == 0:
            yield from self.gc.pause()  # the worker stalls; the queue grows
        yield 0.010
        self._busy = False
        return [self.forward(event, self.downstream)]


def _run(with_gc: bool):
    sink = Sink("sink")
    gc = (
        GarbageCollector("gc", strategy=StopTheWorld(base_pause_s=0.3, seed=5))
        if with_gc
        else None
    )
    service = GCService("svc", sink, gc=gc)
    source = Source.poisson(rate=85.0, target=service, stop_after=60.0, seed=9)
    entities = [service, sink] + ([gc] if gc else [])
    sim = Simulation(sources=[source], entities=entities, end_time=Instant.from_seconds(120))
    sim.run()
    return sink.latency_stats()


def main() -> dict:
    healthy = _run(with_gc=False)
    collapsing = _run(with_gc=True)

    assert healthy.p99_s < 0.3, f"baseline stable: {healthy.p99_s}"
    # Each pause strands ~25 arrivals; at rho=0.85 the drain rate is only
    # 15 req/s of headroom, so the backlog takes seconds to clear.
    assert collapsing.p99_s > 4 * healthy.p99_s
    assert collapsing.mean_s > 2 * healthy.mean_s
    return {
        "healthy_p99_ms": round(healthy.p99_s * 1000, 1),
        "gc_p99_ms": round(collapsing.p99_s * 1000, 1),
        "amplification": round(collapsing.p99_s / healthy.p99_s, 1),
    }


if __name__ == "__main__":
    print(main())
