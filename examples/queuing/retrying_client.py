"""Client retries turn transient timeouts into eventual successes — at a
price.

A client calls a server that drops the first attempt of every request
(e.g. a flaky edge). With no retry policy every request fails; with
exponential backoff each request succeeds on attempt two, roughly doubling
offered load on the backend. Role parity:
``examples/queuing/retrying_client.py``.
"""

from happysim_tpu import ConstantLatency, Entity, Instant, Simulation
from happysim_tpu.components.client import Client, ExponentialBackoff


class FirstAttemptDropper(Entity):
    """Swallows the first attempt of each request id; serves the rest."""

    def __init__(self, name):
        super().__init__(name)
        self.seen: set = set()
        self.received = 0

    def handle_event(self, event):
        self.received += 1
        rid = event.context.get("metadata", {}).get("request_id", self.received)
        if rid not in self.seen:
            self.seen.add(rid)
            yield 10.0  # stall far past the client timeout
            return None
        yield 0.01
        return None


def _run(retry_policy):
    service = FirstAttemptDropper("flaky")
    client = Client("client", target=service, timeout=0.5, retry_policy=retry_policy)
    sim = Simulation(entities=[service, client], end_time=Instant.from_seconds(60))
    sim.schedule(
        [client.send_request(at=Instant.from_seconds(0.1 * i)) for i in range(5)]
    )
    sim.run()
    return client, service


def main() -> dict:
    no_retry, svc_a = _run(None)
    assert no_retry.failures == 5
    assert no_retry.responses_received == 0

    with_retry, svc_b = _run(
        ExponentialBackoff(max_attempts=3, initial_delay=0.1, seed=5)
    )
    assert with_retry.responses_received == 5, "every request succeeds on retry"
    assert with_retry.failures == 0
    assert with_retry.retries == 5
    # Cost: the backend saw double the attempts.
    assert svc_b.received == 10
    return {
        "no_retry_failures": no_retry.failures,
        "with_retry_successes": with_retry.responses_received,
        "backend_attempts": svc_b.received,
    }


if __name__ == "__main__":
    print(main())
