"""Dual-path service: the median lives on the fast path, the tail on the
slow one.

80% of requests take a 10ms fast path, 20% a 100ms slow path (weighted
4:1). The latency distribution is bimodal: p50 sits at the fast mode
while p90+ jumps an order of magnitude to the slow mode — percentile
dashboards that only watch p50 miss the second path entirely. Role
parity: ``examples/queuing/dual_path_queue_latency.py``.
"""

from happysim_tpu import (
    ConstantLatency,
    Instant,
    LoadBalancer,
    Server,
    Simulation,
    Sink,
    Source,
)
from happysim_tpu.components.load_balancer import WeightedRoundRobin


def main() -> dict:
    sink = Sink("sink")
    fast = Server("fast", concurrency=8, service_time=ConstantLatency(0.010), downstream=sink)
    slow = Server("slow", concurrency=8, service_time=ConstantLatency(0.100), downstream=sink)
    router = LoadBalancer("router", strategy=WeightedRoundRobin())
    router.add_backend(fast, weight=4.0)
    router.add_backend(slow, weight=1.0)
    source = Source.poisson(rate=50.0, target=router, stop_after=60.0, seed=12)
    sim = Simulation(
        sources=[source], entities=[router, fast, slow, sink],
        end_time=Instant.from_seconds(70),
    )
    sim.run()

    stats = sink.latency_stats()
    share_fast = fast.requests_completed / (
        fast.requests_completed + slow.requests_completed
    )
    assert abs(share_fast - 0.8) < 0.02, share_fast
    # Bimodal: the median is the fast mode, the tail is the slow mode.
    assert stats.p50_s < 0.02
    assert stats.p99_s > 0.09
    assert stats.p99_s / stats.p50_s > 5, "p50 alone hides the slow path"
    return {
        "fast_share": round(share_fast, 3),
        "p50_ms": round(stats.p50_s * 1000, 1),
        "p99_ms": round(stats.p99_s * 1000, 1),
    }


if __name__ == "__main__":
    print(main())
