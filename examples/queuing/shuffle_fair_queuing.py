"""Fair queuing isolates a polite tenant from a flooding neighbor.

Two tenants share one server near saturation: "flood" sends 10x the
traffic of "drip". Under FIFO the drip tenant queues behind the flood's
backlog; per-flow fair queuing round-robins flows, so the drip tenant
barely notices its neighbor. Role parity:
``examples/queuing/shuffle_fair_queuing.py``.
"""

from happysim_tpu import ConstantLatency, Instant, Server, Simulation, Source
from happysim_tpu.components.queue_policies import FairQueue
from happysim_tpu.core.entity import Entity
from happysim_tpu.load.event_provider import SimpleEventProvider


class TenantSink(Entity):
    """Records sojourn time per tenant (from created_at)."""

    def __init__(self):
        super().__init__("sink")
        self.latencies: dict[str, list] = {}

    def handle_event(self, event):
        tenant = event.context.get("metadata", {}).get("flow", "?")
        sojourn = (event.time - event.context["created_at"]).to_seconds()
        self.latencies.setdefault(tenant, []).append(sojourn)
        return None

    def mean(self, tenant):
        xs = self.latencies[tenant]
        return sum(xs) / len(xs)


def _tenant_source(rate, server, tenant, seed):
    provider = SimpleEventProvider(
        target=server,
        stop_after=Instant.from_seconds(30.0),
        context_fn=lambda t, i: {"metadata": {"flow": tenant}},
    )
    return Source.poisson(rate=rate, event_provider=provider, seed=seed, name=f"src_{tenant}")


def _run(policy):
    sink = TenantSink()
    server = Server(
        "srv",
        service_time=ConstantLatency(0.018),
        downstream=sink,
        queue_policy=policy,
        queue_capacity=10_000,
    )
    sources = [
        _tenant_source(50.0, server, "flood", seed=1),
        _tenant_source(5.0, server, "drip", seed=2),
    ]
    sim = Simulation(
        sources=sources, entities=[server, sink], end_time=Instant.from_seconds(40)
    )
    sim.run()
    return sink


def main() -> dict:
    fifo = _run(None)
    fair = _run(FairQueue())
    # Offered load ~0.99: FIFO makes the drip tenant share the backlog.
    assert fifo.mean("drip") > 2 * fair.mean("drip"), (
        fifo.mean("drip"), fair.mean("drip"),
    )
    # Fair queuing cannot hurt the flood much — it IS the load.
    assert fair.mean("flood") < fifo.mean("flood") * 3
    return {
        "fifo_drip_ms": round(fifo.mean("drip") * 1000, 1),
        "fair_drip_ms": round(fair.mean("drip") * 1000, 1),
        "isolation_factor": round(fifo.mean("drip") / fair.mean("drip"), 1),
    }


if __name__ == "__main__":
    print(main())
