"""A power outage mid-write: what the database still has at reboot.

An LSM store takes a stream of writes under a batched-sync WAL (fsync
every 8 entries) and loses power mid-stream. Flushed SSTables survive;
the memtable evaporates; recovery replays the WAL — but only its SYNCED
prefix, so the last unsynced batch is gone for good. The sync policy is
exactly the durability contract: batch size = maximum loss window. Role
parity: ``examples/storage/power_outage_durability.py``.
"""

from happysim_tpu import Event, Instant, Simulation
from happysim_tpu.components.storage import (
    LSMTree,
    SizeTieredCompaction,
    SyncOnBatch,
    WriteAheadLog,
)
from happysim_tpu.core.entity import Entity

N_WRITES = 53
BATCH = 8


def main() -> dict:
    wal = WriteAheadLog("wal", sync_policy=SyncOnBatch(batch_size=BATCH))
    lsm = LSMTree(
        "db",
        memtable_size=20,
        wal=wal,
        compaction_strategy=SizeTieredCompaction(min_sstables=100),
    )
    outcome = {}

    class Writer(Entity):
        def handle_event(self, event):
            for i in range(N_WRITES):
                yield from lsm.put(f"k{i:03d}", i)
            # --- power cut ---
            lost = lsm.crash()
            recovered = lsm.recover_from_crash()
            survivors = []
            for i in range(N_WRITES):
                value = yield from lsm.get(f"k{i:03d}")
                if value is not None:
                    survivors.append(i)
            outcome.update(lost=lost, recovered=recovered, survivors=survivors)
            return None

    writer = Writer("writer")
    sim = Simulation(entities=[writer, lsm, wal], end_time=Instant.from_seconds(600.0))
    sim.schedule(Event(Instant.Epoch, "go", target=writer))
    sim.run()

    survivors = outcome["survivors"]
    # 53 writes: 40 flushed into SSTables, 13 in the memtable at the cut.
    # The WAL replays only full synced batches of its live tail, so the
    # recovered set is a PREFIX — no holes, just a truncated end.
    assert survivors == list(range(len(survivors))), "durability is a prefix"
    assert len(survivors) >= 40, "flushed SSTables always survive"
    lost_tail = N_WRITES - len(survivors)
    assert 0 < lost_tail <= BATCH, (
        f"the loss window is bounded by the sync batch: lost {lost_tail}"
    )
    return {
        "written": N_WRITES,
        "recovered": len(survivors),
        "lost_tail": lost_tail,
        "wal_replayed": outcome["recovered"]["wal_entries_replayed"],
        "sstable_keys": outcome["recovered"]["sstable_keys"],
    }


if __name__ == "__main__":
    print(main())
