"""Write-ahead-log sync policies trade durability for throughput.

SyncEveryWrite survives a crash with zero loss; SyncOnBatch loses
whatever was buffered past the last sync. Role parity:
``examples/storage/power_outage_durability.py``.
"""

from happysim_tpu import Event, Instant, Simulation
from happysim_tpu.components.storage import SyncEveryWrite, SyncOnBatch, WriteAheadLog
from happysim_tpu.core.entity import Entity

N_WRITES = 50


class Writer(Entity):
    def __init__(self, name, wal):
        super().__init__(name)
        self.wal = wal

    def handle_event(self, event):
        for i in range(N_WRITES):
            yield from self.wal.append(f"seq{i}", i)
        return None


def survivors(sync_policy) -> int:
    wal = WriteAheadLog("wal", sync_policy=sync_policy)
    writer = Writer("writer", wal)
    sim = Simulation(entities=[wal, writer], end_time=Instant.from_seconds(60.0))
    sim.schedule(Event(Instant.Epoch, "go", target=writer))
    sim.run()
    wal.crash()  # power outage: unsynced tail is gone
    return len(wal.recover())


def main() -> dict:
    durable = survivors(SyncEveryWrite())
    batched = survivors(SyncOnBatch(batch_size=16))
    assert durable == N_WRITES
    # The batch policy loses the unsynced tail (50 = 3*16 + 2 buffered).
    assert batched == 48
    return {"sync_every_write": durable, "sync_on_batch": batched}


if __name__ == "__main__":
    print(main())
