"""Isolation levels decide which concurrent transactions must abort.

Two transactions write the same key. Under snapshot isolation the first
committer wins and the second aborts (write-write conflict). Under
serializable, even a read of a key someone else then writes dooms the
reader. Under read committed, both sail through. Role parity:
``examples/storage/transaction_isolation.py``.
"""

from happysim_tpu import Entity, Event, Instant, Simulation
from happysim_tpu.components.storage import IsolationLevel, LSMTree, TransactionManager


def _run(isolation, script_factory):
    lsm = LSMTree("db", memtable_size=1000)
    tm = TransactionManager("tm", store=lsm, isolation=isolation)
    result = {}

    class Driver(Entity):
        def handle_event(self, event):
            yield from script_factory(tm, lsm, result)

    driver = Driver("driver")
    sim = Simulation(entities=[lsm, tm, driver], end_time=Instant.from_seconds(60))
    sim.schedule(Event(Instant.Epoch, "go", target=driver))
    sim.run()
    return result, tm


def main() -> dict:
    def write_write(tm, lsm, out):
        tx1 = yield from tm.begin()
        tx2 = yield from tm.begin()
        yield from tx1.write("k", "tx1")
        yield from tx2.write("k", "tx2")
        out["ok1"] = yield from tx1.commit()
        out["ok2"] = yield from tx2.commit()
        out["value"] = lsm.get_sync("k")

    si, si_tm = _run(IsolationLevel.SNAPSHOT_ISOLATION, write_write)
    assert si == {"ok1": True, "ok2": False, "value": "tx1"}
    assert si_tm.stats.conflicts_detected == 1

    rc, _ = _run(IsolationLevel.READ_COMMITTED, write_write)
    assert rc["ok1"] and rc["ok2"]
    assert rc["value"] == "tx2", "last committer's write lands"

    def read_write(tm, lsm, out):
        lsm.put_sync("k", "initial")
        tx1 = yield from tm.begin()
        tx2 = yield from tm.begin()
        _ = yield from tx2.read("k")
        yield from tx1.write("k", "tx1")
        out["ok1"] = yield from tx1.commit()
        yield from tx2.write("other", 1)
        out["ok2"] = yield from tx2.commit()

    ser, _ = _run(IsolationLevel.SERIALIZABLE, read_write)
    assert ser == {"ok1": True, "ok2": False}, "serializable aborts the stale reader"

    return {
        "snapshot": si,
        "read_committed_value": rc["value"],
        "serializable": ser,
    }


if __name__ == "__main__":
    print(main())
