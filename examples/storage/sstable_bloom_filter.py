"""Bloom filters make absent-key lookups nearly free in an LSM tree.

An SSTable of 1,000 keys answers present-key gets with ~2 page reads
(sparse index + data page). For keys that don't exist, the bloom filter
short-circuits >95% of lookups to ZERO page reads — the reason LSM read
amplification stays bounded as levels stack up. Role parity:
``examples/storage/sstable_bloom_filter.py``.
"""

from happysim_tpu.components.storage import SSTable


def main() -> dict:
    sst = SSTable([(f"user{i:05d}", {"id": i}) for i in range(1000)])

    present_reads = [sst.page_reads_for_get(f"user{i:05d}") for i in range(0, 1000, 50)]
    assert all(1 <= r <= 3 for r in present_reads)
    assert all(sst.get(f"user{i:05d}") == {"id": i} for i in range(0, 1000, 100))

    absent_probes = 1000
    filtered = sum(
        1 for i in range(absent_probes) if sst.page_reads_for_get(f"ghost{i}") == 0
    )
    fp_rate = 1.0 - filtered / absent_probes
    assert fp_rate < 0.05, f"bloom FP rate too high: {fp_rate}"
    assert sst.get("ghost1") is None

    stats = sst.stats
    assert stats.key_count == 1000
    assert stats.bloom_filter_size_bits > 0
    return {
        "present_page_reads": max(present_reads),
        "absent_filtered_pct": round(100 * filtered / absent_probes, 1),
        "nominal_fp_rate": stats.bloom_filter_fp_rate,
    }


if __name__ == "__main__":
    print(main())
