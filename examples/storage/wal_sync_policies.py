"""WAL sync policies: what a crash costs under each fsync discipline.

The same 10-write stream goes through three write-ahead logs. On crash,
sync-every-write loses nothing, batch-sync loses the tail since the last
batch boundary, periodic sync loses everything since the last timer tick —
the classic durability/throughput dial. Role parity:
``examples/storage/wal_sync_policies.py``.
"""

from happysim_tpu import Event, Instant, Simulation
from happysim_tpu.components.storage import (
    SyncEveryWrite,
    SyncOnBatch,
    SyncPeriodic,
    WriteAheadLog,
)
from happysim_tpu.core.entity import Entity


def _run(policy):
    wal = WriteAheadLog("wal", sync_policy=policy)

    class Writer(Entity):
        def handle_event(self, event):
            for i in range(10):
                yield from wal.append(f"k{i}", i)
                yield 0.1  # 10 writes over ~1s
            return None

    writer = Writer("writer")
    sim = Simulation(entities=[wal, writer], end_time=Instant.from_seconds(60))
    sim.schedule(Event(Instant.Epoch, "go", target=writer))
    sim.run()
    lost = wal.crash()
    return lost, len(wal.recover()), wal.stats.syncs


def main() -> dict:
    every_lost, every_kept, every_syncs = _run(SyncEveryWrite())
    batch_lost, batch_kept, batch_syncs = _run(SyncOnBatch(batch_size=4))
    periodic_lost, periodic_kept, periodic_syncs = _run(SyncPeriodic(interval_s=0.35))

    assert every_lost == 0 and every_kept == 10
    assert every_syncs == 10
    # Batch of 4 over 10 writes: entries 9-10 were unsynced.
    assert batch_lost == 2 and batch_kept == 8
    assert batch_syncs == 2
    # Periodic: some tail lost, but far fewer fsyncs than every-write.
    assert 0 < periodic_lost <= 4
    assert periodic_syncs < every_syncs
    return {
        "every_write": {"lost": every_lost, "fsyncs": every_syncs},
        "batch_4": {"lost": batch_lost, "fsyncs": batch_syncs},
        "periodic_350ms": {"lost": periodic_lost, "fsyncs": periodic_syncs},
    }


if __name__ == "__main__":
    print(main())
