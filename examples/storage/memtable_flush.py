"""Memtable flushes convert write bursts into immutable sorted runs.

Writes accumulate in a size-bounded memtable; crossing the threshold
flushes a sorted SSTable. 100 unsorted writes through a 25-entry memtable
yield exactly 4 sorted runs and an empty memtable. Role parity:
``examples/storage/memtable_flush.py``.
"""

from happysim_tpu.components.storage import Memtable


def main() -> dict:
    mem = Memtable("m", size_threshold=25)
    sstables = []
    # Reverse-ish key order: proves the flush sorts, not the writer.
    for i in range(100, 0, -1):
        full = mem.put_sync(f"k{i:03d}", i)
        if full:
            sstables.append(mem.flush())

    assert len(sstables) == 4
    for sst in sstables:
        keys = [k for k, _ in sst.scan(sst.min_key, "kzzz")]
        assert keys == sorted(keys), "each run is sorted regardless of write order"
        assert sst.key_count == 25
    assert mem.size == 0
    assert mem.stats.flushes == 4
    # Point reads hit the right run.
    assert sstables[0].get("k100") == 100  # first flush holds the highest keys
    assert sstables[-1].get("k001") == 1
    return {
        "flushes": mem.stats.flushes,
        "run_sizes": [s.key_count for s in sstables],
    }


if __name__ == "__main__":
    print(main())
