"""B-tree vs LSM-tree: the classic read/write trade.

Same 300-key workload on both engines: the LSM absorbs writes into its
memtable (cheap) and pays on reads across sstables; the B-tree pays
page I/O per write but reads in one descent. Role parity:
``examples/storage/btree_vs_lsm.py``.
"""

from happysim_tpu import Event, Instant, Simulation
from happysim_tpu.components.storage import BTree, LSMTree
from happysim_tpu.core.entity import Entity

N_KEYS = 300


class Workload(Entity):
    def __init__(self, name, engine):
        super().__init__(name)
        self.engine = engine
        self.write_done_s = None
        self.read_done_s = None
        self.missing = 0

    def handle_event(self, event):
        for i in range(N_KEYS):
            yield from self.engine.put(f"key{i:04d}", i)
        self.write_done_s = self.now.to_seconds()
        for i in range(N_KEYS):
            value = yield from self.engine.get(f"key{i:04d}")
            if value != i:
                self.missing += 1
        self.read_done_s = self.now.to_seconds()
        return None


def run(engine) -> Workload:
    workload = Workload(f"wl-{engine.name}", engine)
    sim = Simulation(entities=[engine, workload], end_time=Instant.from_seconds(3600.0))
    sim.schedule(Event(Instant.Epoch, "go", target=workload))
    sim.run()
    assert workload.missing == 0
    return workload


def main() -> dict:
    lsm = run(LSMTree("lsm", memtable_size=64))
    btree = run(BTree("btree", order=16))
    lsm_write = lsm.write_done_s
    btree_write = btree.write_done_s
    # The LSM's buffered writes are faster than the B-tree's page writes.
    assert lsm_write < btree_write
    return {
        "lsm_write_s": round(lsm_write, 4),
        "btree_write_s": round(btree_write, 4),
        "lsm_read_s": round(lsm.read_done_s - lsm_write, 4),
        "btree_read_s": round(btree.read_done_s - btree_write, 4),
    }


if __name__ == "__main__":
    print(main())
