"""LSM compaction bounds read amplification as SSTables pile up.

Without compaction, 200 writes through a 10-entry memtable leave 20
SSTables and every miss probes them all. Size-tiered compaction merges
runs as they accumulate, so the same workload ends with a handful of
tables, newest-value-wins intact. Role parity:
``examples/storage/lsm_compaction.py``.
"""

from happysim_tpu import Event, Instant, Simulation
from happysim_tpu.components.storage import LSMTree, SizeTieredCompaction
from happysim_tpu.core.entity import Entity


def _run(compaction) -> "LSMTree":
    lsm = LSMTree("db", memtable_size=10, compaction_strategy=compaction)

    class Writer(Entity):
        def handle_event(self, event):
            for i in range(200):
                yield from lsm.put(f"k{i % 50:03d}", i)  # rewrites: 4 versions/key
            checks = []
            for i in (0, 25, 49):
                v = yield from lsm.get(f"k{i:03d}")
                checks.append(v)
            lsm.checks = checks
            return None

    writer = Writer("writer")
    sim = Simulation(entities=[lsm, writer], end_time=Instant.from_seconds(600))
    sim.schedule(Event(Instant.Epoch, "go", target=writer))
    sim.run()
    return lsm


def main() -> dict:
    lazy = _run(SizeTieredCompaction(min_sstables=1000))  # effectively off
    eager = _run(SizeTieredCompaction(min_sstables=3))

    assert lazy.stats.compactions == 0
    assert lazy.stats.total_sstables >= 15
    assert eager.stats.compactions >= 1
    assert eager.stats.total_sstables < lazy.stats.total_sstables / 2
    # Newest version of each rewritten key survives both regimes.
    assert lazy.checks == eager.checks == [150, 175, 199]
    return {
        "sstables_without_compaction": lazy.stats.total_sstables,
        "sstables_with_compaction": eager.stats.total_sstables,
        "compactions": eager.stats.compactions,
    }


if __name__ == "__main__":
    print(main())
