"""The Inductor smooths a burst into the downstream's sustainable pace.

A 200-request burst hits a slow server directly (queue explosion) vs
through an Inductor (EWMA pacing): the inductor spreads delivery and
caps the server's peak queue. Role parity:
``examples/performance/inductor_burst_suppression.py``.
"""

from happysim_tpu import (
    ConstantLatency,
    Event,
    Inductor,
    Instant,
    Probe,
    Server,
    Simulation,
    Sink,
    Source,
)

BURST = 200


def run(paced: bool) -> float:
    sink = Sink("sink")
    server = Server(
        "api", service_time=ConstantLatency(0.05), downstream=sink, queue_capacity=1000
    )
    entry = Inductor("inductor", server, time_constant=5.0) if paced else server
    probe = Probe.on(server, "queue_depth", interval_s=0.05)
    # Steady trickle that sets the EWMA, then a burst at t=30.
    source = Source.poisson(rate=4.0, target=entry, stop_after=60.0, seed=2)
    sim = Simulation(
        sources=[source],
        entities=[server, sink] + ([entry] if paced else []),
        probes=[probe],
        end_time=Instant.from_seconds(120.0),
    )
    sim.schedule(
        [Event(Instant.from_seconds(30.0), "req", target=entry) for _ in range(BURST)]
    )
    sim.run()
    return probe.data.max()


def main() -> dict:
    raw_peak = run(paced=False)
    paced_peak = run(paced=True)
    assert paced_peak < raw_peak / 2
    return {"peak_queue_raw": raw_peak, "peak_queue_inductor": paced_peak}


if __name__ == "__main__":
    print(main())
