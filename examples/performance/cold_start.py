"""Cold caches serve slow traffic until the working set loads — or you
pre-warm.

The same Zipf read stream hits a read-through cache twice: started cold,
the first seconds pay backing-store latency on most reads; started after a
warming pass over the hot keys, the hit ratio is high from the first
request. Role parity: ``examples/performance/cold_start.py``.
"""

from happysim_tpu import (
    Event,
    Instant,
    KVStore,
    Simulation,
    ZipfDistribution,
)
from happysim_tpu.components.datastore import CachedStore, LRUEviction
from happysim_tpu.core.entity import Entity

N_KEYS = 500
READS = 150


def _run(prewarm: bool):
    backing = KVStore("kv", read_latency=0.010)
    for i in range(N_KEYS):
        backing.put_sync(f"k{i}", i)
    cache = CachedStore(
        "cache", backing, cache_capacity=64,
        eviction_policy=LRUEviction(), cache_read_latency=0.0005,
    )
    zipf = ZipfDistribution(items=N_KEYS, exponent=1.4, seed=17)
    done = {}

    class Reader(Entity):
        def handle_event(self, event):
            if prewarm:
                # Warm the hot head of the key space before taking traffic.
                for i in range(64):
                    yield from cache.get(f"k{i}")
                # Measure only post-warming traffic.
                warm_hits, warm_misses = cache.stats.hits, cache.stats.misses
            else:
                warm_hits = warm_misses = 0
            start = self.now.to_seconds()
            for _ in range(READS):
                yield from cache.get(f"k{zipf.sample()}")
            done["seconds"] = self.now.to_seconds() - start
            done["hits"] = cache.stats.hits - warm_hits
            done["misses"] = cache.stats.misses - warm_misses
            return None

    reader = Reader("reader")
    sim = Simulation(entities=[backing, cache, reader], end_time=Instant.from_seconds(600))
    sim.schedule(Event(Instant.Epoch, "go", target=reader))
    sim.run()
    return done


def main() -> dict:
    cold = _run(prewarm=False)
    warm = _run(prewarm=True)
    cold_ratio = cold["hits"] / (cold["hits"] + cold["misses"])
    warm_ratio = warm["hits"] / (warm["hits"] + warm["misses"])
    assert warm_ratio > cold_ratio + 0.05
    assert warm["seconds"] < cold["seconds"]
    return {
        "cold_hit_ratio": round(cold_ratio, 3),
        "warm_hit_ratio": round(warm_ratio, 3),
        "cold_seconds": round(cold["seconds"], 2),
        "warm_seconds": round(warm["seconds"], 2),
    }


if __name__ == "__main__":
    print(main())
