"""Run a simulation, then let the analysis/AI stack explain it.

The same M/M/1 is run near-idle (rho=0.05) and saturated (rho=1.5);
``SimulationResult.from_run`` attaches phase detection, anomaly scan, and
rule-based recommendations. The saturated run is told its queue is
saturated/growing, the idle run that it is overprovisioned, and
``to_prompt_context()`` emits the compact text an LLM agent consumes. Role parity:
``examples/performance/ai_analysis.py``.
"""

from happysim_tpu import (
    ExponentialLatency,
    Instant,
    Probe,
    Server,
    Simulation,
    Sink,
    Source,
)
from happysim_tpu.ai import SimulationResult


def _run(lam: float) -> SimulationResult:
    sink = Sink("sink")
    server = Server(
        "server",
        service_time=ExponentialLatency(0.1, seed=1),
        downstream=sink,
        queue_capacity=100_000,
    )
    source = Source.poisson(rate=lam, target=server, stop_after=120.0, seed=4)
    depth = Probe.on(server, "queue_depth", interval_s=0.5)
    sim = Simulation(
        sources=[source], entities=[server, sink], probes=[depth],
        end_time=Instant.from_seconds(120),
    )
    summary = sim.run()
    return SimulationResult.from_run(
        summary, latency=sink.latency_data, queue_depth={"server": depth.data}
    )


def main() -> dict:
    healthy = _run(lam=0.5)
    saturated = _run(lam=15.0)

    sat_text = " ".join(r.description for r in saturated.recommendations).lower()
    assert "saturat" in sat_text or "grow" in sat_text, sat_text
    idle_text = " ".join(r.description for r in healthy.recommendations).lower()
    assert "empty" in idle_text or "overprovision" in idle_text, idle_text
    assert "saturat" not in idle_text

    prompt = saturated.to_prompt_context()
    assert "Recommendations" in prompt
    assert len(prompt) < 8000, "prompt context stays compact for LLM consumption"
    return {
        "healthy_recommendations": [r.description[:60] for r in healthy.recommendations],
        "saturated_recommendations": [r.description[:60] for r in saturated.recommendations],
        "prompt_chars": len(prompt),
    }


if __name__ == "__main__":
    print(main())
