"""The gateway's auth stage becomes the bottleneck before the backends do.

Two fast backends sit behind a gateway whose auth check costs 5ms per
request. At 150 req/s the backends are loafing (each sees ~75 req/s of
10ms work = 75% utilization) while the single-threaded auth stage needs
0.75s of work per second — the gateway, not the fleet, is the choke point.
Role parity: ``examples/performance/api_gateway_bottleneck.py``.
"""

from happysim_tpu import ConstantLatency, Instant, Server, Simulation, Sink, Source
from happysim_tpu.components.microservice import APIGateway, RouteConfig


def main() -> dict:
    sink = Sink("sink")
    backends = [
        Server(f"api{i}", concurrency=4, service_time=ConstantLatency(0.01), downstream=sink)
        for i in range(2)
    ]
    gateway = APIGateway(
        "gw",
        routes={"api": RouteConfig("api", backends=backends, auth_required=True)},
        auth_latency=0.005,
        auth_failure_rate=0.02,
        seed=11,
    )
    from happysim_tpu.load.event_provider import SimpleEventProvider

    provider = SimpleEventProvider(
        target=gateway,
        stop_after=Instant.from_seconds(10.0),
        context_fn=lambda t, i: {"metadata": {"route": "api"}},
    )
    source = Source.poisson(rate=150.0, event_provider=provider, seed=3)
    sim = Simulation(
        sources=[source],
        entities=[gateway, sink, *backends],
        end_time=Instant.from_seconds(15),
    )
    sim.run()

    stats = gateway.stats
    assert stats.requests_routed > 1000
    assert stats.requests_rejected_auth > 0
    per_backend = [b.requests_completed for b in backends]
    # Round-robin split is near-even.
    assert abs(per_backend[0] - per_backend[1]) <= 0.2 * max(per_backend)
    assert sink.events_received == sum(per_backend)
    return {
        "routed": stats.requests_routed,
        "auth_rejected": stats.requests_rejected_auth,
        "per_backend": per_backend,
    }


if __name__ == "__main__":
    print(main())
