"""A metrics pipeline: probes sample, buckets aggregate, sketches compress.

A probe samples a server's queue depth at 100ms cadence into a raw
series; `BucketedData` rolls it into 5s windows (what a dashboard
stores); a quantile sketch compresses per-request latencies to a few
hundred centroids. The pipeline trades fidelity for footprint at each
stage — the example checks the aggregates stay faithful to the raw
stream they summarize. Role parity:
``examples/performance/metric_collection_pipeline.py``.
"""

from happysim_tpu import (
    ExponentialLatency,
    Instant,
    Probe,
    Server,
    Simulation,
    Sink,
    Source,
)
from happysim_tpu.instrumentation import BucketedData
from happysim_tpu.sketching import TDigest


def main() -> dict:
    sink = Sink("sink")
    server = Server(
        "server",
        service_time=ExponentialLatency(0.08, seed=3),
        downstream=sink,
    )
    source = Source.poisson(rate=10.0, target=server, stop_after=120.0, seed=5)
    depth_probe = Probe.on(server, "queue_depth", interval_s=0.1)
    sim = Simulation(
        sources=[source],
        entities=[server, sink],
        probes=[depth_probe],
        end_time=Instant.from_seconds(125.0),
    )
    sim.run()

    raw = depth_probe.data
    assert raw.count() >= 1200, raw.count()

    # Stage 2: dashboard rollup — 5s buckets, 25x fewer points.
    buckets = BucketedData(raw, window_s=5.0)
    assert len(buckets.counts) <= raw.count() / 20
    # Aggregates are faithful: the window means average to the raw mean.
    weighted = sum(
        mean * count for mean, count in zip(buckets.means, buckets.counts)
    ) / sum(buckets.counts)
    assert abs(weighted - raw.mean()) < 1e-6

    # Stage 3: latency quantiles via a mergeable sketch (fixed footprint).
    sketch = TDigest(compression=200.0, seed=1)
    stats = sink.latency_stats()
    for latency in sink.latencies_s:
        sketch.add(latency)
    p99_sketch = sketch.quantile(0.99)
    p99_exact = stats.p99_s
    assert abs(p99_sketch - p99_exact) / p99_exact < 0.05, (p99_sketch, p99_exact)
    return {
        "raw_samples": raw.count(),
        "bucket_count": len(buckets.counts),
        "mean_queue_depth": round(raw.mean(), 3),
        "p99_exact_s": round(p99_exact, 4),
        "p99_sketch_s": round(p99_sketch, 4),
    }


if __name__ == "__main__":
    print(main())
