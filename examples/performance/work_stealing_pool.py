"""Work stealing rebalances a skewed task queue across workers.

All 16 tasks are forced onto worker 0's deque; worker 1 wakes with an empty
queue and steals from its peer, so both workers finish with completed tasks
and the makespan is roughly halved vs. serial draining. Role parity:
``examples/performance/work_stealing_pool.py``.
"""

from happysim_tpu import Counter, Event, Instant, Simulation
from happysim_tpu.components.scheduling import WorkStealingPool


def main() -> dict:
    collector = Counter("done")
    pool = WorkStealingPool(
        "pool", num_workers=2, downstream=collector, default_processing_time=0.1
    )
    # Skew: every task lands on worker 0.
    for i in range(16):
        task = Event(
            Instant.Epoch, "task", target=pool, context={"metadata": {"task_id": i}}
        )
        pool.workers[0]._queue.appendleft(task)

    sim = Simulation(
        entities=[pool, *pool.workers, collector], end_time=Instant.from_seconds(30)
    )
    sim.schedule(
        [Event(Instant.Epoch, "_worker_try_next", target=w) for w in pool.workers]
    )
    sim.run()

    per_worker = [w.tasks_completed for w in pool.worker_stats]
    assert sum(per_worker) == 16
    assert all(c > 0 for c in per_worker), f"both workers contributed: {per_worker}"
    assert pool.stats.total_steals >= 1
    assert pool.worker_stats[1].tasks_stolen > 0
    # Two workers at 0.1s/task over 16 tasks: ~0.8s each, well under serial 1.6s.
    return {
        "per_worker": per_worker,
        "steals": pool.stats.total_steals,
    }


if __name__ == "__main__":
    print(main())
