"""Auto-scaling rides a traffic ramp out and back.

A ramping load drives a target-utilization scaler: the fleet grows
under load and shrinks (respecting cooldowns) when the wave passes.
Role parity: ``examples/performance/auto_scaler.py``.
"""

from happysim_tpu import (
    ConstantLatency,
    Instant,
    LinearRampProfile,
    LoadBalancer,
    Server,
    Simulation,
    Source,
)
from happysim_tpu.components.deployment import AutoScaler, TargetUtilization


def main() -> dict:
    balancer = LoadBalancer("lb")
    seed_server = Server("s0", concurrency=2, service_time=ConstantLatency(0.4))
    balancer.add_backend(seed_server)

    def factory(name):
        return Server(name, concurrency=2, service_time=ConstantLatency(0.4))

    scaler = AutoScaler(
        "scaler",
        balancer,
        factory,
        policy=TargetUtilization(0.5),
        min_instances=1,
        max_instances=8,
        evaluation_interval=2.0,
        scale_out_cooldown=2.0,
        scale_in_cooldown=10.0,
    )
    # Ramp 1/s -> 12/s over 60s, then the source stops and load drains.
    source = Source.with_profile(
        LinearRampProfile(1.0, 12.0, 60.0), target=balancer,
        stop_after=60.0, seed=4,
    )
    sim = Simulation(
        sources=[source], entities=[balancer, scaler, seed_server],
        end_time=Instant.from_seconds(180.0),
    )
    sim.schedule(scaler.start())
    sim.run()

    stats = scaler.stats
    assert stats.scale_out_count >= 2  # grew with the ramp
    assert stats.scale_in_count >= 1  # shrank after it
    assert stats.instances_removed > 0
    return {
        "scale_outs": stats.scale_out_count,
        "scale_ins": stats.scale_in_count,
        "instances_added": stats.instances_added,
        "final_instances": len(balancer.backends),
    }


if __name__ == "__main__":
    print(main())
