"""Zipf traffic makes small caches look heroic — per-cohort stats don't.

100k keys under Zipf(1.1) popularity hit a cache holding just 1% of
them. The AGGREGATE hit rate looks great because the head of the
distribution dominates traffic — but split the keys into cohorts and
the story inverts: the head cohort is nearly fully cached while the long tail
runs essentially uncached. Sizing from the aggregate alone hides that
every tail request still pays the backing store. Role parity:
``examples/performance/zipf_cache_cohorts.py``.
"""

from happysim_tpu import Event, Instant, Simulation
from happysim_tpu.components.datastore import CachedStore, KVStore, LRUEviction
from happysim_tpu.core.entity import Entity
from happysim_tpu.distributions.value_distribution import ZipfDistribution

N_KEYS = 100_000
CACHE_SHARE = 0.01
N_REQUESTS = 20_000


def main() -> dict:
    backing = KVStore("disk", read_latency=0.004, write_latency=0.004)
    for i in range(N_KEYS):  # the dataset exists before the workload
        backing._data[f"key{i}"] = i
    cache = CachedStore(
        "cache",
        backing_store=backing,
        cache_capacity=int(N_KEYS * CACHE_SHARE),
        eviction_policy=LRUEviction(),
        cache_read_latency=0.0001,
    )
    ranks = ZipfDistribution(N_KEYS, exponent=1.1, seed=5)
    cohort_hits = {"head": 0, "head_total": 0, "tail": 0, "tail_total": 0}

    class Workload(Entity):
        def handle_event(self, event):
            for _ in range(N_REQUESTS):
                rank = ranks.sample()
                key = f"key{rank}"
                before = cache.stats.hits
                yield from cache.get(key)
                hit = cache.stats.hits > before
                cohort = "head" if rank < N_KEYS * CACHE_SHARE else "tail"
                cohort_hits[cohort] += hit
                cohort_hits[f"{cohort}_total"] += 1
            return None

    workload = Workload("workload")
    sim = Simulation(
        entities=[workload, cache, backing],
        end_time=Instant.from_seconds(3600.0),
    )
    sim.schedule(Event(Instant.Epoch, "go", target=workload))
    sim.run()

    aggregate = cache.hit_rate
    head_rate = cohort_hits["head"] / cohort_hits["head_total"]
    tail_rate = cohort_hits["tail"] / cohort_hits["tail_total"]
    # The aggregate flatters; the cohorts tell the truth.
    assert aggregate > 0.5, aggregate
    # Not 100%: cold first touches plus LRU churn from tail one-hit
    # wonders evicting head keys.
    assert head_rate > 0.8, head_rate
    assert tail_rate < 0.35, tail_rate
    assert head_rate - tail_rate > 0.5
    return {
        "aggregate_hit_rate": round(aggregate, 3),
        "head_cohort_hit_rate": round(head_rate, 3),
        "tail_cohort_hit_rate": round(tail_rate, 3),
        "tail_share_of_requests": round(
            cohort_hits["tail_total"] / N_REQUESTS, 3
        ),
    }


if __name__ == "__main__":
    print(main())
