"""Virtual nodes smooth a consistent-hash ring.

The same uniform key stream is hashed onto 8 backends with 1, 16, and 150
vnodes per backend. With one point per backend the ring's arc lengths are
wildly uneven; adding vnodes drives the max/min load ratio toward 1. Role
parity: ``examples/load-balancing/vnodes_analysis.py``.
"""

from happysim_tpu import (
    ConstantLatency,
    Instant,
    LoadBalancer,
    Server,
    Simulation,
    Sink,
    Source,
    UniformDistribution,
)
from happysim_tpu.components.load_balancer import ConsistentHash
from happysim_tpu.load.event_provider import SimpleEventProvider

N_BACKENDS = 8


def _imbalance(virtual_nodes: int) -> float:
    sink = Sink("sink")
    lb = LoadBalancer(
        "lb",
        strategy=ConsistentHash(
            virtual_nodes=virtual_nodes,
            get_key=lambda e: e.context.get("metadata", {}).get("key"),
        ),
    )
    backends = [
        Server(f"b{i}", concurrency=64, service_time=ConstantLatency(0.001), downstream=sink)
        for i in range(N_BACKENDS)
    ]
    for b in backends:
        lb.add_backend(b)
    keys = UniformDistribution(items=range(100_000), seed=5)
    provider = SimpleEventProvider(
        target=lb, context_fn=lambda t, i: {"metadata": {"key": f"key{keys.sample()}"}}
    )
    source = Source.constant(rate=400.0, event_provider=provider, stop_after=10.0)
    sim = Simulation(
        sources=[source], entities=[lb, sink, *backends], end_time=Instant.from_seconds(12)
    )
    sim.run()
    counts = [b.requests_completed for b in backends]
    return max(counts) / max(1, min(counts))


def main() -> dict:
    single = _imbalance(1)
    some = _imbalance(16)
    many = _imbalance(150)
    assert single > some > many, (single, some, many)
    assert many < 1.6, "150 vnodes: near-even arcs"
    assert single > 2.0, "one point per backend: lopsided arcs"
    return {
        "imbalance_1_vnode": round(single, 2),
        "imbalance_16_vnodes": round(some, 2),
        "imbalance_150_vnodes": round(many, 2),
    }


if __name__ == "__main__":
    print(main())
