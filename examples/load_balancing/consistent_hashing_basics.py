"""Consistent hashing keeps most keys in place when the fleet changes.

Hash 2,000 session keys onto 5 backends, remove one backend, re-hash:
only ~1/5 of the keys move (modulo hashing would move ~4/5). Role
parity: ``examples/load-balancing/consistent_hashing_basics.py``.
"""

from happysim_tpu import Counter, Event, Instant
from happysim_tpu.components.load_balancer import ConsistentHash
from happysim_tpu.components.load_balancer.strategies import BackendInfo

N_KEYS = 2000


def place(strategy, infos, keys):
    owners = {}
    for key in keys:
        request = Event(
            Instant.Epoch, "Request", target=infos[0].backend,
            context={"metadata": {"session_id": key}},
        )
        owners[key] = strategy.select(infos, request).name
    return owners


def main() -> dict:
    backends = [Counter(f"node{i}") for i in range(5)]
    infos = [BackendInfo(backend=b) for b in backends]
    keys = [f"user:{i}" for i in range(N_KEYS)]

    before = place(ConsistentHash(virtual_nodes=100), infos, keys)
    after = place(ConsistentHash(virtual_nodes=100), infos[:-1], keys)

    moved = sum(1 for key in keys if before[key] != after[key])
    moved_fraction = moved / N_KEYS
    # Only keys owned by the removed node (~1/5) move, plus ring noise.
    assert moved_fraction < 0.35
    # Keys that didn't live on the removed node stay put.
    stayed = sum(
        1 for key in keys if before[key] != "node4" and before[key] == after[key]
    )
    assert stayed / N_KEYS > 0.6
    loads: dict[str, int] = {}
    for owner in before.values():
        loads[owner] = loads.get(owner, 0) + 1
    assert max(loads.values()) < 3.0 * min(loads.values())
    return {"moved_fraction": round(moved_fraction, 3), "loads": loads}


if __name__ == "__main__":
    print(main())
