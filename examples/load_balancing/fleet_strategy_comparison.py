"""Comparing balancing strategies on a heterogeneous fleet.

Round-robin ignores backend speed; least-connections and power-of-two
adapt. On a fleet with one slow node, adaptive strategies hold a lower
p99. Role parity: ``examples/load-balancing/fleet_change_comparison.py``.
"""

from happysim_tpu import (
    ExponentialLatency,
    Instant,
    LoadBalancer,
    Server,
    Simulation,
    Sink,
    Source,
)
from happysim_tpu.components.load_balancer import (
    LeastConnections,
    PowerOfTwoChoices,
    RoundRobin,
)


def run(strategy) -> float:
    sink = Sink("sink")
    servers = [
        Server(f"s{i}", service_time=ExponentialLatency(mean, seed=i), downstream=sink)
        for i, mean in enumerate([0.05, 0.05, 0.25])
    ]
    balancer = LoadBalancer("lb", backends=servers, strategy=strategy)
    source = Source.poisson(rate=12.0, target=balancer, seed=9)
    Simulation(
        sources=[source], entities=[balancer, *servers, sink],
        end_time=Instant.from_seconds(200.0),
    ).run()
    return sink.latency_stats().p99_s


def main() -> dict:
    results = {
        "round_robin": run(RoundRobin()),
        "least_connections": run(LeastConnections()),
        "power_of_two": run(PowerOfTwoChoices(seed=3)),
    }
    assert results["least_connections"] < results["round_robin"]
    return {name: round(p99, 3) for name, p99 in results.items()}


if __name__ == "__main__":
    print(main())
