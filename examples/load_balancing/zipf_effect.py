"""Zipf-skewed keys break key-affinity load balancing.

With uniform keys, a consistent-hash ring spreads load evenly. Feed it a
Zipf(1.2) key stream and the hot keys' owners melt: the busiest backend
carries several times the coldest one's load, while round-robin (no
affinity) stays level — the fundamental cache-affinity vs load-evenness
trade. Role parity (reference tree): ``examples/load-balancing/zipf_effect.py``.
"""

from happysim_tpu import (
    ConstantLatency,
    Instant,
    LoadBalancer,
    Server,
    Simulation,
    Sink,
    Source,
    UniformDistribution,
    ZipfDistribution,
)
from happysim_tpu.components.load_balancer import ConsistentHash, RoundRobin
from happysim_tpu.load.event_provider import SimpleEventProvider

N_BACKENDS = 8
N_REQUESTS = 4000


def _run(strategy, key_dist):
    sink = Sink("sink")
    lb = LoadBalancer("lb", strategy=strategy)
    backends = [
        Server(f"b{i}", concurrency=64, service_time=ConstantLatency(0.001), downstream=sink)
        for i in range(N_BACKENDS)
    ]
    for b in backends:
        lb.add_backend(b)
    provider = SimpleEventProvider(
        target=lb,
        context_fn=lambda t, i: {"metadata": {"key": f"key{key_dist.sample()}"}},
    )
    source = Source.constant(rate=400.0, event_provider=provider, stop_after=10.0)
    sim = Simulation(
        sources=[source], entities=[lb, sink, *backends], end_time=Instant.from_seconds(12)
    )
    sim.run()
    counts = [b.requests_completed for b in backends]
    assert sum(counts) >= N_REQUESTS * 0.95
    return counts


def _key_of(event):
    return event.context.get("metadata", {}).get("key")


def main() -> dict:
    uniform_counts = _run(
        ConsistentHash(get_key=_key_of), UniformDistribution(items=range(4000), seed=1)
    )
    zipf_counts = _run(
        ConsistentHash(get_key=_key_of), ZipfDistribution(items=4000, exponent=1.2, seed=1)
    )
    rr_counts = _run(RoundRobin(), ZipfDistribution(items=4000, exponent=1.2, seed=1))

    def imbalance(counts):
        return max(counts) / max(1, min(counts))

    assert imbalance(rr_counts) < 1.1, "round-robin ignores keys: flat"
    assert imbalance(zipf_counts) > 2 * imbalance(uniform_counts), (
        f"hot keys skew the ring: {zipf_counts} vs {uniform_counts}"
    )
    return {
        "uniform_imbalance": round(imbalance(uniform_counts), 2),
        "zipf_imbalance": round(imbalance(zipf_counts), 2),
        "round_robin_imbalance": round(imbalance(rr_counts), 2),
    }


if __name__ == "__main__":
    print(main())
