"""The visual debugger driven headlessly: step, trace, break, resume.

The same `SimulationBridge` that backs the browser UI (``happysim-debug``
/ ``visual.server.serve``) is a plain Python object — this walkthrough
runs the full debug loop without a browser: activate code tracing on an
entity, step the simulation, read execution traces with a cursor (the
page's polling contract), set a code breakpoint, and continue past it.
Role parity: ``examples/visual/visual_debugger.py`` (the reference
launches the React app; same workflow, same verbs).

To get the actual UI on this model:

    from happysim_tpu.visual import serve
    serve(sim, port=8000)   # then open http://localhost:8000
"""

from happysim_tpu import ExponentialLatency, Instant, Server, Simulation, Sink, Source
from happysim_tpu.visual.bridge import SimulationBridge


def build_sim():
    sink = Sink("sink")
    server = Server(
        "server", service_time=ExponentialLatency(0.05, seed=2), downstream=sink
    )
    source = Source.poisson(rate=12.0, target=server, stop_after=30.0, seed=7)
    sim = Simulation(
        sources=[source], entities=[server, sink],
        end_time=Instant.from_seconds(40.0),
    )
    return sim, server, sink


def main() -> dict:
    sim, server, sink = build_sim()
    bridge = SimulationBridge(sim)

    # 1. Topology + initial state: what the left panel renders.
    topology = bridge.topology.to_dict()
    node_names = {node["id"] for node in topology["nodes"]}
    assert {"server", "sink"} <= node_names

    # 2. Activate code tracing on the server: the code panel's source.
    location = bridge.code_debugger.activate_entity(server)
    assert location.source_lines, "the handler's source is shown"

    # 3. Step 50 events; the event log and traces accumulate.
    state = bridge.step(50)
    assert state["events_processed"] == 50
    assert state["is_paused"]
    events = bridge.events()
    assert events, "the event log panel has rows"

    # 4. Cursor-read traces, like the page's poll loop.
    traces, cursor = bridge.code_debugger.traces_since(0)
    assert traces and cursor > 0
    first = traces[0]
    executed_lines = [record.line_number for record in first.lines]
    assert executed_lines, "per-line execution is recorded"

    # 5. A code breakpoint inside the handler pauses the run mid-handler;
    #    resume() releases it (the UI's continue button).
    target_line = executed_lines[0]
    breakpoint_ = bridge.code_debugger.add_breakpoint("server", target_line)
    assert breakpoint_ in bridge.code_debugger.breakpoints
    bridge.code_debugger.remove_breakpoint(breakpoint_.id)

    # 6. Run to completion; reset rewinds the world and the stream.
    bridge.run_all()
    served_first_run = sink.events_received
    assert served_first_run > 200
    generation = bridge.reset_generation
    bridge.reset()
    assert bridge.reset_generation == generation + 1
    assert bridge.state()["events_processed"] == 0

    bridge.close()
    return {
        "nodes": sorted(node_names),
        "traced_method": first.method_name,
        "traced_lines": len(executed_lines),
        "served": served_first_run,
    }


if __name__ == "__main__":
    print(main())
