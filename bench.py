#!/usr/bin/env python
"""Headline benchmarks: 65k-replica M/M/1 ensembles on the TPU executor.

Prints one JSON line per benchmark:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Two paths are measured:
  1. The closed-form Lindley kernel (tpu/mm1.py) — the flagship number.
  2. The GENERAL array-event engine (tpu/engine.py) running the same M/M/1
     as a declared source->server->sink model with per-event dispatch —
     the path every other vectorizable topology uses.

Baseline: the reference's single-core heap executor does ~134,580 events/s
on its M/M/1 throughput scenario (BASELINE.md); the BASELINE.json north-star
target is >=10M simulated events/sec/chip with mean wait within 1% of
rho/(mu-lambda).
"""

import json
import sys

REFERENCE_EVENTS_PER_SEC = 134_580.0  # BASELINE.md throughput checkpoint


def bench_kernel(devices) -> dict:
    from happysim_tpu.tpu import run_mm1_ensemble

    result = run_mm1_ensemble(
        lam=8.0,
        mu=10.0,
        n_replicas=65536,
        n_customers=4096,
        seed=0,
    )
    return {
        "metric": "simulated-events/sec/chip (65k-replica M/M/1 ensemble)",
        "value": round(result.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(result.events_per_second / REFERENCE_EVENTS_PER_SEC, 2),
        "mean_wait_s": round(result.mean_wait_s, 6),
        "analytic_wait_s": result.analytic_wait_s,
        "wait_error_rel": round(result.wait_error_rel, 6),
        "accuracy_ok": bool(result.wait_error_rel < 0.01),
        "n_replicas": result.n_replicas,
        "customers_per_replica": result.customers_per_replica,
        "simulated_events": result.simulated_events,
        "wall_seconds": round(result.wall_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def bench_general_engine(devices) -> dict:
    from happysim_tpu.tpu import mm1_model, run_ensemble

    lam, mu = 8.0, 10.0
    # Statistics are measured over [warmup, horizon]. The M/M/1 queue-length
    # relaxation time at rho=0.8 is ~1/(mu*(1-sqrt(rho))^2) ~ 9s, so the 40s
    # warmup is ~4.5 time constants (measured residual bias < 0.1% on the
    # virtual-mesh oracle run); the general engine carries the same 1%
    # accuracy gate as the kernel.
    result = run_ensemble(
        mm1_model(lam=lam, mu=mu, horizon_s=160.0, warmup_s=40.0),
        n_replicas=65536,
        seed=0,
    )
    analytic = (lam / mu) / (mu - lam)
    mean_wait = result.server_mean_wait_s[0]
    error = abs(mean_wait - analytic) / analytic
    accuracy_ok = bool(error < 0.01)
    return {
        "metric": "simulated-events/sec/chip (general engine, 65k-replica M/M/1)",
        "value": round(result.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(result.events_per_second / REFERENCE_EVENTS_PER_SEC, 2),
        "mean_wait_s": round(mean_wait, 6),
        "analytic_wait_s": analytic,
        "wait_error_rel": round(error, 6),
        "accuracy_ok": accuracy_ok,
        "north_star_ok": bool(result.events_per_second >= 10_000_000) and accuracy_ok,
        "truncated_replicas": result.truncated_replicas,
        "n_replicas": result.n_replicas,
        "horizon_s": result.horizon_s,
        "simulated_events": result.simulated_events,
        "wall_seconds": round(result.wall_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def main() -> int:
    import jax

    devices = jax.devices()
    print(json.dumps(bench_kernel(devices)))
    print(json.dumps(bench_general_engine(devices)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
