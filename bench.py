#!/usr/bin/env python
"""Headline benchmarks: 65k-replica M/M/1 ensembles on the TPU executor.

Prints one JSON line per benchmark:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Two paths are measured:
  1. The closed-form Lindley kernel (tpu/mm1.py) — the flagship number.
  2. The GENERAL array-event engine (tpu/engine.py) running the same M/M/1
     as a declared source->server->sink model with per-event dispatch —
     the path every other vectorizable topology uses.

Baseline: the reference's single-core heap executor does ~134,580 events/s
on its M/M/1 throughput scenario (BASELINE.md); the BASELINE.json north-star
target is >=10M simulated events/sec/chip with mean wait within 1% of
rho/(mu-lambda).
"""

import json
import os
import sys

REFERENCE_EVENTS_PER_SEC = 134_580.0  # BASELINE.md throughput checkpoint

# Scaled down when the TPU is unreachable and we fall back to CPU, so the
# bench still completes and emits honest (clearly-labeled) numbers.
KERNEL_REPLICAS = 65536
ENGINE_REPLICAS = 65536
ENGINE_HORIZON_S = 160.0
DEVICE_FALLBACK = False


def _tpu_probe(timeout_s: float = 90.0) -> str:
    """Probe JAX init in a child process — a wedged TPU tunnel blocks
    `import jax` indefinitely, so the probe must be killable.

    No pipes (a wedged plugin's helper process holding an inherited pipe
    would deadlock subprocess timeout handling) and the probe gets its
    own session so the timeout can kill the whole tree.

    Returns "ok" (accelerator found), "absent" (probe exited fast with no
    accelerator — a permanent condition, don't retry), or "wedged" (probe
    hung — a transient tunnel state worth retrying).
    """
    import signal
    import subprocess

    probe_src = (
        "import jax; ds = jax.devices(); "
        "assert any(d.platform != 'cpu' for d in ds), 'no accelerator'"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", probe_src],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    try:
        return "ok" if proc.wait(timeout=timeout_s) == 0 else "absent"
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()
        return "wedged"


def _reexec_cpu_fallback() -> "None":
    """Re-exec this script pinned to CPU with the TPU plugin shadowed.

    The shadow must be on PYTHONPATH at interpreter start — runtime
    sys.path edits are too late to stop a wedged plugin's registration
    from blocking `import jax` — hence the re-exec rather than an
    in-process switch.
    """
    import tempfile

    # Per-user fixed path, reused across runs (mkdtemp would leak one
    # dir per fallback invocation — the parent execve's away before any
    # cleanup). The uid suffix keeps the dir user-owned: this path heads
    # the child's PYTHONPATH, so it must not be attacker-writable.
    uid = os.getuid() if hasattr(os, "getuid") else None
    stub = os.path.join(tempfile.gettempdir(), f"happysim_jaxstub_{uid}")
    try:
        os.makedirs(stub, mode=0o700, exist_ok=True)
        owner = os.stat(stub).st_uid if uid is not None else None
        if uid is not None and owner != uid:
            raise OSError("stub dir owned by another user")
    except OSError:
        # Squatted or unusable: take a private one-off dir instead (leaks
        # one dir per run in this adversarial case — acceptable).
        stub = tempfile.mkdtemp(prefix="happysim_jaxstub_")
    os.makedirs(os.path.join(stub, "jax_plugins"), exist_ok=True)
    open(os.path.join(stub, "jax_plugins", "__init__.py"), "w").close()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Drop only the PYTHONPATH entries that carry an interpreter-startup
    # hook (any sitecustomize/usercustomize form) or a real jax_plugins
    # package (observed: /root/.axon_site): those re-wedge the fallback
    # child no matter what JAX_PLATFORMS says — and the child, unlike the
    # probe, has no timeout guarding it. Legitimate user entries (editable
    # installs, vendored deps) are kept; the stub is prepended so its
    # empty jax_plugins shadows any later one.
    startup_hooks = (
        "sitecustomize.py",
        "sitecustomize.pyc",
        os.path.join("sitecustomize", "__init__.py"),
        "usercustomize.py",
        "usercustomize.pyc",
        os.path.join("usercustomize", "__init__.py"),
        os.path.join("jax_plugins", "__init__.py"),
    )
    kept = [
        p
        for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p
        and not any(os.path.exists(os.path.join(p, hook)) for hook in startup_hooks)
    ]
    env["PYTHONPATH"] = os.pathsep.join([stub, *kept])
    env["HS_BENCH_CPU_FALLBACK"] = "1"
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def _apply_fallback_scale() -> None:
    global KERNEL_REPLICAS, ENGINE_REPLICAS, ENGINE_HORIZON_S, DEVICE_FALLBACK
    KERNEL_REPLICAS = 2048
    ENGINE_REPLICAS = 4096
    # Horizon shrinks less than replicas do: the 40s warmup (~4.5 M/M/1
    # relaxation times, see bench_general_engine) must survive, or the
    # accuracy gate would fail from warmup truncation instead of any
    # engine defect.
    ENGINE_HORIZON_S = 120.0
    DEVICE_FALLBACK = True


def bench_kernel(devices) -> dict:
    from happysim_tpu.tpu import run_mm1_ensemble

    result = run_mm1_ensemble(
        lam=8.0,
        mu=10.0,
        n_replicas=KERNEL_REPLICAS,
        n_customers=4096,
        seed=0,
    )
    label = (
        f"simulated-events/sec (CPU fallback, {KERNEL_REPLICAS}-replica M/M/1 ensemble)"
        if DEVICE_FALLBACK
        else f"simulated-events/sec/chip ({KERNEL_REPLICAS // 1000}k-replica M/M/1 ensemble)"
    )
    return {
        "metric": label,
        "value": round(result.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(result.events_per_second / REFERENCE_EVENTS_PER_SEC, 2),
        "mean_wait_s": round(result.mean_wait_s, 6),
        "analytic_wait_s": result.analytic_wait_s,
        "wait_error_rel": round(result.wait_error_rel, 6),
        "accuracy_ok": bool(result.wait_error_rel < 0.01),
        "n_replicas": result.n_replicas,
        "customers_per_replica": result.customers_per_replica,
        "simulated_events": result.simulated_events,
        "wall_seconds": round(result.wall_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def bench_general_engine(devices) -> dict:
    from happysim_tpu.tpu import mm1_model, run_ensemble

    lam, mu = 8.0, 10.0
    # Statistics are measured over [warmup, horizon]. The M/M/1 queue-length
    # relaxation time at rho=0.8 is ~1/(mu*(1-sqrt(rho))^2) ~ 9s, so the 40s
    # warmup is ~4.5 time constants (measured residual bias < 0.1% on the
    # virtual-mesh oracle run); the general engine carries the same 1%
    # accuracy gate as the kernel.
    result = run_ensemble(
        mm1_model(lam=lam, mu=mu, horizon_s=ENGINE_HORIZON_S, warmup_s=40.0),
        n_replicas=ENGINE_REPLICAS,
        seed=0,
    )
    analytic = (lam / mu) / (mu - lam)
    mean_wait = result.server_mean_wait_s[0]
    error = abs(mean_wait - analytic) / analytic
    accuracy_ok = bool(error < 0.01)
    label = (
        f"simulated-events/sec (CPU fallback, general engine, {ENGINE_REPLICAS}-replica M/M/1)"
        if DEVICE_FALLBACK
        else f"simulated-events/sec/chip (general engine, {ENGINE_REPLICAS // 1000}k-replica M/M/1)"
    )
    return {
        "metric": label,
        "value": round(result.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(result.events_per_second / REFERENCE_EVENTS_PER_SEC, 2),
        "mean_wait_s": round(mean_wait, 6),
        "analytic_wait_s": analytic,
        "wait_error_rel": round(error, 6),
        "accuracy_ok": accuracy_ok,
        "north_star_ok": bool(result.events_per_second >= 10_000_000) and accuracy_ok,
        "truncated_replicas": result.truncated_replicas,
        "n_replicas": result.n_replicas,
        "horizon_s": result.horizon_s,
        "simulated_events": result.simulated_events,
        "wall_seconds": round(result.wall_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def _wait_for_tpu() -> bool:
    """Retry the reachability probe so a transiently WEDGED tunnel yields a
    DELAYED TPU bench instead of a CPU fallback. A fast "no accelerator"
    exit is permanent — fall back immediately, don't stall a CPU-only box.
    Budget via HS_BENCH_TPU_WAIT_S (default 20 min; 0 = single probe)."""
    import time

    try:
        budget_s = float(os.environ.get("HS_BENCH_TPU_WAIT_S", "1200"))
    except ValueError:
        budget_s = 1200.0
    deadline = time.monotonic() + budget_s
    attempt = 0
    while True:
        attempt += 1
        verdict = _tpu_probe()
        if verdict == "ok":
            return True
        if verdict == "absent" or time.monotonic() >= deadline:
            return False
        print(
            json.dumps(
                {
                    "note": "TPU tunnel wedged; retrying",
                    "attempt": attempt,
                    "remaining_s": round(deadline - time.monotonic(), 0),
                }
            ),
            file=sys.stderr,
        )
        time.sleep(min(120.0, max(1.0, deadline - time.monotonic())))


def main() -> int:
    if os.environ.get("HS_BENCH_CPU_FALLBACK") == "1":
        _apply_fallback_scale()
    elif not _wait_for_tpu():
        _reexec_cpu_fallback()  # does not return
    import jax

    devices = jax.devices()
    kernel = bench_kernel(devices)
    engine = bench_general_engine(devices)
    if DEVICE_FALLBACK:
        note = "TPU unreachable at bench time; CPU fallback at reduced scale"
        kernel["device_fallback"] = note
        engine["device_fallback"] = note
        engine["north_star_ok"] = False  # per-chip target is a TPU claim
    print(json.dumps(kernel))
    print(json.dumps(engine))
    return 0


if __name__ == "__main__":
    sys.exit(main())
