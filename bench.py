#!/usr/bin/env python
"""Headline benchmarks: 65k-replica M/M/1 ensembles on the TPU executor.

Prints one JSON line per benchmark:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Two paths are measured:
  1. The closed-form Lindley kernel (tpu/mm1.py) — the flagship number.
  2. The GENERAL array-event engine (tpu/engine.py) running the same M/M/1
     as a declared source->server->sink model with per-event dispatch —
     the path every other vectorizable topology uses.

Baseline: the reference's single-core heap executor does ~134,580 events/s
on its M/M/1 throughput scenario (BASELINE.md); the BASELINE.json north-star
target is >=10M simulated events/sec/chip with mean wait within 1% of
rho/(mu-lambda).
"""

import json
import os
import sys

REFERENCE_EVENTS_PER_SEC = 134_580.0  # BASELINE.md throughput checkpoint

# Scaled down when the TPU is unreachable and we fall back to CPU, so the
# bench still completes and emits honest (clearly-labeled) numbers.
KERNEL_REPLICAS = 65536
ENGINE_REPLICAS = 65536
ENGINE_HORIZON_S = 160.0
HETERO_REPLICAS = 65536
HETERO_HORIZON_S = 120.0
DEVICE_FALLBACK = False

# Pallas kernel A/B entry: the fused macro-block kernel vs the lax event
# step on the SAME M/M/1 scan workload (explicit max_events keeps both
# runs off the chain closed form). On a TPU the kernel compiles natively;
# on the CPU fallback it runs in interpret mode (bit-identity still
# asserted, speedup honestly labeled as interpreted).
PALLAS_REPLICAS = 8192
PALLAS_HORIZON_S = 40.0
PALLAS_MACRO_BLOCK = 32

# Multi-chip mesh entry (ISSUE 13): the faulted+telemetry rho-sweep
# M/M/1 sharded over a replica mesh — per-chip events/s, 1-vs-N-device
# bit-identity of counters AND windowed series, and the
# host-vs-device reduce cost. On a real multi-chip host the measurement
# runs in-process at headline scale; on a single-chip host it runs on
# the virtual 8-device CPU mesh in a child process (the XLA
# host-device-count flag must precede jax init), clearly labeled and at
# reduced scale.
MULTICHIP_REPLICAS = 65536
MULTICHIP_VIRTUAL_REPLICAS = 4096
MULTICHIP_HORIZON_S = 30.0
MULTICHIP_WINDOWS = 32
MULTICHIP_MAX_EVENTS = 640
MULTICHIP_VIRTUAL_DEVICES = 8

# Consensus entry (ISSUE 16): quorum-liveness rho-sweep — a 3-server
# quorum cluster losing its majority to a deterministic partition
# window, defended (breaker + retry budget) vs undefended (quorum
# rejections retry freely and the post-heal storm depresses goodput).
# Consensus declines the Pallas kernel BY NAME, so both arms run the
# lax scan; the bench instead asserts 1-vs-N-device mesh bit-identity
# on every consensus counter and windowed series. On a single-chip
# host the measurement runs on the virtual 8-device CPU mesh in a
# child process (same pattern as MULTICHIP), at reduced scale.
CONSENSUS_REPLICAS = 65536
CONSENSUS_VIRTUAL_REPLICAS = 512
CONSENSUS_HORIZON_S = 12.0
CONSENSUS_WINDOWS = 16
CONSENSUS_VIRTUAL_DEVICES = 8

# Trace-ingestion entry (ISSUE 18): streamed open-world load — a
# diurnal and a flash-crowd recorded trace paged host->device in
# fixed-size chunks around the event scan (tpu/traces.py). Traces
# decline the Pallas kernel BY NAME, so the entry measures the scan
# path: events/s/chip replaying the whole trace on every replica, the
# buffer-stall fraction (wall-clock the device spent waiting on host
# paging — 0.0 means the double buffer always prefetched in time), and
# 1-vs-N-device mesh bit-identity of every counter and windowed series.
# On a single-chip host the measurement runs on the virtual 8-device
# CPU mesh in a child process (same pattern as MULTICHIP/CONSENSUS),
# at reduced replica count — the trace itself is shared by all
# replicas, so its page schedule is identical at any scale.
TRACE_REPLICAS = 65536
TRACE_VIRTUAL_REPLICAS = 512
TRACE_HORIZON_S = 16.0
TRACE_CHUNK_LEN = 64
TRACE_MAX_EVENTS = 16384
TRACE_VIRTUAL_DEVICES = 8


def _tpu_probe(timeout_s: float = 90.0) -> str:
    """Probe JAX init in a child process — a wedged TPU tunnel blocks
    `import jax` indefinitely, so the probe must be killable.

    No pipes (a wedged plugin's helper process holding an inherited pipe
    would deadlock subprocess timeout handling) and the probe gets its
    own session so the timeout can kill the whole tree.

    Returns "ok" (accelerator found), "absent" (probe exited fast with no
    accelerator — a permanent condition, don't retry), or "wedged" (probe
    hung — a transient tunnel state worth retrying).
    """
    import signal
    import subprocess

    probe_src = (
        "import jax; ds = jax.devices(); "
        "assert any(d.platform != 'cpu' for d in ds), 'no accelerator'"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", probe_src],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    try:
        return "ok" if proc.wait(timeout=timeout_s) == 0 else "absent"
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()
        return "wedged"


def _reexec_cpu_fallback() -> "None":
    """Re-exec this script pinned to CPU with the TPU plugin shadowed.

    The shadow must be on PYTHONPATH at interpreter start — runtime
    sys.path edits are too late to stop a wedged plugin's registration
    from blocking `import jax` — hence the re-exec rather than an
    in-process switch.
    """
    import tempfile

    # Per-user fixed path, reused across runs (mkdtemp would leak one
    # dir per fallback invocation — the parent execve's away before any
    # cleanup). The uid suffix keeps the dir user-owned: this path heads
    # the child's PYTHONPATH, so it must not be attacker-writable.
    uid = os.getuid() if hasattr(os, "getuid") else None
    stub = os.path.join(tempfile.gettempdir(), f"happysim_jaxstub_{uid}")
    try:
        os.makedirs(stub, mode=0o700, exist_ok=True)
        owner = os.stat(stub).st_uid if uid is not None else None
        if uid is not None and owner != uid:
            raise OSError("stub dir owned by another user")
    except OSError:
        # Squatted or unusable: take a private one-off dir instead (leaks
        # one dir per run in this adversarial case — acceptable).
        stub = tempfile.mkdtemp(prefix="happysim_jaxstub_")
    os.makedirs(os.path.join(stub, "jax_plugins"), exist_ok=True)
    open(os.path.join(stub, "jax_plugins", "__init__.py"), "w").close()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Drop only the PYTHONPATH entries that carry an interpreter-startup
    # hook (any sitecustomize/usercustomize form) or a real jax_plugins
    # package (observed: /root/.axon_site): those re-wedge the fallback
    # child no matter what JAX_PLATFORMS says — and the child, unlike the
    # probe, has no timeout guarding it. Legitimate user entries (editable
    # installs, vendored deps) are kept; the stub is prepended so its
    # empty jax_plugins shadows any later one.
    startup_hooks = (
        "sitecustomize.py",
        "sitecustomize.pyc",
        os.path.join("sitecustomize", "__init__.py"),
        "usercustomize.py",
        "usercustomize.pyc",
        os.path.join("usercustomize", "__init__.py"),
        os.path.join("jax_plugins", "__init__.py"),
    )
    kept = [
        p
        for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p
        and not any(os.path.exists(os.path.join(p, hook)) for hook in startup_hooks)
    ]
    env["PYTHONPATH"] = os.pathsep.join([stub, *kept])
    env["HS_BENCH_CPU_FALLBACK"] = "1"
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def _apply_fallback_scale() -> None:
    global KERNEL_REPLICAS, ENGINE_REPLICAS, ENGINE_HORIZON_S, DEVICE_FALLBACK
    global HETERO_REPLICAS, HETERO_HORIZON_S
    global PALLAS_REPLICAS, PALLAS_HORIZON_S, PALLAS_MACRO_BLOCK
    KERNEL_REPLICAS = 2048
    ENGINE_REPLICAS = 4096
    # Interpret-mode Pallas on CPU pays a large per-op interpreter tax;
    # a small block keeps the A/B honest AND finishable.
    PALLAS_REPLICAS = 64
    PALLAS_HORIZON_S = 8.0
    PALLAS_MACRO_BLOCK = 8
    # Horizon shrinks less than replicas do: the 40s warmup (~4.5 M/M/1
    # relaxation times, see bench_general_engine) must survive, or the
    # accuracy gate would fail from warmup truncation instead of any
    # engine defect.
    ENGINE_HORIZON_S = 120.0
    HETERO_REPLICAS = 2048
    HETERO_HORIZON_S = 60.0
    DEVICE_FALLBACK = True


def bench_kernel(devices) -> dict:
    from happysim_tpu.tpu import run_mm1_ensemble

    result = run_mm1_ensemble(
        lam=8.0,
        mu=10.0,
        n_replicas=KERNEL_REPLICAS,
        n_customers=4096,
        seed=0,
    )
    label = (
        f"simulated-events/sec (CPU fallback, {KERNEL_REPLICAS}-replica M/M/1 ensemble)"
        if DEVICE_FALLBACK
        else f"simulated-events/sec/chip ({KERNEL_REPLICAS // 1000}k-replica M/M/1 ensemble)"
    )
    return {
        "metric": label,
        "value": round(result.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(result.events_per_second / REFERENCE_EVENTS_PER_SEC, 2),
        "mean_wait_s": round(result.mean_wait_s, 6),
        "analytic_wait_s": result.analytic_wait_s,
        "wait_error_rel": round(result.wait_error_rel, 6),
        "accuracy_ok": bool(result.wait_error_rel < 0.01),
        "n_replicas": result.n_replicas,
        "customers_per_replica": result.customers_per_replica,
        "simulated_events": result.simulated_events,
        "wall_seconds": round(result.wall_seconds, 6),
        "compile_seconds": round(result.compile_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def bench_general_engine(devices) -> dict:
    from happysim_tpu.tpu import mm1_model, run_ensemble

    lam, mu = 8.0, 10.0
    # Statistics are measured over [warmup, horizon]. The M/M/1 queue-length
    # relaxation time at rho=0.8 is ~1/(mu*(1-sqrt(rho))^2) ~ 9s, so the 40s
    # warmup is ~4.5 time constants (measured residual bias < 0.1% on the
    # virtual-mesh oracle run); the general engine carries the same 1%
    # accuracy gate as the kernel.
    result = run_ensemble(
        mm1_model(lam=lam, mu=mu, horizon_s=ENGINE_HORIZON_S, warmup_s=40.0),
        n_replicas=ENGINE_REPLICAS,
        seed=0,
    )
    analytic = (lam / mu) / (mu - lam)
    mean_wait = result.server_mean_wait_s[0]
    error = abs(mean_wait - analytic) / analytic
    accuracy_ok = bool(error < 0.01)
    label = (
        f"simulated-events/sec (CPU fallback, general engine, {ENGINE_REPLICAS}-replica M/M/1)"
        if DEVICE_FALLBACK
        else f"simulated-events/sec/chip (general engine, {ENGINE_REPLICAS // 1000}k-replica M/M/1)"
    )
    return {
        "metric": label,
        "value": round(result.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(result.events_per_second / REFERENCE_EVENTS_PER_SEC, 2),
        "mean_wait_s": round(mean_wait, 6),
        "analytic_wait_s": analytic,
        "wait_error_rel": round(error, 6),
        "accuracy_ok": accuracy_ok,
        "north_star_ok": bool(result.events_per_second >= 10_000_000) and accuracy_ok,
        "truncated_replicas": result.truncated_replicas,
        "n_replicas": result.n_replicas,
        "horizon_s": result.horizon_s,
        "simulated_events": result.simulated_events,
        "wall_seconds": round(result.wall_seconds, 6),
        "compile_seconds": round(result.compile_seconds, 6),
        "engine_path": result.engine_path,
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def bench_hetero_sweep(devices) -> dict:
    """Heterogeneous rho sweep (0.1 -> 0.95 across replicas) through a
    deadline/retry M/M/1 — the workload the macro-stepped early exit is
    for: the event budget must cover the worst lane (max rho, plus the
    (1 + max_retries) retry factor), but the while_loop stops as soon as
    the slowest lane is done instead of burning the full budget on every
    replica. Runs the SAME model twice (flat scan vs early exit) and
    reports the measured speedup; results must be bit-identical.
    """
    import numpy as np

    from happysim_tpu.tpu import run_ensemble

    mu = 10.0
    # deadline_s=8.0 is ~e^-4 of sojourns even at rho=0.95: retries are
    # rare, but the budget must still pay the x3 retry factor.
    model = _hetero_model()
    sweeps = {
        "source_rate": np.linspace(0.1 * mu, 0.95 * mu, HETERO_REPLICAS).astype(
            np.float32
        )
    }

    def run(early_exit: bool):
        prior = os.environ.get("HS_TPU_EARLY_EXIT")
        os.environ["HS_TPU_EARLY_EXIT"] = "1" if early_exit else "0"
        try:
            return run_ensemble(
                model, n_replicas=HETERO_REPLICAS, seed=0, sweeps=sweeps
            )
        finally:
            if prior is None:
                os.environ.pop("HS_TPU_EARLY_EXIT", None)
            else:
                os.environ["HS_TPU_EARLY_EXIT"] = prior

    flat = run(False)
    early = run(True)
    speedup = flat.wall_seconds / max(early.wall_seconds, 1e-9)
    bit_identical = bool(
        flat.simulated_events == early.simulated_events
        and flat.sink_count == early.sink_count
        and flat.sink_mean_latency_s == early.sink_mean_latency_s
        and flat.server_completed == early.server_completed
    )
    label = (
        f"simulated-events/sec (CPU fallback, hetero rho sweep 0.1-0.95, {HETERO_REPLICAS}-replica)"
        if DEVICE_FALLBACK
        else f"simulated-events/sec/chip (hetero rho sweep 0.1-0.95, {HETERO_REPLICAS // 1000}k-replica deadline M/M/1)"
    )
    return {
        "metric": label,
        "value": round(early.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(early.events_per_second / REFERENCE_EVENTS_PER_SEC, 2),
        "flat_scan_events_per_sec": round(flat.events_per_second, 0),
        "early_exit_speedup": round(speedup, 2),
        "early_exit_ok": bool(speedup >= 1.5),
        "bit_identical": bit_identical,
        "truncated_replicas": early.truncated_replicas,
        "n_replicas": early.n_replicas,
        "horizon_s": early.horizon_s,
        "simulated_events": early.simulated_events,
        "wall_seconds": round(early.wall_seconds, 6),
        "flat_wall_seconds": round(flat.wall_seconds, 6),
        "compile_seconds": round(early.compile_seconds, 6),
        "flat_compile_seconds": round(flat.compile_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def _hetero_model(telemetry_windows: int = 0):
    """The hetero ρ-sweep deadline M/M/1 (shared by the early-exit and
    telemetry entries; the deadline keeps both runs on the event scan,
    so telemetry on/off is an apples-to-apples program comparison)."""
    from happysim_tpu.tpu.model import EnsembleModel

    mu = 10.0
    model = EnsembleModel(horizon_s=HETERO_HORIZON_S, warmup_s=20.0)
    src = model.source(rate=9.5)  # swept per replica by the caller
    srv = model.server(
        concurrency=1,
        service_mean=1.0 / mu,
        queue_capacity=256,
        deadline_s=8.0,
        max_retries=2,
    )
    snk = model.sink()
    model.connect(src, srv)
    model.connect(srv, snk)
    if telemetry_windows:
        model.telemetry(window_s=HETERO_HORIZON_S / telemetry_windows)
    return model


def bench_telemetry_overhead(devices) -> dict:
    """Windowed-telemetry cost on the ρ-sweep workload: the SAME model
    with and without a 64-window TelemetrySpec. Telemetry is
    observation-only — it adds no RNG draws — so the simulated counters
    must be bit-identical between the two runs (asserted here: a
    divergence means the buffers perturbed the simulation); the wall
    ratio is the enabled-path overhead the docs quote.
    """
    import numpy as np

    from happysim_tpu.tpu import run_ensemble

    mu = 10.0
    sweeps = {
        "source_rate": np.linspace(
            0.1 * mu, 0.95 * mu, HETERO_REPLICAS
        ).astype(np.float32)
    }

    def run(windows: int):
        return run_ensemble(
            _hetero_model(telemetry_windows=windows),
            n_replicas=HETERO_REPLICAS,
            seed=0,
            sweeps=sweeps,
        )

    disabled = run(0)
    enabled = run(64)
    overhead = enabled.wall_seconds / max(disabled.wall_seconds, 1e-9)
    bit_identical = bool(
        disabled.simulated_events == enabled.simulated_events
        and disabled.sink_count == enabled.sink_count
        and disabled.sink_mean_latency_s == enabled.sink_mean_latency_s
        and disabled.server_completed == enabled.server_completed
        and disabled.server_timed_out == enabled.server_timed_out
    )
    assert bit_identical, (
        "telemetry perturbed the simulation: disabled-path results must be "
        "bit-identical to the telemetry run's counters"
    )
    ts = enabled.timeseries
    series_consistent = bool(
        ts is not None
        and ts.sink_count.sum(axis=0).tolist() == enabled.sink_count
    )
    label = (
        f"simulated-events/sec (CPU fallback, 64-window telemetry, {HETERO_REPLICAS}-replica rho sweep)"
        if DEVICE_FALLBACK
        else f"simulated-events/sec/chip (64-window telemetry, {HETERO_REPLICAS // 1000}k-replica rho sweep)"
    )
    return {
        "metric": label,
        "value": round(enabled.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(enabled.events_per_second / REFERENCE_EVENTS_PER_SEC, 2),
        "telemetry_windows": 64,
        "telemetry_overhead": round(overhead, 3),
        "disabled_events_per_sec": round(disabled.events_per_second, 0),
        "bit_identical": bit_identical,
        "series_consistent": series_consistent,
        "n_replicas": enabled.n_replicas,
        "horizon_s": enabled.horizon_s,
        "simulated_events": enabled.simulated_events,
        "wall_seconds": round(enabled.wall_seconds, 6),
        "disabled_wall_seconds": round(disabled.wall_seconds, 6),
        "compile_seconds": round(enabled.compile_seconds, 6),
        "disabled_compile_seconds": round(disabled.compile_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def bench_kernel_telemetry(devices) -> dict:
    """The PR-6 production configuration on the fast path: a ρ-sweep
    FAULTED deadline M/M/1 with a 64-window TelemetrySpec, fused-kernel
    vs lax-step A/B. Three programs run: kernel+telemetry, lax+telemetry
    (must be bit-identical — counters AND every windowed series), and
    kernel without telemetry (same simulation by the no-RNG-draws
    contract; its wall time denominates the kernel-path telemetry
    overhead the docs quote).
    """
    import jax
    import numpy as np

    from happysim_tpu.tpu import run_ensemble
    from happysim_tpu.tpu.kernels import env_override, pallas_available
    from happysim_tpu.tpu.mesh import replica_mesh

    if not pallas_available():
        return {
            "metric": "simulated-events/sec (kernel-path 64-window telemetry)",
            "skipped": "jax.experimental.pallas unavailable in this jaxlib",
        }

    from happysim_tpu.tpu.model import EnsembleModel, FaultSpec

    mu = 10.0

    def build(windows: int):
        model = EnsembleModel(
            horizon_s=PALLAS_HORIZON_S, warmup_s=PALLAS_HORIZON_S / 4
        )
        model.macro_block = PALLAS_MACRO_BLOCK
        src = model.source(rate=9.5)  # swept per replica below
        srv = model.server(
            concurrency=1,
            service_mean=1.0 / mu,
            queue_capacity=256,
            deadline_s=8.0,
            max_retries=2,
            fault=FaultSpec(rate=0.05, mean_duration_s=0.5),
        )
        snk = model.sink()
        model.connect(src, srv)
        model.connect(srv, snk)
        if windows:
            model.telemetry(window_s=PALLAS_HORIZON_S / windows)
        return model

    sweeps = {
        "source_rate": np.linspace(
            0.1 * mu, 0.95 * mu, PALLAS_REPLICAS
        ).astype(np.float32)
    }
    max_events = int(4.0 * 9.5 * PALLAS_HORIZON_S) + 64
    # 1-device mesh pins the A/B to one shard; the kernel itself is
    # mesh-first since ISSUE 13 (the MULTICHIP entry measures that).
    mesh = replica_mesh(jax.devices()[:1])

    def run(pallas: bool, windows: int):
        with env_override("HS_TPU_PALLAS", "1" if pallas else "0"):
            return run_ensemble(
                build(windows),
                n_replicas=PALLAS_REPLICAS,
                seed=0,
                mesh=mesh,
                sweeps=sweeps,
                max_events=max_events,
            )

    kernel_r = run(True, 64)
    lax_r = run(False, 64)
    kernel_plain = run(True, 0)
    assert kernel_r.engine_path == "scan+pallas", kernel_r.kernel_decline
    assert lax_r.engine_path == "scan"
    kts, lts = kernel_r.timeseries, lax_r.timeseries
    bit_identical = bool(
        lax_r.simulated_events == kernel_r.simulated_events
        and lax_r.sink_count == kernel_r.sink_count
        and lax_r.sink_mean_latency_s == kernel_r.sink_mean_latency_s
        and lax_r.server_completed == kernel_r.server_completed
        and lax_r.server_fault_dropped == kernel_r.server_fault_dropped
        and (np.asarray(lax_r.sink_hist) == np.asarray(kernel_r.sink_hist)).all()
        and (kts.sink_count == lts.sink_count).all()
        and (kts.sink_hist == lts.sink_hist).all()
        and (kts.server_fault_dropped == lts.server_fault_dropped).all()
    )
    assert bit_identical, (
        "kernel-path telemetry diverged from the lax event step — the two "
        "paths must be bit-identical, counters and windowed series alike"
    )
    assert kernel_plain.simulated_events == kernel_r.simulated_events, (
        "telemetry perturbed the kernel-path simulation (it must add no "
        "RNG draws)"
    )
    speedup = lax_r.wall_seconds / max(kernel_r.wall_seconds, 1e-9)
    overhead = kernel_r.wall_seconds / max(kernel_plain.wall_seconds, 1e-9)
    label = (
        f"simulated-events/sec (CPU fallback, INTERPRETED kernel, 64-window telemetry, {PALLAS_REPLICAS}-replica faulted rho sweep)"
        if DEVICE_FALLBACK
        else f"simulated-events/sec/chip (Pallas kernel, 64-window telemetry, {PALLAS_REPLICAS // 1000}k-replica faulted rho sweep)"
    )
    return {
        "metric": label,
        "value": round(kernel_r.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(
            kernel_r.events_per_second / REFERENCE_EVENTS_PER_SEC, 2
        ),
        "lax_events_per_sec": round(lax_r.events_per_second, 0),
        "kernel_vs_lax_speedup": round(speedup, 3),
        "kernel_telemetry_overhead": round(overhead, 3),
        "telemetry_windows": 64,
        "bit_identical": bit_identical,
        "fault_dropped": int(sum(kernel_r.server_fault_dropped)),
        "macro_block": PALLAS_MACRO_BLOCK,
        "n_replicas": kernel_r.n_replicas,
        "horizon_s": kernel_r.horizon_s,
        "simulated_events": kernel_r.simulated_events,
        "wall_seconds": round(kernel_r.wall_seconds, 6),
        "lax_wall_seconds": round(lax_r.wall_seconds, 6),
        "plain_kernel_wall_seconds": round(kernel_plain.wall_seconds, 6),
        "compile_seconds": round(kernel_r.compile_seconds, 6),
        "lax_compile_seconds": round(lax_r.compile_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def bench_kernel_router(devices) -> dict:
    """The ISSUE-11 shape on the fast path: a ρ-sweep load-balancer
    fan-out (1 source -> round_robin router -> 4 servers -> fan-in ->
    sink, per-target latency edges), fused-kernel vs lax-step A/B.
    Bit-identity is asserted on the counters INCLUDING the per-server
    completion spread — the routing trace itself — so a route-choice or
    rr_next divergence inside the kernel cannot hide behind aggregate
    sink stats. The explicit max_events budget keeps both runs on the
    event scan (the chain closed form handles constant-edge fan-outs).
    """
    import jax
    import numpy as np

    from happysim_tpu.tpu import run_ensemble
    from happysim_tpu.tpu.kernels import env_override, pallas_available
    from happysim_tpu.tpu.mesh import replica_mesh

    if not pallas_available():
        return {
            "metric": "simulated-events/sec (kernel-path router fan-out)",
            "skipped": "jax.experimental.pallas unavailable in this jaxlib",
        }

    from happysim_tpu.tpu.model import EnsembleModel

    mu = 10.0
    n_servers = 4

    def build():
        model = EnsembleModel(
            horizon_s=PALLAS_HORIZON_S,
            warmup_s=PALLAS_HORIZON_S / 4,
            transit_capacity=16,
        )
        model.macro_block = PALLAS_MACRO_BLOCK
        src = model.source(rate=9.5)  # swept per replica below
        servers = [
            model.server(
                concurrency=1, service_mean=1.0 / mu, queue_capacity=256
            )
            for _ in range(n_servers)
        ]
        router = model.router(policy="round_robin")
        snk = model.sink()
        model.connect(src, router)
        for index, server in enumerate(servers):
            # Constant and exponential per-target edges alternate, so
            # the U_LAT slot and the transit registers are both live.
            model.connect(
                router,
                server,
                latency_s=0.005,
                latency_kind="exponential" if index % 2 else "constant",
            )
            model.connect(server, snk)
        return model

    # Fleet rho sweep: the OFFERED load per server is rate / n_servers,
    # so sweeping rate over [0.1, 0.95] * n_servers * mu walks each
    # 4-server fleet replica from idle to near-saturation.
    sweeps = {
        "source_rate": np.linspace(
            0.1 * n_servers * mu, 0.95 * n_servers * mu, PALLAS_REPLICAS
        ).astype(np.float32)
    }
    # Each job: source fire + transit arrival + completion = 3 events.
    max_events = int(4.0 * 0.95 * n_servers * mu * PALLAS_HORIZON_S) + 64
    mesh = replica_mesh(jax.devices()[:1])  # 1-shard A/B (kernel is mesh-first)

    def run(pallas: bool):
        with env_override("HS_TPU_PALLAS", "1" if pallas else "0"):
            return run_ensemble(
                build(),
                n_replicas=PALLAS_REPLICAS,
                seed=0,
                mesh=mesh,
                sweeps=sweeps,
                max_events=max_events,
            )

    lax_r = run(False)
    kernel_r = run(True)
    assert kernel_r.engine_path == "scan+pallas", kernel_r.kernel_decline
    assert kernel_r.kernel_shape == "router"
    assert lax_r.engine_path == "scan"
    bit_identical = bool(
        lax_r.simulated_events == kernel_r.simulated_events
        and lax_r.sink_count == kernel_r.sink_count
        and lax_r.sink_mean_latency_s == kernel_r.sink_mean_latency_s
        and lax_r.server_completed == kernel_r.server_completed
        and lax_r.server_dropped == kernel_r.server_dropped
        and lax_r.transit_dropped == kernel_r.transit_dropped
        and (np.asarray(lax_r.sink_hist) == np.asarray(kernel_r.sink_hist)).all()
    )
    assert bit_identical, (
        "router fan-out diverged between the Pallas kernel and the lax "
        "event step — the routing trace (per-server counters) must be "
        "bit-identical per lane"
    )
    speedup = lax_r.wall_seconds / max(kernel_r.wall_seconds, 1e-9)
    label = (
        f"simulated-events/sec (CPU fallback, INTERPRETED kernel, {PALLAS_REPLICAS}-replica 4-server LB fan-out rho sweep)"
        if DEVICE_FALLBACK
        else f"simulated-events/sec/chip (Pallas kernel, {PALLAS_REPLICAS // 1000}k-replica 4-server LB fan-out rho sweep)"
    )
    return {
        "metric": label,
        "value": round(kernel_r.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(
            kernel_r.events_per_second / REFERENCE_EVENTS_PER_SEC, 2
        ),
        "lax_events_per_sec": round(lax_r.events_per_second, 0),
        "kernel_vs_lax_speedup": round(speedup, 3),
        "bit_identical": bit_identical,
        "router_policy": "round_robin",
        "n_servers": n_servers,
        "kernel_shape": kernel_r.kernel_shape,
        "fanout_completed": [int(c) for c in kernel_r.server_completed],
        "macro_block": PALLAS_MACRO_BLOCK,
        "n_replicas": kernel_r.n_replicas,
        "horizon_s": kernel_r.horizon_s,
        "simulated_events": kernel_r.simulated_events,
        "wall_seconds": round(kernel_r.wall_seconds, 6),
        "lax_wall_seconds": round(lax_r.wall_seconds, 6),
        "compile_seconds": round(kernel_r.compile_seconds, 6),
        "lax_compile_seconds": round(lax_r.compile_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def bench_kernel_graph(devices) -> dict:
    """The ISSUE-17 shape on the fast path: a ρ-sweep TWO-TIER service
    DAG (ramp-profiled source -> least_outstanding front tier of 2
    servers -> a second least_outstanding router -> shared back tier of
    2 servers -> sink), fused-kernel vs lax-step A/B. This is the
    general topology walk end to end: multi-router planning, the
    adaptive outstanding-count gather, and the profile lookup tables
    riding VMEM as tile-shared constants. ρ is swept via a
    ``service_mean`` sweep — ``source_rate`` sweeps are incompatible
    with profiled sources (the profile already owns rate(t)) — walking
    each replica's back tier from idle to near-saturation. Bit-identity
    is asserted on the counters INCLUDING the per-server completion
    spread across BOTH tiers — the routing trace itself — so a
    divergence in the gather, the route slots, or the table lookup
    cannot hide behind aggregate sink stats.
    """
    import jax
    import numpy as np

    from happysim_tpu.tpu import run_ensemble
    from happysim_tpu.tpu.kernels import env_override, pallas_available
    from happysim_tpu.tpu.mesh import replica_mesh

    if not pallas_available():
        return {
            "metric": "simulated-events/sec (kernel-path 2-tier graph)",
            "skipped": "jax.experimental.pallas unavailable in this jaxlib",
        }

    from happysim_tpu.tpu.model import EnsembleModel

    n_tier = 2  # servers per tier (front + shared back)
    peak_rate = 40.0  # ramp target, req/s

    def build():
        model = EnsembleModel(
            horizon_s=PALLAS_HORIZON_S,
            warmup_s=PALLAS_HORIZON_S / 4,
            transit_capacity=16,
        )
        model.macro_block = PALLAS_MACRO_BLOCK
        src = model.ramp_source(
            peak_rate / 2, peak_rate, PALLAS_HORIZON_S / 2
        )
        front = [
            model.server(concurrency=1, service_mean=0.02, queue_capacity=256)
            for _ in range(n_tier)
        ]
        back = [
            model.server(concurrency=1, service_mean=0.02, queue_capacity=256)
            for _ in range(n_tier)
        ]
        front_lb = model.router(policy="least_outstanding", targets=front)
        back_lb = model.router(policy="least_outstanding", targets=back)
        snk = model.sink()
        model.connect(src, front_lb)
        for server in front:
            model.connect(server, back_lb)
        for server in back:
            model.connect(server, snk)
        return model

    # ρ sweep via service_mean: the ramp averages ~0.75*peak_rate, split
    # over n_tier servers per tier, so mean per-server ρ is
    # (0.75 * peak / n_tier) * service_mean. Sweeping service_mean over
    # [0.1, 0.95] / that arrival rate walks each replica's tiers from
    # idle to near-saturation (source_rate sweeps would fight the
    # profile, so the SERVICE side carries the sweep).
    per_server_rate = 0.75 * peak_rate / n_tier
    sweeps = {
        "service_mean": (
            np.linspace(0.1, 0.95, PALLAS_REPLICAS) / per_server_rate
        ).astype(np.float32)
    }
    # Each job: source fire + front completion + back completion.
    max_events = int(4.0 * peak_rate * PALLAS_HORIZON_S) + 64
    mesh = replica_mesh(jax.devices()[:1])  # 1-shard A/B (kernel is mesh-first)

    def run(pallas: bool):
        with env_override("HS_TPU_PALLAS", "1" if pallas else "0"):
            return run_ensemble(
                build(),
                n_replicas=PALLAS_REPLICAS,
                seed=0,
                mesh=mesh,
                sweeps=sweeps,
                max_events=max_events,
            )

    lax_r = run(False)
    kernel_r = run(True)
    assert kernel_r.engine_path == "scan+pallas", kernel_r.kernel_decline
    assert kernel_r.kernel_shape == "graph"
    assert lax_r.engine_path == "scan"
    bit_identical = bool(
        lax_r.simulated_events == kernel_r.simulated_events
        and lax_r.sink_count == kernel_r.sink_count
        and lax_r.sink_mean_latency_s == kernel_r.sink_mean_latency_s
        and lax_r.server_completed == kernel_r.server_completed
        and lax_r.server_dropped == kernel_r.server_dropped
        and lax_r.transit_dropped == kernel_r.transit_dropped
        and (np.asarray(lax_r.sink_hist) == np.asarray(kernel_r.sink_hist)).all()
    )
    assert bit_identical, (
        "2-tier graph diverged between the Pallas kernel and the lax "
        "event step — the tier-by-tier routing trace (per-server "
        "counters) must be bit-identical per lane"
    )
    speedup = lax_r.wall_seconds / max(kernel_r.wall_seconds, 1e-9)
    label = (
        f"simulated-events/sec (CPU fallback, INTERPRETED kernel, {PALLAS_REPLICAS}-replica 2-tier LB graph rho sweep)"
        if DEVICE_FALLBACK
        else f"simulated-events/sec/chip (Pallas kernel, {PALLAS_REPLICAS // 1000}k-replica 2-tier LB graph rho sweep)"
    )
    return {
        "metric": label,
        "value": round(kernel_r.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(
            kernel_r.events_per_second / REFERENCE_EVENTS_PER_SEC, 2
        ),
        "lax_events_per_sec": round(lax_r.events_per_second, 0),
        "kernel_vs_lax_speedup": round(speedup, 3),
        "bit_identical": bit_identical,
        "router_policies": ["least_outstanding", "least_outstanding"],
        "source_profile": "ramp",
        "n_servers": 2 * n_tier,
        "kernel_shape": kernel_r.kernel_shape,
        "tier_completed": [int(c) for c in kernel_r.server_completed],
        "macro_block": PALLAS_MACRO_BLOCK,
        "n_replicas": kernel_r.n_replicas,
        "horizon_s": kernel_r.horizon_s,
        "simulated_events": kernel_r.simulated_events,
        "wall_seconds": round(kernel_r.wall_seconds, 6),
        "lax_wall_seconds": round(lax_r.wall_seconds, 6),
        "compile_seconds": round(kernel_r.compile_seconds, 6),
        "lax_compile_seconds": round(lax_r.compile_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def bench_kernel_chaos(devices) -> dict:
    """The ISSUE-14 stack on the fast path: a faulted+resilient+lossy
    router ρ-sweep (limiter admission -> round_robin fan-out over 4
    servers with correlated outage-mode faults, backoff+jitter client
    retries, hedged requests, and 5%-lossy latency edges, 64-window
    telemetry), fused-kernel vs lax-step A/B. Bit-identity is asserted
    on the chaos counters (retries, hedges, fault/limiter drops,
    packet losses) AND on every windowed series — the whole chaos trace
    must be identical per lane, so a divergence in any chaos branch
    (a retry re-parking a transit register, a hedge race, a loss
    Bernoulli slot) cannot hide behind aggregate sink stats. The
    explicit max_events budget keeps both runs on the event scan.
    """
    import jax
    import numpy as np

    from happysim_tpu.tpu import run_ensemble
    from happysim_tpu.tpu.kernels import env_override, pallas_available
    from happysim_tpu.tpu.mesh import replica_mesh

    if not pallas_available():
        return {
            "metric": "simulated-events/sec (kernel-path chaos stack)",
            "skipped": "jax.experimental.pallas unavailable in this jaxlib",
        }

    from happysim_tpu.tpu.model import EnsembleModel, FaultSpec

    mu = 10.0
    n_servers = 4
    n_windows = 64

    def build():
        model = EnsembleModel(
            horizon_s=PALLAS_HORIZON_S,
            transit_capacity=16,
        )
        model.macro_block = PALLAS_MACRO_BLOCK
        src = model.source(rate=9.5)  # swept per replica below
        # Light-touch admission: refill above the sweep's peak offered
        # rate, so the bucket rejects bursts without capping the sweep.
        lim = model.limiter(
            refill_rate=1.3 * 0.95 * n_servers * mu, capacity=16.0
        )
        servers = [
            model.server(
                concurrency=1,
                service_mean=1.0 / mu,
                queue_capacity=256,
                max_retries=2,
                retry_backoff_s=0.02,
                retry_jitter=0.5,
                hedge_delay_s=0.3 / mu if index % 2 == 0 else None,
                fault=FaultSpec(
                    rate=0.05,
                    mean_duration_s=0.5,
                    correlated=True,
                ),
            )
            for index in range(n_servers)
        ]
        model.correlated_outages(
            rate=0.02, mean_duration_s=0.5, trigger_p=0.5
        )
        router = model.router(policy="round_robin")
        snk = model.sink()
        model.connect(src, lim)
        model.connect(lim, router)
        for index, server in enumerate(servers):
            model.connect(
                router,
                server,
                latency_s=0.005,
                latency_kind="exponential" if index % 2 else "constant",
                loss_p=0.05 if index % 2 == 0 else 0.0,
            )
            model.connect(server, snk)
        model.telemetry(window_s=PALLAS_HORIZON_S / n_windows)
        return model

    # Fleet rho sweep: offered load per server is rate / n_servers.
    sweeps = {
        "source_rate": np.linspace(
            0.1 * n_servers * mu, 0.95 * n_servers * mu, PALLAS_REPLICAS
        ).astype(np.float32)
    }
    # Each job: source fire + transit arrival + completion = 3 events,
    # plus fault-rejection retries re-crossing transit (max_retries=2).
    max_events = int(6.0 * 0.95 * n_servers * mu * PALLAS_HORIZON_S) + 64
    mesh = replica_mesh(jax.devices()[:1])  # 1-shard A/B (kernel is mesh-first)

    def run(pallas: bool):
        with env_override("HS_TPU_PALLAS", "1" if pallas else "0"):
            return run_ensemble(
                build(),
                n_replicas=PALLAS_REPLICAS,
                seed=0,
                mesh=mesh,
                sweeps=sweeps,
                max_events=max_events,
            )

    lax_r = run(False)
    kernel_r = run(True)
    assert kernel_r.engine_path == "scan+pallas", kernel_r.kernel_decline
    assert kernel_r.kernel_shape == "router"
    assert lax_r.engine_path == "scan"
    counter_fields = (
        "simulated_events",
        "sink_count",
        "sink_mean_latency_s",
        "server_completed",
        "server_dropped",
        "server_retried",
        "server_fault_dropped",
        "server_fault_retried",
        "server_hedged",
        "server_hedge_wins",
        "transit_dropped",
        "limiter_admitted",
        "limiter_dropped",
        "network_lost",
    )
    bit_identical_counters = bool(
        all(
            np.array_equal(
                np.asarray(getattr(lax_r, name)),
                np.asarray(getattr(kernel_r, name)),
            )
            for name in counter_fields
        )
        and (
            np.asarray(lax_r.sink_hist) == np.asarray(kernel_r.sink_hist)
        ).all()
    )
    bit_identical_series = True
    for name in lax_r.timeseries._ARRAY_FIELDS:
        lax_series = getattr(lax_r.timeseries, name)
        kernel_series = getattr(kernel_r.timeseries, name)
        if lax_series is None:
            bit_identical_series &= kernel_series is None
            continue
        bit_identical_series &= bool(
            np.array_equal(
                np.asarray(lax_series),
                np.asarray(kernel_series),
                equal_nan=True,
            )
        )
    assert bit_identical_counters and bit_identical_series, (
        "chaos stack diverged between the Pallas kernel and the lax "
        "event step — the chaos trace (retry/hedge/loss counters and "
        "every windowed series) must be bit-identical per lane"
    )
    speedup = lax_r.wall_seconds / max(kernel_r.wall_seconds, 1e-9)
    label = (
        f"simulated-events/sec (CPU fallback, INTERPRETED kernel, {PALLAS_REPLICAS}-replica chaos-stack LB fan-out rho sweep)"
        if DEVICE_FALLBACK
        else f"simulated-events/sec/chip (Pallas kernel, {PALLAS_REPLICAS // 1000}k-replica chaos-stack LB fan-out rho sweep)"
    )
    return {
        "metric": label,
        "value": round(kernel_r.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(
            kernel_r.events_per_second / REFERENCE_EVENTS_PER_SEC, 2
        ),
        "lax_events_per_sec": round(lax_r.events_per_second, 0),
        "kernel_vs_lax_speedup": round(speedup, 3),
        "bit_identical_counters": bit_identical_counters,
        "bit_identical_series": bit_identical_series,
        "kernel_shape": kernel_r.kernel_shape,
        "kernel_chaos": list(kernel_r.kernel_chaos),
        "n_windows": n_windows,
        "chaos_totals": {
            "fault_retried": int(sum(kernel_r.server_fault_retried)),
            "fault_dropped": int(sum(kernel_r.server_fault_dropped)),
            "hedged": int(sum(kernel_r.server_hedged)),
            "hedge_wins": int(sum(kernel_r.server_hedge_wins)),
            "limiter_dropped": int(sum(kernel_r.limiter_dropped)),
            "network_lost": int(kernel_r.network_lost),
        },
        "macro_block": PALLAS_MACRO_BLOCK,
        "n_replicas": kernel_r.n_replicas,
        "horizon_s": kernel_r.horizon_s,
        "simulated_events": kernel_r.simulated_events,
        "wall_seconds": round(kernel_r.wall_seconds, 6),
        "lax_wall_seconds": round(lax_r.wall_seconds, 6),
        "compile_seconds": round(kernel_r.compile_seconds, 6),
        "lax_compile_seconds": round(lax_r.compile_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def bench_resilience(devices) -> dict:
    """ISSUE-15 metastability quantified at ensemble scale: a
    correlated-outage rho-sweep M/M/1 with deadline retries, run as two
    arms — UNDEFENDED (the retry storm locks in after the outage window
    ends: post-outage demand (1 + max_retries) x lambda exceeds mu, so
    goodput never recovers) and DEFENDED (retry budget + circuit
    breaker: launches capped at ratio x requests, dark-window arrivals
    failed fast), each recording ``goodput_recovery_ratio`` =
    post-outage / pre-outage per-window goodput. Kernel-vs-lax
    bit-identity is asserted on BOTH arms (the fused resilience stack
    runs ``scan+pallas``; counters AND every windowed series), so the
    recovery numbers come off the fast path with the lax step as the
    per-lane oracle.
    """
    import jax
    import numpy as np

    from happysim_tpu.tpu import run_ensemble
    from happysim_tpu.tpu.kernels import env_override, pallas_available
    from happysim_tpu.tpu.mesh import replica_mesh

    if not pallas_available():
        return {
            "metric": "goodput recovery (resilience-defended metastability)",
            "skipped": "jax.experimental.pallas unavailable in this jaxlib",
        }

    from happysim_tpu.tpu.model import EnsembleModel, FaultSpec

    mu = 25.0
    horizon = PALLAS_HORIZON_S
    n_windows = 16
    outage = (0.3 * horizon, 0.45 * horizon)

    def build(defended: bool):
        model = EnsembleModel(horizon_s=horizon, transit_capacity=64)
        model.macro_block = PALLAS_MACRO_BLOCK
        src = model.source(rate=0.6 * mu)  # swept per replica below
        srv = model.server(
            concurrency=1,
            service_mean=1.0 / mu,
            queue_capacity=512,
            deadline_s=0.5,
            max_retries=3,
            retry_backoff_s=1.0,
            fault=FaultSpec(windows=(outage,)),
        )
        snk = model.sink()
        model.connect(src, srv)
        model.connect(srv, snk)
        model.telemetry(
            window_s=horizon / n_windows, metrics=("throughput", "rates")
        )
        if defended:
            model.circuit_breaker(
                failure_threshold=5,
                window_s=1.0,
                cooldown_s=0.5,
                half_open_probes=2,
            )
            model.retry_budget(ratio=0.1, min_per_s=0.5, burst=4.0)
        return model

    # rho sweep confined to the metastable band: every lane is stable at
    # base load (rho <= 0.7) but locks undefended once retries amplify
    # demand past mu ((1 + 3) x 0.45 mu = 1.8 mu at the low end).
    sweeps = {
        "source_rate": np.linspace(
            0.45 * mu, 0.7 * mu, PALLAS_REPLICAS
        ).astype(np.float32)
    }
    max_events = int(12.0 * 0.7 * mu * horizon) + 64
    mesh = replica_mesh(jax.devices()[:1])

    def run(defended: bool, pallas: bool):
        with env_override("HS_TPU_PALLAS", "1" if pallas else "0"):
            return run_ensemble(
                build(defended),
                n_replicas=PALLAS_REPLICAS,
                seed=0,
                mesh=mesh,
                sweeps=sweeps,
                max_events=max_events,
            )

    def recovery_ratio(result) -> float:
        windows = result.timeseries.sink_count[:, 0].astype(np.float64)
        first_dark = int(outage[0] / (horizon / n_windows))
        pre = windows[1:first_dark].mean()  # skip the empty-start window
        post = windows[-3:].mean()
        return float(post / pre) if pre > 0 else 0.0

    arms = {}
    for defended in (False, True):
        lax_r = run(defended, False)
        kernel_r = run(defended, True)
        assert kernel_r.engine_path == "scan+pallas", kernel_r.kernel_decline
        assert lax_r.engine_path == "scan"
        counters = (
            "simulated_events",
            "sink_count",
            "server_completed",
            "server_timed_out",
            "server_retried",
            "server_fault_dropped",
            "server_fault_retried",
            "server_breaker_dropped",
            "breaker_tripped",
            "server_budget_dropped",
            "transit_dropped",
        )
        identical = all(
            np.array_equal(
                np.asarray(getattr(lax_r, name)),
                np.asarray(getattr(kernel_r, name)),
            )
            for name in counters
        ) and lax_r.breaker_open_fraction == kernel_r.breaker_open_fraction
        for name in lax_r.timeseries._ARRAY_FIELDS:
            lax_series = getattr(lax_r.timeseries, name)
            kernel_series = getattr(kernel_r.timeseries, name)
            if lax_series is None:
                identical &= kernel_series is None
                continue
            identical &= bool(
                np.array_equal(
                    np.asarray(lax_series),
                    np.asarray(kernel_series),
                    equal_nan=True,
                )
            )
        assert identical, (
            "resilience stack diverged between the Pallas kernel and the "
            "lax event step — breaker/shed/budget state must be "
            "bit-identical per lane"
        )
        arms["defended" if defended else "undefended"] = (
            kernel_r,
            recovery_ratio(kernel_r),
        )

    undefended_r, undefended_ratio = arms["undefended"]
    defended_r, defended_ratio = arms["defended"]
    # The phenomenon itself, not a tuned bound: defenses must buy
    # strictly more post-outage goodput than their absence.
    assert defended_ratio > undefended_ratio, (
        f"defended {defended_ratio:.3f} <= undefended {undefended_ratio:.3f}"
    )
    label = (
        f"goodput_recovery_ratio (CPU fallback, INTERPRETED kernel, {PALLAS_REPLICAS}-replica correlated-outage rho sweep)"
        if DEVICE_FALLBACK
        else f"goodput_recovery_ratio (Pallas kernel, {PALLAS_REPLICAS // 1000}k-replica correlated-outage rho sweep)"
    )
    return {
        "metric": label,
        "value": round(defended_ratio, 4),
        "unit": "post/pre goodput",
        "goodput_recovery_ratio_defended": round(defended_ratio, 4),
        "goodput_recovery_ratio_undefended": round(undefended_ratio, 4),
        "bit_identical_counters": True,
        "bit_identical_series": True,
        "kernel_chaos_defended": list(defended_r.kernel_chaos),
        "resilience_report": defended_r.engine_report()["resilience"],
        "undefended_retried_total": int(sum(undefended_r.server_retried)),
        "defended_retried_total": int(sum(defended_r.server_retried)),
        "defended_events_per_sec": round(defended_r.events_per_second, 0),
        "outage_window_s": list(outage),
        "n_windows": n_windows,
        "n_replicas": defended_r.n_replicas,
        "horizon_s": defended_r.horizon_s,
        "wall_seconds": round(defended_r.wall_seconds, 6),
        "compile_seconds": round(defended_r.compile_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def bench_pallas_kernel(devices) -> dict:
    """Fused-kernel vs lax-step A/B on the same M/M/1 event-scan
    workload. The two paths are BIT-IDENTICAL by contract (the kernel
    body drives the engine's own step closure; same RNG slot layout,
    same float op order per lane) — asserted here, together with the
    measured speedup and the SEPARATED compile cost of each path.
    """
    import jax

    from happysim_tpu.tpu import mm1_model, run_ensemble
    from happysim_tpu.tpu.kernels import (
        env_override,
        kernel_interpret_mode,
        pallas_available,
    )
    from happysim_tpu.tpu.mesh import replica_mesh

    if not pallas_available():
        # A jaxlib without pallas is a clean skip (matching the CI gate's
        # behavior), not a bench crash that discards every other entry.
        return {
            "metric": "simulated-events/sec (Pallas fused-step kernel)",
            "skipped": "jax.experimental.pallas unavailable in this jaxlib",
        }

    lam, mu = 8.0, 10.0
    model = mm1_model(
        lam=lam, mu=mu, horizon_s=PALLAS_HORIZON_S, warmup_s=PALLAS_HORIZON_S / 4
    )
    model.macro_block = PALLAS_MACRO_BLOCK
    # Explicit budget keeps both runs on the event scan (the chain
    # closed form would otherwise swallow the M/M/1) without truncating:
    # ~3 events/job plus headroom.
    max_events = int(4.0 * lam * PALLAS_HORIZON_S) + 64
    mesh = replica_mesh(jax.devices()[:1])  # 1-shard A/B (kernel is mesh-first)

    def run(pallas: bool):
        with env_override("HS_TPU_PALLAS", "1" if pallas else "0"):
            return run_ensemble(
                model,
                n_replicas=PALLAS_REPLICAS,
                seed=0,
                mesh=mesh,
                max_events=max_events,
            )

    lax_r = run(False)
    kernel_r = run(True)
    assert kernel_r.engine_path == "scan+pallas", kernel_r.kernel_decline
    assert lax_r.engine_path == "scan"
    bit_identical = bool(
        lax_r.simulated_events == kernel_r.simulated_events
        and lax_r.sink_count == kernel_r.sink_count
        and lax_r.sink_mean_latency_s == kernel_r.sink_mean_latency_s
        and lax_r.server_completed == kernel_r.server_completed
        and lax_r.server_mean_wait_s == kernel_r.server_mean_wait_s
        and (lax_r.sink_hist == kernel_r.sink_hist).all()
    )
    assert bit_identical, (
        "Pallas kernel diverged from the lax event step — the two paths "
        "must be bit-identical on every supported shape"
    )
    speedup = lax_r.wall_seconds / max(kernel_r.wall_seconds, 1e-9)
    interpret = kernel_interpret_mode()
    label = (
        f"simulated-events/sec (CPU fallback, INTERPRETED Pallas kernel, {PALLAS_REPLICAS}-replica M/M/1)"
        if DEVICE_FALLBACK
        else f"simulated-events/sec/chip (Pallas fused-step kernel, {PALLAS_REPLICAS // 1000}k-replica M/M/1)"
    )
    return {
        "metric": label,
        "value": round(kernel_r.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(
            kernel_r.events_per_second / REFERENCE_EVENTS_PER_SEC, 2
        ),
        "lax_events_per_sec": round(lax_r.events_per_second, 0),
        "kernel_vs_lax_speedup": round(speedup, 3),
        "bit_identical": bit_identical,
        "interpret_mode": bool(interpret),
        "macro_block": PALLAS_MACRO_BLOCK,
        "n_replicas": kernel_r.n_replicas,
        "horizon_s": kernel_r.horizon_s,
        "simulated_events": kernel_r.simulated_events,
        "wall_seconds": round(kernel_r.wall_seconds, 6),
        "lax_wall_seconds": round(lax_r.wall_seconds, 6),
        "compile_seconds": round(kernel_r.compile_seconds, 6),
        "lax_compile_seconds": round(lax_r.compile_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }


def _reduce_seconds_ab(mesh, n_replicas: int, n_windows: int) -> dict:
    """Host-vs-device A/B of the cross-replica reduce itself, at the
    bench run's shapes: (R,) int32 events, (R, nW) int32 window counts,
    (R, nV=1) float32 busy integrals. Device = the engine's limb/fixed
    reductions compiled once and timed pure; host = the pre-ISSUE-13
    path (fetch every per-replica array, sum in numpy int64/float64).
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from happysim_tpu.tpu.mesh import replica_sharding
    from happysim_tpu.tpu.reduce import sum_f32_fixed, sum_i64_limbs

    rng = np.random.RandomState(0)
    events = rng.randint(0, 512, size=(n_replicas,)).astype(np.int32)
    counts = rng.randint(0, 64, size=(n_replicas, n_windows)).astype(np.int32)
    busy = rng.rand(n_replicas, 1).astype(np.float32)
    sharding = replica_sharding(mesh)
    dev = {
        "events": jax.device_put(events, sharding),
        "counts": jax.device_put(counts, sharding),
        "busy": jax.device_put(busy, sharding),
    }

    def device_reduce(tree):
        return {
            "events": sum_i64_limbs(tree["events"]),
            "counts": sum_i64_limbs(tree["counts"]),
            "busy": sum_f32_fixed(tree["busy"]),
        }

    reduce_fn = jax.jit(device_reduce).lower(dev).compile()
    jax.block_until_ready(reduce_fn(dev))  # warm
    start = time.perf_counter()
    jax.block_until_ready(reduce_fn(dev))
    device_s = time.perf_counter() - start

    start = time.perf_counter()
    host_events = int(np.asarray(dev["events"]).sum(dtype=np.int64))
    host_counts = np.asarray(dev["counts"]).astype(np.int64).sum(axis=0)
    host_busy = np.asarray(dev["busy"], np.float64).sum(axis=0)
    host_s = time.perf_counter() - start
    del host_events, host_counts, host_busy
    return {
        "device_seconds": round(device_s, 6),
        "host_seconds": round(host_s, 6),
    }


def _multichip_measure(devices, n_devices: int, virtual: bool) -> dict:
    """Per-chip engine throughput of the FAULTED + TELEMETRY rho-sweep
    M/M/1 on an n-device replica-sharded mesh vs the identical workload
    on a 1-device mesh (explicit max_events keeps both runs on the
    general event scan with the same budget). Mesh-shape bit-identity of
    the counters AND every windowed series is asserted — the layout
    moves only wall time, never a number.
    """
    import numpy as np

    from happysim_tpu.tpu import run_ensemble
    from happysim_tpu.tpu.mesh import replica_mesh
    from happysim_tpu.tpu.model import EnsembleModel, FaultSpec

    mu = 10.0
    n_replicas = MULTICHIP_VIRTUAL_REPLICAS if virtual else MULTICHIP_REPLICAS

    def build():
        model = EnsembleModel(
            horizon_s=MULTICHIP_HORIZON_S, warmup_s=MULTICHIP_HORIZON_S / 6
        )
        src = model.source(rate=0.95 * mu)  # swept per replica below
        srv = model.server(
            service_mean=1.0 / mu,
            queue_capacity=256,
            deadline_s=8.0,
            max_retries=2,
            fault=FaultSpec(rate=0.05, mean_duration_s=0.5),
        )
        snk = model.sink()
        model.connect(src, srv)
        model.connect(srv, snk)
        model.telemetry(window_s=MULTICHIP_HORIZON_S / MULTICHIP_WINDOWS)
        return model

    sweeps = {
        "source_rate": np.linspace(0.1 * mu, 0.95 * mu, n_replicas).astype(
            np.float32
        )
    }

    def run(nd: int):
        return run_ensemble(
            build(),
            n_replicas=n_replicas,
            seed=0,
            mesh=replica_mesh(devices[:nd]),
            max_events=MULTICHIP_MAX_EVENTS,
            sweeps=sweeps,
        )

    single = run(1)
    multi = run(n_devices)
    speedup = multi.events_per_second / max(single.events_per_second, 1e-9)
    per_chip = multi.events_per_second / n_devices
    mesh_kind = "virtual CPU mesh" if virtual else "TPU mesh"
    counters_identical = bool(
        single.sink_count == multi.sink_count
        and single.simulated_events == multi.simulated_events
        and single.server_fault_dropped == multi.server_fault_dropped
        and single.server_timed_out == multi.server_timed_out
        and single.sink_mean_latency_s == multi.sink_mean_latency_s
        and np.array_equal(single.sink_hist, multi.sink_hist)
    )
    series_identical = bool(single.timeseries == multi.timeseries)
    # Enforced, not just recorded: a layout that moves a single number
    # invalidates every multi-chip claim this entry makes.
    assert counters_identical and series_identical, (
        "mesh-shape bit-identity broke: the 1-device and "
        f"{n_devices}-device runs disagree "
        f"(counters={counters_identical}, series={series_identical})"
    )
    return {
        "metric": (
            f"MULTICHIP per-chip events/sec (faulted+telemetry rho-sweep "
            f"M/M/1, {n_devices}-device {mesh_kind})"
        ),
        "tag": "MULTICHIP",
        "value": round(per_chip, 0),
        "unit": "events/sec/chip",
        "n_devices": n_devices,
        "virtual_mesh": virtual,
        "aggregate_events_per_sec": round(multi.events_per_second, 0),
        "single_device_events_per_sec": round(single.events_per_second, 0),
        "multichip_speedup": round(speedup, 2),
        # The ROADMAP exit criterion: >= per-chip single-device
        # throughput at N chips WITH telemetry enabled. A real-hardware
        # claim — on the shared-core virtual mesh the honest gate is the
        # aggregate speedup.
        "per_chip_ok": (
            bool(per_chip >= single.events_per_second)
            if not virtual
            else None
        ),
        "multichip_ok": bool(speedup >= 1.6),
        "bit_identical_counters": counters_identical,
        "bit_identical_series": series_identical,
        "reduce_seconds": _reduce_seconds_ab(
            replica_mesh(devices[:n_devices]), n_replicas, MULTICHIP_WINDOWS
        ),
        "engine_mesh_report": multi.engine_report()["mesh"],
        "n_replicas": multi.n_replicas,
        "simulated_events": multi.simulated_events,
        "wall_seconds": round(multi.wall_seconds, 6),
        "single_device_wall_seconds": round(single.wall_seconds, 6),
        "compile_seconds": round(multi.compile_seconds, 6),
        "single_device_compile_seconds": round(single.compile_seconds, 6),
        "device": str(devices[0]),
    }


def bench_multichip_mesh(devices) -> dict:
    """MULTICHIP entry. With >1 real device, measure on the real mesh
    in-process; on a single-chip host, spawn a child pinned to the
    virtual 8-device CPU mesh (the XLA host-device-count flag must be
    set before jax initializes, hence the subprocess)."""
    if len(devices) > 1:
        return _multichip_measure(devices, len(devices), virtual=False)

    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={MULTICHIP_VIRTUAL_DEVICES}"
        ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--multichip-virtual"],
            env=env,
            capture_output=True,
            text=True,
            timeout=1800,
        )
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return {
            "metric": "MULTICHIP per-chip events/sec (virtual mesh)", "tag": "MULTICHIP",
            "error": "child emitted no JSON",
            "rc": proc.returncode,
            "stderr_tail": proc.stderr[-500:],
        }
    except subprocess.TimeoutExpired:
        return {
            "metric": "MULTICHIP per-chip events/sec (virtual mesh)", "tag": "MULTICHIP",
            "error": "child timed out",
        }


def _multichip_virtual_child() -> int:
    """Entry for the ``--multichip-virtual`` child: env was pinned to the
    CPU platform with virtual devices by the parent before python started."""
    import jax

    devices = jax.devices()
    n = min(MULTICHIP_VIRTUAL_DEVICES, len(devices))
    print(json.dumps(_multichip_measure(devices, n, virtual=True)))
    return 0


def _consensus_measure(devices, n_devices: int, virtual: bool) -> dict:
    """Quorum-liveness under partition at ensemble scale: a rho-sweep
    3-server quorum cluster (write=2, read=2) whose majority {s1, s2}
    is cut by a deterministic partition window, run as two arms —
    UNDEFENDED (every quorum rejection retries on a backoff; the
    post-heal storm of deadline retries keeps demand above capacity)
    and DEFENDED (retry budget + circuit breaker fail the dark window
    fast and cap the storm) — each recording
    ``availability_recovery_ratio`` = post-heal / pre-partition
    per-window goodput. Both arms run the lax scan (consensus declines
    the kernel by name); 1-vs-n-device mesh bit-identity of every
    consensus counter AND windowed series is asserted instead.
    """
    import numpy as np

    from happysim_tpu.tpu import run_ensemble
    from happysim_tpu.tpu.mesh import replica_mesh
    from happysim_tpu.tpu.model import EnsembleModel

    mu = 8.0  # per server; 3 servers -> cluster capacity 3 mu
    horizon = CONSENSUS_HORIZON_S
    n_windows = CONSENSUS_WINDOWS
    dark = (0.3 * horizon, 0.45 * horizon)
    n_replicas = CONSENSUS_VIRTUAL_REPLICAS if virtual else CONSENSUS_REPLICAS

    def build(defended: bool):
        model = EnsembleModel(horizon_s=horizon, transit_capacity=16)
        src = model.source(rate=0.6 * 3 * mu)  # swept per replica below
        servers = [
            model.server(
                service_mean=1.0 / mu,
                queue_capacity=512,
                deadline_s=0.5,
                max_retries=3,
                retry_backoff_s=1.0,
            )
            for _ in range(3)
        ]
        router = model.router(policy="round_robin")
        snk = model.sink()
        model.connect(src, router)
        for server in servers:
            model.connect(
                router, server, latency_s=0.005, latency_kind="constant"
            )
            model.connect(server, snk)
        model.telemetry(
            window_s=horizon / n_windows, metrics=("throughput", "rates")
        )
        model.network_partition(group=[servers[1], servers[2]], windows=(dark,))
        model.quorum(servers, write=2, read=2)
        model.leader_election(servers, heartbeat_s=0.25, timeout_s=0.75)
        if defended:
            model.circuit_breaker(
                failure_threshold=5,
                window_s=1.0,
                cooldown_s=0.5,
                half_open_probes=2,
            )
            model.retry_budget(ratio=0.1, min_per_s=0.5, burst=4.0)
        return model

    # rho sweep of CLUSTER load: stable at base rate, but the dark
    # window converts every arrival into quorum-rejected retries.
    sweeps = {
        "source_rate": np.linspace(
            0.45 * 3 * mu, 0.7 * 3 * mu, n_replicas
        ).astype(np.float32)
    }
    max_events = int(12.0 * 0.7 * 3 * mu * horizon) + 64

    def run(defended: bool, nd: int):
        return run_ensemble(
            build(defended),
            n_replicas=n_replicas,
            seed=0,
            mesh=replica_mesh(devices[:nd]),
            sweeps=sweeps,
            max_events=max_events,
        )

    def recovery_ratio(result) -> float:
        windows = result.timeseries.sink_count[:, 0].astype(np.float64)
        first_dark = int(dark[0] / (horizon / n_windows))
        pre = windows[1:first_dark].mean()  # skip the empty-start window
        post = windows[-3:].mean()
        return float(post / pre) if pre > 0 else 0.0

    consensus_counters = (
        "simulated_events",
        "sink_count",
        "network_partitioned",
        "server_quorum_dropped",
        "quorum_dark_fraction",
        "leader_changes",
        "time_without_leader_fraction",
        "server_retried",
        "server_timed_out",
        "truncated_replicas",
    )
    arms = {}
    for defended in (False, True):
        single = run(defended, 1)
        multi = run(defended, n_devices)
        assert single.engine_path == "scan" and multi.engine_path == "scan"
        identical = all(
            np.array_equal(
                np.asarray(getattr(single, name)),
                np.asarray(getattr(multi, name)),
            )
            for name in consensus_counters
        )
        identical &= bool(single.timeseries == multi.timeseries)
        assert identical, (
            "consensus stack diverged between the 1-device and "
            f"{n_devices}-device meshes — partition/quorum/leader state "
            "must be bit-identical per lane"
        )
        arms["defended" if defended else "undefended"] = (
            multi,
            recovery_ratio(multi),
        )

    undefended_r, undefended_ratio = arms["undefended"]
    defended_r, defended_ratio = arms["defended"]
    # The phenomenon itself, not a tuned bound: defenses must buy
    # strictly more post-heal goodput than their absence.
    assert defended_ratio > undefended_ratio, (
        f"defended {defended_ratio:.3f} <= undefended {undefended_ratio:.3f}"
    )
    mesh_kind = "virtual CPU mesh" if virtual else "TPU mesh"
    return {
        "metric": (
            f"availability_recovery_ratio ({n_replicas}-replica "
            f"quorum-liveness rho sweep, {n_devices}-device {mesh_kind})"
        ),
        "tag": "CONSENSUS",
        "value": round(defended_ratio, 4),
        "unit": "post/pre goodput",
        "availability_recovery_ratio_defended": round(defended_ratio, 4),
        "availability_recovery_ratio_undefended": round(undefended_ratio, 4),
        "bit_identical_counters": True,
        "bit_identical_series": True,
        "n_devices": n_devices,
        "virtual_mesh": virtual,
        "quorum_dark_fraction": round(defended_r.quorum_dark_fraction, 6),
        "leader_changes_total": int(defended_r.leader_changes),
        "time_without_leader_fraction": round(
            defended_r.time_without_leader_fraction, 6
        ),
        "quorum_dropped_total": int(sum(defended_r.server_quorum_dropped)),
        "network_partitioned_total": int(defended_r.network_partitioned),
        "consensus_report": defended_r.engine_report()["consensus"],
        "undefended_retried_total": int(sum(undefended_r.server_retried)),
        "defended_retried_total": int(sum(defended_r.server_retried)),
        "defended_events_per_sec": round(defended_r.events_per_second, 0),
        "partition_window_s": list(dark),
        "n_windows": n_windows,
        "n_replicas": defended_r.n_replicas,
        "horizon_s": defended_r.horizon_s,
        "wall_seconds": round(defended_r.wall_seconds, 6),
        "compile_seconds": round(defended_r.compile_seconds, 6),
        "device": str(devices[0]),
    }


def bench_consensus(devices) -> dict:
    """CONSENSUS entry. With >1 real device, measure on the real mesh
    in-process; on a single-chip host, spawn a child pinned to the
    virtual 8-device CPU mesh (the XLA host-device-count flag must be
    set before jax initializes, hence the subprocess)."""
    if len(devices) > 1:
        return _consensus_measure(devices, len(devices), virtual=False)

    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={CONSENSUS_VIRTUAL_DEVICES}"
        ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--consensus-virtual"],
            env=env,
            capture_output=True,
            text=True,
            timeout=1800,
        )
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return {
            "metric": "availability_recovery_ratio (quorum-liveness rho sweep)",
            "tag": "CONSENSUS",
            "error": "child emitted no JSON",
            "rc": proc.returncode,
            "stderr_tail": proc.stderr[-500:],
        }
    except subprocess.TimeoutExpired:
        return {
            "metric": "availability_recovery_ratio (quorum-liveness rho sweep)",
            "tag": "CONSENSUS",
            "error": "child timed out",
        }


def _consensus_virtual_child() -> int:
    """Entry for the ``--consensus-virtual`` child: env was pinned to the
    CPU platform with virtual devices by the parent before python started."""
    import jax

    devices = jax.devices()
    n = min(CONSENSUS_VIRTUAL_DEVICES, len(devices))
    print(json.dumps(_consensus_measure(devices, n, virtual=True)))
    return 0


def _trace_measure(devices, n_devices: int, virtual: bool) -> dict:
    """Streamed trace ingestion at ensemble scale: every replica replays
    the SAME recorded arrival trace, paged host->device in
    TRACE_CHUNK_LEN-arrival chunks double-buffered around the event
    scan. Two open-world shapes are measured — a diurnal sinusoid and a
    flash crowd — each on a 1-device and an n-device mesh, with
    bit-identity of every counter AND windowed series asserted across
    the mesh shapes (the page schedule moves wall time, never a
    number). The per-scenario stall fraction is the honesty metric for
    the double buffer itself: 0.0 means the next page was always
    resident before the device asked for it.
    """
    import numpy as np

    from happysim_tpu.tpu import run_ensemble
    from happysim_tpu.tpu.mesh import replica_mesh
    from happysim_tpu.tpu.model import EnsembleModel
    from happysim_tpu.tpu.traces import diurnal_trace, flash_crowd_trace

    n_replicas = TRACE_VIRTUAL_REPLICAS if virtual else TRACE_REPLICAS
    horizon = TRACE_HORIZON_S
    scenarios = {
        "diurnal": diurnal_trace(
            base_rate=200.0,
            amplitude=0.6,
            period_s=horizon / 2,
            horizon_s=horizon,
            seed=11,
            chunk_len=TRACE_CHUNK_LEN,
        ),
        "flash_crowd": flash_crowd_trace(
            base_rate=100.0,
            spike_rate=500.0,
            spike_start_s=horizon / 4,
            spike_end_s=horizon * 3 / 8,
            horizon_s=horizon,
            seed=11,
            chunk_len=TRACE_CHUNK_LEN,
        ),
    }

    def build(trace):
        model = EnsembleModel(horizon_s=horizon, macro_block=16)
        src = model.trace_arrivals(trace)
        srv = model.server(concurrency=4, service_mean=0.004, queue_capacity=64)
        snk = model.sink()
        model.connect(src, srv)
        model.connect(srv, snk)
        model.telemetry(
            window_s=2.0, metrics=("throughput", "latency", "rates")
        )
        return model

    def run(trace, nd: int):
        return run_ensemble(
            build(trace),
            n_replicas=n_replicas,
            seed=0,
            mesh=replica_mesh(devices[:nd]),
            max_events=TRACE_MAX_EVENTS,
        )

    mesh_kind = "virtual CPU mesh" if virtual else "TPU mesh"
    per_scenario = {}
    for name, trace in scenarios.items():
        single = run(trace, 1)
        multi = run(trace, n_devices)
        counters_identical = bool(
            single.simulated_events == multi.simulated_events
            and single.sink_count == multi.sink_count
            and single.server_dropped == multi.server_dropped
            and single.trace_tenant_arrivals == multi.trace_tenant_arrivals
            and single.sink_p99_s == multi.sink_p99_s
            and np.array_equal(single.sink_hist, multi.sink_hist)
        )
        series_identical = bool(single.timeseries == multi.timeseries)
        report = multi.engine_report()["trace"]
        assert multi.engine_path == "scan" and report["enabled"]
        # Enforced, not just recorded — a page schedule that moves one
        # number invalidates the trace determinism contract.
        assert counters_identical and series_identical, (
            f"trace mesh bit-identity broke on {name} "
            f"(counters={counters_identical}, series={series_identical})"
        )
        assert report["max_resident_chunks"] <= 2, report
        per_scenario[name] = {
            "events_per_sec_per_chip": round(
                multi.events_per_second / n_devices, 0
            ),
            "aggregate_events_per_sec": round(multi.events_per_second, 0),
            "single_device_events_per_sec": round(single.events_per_second, 0),
            "n_arrivals": trace.n_arrivals,
            "n_chunks": report["n_chunks"],
            "chunks_streamed": report["chunks_streamed"],
            "max_resident_chunks": report["max_resident_chunks"],
            "buffer_stall_fraction": round(report["stall_fraction"], 6),
            "buffer_stall_seconds": round(report["buffer_stall_seconds"], 6),
            "stream_steps": report["stream_steps"],
            "bit_identical_counters": counters_identical,
            "bit_identical_series": series_identical,
            "simulated_events": multi.simulated_events,
            "wall_seconds": round(multi.wall_seconds, 6),
            "compile_seconds": round(multi.compile_seconds, 6),
        }

    flash = per_scenario["flash_crowd"]
    return {
        "metric": (
            f"TRACE per-chip events/sec (streamed flash-crowd trace, "
            f"{n_devices}-device {mesh_kind})"
        ),
        "tag": "TRACE",
        "value": flash["events_per_sec_per_chip"],
        "unit": "events/sec/chip",
        "n_devices": n_devices,
        "virtual_mesh": virtual,
        "n_replicas": n_replicas,
        "chunk_len": TRACE_CHUNK_LEN,
        "buffer_stall_fraction": flash["buffer_stall_fraction"],
        "bit_identical_counters": all(
            s["bit_identical_counters"] for s in per_scenario.values()
        ),
        "bit_identical_series": all(
            s["bit_identical_series"] for s in per_scenario.values()
        ),
        "scenarios": per_scenario,
        "device": str(devices[0]),
    }


def bench_trace_ingestion(devices) -> dict:
    """TRACE entry. With >1 real device, measure on the real mesh
    in-process; on a single-chip host, spawn a child pinned to the
    virtual 8-device CPU mesh (the XLA host-device-count flag must be
    set before jax initializes, hence the subprocess)."""
    if len(devices) > 1:
        return _trace_measure(devices, len(devices), virtual=False)

    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={TRACE_VIRTUAL_DEVICES}"
        ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--trace-virtual"],
            env=env,
            capture_output=True,
            text=True,
            timeout=1800,
        )
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return {
            "metric": "TRACE per-chip events/sec (streamed trace, virtual mesh)",
            "tag": "TRACE",
            "error": "child emitted no JSON",
            "rc": proc.returncode,
            "stderr_tail": proc.stderr[-500:],
        }
    except subprocess.TimeoutExpired:
        return {
            "metric": "TRACE per-chip events/sec (streamed trace, virtual mesh)",
            "tag": "TRACE",
            "error": "child timed out",
        }


def _trace_virtual_child() -> int:
    """Entry for the ``--trace-virtual`` child: env was pinned to the
    CPU platform with virtual devices by the parent before python started."""
    import jax

    devices = jax.devices()
    n = min(TRACE_VIRTUAL_DEVICES, len(devices))
    print(json.dumps(_trace_measure(devices, n, virtual=True)))
    return 0


def _default_cache_dir() -> str:
    """Per-user persistent XLA cache dir, with the same squat-resistance
    discipline as the fallback stub above: the path is predictable, and
    the cache DESERIALIZES compiled executables, so it must never point
    at a directory another user could have pre-seeded."""
    import tempfile

    uid = os.getuid() if hasattr(os, "getuid") else None
    path = os.path.join(tempfile.gettempdir(), f"happysim_tpu_xla_cache_{uid}")
    try:
        os.makedirs(path, mode=0o700, exist_ok=True)
        if uid is not None and os.stat(path).st_uid != uid:
            raise OSError("cache dir owned by another user")
    except OSError:
        # Squatted or unusable: take a private one-off dir (loses reuse
        # across runs in this adversarial case — acceptable).
        path = tempfile.mkdtemp(prefix="happysim_tpu_xla_cache_")
    return path


def _wait_for_tpu() -> bool:
    """Retry the reachability probe so a transiently WEDGED tunnel yields a
    DELAYED TPU bench instead of a CPU fallback. A fast "no accelerator"
    exit is permanent — fall back immediately, don't stall a CPU-only box.
    Budget via HS_BENCH_TPU_WAIT_S (default 20 min; 0 = single probe)."""
    import time

    try:
        budget_s = float(os.environ.get("HS_BENCH_TPU_WAIT_S", "1200"))
    except ValueError:
        budget_s = 1200.0
    deadline = time.monotonic() + budget_s
    attempt = 0
    while True:
        attempt += 1
        verdict = _tpu_probe()
        if verdict == "ok":
            return True
        if verdict == "absent" or time.monotonic() >= deadline:
            return False
        print(
            json.dumps(
                {
                    "note": "TPU tunnel wedged; retrying",
                    "attempt": attempt,
                    "remaining_s": round(deadline - time.monotonic(), 0),
                }
            ),
            file=sys.stderr,
        )
        time.sleep(min(120.0, max(1.0, deadline - time.monotonic())))


def main() -> int:
    if "--multichip-virtual" in sys.argv:
        return _multichip_virtual_child()
    if "--consensus-virtual" in sys.argv:
        return _consensus_virtual_child()
    if "--trace-virtual" in sys.argv:
        return _trace_virtual_child()
    if os.environ.get("HS_BENCH_CPU_FALLBACK") == "1":
        _apply_fallback_scale()
    elif not _wait_for_tpu():
        _reexec_cpu_fallback()  # does not return
    # Persistent XLA compilation cache: repeated bench invocations stop
    # re-lowering identical topologies (docs/tpu-engine.md "Compilation
    # cache"). Export HS_TPU_COMPILE_CACHE yourself to relocate or
    # pre-seed it; empty-string disables.
    os.environ.setdefault("HS_TPU_COMPILE_CACHE", _default_cache_dir())
    import jax

    from happysim_tpu.tpu import maybe_enable_compile_cache

    maybe_enable_compile_cache()

    devices = jax.devices()
    kernel = bench_kernel(devices)
    engine = bench_general_engine(devices)
    hetero = bench_hetero_sweep(devices)
    telemetry = bench_telemetry_overhead(devices)
    pallas = bench_pallas_kernel(devices)
    ktel = bench_kernel_telemetry(devices)
    krouter = bench_kernel_router(devices)
    kgraph = bench_kernel_graph(devices)
    kchaos = bench_kernel_chaos(devices)
    resilience = bench_resilience(devices)
    multichip = bench_multichip_mesh(devices)
    consensus = bench_consensus(devices)
    trace = bench_trace_ingestion(devices)
    if DEVICE_FALLBACK:
        note = "TPU unreachable at bench time; CPU fallback at reduced scale"
        kernel["device_fallback"] = note
        engine["device_fallback"] = note
        hetero["device_fallback"] = note
        telemetry["device_fallback"] = note
        pallas["device_fallback"] = note
        ktel["device_fallback"] = note
        krouter["device_fallback"] = note
        kgraph["device_fallback"] = note
        kchaos["device_fallback"] = note
        resilience["device_fallback"] = note
        engine["north_star_ok"] = False  # per-chip target is a TPU claim
    # The general-engine entry stays LAST: trajectory tooling that keys
    # on the final JSON line keeps comparing like with like across rounds.
    print(json.dumps(kernel))
    print(json.dumps(hetero))
    print(json.dumps(telemetry))
    print(json.dumps(pallas))
    print(json.dumps(ktel))
    print(json.dumps(krouter))
    print(json.dumps(kgraph))
    print(json.dumps(kchaos))
    print(json.dumps(resilience))
    print(json.dumps(multichip))
    print(json.dumps(consensus))
    print(json.dumps(trace))
    print(json.dumps(engine))
    return 0


if __name__ == "__main__":
    sys.exit(main())
