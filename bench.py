#!/usr/bin/env python
"""Headline benchmark: 65k-replica M/M/1 ensemble on the TPU executor.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Baseline: the reference's single-core heap executor does ~134,580 events/s
on its M/M/1 throughput scenario (BASELINE.md); the BASELINE.json north-star
target is >=10M simulated events/sec/chip with mean wait within 1% of
rho/(mu-lambda).
"""

import json
import sys

REFERENCE_EVENTS_PER_SEC = 134_580.0  # BASELINE.md throughput checkpoint


def main() -> int:
    import jax

    from happysim_tpu.tpu import run_mm1_ensemble

    result = run_mm1_ensemble(
        lam=8.0,
        mu=10.0,
        n_replicas=65536,
        n_customers=4096,
        seed=0,
    )
    devices = jax.devices()
    record = {
        "metric": "simulated-events/sec/chip (65k-replica M/M/1 ensemble)",
        "value": round(result.events_per_second, 0),
        "unit": "events/sec",
        "vs_baseline": round(result.events_per_second / REFERENCE_EVENTS_PER_SEC, 2),
        "mean_wait_s": round(result.mean_wait_s, 6),
        "analytic_wait_s": result.analytic_wait_s,
        "wait_error_rel": round(result.wait_error_rel, 6),
        "accuracy_ok": bool(result.wait_error_rel < 0.01),
        "n_replicas": result.n_replicas,
        "customers_per_replica": result.customers_per_replica,
        "simulated_events": result.simulated_events,
        "wall_seconds": round(result.wall_seconds, 6),
        "device": str(devices[0]),
        "n_devices": len(devices),
    }
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
