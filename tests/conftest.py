"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver dry-runs the real multi-chip path
separately via __graft_entry__.dryrun_multichip). Flags must be set before
jax initializes a backend, hence the top-of-conftest placement.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

try:  # Force the CPU backend even when a TPU plugin self-registered
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:  # pragma: no cover - jax-less environments
    pass


@pytest.fixture
def test_output_dir(tmp_path):
    return tmp_path


@pytest.fixture(scope="session")
def cpu_devices():
    """8 virtual CPU devices (JAX_PLATFORMS may be pinned to a TPU platform
    by the environment, so request the cpu backend explicitly)."""
    import jax

    devices = jax.devices("cpu")
    assert len(devices) >= 8, "xla_force_host_platform_device_count not applied"
    return devices


@pytest.fixture(scope="session")
def cpu_mesh(cpu_devices):
    from happysim_tpu.tpu.mesh import replica_mesh

    return replica_mesh(cpu_devices[:8])
