"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver dry-runs the real multi-chip path
separately via __graft_entry__.dryrun_multichip). Flags must be set before
jax initializes a backend, hence the top-of-conftest placement.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

try:  # Force the CPU backend even when a TPU plugin self-registered
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:  # pragma: no cover - jax-less environments
    pass


# Files whose tests compile XLA programs (minutes each on the CPU backend).
# Auto-marked `tpu` so `-m "not tpu"` is the fast (<60s) developer loop;
# `tests/unit` stays unmarked and runs in seconds.
_TPU_TEST_FILES = {
    "test_tpu_engine.py",
    "test_tpu_mg1.py",
    "test_tpu_mm1.py",
    "test_tpu_widened.py",
    "test_tpu_outage.py",
    "test_tpu_partitioned.py",
    "test_tpu_opinion.py",
    "test_analysis_tpu.py",
    "test_mm1_queue.py",
    "test_tpu_checkpoint.py",
    "test_tpu_macro_block.py",
    "test_tpu_telemetry.py",
    "test_arrival_regression.py",
    "test_telemetry_regression.py",
    "test_router_regression.py",
    "test_graph_regression.py",
    "test_chaos_regression.py",
    "test_resilience_regression.py",
    "test_tpu_resilience.py",
    "test_tpu_pallas.py",
    "test_kernel_event_step.py",
    "test_kernel_regression.py",
    "test_engine_path_reasons.py",
    "test_tpu_mesh.py",
    "test_tpu_mesh_resume.py",
    "test_tpu_consensus.py",
    "test_consensus_regression.py",
    "test_traces.py",
    "test_tpu_traces.py",
    "test_trace_regression.py",
}
# Long host-side suites (examples execute end-to-end, some on the TPU path).
_SLOW_TEST_FILES = {"test_examples.py"}


def pytest_collection_modifyitems(config, items):
    for item in items:
        name = item.path.name if hasattr(item, "path") else item.fspath.basename
        if name in _TPU_TEST_FILES:
            item.add_marker(pytest.mark.tpu)
        elif name in _SLOW_TEST_FILES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture
def test_output_dir(tmp_path):
    return tmp_path


@pytest.fixture(scope="session")
def cpu_devices():
    """8 virtual CPU devices (JAX_PLATFORMS may be pinned to a TPU platform
    by the environment, so request the cpu backend explicitly)."""
    import jax

    devices = jax.devices("cpu")
    assert len(devices) >= 8, "xla_force_host_platform_device_count not applied"
    return devices


@pytest.fixture(scope="session")
def cpu_mesh(cpu_devices):
    from happysim_tpu.tpu.mesh import replica_mesh

    return replica_mesh(cpu_devices[:8])
