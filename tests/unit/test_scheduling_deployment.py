"""Unit tests: scheduling (DAG jobs, work stealing) + deployment."""

import pytest

from happysim_tpu import (
    ConstantLatency,
    Entity,
    Event,
    Instant,
    LoadBalancer,
    Server,
    Simulation,
)
from happysim_tpu.components.deployment import (
    AutoScaler,
    CanaryDeployer,
    CanaryStage,
    ErrorRateEvaluator,
    QueueDepthScaling,
    RollingDeployer,
    StepScaling,
    TargetUtilization,
)
from happysim_tpu.components.scheduling import (
    JobDefinition,
    JobScheduler,
    WorkStealingPool,
)


def t(seconds):
    return Instant.from_seconds(seconds)


class Recorder(Entity):
    def __init__(self, name, work_s=0.1):
        super().__init__(name)
        self.work_s = work_s
        self.runs = []

    def handle_event(self, event):
        self.runs.append(round(self.now.to_seconds(), 3))
        yield self.work_s


# ------------------------------------------------------------ JobScheduler ----
class TestJobScheduler:
    def test_dag_order_respected(self):
        extract = Recorder("extract", work_s=1.0)
        transform = Recorder("transform", work_s=1.0)
        load = Recorder("load", work_s=1.0)
        scheduler = JobScheduler("etl", tick_interval=0.5)
        scheduler.add_job(JobDefinition(name="extract", target=extract))
        scheduler.add_job(
            JobDefinition(name="transform", target=transform, dependencies=("extract",))
        )
        scheduler.add_job(
            JobDefinition(name="load", target=load, dependencies=("transform",))
        )
        sim = Simulation(entities=[scheduler, extract, transform, load], duration=30.0)
        sim.schedule(scheduler.start())
        sim.run()
        assert len(extract.runs) == 1
        assert len(transform.runs) == 1
        assert len(load.runs) == 1
        assert extract.runs[0] < transform.runs[0] < load.runs[0]
        # transform starts only after extract COMPLETES (1s of work).
        assert transform.runs[0] >= extract.runs[0] + 1.0
        assert scheduler.stats.jobs_completed == 3

    def test_unknown_dependency_rejected(self):
        scheduler = JobScheduler("s")
        with pytest.raises(ValueError):
            scheduler.add_job(JobDefinition(name="a", target=Recorder("r"), dependencies=("nope",)))

    def test_disabled_job_not_dispatched(self):
        job = Recorder("job")
        scheduler = JobScheduler("s", tick_interval=0.5)
        scheduler.add_job(JobDefinition(name="job", target=job))
        scheduler.disable_job("job")
        sim = Simulation(entities=[scheduler, job], duration=5.0)
        sim.schedule(scheduler.start())
        sim.run()
        assert job.runs == []


# -------------------------------------------------------- WorkStealingPool ----
class TestWorkStealingPool:
    def test_tasks_complete_and_balance(self):
        done = []

        class Collector(Entity):
            def handle_event(self, event):
                done.append(event.context.get("metadata", {}).get("task_id"))
                return None

        collector = Collector("collector")
        pool = WorkStealingPool("pool", num_workers=4, downstream=collector,
                                default_processing_time=0.1)
        sim = Simulation(entities=[pool, *pool.workers, collector], duration=60.0)
        sim.schedule([
            Event(t(0.0), "task", target=pool,
                  context={"metadata": {"task_id": i}})
            for i in range(20)
        ])
        sim.run()
        assert sorted(done) == list(range(20))
        assert pool.stats.tasks_completed == 20
        # Work spread across workers (shortest-queue placement).
        assert sum(1 for w in pool.worker_stats if w.tasks_completed > 0) >= 3

    def test_stealing_rebalances_skew(self):
        pool = WorkStealingPool("pool", num_workers=2, default_processing_time=0.05)
        # Force ALL work onto worker 0's queue, then wake both workers:
        # worker 1 finds its queue empty and must steal.
        for i in range(10):
            task = Event(t(0.0), "task", target=pool,
                         context={"metadata": {"task_id": i}})
            pool.workers[0]._queue.appendleft(task)
        sim = Simulation(entities=[pool, *pool.workers], duration=60.0)
        sim.schedule([
            Event(t(0.0), "_worker_try_next", target=pool.workers[0]),
            Event(t(0.0), "_worker_try_next", target=pool.workers[1]),
        ])
        sim.run()
        completed = sum(w.tasks_completed for w in pool.worker_stats)
        assert completed == 10
        assert pool.stats.total_steals > 0  # idle worker stole from busy one
        assert pool.worker_stats[1].tasks_stolen > 0


# -------------------------------------------------------------- AutoScaler ----
class TestAutoScaler:
    def _fleet(self, n=1):
        lb = LoadBalancer("lb")
        servers = [Server(f"s{i}", concurrency=2, service_time=ConstantLatency(0.5))
                   for i in range(n)]
        for s in servers:
            lb.add_backend(s)
        return lb, servers

    def test_scale_out_under_load(self):
        lb, servers = self._fleet(1)
        created = []

        def factory(name):
            server = Server(name, concurrency=2, service_time=ConstantLatency(0.5))
            created.append(server)
            return server

        scaler = AutoScaler("scaler", lb, factory, policy=TargetUtilization(0.5),
                            min_instances=1, max_instances=5,
                            evaluation_interval=1.0, scale_out_cooldown=0.0,
                            scale_in_cooldown=1000.0)
        sim = Simulation(entities=[lb, scaler, *servers], duration=30.0)
        sim.schedule(scaler.start())
        # Hammer the LB so utilization stays high.
        sim.schedule([Event(t(0.01 * i), "req", target=lb) for i in range(400)])
        sim.run()
        assert scaler.stats.scale_out_count >= 1
        assert len(lb.backends) > 1
        assert scaler.stats.evaluations > 5

    def test_scale_in_when_idle(self):
        lb, servers = self._fleet(1)

        def factory(name):
            return Server(name, concurrency=2, service_time=ConstantLatency(0.01))

        scaler = AutoScaler("scaler", lb, factory, policy=QueueDepthScaling(
            scale_out_threshold=5, scale_in_threshold=0),
            min_instances=1, max_instances=5,
            evaluation_interval=1.0, scale_out_cooldown=0.0, scale_in_cooldown=0.0)
        sim = Simulation(entities=[lb, scaler, *servers], duration=20.0)
        sim.schedule(scaler.start())
        sim.run()
        # Fleet stays at min when idle; never exceeds it.
        assert len(lb.backends) == 1

    def test_cooldown_blocks(self):
        lb, servers = self._fleet(1)
        scaler = AutoScaler("scaler", lb,
                            lambda n: Server(n, concurrency=2,
                                             service_time=ConstantLatency(0.5)),
                            policy=StepScaling([(0.1, 1)]),
                            min_instances=1, max_instances=10,
                            evaluation_interval=0.5, scale_out_cooldown=100.0,
                            scale_in_cooldown=100.0)
        sim = Simulation(entities=[lb, scaler, *servers], duration=20.0)
        sim.schedule(scaler.start())
        sim.schedule([Event(t(0.01 * i), "req", target=lb) for i in range(500)])
        sim.run()
        # First scale-out allowed; further attempts blocked by cooldown.
        assert scaler.stats.scale_out_count == 1
        assert scaler.stats.cooldown_blocks > 0


# ---------------------------------------------------------- CanaryDeployer ----
class TestCanaryDeployer:
    def test_healthy_canary_promotes(self):
        lb = LoadBalancer("lb")
        baselines = [Server(f"old{i}", concurrency=4,
                            service_time=ConstantLatency(0.01)) for i in range(2)]
        for s in baselines:
            lb.add_backend(s)
        deployer = CanaryDeployer(
            "cd", lb, lambda n: Server(n, concurrency=4, service_time=ConstantLatency(0.01)),
            stages=[CanaryStage(0.1, 1.0), CanaryStage(1.0, 1.0)],
            evaluation_interval=0.5,
        )
        sim = Simulation(entities=[lb, deployer, *baselines], duration=30.0)
        sim.schedule(deployer.deploy())
        sim.schedule([Event(t(0.05 * i), "req", target=lb) for i in range(200)])
        sim.run()
        assert deployer.state.status == "completed"
        assert deployer.stats.deployments_completed == 1
        names = {b.name for b in lb.backends}
        assert names == {"cd_canary"}  # baselines removed after promote

    def test_unhealthy_canary_rolls_back(self):
        lb = LoadBalancer("lb")
        baseline = Server("old", concurrency=4, service_time=ConstantLatency(0.01))
        lb.add_backend(baseline)

        class AlwaysUnhealthy:
            def is_healthy(self, canary, baselines):
                return False

        deployer = CanaryDeployer(
            "cd", lb, lambda n: Server(n, concurrency=4,
                                       service_time=ConstantLatency(0.01)),
            stages=[CanaryStage(0.5, 5.0)],
            metric_evaluator=AlwaysUnhealthy(),
            evaluation_interval=0.5,
        )
        sim = Simulation(entities=[lb, deployer, baseline], duration=30.0)
        sim.schedule(deployer.deploy())
        sim.run()
        assert deployer.state.status == "rolled_back"
        assert {b.name for b in lb.backends} == {"old"}


# --------------------------------------------------------- RollingDeployer ----
class TestRollingDeployer:
    def test_full_fleet_replaced(self):
        lb = LoadBalancer("lb")
        olds = [Server(f"old{i}", concurrency=2,
                       service_time=ConstantLatency(0.01)) for i in range(3)]
        for s in olds:
            lb.add_backend(s)
        deployer = RollingDeployer(
            "rd", lb, lambda n: Server(n, concurrency=2,
                                       service_time=ConstantLatency(0.01)),
            batch_size=1, health_check_timeout=5.0, batch_delay=0.5,
        )
        sim = Simulation(entities=[lb, deployer, *olds], duration=60.0)
        sim.schedule(deployer.deploy())
        sim.run()
        assert deployer.state.status == "completed"
        names = {b.name for b in lb.backends}
        assert len(names) == 3
        assert all(n.startswith("rd_v2_") for n in names)
        assert deployer.stats.instances_replaced == 3

    def test_failed_health_check_rolls_back(self):
        lb = LoadBalancer("lb")
        olds = [Server(f"old{i}", concurrency=2,
                       service_time=ConstantLatency(0.01)) for i in range(2)]
        for s in olds:
            lb.add_backend(s)

        class DeadServer(Entity):
            def handle_event(self, event):
                return None  # never completes -> hooks never fire? it does...

        # A server whose health check takes longer than the timeout.
        def slow_factory(name):
            return Server(name, concurrency=1, service_time=ConstantLatency(60.0))

        deployer = RollingDeployer("rd", lb, slow_factory, batch_size=1,
                                   health_check_timeout=1.0)
        sim = Simulation(entities=[lb, deployer, *olds], duration=120.0)
        sim.schedule(deployer.deploy())
        sim.run()
        assert deployer.state.status == "rolled_back"
        assert {b.name for b in lb.backends} == {"old0", "old1"}
