"""Retry-policy timing contracts.

Pins the exact delay/should_retry math for all four policies — attempt
numbering is 1-based and off-by-ones here silently double or halve
retry storms.

Parity target: ``happysimulator/tests/unit/test_retry.py``.
"""

from __future__ import annotations

import pytest

from happysim_tpu.components.client import (
    DecorrelatedJitter,
    ExponentialBackoff,
    FixedRetry,
    NoRetry,
)


class TestNoRetry:
    def test_never_retries(self):
        policy = NoRetry()
        assert not policy.should_retry(1)
        assert not policy.should_retry(99)
        assert policy.delay(1) == 0.0


class TestFixedRetry:
    def test_total_attempts_not_retries(self):
        """max_attempts counts ATTEMPTS: 3 means retry after 1 and 2 only."""
        policy = FixedRetry(max_attempts=3, delay_s=0.5)
        assert policy.should_retry(1)
        assert policy.should_retry(2)
        assert not policy.should_retry(3)

    def test_constant_delay(self):
        policy = FixedRetry(max_attempts=5, delay_s=0.25)
        assert [policy.delay(a) for a in (1, 2, 3, 4)] == [0.25] * 4

    def test_single_attempt_is_no_retry(self):
        policy = FixedRetry(max_attempts=1)
        assert not policy.should_retry(1)

    def test_rejects_zero_attempts(self):
        with pytest.raises(ValueError):
            FixedRetry(max_attempts=0)


class TestExponentialBackoff:
    def test_doubling_sequence(self):
        policy = ExponentialBackoff(
            max_attempts=5, initial_delay=0.1, multiplier=2.0, max_delay=100.0
        )
        delays = [policy.delay(a) for a in (1, 2, 3, 4)]
        assert delays == pytest.approx([0.1, 0.2, 0.4, 0.8])

    def test_cap_binds(self):
        policy = ExponentialBackoff(
            max_attempts=10, initial_delay=1.0, multiplier=10.0, max_delay=5.0
        )
        assert policy.delay(1) == 1.0
        assert policy.delay(2) == 5.0  # 10.0 capped
        assert policy.delay(9) == 5.0

    def test_jitter_bounded_by_base(self):
        policy = ExponentialBackoff(
            max_attempts=5, initial_delay=0.2, multiplier=2.0, jitter=True, seed=3
        )
        for attempt in (1, 2, 3):
            base = 0.2 * 2.0 ** (attempt - 1)
            for _ in range(20):
                assert 0.0 <= policy.delay(attempt) <= base

    def test_jitter_is_seeded(self):
        a = ExponentialBackoff(max_attempts=3, jitter=True, seed=7)
        b = ExponentialBackoff(max_attempts=3, jitter=True, seed=7)
        assert [a.delay(1) for _ in range(5)] == [b.delay(1) for _ in range(5)]

    def test_attempt_budget(self):
        policy = ExponentialBackoff(max_attempts=4)
        assert [policy.should_retry(a) for a in (1, 2, 3, 4)] == [
            True, True, True, False,
        ]


class TestDecorrelatedJitter:
    def test_delays_within_envelope(self):
        policy = DecorrelatedJitter(
            max_attempts=10, base_delay=0.1, max_delay=2.0, seed=5
        )
        previous = 0.1
        for attempt in range(1, 9):
            delay = policy.delay(attempt)
            assert 0.1 <= delay <= min(2.0, previous * 3) + 1e-12
            previous = delay

    def test_cap_is_hard(self):
        policy = DecorrelatedJitter(
            max_attempts=50, base_delay=1.0, max_delay=3.0, seed=1
        )
        assert all(policy.delay(a) <= 3.0 for a in range(1, 40))

    def test_seeded_reproducibility(self):
        a = DecorrelatedJitter(max_attempts=5, seed=9)
        b = DecorrelatedJitter(max_attempts=5, seed=9)
        assert [a.delay(i) for i in (1, 2, 3)] == [b.delay(i) for i in (1, 2, 3)]

    def test_spreads_a_retry_herd(self):
        """Distinct seeds must decorrelate: 50 clients retrying after a
        shared failure should not pile onto one instant."""
        delays = {
            round(DecorrelatedJitter(max_attempts=3, seed=s).delay(1), 6)
            for s in range(50)
        }
        assert len(delays) > 40
