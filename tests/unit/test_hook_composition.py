"""Completion-hook composability across wrapper chains.

These are the invariants the framework guarantees (beyond the reference,
which fires hooks at enqueue/forward time): a completion hook attached to a
request fires EXACTLY ONCE — at true downstream completion, or at the moment
the request is terminally dropped (with ``metadata["dropped_by"]`` set).
"""

import pytest

from happysim_tpu import (
    Client,
    ConstantLatency,
    Event,
    FixedRetry,
    Instant,
    LoadBalancer,
    Server,
    Simulation,
    Sink,
)
from happysim_tpu.components.resilience import Bulkhead, CircuitBreaker
from happysim_tpu.core.entity import Entity


def t(seconds):
    return Instant.from_seconds(seconds)


class TestForwardMovesHooks:
    def test_client_through_load_balancer_sees_real_latency(self):
        sink = Sink()
        servers = [
            Server(f"s{i}", concurrency=1, service_time=ConstantLatency(0.3), downstream=sink)
            for i in range(2)
        ]
        lb = LoadBalancer("lb", backends=servers)
        client = Client("c", target=lb, timeout=5.0)
        sim = Simulation(entities=[sink, lb, client, *servers])
        sim.schedule([client.send_request(at=t(0)), client.send_request(at=t(1))])
        sim.run()
        assert client.responses_received == 2
        # Response time must include the 0.3s service, not fire at forward.
        assert client.average_response_time == pytest.approx(0.3)

    def test_hook_fires_once_through_wrapper_chain(self):
        fired = []
        server = Server("s", concurrency=1, service_time=ConstantLatency(0.1))
        cb = CircuitBreaker("cb", server, call_timeout=None)
        bh = Bulkhead("bh", cb, max_concurrent=4)
        sim = Simulation(entities=[server, cb, bh])
        request = Event(t(0), "req", target=bh)
        request.add_completion_hook(lambda time: fired.append(time.to_seconds()) or None)
        sim.schedule(request)
        sim.run()
        assert fired == [pytest.approx(0.1)]


class TestDropUnwind:
    def test_queue_drop_releases_bulkhead_permit(self):
        """A downstream queue drop must not leak bulkhead permits."""
        server = Server(
            "s", concurrency=1, service_time=ConstantLatency(1.0), queue_capacity=1
        )
        bh = Bulkhead("bh", server, max_concurrent=3)
        sim = Simulation(entities=[server, bh], duration=30.0)
        # Burst of 3 permits: same-instant enqueues land before the first
        # delivery, so 1 is accepted and 2 drop at the full queue.
        sim.schedule([Event(t(0), "req", target=bh) for _ in range(3)])
        # Later wave must find ALL permits free again if the drop unwound.
        sim.schedule([Event(t(10.0), "req", target=bh) for _ in range(3)])
        sim.run()
        assert bh.active_count == 0
        assert bh.stats.requests_forwarded == 6  # nothing rejected at bulkhead
        assert bh.stats.requests_rejected == 0
        assert server.queue.dropped == 4

    def test_client_fast_fails_on_queue_drop_and_retries(self):
        server = Server(
            "s", concurrency=1, service_time=ConstantLatency(2.0), queue_capacity=1
        )
        failures = []
        client = Client(
            "c",
            target=server,
            timeout=10.0,
            retry_policy=FixedRetry(max_attempts=2, delay_s=0.5),
            on_failure=lambda req, reason: failures.append(reason),
        )
        sim = Simulation(entities=[server, client], duration=60.0)
        # #1 occupies the server, #2 fills the queue, #3 gets dropped fast.
        sim.schedule(
            [
                client.send_request(at=t(0)),
                client.send_request(at=t(0.1)),
                client.send_request(at=t(0.2)),
            ]
        )
        sim.run()
        # The third request dropped fast, retried per policy at t=0.7 (queue
        # still full), and failed fast again — no 10s timeout wait.
        assert client.retries >= 1
        assert len(failures) == 1
        assert "s.queue" in failures[0]
        assert client.responses_received == 2

    def test_crashed_target_unwinds_hooks(self):
        class Crashed(Entity):
            _crashed = True

            def handle_event(self, event):
                return None

        dead = Crashed("dead")
        lb = LoadBalancer("lb", backends=[dead])
        sim = Simulation(entities=[lb, dead], duration=5.0)
        sim.schedule([Event(t(i * 0.1), "req", target=lb) for i in range(3)])
        sim.run()
        info = lb.backend_info(dead)
        assert info.in_flight == 0  # unwound, not leaked
        assert info.total_failures == 3
        assert info.consecutive_successes == 0
        assert lb.stats.requests_failed == 3

    def test_fallback_goes_to_backup_on_primary_drop(self):
        from happysim_tpu.components.resilience import Fallback

        sink = Sink()
        # Primary whose queue is always full after the first occupant.
        primary = Server("p", concurrency=1, service_time=ConstantLatency(5.0), queue_capacity=1)
        backup = Server("b", concurrency=4, service_time=ConstantLatency(0.01), downstream=sink)
        fb = Fallback("fb", primary=primary, fallback=backup, timeout=2.0)
        sim = Simulation(entities=[sink, primary, backup, fb], duration=30.0)
        sim.schedule([Event(t(i * 0.1), "req", target=fb) for i in range(4)])
        sim.run()
        # Requests 3+4 drop at the primary's queue and fail over IMMEDIATELY
        # (not after the 2s deadline); 1 is served slow (deadline fallback),
        # 2 sits in queue past deadline (deadline fallback).
        assert fb.stats.fallback_attempts == 4
        assert backup.requests_completed == 4
        drop_failovers = [s for s in sink.latencies_s if s < 1.0]
        assert len(drop_failovers) == 2

    def test_fallback_fires_upstream_hooks_on_backup_success(self):
        from happysim_tpu.components.resilience import Fallback

        fired = []
        slow = Server("slow", concurrency=1, service_time=ConstantLatency(50.0))
        backup = Server("b", concurrency=4, service_time=ConstantLatency(0.01))
        fb = Fallback("fb", primary=slow, fallback=backup, timeout=1.0)
        sim = Simulation(entities=[slow, backup, fb], duration=10.0)
        request = Event(t(0), "req", target=fb)
        request.add_completion_hook(lambda time: fired.append(time.to_seconds()) or None)
        sim.schedule(request)
        sim.run()
        # Upstream hook fires when the BACKUP completes (t≈1.01), not never
        # (hooks parked on the hung primary) and not at primary finish.
        assert fired == [pytest.approx(1.01)]

    def test_pool_dial_timeout_does_not_orphan_connection(self):
        from happysim_tpu import ConnectionPool, PooledClient

        hole = Server("hole", concurrency=1, service_time=ConstantLatency(100.0))
        pool = ConnectionPool(
            "pool", target=hole, max_connections=1, connect_latency=ConstantLatency(1.0)
        )
        client = PooledClient("pc", connection_pool=pool, timeout=0.5)
        sim = Simulation(entities=[hole, pool, client], duration=10.0)
        sim.schedule(client.send_request(at=t(0)))
        sim.run()
        assert client.timeouts == 1
        assert client.stats.failures == 1
        # The dial completed after the caller gave up: the connection must be
        # parked idle, not orphaned active.
        assert pool.active_connections == 0
        assert pool.idle_connections == 1

    def test_load_balancer_failure_vs_success_tracking(self):
        sink = Sink()
        good = Server("good", concurrency=4, service_time=ConstantLatency(0.05), downstream=sink)
        bad = Server("bad", concurrency=1, service_time=ConstantLatency(0.05), queue_capacity=0)
        bad._crashed = True
        lb = LoadBalancer("lb", backends=[good, bad])
        sim = Simulation(entities=[sink, good, bad, lb], duration=10.0)
        sim.schedule([Event(t(i * 0.5), "req", target=lb) for i in range(6)])
        sim.run()
        assert lb.backend_info(good).total_failures == 0
        assert lb.backend_info(good).consecutive_successes == 3
        assert lb.backend_info(bad).total_failures == 3
        assert lb.backend_info(bad).consecutive_failures == 3
