"""Unit tests: circuit breaker, bulkhead, hedge, timeout, fallback."""

import pytest

from happysim_tpu import ConstantLatency, Event, Instant, Server, Simulation, Sink
from happysim_tpu.components.resilience import (
    Bulkhead,
    CircuitBreaker,
    CircuitState,
    Fallback,
    Hedge,
    TimeoutWrapper,
)
from happysim_tpu.core.entity import Entity


class _SlowThenFast(Entity):
    """First ``slow_count`` requests take ``slow``s, the rest ``fast``s."""

    def __init__(self, slow_count=3, slow=2.0, fast=0.01):
        super().__init__("flaky")
        self.slow_count = slow_count
        self.slow = slow
        self.fast = fast
        self.handled = 0

    def handle_event(self, event):
        self.handled += 1
        delay = self.slow if self.handled <= self.slow_count else self.fast
        yield delay


def _requests(target, n, spacing=0.1, start=0.0):
    return [
        Event(Instant.from_seconds(start + i * spacing), "request", target=target)
        for i in range(n)
    ]


class TestCircuitBreaker:
    def test_opens_after_failures(self):
        slow = _SlowThenFast(slow_count=100, slow=10.0)
        cb = CircuitBreaker(
            "cb", slow, failure_threshold=3, call_timeout=0.5, recovery_timeout=60.0
        )
        sim = Simulation(entities=[slow, cb], duration=10.0)
        sim.schedule(_requests(cb, 6, spacing=1.0))
        sim.run()
        assert cb.state is CircuitState.OPEN
        assert cb.stats.failures >= 3
        assert cb.stats.requests_rejected >= 1  # later requests fail fast

    def test_half_open_probe_closes_on_success(self):
        flaky = _SlowThenFast(slow_count=3, slow=10.0, fast=0.01)
        cb = CircuitBreaker(
            "cb",
            flaky,
            failure_threshold=3,
            success_threshold=1,
            call_timeout=0.5,
            recovery_timeout=2.0,
        )
        sim = Simulation(entities=[flaky, cb], duration=30.0)
        # 3 failures by t~2.5 -> OPEN; probe at t=6 (after recovery) succeeds.
        sim.schedule(_requests(cb, 3, spacing=1.0) + _requests(cb, 2, spacing=1.0, start=6.0))
        sim.run()
        assert cb.state is CircuitState.CLOSED
        assert cb.stats.successes >= 1

    def test_half_open_failure_reopens(self):
        slow = _SlowThenFast(slow_count=100, slow=10.0)
        cb = CircuitBreaker(
            "cb", slow, failure_threshold=2, call_timeout=0.3, recovery_timeout=1.0
        )
        sim = Simulation(entities=[slow, cb], duration=20.0)
        sim.schedule(_requests(cb, 2, spacing=0.5) + _requests(cb, 1, start=5.0))
        sim.run()
        # The half-open probe failed and re-opened the circuit (the final
        # state may read HALF_OPEN again because the run's last event is past
        # another recovery window — the lazy transition is by design).
        assert cb.stats.failures == 3
        assert cb.stats.successes == 0
        assert cb.stats.state_transitions >= 3  # closed→open→half_open→open

    def test_forced_transitions(self):
        sink = Sink()
        cb = CircuitBreaker("cb", sink)
        cb.force_open()
        assert cb._state is CircuitState.OPEN
        cb.force_close()
        assert cb._state is CircuitState.CLOSED


class TestBulkhead:
    def test_rejects_over_capacity(self):
        server = Server("s", concurrency=10, service_time=ConstantLatency(1.0))
        bh = Bulkhead("bh", server, max_concurrent=2, max_wait_queue=0)
        sim = Simulation(entities=[server, bh], duration=10.0)
        sim.schedule(_requests(bh, 5, spacing=0.0))
        sim.run()
        assert bh.stats.requests_forwarded == 2
        assert bh.stats.requests_rejected == 3

    def test_queue_drains_as_permits_free(self):
        server = Server("s", concurrency=10, service_time=ConstantLatency(0.5))
        bh = Bulkhead("bh", server, max_concurrent=1, max_wait_queue=10)
        sim = Simulation(entities=[server, bh], duration=10.0)
        sim.schedule(_requests(bh, 3, spacing=0.0))
        sim.run()
        assert bh.stats.requests_forwarded == 3
        assert bh.stats.requests_rejected == 0
        assert server.requests_completed == 3
        assert server.busy_seconds == pytest.approx(1.5)  # serialized by permit

    def test_wait_time_eviction(self):
        server = Server("s", concurrency=10, service_time=ConstantLatency(2.0))
        bh = Bulkhead("bh", server, max_concurrent=1, max_wait_queue=5, max_wait_time=0.5)
        sim = Simulation(entities=[server, bh], duration=10.0)
        sim.schedule(_requests(bh, 3, spacing=0.0))
        sim.run()
        assert bh.stats.requests_evicted == 2
        assert bh.stats.requests_forwarded == 1


class TestHedge:
    def test_hedge_fires_for_slow_primary(self):
        class SlowFirst(Entity):
            def __init__(self):
                super().__init__("sf")
                self.calls = 0

            def handle_event(self, event):
                self.calls += 1
                yield 1.0 if self.calls == 1 else 0.05

        backend = SlowFirst()
        hedge = Hedge("h", backend, hedge_delay=0.2, max_hedges=1)
        sim = Simulation(entities=[backend, hedge], duration=5.0)
        sim.schedule(_requests(hedge, 1))
        sim.run()
        assert hedge.stats.hedges_sent == 1
        assert hedge.stats.hedge_wins == 1
        assert backend.calls == 2

    def test_fast_primary_no_hedge(self):
        server = Server("s", concurrency=4, service_time=ConstantLatency(0.05))
        hedge = Hedge("h", server, hedge_delay=0.5, max_hedges=2)
        sim = Simulation(entities=[server, hedge], duration=5.0)
        sim.schedule(_requests(hedge, 3, spacing=1.0))
        sim.run()
        assert hedge.stats.hedges_sent == 0
        assert hedge.stats.primary_wins == 3


class TestTimeoutWrapper:
    def test_counts_misses_and_hits(self):
        flaky = _SlowThenFast(slow_count=2, slow=1.0, fast=0.01)
        timed_out = []
        tw = TimeoutWrapper("tw", flaky, timeout=0.5, on_timeout=timed_out.append)
        sim = Simulation(entities=[flaky, tw], duration=20.0)
        sim.schedule(_requests(tw, 4, spacing=2.0))
        sim.run()
        assert tw.stats.timeouts == 2
        assert tw.stats.completions == 2
        assert len(timed_out) == 2


class TestFallback:
    def test_failover_to_backup_entity(self):
        slow = _SlowThenFast(slow_count=100, slow=5.0)
        backup = Server("backup", concurrency=4, service_time=ConstantLatency(0.02))
        fb = Fallback("fb", primary=slow, fallback=backup, timeout=0.5)
        sim = Simulation(entities=[slow, backup, fb], duration=20.0)
        sim.schedule(_requests(fb, 3, spacing=1.0))
        sim.run()
        assert fb.stats.fallback_attempts == 3
        assert backup.requests_completed == 3

    def test_primary_success_no_fallback(self):
        fast = Server("fast", concurrency=4, service_time=ConstantLatency(0.01))
        fb = Fallback("fb", primary=fast, fallback=lambda request: None, timeout=1.0)
        sim = Simulation(entities=[fast, fb], duration=10.0)
        sim.schedule(_requests(fb, 3, spacing=0.5))
        sim.run()
        assert fb.stats.primary_successes == 3
        assert fb.stats.fallback_attempts == 0

    def test_callable_fallback(self):
        slow = _SlowThenFast(slow_count=100, slow=5.0)
        produced = []
        fb = Fallback(
            "fb",
            primary=slow,
            fallback=lambda request: produced.append(request) or None,
            timeout=0.2,
        )
        sim = Simulation(entities=[slow, fb], duration=5.0)
        sim.schedule(_requests(fb, 2, spacing=1.0))
        sim.run()
        assert len(produced) == 2
        assert fb.stats.fallback_successes == 2


class TestHedgeDropIsolation:
    def test_dropped_primary_does_not_poison_hedge_win(self):
        """A primary that fast-fails must not mark the ORIGINAL event as
        dropped when a hedge later succeeds (upstream hooks would
        misclassify the success as a drop)."""

        class DropFirstServeSecond(Entity):
            def __init__(self):
                super().__init__("dfss")
                self.calls = 0

            def handle_event(self, event):
                self.calls += 1
                if self.calls == 1:
                    return event.complete_as_dropped(self.now, self.name)
                yield 0.05

        backend = DropFirstServeSecond()
        hedge = Hedge("h", backend, hedge_delay=0.2, max_hedges=1)
        sim = Simulation(entities=[backend, hedge], duration=5.0)
        req = Event(Instant.from_seconds(0.0), "req", target=hedge)
        outcome = {}
        req.add_completion_hook(
            lambda at: outcome.update(
                dropped=req.context["metadata"].get("dropped_by"), at=at.to_seconds()
            )
            or None
        )
        sim.schedule([req])
        sim.run()
        assert hedge.stats.hedge_wins == 1
        assert outcome["dropped"] is None  # success, not a drop
        assert outcome["at"] == pytest.approx(0.25)

    def test_all_attempts_dropped_marks_original(self):
        class AlwaysDrop(Entity):
            def handle_event(self, event):
                return event.complete_as_dropped(self.now, self.name)

        backend = AlwaysDrop("ad")
        hedge = Hedge("h", backend, hedge_delay=0.1, max_hedges=1)
        sim = Simulation(entities=[backend, hedge], duration=5.0)
        req = Event(Instant.from_seconds(0.0), "req", target=hedge)
        outcome = {}
        req.add_completion_hook(
            lambda at: outcome.update(dropped=req.context["metadata"].get("dropped_by")) or None
        )
        sim.schedule([req])
        sim.run()
        assert outcome["dropped"] == "ad"  # total failure IS reported as a drop
