"""Unit tests: sync primitives (Mutex/Semaphore/RWLock/Barrier/Condition).

Mirrors the reference's coverage (tests/unit/components/sync/) using tiny
real simulations, per the unit≈micro-integration strategy (SURVEY.md §4).
"""

import pytest

from happysim_tpu import Entity, Event, Instant, Simulation
from happysim_tpu.components.sync import (
    Barrier,
    BrokenBarrierError,
    Condition,
    Mutex,
    RWLock,
    Semaphore,
)


class CriticalWorker(Entity):
    """Acquires a mutex, holds it for hold_s, records entry/exit times."""

    def __init__(self, name, mutex, hold_s):
        super().__init__(name)
        self.mutex = mutex
        self.hold_s = hold_s
        self.entered_at = None
        self.exited_at = None

    def handle_event(self, event):
        yield self.mutex.acquire(owner=self.name)
        self.entered_at = self.now.to_seconds()
        yield self.hold_s
        self.exited_at = self.now.to_seconds()
        self.mutex.release()


def _kickoff(sim_entities, *starts):
    sim = Simulation(entities=sim_entities)
    for t, entity in starts:
        sim.schedule(Event(Instant.Epoch + t, "go", target=entity))
    return sim


# ---------------------------------------------------------------- Mutex ----
def test_mutex_serializes_critical_sections():
    mutex = Mutex("m")
    a = CriticalWorker("a", mutex, hold_s=1.0)
    b = CriticalWorker("b", mutex, hold_s=1.0)
    sim = _kickoff([mutex, a, b], (0.0, a), (0.1, b))
    sim.run()
    # b waits until a releases at t=1.0
    assert a.entered_at == 0.0
    assert b.entered_at == 1.0
    assert mutex.stats.contentions == 1
    assert mutex.stats.acquisitions == 2
    assert mutex.stats.releases == 2
    assert mutex.stats.total_wait_time_ns == int(0.9e9)
    assert not mutex.is_locked


def test_mutex_try_acquire_and_owner():
    mutex = Mutex("m")
    assert mutex.try_acquire(owner="me")
    assert mutex.owner == "me"
    assert not mutex.try_acquire()
    mutex.release()
    assert not mutex.is_locked
    with pytest.raises(RuntimeError):
        mutex.release()


def test_mutex_fifo_handoff():
    mutex = Mutex("m")
    workers = [CriticalWorker(f"w{i}", mutex, hold_s=0.5) for i in range(4)]
    sim = _kickoff([mutex, *workers], *((i * 0.01, w) for i, w in enumerate(workers)))
    sim.run()
    entries = [w.entered_at for w in workers]
    assert entries == sorted(entries)  # FIFO order preserved
    assert entries == [0.0, 0.5, 1.0, 1.5]


# ------------------------------------------------------------ Semaphore ----
class PermitWorker(Entity):
    def __init__(self, name, sem, count, hold_s):
        super().__init__(name)
        self.sem = sem
        self.count = count
        self.hold_s = hold_s
        self.entered_at = None

    def handle_event(self, event):
        yield self.sem.acquire(self.count)
        self.entered_at = self.now.to_seconds()
        yield self.hold_s
        self.sem.release(self.count)


def test_semaphore_limits_concurrency():
    sem = Semaphore("s", initial_count=2)
    workers = [PermitWorker(f"w{i}", sem, 1, hold_s=1.0) for i in range(4)]
    sim = _kickoff([sem, *workers], *((0.0, w) for w in workers))
    sim.run()
    entries = sorted(w.entered_at for w in workers)
    assert entries == [0.0, 0.0, 1.0, 1.0]
    assert sem.available == 2
    assert sem.stats.peak_waiters == 2


def test_semaphore_multi_permit_no_barging():
    sem = Semaphore("s", initial_count=2)
    big = PermitWorker("big", sem, 2, hold_s=1.0)       # takes both
    bigger = PermitWorker("bigger", sem, 2, hold_s=1.0)  # queues for both
    small = PermitWorker("small", sem, 1, hold_s=1.0)    # must NOT barge past
    sim = _kickoff([sem, big, bigger, small], (0.0, big), (0.1, bigger), (0.2, small))
    sim.run()
    assert big.entered_at == 0.0
    assert bigger.entered_at == 1.0
    assert small.entered_at == 2.0  # FIFO: waits behind bigger


def test_semaphore_try_acquire():
    sem = Semaphore("s", initial_count=1)
    assert sem.try_acquire()
    assert not sem.try_acquire()
    sem.release()
    assert sem.available == 1
    with pytest.raises(ValueError):
        sem.try_acquire(0)


# --------------------------------------------------------------- RWLock ----
class Reader(Entity):
    def __init__(self, name, lock, hold_s):
        super().__init__(name)
        self.lock = lock
        self.hold_s = hold_s
        self.entered_at = None

    def handle_event(self, event):
        yield self.lock.acquire_read()
        self.entered_at = self.now.to_seconds()
        yield self.hold_s
        self.lock.release_read()


class Writer(Entity):
    def __init__(self, name, lock, hold_s):
        super().__init__(name)
        self.lock = lock
        self.hold_s = hold_s
        self.entered_at = None

    def handle_event(self, event):
        yield self.lock.acquire_write()
        self.entered_at = self.now.to_seconds()
        yield self.hold_s
        self.lock.release_write()


def test_rwlock_concurrent_readers_exclusive_writer():
    lock = RWLock("rw")
    r1 = Reader("r1", lock, hold_s=1.0)
    r2 = Reader("r2", lock, hold_s=1.0)
    w = Writer("w", lock, hold_s=1.0)
    sim = _kickoff([lock, r1, r2, w], (0.0, r1), (0.0, r2), (0.1, w))
    sim.run()
    assert r1.entered_at == 0.0 and r2.entered_at == 0.0  # shared
    assert w.entered_at == 1.0  # waits for both readers
    assert lock.stats.peak_readers == 2
    assert lock.stats.write_contentions == 1


def test_rwlock_writer_preference_blocks_new_readers():
    lock = RWLock("rw")
    r1 = Reader("r1", lock, hold_s=1.0)
    w = Writer("w", lock, hold_s=1.0)
    r2 = Reader("r2", lock, hold_s=1.0)
    # r1 holds; w queues at 0.1; r2 arrives at 0.2 and must NOT overtake w.
    sim = _kickoff([lock, r1, w, r2], (0.0, r1), (0.1, w), (0.2, r2))
    sim.run()
    assert r1.entered_at == 0.0
    assert w.entered_at == 1.0
    assert r2.entered_at == 2.0


def test_rwlock_max_readers_cap():
    lock = RWLock("rw", max_readers=1)
    r1 = Reader("r1", lock, hold_s=1.0)
    r2 = Reader("r2", lock, hold_s=1.0)
    sim = _kickoff([lock, r1, r2], (0.0, r1), (0.0, r2))
    sim.run()
    assert sorted([r1.entered_at, r2.entered_at]) == [0.0, 1.0]


def test_rwlock_release_errors():
    lock = RWLock("rw")
    with pytest.raises(RuntimeError):
        lock.release_read()
    with pytest.raises(RuntimeError):
        lock.release_write()


# -------------------------------------------------------------- Barrier ----
class Party(Entity):
    def __init__(self, name, barrier, arrive_after_s):
        super().__init__(name)
        self.barrier = barrier
        self.arrive_after_s = arrive_after_s
        self.released_at = None
        self.index = None
        self.error = None

    def handle_event(self, event):
        yield self.arrive_after_s
        try:
            self.index = yield self.barrier.wait()
        except BrokenBarrierError as exc:
            self.error = exc
            return
        self.released_at = self.now.to_seconds()


def test_barrier_releases_all_on_last_arrival():
    barrier = Barrier("b", parties=3)
    parties = [Party(f"p{i}", barrier, arrive_after_s=float(i)) for i in range(3)]
    sim = _kickoff([barrier, *parties], *((0.0, p) for p in parties))
    sim.run()
    # All released when the last (t=2.0) arrives.
    assert [p.released_at for p in parties] == [2.0, 2.0, 2.0]
    # Last arrival is the leader (index 0); earlier arrivals get 1..n-1.
    assert parties[2].index == 0
    assert sorted(p.index for p in parties) == [0, 1, 2]
    assert barrier.generation == 1
    assert barrier.waiting == 0


def test_barrier_reusable_across_generations():
    barrier = Barrier("b", parties=2)

    class Repeater(Entity):
        def __init__(self, name, barrier, delay_s):
            super().__init__(name)
            self.barrier = barrier
            self.delay_s = delay_s
            self.release_times = []

        def handle_event(self, event):
            for _ in range(2):
                yield self.delay_s
                yield self.barrier.wait()
                self.release_times.append(self.now.to_seconds())

    fast = Repeater("fast", barrier, 1.0)
    slow = Repeater("slow", barrier, 2.0)
    sim = _kickoff([barrier, fast, slow], (0.0, fast), (0.0, slow))
    sim.run()
    assert fast.release_times == [2.0, 4.0]
    assert slow.release_times == [2.0, 4.0]
    assert barrier.generation == 2


def test_barrier_abort_rejects_waiters():
    barrier = Barrier("b", parties=3)
    p1 = Party("p1", barrier, 0.0)
    p2 = Party("p2", barrier, 0.0)

    class Aborter(Entity):
        def handle_event(self, event):
            barrier.abort()

    aborter = Aborter("aborter")
    sim = _kickoff([barrier, p1, p2, aborter], (0.0, p1), (0.0, p2), (1.0, aborter))
    sim.run()
    assert isinstance(p1.error, BrokenBarrierError)
    assert isinstance(p2.error, BrokenBarrierError)
    assert barrier.broken
    with pytest.raises(BrokenBarrierError):
        barrier.wait()
    barrier.reset()
    assert not barrier.broken


# ------------------------------------------------------------ Condition ----
class Consumer(Entity):
    def __init__(self, name, mutex, cond, buffer):
        super().__init__(name)
        self.mutex = mutex
        self.cond = cond
        self.buffer = buffer
        self.consumed = []
        self.consumed_at = []

    def handle_event(self, event):
        yield self.mutex.acquire(owner=self.name)
        while not self.buffer:
            yield self.cond.wait(owner=self.name)
        self.consumed.append(self.buffer.pop(0))
        self.consumed_at.append(self.now.to_seconds())
        self.mutex.release()


class Producer(Entity):
    def __init__(self, name, mutex, cond, buffer, item):
        super().__init__(name)
        self.mutex = mutex
        self.cond = cond
        self.buffer = buffer
        self.item = item

    def handle_event(self, event):
        yield self.mutex.acquire(owner=self.name)
        self.buffer.append(self.item)
        self.cond.notify()
        self.mutex.release()


def test_condition_producer_consumer():
    mutex = Mutex("m")
    cond = Condition("c", mutex)
    buffer = []
    consumer = Consumer("consumer", mutex, cond, buffer)
    producer = Producer("producer", mutex, cond, buffer, item="x")
    sim = _kickoff([mutex, cond, consumer, producer], (0.0, consumer), (1.0, producer))
    sim.run()
    assert consumer.consumed == ["x"]
    assert consumer.consumed_at == [1.0]
    assert not mutex.is_locked
    assert cond.stats.waits == 1
    assert cond.stats.wakeups == 1


def test_condition_wait_requires_lock():
    mutex = Mutex("m")
    cond = Condition("c", mutex)
    with pytest.raises(RuntimeError):
        cond.wait()


def test_condition_notify_all_wakes_everyone():
    mutex = Mutex("m")
    cond = Condition("c", mutex)
    buffer = []

    class GreedyConsumer(Consumer):
        pass

    consumers = [GreedyConsumer(f"c{i}", mutex, cond, buffer) for i in range(2)]

    class BatchProducer(Entity):
        def handle_event(self, event):
            yield mutex.acquire(owner=self.name)
            buffer.extend(["a", "b"])
            cond.notify_all()
            mutex.release()

    producer = BatchProducer("producer")
    sim = _kickoff(
        [mutex, cond, *consumers, producer],
        (0.0, consumers[0]),
        (0.0, consumers[1]),
        (1.0, producer),
    )
    sim.run()
    assert sorted(consumers[0].consumed + consumers[1].consumed) == ["a", "b"]
    assert not mutex.is_locked


def test_condition_wait_for_predicate():
    mutex = Mutex("m")
    cond = Condition("c", mutex)
    state = {"ready": False}

    class WaiterEntity(Entity):
        def __init__(self, name):
            super().__init__(name)
            self.result = None
            self.done_at = None

        def handle_event(self, event):
            yield mutex.acquire(owner=self.name)
            self.result = yield from cond.wait_for(lambda: state["ready"])
            self.done_at = self.now.to_seconds()
            mutex.release()

    class Setter(Entity):
        def handle_event(self, event):
            yield mutex.acquire(owner=self.name)
            state["ready"] = True
            cond.notify_all()
            mutex.release()

    waiter = WaiterEntity("waiter")
    setter = Setter("setter")
    sim = _kickoff([mutex, cond, waiter, setter], (0.0, waiter), (2.0, setter))
    sim.run()
    assert waiter.result is True
    assert waiter.done_at == 2.0


# ---------------------------------------------------- cancellation races ----
def test_acquire_timeout_cancel_does_not_strand_lock():
    """Losing an any_of race + cancel() must not leave the lock stranded."""
    from happysim_tpu import SimFuture, any_of

    mutex = Mutex("m")

    class Holder(Entity):
        def handle_event(self, event):
            yield mutex.acquire(owner="holder")
            yield 2.0
            mutex.release()

    class ImpatientWaiter(Entity):
        def __init__(self, name):
            super().__init__(name)
            self.timed_out = None

        def handle_event(self, event):
            acq = mutex.acquire(owner=self.name)
            timer = SimFuture()
            fire = Event.once(self.now + 0.5, lambda: timer.resolve("timeout"))
            index, _ = yield any_of(acq, timer), [fire]
            self.timed_out = index == 1
            if self.timed_out:
                acq.cancel()

    class LateWaiter(CriticalWorker):
        pass

    holder = Holder("holder")
    impatient = ImpatientWaiter("impatient")
    late = LateWaiter("late", mutex, hold_s=0.1)
    sim = _kickoff([mutex, holder, impatient, late], (0.0, holder), (0.1, impatient), (0.2, late))
    sim.run()
    assert impatient.timed_out is True
    # Holder releases at 2.0; the cancelled waiter is skipped; late gets it.
    assert late.entered_at == 2.0
    assert late.exited_at == 2.1
    assert not mutex.is_locked


def test_semaphore_cancelled_waiter_skipped():
    sem = Semaphore("s", initial_count=1)
    assert sem.try_acquire()
    abandoned = sem.acquire()  # queued

    class Releaser(Entity):
        def handle_event(self, event):
            abandoned.cancel()
            sem.release()

    class Late(PermitWorker):
        pass

    releaser = Releaser("releaser")
    late = Late("late", sem, 1, hold_s=0.1)
    sim = _kickoff([sem, releaser, late], (1.0, releaser), (0.5, late))
    sim.run()
    assert late.entered_at == 1.0
    assert sem.available == 1


def test_rwlock_cancelled_writer_unblocks_readers():
    lock = RWLock("rw")
    assert lock.try_acquire_read()  # a reader holds
    w = lock.acquire_write()        # writer queues -> blocks new readers
    assert not lock.try_acquire_read()
    w.cancel()                      # writer gives up
    assert lock.try_acquire_read()  # readers no longer blocked


def test_semaphore_acquire_over_capacity_raises():
    sem = Semaphore("s", initial_count=2)
    with pytest.raises(ValueError):
        sem.acquire(3)
    with pytest.raises(ValueError):
        sem.try_acquire(3)


def test_semaphore_cancel_unblocks_queue_immediately():
    """Cancelling a head-of-line waiter wakes eligible waiters NOW, not at
    the next release."""
    sem = Semaphore("s", initial_count=2)

    class Hog(Entity):
        def handle_event(self, event):
            yield sem.acquire(1)   # holds one permit forever
            yield 100.0
            sem.release(1)

    class BigWaiter(Entity):
        def __init__(self, name):
            super().__init__(name)
            self.fut = None

        def handle_event(self, event):
            self.fut = sem.acquire(2)   # can't be satisfied while Hog holds
            yield 0.5                   # ...waits a bit, then gives up
            self.fut.cancel()

    class SmallWaiter(PermitWorker):
        pass

    hog = Hog("hog")
    big = BigWaiter("big")
    small = SmallWaiter("small", sem, 1, hold_s=0.1)
    sim = _kickoff([sem, hog, big, small], (0.0, hog), (0.1, big), (0.2, small))
    sim.run()
    # small is unblocked at big's cancel (t=0.6), NOT at hog's release (t=100)
    assert small.entered_at == 0.6


def test_rwlock_cancelled_writer_releases_queued_readers():
    """A QUEUED reader behind a cancelled writer wakes immediately."""
    lock = RWLock("rw")

    class HoldingReader(Entity):
        def handle_event(self, event):
            yield lock.acquire_read()
            yield 100.0
            lock.release_read()

    class GivingUpWriter(Entity):
        def __init__(self, name):
            super().__init__(name)
            self.fut = None

        def handle_event(self, event):
            self.fut = lock.acquire_write()
            yield 0.5
            self.fut.cancel()

    class QueuedReader(Reader):
        pass

    r1 = HoldingReader("r1")
    w = GivingUpWriter("w")
    r2 = QueuedReader("r2", lock, hold_s=0.1)
    sim = _kickoff([lock, r1, w, r2], (0.0, r1), (0.1, w), (0.2, r2))
    sim.run()
    # r2 shares with r1 as soon as the writer cancels at t=0.6.
    assert r2.entered_at == 0.6


def test_condition_waiter_cancelled_mid_reacquire_returns_mutex():
    """Cancel between notify() and mutex re-acquisition must not strand the
    mutex on the departed waiter."""
    from happysim_tpu import SimFuture, any_of

    mutex = Mutex("m")
    cond = Condition("c", mutex)

    class ImpatientWaiter(Entity):
        def __init__(self, name):
            super().__init__(name)
            self.timed_out = None

        def handle_event(self, event):
            yield mutex.acquire(owner=self.name)
            wait_fut = cond.wait(owner=self.name)
            timer = SimFuture()
            fire = Event.once(self.now + 1.5, lambda: timer.resolve("timeout"))
            index, _ = yield any_of(wait_fut, timer), [fire]
            self.timed_out = index == 1
            if self.timed_out:
                wait_fut.cancel()

    class SlowNotifier(Entity):
        def handle_event(self, event):
            yield mutex.acquire(owner=self.name)
            cond.notify()
            yield 2.0          # holds mutex past the waiter's timeout (1.5)
            mutex.release()

    class LateLocker(CriticalWorker):
        pass

    waiter = ImpatientWaiter("waiter")
    notifier = SlowNotifier("notifier")
    late = LateLocker("late", mutex, hold_s=0.1)
    sim = _kickoff([mutex, cond, waiter, notifier, late], (0.0, waiter), (1.0, notifier), (2.0, late))
    sim.run()
    assert waiter.timed_out is True
    # Notifier releases at 3.0; cancelled waiter's re-acquire hands back; late runs.
    assert late.entered_at == 3.0
    assert not mutex.is_locked
