"""Unit tests: replication (primary-backup, chain, multi-leader) + CRDTs."""

import pytest

from happysim_tpu import ConstantLatency, Entity, Event, Instant, KVStore, Network, NetworkLink, Simulation, SimFuture
from happysim_tpu.components.crdt import CRDTStore, GCounter, LWWRegister, ORSet, PNCounter
from happysim_tpu.components.replication import (
    BackupNode,
    ChainNode,
    ChainNodeRole,
    CustomResolver,
    LastWriterWins,
    LeaderNode,
    PrimaryNode,
    ReplicationMode,
    VectorClockMerge,
    VersionedValue,
)


def t(seconds):
    return Instant.from_seconds(seconds)


def make_network(latency=0.01):
    return Network("net", default_link=NetworkLink("link", latency=ConstantLatency(latency)))


def write_event(target, key, value, reply=None, at=0.0):
    return Event(
        t(at), "Write", target=target,
        context={"metadata": {"key": key, "value": value, "reply_future": reply}},
    )


# ------------------------------------------------------------------ CRDTs ----
class TestCRDTs:
    def test_g_counter_merge(self):
        a, b = GCounter("a"), GCounter("b")
        a.increment(5)
        b.increment(3)
        a.merge(b)
        b.merge(a)
        assert a.value == b.value == 8
        a.merge(b)  # idempotent
        assert a.value == 8

    def test_pn_counter(self):
        a, b = PNCounter("a"), PNCounter("b")
        a.increment(10)
        b.decrement(4)
        a.merge(b)
        assert a.value == 6
        roundtrip = PNCounter.from_dict(a.to_dict())
        assert roundtrip.value == 6

    def test_lww_register(self):
        a, b = LWWRegister("a"), LWWRegister("b")
        a.set("first", 1.0)
        b.set("second", 2.0)
        a.merge(b)
        assert a.value == "second"
        b.merge(a)
        assert b.value == "second"

    def test_or_set_add_wins(self):
        a, b = ORSet("a"), ORSet("b")
        a.add("x")
        b.merge(a)
        b.remove("x")  # removes the observed tag
        a.add("x")  # concurrent re-add with a NEW tag
        a.merge(b)
        assert "x" in a  # add wins
        b.merge(a)
        assert a.value == b.value

    def test_or_set_remove_observed(self):
        s = ORSet("a")
        s.add("x")
        s.remove("x")
        assert "x" not in s
        assert len(s) == 0

    def test_crdt_store_gossip_convergence(self):
        network = make_network(0.005)
        stores = [
            CRDTStore(f"s{i}", network, gossip_interval=0.5, seed=i) for i in range(3)
        ]
        for s in stores:
            s.add_peers(stores)

        class Writer(Entity):
            def __init__(self, name, store, amount):
                super().__init__(name)
                self.store = store
                self.amount = amount

            def handle_event(self, event):
                self.store.get_or_create("hits").increment(self.amount)
                return self.store.get_gossip_event()

        class Idle(Entity):
            def handle_event(self, event):
                return None

        idle = Idle("idle")
        writers = [Writer(f"w{i}", stores[i], amount=i + 1) for i in range(3)]
        sim = Simulation(entities=[network, idle, *stores, *writers], duration=30.0)
        for i, w in enumerate(writers):
            sim.schedule(Event(t(0.1 * i), "go", target=w))
        # Something primary to keep the sim alive while gossip (daemon) runs.
        sim.schedule(Event(t(20.0), "noop", target=idle))
        sim.run()
        values = [s._crdts["hits"].value for s in stores]
        assert values == [6, 6, 6]  # 1+2+3 everywhere
        hashes = {s.state_hash() for s in stores}
        assert len(hashes) == 1


# -------------------------------------------------------- conflict resolvers ----
class TestConflictResolvers:
    def test_lww(self):
        v1 = VersionedValue("old", 1.0, "a")
        v2 = VersionedValue("new", 2.0, "b")
        assert LastWriterWins().resolve("k", [v1, v2]).value == "new"

    def test_lww_tie_break(self):
        v1 = VersionedValue("a-val", 1.0, "a")
        v2 = VersionedValue("b-val", 1.0, "b")
        assert LastWriterWins().resolve("k", [v1, v2]).value == "b-val"

    def test_vector_clock_dominance(self):
        v1 = VersionedValue("old", 1.0, "a", vector_clock={"a": 1})
        v2 = VersionedValue("new", 0.5, "b", vector_clock={"a": 1, "b": 1})
        # v2 causally dominates despite the older wall timestamp.
        assert VectorClockMerge().resolve("k", [v1, v2]).value == "new"

    def test_vector_clock_concurrent_merges(self):
        v1 = VersionedValue({"x"}, 1.0, "a", vector_clock={"a": 1})
        v2 = VersionedValue({"y"}, 2.0, "b", vector_clock={"b": 1})
        merged = VectorClockMerge(
            merge_fn=lambda k, a, b: VersionedValue(
                a.value | b.value, max(a.timestamp, b.timestamp), "merged"
            )
        ).resolve("k", [v1, v2])
        assert merged.value == {"x", "y"}

    def test_custom(self):
        resolver = CustomResolver(lambda k, vs: min(vs, key=lambda v: v.timestamp))
        v1 = VersionedValue("first", 1.0, "a")
        v2 = VersionedValue("second", 2.0, "b")
        assert resolver.resolve("k", [v1, v2]).value == "first"


# -------------------------------------------------------- primary-backup ----
class TestPrimaryBackup:
    def _build(self, mode):
        network = make_network(0.01)
        backups = [
            BackupNode(f"b{i}", KVStore(f"bs{i}", write_latency=0.002), network)
            for i in range(2)
        ]
        primary = PrimaryNode("primary", KVStore("ps", write_latency=0.002),
                              backups, network, mode=mode)
        for b in backups:
            b.set_primary(primary)
        return network, primary, backups

    def _run_write(self, network, primary, backups, duration=10.0):
        done = {}

        class Client(Entity):
            def handle_event(self, event):
                reply = SimFuture()
                write = write_event(primary, "k", "v", reply=reply)
                write = Event(self.now, "Write", target=primary,
                              context=write.context)
                result = yield reply, [write]
                done["result"] = result
                done["at"] = round(self.now.to_seconds(), 4)

        client = Client("client")
        sim = Simulation(entities=[network, client, primary, *backups], duration=duration)
        sim.schedule(Event(t(0.0), "go", target=client))
        sim.run()
        return done

    def test_async_acks_before_replication(self):
        network, primary, backups = self._build(ReplicationMode.ASYNC)
        done = self._run_write(network, primary, backups)
        assert done["result"]["status"] == "ok"
        # Ack at local write latency only (0.002), before network round trip.
        assert done["at"] < 0.01
        # Replication still lands eventually.
        assert all(b.store.get_sync("k") == "v" for b in backups)

    def test_sync_waits_for_all_backups(self):
        network, primary, backups = self._build(ReplicationMode.SYNC)
        done = self._run_write(network, primary, backups)
        # local 0.002 + network 0.01 + backup 0.002 ≈ 0.014+
        assert done["at"] >= 0.012
        assert all(b.store.get_sync("k") == "v" for b in backups)
        assert primary.backup_lag == {"b0": 0, "b1": 0}

    def test_semi_sync_waits_for_first(self):
        network, primary, backups = self._build(ReplicationMode.SEMI_SYNC)
        done = self._run_write(network, primary, backups)
        assert done["result"]["status"] == "ok"
        assert done["at"] >= 0.012  # at least one backup round trip


# ------------------------------------------------------------------ chain ----
class TestChainReplication:
    def _build(self, n=3, craq=False):
        network = make_network(0.01)
        nodes = [
            ChainNode(f"c{i}", KVStore(f"cs{i}", write_latency=0.001), network,
                      craq_enabled=craq)
            for i in range(n)
        ]
        ChainNode.link_chain(nodes)
        return network, nodes

    def test_write_propagates_to_tail_then_acks(self):
        network, nodes = self._build(3)
        done = {}

        class Client(Entity):
            def handle_event(self, event):
                reply = SimFuture()
                write = Event(self.now, "Write", target=nodes[0],
                              context={"metadata": {"key": "k", "value": "v",
                                                    "reply_future": reply}})
                result = yield reply, [write]
                done["result"] = result
                done["at"] = round(self.now.to_seconds(), 4)

        client = Client("client")
        sim = Simulation(entities=[network, client, *nodes], duration=10.0)
        sim.schedule(Event(t(0.0), "go", target=client))
        sim.run()
        assert done["result"]["status"] == "ok"
        assert all(n.store.get_sync("k") == "v" for n in nodes)
        # Full chain: 2 hops down + ack back ≈ 3 network latencies minimum.
        assert done["at"] >= 0.03
        assert nodes[0].role == ChainNodeRole.HEAD
        assert nodes[2].role == ChainNodeRole.TAIL

    def test_reads_served_by_tail(self):
        network, nodes = self._build(3)
        nodes[2].store.put_sync("k", "tail-value")
        done = {}

        class Client(Entity):
            def handle_event(self, event):
                reply = SimFuture()
                read = Event(self.now, "Read", target=nodes[2],
                             context={"metadata": {"key": "k", "reply_future": reply}})
                result = yield reply, [read]
                done["result"] = result

        client = Client("client")
        sim = Simulation(entities=[network, client, *nodes], duration=10.0)
        sim.schedule(Event(t(0.0), "go", target=client))
        sim.run()
        assert done["result"]["value"] == "tail-value"
        assert done["result"]["served_by"] == "c2"

    def test_craq_clean_reads_local_dirty_forward(self):
        network, nodes = self._build(3, craq=True)
        # Clean key: middle node serves locally.
        for n in nodes:
            n.store.put_sync("clean", 1)
        done = {}

        class Client(Entity):
            def handle_event(self, event):
                reply = SimFuture()
                read = Event(self.now, "Read", target=nodes[1],
                             context={"metadata": {"key": "clean", "reply_future": reply}})
                result = yield reply, [read]
                done["clean"] = result

        client = Client("client")
        sim = Simulation(entities=[network, client, *nodes], duration=10.0)
        sim.schedule(Event(t(0.0), "go", target=client))
        sim.run()
        assert done["clean"]["served_by"] == "c1"  # local CRAQ read


# ------------------------------------------------------------ multi-leader ----
class TestMultiLeader:
    def test_concurrent_writes_converge_via_lww(self):
        network = make_network(0.01)
        leaders = [
            LeaderNode(f"L{i}", KVStore(f"ls{i}", write_latency=0.001), network, seed=i)
            for i in range(2)
        ]
        for leader in leaders:
            leader.add_peers(leaders)

        class Writer(Entity):
            def __init__(self, name, leader, value):
                super().__init__(name)
                self.leader = leader
                self.value = value

            def handle_event(self, event):
                reply = SimFuture()
                write = Event(self.now, "Write", target=self.leader,
                              context={"metadata": {"key": "k", "value": self.value,
                                                    "reply_future": reply}})
                yield reply, [write]

        w1 = Writer("w1", leaders[0], "from-L0")
        w2 = Writer("w2", leaders[1], "from-L1")
        sim = Simulation(entities=[network, w1, w2, *leaders], duration=10.0)
        sim.schedule(Event(t(0.0), "go", target=w1))
        sim.schedule(Event(t(0.001), "go", target=w2))  # later write wins
        sim.run()
        assert leaders[0].store.get_sync("k") == "from-L1"
        assert leaders[1].store.get_sync("k") == "from-L1"
        assert leaders[0].stats.conflicts_resolved >= 1

    def test_anti_entropy_repairs_missed_replication(self):
        network = make_network(0.01)
        leaders = [
            LeaderNode(f"L{i}", KVStore(f"ls{i}", write_latency=0.001), network,
                       anti_entropy_interval=1.0, seed=i)
            for i in range(2)
        ]
        for leader in leaders:
            leader.add_peers(leaders)
        # Simulate a missed replication: L0 has a key L1 never saw.
        leaders[0]._apply_version(
            "lost", VersionedValue("repaired", 1.0, "L0")
        )

        class Kicker(Entity):
            def handle_event(self, event):
                events = []
                for leader in leaders:
                    kick = leader.get_anti_entropy_event()
                    if kick is not None:
                        events.append(kick)
                return events

        kicker = Kicker("kicker")
        sim = Simulation(entities=[network, kicker, *leaders], duration=20.0)
        sim.schedule(Event(t(0.0), "go", target=kicker))
        sim.schedule(Event(t(15.0), "noop", target=kicker))  # hold sim open
        sim.run()
        assert leaders[1].store.get_sync("lost") == "repaired"
        assert leaders[1].stats.anti_entropy_repairs >= 1
        assert leaders[0].merkle_tree.root_hash == leaders[1].merkle_tree.root_hash


class TestReviewRegressions:
    def test_or_set_roundtrip_counter_no_collision(self):
        s = ORSet("a")
        s.add("x")
        s.remove("x")
        restored = ORSet.from_dict(s.to_dict())
        restored.add("x")  # must mint a FRESH tag, not collide with tombstone
        assert "x" in restored

    def test_backup_ignores_reordered_stale_write(self):
        network = make_network(0.01)
        backup = BackupNode("b", KVStore("bs"), network)
        from happysim_tpu.core.clock import Clock

        clock = Clock()
        for e in (network, backup):
            e.set_clock(clock)
        # Deliver seq=2 then the late seq=1 for the same key.
        for seq, value in ((2, "new"), (1, "old")):
            gen = backup._handle_replicate(
                Event(t(0.0), "Replicate", target=backup,
                      context={"metadata": {"key": "k", "value": value, "seq": seq}})
            )
            try:
                while True:
                    next(gen)
            except StopIteration:
                pass
        assert backup.store.get_sync("k") == "new"  # stale write ignored

    def test_raft_step_down_reschedules_election_timer(self):
        """A leader stepping down on an UNGRANTED vote keeps a live timer
        (cluster can't go permanently leaderless)."""
        from happysim_tpu.components.consensus import RaftNode, RaftState

        network = make_network(0.01)
        nodes = [RaftNode(f"n{i}", network, election_timeout_min=1.0,
                          election_timeout_max=1.5, seed=i) for i in range(2)]
        for n in nodes:
            n.set_peers(nodes)

        class Prober(Entity):
            def handle_event(self, event):
                leader = next((n for n in nodes if n.is_leader), None)
                if leader is None:
                    return None
                # Stale-log candidate forces step-down WITHOUT vote grant.
                leader._log.append(leader.current_term, "entry")
                return leader._on_request_vote(
                    Event(self.now, "RaftRequestVote", target=leader,
                          context={"metadata": {
                              "term": leader.current_term + 1,
                              "candidate_id": nodes[1].name if leader is nodes[0] else nodes[0].name,
                              "source": nodes[1].name if leader is nodes[0] else nodes[0].name,
                              "last_log_index": 0,
                              "last_log_term": 0,
                          }})
                )

        prober = Prober("prober")
        sim = Simulation(entities=[network, prober, *nodes], duration=30.0)
        for n in nodes:
            sim.schedule(n.start())
        sim.schedule(Event(t(6.0), "poke", target=prober))
        sim.run()
        # The cluster recovered a leader after the forced step-down.
        assert any(n.is_leader for n in nodes)


class TestAdvisorRegressions:
    def test_craq_head_stays_dirty_under_overlapping_writes(self):
        """Two in-flight writes to one key: the head's dirty count must not
        reach zero until BOTH commit (double-decrement regression)."""
        network = make_network(0.01)
        nodes = [
            ChainNode(f"c{i}", KVStore(f"cs{i}", write_latency=0.001), network,
                      craq_enabled=True)
            for i in range(3)
        ]
        ChainNode.link_chain(nodes)
        head = nodes[0]
        observed = {}

        class Checker(Entity):
            def handle_event(self, event):
                observed["dirty_mid_flight"] = set(head.dirty_keys)
                return None

        checker = Checker("checker")
        sim = Simulation(entities=[network, checker, *nodes], duration=1.0)
        sim.schedule(write_event(head, "k", "v1", at=0.0))
        sim.schedule(write_event(head, "k", "v2", at=0.005))
        # Write 1 commits at the head ~0.033s; write 2 not until ~0.038s.
        sim.schedule(Event(t(0.035), "check", target=checker))
        sim.run()
        assert "k" in observed["dirty_mid_flight"]
        assert head.dirty_keys == set()  # everything committed by the end

    def test_anti_entropy_ships_only_divergent_ranges(self):
        """Merkle sync must localize the diff, not ship the whole keyspace."""
        network = make_network(0.01)
        leaders = [
            LeaderNode(f"L{i}", KVStore(f"ls{i}", write_latency=0.001), network,
                       anti_entropy_interval=1.0, seed=i)
            for i in range(2)
        ]
        for leader in leaders:
            leader.add_peers(leaders)
        for i in range(200):
            version = VersionedValue(f"v{i}", 1.0, "L0")
            leaders[0]._apply_version(f"key{i:03d}", version)
            leaders[1]._apply_version(f"key{i:03d}", version)
        leaders[0]._apply_version("key150x", VersionedValue("extra", 2.0, "L0"))

        shipped = []
        orig_send = network.send

        def counting_send(source, destination, event_type, payload=None, **kwargs):
            if event_type == "AntiEntropySync" and payload:
                shipped.append(len(payload.get("versions", {})))
            return orig_send(source, destination, event_type,
                             payload=payload, **kwargs)

        network.send = counting_send

        class Kicker(Entity):
            def handle_event(self, event):
                events = []
                for leader in leaders:
                    kick = leader.get_anti_entropy_event()
                    if kick is not None:
                        events.append(kick)
                return events

        kicker = Kicker("kicker")
        sim = Simulation(entities=[network, kicker, *leaders], duration=20.0)
        sim.schedule(Event(t(0.0), "go", target=kicker))
        sim.schedule(Event(t(15.0), "noop", target=kicker))
        sim.run()
        assert leaders[1].store.get_sync("key150x") == "extra"
        assert leaders[0].merkle_tree.root_hash == leaders[1].merkle_tree.root_hash
        # 201 keys total; the sync must ship far fewer than the full map.
        assert shipped and sum(shipped) <= 40
