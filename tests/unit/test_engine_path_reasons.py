"""Decline reasons are SURFACED, not just logged (ISSUE 4 satellite).

A kernel-declined model must tell the user which engine path actually
ran and which flag controls it: ``EnsembleResult.kernel_decline`` names
``HS_TPU_PALLAS``, and the ``run_partitioned`` telemetry rejection names
the scan-path escape hatches (``HS_TPU_PALLAS``, ``HS_TPU_EARLY_EXIT``).
"""

import pytest

import jax

from happysim_tpu.tpu import run_ensemble
from happysim_tpu.tpu.mesh import replica_mesh
from happysim_tpu.tpu.model import EnsembleModel


def _router_model(policy="least_outstanding"):
    """Two-server fan-out. Every policy — the adaptive
    ``least_outstanding`` default included, since ISSUE 17's graph
    planner — is kernel-approved, so this is an APPROVED fixture; the
    decline fixtures for this file are the consensus models below.
    macro_block=2: the fan-out compiles the KERNEL under the CI gate's
    forced HS_TPU_PALLAS=1, and interpret compile scales with the
    unroll (macro 32 costs two minutes)."""
    model = EnsembleModel(horizon_s=1.0, macro_block=2)
    src = model.source(rate=4.0)
    first = model.server(service_mean=0.05, queue_capacity=4)
    second = model.server(service_mean=0.05, queue_capacity=4)
    router = model.router(policy=policy, targets=[first, second])
    snk = model.sink()
    model.connect(src, router)
    model.connect(first, snk)
    model.connect(second, snk)
    return model


def _faulted_telemetry_mm1():
    from happysim_tpu.tpu.model import FaultSpec

    model = EnsembleModel(horizon_s=2.0, macro_block=2)
    src = model.source(rate=5.0)
    srv = model.server(
        service_mean=0.1,
        queue_capacity=8,
        fault=FaultSpec(rate=0.5, mean_duration_s=0.3),
    )
    snk = model.sink()
    model.connect(src, srv)
    model.connect(srv, snk)
    model.telemetry(window_s=0.5)
    return model


def test_removed_decline_reasons_no_longer_appear(monkeypatch):
    """PR-6 contract: "model has windowed telemetry" and "has a
    stochastic fault schedule" are no longer decline reasons — a faulted
    model with telemetry on reports engine_path == "scan+pallas" when
    the kernel is forced (the realistic production configuration runs
    on the fast path)."""
    pytest.importorskip("jax.experimental.pallas")
    from happysim_tpu.tpu.kernels import kernel_plan

    plan, reason = kernel_plan(_faulted_telemetry_mm1())
    assert plan is not None and reason == ""
    assert "telemetry" not in reason and "fault" not in reason

    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    result = run_ensemble(
        _faulted_telemetry_mm1(),
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
        max_events=48,
    )
    assert result.engine_path == "scan+pallas", result.kernel_decline
    assert result.kernel_decline == ""
    assert result.timeseries is not None


def _chaos_mm1():
    """The tier-1 chaos canary (ISSUE 14): every chaos feature on the
    SMALLEST kernel shape — one server with a correlated fault schedule,
    backoff+jitter retries, hedging, and a brownout window, behind a
    token-bucket limiter over a lossy edge, with windowed telemetry.
    Chain-shaped so the interpret-mode compile stays cheap enough for
    tier-1; the fan-out chaos matrix lives in the slow-marked tiers."""
    from happysim_tpu.tpu.model import FaultSpec

    model = EnsembleModel(horizon_s=2.0, macro_block=2, transit_capacity=4)
    src = model.source(rate=5.0)
    lim = model.limiter(refill_rate=8.0, capacity=4.0)
    srv = model.server(
        service_mean=0.1,
        queue_capacity=8,
        deadline_s=0.8,
        max_retries=2,
        retry_backoff_s=0.05,
        retry_jitter=0.5,
        hedge_delay_s=0.25,
        fault=FaultSpec(rate=0.5, mean_duration_s=0.3, correlated=True),
        outage=(1.0, 1.3),
    )
    model.correlated_outages(rate=0.3, mean_duration_s=0.3, trigger_p=0.5)
    snk = model.sink()
    model.connect(src, lim)
    model.connect(lim, srv, loss_p=0.05)
    model.connect(srv, snk)
    model.telemetry(window_s=0.5)
    return model


ALL_CHAOS = (
    "faults",
    "correlated_outages",
    "backoff_retries",
    "hedging",
    "brownouts",
    "packet_loss",
    "limiters",
    "telemetry",
)


def test_chaos_stack_decline_removed(monkeypatch):
    """ISSUE-14 contract: limiters, correlated outages, backoff
    retries, hedging, brownouts, and packet loss are no longer decline
    reasons — the whole chaos stack runs engine_path == "scan+pallas"
    when the kernel is forced, and the chaos dimension reaches
    engine_report()."""
    pytest.importorskip("jax.experimental.pallas")
    from happysim_tpu.tpu.kernels import kernel_plan

    plan, reason = kernel_plan(_chaos_mm1())
    assert plan is not None and reason == ""
    assert plan["chaos"] == ALL_CHAOS

    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    result = run_ensemble(
        _chaos_mm1(),
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
        max_events=48,
    )
    assert result.engine_path == "scan+pallas", result.kernel_decline
    assert result.kernel_decline == ""
    assert result.kernel_shape == "mm1"
    assert result.kernel_chaos == ALL_CHAOS
    assert result.engine_report()["kernel_chaos"] == ALL_CHAOS
    assert result.timeseries is not None


def _resilient_chaos_mm1():
    """The tier-1 RESILIENCE canary (ISSUE 15): the chaos canary with
    the full defense layer on top — breaker tuned to trip at this seed,
    queue-depth shedding with a priority fraction, and a retry budget
    tight enough to suppress launches. Chain-shaped and macro_block=2
    so the interpret-mode compile stays inside the tier-1 envelope."""
    model = _chaos_mm1()
    model.circuit_breaker(
        failure_threshold=1, window_s=0.5, cooldown_s=0.3, half_open_probes=1
    )
    model.load_shed(policy="queue_depth", threshold=2, priority_fraction=0.25)
    model.retry_budget(ratio=0.1, min_per_s=0.2, burst=1.0)
    return model


ALL_RESILIENCE = ("circuit_breaker", "load_shed", "retry_budget")


def test_resilience_stack_runs_fused_and_breaker_trips(monkeypatch):
    """ISSUE-15 contract + the tier-1 breaker-trips canary: the defense
    layer adds NO decline reasons — breaker + shed + budget on the
    chaos canary still runs engine_path == "scan+pallas" when forced,
    the resilience features reach kernel_chaos / engine_report, and the
    breaker actually TRIPS at this seed (a canary of zeros would pin
    nothing)."""
    pytest.importorskip("jax.experimental.pallas")
    from happysim_tpu.tpu.kernels import kernel_plan

    plan, reason = kernel_plan(_resilient_chaos_mm1())
    assert plan is not None and reason == ""
    assert plan["chaos"] == ALL_CHAOS[:-1] + ALL_RESILIENCE + ("telemetry",)

    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    result = run_ensemble(
        _resilient_chaos_mm1(),
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
        max_events=64,
    )
    assert result.engine_path == "scan+pallas", result.kernel_decline
    assert result.kernel_decline == ""
    assert result.resilience_features == ALL_RESILIENCE
    report = result.engine_report()["resilience"]
    assert report["circuit_breaker"] and report["load_shed"] and report["retry_budget"]
    # The canary teeth: the breaker tripped and short-circuited work.
    assert sum(result.breaker_tripped) > 0
    assert sum(result.server_breaker_dropped) > 0
    assert report["breaker_tripped_total"] == sum(result.breaker_tripped)
    assert max(result.breaker_open_fraction) > 0.0


def test_resilience_adds_no_decline_reasons():
    """The per-feature decline list stays purely non-resilience: the
    same declined shape (the consensus M/M/1) collects the same
    "; "-joined reasons with and without the full defense layer, and no
    resilience feature name ever appears in a decline."""
    from happysim_tpu.tpu.kernels import kernel_plan

    def declined(defended: bool):
        model = _consensus_mm1()
        if defended:
            for server in model.servers:
                server.deadline_s = 0.3
                server.max_retries = 1
            model.circuit_breaker()
            model.load_shed(policy="utilization", threshold=1.0)
            model.retry_budget(ratio=0.2)
        return kernel_plan(model)

    plan, bare_reason = declined(False)
    assert plan is None
    plan, defended_reason = declined(True)
    assert plan is None
    assert defended_reason == bare_reason
    for feature in ALL_RESILIENCE:
        assert feature not in defended_reason


def _consensus_mm1():
    """The chain-eligible M/M/1 shape with the full consensus layer on
    top: partition windows (the dark source), a 1-of-1 quorum, and a
    single-member election — the smallest model that must decline BOTH
    fast paths by name."""
    model = EnsembleModel(horizon_s=2.0, macro_block=2)
    src = model.source(rate=5.0)
    srv = model.server(service_mean=0.1, queue_capacity=8)
    snk = model.sink()
    model.connect(src, srv)
    model.connect(srv, snk)
    model.network_partition(group=[srv], windows=((0.5, 1.0),))
    model.quorum([srv], write=1, read=1)
    model.leader_election([srv], heartbeat_s=0.1, timeout_s=0.3)
    return model


def _two_sink_mm1():
    """An M/M/1 with a second, unconnected sink — the smallest purely
    TOPOLOGICAL decline left now that the graph planner approves every
    single-source single-sink service graph (ISSUE 17)."""
    model = EnsembleModel(horizon_s=2.0, macro_block=2)
    src = model.source(rate=5.0)
    srv = model.server(service_mean=0.1, queue_capacity=8)
    snk = model.sink()
    model.sink()  # second sink: kernel supports exactly one
    model.connect(src, srv)
    model.connect(srv, snk)
    return model


CONSENSUS_DECLINES = (
    "network partitions",
    "quorum group",
    "leader election",
)


def test_consensus_declines_kernel_by_name(monkeypatch):
    """ISSUE-16 contract: partitions, quorum, and leader election each
    decline the Pallas kernel with a NAMED per-feature reason (no
    blanket "consensus" reason), all collected into the one "; "-joined
    kernel_decline note."""
    from happysim_tpu.tpu.kernels import kernel_plan

    plan, reason = kernel_plan(_consensus_mm1())
    assert plan is None
    for feature in CONSENSUS_DECLINES:
        assert feature in reason, (feature, reason)
    # One joined list, partitions first (the consult site the kernel
    # would have to fuse first).
    assert reason.index("network partitions") < reason.index("quorum group")
    assert reason.index("quorum group") < reason.index("leader election")
    assert reason.count("; ") >= 2

    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    result = run_ensemble(
        _consensus_mm1(),
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
        max_events=48,
    )
    # The chain closed form also declines (silently, by construction):
    # the scan ran, and the decline note surfaces every reason.
    assert result.engine_path == "scan"
    for feature in CONSENSUS_DECLINES:
        assert feature in result.kernel_decline
    assert "HS_TPU_PALLAS" in result.kernel_decline
    assert result.consensus_features == (
        "network_partitions",
        "quorum",
        "leader_election",
    )


def test_consensus_chain_decline_by_feature():
    """Each consensus feature ALONE pushes the chain-eligible M/M/1 off
    the closed form onto the scan — and the consensus-free base model
    still runs the chain (the decline is per-feature, not blanket)."""
    from happysim_tpu.tpu.model import mm1_model

    base = mm1_model(lam=4.0, mu=9.0, horizon_s=2.0)
    result = run_ensemble(
        base,
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
    )
    assert result.engine_path == "chain"

    def with_feature(feature):
        from happysim_tpu.tpu.model import SERVER, NodeRef

        model = mm1_model(lam=4.0, mu=9.0, horizon_s=2.0)
        srv = NodeRef(SERVER, 0)
        if feature in ("partition", "quorum", "leader"):
            model.network_partition(group=[srv], windows=((0.5, 1.0),))
        if feature == "quorum":
            model.quorum([srv], write=1, read=1)
        if feature == "leader":
            model.leader_election([srv], heartbeat_s=0.1, timeout_s=0.3)
        return run_ensemble(
            model,
            n_replicas=4,
            seed=0,
            mesh=replica_mesh(jax.devices("cpu")[:1]),
            max_events=48,
        )

    for feature in ("partition", "quorum", "leader"):
        assert with_feature(feature).engine_path == "scan", feature


def test_consensus_free_models_add_no_new_reasons():
    """The declined-shape reason list is unchanged for models without
    consensus specs, and no consensus feature name ever appears in a
    consensus-free decline."""
    from happysim_tpu.tpu.kernels import kernel_plan

    model = _two_sink_mm1()  # 2 sinks: a topological decline
    plan, reason = kernel_plan(model)
    assert plan is None
    for feature in CONSENSUS_DECLINES:
        assert feature not in reason


def test_kernel_decline_surfaces_every_reason(monkeypatch):
    """ISSUE-14 satellite: EnsembleResult.kernel_decline carries the
    FULL decline list (``; ``-joined, first reason first), not just the
    first reason hit."""
    from happysim_tpu.tpu.model import SERVER, NodeRef

    model = _two_sink_mm1()
    model.network_partition(
        group=[NodeRef(SERVER, 0)], windows=((0.5, 1.0),)
    )
    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    result = run_ensemble(
        model,
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
        max_events=32,
    )
    assert result.engine_path == "scan"
    decline = result.kernel_decline
    assert "network partitions" in decline and "2 sinks" in decline
    # One joined list: the feature reason precedes the topology reason,
    # separated by the "; " joiner inside one decline note.
    assert decline.index("network partitions") < decline.index("2 sinks")
    assert "; " in decline.split("(", 1)[1]
    assert "HS_TPU_PALLAS" in decline
    assert result.kernel_chaos == ()


def test_blanket_router_decline_removed(monkeypatch):
    """ISSUE-11 contract: "model has routers" is no longer a decline
    reason. A random-policy load-balancer fan-out is kernel-approved and
    runs engine_path == "scan+pallas" when forced (explicit max_events
    keeps it off the chain closed form); the remaining router declines
    are per-feature (asserted in tests/unit/test_kernel_event_step.py).
    """
    pytest.importorskip("jax.experimental.pallas")
    from happysim_tpu.tpu.kernels import kernel_plan

    plan, reason = kernel_plan(_router_model(policy="random"))
    assert plan is not None and reason == ""
    assert plan["shape"] == "router"

    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    result = run_ensemble(
        _router_model(policy="random"),
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
        max_events=32,
    )
    assert result.engine_path == "scan+pallas", result.kernel_decline
    assert result.kernel_decline == ""
    assert result.kernel_shape == "router"
    assert result.engine_report()["kernel_shape"] == "router"


def _graph_dag_model():
    """ISSUE 17's acceptance shape: a ramp-profiled source feeding a
    2-router shared-backend DAG under adaptive least_outstanding
    routing — front tier fans out, both front servers feed the back
    router, back tier drains to the sink. macro_block=2 keeps the
    interpret-mode kernel compile inside the tier-1 envelope."""
    model = EnsembleModel(horizon_s=1.0, macro_block=2, transit_capacity=4)
    src = model.ramp_source(start_rate=3.0, end_rate=9.0, ramp_duration_s=0.8)
    front = [model.server(service_mean=0.05, queue_capacity=4) for _ in range(2)]
    back = [model.server(service_mean=0.04, queue_capacity=4) for _ in range(2)]
    front_lb = model.router(policy="least_outstanding")
    back_lb = model.router(policy="least_outstanding")
    snk = model.sink()
    model.connect(src, front_lb)
    for server in front:
        model.connect(front_lb, server)
        model.connect(server, back_lb)
    for server in back:
        model.connect(back_lb, server)
        model.connect(server, snk)
    return model


def test_graph_era_decline_reasons_removed():
    """ISSUE-17 contract: adaptive (least_outstanding) routing, rate
    profiles, and >1 router are no longer decline reasons — the 2-router
    shared-backend DAG with a ramp profile is kernel-APPROVED with
    shape "graph", and none of the retired reason fragments appear
    anywhere in the (empty) reason."""
    from happysim_tpu.tpu.kernels import kernel_plan

    plan, reason = kernel_plan(_graph_dag_model())
    assert plan is not None and reason == "", reason
    assert plan["shape"] == "graph"
    assert plan["servers"] == [0, 1, 2, 3]
    assert plan["routers"] == [0, 1]
    assert plan["policies"] == ("least_outstanding", "least_outstanding")


def test_graph_shape_runs_fused(monkeypatch):
    """The tier-1 graph canary: the DAG above runs engine_path ==
    "scan+pallas" when forced, with kernel_shape == "graph" provenance
    reaching engine_report()."""
    pytest.importorskip("jax.experimental.pallas")
    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    result = run_ensemble(
        _graph_dag_model(),
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
        max_events=48,
    )
    assert result.engine_path == "scan+pallas", result.kernel_decline
    assert result.kernel_decline == ""
    assert result.kernel_shape == "graph"
    assert result.engine_report()["kernel_shape"] == "graph"
    assert sum(result.sink_count) > 0


def test_multi_device_mesh_runs_the_kernel(monkeypatch):
    """ISSUE-13 contract: ">1-device mesh" is no longer a decline
    reason. The faulted+telemetry canary on the 8-device virtual mesh
    runs engine_path == "scan+pallas" (shard_map, per-shard tile) when
    forced, and the mesh provenance reaches engine_report()."""
    pytest.importorskip("jax.experimental.pallas")
    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    result = run_ensemble(
        _faulted_telemetry_mm1(),
        n_replicas=8,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:8]),
        max_events=48,
    )
    assert result.engine_path == "scan+pallas", result.kernel_decline
    assert result.kernel_decline == ""
    report = result.engine_report()["mesh"]
    assert report["devices"] == 8
    assert report["per_shard_replicas"] == 1
    assert report["reduce_path"] == "device-psum-tree"


def test_host_mesh_decline_names_the_mesh_first_path(monkeypatch):
    """The one remaining mesh decline (2-D hosts/replicas) names the
    1-D mesh-first layout instead of the old single-device-only
    advice."""
    from happysim_tpu.tpu.kernels import kernel_decision
    from happysim_tpu.tpu.mesh import host_replica_mesh

    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    mesh = host_replica_mesh(jax.devices("cpu")[:8], n_hosts=2)
    use, note = kernel_decision(
        _faulted_telemetry_mm1(), mesh=mesh, checkpointing=False, macro=2
    )
    assert not use
    assert "1-D" in note and "replica_mesh" in note
    assert "single-device" not in note


def test_engine_report_names_escape_hatches_on_decline(monkeypatch):
    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    result = run_ensemble(
        _consensus_mm1(),
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
        max_events=32,
    )
    report = result.engine_report()
    assert report["engine_path"] == "scan"
    assert "network partitions" in report["kernel_decline"]
    assert set(report["escape_hatches"]) == {
        "HS_TPU_PALLAS",
        "HS_TPU_EARLY_EXIT",
    }
    # Occupancy counters are exposed on the scan path...
    assert report["blocks_total"] > 0
    assert sum(report["block_occupancy"].values()) == result.n_replicas
    assert report["events_per_block"] > 0
    # ...and the summary's Engine entity names the hatches too.
    engine_entities = [
        e for e in result.summary().entities if e.kind == "Engine"
    ]
    assert len(engine_entities) == 1
    extra = engine_entities[0].extra
    assert "HS_TPU_PALLAS" in extra["escape_hatches"]
    assert "HS_TPU_EARLY_EXIT" in extra["escape_hatches"]
    assert "network partitions" in extra["kernel_decline"]


def test_engine_report_on_the_chain_path():
    """The chain closed form runs no macro-blocks, but engine_report()
    still exposes the occupancy counters (zeroed) and the path name."""
    from happysim_tpu.tpu.model import mm1_model

    result = run_ensemble(
        mm1_model(lam=4.0, mu=9.0, horizon_s=4.0),
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
    )
    assert result.engine_path == "chain"
    report = result.engine_report()
    assert report["blocks_total"] == 0
    assert report["block_occupancy"] == {}
    assert report["events_per_block"] == 0.0
    assert report["profiler_scopes"] == (
        "hs.macro_block",
        "hs.kernel",
        "hs.reduce",
    )


def test_kernel_decline_reason_reaches_result(monkeypatch):
    """Forcing HS_TPU_PALLAS=1 on an unsupported shape soundly runs the
    lax scan AND surfaces the decline (naming the flag) on the result."""
    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    result = run_ensemble(
        _two_sink_mm1(),
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
        max_events=32,
    )
    assert result.engine_path == "scan"
    assert "2 sinks" in result.kernel_decline
    assert "HS_TPU_PALLAS" in result.kernel_decline
    assert "lax" in result.kernel_decline


def test_kernel_disabled_note_is_surfaced(monkeypatch):
    """HS_TPU_PALLAS=0's note reaches the result too (decision-level —
    the run itself is covered by the forced-on test above, and a second
    compiled program here would only re-pay XLA)."""
    from happysim_tpu.tpu.kernels import kernel_decision

    monkeypatch.setenv("HS_TPU_PALLAS", "0")
    use, note = kernel_decision(
        _router_model(),
        mesh=replica_mesh(jax.devices("cpu")[:1]),
        checkpointing=False,
        macro=32,
    )
    assert not use and "HS_TPU_PALLAS=0" in note


def test_partitioned_telemetry_rejection_names_flags():
    from happysim_tpu.tpu.partitioned import run_partitioned

    model = EnsembleModel(horizon_s=2.0)
    src = model.source(rate=4.0)
    srv = model.server(service_mean=0.05)
    snk = model.sink()
    egress = model.remote(ingress=srv, latency_s=0.5)
    model.connect(src, srv)
    model.connect(srv, snk)
    del egress
    model.telemetry(window_s=0.5)
    with pytest.raises(ValueError) as excinfo:
        run_partitioned(model, window_s=0.25)
    message = str(excinfo.value)
    assert "HS_TPU_PALLAS" in message
    assert "HS_TPU_EARLY_EXIT" in message
    assert "run_ensemble" in message


def test_compile_cache_noop_without_env(monkeypatch):
    """Without HS_TPU_COMPILE_CACHE the helper must not touch jax config
    (the suite would otherwise start writing cache files everywhere)."""
    from happysim_tpu.tpu import maybe_enable_compile_cache

    monkeypatch.delenv("HS_TPU_COMPILE_CACHE", raising=False)
    import happysim_tpu.tpu.engine as engine

    before = engine._COMPILE_CACHE_WIRED
    assert maybe_enable_compile_cache() == before
    assert engine._COMPILE_CACHE_WIRED == before


def _traced_mm1(chunk_len=8):
    """The chain-eligible M/M/1 shape with a recorded trace driving the
    source — the smallest model that must decline BOTH fast paths by
    name (ISSUE 18): the chain's closed form prices Poisson streams
    only, and the kernel's fused dispatch has no page-advance boundary
    to stream trace pages through."""
    import numpy as np

    from happysim_tpu.tpu.traces import TraceSpec

    times = np.linspace(0.05, 1.9, 24).astype(np.float32)
    trace = TraceSpec(times=times, tenants=None, chunk_len=chunk_len)
    model = EnsembleModel(horizon_s=2.0, macro_block=2)
    src = model.trace_arrivals(trace)
    srv = model.server(service_mean=0.05, queue_capacity=8)
    snk = model.sink()
    model.connect(src, srv)
    model.connect(srv, snk)
    return model


def test_trace_declines_kernel_by_name(monkeypatch):
    """ISSUE-18 contract: trace-driven arrivals decline the Pallas
    kernel with a NAMED reason, and forcing HS_TPU_PALLAS=1 soundly
    runs the scan with the decline surfaced on the result."""
    from happysim_tpu.tpu.kernels import kernel_plan

    plan, reason = kernel_plan(_traced_mm1())
    assert plan is None
    assert "trace-driven arrivals" in reason

    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    result = run_ensemble(
        _traced_mm1(),
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
        max_events=64,
    )
    assert result.engine_path == "scan"
    assert "trace-driven arrivals" in result.kernel_decline
    assert "HS_TPU_PALLAS" in result.kernel_decline


def test_trace_declines_chain_by_name():
    """The chain closed form declines traced sources: the same M/M/1
    shape runs the chain without a trace and the scan WITH one (no
    explicit max_events, so the chain dispatch is reachable)."""
    from happysim_tpu.tpu.chain import fast_plan
    from happysim_tpu.tpu.model import mm1_model

    base = mm1_model(lam=4.0, mu=9.0, horizon_s=2.0)
    assert fast_plan(base) is not None
    assert fast_plan(_traced_mm1()) is None

    result = run_ensemble(
        _traced_mm1(),
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
    )
    assert result.engine_path == "scan"


def test_partitioned_trace_rejection_names_feature():
    """run_partitioned declines traced models naming the feature and
    the mesh-first path that does support it."""
    from happysim_tpu.tpu.partitioned import run_partitioned

    from happysim_tpu.tpu.model import SERVER, NodeRef

    model = _traced_mm1()  # plus a remote: the partitioned executor's gate
    model.remote(ingress=NodeRef(SERVER, 0), latency_s=0.5)
    with pytest.raises(ValueError) as excinfo:
        run_partitioned(model, window_s=0.25)
    message = str(excinfo.value)
    assert "trace_arrivals" in message
    assert "run_ensemble" in message


def test_traced_model_runs_scan_end_to_end(monkeypatch):
    """The tier-1 trace canary: a traced M/M/1 runs engine_path ==
    "scan" end to end (kernel forced on — the decline must route around
    it), delivers exactly n_replicas * n_arrivals jobs, and the
    ingestion accounting reaches engine_report()["trace"]."""
    monkeypatch.setenv("HS_TPU_PALLAS", "1")
    model = _traced_mm1(chunk_len=8)
    n_arrivals = model.sources[0].trace.n_arrivals
    result = run_ensemble(
        model,
        n_replicas=4,
        seed=0,
        mesh=replica_mesh(jax.devices("cpu")[:1]),
        max_events=128,
    )
    assert result.engine_path == "scan"
    assert result.trace
    assert sum(result.trace_tenant_arrivals) == 4 * n_arrivals
    report = result.engine_report()["trace"]
    assert report["enabled"] is True
    assert report["chunk_len"] == 8
    assert report["n_chunks"] == 3  # 24 arrivals / 8 per page
    assert report["max_resident_chunks"] <= 2
    assert report["chunks_streamed"] >= report["n_chunks"]
    assert report["stream_steps"] >= 1


def test_trace_profile_conflict_rejected():
    """ISSUE-18 small fix: a profile and trace_arrivals on the same
    source is rejected at validate() time, naming both."""
    from happysim_tpu.tpu.model import RateProfile

    model = _traced_mm1()
    model.sources[0].profile = RateProfile(
        kind="ramp", end_rate=2.0, ramp_duration_s=1.0
    )
    with pytest.raises(ValueError) as excinfo:
        model.validate()
    message = str(excinfo.value)
    assert "profile" in message and "trace_arrivals" in message
    assert "ramp" in message


def test_rate_profile_errors_name_the_kind():
    """ISSUE-18 small fix: RateProfile validation errors carry the
    offending kind."""
    from happysim_tpu.tpu.model import RateProfile

    with pytest.raises(ValueError, match="ramp"):
        RateProfile(kind="ramp", end_rate=2.0, ramp_duration_s=0.0).validate()
    with pytest.raises(ValueError, match="spike"):
        RateProfile(
            kind="spike", spike_rate=-1.0, spike_start_s=0.0, spike_end_s=1.0
        ).validate()
    with pytest.raises(ValueError, match="wobble"):
        RateProfile(kind="wobble").validate()


def test_chain_decline_log_names_flags(caplog):
    """The chain fast path's certificate fallback tells the user which
    scan flavor ran (flag names in the log record)."""
    import logging

    from happysim_tpu.tpu.model import mm1_model

    # Overloaded M/M/1 with a tiny queue: the certificate must fail and
    # the run must fall back to the scan (drops prove the loop ran).
    model = mm1_model(lam=9.0, mu=10.0, horizon_s=8.0, queue_capacity=1)
    with caplog.at_level(logging.INFO, logger="happysim_tpu.tpu.chain"):
        result = run_ensemble(
            model,
            n_replicas=8,
            seed=1,
            mesh=replica_mesh(jax.devices("cpu")[:1]),
        )
    # Either scan flavor: the CI kernel-equivalence gate re-runs this
    # file with HS_TPU_PALLAS=1, where the supported M/M/1 shape lands
    # on the fused kernel after the certificate fallback.
    assert result.engine_path in ("scan", "scan+pallas")
    assert result.server_dropped[0] > 0
    fallback_logs = [
        r.getMessage() for r in caplog.records if "falling back" in r.getMessage()
    ]
    assert fallback_logs, "expected the chain certificate fallback log"
    assert any("HS_TPU_PALLAS" in m for m in fallback_logs)
    assert any("HS_TPU_EARLY_EXIT" in m for m in fallback_logs)
