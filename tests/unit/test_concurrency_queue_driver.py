"""Depth tests for server concurrency models and the queue<->worker driver
protocol (ref components/server/concurrency.py:15-293,
components/queue_driver.py:27)."""

import pytest

from happysim_tpu import Instant, Simulation
from happysim_tpu.components.queue import Queue
from happysim_tpu.components.queue_driver import QueueDriver
from happysim_tpu.components.server.concurrency import (
    DynamicConcurrency,
    FixedConcurrency,
    WeightedConcurrency,
)
from happysim_tpu.core.entity import Entity
from happysim_tpu.core.event import Event


class TestFixedConcurrency:
    def test_capacity_boundary(self):
        c = FixedConcurrency(limit=2)
        assert c.has_capacity()
        c.acquire()
        c.acquire()
        assert not c.has_capacity()
        assert c.active == 2

    def test_over_acquire_raises(self):
        c = FixedConcurrency(limit=1)
        c.acquire()
        with pytest.raises(RuntimeError, match="beyond concurrency limit"):
            c.acquire()

    def test_release_without_acquire_raises(self):
        with pytest.raises(RuntimeError, match="nothing in flight"):
            FixedConcurrency().release()

    def test_invalid_limit(self):
        with pytest.raises(ValueError):
            FixedConcurrency(limit=0)

    def test_release_restores_capacity(self):
        c = FixedConcurrency(limit=1)
        c.acquire()
        c.release()
        assert c.has_capacity()
        assert c.active == 0


class TestDynamicConcurrency:
    def test_set_limit_widens_and_narrows(self):
        c = DynamicConcurrency(initial_limit=1)
        c.acquire()
        assert not c.has_capacity()
        c.set_limit(3)
        assert c.has_capacity()
        c.set_limit(1)
        # Narrowing below in-flight work is allowed: existing work finishes,
        # new admissions stop.
        assert not c.has_capacity()
        assert c.limit == 1

    def test_invalid_limits(self):
        with pytest.raises(ValueError):
            DynamicConcurrency(initial_limit=0)
        with pytest.raises(ValueError):
            DynamicConcurrency().set_limit(0)


class TestWeightedConcurrency:
    def _event(self, cost):
        class _E:
            pass

        e = _E()
        e.cost = cost
        return e

    def test_cost_function_admission(self):
        c = WeightedConcurrency(capacity=10.0, cost_fn=lambda e: e.cost)
        big = self._event(8.0)
        small = self._event(3.0)
        assert c.has_capacity(big)
        c.acquire(big)
        assert not c.has_capacity(small)  # 8 + 3 > 10
        assert c.has_capacity(self._event(2.0))
        c.release(big)
        assert c.active == 0.0

    def test_default_unit_cost(self):
        c = WeightedConcurrency(capacity=2.0)
        c.acquire()
        c.acquire()
        assert not c.has_capacity()

    def test_release_floors_at_zero(self):
        c = WeightedConcurrency(capacity=5.0, cost_fn=lambda e: e.cost)
        c.release(self._event(3.0))
        assert c.active == 0.0

    def test_invalid_capacity(self):
        with pytest.raises(ValueError):
            WeightedConcurrency(capacity=0.0)


class _SlotWorker(Entity):
    """Worker with explicit slots; records service order; completes instantly."""

    def __init__(self, name, slots=1):
        super().__init__(name)
        self.slots = slots
        self.in_flight = 0
        self.handled = []

    def has_capacity(self):
        return self.in_flight < self.slots

    def handle_event(self, event):
        self.handled.append(event.context.get("request_id"))
        return None


def _enqueue(queue, t, request_id):
    return Event(
        Instant.from_seconds(t),
        "Request",
        target=queue,
        context={"request_id": request_id},
    )


class TestQueueDriver:
    def _rig(self, slots=1, capacity=None):
        worker = _SlotWorker("worker", slots=slots)
        queue = Queue("q", capacity=capacity) if capacity else Queue("q")
        driver = QueueDriver("drv", queue=queue, worker=worker)
        return queue, driver, worker

    def test_single_item_flows_through(self):
        queue, driver, worker = self._rig()
        sim = Simulation(entities=[queue, driver, worker], end_time=Instant.from_seconds(5))
        sim.schedule(_enqueue(queue, 1, 0))
        sim.run()
        assert worker.handled == [0]
        assert queue.depth == 0

    def test_fifo_order_preserved(self):
        queue, driver, worker = self._rig()
        sim = Simulation(entities=[queue, driver, worker], end_time=Instant.from_seconds(5))
        for i in range(5):
            sim.schedule(_enqueue(queue, 1, i))
        sim.run()
        assert worker.handled == [0, 1, 2, 3, 4]

    def test_same_instant_burst_drains(self):
        queue, driver, worker = self._rig(slots=2)
        sim = Simulation(entities=[queue, driver, worker], end_time=Instant.from_seconds(5))
        for i in range(6):
            sim.schedule(_enqueue(queue, 1, i))
        sim.run()
        assert sorted(worker.handled) == [0, 1, 2, 3, 4, 5]

    def test_downstream_entities_names_worker(self):
        queue, driver, worker = self._rig()
        assert driver.downstream_entities() == [worker]

    def test_backpressure_holds_items_in_queue(self):
        class _Sticky(_SlotWorker):
            """Worker that never frees its slot (stuck service)."""

            def handle_event(self, event):
                self.in_flight += 1
                self.handled.append(event.context.get("request_id"))
                return None

        worker = _Sticky("worker", slots=1)
        queue = Queue("q")
        driver = QueueDriver("drv", queue=queue, worker=worker)
        sim = Simulation(entities=[queue, driver, worker], end_time=Instant.from_seconds(5))
        for i in range(3):
            sim.schedule(_enqueue(queue, 1, i))
        sim.run()
        assert worker.handled == [0]
        assert queue.depth == 2
