"""Unit tests: behavior package (agents, decisions, influence, populations).

Mirrors the reference's coverage (tests/unit/components/behavior/) using
tiny real simulations, per the unit≈micro-integration strategy
(SURVEY.md §4).
"""

import random

import pytest

from happysim_tpu import Event, Instant, Simulation
from happysim_tpu.components.behavior import (
    Agent,
    AgentState,
    BoundedConfidenceModel,
    BoundedRationalityModel,
    Choice,
    CompositeModel,
    DecisionContext,
    DeGrootModel,
    DemographicSegment,
    Environment,
    Memory,
    NormalTraitDistribution,
    PersonalityTraits,
    Population,
    Rule,
    RuleBasedModel,
    SocialGraph,
    SocialInfluenceModel,
    UniformTraitDistribution,
    UtilityModel,
    VoterModel,
    broadcast_stimulus,
    influence_propagation,
    policy_announcement,
    price_change,
    targeted_stimulus,
)


def _ctx(choices, traits=None, state=None, **kw):
    return DecisionContext(
        traits=traits or PersonalityTraits.big_five(),
        state=state or AgentState(),
        choices=[Choice(c) if isinstance(c, str) else c for c in choices],
        **kw,
    )


# ---------------------------------------------------------------- traits ----
def test_big_five_clamps_and_defaults():
    t = PersonalityTraits.big_five(openness=1.7, neuroticism=-0.3)
    assert t.get("openness") == 1.0
    assert t.get("neuroticism") == 0.0
    assert t.get("extraversion") == 0.5
    assert t.get("never_defined") == 0.5  # unknown dims read neutral
    assert set(t.names()) == {
        "openness",
        "conscientiousness",
        "extraversion",
        "agreeableness",
        "neuroticism",
    }


def test_trait_distributions_are_seeded_and_bounded():
    normal = NormalTraitDistribution({"openness": 0.9}, {"openness": 5.0})
    uniform = UniformTraitDistribution(["a", "b"])
    for dist in (normal, uniform):
        a = dist.sample(random.Random(7))
        b = dist.sample(random.Random(7))
        assert a.dimensions == b.dimensions  # same seed, same sample
        assert all(0.0 <= v <= 1.0 for v in a.dimensions.values())


# ----------------------------------------------------------------- state ----
def test_state_decay_moves_toward_resting_values():
    s = AgentState(mood=0.9, energy=1.0, needs={"hunger": 0.2})
    s.decay(10.0)
    assert s.mood == pytest.approx(0.7)  # settles toward 0.5 at 0.02/s
    assert s.energy == pytest.approx(0.95)  # drains at 0.005/s
    assert s.needs["hunger"] == pytest.approx(0.3)  # grows at 0.01/s
    s.decay(1000.0)
    assert s.mood == 0.5 and s.energy == 0.0 and s.needs["hunger"] == 1.0


def test_state_decay_from_below_neutral_and_noop():
    s = AgentState(mood=0.1)
    s.decay(5.0)
    assert s.mood == pytest.approx(0.2)
    before = s.mood
    s.decay(0.0)
    assert s.mood == before


def test_memory_ring_and_valence():
    s = AgentState()
    for i in range(150):
        s.add_memory(Memory(time=float(i), event_type="e", valence=1.0 if i >= 145 else 0.0))
    assert len(s.recent_memories(1000)) == 100  # bounded at capacity
    newest = s.recent_memories(3)
    assert [m.time for m in newest] == [149.0, 148.0, 147.0]
    assert s.average_recent_valence(5) == 1.0
    assert AgentState().average_recent_valence() == 0.0


# -------------------------------------------------------------- decision ----
def test_utility_model_argmax_and_softmax():
    util = UtilityModel(lambda c, ctx: {"a": 0.1, "b": 0.9}[c.action])
    assert util.decide(_ctx(["a", "b"]), random.Random(0)).action == "b"
    # High temperature: both actions get picked over many trials
    soft = UtilityModel(lambda c, ctx: {"a": 0.1, "b": 0.9}[c.action], temperature=5.0)
    rng = random.Random(0)
    picks = {soft.decide(_ctx(["a", "b"]), rng).action for _ in range(50)}
    assert picks == {"a", "b"}
    assert util.decide(_ctx([]), random.Random(0)) is None


def test_rule_based_priority_and_short_circuit():
    rules = [
        Rule(lambda ctx: True, "low", priority=1),
        Rule(lambda ctx: True, "high", priority=9),
        Rule(lambda ctx: False, "never", priority=99),
    ]
    model = RuleBasedModel(rules)
    assert model.decide(_ctx(["low", "high"]), random.Random(0)).action == "high"
    # Winning rule names an absent action -> abstain (no fall-through)
    assert model.decide(_ctx(["low"]), random.Random(0)) is None
    # No rule fires -> default action
    fallback = RuleBasedModel([Rule(lambda ctx: False, "x")], default_action="d")
    assert fallback.decide(_ctx(["d"]), random.Random(0)).action == "d"


def test_bounded_rationality_satisfices_then_settles():
    util = lambda c, ctx: {"bad": 0.1, "ok": 0.6, "great": 0.95}[c.action]
    model = BoundedRationalityModel(util, aspiration=0.5)
    pick = model.decide(_ctx(["bad", "ok", "great"]), random.Random(3))
    assert pick.action in {"ok", "great"}  # first over aspiration, order shuffled
    # Nothing clears the bar -> best available
    picky = BoundedRationalityModel(util, aspiration=0.99)
    assert picky.decide(_ctx(["bad", "ok", "great"]), random.Random(3)).action == "great"


def test_social_influence_follows_the_crowd():
    # Individual utility is flat; highly agreeable agent + strong peer signal
    model = SocialInfluenceModel(lambda c, ctx: 0.5, conformity_weight=1.0)
    traits = PersonalityTraits.big_five(agreeableness=1.0)
    rng = random.Random(1)
    tally = {"a": 0, "b": 0}
    for _ in range(200):
        ctx = _ctx(
            ["a", "b"], traits=traits, social_context={"peer_actions": {"b": 98, "a": 2}}
        )
        tally[model.decide(ctx, rng).action] += 1
    assert tally["b"] > tally["a"] * 2


def test_composite_model_weighted_vote():
    always_a = UtilityModel(lambda c, ctx: 1.0 if c.action == "a" else 0.0)
    always_b = UtilityModel(lambda c, ctx: 1.0 if c.action == "b" else 0.0)
    model = CompositeModel([(always_a, 1.0), (always_b, 3.0)])
    assert model.decide(_ctx(["a", "b"]), random.Random(0)).action == "b"
    assert CompositeModel([]).decide(_ctx(["a"]), random.Random(0)) is None


# ------------------------------------------------------------- influence ----
def test_degroot_weighted_average():
    model = DeGrootModel(self_weight=0.5)
    out = model.compute_influence(0.0, [1.0, -1.0], [3.0, 1.0], random.Random(0))
    assert out == pytest.approx(0.5 * 0.0 + 0.5 * 0.5)  # neighbor mean = 0.5
    assert model.compute_influence(0.3, [], [], random.Random(0)) == 0.3


def test_bounded_confidence_ignores_distant_opinions():
    model = BoundedConfidenceModel(epsilon=0.2, self_weight=0.0)
    out = model.compute_influence(0.0, [0.1, 0.9], [1.0, 100.0], random.Random(0))
    assert out == pytest.approx(0.1)  # 0.9 is outside epsilon despite huge weight
    assert model.compute_influence(0.0, [0.9], [1.0], random.Random(0)) == 0.0


def test_voter_model_adopts_a_neighbor_opinion():
    model = VoterModel()
    rng = random.Random(5)
    outs = {model.compute_influence(0.0, [0.7, -0.7], [1.0, 1.0], rng) for _ in range(30)}
    assert outs <= {0.7, -0.7} and len(outs) == 2


# ------------------------------------------------------------ social graph --
def test_graph_edges_and_reverse_index():
    g = SocialGraph()
    g.add_edge("a", "b", weight=0.9, trust=0.8)
    g.add_edge("c", "b", weight=0.2)
    g.add_bidirectional_edge("a", "c")
    assert g.neighbors("a") == ["b", "c"]
    assert sorted(g.influencers("b")) == ["a", "c"]
    assert g.influence_weights("b") == {"a": 0.9, "c": 0.2}
    assert g.get_edge("a", "b").trust == 0.8
    assert g.get_edge("b", "z") is None
    g.record_interaction("a", "b")
    assert g.get_edge("a", "b").interaction_count == 1
    g.remove_edge("a", "b")
    assert g.influencers("b") == ["c"]


def test_graph_generators():
    names = [f"n{i}" for i in range(10)]
    complete = SocialGraph.complete(names)
    assert complete.edge_count == 10 * 9  # directed both ways
    er = SocialGraph.random_erdos_renyi(names, p=0.3, rng=random.Random(4))
    er2 = SocialGraph.random_erdos_renyi(names, p=0.3, rng=random.Random(4))
    assert er.edge_count == er2.edge_count > 0  # seeded determinism
    sw = SocialGraph.small_world(names, k=4, p_rewire=0.2, rng=random.Random(4))
    assert sw.nodes == set(names)
    # Ring lattice with k=4 creates 4n directed edges; rewiring preserves count
    assert sw.edge_count == 4 * 10
    tiny = SocialGraph.small_world(["a", "b"], k=4)
    assert tiny.edge_count == 2  # falls back to complete


# ----------------------------------------------------------------- agent ----
def _stimulus(agent, t, choices, **meta):
    return Event(
        time=Instant.Epoch + t,
        event_type="Stimulus",
        target=agent,
        context={"metadata": {"choices": choices, **meta}},
    )


def test_agent_decision_pipeline_runs_action_handler():
    acted = []
    agent = Agent(
        "a",
        decision_model=UtilityModel(lambda c, ctx: 1.0 if c.action == "buy" else 0.0),
        seed=1,
    )
    agent.on_action("buy", lambda ag, choice, ev: acted.append(choice.action) or None)
    sim = Simulation(entities=[agent])
    sim.schedule(_stimulus(agent, 0.0, ["buy", "wait"], valence=0.5))
    sim.run()
    assert acted == ["buy"]
    snap = agent.stats
    assert snap.events_received == 1 and snap.decisions_made == 1
    assert snap.actions_by_type == {"buy": 1}
    assert agent.state.mood == pytest.approx(0.55)  # +0.1 * valence
    assert agent.state.recent_memories(1)[0].event_type == "Stimulus"


def test_agent_action_delay_defers_handler():
    when = []
    agent = Agent(
        "a",
        decision_model=UtilityModel(lambda c, ctx: 1.0),
        action_delay=2.0,
        seed=1,
    )
    agent.on_action("go", lambda ag, choice, ev: when.append(ag.now.to_seconds()) or None)
    sim = Simulation(entities=[agent])
    sim.schedule(_stimulus(agent, 1.0, ["go"]))
    sim.run()
    assert when == [3.0]


def test_agent_choices_coerced_from_str_and_dict():
    picked = []
    agent = Agent("a", decision_model=UtilityModel(lambda c, ctx: c.context.get("u", 0.5)))
    agent.on_action("x", lambda ag, choice, ev: picked.append(choice) or None)
    sim = Simulation(entities=[agent])
    sim.schedule(_stimulus(agent, 0.0, ["y", {"action": "x", "context": {"u": 2.0}}]))
    sim.run()
    assert picked[0].action == "x" and picked[0].context == {"u": 2.0}


def test_agent_heartbeat_reschedules_as_daemon():
    agent = Agent("a", heartbeat_interval=1.0)
    sim = Simulation(entities=[agent], end_time=Instant.Epoch + 5.5)
    first = agent.schedule_first_heartbeat(Instant.Epoch)
    assert first is not None and first.daemon
    assert agent.schedule_first_heartbeat(Instant.Epoch) is None  # armed once
    sim.schedule(first)
    # A primary event holds the sim open; daemon heartbeats alone would not
    sim.schedule(_stimulus(agent, 5.2, []))
    sim.run()
    # Heartbeats at t=1..5 plus the stimulus
    assert agent.stats.events_received == 6


def test_agent_social_message_updates_beliefs_and_knowledge():
    agent = Agent("a", traits=PersonalityTraits.big_five(agreeableness=1.0))
    agent.state.beliefs["tea"] = 0.0
    sim = Simulation(entities=[agent])
    sim.schedule(
        Event(
            time=Instant.Epoch,
            event_type="SocialMessage",
            target=agent,
            context={
                "metadata": {
                    "topic": "tea",
                    "opinion": 1.0,
                    "credibility": 0.5,
                    "knowledge": ["oolong"],
                }
            },
        )
    )
    sim.run()
    # belief moves susceptibility * (opinion - held) = 1.0*0.5*1.0
    assert agent.state.beliefs["tea"] == pytest.approx(0.5)
    assert "oolong" in agent.state.knowledge
    assert agent.stats.social_messages_received == 1


def test_agent_state_decays_between_events():
    agent = Agent("a", state=AgentState(energy=1.0))
    sim = Simulation(entities=[agent])
    sim.schedule(_stimulus(agent, 0.0, []))
    sim.schedule(_stimulus(agent, 10.0, []))
    sim.run()
    assert agent.state.energy == pytest.approx(0.95)  # 10s * 0.005/s


# ----------------------------------------------------------- environment ----
def _buy_model():
    return UtilityModel(lambda c, ctx: 1.0 if c.action == "buy" else 0.0)


def test_environment_broadcast_reaches_all_agents():
    agents = [Agent(f"a{i}", decision_model=_buy_model(), seed=i) for i in range(3)]
    env = Environment("env", agents=agents, shared_state={"price": 10})
    seen_env = []
    for a in agents:
        a.on_action("buy", lambda ag, ch, ev: seen_env.append(ev.context["metadata"]["environment"]) or None)
    sim = Simulation(entities=[env, *agents])
    sim.schedule(broadcast_stimulus(0.0, env, "Sale", choices=["buy", "wait"]))
    sim.run()
    assert len(seen_env) == 3
    assert all(m == {"price": 10} for m in seen_env)  # shared state enrichment
    assert env.stats.broadcasts_sent == 1


def test_environment_targeted_only_hits_named_agents():
    agents = [Agent(f"a{i}", decision_model=_buy_model(), seed=i) for i in range(3)]
    env = Environment("env", agents=agents)
    sim = Simulation(entities=[env, *agents])
    sim.schedule(targeted_stimulus(0.0, env, ["a1", "missing"], "Ping", choices=["buy"]))
    sim.run()
    received = [a.stats.events_received for a in agents]
    assert received == [0, 1, 0]
    assert env.stats.targeted_sends == 1


def test_environment_influence_round_converges_opinions():
    # Fully agreeable so social messages apply at full credibility-scaled step
    friendly = PersonalityTraits.big_five(agreeableness=1.0)
    agents = [Agent(f"a{i}", traits=friendly, seed=i) for i in range(2)]
    agents[0].state.beliefs["topic"] = 1.0
    agents[1].state.beliefs["topic"] = -1.0
    graph = SocialGraph.complete(["a0", "a1"], weight=1.0, trust=1.0)
    env = Environment(
        "env", agents=agents, social_graph=graph, influence_model=DeGrootModel(0.5)
    )
    sim = Simulation(entities=[env, *agents])
    sim.schedule(influence_propagation(0.0, env, "topic"))
    sim.run()
    # DeGroot pulls each toward the other; SocialMessage applies the damped move
    assert abs(agents[0].state.beliefs["topic"]) < 1.0
    assert abs(agents[1].state.beliefs["topic"]) < 1.0
    assert env.stats.influence_rounds == 1


def test_environment_state_change_event():
    env = Environment("env")
    sim = Simulation(entities=[env])
    sim.schedule(
        Event(
            time=Instant.Epoch,
            event_type="StateChange",
            target=env,
            context={"metadata": {"key": "tax", "value": 0.2}},
        )
    )
    sim.run()
    assert env.shared_state == {"tax": 0.2}
    assert env.stats.state_changes == 1


def test_environment_peer_actions_enrichment():
    leader = Agent("leader", decision_model=_buy_model(), seed=0)
    follower = Agent("follower", decision_model=_buy_model(), seed=1)
    graph = SocialGraph()
    graph.add_edge("leader", "follower")  # leader influences follower
    env = Environment("env", agents=[leader, follower], social_graph=graph)
    contexts = []
    follower.on_action(
        "buy", lambda ag, ch, ev: contexts.append(ev.context["metadata"]["social_context"]) or None
    )
    leader.on_action("buy", lambda ag, ch, ev: None)
    sim = Simulation(entities=[env, leader, follower])
    sim.schedule(targeted_stimulus(0.0, env, ["leader"], "Sale", choices=["buy"]))
    sim.schedule(targeted_stimulus(1.0, env, ["follower"], "Sale", choices=["buy"]))
    sim.run()
    assert contexts == [{"peer_actions": {"buy": 1}}]  # leader's prior action visible


# ------------------------------------------------------------- population ---
def test_population_uniform_builds_agents_and_graph():
    pop = Population.uniform(12, decision_model=_buy_model(), seed=9)
    assert pop.size == 12
    assert pop.social_graph.nodes == {a.name for a in pop.agents}
    assert pop.agents[0].name == "agent_0"
    # Deterministic under the same seed
    again = Population.uniform(12, seed=9)
    assert [a.traits.dimensions for a in again.agents] == [
        a.traits.dimensions for a in Population.uniform(12, seed=9).agents
    ]


def test_population_from_segments_distributes_remainder():
    segs = [
        DemographicSegment("early", 0.3, decision_model_factory=_buy_model),
        DemographicSegment("late", 0.6),
    ]
    pop = Population.from_segments(10, segs, seed=2, graph_type="complete")
    assert pop.size == 10  # 3 + 6 + remainder 1 -> largest segment
    with_model = [a for a in pop.agents if a.decision_model is not None]
    assert len(with_model) == 3


def test_population_stats_aggregates():
    pop = Population.uniform(2, decision_model=_buy_model(), seed=0, graph_type="complete")
    env = Environment("env", agents=pop.agents, social_graph=pop.social_graph)
    for a in pop.agents:
        a.on_action("buy", lambda ag, ch, ev: None)
    sim = Simulation(entities=[env, *pop.agents])
    sim.schedule(broadcast_stimulus(0.0, env, "Sale", choices=["buy"]))
    sim.run()
    stats = pop.stats
    assert stats.size == 2
    assert stats.total_events == 2
    assert stats.total_actions == {"buy": 2}


# ---------------------------------------------------------------- stimulus --
def test_stimulus_factories_build_expected_metadata():
    env = Environment("env")
    drop = price_change(1.0, env, "widget", old_price=10.0, new_price=8.0)
    meta = drop.context["metadata"]
    assert drop.event_type == "BroadcastStimulus"
    assert meta["valence"] == 0.3 and meta["new_price"] == 8.0
    assert {c.action for c in meta["choices"]} == {"buy", "wait", "switch"}

    rise = price_change(1.0, env, "widget", old_price=8.0, new_price=10.0)
    assert rise.context["metadata"]["valence"] == -0.3

    pol = policy_announcement(2.0, env, "p1", "desc", valence=-0.1)
    assert {c.action for c in pol.context["metadata"]["choices"]} == {
        "accept",
        "protest",
        "ignore",
    }

    inf = influence_propagation(3.0, env, "topic")
    assert inf.event_type == "InfluencePropagation"
    assert inf.time.to_seconds() == 3.0
