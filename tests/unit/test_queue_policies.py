"""Unit tests: CoDel, RED, fair queues, deadline queue, adaptive LIFO."""

import pytest

from happysim_tpu import ConstantLatency, Event, Instant, Server, Simulation, Sink
from happysim_tpu.components.queue_policies import (
    AdaptiveLIFO,
    CoDelQueue,
    DeadlineQueue,
    FairQueue,
    REDQueue,
    WeightedFairQueue,
)


def t(seconds: float) -> Instant:
    return Instant.from_seconds(seconds)


class _FakeClock:
    def __init__(self):
        self.now = Instant.Epoch

    def __call__(self):
        return self.now

    def set(self, seconds):
        self.now = t(seconds)


class TestCoDel:
    def test_no_drops_when_fast(self):
        clock = _FakeClock()
        q = CoDelQueue(target_delay=0.1, interval=0.5, clock_func=clock)
        for i in range(5):
            q.push(i)
        assert [q.pop() for _ in range(5)] == [0, 1, 2, 3, 4]
        assert q.stats.dropped == 0

    def test_drops_under_sustained_delay(self):
        clock = _FakeClock()
        q = CoDelQueue(target_delay=0.01, interval=0.1, clock_func=clock)
        for i in range(50):
            q.push(i)
        popped = []
        # Pop slowly: sojourn grows far beyond target for over an interval.
        for step in range(50):
            clock.set(0.5 + step * 0.05)
            item = q.pop()
            if item is not None:
                popped.append(item)
        assert q.stats.dropped > 0
        assert q.stats.drop_mode_entries >= 1
        assert len(popped) + q.stats.dropped == 50

    def test_integrated_with_server(self):
        sink = Sink()
        server = Server(
            "s",
            concurrency=1,
            service_time=ConstantLatency(0.2),
            queue_policy=CoDelQueue(target_delay=0.05, interval=0.2),
            downstream=sink,
        )
        sim = Simulation(entities=[server, sink], duration=60.0)
        sim.schedule([Event(t(i * 0.05), "req", target=server) for i in range(100)])
        sim.run()
        # Offered 20/s vs capacity 5/s: CoDel must shed load.
        assert server.queue.policy.stats.dropped > 0
        assert sink.events_received + server.queue.policy.stats.dropped + server.queue.depth + 1 >= 100


class TestRED:
    def test_no_drops_below_min_threshold(self):
        q = REDQueue(min_threshold=5, max_threshold=15, seed=0)
        for i in range(4):
            assert q.push(i) is True
        assert q.stats.early_drops == 0

    def test_probabilistic_drops_between_thresholds(self):
        q = REDQueue(min_threshold=2, max_threshold=10, max_p=1.0, weight=1.0, seed=42)
        accepted = sum(1 for i in range(50) if q.push(i))
        assert 0 < accepted < 50
        assert q.stats.early_drops + q.stats.forced_drops == 50 - accepted

    def test_forced_drops_above_max(self):
        q = REDQueue(min_threshold=1, max_threshold=3, weight=1.0, seed=0)
        for i in range(20):
            q.push(i)
        assert q.stats.forced_drops > 0


class TestFairQueue:
    def _event(self, flow, seconds=0.0):
        return Event(
            t(seconds), "req", target=_SINK, context={"metadata": {"flow": flow}}
        )

    def test_round_robin_across_flows(self):
        q = FairQueue()
        for i in range(3):
            q.push(self._event("a", i * 0.01))
        q.push(self._event("b"))
        order = [q.pop().context["metadata"]["flow"] for _ in range(4)]
        # b must not wait behind all three a's.
        assert order.index("b") <= 1

    def test_single_flow_fifo(self):
        q = FairQueue()
        events = [self._event("a", i * 0.01) for i in range(3)]
        for e in events:
            q.push(e)
        assert [q.pop() for _ in range(3)] == events

    def test_weighted_fair_queue_proportional(self):
        q = WeightedFairQueue(weights={"heavy": 3.0, "light": 1.0})
        for i in range(12):
            q.push(self._event("heavy", i * 0.001))
        for i in range(12):
            q.push(self._event("light", i * 0.001))
        first_eight = [q.pop().context["metadata"]["flow"] for _ in range(8)]
        # Weight 3:1 → roughly 6 heavy / 2 light among the first 8.
        assert first_eight.count("heavy") >= 5


class TestDeadlineQueue:
    def _event(self, deadline, label):
        e = Event(t(0), "req", target=_SINK, context={"metadata": {"deadline": deadline}})
        e.context["metadata"]["label"] = label
        return e

    def test_edf_order(self):
        clock = _FakeClock()
        q = DeadlineQueue(clock_func=clock)
        q.push(self._event(3.0, "late"))
        q.push(self._event(1.0, "urgent"))
        q.push(self._event(2.0, "middle"))
        labels = [q.pop().context["metadata"]["label"] for _ in range(3)]
        assert labels == ["urgent", "middle", "late"]

    def test_expired_dropped_at_pop(self):
        clock = _FakeClock()
        q = DeadlineQueue(clock_func=clock)
        q.push(self._event(0.5, "expired"))
        q.push(self._event(5.0, "ok"))
        clock.set(1.0)
        assert q.pop().context["metadata"]["label"] == "ok"
        assert q.stats.expired == 1

    def test_purge(self):
        clock = _FakeClock()
        q = DeadlineQueue(clock_func=clock)
        for i in range(5):
            q.push(self._event(0.1 * (i + 1), str(i)))
        clock.set(0.35)
        assert q.count_expired() == 3
        assert q.purge_expired() == 3
        assert len(q) == 2


class TestAdaptiveLIFO:
    def test_fifo_normally(self):
        q = AdaptiveLIFO(congestion_threshold=100)
        for i in range(5):
            q.push(i)
        assert [q.pop() for _ in range(5)] == [0, 1, 2, 3, 4]
        assert q.mode == "fifo"

    def test_switches_to_lifo_under_congestion(self):
        q = AdaptiveLIFO(congestion_threshold=5, recovery_threshold=2)
        for i in range(6):
            q.push(i)
        assert q.mode == "lifo"
        assert q.pop() == 5  # newest first under congestion
        assert q.pop() == 4

    def test_recovers_to_fifo(self):
        q = AdaptiveLIFO(congestion_threshold=4, recovery_threshold=1)
        for i in range(5):
            q.push(i)
        while len(q) > 1:
            q.pop()
        assert q.mode == "fifo"
        assert q.mode_switches == 2


class _Sink:
    name = "sink"


_SINK = _Sink()


class TestFairRequeue:
    def _event(self, flow):
        return Event(
            t(0), "req", target=_SINK, context={"metadata": {"flow": flow}}
        )

    def test_requeue_restores_front_and_rotation(self):
        """A popped-but-undeliverable item must go back to the FRONT of its
        lane with its flow next in rotation — otherwise the driver's
        spurious poll/requeue cycles starve sparse flows (regression:
        shuffle_fair_queuing example showed inverted isolation)."""
        q = FairQueue()
        for i in range(3):
            q.push(self._event("flood"))
        q.push(self._event("drip"))
        first = q.pop()  # flood head; rotation now favors drip
        q.requeue(first)
        assert len(q) == 4
        # The requeued item is served next (front of lane, flow first).
        assert q.pop() is first
        # Rotation was restored too: drip still gets the following turn.
        assert q.pop().context["metadata"]["flow"] == "drip"

    def test_wfq_requeue_is_exact_undo_even_among_ties(self):
        q = WeightedFairQueue()
        a, b = self._event("a"), self._event("b")
        q.push(a)
        q.push(b)  # same finish time as a; a holds the earlier tiebreak
        assert q.pop() is a
        q.requeue(a)  # restores a's ORIGINAL heap entry
        assert q.pop() is a  # still ahead of its equal-finish peer
        assert q.pop() is b


    def test_requeue_rejection_accounts_as_drop(self):
        """A re-screening policy (RED under congestion) may reject the
        requeue; the unified path must record a drop and unwind hooks,
        keeping enqueued == dequeued + depth + dropped."""
        from happysim_tpu.components.queue import Queue

        class RejectingPolicy(FairQueue):
            def requeue(self, item):
                return False  # simulate RED rejecting the re-admission

        queue = Queue("q", policy=RejectingPolicy())
        from happysim_tpu.core.clock import Clock

        queue.set_clock(Clock())
        victim = Event(t(0), "req", target=_SINK)
        fates = []
        victim.add_completion_hook(
            lambda time, dropped_by=None: fates.append(dropped_by) or []
        )
        queue.policy.push(victim)
        queue.enqueued += 1
        popped = queue.policy.pop()
        queue.dequeued += 1
        queue.requeue(popped)
        assert queue.dropped == 1
        assert queue.dequeued == 0  # the pop was undone
        assert queue.enqueued == queue.dequeued + queue.depth + queue.dropped
        assert fates, "the victim's hooks were unwound as a drop"

    def test_wfq_multi_requeue_preserves_pop_order(self):
        """Two same-instant pops requeued in order must pop in that same
        order again (FIFO within flow survives concurrency>=2 races)."""
        q = WeightedFairQueue()
        d, e = self._event("f"), self._event("f")
        q.push(d)
        q.push(e)
        assert q.pop() is d
        assert q.pop() is e
        q.requeue(d)
        q.requeue(e)
        assert q.pop() is d
        assert q.pop() is e

    def test_wfq_pop_of_requeued_item_does_not_rewind_virtual_time(self):
        """Popping a snapshot-requeued item must not rewind _virtual_now:
        a rewind hands artificially early finish tags to flows pushed
        afterward, letting them jump earlier arrivals."""
        q = WeightedFairQueue(weights={"fast": 10.0, "slow": 1.0})
        a, b = self._event("fast"), self._event("slow")
        q.push(a)   # finish 0.1
        q.push(b)   # finish 1.0
        assert q.pop() is a
        assert q.pop() is b     # virtual_now -> 1.0
        q.requeue(a)            # re-enters at its snapshot 0.1
        c = self._event("c")    # pushed BEFORE the requeued pop drains
        q.push(c)               # finish 2.0
        assert q.pop() is a     # must NOT rewind virtual_now to 0.1
        d = self._event("d")    # arrives after c
        q.push(d)               # with rewind this would get finish 1.1 < c
        assert q.pop() is c, "later arrival jumped an earlier one"
        assert q.pop() is d

    def test_wfq_requeue_uses_snapshotted_finish_after_later_pops(self):
        """A multi-slot driver may pop a SECOND item before requeueing the
        first. The requeue must restore the first item's own finish tag,
        not the later _virtual_now — otherwise it loses its place."""
        q = WeightedFairQueue(weights={"fast": 10.0, "slow": 1.0})
        first = self._event("fast")   # finish = 0.1
        second = self._event("slow")  # finish = 1.0
        q.push(first)
        q.push(second)
        assert q.pop() is first   # virtual_now -> 0.1
        assert q.pop() is second  # virtual_now -> 1.0
        q.requeue(first)          # must re-enter at 0.1, not 1.0
        q.requeue(second)
        assert q.pop() is first, "first lost its place to the later pop"
        assert q.pop() is second


class TestPriorityRequeue:
    def _event(self, priority):
        return Event(t(0), "req", target=_SINK, context={"priority": priority})

    def test_requeue_restores_position_among_equal_priorities(self):
        """PriorityQueue is FIFO within equal priorities; a driver requeue
        must restore the popped item AHEAD of every equal-priority peer,
        including ones pushed after the pop (regression: requeue fell back
        to push(), sending the item to the back of its priority class)."""
        from happysim_tpu.components.queue_policy import PriorityQueue

        q = PriorityQueue()
        a, b = self._event(1), self._event(1)
        q.push(a)
        q.push(b)
        popped = q.pop()
        assert popped is a
        late = self._event(1)
        q.push(late)  # arrives between the pop and the requeue
        q.requeue(a)
        assert q.pop() is a, "requeued item lost FIFO position"
        assert q.pop() is b
        assert q.pop() is late

    def test_requeue_respects_priority_classes(self):
        """A requeued low-priority item must not jump a higher class."""
        from happysim_tpu.components.queue_policy import PriorityQueue

        q = PriorityQueue()
        low = self._event(5)
        q.push(low)
        assert q.pop() is low
        urgent = self._event(0)
        q.push(urgent)
        q.requeue(low)
        assert q.pop() is urgent
        assert q.pop() is low

    def test_multi_requeue_preserves_pop_order(self):
        from happysim_tpu.components.queue_policy import PriorityQueue

        q = PriorityQueue()
        a, b = self._event(1), self._event(1)
        q.push(a)
        q.push(b)
        assert q.pop() is a
        assert q.pop() is b
        q.requeue(a)
        q.requeue(b)
        assert q.pop() is a
        assert q.pop() is b


class TestRequeueAcrossPolicies:
    """Every shipped policy must treat requeue as an exact pop undo."""

    def _event(self, deadline=None):
        metadata = {} if deadline is None else {"deadline": deadline}
        return Event(t(0), "req", target=_SINK, context={"metadata": metadata})

    def test_deadline_requeue_restores_edf_position(self):
        q = DeadlineQueue()
        a, b = self._event(5.0), self._event(5.0)
        q.push(a)
        q.push(b)
        assert q.pop() is a
        late = self._event(5.0)
        q.push(late)
        q.requeue(a)
        assert q.pop() is a, "requeued item lost FIFO-within-deadline spot"
        assert q.pop() is b
        assert q.pop() is late
        # Stats invariant: pushed == popped + depth + expired.
        assert q.pushed == q.popped + len(q) + q.expired

    def test_codel_requeue_keeps_sojourn_baseline(self):
        clock = _FakeClock()
        q = CoDelQueue(target_delay=0.1, interval=0.5, clock_func=clock)
        q.push("a")
        q.push("b")
        popped = q.pop()
        assert popped == "a"
        clock.set(10.0)  # much later; a fresh push timestamp would hide the delay
        q.requeue(popped)
        assert q.peek() == "a", "requeue lost front position"
        # The original t=0 enqueue time survived: CoDel sees a 10s sojourn
        # and enters drop mode against the stale front item.
        assert q.pop() in ("a", "b")
        assert q.stats.dropped + q.stats.popped >= 1
        assert q.pushed == q.popped + len(q) + q.dropped

    def test_red_requeue_skips_drop_screening(self):
        q = REDQueue(min_threshold=1, max_threshold=3, max_p=1.0, seed=7)
        q.push("a")
        popped = q.pop()
        # Fill to the forced-drop region: a requeue must still be accepted.
        q.push("b")
        q.push("c")
        q.push("d")
        assert q.requeue(popped) is True
        assert q.peek() == "a"
        assert q.pushed == q.popped + len(q)

    def test_adaptive_lifo_requeue_restores_hysteresis_state(self):
        """A spurious pop+requeue inside the hysteresis band must not flip
        the serving discipline: the pre-pop mode and switch count come back
        when nothing else touched the queue in between."""
        q = AdaptiveLIFO(congestion_threshold=4, recovery_threshold=2)
        for x in ("a", "b", "c", "d"):
            q.push(x)
        assert q.mode == "lifo"
        q.pop()  # depth 3, still lifo (hysteresis)
        q.pop()  # depth 2 <= recovery -> flips to fifo
        switches_before_race = q.mode_switches
        popped = q.pop()  # depth 1, fifo (head = "a")
        assert q.mode == "fifo"
        q.requeue(popped)
        assert q.mode == "fifo"
        assert q.mode_switches == switches_before_race, (
            "undo must not inflate mode_switches"
        )
        # Now the race that matters: congested pop dips into recovery, the
        # delivery fails, requeue must restore LIFO mode.
        q2 = AdaptiveLIFO(congestion_threshold=3, recovery_threshold=2)
        for x in ("a", "b", "c"):
            q2.push(x)
        assert q2.mode == "lifo"
        victim = q2.pop()  # depth 2 -> flips to fifo
        q2.requeue(victim)  # exact undo: back to lifo, switch count rolled back
        assert q2.mode == "lifo"
        assert q2.mode_switches == 1

    def test_adaptive_lifo_stale_snapshot_does_not_roll_back(self):
        """The exact-undo branch may only fire when NOTHING touched the
        queue since that pop: intervening ops that happen to leave the mode
        state equal must not resurrect a stale pre-pop mode."""
        q = AdaptiveLIFO(congestion_threshold=4, recovery_threshold=2)
        for x in ("a", "b", "c", "d"):
            q.push(x)  # mode -> lifo, switches = 1
        d = q.pop()   # lifo pop, no flip
        c = q.pop()   # flips to fifo (depth 2 <= recovery), switches = 2
        a = q.pop()   # fifo pop, no flip — state again (fifo, 2)
        assert (q.mode, q.mode_switches) == ("fifo", 2)
        q.requeue(c)  # c's snapshot is STALE (a's pop intervened)
        assert q.mode == "fifo", "stale snapshot must not flip mode back"
        assert q.mode_switches == 2
        # c was a lifo-mode tail pop, so it's restored to the tail; the
        # queue serves fifo from the head.
        assert q.pop() == "b"
        assert q.pop() == "c"
        del d, a

    def test_hard_capacity_bound_rejects_requeue_after_refill(self):
        """capacity=1: pop frees the slot, a same-instant push refills it —
        the requeue must be rejected (drop), not grow past the bound."""
        red = REDQueue(min_threshold=5, max_threshold=10, capacity=1)
        red.push("a")
        popped = red.pop()
        red.push("b")  # refills the only slot
        assert red.requeue(popped) is False
        assert len(red) == 1

        clock = _FakeClock()
        codel = CoDelQueue(
            target_delay=0.1, interval=0.5, capacity=1, clock_func=clock
        )
        codel.push("a")
        popped = codel.pop()
        codel.push("b")
        assert codel.requeue(popped) is False
        assert len(codel) == 1
        # Reject converts the pop into a drop — one final fate per item.
        assert codel.pushed == codel.popped + len(codel) + codel.dropped

        alifo = AdaptiveLIFO(congestion_threshold=10, capacity=1)
        alifo.push("a")
        popped = alifo.pop()
        alifo.push("b")
        assert alifo.requeue(popped) is False
        assert len(alifo) == 1

    def test_same_instant_double_requeue_preserves_pop_order(self):
        """Undoing "pop A, pop B" arrives as requeue(A), requeue(B); naive
        front-insertion would serve B before A. Every deque policy must
        restore pop order."""
        from happysim_tpu.components.queue_policy import FIFOQueue, LIFOQueue

        fifo = FIFOQueue()
        for x in ("a", "b", "c"):
            fifo.push(x)
        a, b = fifo.pop(), fifo.pop()
        fifo.requeue(a)
        fifo.requeue(b)
        assert [fifo.pop() for _ in range(3)] == ["a", "b", "c"]

        lifo = LIFOQueue()
        for x in ("x", "y", "z"):
            lifo.push(x)
        z, y = lifo.pop(), lifo.pop()
        lifo.requeue(z)
        lifo.requeue(y)
        assert [lifo.pop() for _ in range(3)] == ["z", "y", "x"]

        red = REDQueue(min_threshold=50, max_threshold=60)
        for x in ("a", "b", "c"):
            red.push(x)
        a, b = red.pop(), red.pop()
        red.requeue(a)
        red.requeue(b)
        assert [red.pop() for _ in range(3)] == ["a", "b", "c"]
        assert red.pushed == red.popped + len(red)

        clock = _FakeClock()
        codel = CoDelQueue(target_delay=1.0, interval=5.0, clock_func=clock)
        for x in ("a", "b", "c"):
            codel.push(x)
        a, b = codel.pop(), codel.pop()
        codel.requeue(a)
        codel.requeue(b)
        assert [codel.pop() for _ in range(3)] == ["a", "b", "c"]

        alifo = AdaptiveLIFO(congestion_threshold=100)
        for x in ("a", "b", "c"):
            alifo.push(x)
        a, b = alifo.pop(), alifo.pop()
        alifo.requeue(a)
        alifo.requeue(b)
        assert [alifo.pop() for _ in range(3)] == ["a", "b", "c"]

        # LIFO-mode tail restores too: pop order z (top) then y.
        alifo2 = AdaptiveLIFO(congestion_threshold=3)
        for x in ("x", "y", "z"):
            alifo2.push(x)
        assert alifo2.mode == "lifo"
        z, y = alifo2.pop(), alifo2.pop()
        assert (z, y) == ("z", "y")
        alifo2.requeue(z)
        alifo2.requeue(y)
        assert alifo2.pop() == "z"
        assert alifo2.pop() == "y"

    def test_fair_queue_multi_requeue_restores_lane_and_rotation(self):
        """Same-instant requeues across flows must restore pop order within
        each lane AND the original flow rotation order."""
        q = FairQueue()

        def ev(flow):
            return Event(
                t(0), "req", target=_SINK, context={"metadata": {"flow": flow}}
            )

        a1, a2, b1 = ev("fa"), ev("fa"), ev("fb")
        q.push(a1)
        q.push(a2)
        q.push(b1)
        # Round-robin pops: a1 (fa), b1 (fb), then fa again.
        p1 = q.pop()
        p2 = q.pop()
        assert (p1, p2) == (a1, b1)
        q.requeue(p1)
        q.requeue(p2)
        # Pop order restored: fa first (a1), then fb (b1), then a2.
        assert q.pop() is a1
        assert q.pop() is b1
        assert q.pop() is a2

        # Same-flow double requeue keeps lane order.
        q2 = FairQueue()
        c1, c2 = ev("fc"), ev("fc")
        q2.push(c1)
        q2.push(c2)
        x1 = q2.pop()
        # fc lane rotated out and back; pop again gets c2.
        x2 = q2.pop()
        assert (x1, x2) == (c1, c2)
        q2.requeue(x1)
        q2.requeue(x2)
        assert q2.pop() is c1
        assert q2.pop() is c2

    def test_adaptive_lifo_requeue_restores_popped_end(self):
        q = AdaptiveLIFO(congestion_threshold=100)
        for x in ("a", "b", "c"):
            q.push(x)
        popped = q.pop()  # FIFO mode: from the head
        assert popped == "a"
        q.requeue(popped)
        assert q.pop() == "a", "FIFO-mode requeue must restore the head"
        # LIFO mode: pops come from the tail and must requeue to the tail.
        q2 = AdaptiveLIFO(congestion_threshold=2)
        q2.push("x")
        q2.push("y")
        assert q2.mode == "lifo"
        popped2 = q2.pop()
        assert popped2 == "y"
        q2.requeue(popped2)
        assert q2.pop() == "y"
