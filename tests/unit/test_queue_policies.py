"""Unit tests: CoDel, RED, fair queues, deadline queue, adaptive LIFO."""

import pytest

from happysim_tpu import ConstantLatency, Event, Instant, Server, Simulation, Sink
from happysim_tpu.components.queue_policies import (
    AdaptiveLIFO,
    CoDelQueue,
    DeadlineQueue,
    FairQueue,
    REDQueue,
    WeightedFairQueue,
)


def t(seconds: float) -> Instant:
    return Instant.from_seconds(seconds)


class _FakeClock:
    def __init__(self):
        self.now = Instant.Epoch

    def __call__(self):
        return self.now

    def set(self, seconds):
        self.now = t(seconds)


class TestCoDel:
    def test_no_drops_when_fast(self):
        clock = _FakeClock()
        q = CoDelQueue(target_delay=0.1, interval=0.5, clock_func=clock)
        for i in range(5):
            q.push(i)
        assert [q.pop() for _ in range(5)] == [0, 1, 2, 3, 4]
        assert q.stats.dropped == 0

    def test_drops_under_sustained_delay(self):
        clock = _FakeClock()
        q = CoDelQueue(target_delay=0.01, interval=0.1, clock_func=clock)
        for i in range(50):
            q.push(i)
        popped = []
        # Pop slowly: sojourn grows far beyond target for over an interval.
        for step in range(50):
            clock.set(0.5 + step * 0.05)
            item = q.pop()
            if item is not None:
                popped.append(item)
        assert q.stats.dropped > 0
        assert q.stats.drop_mode_entries >= 1
        assert len(popped) + q.stats.dropped == 50

    def test_integrated_with_server(self):
        sink = Sink()
        server = Server(
            "s",
            concurrency=1,
            service_time=ConstantLatency(0.2),
            queue_policy=CoDelQueue(target_delay=0.05, interval=0.2),
            downstream=sink,
        )
        sim = Simulation(entities=[server, sink], duration=60.0)
        sim.schedule([Event(t(i * 0.05), "req", target=server) for i in range(100)])
        sim.run()
        # Offered 20/s vs capacity 5/s: CoDel must shed load.
        assert server.queue.policy.stats.dropped > 0
        assert sink.events_received + server.queue.policy.stats.dropped + server.queue.depth + 1 >= 100


class TestRED:
    def test_no_drops_below_min_threshold(self):
        q = REDQueue(min_threshold=5, max_threshold=15, seed=0)
        for i in range(4):
            assert q.push(i) is True
        assert q.stats.early_drops == 0

    def test_probabilistic_drops_between_thresholds(self):
        q = REDQueue(min_threshold=2, max_threshold=10, max_p=1.0, weight=1.0, seed=42)
        accepted = sum(1 for i in range(50) if q.push(i))
        assert 0 < accepted < 50
        assert q.stats.early_drops + q.stats.forced_drops == 50 - accepted

    def test_forced_drops_above_max(self):
        q = REDQueue(min_threshold=1, max_threshold=3, weight=1.0, seed=0)
        for i in range(20):
            q.push(i)
        assert q.stats.forced_drops > 0


class TestFairQueue:
    def _event(self, flow, seconds=0.0):
        return Event(
            t(seconds), "req", target=_SINK, context={"metadata": {"flow": flow}}
        )

    def test_round_robin_across_flows(self):
        q = FairQueue()
        for i in range(3):
            q.push(self._event("a", i * 0.01))
        q.push(self._event("b"))
        order = [q.pop().context["metadata"]["flow"] for _ in range(4)]
        # b must not wait behind all three a's.
        assert order.index("b") <= 1

    def test_single_flow_fifo(self):
        q = FairQueue()
        events = [self._event("a", i * 0.01) for i in range(3)]
        for e in events:
            q.push(e)
        assert [q.pop() for _ in range(3)] == events

    def test_weighted_fair_queue_proportional(self):
        q = WeightedFairQueue(weights={"heavy": 3.0, "light": 1.0})
        for i in range(12):
            q.push(self._event("heavy", i * 0.001))
        for i in range(12):
            q.push(self._event("light", i * 0.001))
        first_eight = [q.pop().context["metadata"]["flow"] for _ in range(8)]
        # Weight 3:1 → roughly 6 heavy / 2 light among the first 8.
        assert first_eight.count("heavy") >= 5


class TestDeadlineQueue:
    def _event(self, deadline, label):
        e = Event(t(0), "req", target=_SINK, context={"metadata": {"deadline": deadline}})
        e.context["metadata"]["label"] = label
        return e

    def test_edf_order(self):
        clock = _FakeClock()
        q = DeadlineQueue(clock_func=clock)
        q.push(self._event(3.0, "late"))
        q.push(self._event(1.0, "urgent"))
        q.push(self._event(2.0, "middle"))
        labels = [q.pop().context["metadata"]["label"] for _ in range(3)]
        assert labels == ["urgent", "middle", "late"]

    def test_expired_dropped_at_pop(self):
        clock = _FakeClock()
        q = DeadlineQueue(clock_func=clock)
        q.push(self._event(0.5, "expired"))
        q.push(self._event(5.0, "ok"))
        clock.set(1.0)
        assert q.pop().context["metadata"]["label"] == "ok"
        assert q.stats.expired == 1

    def test_purge(self):
        clock = _FakeClock()
        q = DeadlineQueue(clock_func=clock)
        for i in range(5):
            q.push(self._event(0.1 * (i + 1), str(i)))
        clock.set(0.35)
        assert q.count_expired() == 3
        assert q.purge_expired() == 3
        assert len(q) == 2


class TestAdaptiveLIFO:
    def test_fifo_normally(self):
        q = AdaptiveLIFO(congestion_threshold=100)
        for i in range(5):
            q.push(i)
        assert [q.pop() for _ in range(5)] == [0, 1, 2, 3, 4]
        assert q.mode == "fifo"

    def test_switches_to_lifo_under_congestion(self):
        q = AdaptiveLIFO(congestion_threshold=5, recovery_threshold=2)
        for i in range(6):
            q.push(i)
        assert q.mode == "lifo"
        assert q.pop() == 5  # newest first under congestion
        assert q.pop() == 4

    def test_recovers_to_fifo(self):
        q = AdaptiveLIFO(congestion_threshold=4, recovery_threshold=1)
        for i in range(5):
            q.push(i)
        while len(q) > 1:
            q.pop()
        assert q.mode == "fifo"
        assert q.mode_switches == 2


class _Sink:
    name = "sink"


_SINK = _Sink()


class TestFairRequeue:
    def _event(self, flow):
        return Event(
            t(0), "req", target=_SINK, context={"metadata": {"flow": flow}}
        )

    def test_requeue_restores_front_and_rotation(self):
        """A popped-but-undeliverable item must go back to the FRONT of its
        lane with its flow next in rotation — otherwise the driver's
        spurious poll/requeue cycles starve sparse flows (regression:
        shuffle_fair_queuing example showed inverted isolation)."""
        q = FairQueue()
        for i in range(3):
            q.push(self._event("flood"))
        q.push(self._event("drip"))
        first = q.pop()  # flood head; rotation now favors drip
        q.requeue(first)
        assert len(q) == 4
        # The requeued item is served next (front of lane, flow first).
        assert q.pop() is first
        # Rotation was restored too: drip still gets the following turn.
        assert q.pop().context["metadata"]["flow"] == "drip"

    def test_wfq_requeue_is_exact_undo_even_among_ties(self):
        q = WeightedFairQueue()
        a, b = self._event("a"), self._event("b")
        q.push(a)
        q.push(b)  # same finish time as a; a holds the earlier tiebreak
        assert q.pop() is a
        q.requeue(a)  # restores a's ORIGINAL heap entry
        assert q.pop() is a  # still ahead of its equal-finish peer
        assert q.pop() is b


    def test_requeue_rejection_accounts_as_drop(self):
        """A re-screening policy (RED under congestion) may reject the
        requeue; the unified path must record a drop and unwind hooks,
        keeping enqueued == dequeued + depth + dropped."""
        from happysim_tpu.components.queue import Queue

        class RejectingPolicy(FairQueue):
            def requeue(self, item):
                return False  # simulate RED rejecting the re-admission

        queue = Queue("q", policy=RejectingPolicy())
        from happysim_tpu.core.clock import Clock

        queue.set_clock(Clock())
        victim = Event(t(0), "req", target=_SINK)
        fates = []
        victim.add_completion_hook(
            lambda time, dropped_by=None: fates.append(dropped_by) or []
        )
        queue.policy.push(victim)
        queue.enqueued += 1
        popped = queue.policy.pop()
        queue.dequeued += 1
        queue.requeue(popped)
        assert queue.dropped == 1
        assert queue.dequeued == 0  # the pop was undone
        assert queue.enqueued == queue.dequeued + queue.depth + queue.dropped
        assert fates, "the victim's hooks were unwound as a drop"

    def test_wfq_multi_requeue_preserves_pop_order(self):
        """Two same-instant pops requeued in order must pop in that same
        order again (FIFO within flow survives concurrency>=2 races)."""
        q = WeightedFairQueue()
        d, e = self._event("f"), self._event("f")
        q.push(d)
        q.push(e)
        assert q.pop() is d
        assert q.pop() is e
        q.requeue(d)
        q.requeue(e)
        assert q.pop() is d
        assert q.pop() is e
