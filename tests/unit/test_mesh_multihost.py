"""Multi-host mesh construction and sharding math (SURVEY §5.8).

Runs on the virtual 8-device CPU backend: a 2-host x 4-device layout is
emulated by passing n_hosts explicitly (the real multi-host path differs
only in where the device list comes from — jax.distributed makes
jax.devices() global).
"""

import jax
import numpy as np
import pytest

from happysim_tpu.tpu.mesh import (
    HOST_AXIS,
    REPLICA_AXIS,
    distributed_initialize,
    host_replica_mesh,
    pad_to_multiple,
    replica_mesh,
    replica_sharding,
)


@pytest.fixture(scope="module")
def devices():
    return jax.devices("cpu")[:8]


class TestHostReplicaMesh:
    def test_two_hosts_by_four_devices(self, devices):
        mesh = host_replica_mesh(devices, n_hosts=2)
        assert mesh.axis_names == (HOST_AXIS, REPLICA_AXIS)
        assert mesh.devices.shape == (2, 4)
        assert mesh.size == 8
        # Host-major grouping: each row is one host's contiguous slice.
        assert list(mesh.devices[0]) == list(devices[:4])
        assert list(mesh.devices[1]) == list(devices[4:])

    def test_single_process_emulation_keeps_caller_order(self, devices):
        """All devices on one process: the process_index sort is stable,
        so a custom layout (here: reversed) reshapes exactly as given."""
        mesh = host_replica_mesh(list(reversed(devices)), n_hosts=2)
        assert list(mesh.devices[0]) == list(reversed(devices))[:4]
        assert list(mesh.devices[1]) == list(reversed(devices))[4:]

    def test_uneven_split_rejected(self, devices):
        with pytest.raises(ValueError, match="do not split evenly"):
            host_replica_mesh(devices, n_hosts=3)

    def test_defaults_to_process_count(self, devices):
        # Single-process test runtime: one host row spanning everything.
        mesh = host_replica_mesh(devices)
        assert mesh.devices.shape == (1, 8)

    def test_replica_sharding_spans_both_axes(self, devices):
        mesh = host_replica_mesh(devices, n_hosts=2)
        sharding = replica_sharding(mesh)
        # The leading dim shards over hosts x replicas: 8 distinct shards,
        # host-major — replica block i lives on device grid[i // 4, i % 4].
        arr = jax.device_put(np.arange(16.0), sharding)
        assert len(arr.addressable_shards) == 8
        for i, shard in enumerate(
            sorted(arr.addressable_shards, key=lambda s: s.index[0].start)
        ):
            assert shard.data.shape == (2,)
            assert shard.device == mesh.devices[i // 4, i % 4]

    def test_flat_mesh_sharding_unchanged(self, devices):
        mesh = replica_mesh(devices)
        sharding = replica_sharding(mesh)
        arr = jax.device_put(np.arange(8.0), sharding)
        assert len(arr.addressable_shards) == 8

    def test_pad_to_multiple_uses_total_size(self, devices):
        mesh = host_replica_mesh(devices, n_hosts=2)
        assert pad_to_multiple(13, mesh.size) == 16


class TestDistributedInitialize:
    def test_single_process_noop(self):
        # No cluster environment: stays single-process, returns False,
        # and is safe to call repeatedly.
        assert distributed_initialize() is False
        assert distributed_initialize() is False
        assert jax.process_count() == 1
