"""Depth tests for run summaries, sketch hashing, and shared stats helpers
(ref instrumentation/summary.py:15-48, utils)."""

from happysim_tpu import Instant
from happysim_tpu.instrumentation.summary import (
    EntitySummary,
    QueueStats,
    SimulationSummary,
)
from happysim_tpu.sketching.hashing import hash64, hash_pair, item_bytes
from happysim_tpu.utils.stats import percentile_nearest_rank, stable_seed


class TestSimulationSummary:
    def _summary(self, **kw):
        defaults = dict(
            start_time=Instant.Epoch,
            end_time=Instant.from_seconds(60),
            events_processed=1200,
            wall_clock_seconds=0.4,
        )
        defaults.update(kw)
        return SimulationSummary(**defaults)

    def test_derived_rates(self):
        s = self._summary()
        assert s.simulated_seconds == 60.0
        assert s.events_per_second == 3000.0

    def test_zero_wall_clock_guard(self):
        assert self._summary(wall_clock_seconds=0.0).events_per_second == 0.0

    def test_str_mentions_backend_and_pause(self):
        s = self._summary(completed=False, backend="tpu", replicas=4096)
        text = str(s)
        assert "paused" in text
        assert "backend=tpu" in text
        assert "replicas=4096" in text

    def test_str_warns_on_truncated_replicas(self):
        assert "WARNING" in str(self._summary(truncated_replicas=3))
        assert "WARNING" not in str(self._summary())

    def test_entities_rendered(self):
        s = self._summary(
            entities=[
                EntitySummary("sink", "Sink", events_received=10),
                EntitySummary("ctr", "Counter", count=5, extra={"p99_ms": 12}),
            ]
        )
        text = str(s)
        assert "sink [Sink] received=10" in text
        assert "p99_ms=12" in text

    def test_to_dict_keys(self):
        d = self._summary(entities=[EntitySummary("s", "Sink")]).to_dict()
        assert d["events_processed"] == 1200
        assert d["backend"] == "python"
        assert d["entities"] == [{"name": "s", "kind": "Sink"}]

    def test_queue_stats_defaults(self):
        q = QueueStats()
        assert (q.depth, q.enqueued, q.dequeued, q.dropped) == (0, 0, 0, 0)


class TestEntitySummary:
    def test_optional_fields_omitted(self):
        d = EntitySummary("x", "Thing").to_dict()
        assert "events_received" not in d and "count" not in d

    def test_extra_merged(self):
        d = EntitySummary("x", "Thing", extra={"busy_s": 1.5}).to_dict()
        assert d["busy_s"] == 1.5


class TestHashing:
    def test_deterministic_across_calls(self):
        assert hash64("alpha", seed=3) == hash64("alpha", seed=3)

    def test_seed_gives_independent_streams(self):
        vals = {hash64("alpha", seed=s) for s in range(16)}
        assert len(vals) == 16

    def test_distinct_items_distinct_hashes(self):
        vals = {hash64(f"item{i}") for i in range(1000)}
        assert len(vals) == 1000

    def test_item_bytes_stable_encodings(self):
        assert item_bytes(b"raw") == b"raw"
        assert item_bytes("s") == b"s"
        assert item_bytes(42) == item_bytes(42)
        assert item_bytes((1, "a")) == item_bytes((1, "a"))

    def test_hash_pair_second_hash_odd(self):
        for i in range(50):
            _, h2 = hash_pair(f"k{i}")
            assert h2 % 2 == 1  # coprime with any power-of-two table size

    def test_hash_pair_parts_differ(self):
        h1, h2 = hash_pair("k")
        assert h1 != h2

    def test_kirsch_mitzenmacher_rows_spread(self):
        # h1 + i*h2 mod m should hit many distinct buckets across rows.
        h1, h2 = hash_pair("key", seed=1)
        m = 1 << 16
        rows = {(h1 + i * h2) % m for i in range(8)}
        assert len(rows) == 8


class TestStatsHelpers:
    def test_percentile_empty(self):
        assert percentile_nearest_rank([], 0.5) == 0.0

    def test_percentile_single(self):
        assert percentile_nearest_rank([7.0], 0.99) == 7.0

    def test_percentile_nearest_rank_definition(self):
        values = list(range(1, 11))  # 1..10
        assert percentile_nearest_rank(values, 0.5) == 5
        assert percentile_nearest_rank(values, 0.9) == 9
        assert percentile_nearest_rank(values, 1.0) == 10
        assert percentile_nearest_rank(values, 0.0) == 1

    def test_percentile_unsorted_input(self):
        assert percentile_nearest_rank([3.0, 1.0, 2.0], 0.5) == 2.0

    def test_stable_seed_is_stable_and_distinct(self):
        assert stable_seed("node-1") == stable_seed("node-1")
        assert stable_seed("node-1") != stable_seed("node-2")
