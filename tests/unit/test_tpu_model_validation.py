"""EnsembleModel construction and validation: every rejection rule.

A vectorizable model that compiles wrong wastes minutes of XLA time
before failing obscurely; ``validate()`` exists to fail fast with a
named reason. Each rule gets a directed case — constructor-level and
validate-level. Pure host-side Python: no jax involvement.

Parity target: the builder-validation cases of
``happysimulator/tests/unit/test_simulation_validation.py``.
"""

from __future__ import annotations

import pytest

from happysim_tpu.tpu.model import (
    EnsembleModel,
    FaultSpec,
    mm1_model,
    pipeline_model,
)


def base():
    return EnsembleModel(horizon_s=10.0)


class TestConstructorRules:
    def test_bad_service_kind(self):
        with pytest.raises(ValueError, match="service kind"):
            base().server(service="weibull")

    def test_bad_concurrency(self):
        with pytest.raises(ValueError, match="concurrency"):
            base().server(concurrency=0)

    def test_bad_queue_capacity(self):
        with pytest.raises(ValueError, match="queue_capacity"):
            base().server(queue_capacity=0)

    def test_retries_require_deadline(self):
        with pytest.raises(ValueError, match="deadline"):
            base().server(max_retries=2)

    def test_correlated_fault_with_own_rate_needs_duration(self):
        """correlated=True must not bypass the duration check when the
        spec ALSO declares its own stochastic windows (rate > 0): a zero
        mean duration makes every sampled window empty, so the
        configured rate would silently never fire."""
        with pytest.raises(ValueError, match="mean_duration_s"):
            base().server(fault=FaultSpec(rate=0.5, correlated=True))

    def test_correlated_fault_without_own_rate_is_valid(self):
        base().server(fault=FaultSpec(correlated=True))  # shared schedule only

    def test_bad_deadline(self):
        with pytest.raises(ValueError, match="deadline_s"):
            base().server(deadline_s=0.0)

    def test_erlang_k_bounds(self):
        with pytest.raises(ValueError, match="erlang"):
            base().server(service="erlang", service_k=5)

    def test_hyperexp_needs_scv_above_one(self):
        with pytest.raises(ValueError, match="scv"):
            base().server(service="hyperexp", service_scv=0.8)

    def test_pareto_needs_finite_mean(self):
        with pytest.raises(ValueError, match="alpha"):
            base().server(service="pareto", pareto_alpha=0.9)

    def test_empty_outage_window(self):
        with pytest.raises(ValueError, match="outage"):
            base().server(outage=(5.0, 5.0))

    def test_negative_outage_start(self):
        with pytest.raises(ValueError, match="outage"):
            base().server(outage=(-1.0, 2.0))

    def test_limiter_needs_positive_rate_and_capacity(self):
        with pytest.raises(ValueError, match="refill_rate"):
            base().limiter(refill_rate=0.0, capacity=5.0)
        with pytest.raises(ValueError, match="capacity"):
            base().limiter(refill_rate=1.0, capacity=0.5)

    def test_router_policy_checked(self):
        with pytest.raises(ValueError, match="policy"):
            base().router(policy="sticky")

    def test_remote_needs_server_ingress(self):
        model = base()
        sink = model.sink()
        with pytest.raises(ValueError, match="ingress"):
            model.remote(ingress=sink, latency_s=0.1)


class TestConnectRules:
    def test_negative_edge_latency(self):
        model = base()
        source, server = model.source(rate=1.0), model.server()
        with pytest.raises(ValueError, match="latency_s"):
            model.connect(source, server, latency_s=-0.1)

    def test_latency_into_limiter_rejected(self):
        model = base()
        source = model.source(rate=1.0)
        limiter = model.limiter(refill_rate=1.0, capacity=5.0)
        with pytest.raises(ValueError, match="limiter"):
            model.connect(source, limiter, latency_s=0.5)

    def test_latency_into_router_rejected(self):
        model = base()
        source = model.source(rate=1.0)
        router = model.router()
        with pytest.raises(ValueError, match="router"):
            model.connect(source, router, latency_s=0.5)

    def test_router_to_router_is_legal(self):
        """ISSUE 17: multi-router tiers are a supported topology — the
        old "single hop" connect rejection is gone. Cycles between
        routers are caught at validate() time instead (see
        TestValidateRules)."""
        model = base()
        a, b = model.router(), model.router()
        model.connect(a, b)
        assert model.routers[0].targets[-1].kind == "router"
        assert model.routers[0].targets[-1].index == 1

    def test_latency_into_downstream_router_still_rejected(self):
        """Router->router is legal ONLY as an immediate hop: a latency
        edge into the downstream router would need a transit register
        per tier, and connect keeps rejecting it."""
        model = base()
        a, b = model.router(), model.router()
        with pytest.raises(ValueError, match="router"):
            model.connect(a, b, latency_s=0.1)

    def test_limiter_to_limiter_rejected(self):
        model = base()
        a = model.limiter(refill_rate=1.0, capacity=2.0)
        b = model.limiter(refill_rate=1.0, capacity=2.0)
        with pytest.raises(ValueError, match="chain"):
            model.connect(a, b)

    def test_sink_has_no_downstream(self):
        model = base()
        sink = model.sink()
        with pytest.raises(ValueError, match="Sinks"):
            model.connect(sink, model.server())

    def test_bad_latency_kind(self):
        model = base()
        source, server = model.source(rate=1.0), model.server()
        with pytest.raises(ValueError, match="latency kind"):
            model.connect(source, server, latency_s=0.1, latency_kind="gamma")


class TestValidateRules:
    def test_needs_source_and_sink(self):
        model = base()
        model.sink()
        with pytest.raises(ValueError, match="source"):
            model.validate()
        other = base()
        other.source(rate=1.0)
        with pytest.raises(ValueError, match="sink"):
            other.validate()

    def test_dangling_source(self):
        model = base()
        model.source(rate=1.0)
        model.sink()
        with pytest.raises(ValueError, match="no downstream"):
            model.validate()

    def test_dangling_server(self):
        model = base()
        source = model.source(rate=1.0)
        server = model.server()
        model.sink()
        model.connect(source, server)
        with pytest.raises(ValueError, match=r"server\[0\] has no downstream"):
            model.validate()

    def test_empty_router(self):
        model = base()
        source = model.source(rate=1.0)
        router = model.router()
        model.sink()
        model.connect(source, router)
        with pytest.raises(ValueError, match="no targets"):
            model.validate()

    def test_remote_requires_partitioned_mode(self):
        model = base()
        source = model.source(rate=1.0)
        server = model.server()
        sink = model.sink()
        model.connect(source, server)
        model.connect(server, sink)
        model.remote(ingress=server, latency_s=0.1)
        with pytest.raises(ValueError, match="run_partitioned"):
            model.validate()
        model.validate(allow_remote=True)  # partitioned mode accepts it

    def test_least_outstanding_needs_server_targets(self):
        model = base()
        source = model.source(rate=1.0)
        sink = model.sink()
        router = model.router(policy="least_outstanding")
        model.connect(source, router)
        model.connect(router, sink)
        with pytest.raises(ValueError, match="least_outstanding"):
            model.validate()

    def test_router_cycle_rejected_naming_the_cycle(self):
        """ISSUE 17: direct router->router cycles would trace forever
        (the delivery hop recurses into the chosen downstream router),
        so validate() rejects them with the full cycle spelled out —
        while feedback THROUGH a server stays legal."""
        model = base()
        source = model.source(rate=1.0)
        a = model.router(policy="random")
        b = model.router(policy="random")
        model.sink()
        model.connect(source, a)
        model.connect(a, b)
        model.connect(b, a)
        with pytest.raises(
            ValueError,
            match=r"router cycle \(router\[0\] -> router\[1\] -> router\[0\]\)",
        ):
            model.validate()

    def test_router_self_loop_rejected(self):
        model = base()
        source = model.source(rate=1.0)
        a = model.router(policy="random")
        model.sink()
        model.connect(source, a)
        model.connect(a, a)
        with pytest.raises(ValueError, match=r"router\[0\] is on a router cycle"):
            model.validate()

    def test_server_mediated_router_feedback_is_legal(self):
        """The cycle check only walks DIRECT router->router edges: a
        server on the loop ends each delivery, so router -> server ->
        router feedback validates."""
        model = base()
        source = model.source(rate=1.0)
        done = model.server()
        retry = model.server()
        sink = model.sink()
        router = model.router(policy="random")
        model.connect(source, router)
        model.connect(router, done)
        model.connect(router, retry)
        model.connect(done, sink)
        model.connect(retry, router)  # loop back through the server
        model.validate()

    def test_router_sink_mix_rejected_naming_the_router(self):
        """ISSUE 17: a router target list mixing a downstream ROUTER
        with a SINK races a zero-work exit against a routing tier —
        rejected by name; the probabilistic exit belongs on the
        downstream router's own list."""
        model = base()
        source = model.source(rate=1.0)
        server = model.server()
        sink = model.sink()
        back = model.router(policy="random")
        model.connect(back, server)
        front = model.router(policy="random")
        model.connect(source, front)
        model.connect(front, back)
        model.connect(front, sink)
        model.connect(server, sink)
        with pytest.raises(
            ValueError, match=r"router\[1\] mixes a downstream router"
        ):
            model.validate()

    def test_least_outstanding_rejects_router_targets(self):
        """least_outstanding gathers per-SERVER outstanding counts, so
        a router target has no defined ordering key — rejected at
        validate() with the policy named."""
        model = base()
        source = model.source(rate=1.0)
        server = model.server()
        sink = model.sink()
        back = model.router(policy="random")
        model.connect(back, server)
        front = model.router(policy="least_outstanding")
        model.connect(source, front)
        model.connect(front, back)
        model.connect(front, server)
        model.connect(server, sink)
        with pytest.raises(
            ValueError, match="only servers carry outstanding work"
        ):
            model.validate()

    def test_mixed_server_sink_router_is_legal(self):
        model = base()
        source = model.source(rate=1.0)
        server = model.server()
        sink = model.sink()
        router = model.router(policy="random")
        model.connect(source, server)
        model.connect(server, router)
        model.connect(router, sink)
        model.connect(router, server)  # probabilistic feedback
        model.validate()

    def test_weighted_router_validates_weights(self):
        model = base()
        source = model.source(rate=1.0)
        servers = [model.server(), model.server()]
        sink = model.sink()
        router = model.router(policy="weighted", weights=(1.0, 3.0))
        model.connect(source, router)
        for server in servers:
            model.connect(router, server)
            model.connect(server, sink)
        model.validate()

    def test_weighted_router_rejects_bad_weights(self):
        with pytest.raises(ValueError, match="weights"):
            base().router(policy="weighted")  # weights required
        with pytest.raises(ValueError, match="> 0"):
            base().router(policy="weighted", weights=(1.0, 0.0))
        with pytest.raises(ValueError, match="policy='weighted'"):
            base().router(policy="random", weights=(1.0, 2.0))

    def test_weighted_weights_join_the_fingerprint_only_when_present(self):
        """Different weights compile different steps -> different
        digests; unweighted router models keep their pre-weighted-policy
        fingerprints (RouterSpec.weights is repr=False and appended
        separately — the telemetry_spec discipline)."""
        from happysim_tpu.tpu.engine import model_fingerprint

        def fleet(policy, weights=None):
            model = base()
            source = model.source(rate=1.0)
            servers = [model.server(), model.server()]
            sink = model.sink()
            router = model.router(policy=policy, weights=weights)
            model.connect(source, router)
            for server in servers:
                model.connect(router, server)
                model.connect(server, sink)
            return model

        one_three = model_fingerprint(fleet("weighted", (1.0, 3.0)))
        assert one_three != model_fingerprint(fleet("weighted", (3.0, 1.0)))
        # An unweighted router's repr carries no weights field at all.
        assert "weights" not in repr(fleet("random").routers[0])

    def test_weighted_router_rejects_weight_target_mismatch(self):
        """Targets wired AFTER router() must still match the weights
        length — caught at validate() time, not silently renormalized."""
        model = base()
        source = model.source(rate=1.0)
        servers = [model.server(), model.server(), model.server()]
        sink = model.sink()
        router = model.router(policy="weighted", weights=(1.0, 2.0))
        model.connect(source, router)
        for server in servers:
            model.connect(router, server)
            model.connect(server, sink)
        with pytest.raises(ValueError, match="2 weights for 3 targets"):
            model.validate()


class TestFactories:
    def test_mm1_model_validates(self):
        mm1_model().validate()

    def test_pipeline_model_validates(self):
        pipeline_model(rate=5.0, service_means=[0.05, 0.04, 0.03]).validate()

    def test_max_queue_capacity_is_fleet_max(self):
        model = base()
        source = model.source(rate=1.0)
        a = model.server(queue_capacity=8)
        b = model.server(queue_capacity=64)
        sink = model.sink()
        model.connect(source, a)
        model.connect(a, b)
        model.connect(b, sink)
        assert model.max_queue_capacity == 64


class TestResilienceSpecs:
    """The resilience-layer builders (ISSUE 15): every rejection rule
    plus the feature-descriptor contract the kernel claim reads."""

    def _chain(self, **server_kwargs):
        model = base()
        source = model.source(rate=5.0)
        server = model.server(service_mean=0.1, **server_kwargs)
        sink = model.sink()
        model.connect(source, server)
        model.connect(server, sink)
        return model

    def test_breaker_spec_bounds(self):
        model = self._chain(deadline_s=0.5)
        with pytest.raises(ValueError, match="failure_threshold"):
            model.circuit_breaker(failure_threshold=0)
        with pytest.raises(ValueError, match="window_s"):
            model.circuit_breaker(window_s=0.0)
        with pytest.raises(ValueError, match="cooldown_s"):
            model.circuit_breaker(cooldown_s=-1.0)
        with pytest.raises(ValueError, match="half_open_probes"):
            model.circuit_breaker(half_open_probes=0)

    def test_breaker_requires_a_failure_site(self):
        model = self._chain()  # no deadline, fault, or brownout anywhere
        model.circuit_breaker()
        with pytest.raises(ValueError, match="failure site"):
            model.validate()
        for site in (
            dict(deadline_s=0.5),
            dict(fault=FaultSpec(rate=0.5, mean_duration_s=0.2)),
            dict(outage=(1.0, 2.0)),
        ):
            model = self._chain(**site)
            model.circuit_breaker()
            model.validate()

    def test_breaker_rejects_degrade_only_fault_site(self):
        """A degrade-mode fault slows service but never rejects an
        arrival, so alone it is NOT a failure signal the breaker can
        observe — rejected unless a deadline turns the slowdown into
        timeouts."""
        degrade = FaultSpec(
            rate=0.5, mean_duration_s=0.2, mode="degrade", latency_factor=3.0
        )
        model = self._chain(fault=degrade)
        model.circuit_breaker()
        with pytest.raises(ValueError, match="failure site"):
            model.validate()
        model = self._chain(fault=degrade, deadline_s=0.5)
        model.circuit_breaker()
        model.validate()

    def test_shed_spec_bounds(self):
        model = self._chain()
        with pytest.raises(ValueError, match="policy"):
            model.load_shed(policy="latency")
        with pytest.raises(ValueError, match="queue_depth threshold"):
            model.load_shed(policy="queue_depth", threshold=0)
        with pytest.raises(ValueError, match="utilization threshold"):
            model.load_shed(policy="utilization", threshold=1.5)
        with pytest.raises(ValueError, match="priority_fraction"):
            model.load_shed(priority_fraction=1.0)
        model.load_shed(policy="utilization", threshold=1.0)
        model.validate()

    def test_budget_spec_bounds(self):
        model = self._chain(deadline_s=0.5, max_retries=2)
        with pytest.raises(ValueError, match="ratio"):
            model.retry_budget(ratio=-0.1)
        with pytest.raises(ValueError, match="never refill"):
            model.retry_budget(ratio=0.0, min_per_s=0.0)
        with pytest.raises(ValueError, match="burst"):
            model.retry_budget(ratio=0.1, burst=0.5)
        model.retry_budget(ratio=0.1)
        model.validate()

    def test_budget_requires_a_consumer(self):
        model = self._chain()  # no retries, no hedging
        model.retry_budget(ratio=0.1)
        with pytest.raises(ValueError, match="gate nothing"):
            model.validate()
        model = self._chain(hedge_delay_s=0.2)
        model.retry_budget(ratio=0.1)
        model.validate()  # hedges alone are a consumer

    def test_resilience_features_descriptor(self):
        model = self._chain(deadline_s=0.5, max_retries=1)
        assert model.resilience_features() == ()
        model.circuit_breaker()
        model.load_shed(policy="queue_depth", threshold=4)
        model.retry_budget(ratio=0.1)
        assert model.resilience_features() == (
            "circuit_breaker",
            "load_shed",
            "retry_budget",
        )
        # The chaos descriptor (the kernel's claim surface) includes the
        # resilience names, keeping telemetry last.
        model.telemetry(window_s=1.0)
        features = model.chaos_features()
        assert features[-1] == "telemetry"
        assert set(
            ("circuit_breaker", "load_shed", "retry_budget")
        ) <= set(features)

    def test_resilience_specs_join_the_fingerprint_only_when_present(self):
        from happysim_tpu.tpu.engine import model_fingerprint

        plain = self._chain(deadline_s=0.5, max_retries=1)
        baseline = model_fingerprint(plain)
        defended = self._chain(deadline_s=0.5, max_retries=1)
        defended.retry_budget(ratio=0.1)
        assert model_fingerprint(defended) != baseline
        # ...and a second spec-free build reproduces the baseline, so
        # pre-resilience checkpoints keep their fingerprints.
        assert model_fingerprint(
            self._chain(deadline_s=0.5, max_retries=1)
        ) == baseline
