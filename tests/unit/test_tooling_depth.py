"""Depth tests for the tooling tier: MCP stdio loop, chart transforms,
serializers, data-series edge cases, and network condition presets."""

import io
import json

import pytest

from happysim_tpu import Data, Instant
from happysim_tpu.components.network.conditions import (
    cross_region_network,
    datacenter_network,
    internet_network,
    local_network,
    lossy_network,
    mobile_3g_network,
    mobile_4g_network,
    satellite_network,
    slow_network,
)
from happysim_tpu.mcp.server import serve
from happysim_tpu.mcp.tools import format_distributions
from happysim_tpu.visual.dashboard import Chart
from happysim_tpu.visual.serializers import is_internal_event, serialize_entity


def _rpc(method, request_id=1, **params):
    msg = {"jsonrpc": "2.0", "id": request_id, "method": method}
    if params:
        msg["params"] = params
    return json.dumps(msg).encode() + b"\n"


class TestMcpStdioLoop:
    def _drive(self, *lines):
        stdin = io.BytesIO(b"".join(lines))
        stdout = io.BytesIO()
        serve(stdin=stdin, stdout=stdout)
        return [json.loads(l) for l in stdout.getvalue().splitlines()]

    def test_initialize_then_list_then_ping(self):
        replies = self._drive(
            _rpc("initialize", 1),
            _rpc("tools/list", 2),
            _rpc("ping", 3),
        )
        assert replies[0]["result"]["serverInfo"]
        tool_names = {t["name"] for t in replies[1]["result"]["tools"]}
        assert {"simulate_queue", "simulate_pipeline"} <= tool_names
        assert replies[2] == {"jsonrpc": "2.0", "id": 3, "result": {}}

    def test_tool_call_runs_simulation(self):
        replies = self._drive(
            _rpc(
                "tools/call",
                7,
                name="simulate_queue",
                arguments={"arrival_rate": 5.0, "service_rate": 10.0, "duration": 20.0, "seed": 1},
            )
        )
        text = replies[0]["result"]["content"][0]["text"]
        assert "rho" in text.lower() or "utilization" in text.lower() or "latency" in text.lower()
        assert not replies[0]["result"].get("isError")

    def test_bad_tool_errors_in_band(self):
        replies = self._drive(
            _rpc("tools/call", 8, name="no_such_tool", arguments={})
        )
        assert replies[0]["result"]["isError"]

    def test_unknown_method_code(self):
        replies = self._drive(_rpc("wat", 9))
        assert replies[0]["error"]["code"] == -32601

    def test_notifications_and_garbage_skipped(self):
        stdin = io.BytesIO(
            b"not json\n"
            + b"\n"
            + json.dumps({"jsonrpc": "2.0", "method": "notifications/initialized"}).encode()
            + b"\n"
            + _rpc("ping", 4)
        )
        stdout = io.BytesIO()
        serve(stdin=stdin, stdout=stdout)
        replies = [json.loads(l) for l in stdout.getvalue().splitlines()]
        assert len(replies) == 1  # only the ping got a response
        assert replies[0]["id"] == 4

    def test_format_distributions_default(self):
        text = format_distributions().lower()
        assert "exponential" in text and "constant" in text


class TestChartTransforms:
    def _data(self):
        d = Data("lat")
        for i in range(100):
            d.add(Instant.from_seconds(i * 0.1), float(i % 10))
        return d

    def test_raw_passthrough(self):
        chart = Chart("t", self._data(), transform="raw")
        s = chart.series()
        assert len(s["times"]) == 100
        assert s["values"][3] == 3.0

    @pytest.mark.parametrize("transform", ["mean", "p50", "p99", "p999", "max"])
    def test_bucketed_transforms(self, transform):
        chart = Chart("t", self._data(), transform=transform, window_s=1.0)
        s = chart.series()
        assert len(s["times"]) == 10
        if transform == "max":
            assert all(v == 9.0 for v in s["values"])
        if transform == "mean":
            assert all(v == pytest.approx(4.5) for v in s["values"])

    def test_rate_transform(self):
        chart = Chart("t", self._data(), transform="rate", window_s=1.0)
        s = chart.series()
        assert all(v == pytest.approx(10.0) for v in s["values"])

    def test_lazy_data_refetched(self):
        backing = {"d": Data("a")}
        chart = Chart("t", lambda: backing["d"], transform="raw")
        assert chart.series()["values"] == []
        fresh = Data("b")
        fresh.add(Instant.from_seconds(1), 5.0)
        backing["d"] = fresh
        assert chart.series()["values"] == [5.0]

    def test_unknown_transform_rejected(self):
        with pytest.raises(ValueError):
            Chart("t", Data("x"), transform="median")


class TestSerializers:
    def test_internal_events_filtered(self):
        assert is_internal_event("Queue.poll")
        assert not is_internal_event("Request")

    def test_entity_snapshot_jsonable(self):
        from happysim_tpu import ConstantLatency, Server

        server = Server("srv", service_time=ConstantLatency(0.01))
        snapshot = serialize_entity(server)
        json.dumps(snapshot)  # must be JSON-clean
        assert snapshot["name"] == "srv"

    def test_deeply_nested_values_capped(self):
        class Weird:
            name = "w"

            def __init__(self):
                self.loop = {"a": {"b": {"c": {"d": {"e": {"f": 1}}}}}}

        payload = serialize_entity(Weird())
        json.dumps(payload)  # depth-capped, not infinite


class TestDataEdgeCases:
    def test_empty_series(self):
        d = Data("x")
        assert d.count() == 0
        assert d.mean() == 0.0
        assert d.percentile(0.99) == 0.0
        assert list(d.bucket(1.0).means) == []

    def test_single_point(self):
        d = Data("x")
        d.add(Instant.from_seconds(2), 7.0)
        assert d.mean() == 7.0
        assert d.min() == d.max() == 7.0
        assert d.percentile(0.5) == 7.0

    def test_between_inclusive_endpoints(self):
        d = Data("x")
        for t in (1.0, 2.0, 3.0):
            d.add(Instant.from_seconds(t), t)
        window = d.between(1.0, 3.0)
        assert window.count() == 3  # inclusive of both endpoints
        assert d.between(1.5, 2.5).count() == 1

    def test_bucket_alignment(self):
        d = Data("x")
        d.add(Instant.from_seconds(0.5), 1.0)
        d.add(Instant.from_seconds(1.5), 3.0)
        b = d.bucket(1.0)
        assert list(b.counts) == [1, 1]
        assert b.means[0] == 1.0 and b.means[1] == 3.0


class TestNetworkPresets:
    PRESETS = [
        local_network,
        datacenter_network,
        cross_region_network,
        internet_network,
        satellite_network,
        lambda seed: lossy_network(0.1, seed=seed),
        lambda seed: slow_network(1.0, seed=seed),
        mobile_3g_network,
        mobile_4g_network,
    ]
    IDS = ["local", "datacenter", "cross_region", "internet", "satellite",
           "lossy", "slow", "mobile_3g", "mobile_4g"]

    @pytest.mark.parametrize("factory", PRESETS, ids=IDS)
    def test_preset_builds_and_samples(self, factory):
        link = factory(seed=3)
        latency = link.latency.get_latency(Instant.Epoch)
        assert latency.to_seconds() >= 0.0

    def test_latency_ordering_makes_sense(self):
        fast = local_network(seed=1).latency.mean().to_seconds()
        dc = datacenter_network(seed=1).latency.mean().to_seconds()
        wan = cross_region_network(seed=1).latency.mean().to_seconds()
        sat = satellite_network(seed=1).latency.mean().to_seconds()
        assert fast < dc < wan < sat

    def test_lossy_network_drops(self):
        link = lossy_network(loss_rate=0.5, seed=2)
        assert link.packet_loss_rate == pytest.approx(0.5)
        with pytest.raises(ValueError):
            lossy_network(loss_rate=1.5)
