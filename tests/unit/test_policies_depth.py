"""Depth tests for rate-limiter policies, LB strategies, and network-link
behaviors beyond the basics (ref rate_limiter/policy.py:65-310,
load_balancer/strategies.py:30-436, network/link.py)."""

import pytest

from happysim_tpu import (
    ConstantLatency,
    Duration,
    Event,
    Instant,
    LoadBalancer,
    Network,
    NetworkLink,
    Server,
    Simulation,
    Sink,
    Source,
)
from happysim_tpu.components.load_balancer import (
    LeastResponseTime,
    PowerOfTwoChoices,
    WeightedRoundRobin,
)
from happysim_tpu.components.rate_limiter.policy import (
    AdaptivePolicy,
    FixedWindowPolicy,
    LeakyBucketPolicy,
    SlidingWindowPolicy,
    TokenBucketPolicy,
)


def t(seconds):
    return Instant.from_seconds(seconds)


class TestSlidingWindow:
    def test_trailing_window_slides(self):
        p = SlidingWindowPolicy(window_size_seconds=1.0, max_requests=2)
        assert p.try_acquire(t(0.0))
        assert p.try_acquire(t(0.5))
        assert not p.try_acquire(t(0.9))  # 2 in the last second
        assert p.try_acquire(t(1.01))  # t=0.0 aged out
        assert not p.try_acquire(t(1.2))  # 0.5 and 1.01 still inside

    def test_time_until_available_tracks_oldest(self):
        p = SlidingWindowPolicy(window_size_seconds=1.0, max_requests=1)
        p.try_acquire(t(2.0))
        wait = p.time_until_available(t(2.4))
        assert wait.to_seconds() == pytest.approx(0.6)
        assert p.time_until_available(t(3.01)).to_seconds() == 0.0


class TestFixedWindow:
    def test_aligned_reset(self):
        p = FixedWindowPolicy(requests_per_window=2, window_size=1.0)
        assert p.try_acquire(t(0.1)) and p.try_acquire(t(0.2))
        assert not p.try_acquire(t(0.99))
        assert p.try_acquire(t(1.0))  # new aligned window

    def test_boundary_burst(self):
        """The classic fixed-window artifact: 2N requests straddle a
        boundary — exactly why sliding windows exist."""
        p = FixedWindowPolicy(requests_per_window=2, window_size=1.0)
        admitted = sum(p.try_acquire(t(x)) for x in (0.8, 0.9, 1.0, 1.1))
        assert admitted == 4
        sliding = SlidingWindowPolicy(window_size_seconds=1.0, max_requests=2)
        admitted_sliding = sum(sliding.try_acquire(t(x)) for x in (0.8, 0.9, 1.0, 1.1))
        assert admitted_sliding == 2

    def test_time_until_next_window(self):
        p = FixedWindowPolicy(requests_per_window=1, window_size=2.0)
        p.try_acquire(t(0.5))
        assert p.time_until_available(t(0.5)).to_seconds() == pytest.approx(1.5)


class TestLeakyBucket:
    def test_steady_drain(self):
        p = LeakyBucketPolicy(leak_rate=2.0)  # 2/s
        assert p.try_acquire(t(0.0))
        # Fill the bucket at t=0, then confirm the leak frees space.
        while p.try_acquire(t(0.0)):
            pass
        assert not p.try_acquire(t(0.0))  # full
        assert p.try_acquire(t(0.6))  # ~1 unit leaked by then


class TestAdaptiveAIMD:
    def test_backpressure_halves_rate(self):
        p = AdaptivePolicy(initial_rate=100.0, min_rate=1.0, decrease_factor=0.5)
        p.record_backpressure(t(1.0))
        assert p.current_rate == 50.0
        p.record_backpressure(t(2.0))
        assert p.current_rate == 25.0

    def test_success_additive_increase_caps(self):
        p = AdaptivePolicy(initial_rate=99.5, max_rate=100.0, increase_per_second=1.0)
        p.record_success(t(1.0))
        assert p.current_rate == 100.0
        p.record_success(t(2.0))
        assert p.current_rate == 100.0  # capped

    def test_floor_respected(self):
        p = AdaptivePolicy(initial_rate=2.0, min_rate=1.0, decrease_factor=0.1)
        p.record_backpressure(t(1.0))
        assert p.current_rate == 1.0

    def test_sawtooth_history_recorded(self):
        p = AdaptivePolicy(initial_rate=10.0)
        p.record_success(t(1.0))
        p.record_backpressure(t(2.0))
        p.record_success(t(3.0))
        rates = [snap.rate for snap in p.history]
        assert rates == [11.0, 5.5, 6.5]

    def test_validation(self):
        with pytest.raises(ValueError):
            AdaptivePolicy(initial_rate=0.5, min_rate=1.0)
        with pytest.raises(ValueError):
            AdaptivePolicy(decrease_factor=1.0)


class TestTokenBucketEdge:
    def test_burst_up_to_capacity_then_paced(self):
        p = TokenBucketPolicy(capacity=3.0, refill_rate=1.0)
        burst = sum(p.try_acquire(t(0.0)) for _ in range(5))
        assert burst == 3
        assert not p.try_acquire(t(0.5))
        assert p.try_acquire(t(1.01))  # one token refilled


def _run_lb(strategy, service_means, n_requests=200, weights=None):
    sink = Sink("sink")
    lb = LoadBalancer("lb", strategy=strategy)
    backends = [
        Server(
            f"b{i}", concurrency=4, service_time=ConstantLatency(mean), downstream=sink
        )
        for i, mean in enumerate(service_means)
    ]
    for i, b in enumerate(backends):
        lb.add_backend(b, weight=(weights[i] if weights else 1.0))
    source = Source.constant(rate=50.0, target=lb, stop_after=n_requests / 50.0)
    sim = Simulation(
        sources=[source],
        entities=[lb, sink, *backends],
        end_time=Instant.from_seconds(n_requests / 50.0 + 5),
    )
    sim.run()
    return [b.requests_completed for b in backends]


class TestStrategiesDepth:
    def test_weighted_round_robin_ratio(self):
        counts = _run_lb(
            WeightedRoundRobin(), [0.001, 0.001], weights=[3.0, 1.0]
        )
        assert counts[0] / counts[1] == pytest.approx(3.0, rel=0.1)

    def test_least_response_time_prefers_fast_backend(self):
        counts = _run_lb(LeastResponseTime(), [0.002, 0.08])
        assert counts[0] > counts[1] * 2

    def test_power_of_two_balances(self):
        counts = _run_lb(PowerOfTwoChoices(seed=5), [0.01] * 8)
        assert max(counts) < 2.5 * min(counts)


class TestNetworkLinkDepth:
    def test_per_pair_link_overrides_default(self):
        received = []
        from happysim_tpu.core.callback_entity import CallbackEntity

        a = CallbackEntity("a", lambda: None)
        b = CallbackEntity("b", lambda e, now: received.append(now.to_seconds()))
        net = Network(
            "net", default_link=NetworkLink("slow", latency=ConstantLatency(1.0))
        )
        net.add_link(a, b, NetworkLink("fast", latency=ConstantLatency(0.01)))
        sim = Simulation(entities=[net, a, b], end_time=Instant.from_seconds(10))

        class Go(CallbackEntity):
            def __init__(self):
                super().__init__("go", self._fire)

            def _fire(self, event):
                return [net.send(source=a, destination=b, event_type="Msg", payload={})]

        go = Go()
        sim.schedule(Event(Instant.from_seconds(1.0), "Go", target=go))
        sim.run()
        assert received == [pytest.approx(1.01)]

    def test_bandwidth_serialization_delay(self):
        from happysim_tpu.core.clock import Clock

        link = NetworkLink(
            "thin", latency=ConstantLatency(0.0), bandwidth_bps=8_000
        )  # 1 KB/s
        link.set_clock(Clock())
        assert link._delay(payload_size=500) == pytest.approx(0.5)

    def test_link_delay_samples_its_distribution(self):
        from happysim_tpu import ExponentialLatency
        from happysim_tpu.core.clock import Clock

        link = NetworkLink("j", latency=ExponentialLatency(0.01, seed=4))
        link.set_clock(Clock())
        samples = {round(link._delay(payload_size=0), 9) for _ in range(20)}
        assert len(samples) > 10  # the LINK's per-delivery delay varies
