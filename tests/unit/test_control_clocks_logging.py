"""Unit tests: interactive control (pause/step/reset/breakpoints/heap
introspection), node + logical clocks, and logging configuration
(VERDICT directive #9)."""

import json
import logging

import pytest

from happysim_tpu import (
    ConditionBreakpoint,
    ConstantLatency,
    Duration,
    Event,
    EventCountBreakpoint,
    EventTypeBreakpoint,
    FixedSkew,
    HLCTimestamp,
    HybridLogicalClock,
    Instant,
    LamportClock,
    LinearDrift,
    MetricBreakpoint,
    NodeClock,
    Server,
    Simulation,
    Sink,
    Source,
    TimeBreakpoint,
    VectorClock,
)
from happysim_tpu import logging_config
from happysim_tpu.core.clock import Clock


def mm1(rate=10.0, duration=60.0):
    sink = Sink("sink")
    server = Server("srv", service_time=ConstantLatency(0.01), downstream=sink)
    source = Source.constant(rate=rate, target=server, stop_after=duration)
    sim = Simulation(
        sources=[source], entities=[server, sink],
        end_time=Instant.from_seconds(duration),
    )
    return sim, server, sink


class TestControlPauseStepReset:
    def test_time_breakpoint_pauses_and_resume_finishes(self):
        sim, server, sink = mm1()
        sim.control.add_breakpoint(TimeBreakpoint(5.0))
        summary = sim.run()
        assert not summary.completed
        assert sim.control.is_paused
        assert sim.now.to_seconds() <= 5.01
        received_at_pause = sink.events_received
        final = sim.control.resume()
        assert final.completed
        assert sink.events_received > received_at_pause

    def test_step_processes_exactly_n_events(self):
        sim, _, _ = mm1()
        sim.control.pause()
        sim.run()
        before = sim.control.get_state().events_processed
        sim.control.step(5)
        after = sim.control.get_state().events_processed
        assert after - before == 5
        assert sim.control.is_paused

    def test_event_count_breakpoint(self):
        sim, _, _ = mm1()
        sim.control.add_breakpoint(EventCountBreakpoint(10))
        sim.run()
        assert sim.control.get_state().events_processed == 10

    def test_event_type_breakpoint_with_target(self):
        sim, server, sink = mm1()
        sim.control.add_breakpoint(EventTypeBreakpoint("Request", "srv"))
        sim.run()
        assert sim.control.is_paused
        assert sim.control.peek_next().event_type == "Request"

    def test_condition_and_metric_breakpoints(self):
        sim, server, sink = mm1()
        sim.control.add_breakpoint(
            ConditionBreakpoint(lambda ctx: ctx.time.to_seconds() >= 1.0)
        )
        sim.run()
        assert sim.control.is_paused
        assert sim.now.to_seconds() >= 1.0
        sim.control.clear_breakpoints()
        sim.control.add_breakpoint(
            MetricBreakpoint(sink, "events_received", ">=", 100)
        )
        sim.control.resume()
        assert 100 <= sink.events_received < 110

    def test_remove_breakpoint(self):
        sim, _, _ = mm1()
        bp = sim.control.add_breakpoint(TimeBreakpoint(1.0))
        sim.control.remove_breakpoint(bp)
        assert sim.control.breakpoints == []
        assert sim.run().completed

    def test_reset_replays_pre_run_events(self):
        sink = Sink("sink")
        sim = Simulation(entities=[sink], end_time=Instant.from_seconds(10))
        sim.schedule(Event(Instant.from_seconds(1.0), "Ping", target=sink))
        sim.run()
        assert sink.events_received == 1
        sim.control.reset()
        assert sim.control.get_state().events_processed == 0
        sim.run()
        # The pre-run schedule replays (entity state intentionally kept).
        assert sink.events_received == 2

    def test_on_event_and_time_advance_hooks(self):
        sim, _, _ = mm1(duration=1.0)
        seen_events, time_advances = [], []
        sim.control.on_event(seen_events.append)
        sim.control.on_time_advance(time_advances.append)
        sim.run()
        assert len(seen_events) == sim.control.get_state().events_processed
        assert time_advances == sorted(time_advances)

    def test_heap_introspection(self):
        sink = Sink("sink")
        sim = Simulation(entities=[sink], end_time=Instant.from_seconds(10))
        sim.schedule(
            [Event(Instant.from_seconds(t), "Ping", target=sink) for t in (3.0, 1.0, 2.0)]
        )
        assert sim.control.peek_next().time.to_seconds() == pytest.approx(1.0)
        found = sim.control.find_events(lambda e: e.time.to_seconds() > 1.5)
        assert len(found) == 2


class TestNodeClocks:
    def test_fixed_skew_offsets_view(self):
        clock = Clock(Instant.from_seconds(100.0))
        node = NodeClock(FixedSkew(Duration.from_seconds(2.5)))
        node.set_clock(clock)
        assert node.now.to_seconds() == pytest.approx(102.5)

    def test_linear_drift_accumulates(self):
        clock = Clock(Instant.from_seconds(1000.0))
        node = NodeClock(LinearDrift(rate_ppm=100.0))  # 100us/s
        node.set_clock(clock)
        assert node.now.to_seconds() == pytest.approx(1000.0 + 0.1)

    def test_unmodeled_clock_is_true_time(self):
        clock = Clock(Instant.from_seconds(42.0))
        node = NodeClock()
        node.set_clock(clock)
        assert node.now.to_seconds() == 42.0

    def test_unattached_raises(self):
        with pytest.raises(RuntimeError):
            NodeClock().now


class TestLogicalClocks:
    def test_lamport_tick_and_update(self):
        a, b = LamportClock(), LamportClock()
        a.tick()  # a=1
        b.update(a.time)  # b = max(0,1)+1 = 2
        assert (a.time, b.time) == (1, 2)
        a.update(b.time)
        assert a.time == 3

    def test_vector_clock_causality(self):
        a, b = VectorClock("a"), VectorClock("b")
        a.increment()
        b.merge(a)  # a -> b
        assert a.happened_before(b)
        assert not b.happened_before(a)
        c = VectorClock("c").increment()
        assert c.is_concurrent(a)

    def test_vector_clock_merge_equality(self):
        a, b = VectorClock("a").increment(), VectorClock("b").increment()
        a_copy = a.copy()
        a.merge(b)
        assert a_copy.happened_before(a)
        assert a == VectorClock("a", a.clocks)

    def test_hlc_tracks_physical_time(self):
        hlc = HybridLogicalClock()
        t1 = hlc.now(Instant.from_seconds(1.0))
        t2 = hlc.now(Instant.from_seconds(2.0))
        assert t2 > t1
        assert t2.logical == 0  # fresh wall time resets logical

    def test_hlc_same_instant_bumps_logical(self):
        hlc = HybridLogicalClock()
        t1 = hlc.now(Instant.from_seconds(1.0))
        t2 = hlc.now(Instant.from_seconds(1.0))
        assert t2.wall == t1.wall and t2.logical == t1.logical + 1

    def test_hlc_receive_dominates_remote(self):
        local = HybridLogicalClock()
        remote = HLCTimestamp(wall=int(5e9), logical=7)
        stamped = local.receive(remote, Instant.from_seconds(1.0))
        assert stamped > remote
        assert stamped.wall == remote.wall and stamped.logical == 8

    def test_hlc_total_order(self):
        assert HLCTimestamp(1, 5) < HLCTimestamp(2, 0) < HLCTimestamp(2, 1)


class TestLoggingConfig:
    def teardown_method(self):
        logging_config.disable_logging()

    def test_silent_by_default(self):
        root = logging.getLogger("happysim_tpu")
        assert all(isinstance(h, logging.NullHandler) for h in root.handlers)

    def test_console_logging_captures(self, capsys):
        logging_config.enable_console_logging("DEBUG")
        logging.getLogger("happysim_tpu.test").debug("hello world")
        assert "hello world" in capsys.readouterr().err

    def test_file_logging(self, tmp_path):
        path = tmp_path / "sim.log"
        logging_config.enable_file_logging(str(path), "INFO")
        logging.getLogger("happysim_tpu.test").info("to file")
        logging_config.disable_logging()
        assert "to file" in path.read_text()

    def test_json_logging(self, capsys):
        logging_config.enable_json_logging("INFO")
        logging.getLogger("happysim_tpu.test").info("structured")
        line = capsys.readouterr().err.strip().splitlines()[-1]
        payload = json.loads(line)
        assert payload["message"] == "structured"
        assert payload["level"] == "INFO"

    def test_module_level_filtering(self, capsys):
        logging_config.enable_console_logging("DEBUG")
        logging_config.set_module_level("tpu", "ERROR")
        logging.getLogger("happysim_tpu.tpu.engine").info("suppressed")
        logging.getLogger("happysim_tpu.core").info("visible")
        err = capsys.readouterr().err
        assert "suppressed" not in err and "visible" in err

    def test_configure_from_env(self, capsys):
        enabled = logging_config.configure_from_env({"HS_LOGGING": "debug"})
        assert enabled
        logging.getLogger("happysim_tpu.env").debug("from env")
        assert "from env" in capsys.readouterr().err
        assert not logging_config.configure_from_env({})

    def test_env_file_and_json(self, tmp_path):
        path = tmp_path / "env.log"
        logging_config.configure_from_env(
            {"HS_LOGGING": "1", "HS_LOG_FILE": str(path), "HS_LOG_JSON": "true"}
        )
        logging.getLogger("happysim_tpu.env").info("json to file")
        logging_config.disable_logging()
        assert json.loads(path.read_text().strip())["message"] == "json to file"

    def test_rotating_file(self, tmp_path):
        path = tmp_path / "rot.log"
        logging_config.enable_file_logging(str(path), rotate_bytes=200, backup_count=1)
        for i in range(50):
            logging.getLogger("happysim_tpu.rot").warning("row %d", i)
        logging_config.disable_logging()
        assert path.exists()
