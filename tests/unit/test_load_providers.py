"""Depth tests for event providers (ref load/event_provider.py:15,
load/source.py:31, load/providers/distributed_field.py)."""

from happysim_tpu import Instant, Simulation, Sink, Source, UniformDistribution, ZipfDistribution
from happysim_tpu.core.callback_entity import NullEntity
from happysim_tpu.load.event_provider import EventProvider, SimpleEventProvider
from happysim_tpu.load.providers.distributed_field import DistributedFieldProvider


class TestSimpleEventProvider:
    def test_sequential_request_ids(self):
        p = SimpleEventProvider(target=NullEntity)
        a = p.get_events(Instant.from_seconds(1))[0]
        b = p.get_events(Instant.from_seconds(2))[0]
        assert a.context["request_id"] == 0
        assert b.context["request_id"] == 1
        assert a.context["created_at"] == Instant.from_seconds(1)
        assert p.generated == 2

    def test_stop_after_exhausts(self):
        p = SimpleEventProvider(target=NullEntity, stop_after=Instant.from_seconds(5))
        assert p.get_events(Instant.from_seconds(5))  # boundary still emits
        assert p.get_events(Instant.from_seconds(6)) == []
        assert p.is_exhausted(Instant.from_seconds(6))
        assert not p.is_exhausted(Instant.from_seconds(4))

    def test_context_fn_merges(self):
        p = SimpleEventProvider(
            target=NullEntity,
            context_fn=lambda time, i: {"tenant": f"t{i}"},
        )
        e = p.get_events(Instant.from_seconds(1))[0]
        assert e.context["tenant"] == "t0"
        assert "request_id" in e.context

    def test_reset_rewinds_ids(self):
        p = SimpleEventProvider(target=NullEntity)
        p.get_events(Instant.from_seconds(1))
        p.reset()
        assert p.generated == 0
        assert p.get_events(Instant.from_seconds(2))[0].context["request_id"] == 0

    def test_custom_event_type(self):
        p = SimpleEventProvider(target=NullEntity, event_type="Write")
        assert p.get_events(Instant.Epoch)[0].event_type == "Write"


class TestDistributedFieldProvider:
    def test_fields_sampled_per_event(self):
        p = DistributedFieldProvider(
            target=NullEntity,
            fields={
                "key": ZipfDistribution(items=100, exponent=1.2, seed=7),
                "size": UniformDistribution(low=1.0, high=2.0, seed=8),
            },
        )
        events = [p.get_events(Instant.from_seconds(t))[0] for t in range(20)]
        keys = {e.context["key"] for e in events}
        assert len(keys) > 1  # not constant
        assert all(1.0 <= e.context["size"] <= 2.0 for e in events)

    def test_zipf_skews_toward_head(self):
        p = DistributedFieldProvider(
            target=NullEntity,
            fields={"key": ZipfDistribution(items=1000, exponent=1.5, seed=3)},
        )
        keys = [p.get_events(Instant.Epoch)[0].context["key"] for _ in range(500)]
        head_share = sum(1 for k in keys if k < 10) / len(keys)
        assert head_share > 0.4

    def test_stop_after_and_reset(self):
        p = DistributedFieldProvider(
            target=NullEntity, stop_after=Instant.from_seconds(1)
        )
        p.get_events(Instant.from_seconds(1))
        assert p.get_events(Instant.from_seconds(2)) == []
        p.reset()
        assert p.get_events(Instant.from_seconds(0))[0].context["request_id"] == 0

    def test_no_fields_still_emits(self):
        p = DistributedFieldProvider(target=NullEntity)
        e = p.get_events(Instant.Epoch)[0]
        assert e.context["request_id"] == 0

    def test_drives_source_in_simulation(self):
        sink = Sink("sink")
        provider = DistributedFieldProvider(
            target=sink, fields={"key": ZipfDistribution(items=50, seed=1)}
        )
        source = Source.constant(rate=10.0, stop_after=5.0, event_provider=provider)
        sim = Simulation(sources=[source], entities=[sink], end_time=Instant.from_seconds(6))
        sim.run()
        assert sink.events_received >= 45


class TestEventProviderDefaults:
    def test_base_defaults(self):
        class Fixed(EventProvider):
            def get_events(self, time):
                return []

        f = Fixed()
        assert f.is_exhausted(Instant.from_seconds(1e9)) is False
        f.reset()  # no-op, must not raise
