"""Network link/topology tests (SURVEY §2.4 network/)."""

import pytest

from happysim_tpu import (
    ConstantLatency,
    Network,
    NetworkLink,
    Simulation,
    Sink,
    Source,
    datacenter_network,
    local_network,
    lossy_network,
)
from happysim_tpu.core.callback_entity import CallbackEntity
from happysim_tpu.core.event import Event


def _net_sim(network, entities, duration, sources=None):
    return Simulation(
        sources=sources or [], entities=[network, *entities], duration=duration
    )


class TestNetworkLink:
    def test_latency_delays_delivery(self):
        sink = Sink("sink")
        link = NetworkLink("l", latency=ConstantLatency(0.25), egress=sink)
        sim = Simulation(entities=[link, sink], duration=10.0)
        sim.schedule(
            Event(
                time=0.0,
                event_type="pkt",
                target=link,
                context={"created_at": sim.now},
            )
        )
        sim.run()
        stats = sink.latency_stats()
        assert sink.events_received == 1
        assert stats.mean_s == pytest.approx(0.25)
        assert link.packets_sent == 1

    def test_bandwidth_adds_transmission_time(self):
        sink = Sink("sink")
        # 1 Mbps link, 125_000-byte payload = 1.0s transmission
        link = NetworkLink(
            "l", latency=ConstantLatency(0.0), bandwidth_bps=1_000_000, egress=sink
        )
        sim = Simulation(entities=[link, sink], duration=10.0)
        sim.schedule(
            Event(
                time=0.0,
                event_type="pkt",
                target=link,
                context={
                    "created_at": sim.now,
                    "metadata": {"payload_size": 125_000},
                },
            )
        )
        sim.run()
        assert sink.latency_stats().mean_s == pytest.approx(1.0)
        assert link.bytes_transmitted == 125_000

    def test_packet_loss_drops(self):
        sink = Sink("sink")
        link = NetworkLink(
            "l", latency=ConstantLatency(0.001), packet_loss_rate=1.0, egress=sink
        )
        sim = Simulation(entities=[link, sink], duration=1.0)
        sim.schedule(Event(time=0.0, event_type="pkt", target=link))
        sim.run()
        assert sink.events_received == 0
        assert link.packets_dropped == 1

    def test_seeded_loss_reproducible(self):
        def run(seed):
            sink = Sink("sink")
            link = lossy_network(0.5, seed=seed)
            link.egress = sink
            sim = Simulation(entities=[link, sink], duration=100.0)
            for i in range(100):
                sim.schedule(Event(time=float(i), event_type="pkt", target=link))
            sim.run()
            return link.packets_dropped

        assert run(7) == run(7)
        assert 20 < run(7) < 80

    def test_invalid_loss_rate(self):
        with pytest.raises(ValueError):
            NetworkLink("l", latency=ConstantLatency(0.0), packet_loss_rate=1.5)


class TestNetwork:
    def _build(self):
        a, b = Sink("a"), Sink("b")
        net = Network("net")
        net.add_bidirectional_link(a, b, datacenter_network())
        return net, a, b

    def test_routing_via_metadata(self):
        net, a, b = self._build()
        sim = Simulation(entities=[net, a, b], duration=1.0)
        sim.schedule(net.send(a, b, "msg"))
        sim.run()
        assert b.events_received == 1
        assert net.events_routed == 1

    def test_partition_drops_then_heals(self):
        net, a, b = self._build()
        sim = Simulation(entities=[net, a, b], duration=1.0)
        handle = net.partition([a], [b])
        assert net.is_partitioned("a", "b") and net.is_partitioned("b", "a")
        sim.schedule(net.send(a, b, "msg"))
        sim.run()
        assert b.events_received == 0
        assert net.events_dropped_partition == 1
        assert handle.is_active
        handle.heal()
        assert not net.is_partitioned("a", "b")
        assert not handle.is_active

    def test_asymmetric_partition(self):
        net, a, b = self._build()
        net.partition([a], [b], asymmetric=True)
        assert net.is_partitioned("a", "b")
        assert not net.is_partitioned("b", "a")

    def test_default_link_fallback(self):
        a, b = Sink("a"), Sink("b")
        net = Network("net", default_link=local_network())
        net._known_entities["a"] = a
        net._known_entities["b"] = b
        sim = Simulation(entities=[net, a, b], duration=1.0)
        sim.schedule(net.send(a, b, "msg"))
        sim.run()
        assert b.events_received == 1

    def test_missing_metadata_dropped(self):
        net, a, b = self._build()
        sim = Simulation(entities=[net, a, b], duration=1.0)
        sim.schedule(Event(time=0.0, event_type="msg", target=net))
        sim.run()
        assert net.events_dropped_no_route == 1

    def test_traffic_matrix(self):
        net, a, b = self._build()
        sim = Simulation(entities=[net, a, b], duration=1.0)
        sim.schedule(net.send(a, b, "msg"))
        sim.run()
        matrix = {(s.source, s.destination): s for s in net.traffic_matrix()}
        assert matrix[("a", "b")].packets_sent == 1
        assert matrix[("b", "a")].packets_sent == 0
