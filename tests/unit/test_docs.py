"""Docs stay honest: nav targets exist, snippets parse, API pages are
regenerable and match the package surface."""

import pathlib
import re

import pytest

ROOT = pathlib.Path(__file__).parent.parent.parent
DOCS = ROOT / "docs"


def _doc_files():
    return sorted(DOCS.rglob("*.md"))


def test_docs_exist():
    assert (DOCS / "index.md").exists()
    assert len(list((DOCS / "guides").glob("*.md"))) >= 10
    assert len(list((DOCS / "api").glob("*.md"))) >= 25


def test_mkdocs_nav_targets_exist():
    nav_paths = re.findall(r":\s*([\w\-/]+\.md)\s*$", (ROOT / "mkdocs.yml").read_text(), re.M)
    assert len(nav_paths) > 30
    for rel in nav_paths:
        assert (DOCS / rel).exists(), f"mkdocs nav points at missing {rel}"


@pytest.mark.parametrize(
    "path", _doc_files(), ids=[str(p.relative_to(DOCS)) for p in _doc_files()]
)
def test_python_snippets_parse(path):
    text = path.read_text()
    blocks = re.findall(r"```python\n(.*?)```", text, re.S)
    for i, block in enumerate(blocks):
        # Fragments referencing undefined names are fine; they must PARSE.
        # Blocks showing generator bodies use bare yields: retry wrapped.
        try:
            compile(block, f"{path.name}[{i}]", "exec")
        except SyntaxError:
            indented = "\n".join("    " + line for line in block.splitlines())
            try:
                compile(f"def _snippet():\n{indented}\n", f"{path.name}[{i}]", "exec")
            except SyntaxError as exc:
                pytest.fail(f"snippet {i} in {path.name} does not parse: {exc}")


def test_api_pages_mention_core_exports():
    core = (DOCS / "api" / "core.md").read_text()
    for name in ("Simulation", "Event", "EventHeap", "SimFuture", "Instant"):
        assert name in core
    consensus = (DOCS / "api" / "components-consensus.md").read_text()
    for name in ("RaftNode", "PaxosNode", "MultiPaxosNode", "DistributedLock"):
        assert name in consensus
