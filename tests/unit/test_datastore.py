"""Unit tests: datastore (KVStore, caches, sharding, replication, Database)."""

import pytest

from happysim_tpu import Entity, Event, Instant, Simulation
from happysim_tpu.components.datastore import (
    CacheWarmer,
    CachedStore,
    ClockEviction,
    ConsistencyLevel,
    ConsistentHashSharding,
    Database,
    FIFOEviction,
    HashSharding,
    KVStore,
    LFUEviction,
    LRUEviction,
    MultiTierCache,
    PromotionPolicy,
    RandomEviction,
    RangeSharding,
    ReplicatedStore,
    SLRUEviction,
    SampledLRUEviction,
    ShardedStore,
    SoftTTLCache,
    TTLEviction,
    TwoQueueEviction,
    WriteBack,
)


def t(seconds):
    return Instant.from_seconds(seconds)


class Driver(Entity):
    """Runs a scripted generator against stores inside a real simulation."""

    def __init__(self, name, script):
        super().__init__(name)
        self.script = script
        self.results = []
        self.done_at = None

    def handle_event(self, event):
        result = yield from self.script(self)
        self.results.append(result)
        self.done_at = self.now.to_seconds()


def run_script(script, entities, at=0.0, duration=300.0):
    driver = Driver("driver", script)
    sim = Simulation(entities=[driver, *entities], duration=duration)
    sim.schedule([Event(t(at), "go", target=driver)])
    sim.run()
    return driver


# ---------------------------------------------------------------- KVStore ----
class TestKVStore:
    def test_put_get_delete_with_latency(self):
        store = KVStore("kv", read_latency=0.001, write_latency=0.005)

        def script(self):
            yield from store.put("a", 1)
            value = yield from store.get("a")
            missing = yield from store.get("b")
            deleted = yield from store.delete("a")
            return (value, missing, deleted)

        driver = run_script(script, [store])
        assert driver.results == [(1, None, True)]
        # 0.005 (put) + 0.001*2 (gets) + 0.005 (delete)
        assert driver.done_at == pytest.approx(0.012)
        assert store.stats.hits == 1
        assert store.stats.misses == 1

    def test_capacity_fifo_eviction(self):
        store = KVStore("kv", capacity=2)
        store.put_sync("a", 1)
        store.put_sync("b", 2)
        store.put_sync("c", 3)
        assert store.size == 2
        assert not store.contains("a")  # FIFO: oldest out
        assert store.stats.evictions == 1


# ----------------------------------------------------- eviction policies ----
class TestEvictionPolicies:
    def _fill(self, policy, keys):
        for k in keys:
            policy.on_insert(k)

    def test_lru(self):
        p = LRUEviction()
        self._fill(p, ["a", "b", "c"])
        p.on_access("a")
        assert p.evict() == "b"

    def test_lfu_ties_break_fifo(self):
        p = LFUEviction()
        self._fill(p, ["a", "b", "c"])
        p.on_access("a")
        p.on_access("a")
        p.on_access("b")
        assert p.evict() == "c"  # least frequent
        assert p.evict() == "b"

    def test_fifo(self):
        p = FIFOEviction()
        self._fill(p, ["a", "b"])
        p.on_access("a")  # access is irrelevant
        assert p.evict() == "a"

    def test_ttl_prefers_expired(self):
        clock = {"t": 0.0}
        p = TTLEviction(ttl=10.0, clock_func=lambda: clock["t"])
        p.on_insert("old")
        clock["t"] = 20.0
        p.on_insert("new")
        assert p.is_expired("old")
        assert not p.is_expired("new")
        assert p.evict() == "old"

    def test_random_seeded(self):
        p1 = RandomEviction(seed=7)
        p2 = RandomEviction(seed=7)
        for p in (p1, p2):
            self._fill(p, [f"k{i}" for i in range(10)])
        assert [p1.evict() for _ in range(10)] == [p2.evict() for _ in range(10)]

    def test_slru_protects_reaccessed(self):
        p = SLRUEviction(protected_ratio=0.5)
        self._fill(p, ["a", "b", "c", "d"])
        p.on_access("a")  # a -> protected
        assert p.protected_size == 1
        assert p.evict() == "b"  # probationary first

    def test_sampled_lru_full_sample_is_exact(self):
        p = SampledLRUEviction(sample_size=100, seed=1)
        self._fill(p, ["a", "b", "c"])
        p.on_access("a")
        p.on_access("b")
        assert p.evict() == "c"

    def test_clock_second_chance(self):
        p = ClockEviction()
        self._fill(p, ["a", "b", "c"])
        # All bits set at insert; first sweep clears, second evicts in order.
        victim = p.evict()
        assert victim in {"a", "b", "c"}
        assert p.size == 2

    def test_two_queue_promotion(self):
        p = TwoQueueEviction(kin_ratio=0.5)
        self._fill(p, ["a", "b"])
        p.on_access("a")  # a -> main queue
        assert p.evict() == "b"  # one-hit-wonder washes out of kin
        assert p.evict() == "a"


# ------------------------------------------------------------ CachedStore ----
class TestCachedStore:
    def test_read_through_and_hit(self):
        backing = KVStore("kv", read_latency=0.010)
        cache = CachedStore("c", backing, cache_capacity=10,
                            eviction_policy=LRUEviction(), cache_read_latency=0.001)
        backing.put_sync("a", "val")

        def script(self):
            miss = yield from cache.get("a")  # reads through at 0.010
            hit = yield from cache.get("a")  # cache hit at 0.001
            return (miss, hit)

        driver = run_script(script, [backing, cache])
        assert driver.results == [("val", "val")]
        assert driver.done_at == pytest.approx(0.011)
        assert cache.stats.hits == 1
        assert cache.stats.misses == 1

    def test_lru_eviction_at_capacity(self):
        backing = KVStore("kv")
        cache = CachedStore("c", backing, cache_capacity=2, eviction_policy=LRUEviction())

        def script(self):
            yield from cache.put("a", 1)
            yield from cache.put("b", 2)
            yield from cache.get("a")  # a now MRU
            yield from cache.put("c", 3)  # evicts b
            return cache.get_cached_keys()

        driver = run_script(script, [backing, cache])
        assert sorted(driver.results[0]) == ["a", "c"]
        assert cache.stats.evictions == 1

    def test_write_back_flush(self):
        backing = KVStore("kv")
        cache = CachedStore("c", backing, cache_capacity=10,
                            eviction_policy=LRUEviction(), write_through=False)

        def script(self):
            yield from cache.put("a", 1)
            assert backing.get_sync("a") is None  # not yet written
            flushed = yield from cache.flush()
            return flushed

        driver = run_script(script, [backing, cache])
        assert driver.results == [1]
        assert backing.get_sync("a") == 1
        assert cache.stats.writebacks == 1


# --------------------------------------------------------- MultiTierCache ----
class TestMultiTierCache:
    def _build(self, promotion=PromotionPolicy.ALWAYS):
        backing = KVStore("kv", read_latency=0.100)
        l1_store = KVStore("l1kv", read_latency=0.0)
        l2_store = KVStore("l2kv", read_latency=0.0)
        l1 = CachedStore("l1", l1_store, cache_capacity=2,
                         eviction_policy=LRUEviction(), cache_read_latency=0.001)
        l2 = CachedStore("l2", l2_store, cache_capacity=10,
                         eviction_policy=LRUEviction(), cache_read_latency=0.010)
        mtc = MultiTierCache("mtc", [l1, l2], backing, promotion_policy=promotion)
        return mtc, l1, l2, backing, [l1_store, l2_store]

    def test_miss_populates_l1_then_hits(self):
        mtc, l1, l2, backing, extras = self._build()
        backing.put_sync("a", "v")

        def script(self):
            first = yield from mtc.get("a")  # backing: 0.100
            second = yield from mtc.get("a")  # l1: 0.001
            return (first, second)

        driver = run_script(script, [mtc, l1, l2, backing, *extras])
        assert driver.results == [("v", "v")]
        assert driver.done_at == pytest.approx(0.101)
        assert mtc.stats.tier_hits.get(0) == 1
        assert mtc.stats.backing_store_hits == 1

    def test_l2_hit_promotes_to_l1(self):
        mtc, l1, l2, backing, extras = self._build()
        l2._cache_put("a", "v")

        def script(self):
            value = yield from mtc.get("a")
            return value

        driver = run_script(script, [mtc, l1, l2, backing, *extras])
        assert driver.results == ["v"]
        assert l1.contains_cached("a")  # promoted
        assert mtc.stats.promotions == 1


# ------------------------------------------------------------ SoftTTLCache ----
class TestSoftTTLCache:
    def test_fresh_stale_hard_transitions(self):
        backing = KVStore("kv", read_latency=0.010)
        cache = SoftTTLCache("sttl", backing, soft_ttl=1.0, hard_ttl=5.0,
                             cache_read_latency=0.001)
        backing.put_sync("a", "v1")

        events = []

        class Reader(Entity):
            def handle_event(self, event):
                value = yield from cache.get("a")
                events.append((round(self.now.to_seconds(), 3), value))

        reader = Reader("reader")
        sim = Simulation(entities=[reader, cache, backing], duration=60.0)
        # t=0: hard miss; t=0.5: fresh; t=2: stale (refresh); t=10: hard miss
        for at in (0.0, 0.5, 2.0, 10.0):
            sim.schedule([Event(t(at), "go", target=reader)])
        sim.run()
        assert [v for _, v in events] == ["v1"] * 4
        assert cache.stats.hard_misses == 2
        assert cache.stats.fresh_hits == 1
        assert cache.stats.stale_hits == 1
        assert cache.stats.background_refreshes == 1
        assert cache.stats.refresh_successes == 1


# ------------------------------------------------------------ CacheWarmer ----
class TestCacheWarmer:
    def test_warms_at_rate(self):
        backing = KVStore("kv", read_latency=0.001)
        cache = CachedStore("c", backing, cache_capacity=100,
                            eviction_policy=LRUEviction())
        for i in range(5):
            backing.put_sync(f"k{i}", i)
        warmer = CacheWarmer("w", cache, [f"k{i}" for i in range(5)], warmup_rate=10.0)
        sim = Simulation(entities=[warmer, cache, backing], duration=60.0)
        sim.schedule([warmer.start_warming(at=t(0.0))])
        sim.run()
        assert warmer.is_complete
        assert warmer.stats.keys_warmed == 5
        assert cache.cache_size == 5
        # 5 keys at 10/s -> ~0.5s (plus fetch latencies)
        assert warmer.stats.warmup_time_seconds == pytest.approx(0.505, abs=0.01)


# ------------------------------------------------------------ ShardedStore ----
class TestShardedStore:
    def test_keys_route_consistently(self):
        shards = [KVStore(f"s{i}") for i in range(4)]
        store = ShardedStore("sharded", shards, HashSharding())

        def script(self):
            for i in range(20):
                yield from store.put(f"key{i}", i)
            values = []
            for i in range(20):
                v = yield from store.get(f"key{i}")
                values.append(v)
            return values

        driver = run_script(script, [store, *shards])
        assert driver.results == [list(range(20))]
        # All shards touched (20 hashed keys over 4 shards)
        assert sum(1 for v in store.stats.shard_writes.values() if v > 0) >= 3
        total_stored = sum(s.size for s in shards)
        assert total_stored == 20

    def test_range_sharding_with_boundaries(self):
        strategy = RangeSharding(boundaries=["g", "p"])
        assert strategy.get_shard("apple", 3) == 0
        assert strategy.get_shard("mango", 3) == 1
        assert strategy.get_shard("zebra", 3) == 2

    def test_consistent_hash_minimal_remap(self):
        strategy = ConsistentHashSharding(virtual_nodes=100, seed=1)
        keys = [f"key{i}" for i in range(200)]
        before = {k: strategy.get_shard(k, 4) for k in keys}
        strategy2 = ConsistentHashSharding(virtual_nodes=100, seed=1)
        after = {k: strategy2.get_shard(k, 5) for k in keys}
        moved = sum(1 for k in keys if before[k] != after[k])
        # Consistent hashing moves ~1/5 of keys; mod-hash would move ~4/5.
        assert moved < len(keys) * 0.45


# --------------------------------------------------------- ReplicatedStore ----
class TestReplicatedStore:
    def test_quorum_read_write(self):
        replicas = [KVStore(f"r{i}", read_latency=0.001, write_latency=0.002)
                    for i in range(3)]
        store = ReplicatedStore("repl", replicas,
                                read_consistency=ConsistencyLevel.QUORUM,
                                write_consistency=ConsistencyLevel.QUORUM)
        assert store.quorum_size == 2

        def script(self):
            ok = yield from store.put("a", "v")
            value = yield from store.get("a")
            return (ok, value)

        driver = run_script(script, [store, *replicas])
        assert driver.results == [(True, "v")]
        assert all(r.get_sync("a") == "v" for r in replicas)
        assert store.stats.write_successes == 1
        assert store.stats.read_successes == 1

    def test_read_one_stops_early(self):
        replicas = [KVStore(f"r{i}", read_latency=0.010) for i in range(3)]
        replicas[0].put_sync("a", "v")
        store = ReplicatedStore("repl", replicas,
                                read_consistency=ConsistencyLevel.ONE)

        def script(self):
            value = yield from store.get("a")
            return value

        driver = run_script(script, [store, *replicas])
        assert driver.results == ["v"]
        assert driver.done_at == pytest.approx(0.010)  # only one replica read
        assert replicas[1].stats.reads == 0


# ---------------------------------------------------------------- Database ----
class TestDatabase:
    def test_execute_and_latency(self):
        db = Database("db", query_latency=0.005, connection_latency=0.001)

        def script(self):
            rows = yield from db.execute("SELECT * FROM users")
            result = yield from db.execute("INSERT INTO users VALUES (1)")
            return (rows, result)

        driver = run_script(script, [db])
        assert driver.results == [([], {"affected_rows": 1})]
        assert db.stats.queries_executed == 2
        assert driver.done_at == pytest.approx(0.012)

    def test_transaction_commit_and_rollback(self):
        db = Database("db")

        def script(self):
            tx = yield from db.begin_transaction()
            yield from tx.execute("INSERT INTO t VALUES (1)")
            yield from tx.commit()
            tx2 = yield from db.begin_transaction()
            yield from tx2.execute("UPDATE t SET x=2")
            yield from tx2.rollback()
            return (tx.state.value, tx2.state.value)

        driver = run_script(script, [db])
        assert driver.results == [("committed", "rolled_back")]
        assert db.stats.transactions_committed == 1
        assert db.stats.transactions_rolled_back == 1
        assert db.active_connections == 0  # all released

    def test_connection_pool_exhaustion_waits(self):
        db = Database("db", max_connections=1, query_latency=1.0,
                      connection_latency=0.0)
        done = []

        class Querier(Entity):
            def handle_event(self, event):
                yield from db.execute("SELECT 1")
                done.append((self.name, round(self.now.to_seconds(), 3)))

        q1, q2 = Querier("q1"), Querier("q2")
        sim = Simulation(entities=[db, q1, q2], duration=60.0)
        sim.schedule([Event(t(0.0), "go", target=q1), Event(t(0.0), "go", target=q2)])
        sim.run()
        assert done == [("q1", 1.0), ("q2", 2.0)]  # serialized on 1 conn
        assert db.stats.connection_wait_count == 1
        assert db.stats.connection_wait_time_total == pytest.approx(1.0)
