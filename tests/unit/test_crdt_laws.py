"""CRDT algebraic laws: merge must be commutative, associative, and
idempotent, and every replica schedule must converge.

These laws ARE the correctness contract of state-based CRDTs — a merge
that violates any of them diverges silently under gossip reordering or
redelivery. Exercised with randomized op schedules over every CRDT type.

Reference analogue: the per-type unit files
``happysimulator/tests/unit/test_g_counter.py`` / ``test_pn_counter.py`` /
``test_lww_register.py`` / ``test_or_set.py`` (directed cases); this file
adds the law-based randomized coverage.
"""

from __future__ import annotations

import random

import pytest

from happysim_tpu.components.crdt import GCounter, LWWRegister, ORSet, PNCounter


def clone(crdt):
    """Deep copy through the wire format (also exercises serialization)."""
    return type(crdt).from_dict(crdt.to_dict())


def make(kind: str, node_id: str):
    return {
        "g_counter": GCounter,
        "pn_counter": PNCounter,
        "lww": LWWRegister,
        "or_set": ORSet,
    }[kind](node_id)


def random_ops(crdt, rng: random.Random, n_ops: int = 12) -> None:
    """Apply a random local-op schedule appropriate to the type."""
    if isinstance(crdt, GCounter):
        for _ in range(n_ops):
            crdt.increment(rng.randint(1, 5))
    elif isinstance(crdt, PNCounter):
        for _ in range(n_ops):
            if rng.random() < 0.6:
                crdt.increment(rng.randint(1, 5))
            else:
                crdt.decrement(rng.randint(1, 3))
    elif isinstance(crdt, LWWRegister):
        for _ in range(n_ops):
            crdt.set(rng.randint(0, 99), timestamp=rng.randint(1, 50))
    elif isinstance(crdt, ORSet):
        for _ in range(n_ops):
            element = f"e{rng.randint(0, 5)}"
            if rng.random() < 0.65:
                crdt.add(element)
            else:
                crdt.remove(element)
    else:  # pragma: no cover
        raise AssertionError(type(crdt))


def observed(crdt):
    """The convergent observable state (value; ORSet: the element set)."""
    if isinstance(crdt, ORSet):
        return crdt.value
    if isinstance(crdt, LWWRegister):
        return (crdt.value, crdt.timestamp)
    return crdt.value


KINDS = ["g_counter", "pn_counter", "lww", "or_set"]


@pytest.mark.parametrize("kind", KINDS)
@pytest.mark.parametrize("seed", range(4))
class TestMergeLaws:
    def _two(self, kind, seed):
        rng = random.Random(seed)
        a, b = make(kind, "A"), make(kind, "B")
        random_ops(a, rng)
        random_ops(b, rng)
        return a, b

    def test_commutative(self, kind, seed):
        a, b = self._two(kind, seed)
        ab, ba = clone(a), clone(b)
        ab.merge(clone(b))
        ba.merge(clone(a))
        assert observed(ab) == observed(ba)

    def test_associative(self, kind, seed):
        a, b = self._two(kind, seed)
        c = make(kind, "C")
        random_ops(c, random.Random(seed + 100))
        left = clone(a)
        left.merge(clone(b))
        left.merge(clone(c))
        bc = clone(b)
        bc.merge(clone(c))
        right = clone(a)
        right.merge(bc)
        assert observed(left) == observed(right)

    def test_idempotent(self, kind, seed):
        a, _ = self._two(kind, seed)
        merged = clone(a)
        merged.merge(clone(a))
        assert observed(merged) == observed(a)
        merged.merge(clone(a))  # re-delivery of the same state
        assert observed(merged) == observed(a)

    def test_serialization_roundtrip_preserves_state(self, kind, seed):
        a, _ = self._two(kind, seed)
        assert observed(clone(a)) == observed(a)


@pytest.mark.parametrize("kind", KINDS)
@pytest.mark.parametrize("seed", range(3))
def test_replicas_converge_under_random_gossip(kind, seed):
    """N replicas, random ops, then enough random pairwise merges that the
    union of states reaches everyone: all observables must agree."""
    rng = random.Random(seed)
    replicas = [make(kind, f"N{i}") for i in range(4)]
    for replica in replicas:
        random_ops(replica, rng)
    # Random gossip until closure, then a deterministic full round so
    # every replica has definitely absorbed every other.
    for _ in range(12):
        i, j = rng.sample(range(4), 2)
        replicas[i].merge(clone(replicas[j]))
    for i in range(4):
        for j in range(4):
            if i != j:
                replicas[i].merge(clone(replicas[j]))
    first = observed(replicas[0])
    for replica in replicas[1:]:
        assert observed(replica) == first, (
            f"replicas diverged: {observed(replica)!r} != {first!r}"
        )


class TestORSetSemantics:
    def test_add_wins_over_concurrent_remove(self):
        a, b = ORSet("A"), ORSet("B")
        a.add("x")
        b.merge(clone(a))
        # Concurrently: A removes x (observing its tag), B re-adds x.
        a.remove("x")
        b.add("x")
        a.merge(clone(b))
        b.merge(clone(a))
        assert "x" in a.value and "x" in b.value  # the unseen add survives

    def test_observed_remove_holds_without_concurrent_add(self):
        a, b = ORSet("A"), ORSet("B")
        a.add("x")
        b.merge(clone(a))
        b.remove("x")
        a.merge(clone(b))
        assert "x" not in a.value and "x" not in b.value

    def test_re_add_after_remove_is_visible(self):
        a = ORSet("A")
        a.add("x")
        a.remove("x")
        a.add("x")
        assert a.contains("x")

    def test_remove_unseen_element_is_noop(self):
        a = ORSet("A")
        a.remove("ghost")
        assert a.value == frozenset()

    def test_tag_counter_survives_roundtrip(self):
        """from_dict must resume tagging past existing own tags, or a
        restored replica mints tags that collide with its tombstones and
        fresh adds get silently deleted."""
        a = ORSet("A")
        a.add("x")
        a.remove("x")
        restored = clone(a)
        restored.add("x")
        assert restored.contains("x")


class TestLWWSemantics:
    def test_higher_timestamp_wins(self):
        a, b = LWWRegister("A"), LWWRegister("B")
        a.set("old", timestamp=1)
        b.set("new", timestamp=2)
        a.merge(clone(b))
        assert a.value == "new"

    def test_lower_timestamp_loses_even_if_merged_later(self):
        a, b = LWWRegister("A"), LWWRegister("B")
        a.set("winner", timestamp=9)
        b.set("loser", timestamp=3)
        a.merge(clone(b))
        assert a.value == "winner"

    def test_equal_timestamp_tiebreak_is_symmetric(self):
        """Concurrent same-timestamp writes must converge to the SAME
        winner on both replicas (writer-id ordering), whichever side
        merges first."""
        a, b = LWWRegister("A"), LWWRegister("B")
        a.set("from_a", timestamp=5)
        b.set("from_b", timestamp=5)
        a.merge(clone(b))
        b.merge(clone(a))
        assert a.value == b.value

    def test_unset_register_adopts_any_write(self):
        a, b = LWWRegister("A"), LWWRegister("B")
        b.set(42, timestamp=1)
        a.merge(clone(b))
        assert a.value == 42


class TestCounterSemantics:
    def test_gcounter_merge_takes_per_node_max(self):
        a, b = GCounter("A"), GCounter("B")
        a.increment(3)
        b.merge(clone(a))  # b sees A=3
        a.increment(2)  # A=5 locally
        b.increment(7)  # B=7
        a.merge(clone(b))
        assert a.value == 12  # max(A)=5 + max(B)=7, no double count
        assert a.node_value("A") == 5 and a.node_value("B") == 7

    def test_gcounter_rejects_negative(self):
        counter = GCounter("A")
        with pytest.raises(ValueError):
            counter.increment(-1)

    def test_pncounter_value_can_go_negative(self):
        counter = PNCounter("A")
        counter.decrement(5)
        counter.increment(2)
        assert counter.value == -3
        assert counter.increments == 2 and counter.decrements == 5

    def test_pncounter_concurrent_inc_dec_all_count(self):
        a, b = PNCounter("A"), PNCounter("B")
        a.increment(10)
        b.decrement(4)
        a.merge(clone(b))
        b.merge(clone(a))
        assert a.value == b.value == 6
