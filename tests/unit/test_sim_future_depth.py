"""SimFuture settle-semantics edges: rejection, cancellation, combinator
races, idempotent settling, and callback one-shot firing.

These pin the contracts the sync primitives and resilience components
build on (any_of timeout races, Barrier aborts via reject, cancel-after-
lost-race). Complements the happy paths in ``test_sim_future.py``.

Parity target: the reference's future/condition wake semantics
(``happysimulator/core/simulation.py`` waiter hand-off).
"""

from __future__ import annotations

import pytest

from happysim_tpu import Instant, Simulation, Sink
from happysim_tpu.core.event import Event
from happysim_tpu.core.sim_future import (
    CancelledError,
    SimFuture,
    all_of,
    any_of,
)


def run_process(gen_fn, duration=5.0):
    """Run a one-shot generator handler inside a real simulation."""

    class Host(Sink):
        def handle_event(self, event):
            if event.event_type == "kick":
                return gen_fn(self)
            return super().handle_event(event)

    host = Host("host")
    sim = Simulation(entities=[host], end_time=Instant.from_seconds(duration))
    sim.schedule(Event(Instant.from_seconds(0.0), "kick", target=host))
    sim.run()
    return sim


class TestSettleIdempotence:
    def test_resolve_twice_keeps_first_value(self):
        future = SimFuture()
        outcome = []

        def process(host):
            value = yield future, []
            outcome.append(value)

        def kicker(host):
            future.resolve("first")
            future.resolve("second")
            return None
            yield  # pragma: no cover

        class Host(Sink):
            def handle_event(self, event):
                if event.event_type == "wait":
                    return process(self)
                if event.event_type == "kick":
                    future.resolve("first")
                    future.resolve("second")
                return None

        host = Host("h")
        sim = Simulation(entities=[host], end_time=Instant.from_seconds(1.0))
        sim.schedule(Event(Instant.from_seconds(0.0), "wait", target=host))
        sim.schedule(Event(Instant.from_seconds(0.1), "kick", target=host))
        sim.run()
        assert outcome == ["first"]

    def test_cancel_after_resolve_is_noop(self):
        future = SimFuture()

        class Host(Sink):
            def handle_event(self, event):
                future.resolve(42)
                future.cancel()
                return None

        host = Host("h")
        sim = Simulation(entities=[host], end_time=Instant.from_seconds(1.0))
        sim.schedule(Event(Instant.from_seconds(0.0), "kick", target=host))
        sim.run()
        assert future.value == 42
        assert not future.is_cancelled

    def test_resolve_after_cancel_is_noop(self):
        future = SimFuture()

        class Host(Sink):
            def handle_event(self, event):
                future.cancel()
                future.resolve(42)
                return None

        host = Host("h")
        sim = Simulation(entities=[host], end_time=Instant.from_seconds(1.0))
        sim.schedule(Event(Instant.from_seconds(0.0), "kick", target=host))
        sim.run()
        assert future.is_cancelled
        with pytest.raises(CancelledError):
            _ = future.value


class TestValueAccess:
    def test_value_before_resolution_raises(self):
        with pytest.raises(RuntimeError, match="before resolution"):
            _ = SimFuture().value

    def test_rejected_value_raises_original_error(self):
        future = SimFuture()

        class Host(Sink):
            def handle_event(self, event):
                future.reject(ValueError("boom"))
                return None

        host = Host("h")
        sim = Simulation(entities=[host], end_time=Instant.from_seconds(1.0))
        sim.schedule(Event(Instant.from_seconds(0.0), "kick", target=host))
        sim.run()
        assert isinstance(future.error, ValueError)
        with pytest.raises(ValueError, match="boom"):
            _ = future.value

    def test_resolve_outside_sim_with_parked_process_raises(self):
        future = SimFuture()
        # No active sim context at all: plain resolve without a parked
        # process succeeds (value-only future)...
        future2 = SimFuture()
        future2.resolve(1)
        assert future2.value == 1
        # ...but waking a parked continuation requires the sim loop.
        outcome = []

        def process(host):
            outcome.append((yield future, []))

        class Host(Sink):
            def handle_event(self, event):
                return process(self)

        host = Host("h")
        sim = Simulation(entities=[host], end_time=Instant.from_seconds(1.0))
        sim.schedule(Event(Instant.from_seconds(0.0), "kick", target=host))
        sim.run()
        with pytest.raises(RuntimeError, match="outside a running simulation"):
            future.resolve("too late")


class TestRejectionIntoGenerator:
    def test_reject_raises_at_the_yield(self):
        caught = []

        def process(host):
            future = SimFuture()
            wake = Event.once(
                Instant.from_seconds(0.5),
                lambda: future.reject(RuntimeError("barrier broke")),
            )
            try:
                yield future, [wake]
            except RuntimeError as exc:
                caught.append(str(exc))
            return None

        run_process(process)
        assert caught == ["barrier broke"]

    def test_cancel_raises_cancelled_error_at_the_yield(self):
        caught = []

        def process(host):
            future = SimFuture()
            wake = Event.once(Instant.from_seconds(0.5), future.cancel)
            try:
                yield future, [wake]
            except CancelledError:
                caught.append("cancelled")
            return None

        run_process(process)
        assert caught == ["cancelled"]


class TestCombinators:
    def test_any_of_loser_settling_later_changes_nothing(self):
        results = []

        def process(host):
            fast, slow = SimFuture(), SimFuture()
            e_fast = Event.once(Instant.from_seconds(0.1), lambda: fast.resolve("fast"))
            e_slow = Event.once(Instant.from_seconds(0.9), lambda: slow.resolve("slow"))
            index, value = yield any_of(fast, slow), [e_fast, e_slow]
            results.append((index, value))
            return None

        run_process(process)
        assert results == [(0, "fast")]

    def test_any_of_with_rejection_settles_with_error_entry(self):
        results = []

        def process(host):
            bad, good = SimFuture(), SimFuture()
            e_bad = Event.once(
                Instant.from_seconds(0.1), lambda: bad.reject(ValueError("dead"))
            )
            combined = any_of(bad, good)
            try:
                yield combined, [e_bad]
                results.append("no raise")
            except ValueError:
                results.append("raised")
            return None

        run_process(process)
        # Either contract is defensible, but it must be DETERMINISTIC:
        # the combined future settles from the first settler (the
        # rejection) — the error propagates to the waiter.
        assert results == ["raised"]

    def test_all_of_collects_in_argument_order(self):
        results = []

        def process(host):
            a, b = SimFuture(), SimFuture()
            # b resolves FIRST, a second; values must still arrive [a, b].
            e_b = Event.once(Instant.from_seconds(0.1), lambda: b.resolve("bee"))
            e_a = Event.once(Instant.from_seconds(0.2), lambda: a.resolve("ay"))
            values = yield all_of(a, b), [e_a, e_b]
            results.append(values)
            return None

        run_process(process)
        assert results == [["ay", "bee"]]

    def test_all_of_single_future(self):
        results = []

        def process(host):
            only = SimFuture()
            e = Event.once(Instant.from_seconds(0.1), lambda: only.resolve(7))
            results.append((yield all_of(only), [e]))
            return None

        run_process(process)
        assert results == [[7]]


class TestParkContract:
    def test_double_await_rejected(self):
        """Two generators awaiting one future is a wiring bug; the park
        happens in the ENGINE (not at the yield), so the error surfaces
        from the run loop rather than inside the second generator."""
        future = SimFuture()

        def first(host):
            yield future, []

        def second(host):
            yield future, []

        class Host(Sink):
            def handle_event(self, event):
                return first(self) if event.event_type == "one" else second(self)

        host = Host("h")
        sim = Simulation(entities=[host], end_time=Instant.from_seconds(1.0))
        sim.schedule(Event(Instant.from_seconds(0.0), "one", target=host))
        sim.schedule(Event(Instant.from_seconds(0.1), "two", target=host))
        with pytest.raises(RuntimeError, match="parked process"):
            sim.run()
