"""Unit tests: messaging (MessageQueue/DeadLetterQueue/Topic).

Mirrors the reference's coverage for messaging components using tiny real
simulations (SURVEY.md §4).
"""

import pytest

from happysim_tpu import Entity, Event, Instant, Simulation
from happysim_tpu.components.messaging import (
    DeadLetterQueue,
    MessageQueue,
    MessageState,
    Topic,
)


def t(seconds):
    return Instant.from_seconds(seconds)


class AckingConsumer(Entity):
    """Processes each delivery for work_s, then acks."""

    def __init__(self, name, queue, work_s=0.01):
        super().__init__(name)
        self.queue = queue
        self.work_s = work_s
        self.received = []

    def handle_event(self, event):
        if event.event_type != "message_delivery":
            return None
        meta = event.context["metadata"]
        self.received.append((meta["message_id"], self.now.to_seconds()))
        yield self.work_s
        self.queue.acknowledge(meta["message_id"])


class NackingConsumer(Entity):
    """Rejects the first fail_times deliveries of each message, then acks."""

    def __init__(self, name, queue, fail_times=1, requeue=True):
        super().__init__(name)
        self.queue = queue
        self.fail_times = fail_times
        self.requeue = requeue
        self.attempts = {}

    def handle_event(self, event):
        if event.event_type != "message_delivery":
            return None
        meta = event.context["metadata"]
        mid = meta["message_id"]
        self.attempts[mid] = self.attempts.get(mid, 0) + 1
        if self.attempts[mid] <= self.fail_times:
            return self.queue.reject(mid, requeue=self.requeue)
        self.queue.acknowledge(mid)
        return None


class Producer(Entity):
    def __init__(self, name, queue, n=1):
        super().__init__(name)
        self.queue = queue
        self.n = n
        self.ids = []

    def handle_event(self, event):
        produced = []
        for i in range(self.n):
            payload = Event(self.now, "order", target=self.queue)
            produced.extend(self.queue.publish(payload))
        return produced or None


def _run(entities, starts, duration=60.0):
    sim = Simulation(entities=entities, duration=duration)
    sim.schedule([Event(t(at), "go", target=e) for at, e in starts])
    sim.run()
    return sim


# ----------------------------------------------------------- MessageQueue ----
class TestMessageQueue:
    def test_publish_deliver_ack_roundtrip(self):
        mq = MessageQueue("orders", delivery_latency=0.005)
        consumer = AckingConsumer("c", mq)
        mq.subscribe(consumer)
        producer = Producer("p", mq, n=3)
        _run([mq, consumer, producer], [(0.0, producer)])
        assert len(consumer.received) == 3
        assert mq.stats.messages_published == 3
        assert mq.stats.messages_delivered == 3
        assert mq.stats.messages_acknowledged == 3
        assert mq.pending_count == 0
        assert mq.in_flight_count == 0
        assert mq.stats.ack_rate == 1.0

    def test_round_robin_across_consumers(self):
        mq = MessageQueue("orders")
        c1 = AckingConsumer("c1", mq)
        c2 = AckingConsumer("c2", mq)
        mq.subscribe(c1)
        mq.subscribe(c2)
        producer = Producer("p", mq, n=4)
        _run([mq, c1, c2, producer], [(0.0, producer)])
        assert len(c1.received) == 2
        assert len(c2.received) == 2

    def test_reject_requeues_until_max_then_dlq(self):
        dlq = DeadLetterQueue("dlq")
        mq = MessageQueue("orders", max_redeliveries=2, dead_letter_queue=dlq)
        consumer = NackingConsumer("c", mq, fail_times=99)  # always fails
        mq.subscribe(consumer)
        producer = Producer("p", mq, n=1)
        _run([mq, dlq, consumer, producer], [(0.0, producer)])
        # Delivered twice (max_redeliveries=2), then dead-lettered.
        mid = next(iter(consumer.attempts))
        assert consumer.attempts[mid] == 2
        assert mq.stats.messages_dead_lettered == 1
        assert dlq.message_count == 1
        assert dlq.peek().delivery_count == 2

    def test_reject_then_success(self):
        mq = MessageQueue("orders", max_redeliveries=3)
        consumer = NackingConsumer("c", mq, fail_times=1)
        mq.subscribe(consumer)
        producer = Producer("p", mq, n=1)
        _run([mq, consumer, producer], [(0.0, producer)])
        assert mq.stats.messages_acknowledged == 1
        assert mq.stats.messages_rejected == 1
        assert mq.stats.messages_redelivered == 1

    def test_visibility_timeout_redelivers_unacked(self):
        """A consumer that never acks gets the message redelivered after
        redelivery_delay, automatically."""
        mq = MessageQueue("orders", redelivery_delay=1.0, max_redeliveries=3)

        class SilentConsumer(Entity):
            def __init__(self):
                super().__init__("silent")
                self.delivery_times = []

            def handle_event(self, event):
                if event.event_type == "message_delivery":
                    self.delivery_times.append(round(self.now.to_seconds(), 3))
                return None  # never acks

        consumer = SilentConsumer()
        mq.subscribe(consumer)
        producer = Producer("p", mq, n=1)
        _run([mq, consumer, producer], [(0.0, producer)], duration=10.0)
        # Initial delivery + redeliveries spaced ~1s apart.
        assert len(consumer.delivery_times) >= 2
        assert consumer.delivery_times[1] - consumer.delivery_times[0] == pytest.approx(
            1.0, abs=0.1
        )

    def test_ack_cancels_visibility_timer(self):
        mq = MessageQueue("orders", redelivery_delay=1.0)
        consumer = AckingConsumer("c", mq)
        mq.subscribe(consumer)
        producer = Producer("p", mq, n=1)
        _run([mq, consumer, producer], [(0.0, producer)], duration=10.0)
        assert len(consumer.received) == 1  # no spurious redelivery
        assert mq.stats.messages_redelivered == 0

    def test_capacity_limit(self):
        mq = MessageQueue("orders", capacity=2)
        payload = Event(t(0), "x", target=mq)
        mq.publish(payload)
        mq.publish(payload)
        assert mq.is_full
        with pytest.raises(RuntimeError):
            mq.publish(payload)

    def test_no_consumers_messages_wait(self):
        mq = MessageQueue("orders")
        producer = Producer("p", mq, n=2)
        _run([mq, producer], [(0.0, producer)], duration=5.0)
        assert mq.pending_count == 2
        assert mq.stats.messages_delivered == 0


# ------------------------------------------------------------------- DLQ ----
class TestDeadLetterQueue:
    def _dead_letter_one(self, dlq):
        mq = MessageQueue("orders", max_redeliveries=1, dead_letter_queue=dlq)
        consumer = NackingConsumer("c", mq, fail_times=99)
        mq.subscribe(consumer)
        producer = Producer("p", mq, n=1)
        _run([mq, dlq, consumer, producer], [(0.0, producer)])
        return mq

    def test_capacity_evicts_oldest(self):
        dlq = DeadLetterQueue("dlq", capacity=2)
        mq = MessageQueue("orders", max_redeliveries=1, dead_letter_queue=dlq)
        consumer = NackingConsumer("c", mq, fail_times=99)
        mq.subscribe(consumer)
        producer = Producer("p", mq, n=3)
        _run([mq, dlq, consumer, producer], [(0.0, producer)])
        assert dlq.message_count == 2
        assert dlq.stats.messages_received == 3
        assert dlq.stats.messages_discarded == 1

    def test_reprocess_republishes(self):
        dlq = DeadLetterQueue("dlq")
        mq = self._dead_letter_one(dlq)
        assert dlq.message_count == 1

        # Second phase: consumer now succeeds; reprocess the dead letter.
        fixed_consumer = AckingConsumer("fixed", mq)
        mq._consumers = []
        mq.subscribe(fixed_consumer)

        class Operator(Entity):
            def handle_event(self, event):
                return dlq.reprocess_all(mq)

        operator = Operator("op")
        _run([mq, dlq, fixed_consumer, operator], [(0.0, operator)])
        assert dlq.message_count == 0
        assert dlq.stats.messages_reprocessed == 1
        assert len(fixed_consumer.received) == 1

    def test_pop_peek_clear(self):
        dlq = DeadLetterQueue("dlq")
        self._dead_letter_one(dlq)
        assert dlq.peek() is not None
        msg = dlq.pop()
        assert msg.state == MessageState.REJECTED
        assert dlq.message_count == 0
        assert dlq.pop() is None
        self._dead_letter_one(DeadLetterQueue("other"))  # unrelated
        assert dlq.clear() == 0


# ----------------------------------------------------------------- Topic ----
class TestTopic:
    def test_broadcast_to_all_subscribers(self):
        topic = Topic("events", delivery_latency=0.01)

        class Listener(Entity):
            def __init__(self, name):
                super().__init__(name)
                self.got = []

            def handle_event(self, event):
                if event.event_type == "topic_message":
                    self.got.append(round(self.now.to_seconds(), 4))
                return None

        l1, l2 = Listener("l1"), Listener("l2")
        topic.subscribe(l1)
        topic.subscribe(l2)

        class Publisher(Entity):
            def handle_event(self, event):
                return topic.publish(Event(self.now, "news", target=topic))

        pub = Publisher("pub")
        _run([topic, l1, l2, pub], [(1.0, pub)])
        assert l1.got == [1.01]
        assert l2.got == [1.01]
        assert topic.stats.messages_published == 1
        assert topic.stats.messages_delivered == 2

    def test_unsubscribe_stops_delivery(self):
        topic = Topic("events")
        sink_counts = {"a": 0}

        class L(Entity):
            def handle_event(self, event):
                sink_counts["a"] += 1
                return None

        listener = L("l")
        topic.subscribe(listener)
        topic.unsubscribe(listener)

        class Publisher(Entity):
            def handle_event(self, event):
                return topic.publish(Event(self.now, "news", target=topic)) or None

        pub = Publisher("pub")
        _run([topic, listener, pub], [(0.0, pub)])
        assert sink_counts["a"] == 0
        assert topic.subscriber_count == 0

    def test_history_replay_for_late_subscriber(self):
        topic = Topic("events")
        topic.set_retain_messages(True)

        class Listener(Entity):
            def __init__(self, name):
                super().__init__(name)
                self.replays = 0

            def handle_event(self, event):
                if event.event_type == "topic_message":
                    if event.context["metadata"]["is_replay"]:
                        self.replays += 1
                return None

        early_payloads = [Event(t(0), f"m{i}", target=topic) for i in range(3)]
        for p in early_payloads:
            topic.publish(p)  # outside sim: history only
        late = Listener("late")

        class Joiner(Entity):
            def handle_event(self, event):
                return topic.subscribe(late, replay_history=True) or None

        joiner = Joiner("joiner")
        _run([topic, late, joiner], [(5.0, joiner)])
        assert late.replays == 3

    def test_max_subscribers(self):
        topic = Topic("events", max_subscribers=1)
        topic.subscribe(Entity.__new__(Entity) if False else _dummy("a"))
        with pytest.raises(RuntimeError):
            topic.subscribe(_dummy("b"))


def _dummy(name):
    class D(Entity):
        def handle_event(self, event):
            return None

    return D(name)


class TestMessageQueueReviewRegressions:
    def test_reject_with_dropped_return_still_redelivers(self):
        """A consumer that calls reject() and drops the returned events must
        not stall the message (kick is self-scheduled in-sim)."""
        mq = MessageQueue("orders", max_redeliveries=5, redelivery_delay=1.0)

        class DropReturnConsumer(Entity):
            def __init__(self):
                super().__init__("drc")
                self.deliveries = 0

            def handle_event(self, event):
                if event.event_type != "message_delivery":
                    return None
                self.deliveries += 1
                mid = event.context["metadata"]["message_id"]
                if self.deliveries < 3:
                    mq.reject(mid)  # return value dropped on the floor
                    return None
                mq.acknowledge(mid)
                return None

        consumer = DropReturnConsumer()
        mq.subscribe(consumer)
        producer = Producer("p", mq, n=1)
        _run([mq, consumer, producer], [(0.0, producer)], duration=30.0)
        assert consumer.deliveries == 3
        assert mq.stats.messages_acknowledged == 1
        assert mq.pending_count == 0

    def test_redelivery_timer_after_kick_does_not_duplicate(self):
        """schedule_redelivery + a later publish-kick must deliver the
        requeued message exactly once."""
        mq = MessageQueue("orders", redelivery_delay=5.0, auto_redelivery=False)
        seen = []

        class Recorder(Entity):
            def handle_event(self, event):
                if event.event_type == "message_delivery":
                    seen.append(
                        (event.context["metadata"]["message_id"],
                         round(self.now.to_seconds(), 3))
                    )
                return None

        consumer = Recorder("rec")
        mq.subscribe(consumer)

        class Script(Entity):
            def handle_event(self, event):
                produced = list(mq.publish(Event(self.now, "m1", target=mq)))
                yield 0.1
                # m1 delivered; manually requeue it with a 5s timer...
                mid = seen[0][0]
                redeliver = mq.schedule_redelivery(mid)
                # ...then publish m2, whose kick would poll m1 early.
                produced2 = list(mq.publish(Event(self.now, "m2", target=mq)))
                return [*produced, *( [redeliver] if redeliver else [] ), *produced2]

        script = Script("script")
        _run([mq, consumer, script], [(0.0, script)], duration=30.0)
        m1_deliveries = [s for s in seen if s[0].endswith("-1")]
        # m1: initial delivery + exactly ONE redelivery (no timer duplicate).
        assert len(m1_deliveries) == 2

    def test_direct_poll_arms_visibility_timer(self):
        """Pull-style consumption also gets unacked-redelivery protection."""
        mq = MessageQueue("orders", redelivery_delay=1.0, max_redeliveries=2)
        deliveries = []

        class Sink(Entity):
            def handle_event(self, event):
                if event.event_type == "message_delivery":
                    deliveries.append(round(self.now.to_seconds(), 3))
                return None

        sink = Sink("sink")
        mq.subscribe(sink)
        mq.unsubscribe  # noqa: B018 — keep subscribed; pull still uses consumer list

        class Puller(Entity):
            def handle_event(self, event):
                mq.publish(Event(self.now, "m", target=mq))
                delivery = mq.poll()
                return [delivery] if delivery else None

        puller = Puller("puller")
        _run([mq, sink, puller], [(0.0, puller)], duration=10.0)
        # Never acked -> redelivered via the timer armed by poll().
        assert len(deliveries) >= 2


class TestMessageQueueStateMachineRegressions:
    def test_late_ack_after_requeue_withdraws_queued_copy(self):
        """Visibility timeout requeues; a late ack must remove the queued
        copy so the head can never wedge delivery for later messages."""
        mq = MessageQueue("orders", redelivery_delay=1.0, max_redeliveries=5)
        log = []

        class SlowAcker(Entity):
            def __init__(self):
                super().__init__("slow")
                self.first = True

            def handle_event(self, event):
                if event.event_type != "message_delivery":
                    return None
                mid = event.context["metadata"]["message_id"]
                log.append(mid)
                if self.first:
                    self.first = False
                    yield 1.5  # ack AFTER the 1.0s visibility timeout
                    mq.acknowledge(mid)
                else:
                    mq.acknowledge(mid)

        consumer = SlowAcker()
        mq.subscribe(consumer)

        class LateProducer(Producer):
            pass

        p1 = Producer("p1", mq, n=1)
        p2 = LateProducer("p2", mq, n=1)
        _run([mq, consumer, p1, p2], [(0.0, p1), (3.0, p2)], duration=30.0)
        # The second message MUST get through (no wedged head).
        assert any(m.endswith("-2") for m in log)
        assert mq.pending_count == 0
        assert mq.in_flight_count == 0

    def test_schedule_redelivery_honors_delay_despite_kicks(self):
        mq = MessageQueue("orders", redelivery_delay=2.0, auto_redelivery=False)
        deliveries = []

        class Recorder(Entity):
            def handle_event(self, event):
                if event.event_type == "message_delivery":
                    deliveries.append(
                        (event.context["metadata"]["message_id"],
                         round(self.now.to_seconds(), 2))
                    )
                return None

        consumer = Recorder("rec")
        mq.subscribe(consumer)

        class Script(Entity):
            def handle_event(self, event):
                out = list(mq.publish(Event(self.now, "m1", target=mq)))
                yield 0.1
                mid = deliveries[0][0]
                timer = mq.schedule_redelivery(mid)
                # Kick the cycle with another publish before the delay ends.
                out2 = list(mq.publish(Event(self.now, "m2", target=mq)))
                return [*out, *([timer] if timer else []), *out2]

        script = Script("script")
        _run([mq, consumer, script], [(0.0, script)], duration=30.0)
        m1_times = [at for mid, at in deliveries if mid.endswith("-1")]
        # m1 redelivered at ~2.1 (0.1 + 2.0 delay), not at the m2 kick (~0.1).
        assert len(m1_times) == 2
        assert m1_times[1] == pytest.approx(2.1, abs=0.05)

    def test_double_reject_no_duplicate(self):
        mq = MessageQueue("orders", max_redeliveries=5)
        seen = []

        class OneShot(Entity):
            def __init__(self):
                super().__init__("os")
                self.count = 0

            def handle_event(self, event):
                if event.event_type != "message_delivery":
                    return None
                mid = event.context["metadata"]["message_id"]
                seen.append(mid)
                self.count += 1
                if self.count == 1:
                    mq.reject(mid)
                    mq.reject(mid)  # double reject must be a no-op
                    return None
                mq.acknowledge(mid)
                return None

        consumer = OneShot()
        mq.subscribe(consumer)
        producer = Producer("p", mq, n=1)
        _run([mq, consumer, producer], [(0.0, producer)], duration=30.0)
        assert len(seen) == 2  # initial + exactly one redelivery
        assert mq.stats.messages_rejected == 1
