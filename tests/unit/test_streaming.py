"""Unit tests: streaming (EventLog, ConsumerGroup, StreamProcessor)."""

import pytest

from happysim_tpu import Entity, Event, Instant, Simulation
from happysim_tpu.components.streaming import (
    ConsumerGroup,
    EventLog,
    LateEventPolicy,
    RangeAssignment,
    RoundRobinAssignment,
    SessionWindow,
    SizeRetention,
    SlidingWindow,
    StickyAssignment,
    StreamProcessor,
    TimeRetention,
    TumblingWindow,
)


def t(seconds):
    return Instant.from_seconds(seconds)


class Driver(Entity):
    def __init__(self, name, script):
        super().__init__(name)
        self.script = script
        self.results = []

    def handle_event(self, event):
        result = yield from self.script(self)
        self.results.append(result)


def run_script(script, entities, duration=600.0, at=0.0):
    driver = Driver("driver", script)
    sim = Simulation(entities=[driver, *entities], duration=duration)
    sim.schedule([Event(t(at), "go", target=driver)])
    sim.run()
    return driver


# ---------------------------------------------------------------- EventLog ----
class TestEventLog:
    def test_append_read_roundtrip(self):
        log = EventLog("log", num_partitions=2)

        def script(self):
            records = []
            for i in range(6):
                rec = yield from log.append(f"key{i}", f"v{i}")
                records.append(rec)
            read_back = []
            for pid in range(2):
                recs = yield from log.read(pid, offset=0)
                read_back.extend(recs)
            return (len(records), len(read_back))

        driver = run_script(script, [log])
        assert driver.results == [(6, 6)]
        assert log.total_records == 6
        assert sum(log.high_watermarks().values()) == 6
        # Offsets are per-partition monotone from 0.
        for p in log.partitions:
            assert [r.offset for r in p.records] == list(range(len(p.records)))

    def test_same_key_same_partition(self):
        log = EventLog("log", num_partitions=4)

        def script(self):
            partitions = set()
            for _ in range(5):
                rec = yield from log.append("stable-key", "v")
                partitions.add(rec.partition)
            return partitions

        driver = run_script(script, [log])
        assert len(driver.results[0]) == 1  # same key -> same partition

    def test_size_retention(self):
        log = EventLog("log", num_partitions=1,
                       retention_policy=SizeRetention(max_records=3),
                       retention_check_interval=1.0)

        def script(self):
            for i in range(10):
                yield from log.append("k", i)
            yield 2.0  # let the retention daemon sweep
            return log.total_records

        driver = run_script(script, [log], duration=30.0)
        assert driver.results[0] <= 3
        assert log.stats.records_expired >= 7

    def test_time_retention(self):
        log = EventLog("log", num_partitions=1,
                       retention_policy=TimeRetention(max_age_s=1.0),
                       retention_check_interval=0.5)

        def script(self):
            yield from log.append("k", "old")
            yield 5.0
            yield from log.append("k", "new")
            yield 0.6  # sweep happens
            return [r.value for p in log.partitions for r in p.records]

        driver = run_script(script, [log], duration=30.0)
        assert driver.results[0] == ["new"]

    def test_read_from_offset(self):
        log = EventLog("log", num_partitions=1)

        def script(self):
            for i in range(5):
                yield from log.append("k", i)
            recs = yield from log.read(0, offset=3)
            return [r.value for r in recs]

        driver = run_script(script, [log])
        assert driver.results == [[3, 4]]


# ----------------------------------------------------------- assignments ----
class TestAssignmentStrategies:
    def test_range(self):
        a = RangeAssignment().assign([0, 1, 2, 3, 4], ["c1", "c2"])
        assert a == {"c1": [0, 1, 2], "c2": [3, 4]}

    def test_round_robin(self):
        a = RoundRobinAssignment().assign([0, 1, 2, 3, 4], ["c1", "c2"])
        assert a == {"c1": [0, 2, 4], "c2": [1, 3]}

    def test_sticky_minimizes_movement(self):
        sticky = StickyAssignment()
        first = sticky.assign([0, 1, 2, 3], ["c1", "c2"])
        second = sticky.assign([0, 1, 2, 3], ["c1", "c2", "c3"])
        # c1 and c2 keep some of their prior partitions.
        kept = sum(len(set(first[c]) & set(second[c])) for c in ("c1", "c2"))
        assert kept >= 2
        assert sorted(p for parts in second.values() for p in parts) == [0, 1, 2, 3]

    def test_empty_consumers(self):
        assert RangeAssignment().assign([0, 1], []) == {}


# ------------------------------------------------------------ ConsumerGroup ----
class TestConsumerGroup:
    def test_join_poll_commit_lag(self):
        log = EventLog("log", num_partitions=2)
        group = ConsumerGroup("group", log, rebalance_delay=0.1)

        class NullConsumer(Entity):
            def handle_event(self, event):
                return None

        c1 = NullConsumer("c1")

        def script(self):
            for i in range(8):
                yield from log.append(f"key{i}", i)
            assigned = yield from group.join("c1", c1)
            records = yield from group.poll("c1", max_records=100)
            # Commit the consumed offsets per partition.
            commits = {}
            for rec in records:
                commits[rec.partition] = max(commits.get(rec.partition, 0), rec.offset + 1)
            yield from group.commit("c1", commits)
            return (sorted(assigned), len(records), group.total_lag())

        driver = run_script(script, [log, group, c1])
        assigned, polled, lag = driver.results[0]
        assert assigned == [0, 1]
        assert polled == 8
        assert lag == 0
        assert group.stats.polls == 1
        assert group.stats.commits == 1

    def test_rebalance_on_join_and_leave(self):
        log = EventLog("log", num_partitions=4)
        group = ConsumerGroup("group", log, rebalance_delay=0.05)

        class NullConsumer(Entity):
            def handle_event(self, event):
                return None

        c1, c2 = NullConsumer("c1"), NullConsumer("c2")

        def script(self):
            a1 = yield from group.join("c1", c1)
            a2 = yield from group.join("c2", c2)
            gen_after_joins = group.generation
            yield from group.leave("c2")
            a1_after = group.assignments.get("c1", [])
            return (len(a1), sorted(group.assignments), gen_after_joins, sorted(a1_after))

        driver = run_script(script, [log, group, c1, c2])
        n_first, consumers_after, gen, c1_parts = driver.results[0]
        assert n_first == 4  # sole consumer gets everything
        assert consumers_after == ["c1"]
        assert gen == 2
        assert c1_parts == [0, 1, 2, 3]  # back to everything after leave
        assert group.stats.rebalances == 3

    def test_poll_respects_committed_offsets(self):
        log = EventLog("log", num_partitions=1)
        group = ConsumerGroup("group", log, rebalance_delay=0.01)

        class NullConsumer(Entity):
            def handle_event(self, event):
                return None

        c1 = NullConsumer("c1")

        def script(self):
            for i in range(5):
                yield from log.append("k", i)
            yield from group.join("c1", c1)
            first = yield from group.poll("c1")
            yield from group.commit("c1", {0: 3})
            second = yield from group.poll("c1")
            return ([r.value for r in first], [r.value for r in second])

        driver = run_script(script, [log, group, c1])
        first, second = driver.results[0]
        assert first == [0, 1, 2, 3, 4]
        assert second == [3, 4]  # from committed offset


# ---------------------------------------------------------- StreamProcessor ----
class ResultSink(Entity):
    def __init__(self, name="sink"):
        super().__init__(name)
        self.windows = []
        self.late = []

    def handle_event(self, event):
        meta = event.context["metadata"]
        if event.event_type == "WindowResult":
            self.windows.append(
                (meta["key"], meta["window_start"], meta["window_end"], meta["result"])
            )
        elif event.event_type == "LateEvent":
            self.late.append(meta["value"])
        return None


def _process_event(processor, at, key, value, event_time_s=None):
    return Event(
        t(at),
        "Process",
        target=processor,
        context={
            "metadata": {
                "key": key,
                "value": value,
                "event_time_s": event_time_s if event_time_s is not None else at,
            }
        },
    )


class TestStreamProcessor:
    def test_tumbling_window_aggregation(self):
        sink = ResultSink()
        proc = StreamProcessor("proc", TumblingWindow(10.0), sum, sink,
                               watermark_interval_s=1.0)
        sim = Simulation(entities=[proc, sink], duration=60.0)
        # Two windows: [0,10) gets 1+2+3, [10,20) gets 10
        for at, v in ((1.0, 1), (5.0, 2), (9.0, 3), (12.0, 10)):
            sim.schedule([_process_event(proc, at, "k", v)])
        sim.run()
        results = {(s, e): r for _, s, e, r in sink.windows}
        assert results[(0.0, 10.0)] == 6
        assert results[(10.0, 20.0)] == 10

    def test_sliding_window_overlap(self):
        sink = ResultSink()
        proc = StreamProcessor("proc", SlidingWindow(size_s=10.0, slide_s=5.0),
                               len, sink, watermark_interval_s=1.0)
        sim = Simulation(entities=[proc, sink], duration=60.0)
        sim.schedule([_process_event(proc, 7.0, "k", "x")])  # in [0,10) and [5,15)
        sim.run()
        spans = sorted((s, e) for _, s, e, _ in sink.windows)
        assert spans == [(0.0, 10.0), (5.0, 15.0)]

    def test_session_window_merges_on_gap(self):
        sink = ResultSink()
        proc = StreamProcessor("proc", SessionWindow(gap_s=5.0), len, sink,
                               watermark_interval_s=1.0)
        sim = Simulation(entities=[proc, sink], duration=120.0)
        # Burst (1,3,6) merges into one session; 30 starts another.
        for at in (1.0, 3.0, 6.0, 30.0):
            sim.schedule([_process_event(proc, at, "user", at)])
        sim.run()
        counts = sorted(r for _, _, _, r in sink.windows)
        assert counts == [1, 3]

    def test_late_event_dropped(self):
        sink = ResultSink()
        proc = StreamProcessor("proc", TumblingWindow(5.0), sum, sink,
                               late_event_policy=LateEventPolicy.DROP,
                               watermark_interval_s=1.0)
        sim = Simulation(entities=[proc, sink], duration=60.0)
        sim.schedule([_process_event(proc, 1.0, "k", 1)])
        # Arrives at t=20 with event time 2.0 — far behind the watermark.
        sim.schedule([_process_event(proc, 20.0, "k", 100, event_time_s=2.0)])
        sim.run()
        assert proc.stats.late_events_dropped == 1
        results = {(s, e): r for _, s, e, r in sink.windows}
        assert results[(0.0, 5.0)] == 1  # late value not included

    def test_late_event_side_output(self):
        sink = ResultSink()
        side = ResultSink("side")
        proc = StreamProcessor("proc", TumblingWindow(5.0), sum, sink,
                               late_event_policy=LateEventPolicy.SIDE_OUTPUT,
                               side_output=side, watermark_interval_s=1.0)
        sim = Simulation(entities=[proc, sink, side], duration=60.0)
        sim.schedule([_process_event(proc, 1.0, "k", 1)])
        sim.schedule([_process_event(proc, 20.0, "k", 100, event_time_s=2.0)])
        sim.run()
        assert side.late == [100]
        assert proc.stats.late_events_side_output == 1

    def test_late_event_update_reemits(self):
        sink = ResultSink()
        proc = StreamProcessor("proc", TumblingWindow(5.0), sum, sink,
                               late_event_policy=LateEventPolicy.UPDATE,
                               watermark_interval_s=1.0)
        sim = Simulation(entities=[proc, sink], duration=60.0)
        sim.schedule([_process_event(proc, 1.0, "k", 1)])
        sim.schedule([_process_event(proc, 20.0, "k", 100, event_time_s=2.0)])
        sim.run()
        # Window emitted twice: once with 1, re-emitted with 101.
        window_results = [r for _, s, e, r in sink.windows if (s, e) == (0.0, 5.0)]
        assert window_results == [1, 101]
        assert proc.stats.late_events_updated == 1
