"""Differential fuzz over every queue policy's push/pop/requeue contract.

The driver's requeue contract (``QueuePolicy.requeue``): a pop followed by
requeue(s) in POP order is a no-op — the queue must behave as if the pops
never happened. This property killed three rounds of WFQ/FIFO bugs
(commits 5a13b06, e517076, 47020f8); this fuzz hammers it with random
interleavings so the NEXT policy added can't silently reintroduce the
bug class.

Protocol: two instances of the same policy receive the identical random
push/pop stream; instance B additionally suffers random injected
"pop k, then requeue those k in pop order" undo sequences between ops.
After the stream, both are drained; the drain orders must match exactly.

Reference analogue: the requeue-race regression tests of
``happysimulator/tests/unit/test_queue_policies.py`` (directed cases);
this file generalizes them to arbitrary interleavings.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

import pytest

from happysim_tpu.components.queue_policies import (
    AdaptiveLIFO,
    CoDelQueue,
    DeadlineQueue,
    FairQueue,
    REDQueue,
    WeightedFairQueue,
)
from happysim_tpu.components.queue_policy import (
    FIFOQueue,
    LIFOQueue,
    PriorityQueue,
)
from happysim_tpu.core.temporal import Instant


@dataclass
class Job:
    uid: int
    priority: float = 0.0
    deadline: float = float("inf")
    flow: str = "f0"

    __hash__ = object.__hash__


class FrozenClock:
    """A clock the fuzz advances explicitly (CoDel sojourn baselines)."""

    def __init__(self):
        self.now_s = 0.0

    def __call__(self) -> Instant:
        return Instant.from_seconds(self.now_s)


def _make_policy(name: str, clock: FrozenClock):
    """Fresh policy under test. Parameters are chosen so that pop() is a
    pure dequeue (no time-based drops) — drop behavior has its own
    directed tests; the fuzz targets ORDERING under requeue races."""
    if name == "fifo":
        return FIFOQueue()
    if name == "lifo":
        return LIFOQueue()
    if name == "priority":
        return PriorityQueue()
    if name == "deadline":
        return DeadlineQueue()  # no clock => nothing expires
    if name == "codel":
        # Enormous target: no sojourn ever exceeds it, so pop == popleft.
        return CoDelQueue(target_delay=1e9, interval=1e9, clock_func=clock)
    if name == "red":
        # Thresholds above any depth the fuzz reaches: no early drops
        # (push-time drops would be symmetric anyway, but acceptance is
        # asserted to match between instances).
        return REDQueue(min_threshold=10_000, max_threshold=20_000, seed=7)
    if name == "adaptive_lifo":
        return AdaptiveLIFO(congestion_threshold=18, recovery_threshold=9)
    if name == "fair":
        return FairQueue(flow_key=lambda job: job.flow)
    if name == "wfq":
        return WeightedFairQueue(
            weights={"f0": 1.0, "f1": 2.5, "f2": 0.5},
            flow_key=lambda job: job.flow,
        )
    raise AssertionError(name)


POLICIES = [
    "fifo",
    "lifo",
    "priority",
    "deadline",
    "codel",
    "red",
    "adaptive_lifo",
    "fair",
    "wfq",
]

# AdaptiveLIFO's exact-undo (mode/hysteresis rollback) only holds for a
# single un-interleaved pop+requeue — a 2-pop batch moves the op counter,
# and a threshold crossing inside the batch legitimately latches. Every
# other policy supports multi-item undo batches in pop order.
MAX_UNDO_K = {"adaptive_lifo": 1}


def _drain(policy) -> list:
    out = []
    while len(policy):
        out.append(policy.pop())
    return out


def _run_differential(name: str, seed: int, n_ops: int = 400) -> None:
    rng = random.Random(seed)
    clock = FrozenClock()
    plain = _make_policy(name, clock)
    raced = _make_policy(name, clock)
    max_k = MAX_UNDO_K.get(name, 3)

    uid = 0
    live = 0  # items currently queued (identical for both instances)
    for _ in range(n_ops):
        # Maybe torture the raced instance with an undo batch first.
        if live and rng.random() < 0.45:
            k = min(live, rng.randint(1, max_k))
            popped = [raced.pop() for _ in range(k)]
            for job in popped:  # requeues arrive in POP order
                raced.requeue(job)
            assert len(raced) == len(plain), (
                f"{name}: undo batch changed the depth"
            )

        if live == 0 or rng.random() < 0.6:
            job = Job(
                uid=uid,
                priority=float(rng.randint(0, 2)),
                deadline=float(rng.randint(100, 200)),
                flow=f"f{rng.randint(0, 2)}",
            )
            uid += 1
            accepted_plain = plain.push(job)
            accepted_raced = raced.push(job)
            assert accepted_plain == accepted_raced, (
                f"{name}: push acceptance diverged after an undo batch"
            )
            if accepted_plain is not False:
                live += 1
        else:
            a = plain.pop()
            b = raced.pop()
            assert a is b, (
                f"{name}: pop order diverged after an undo batch "
                f"(plain={a and a.uid}, raced={b and b.uid})"
            )
            live -= 1
        clock.now_s += rng.random() * 0.1

    plain_rest = _drain(plain)
    raced_rest = _drain(raced)
    assert [j.uid for j in plain_rest] == [j.uid for j in raced_rest], (
        f"{name}: final drain order diverged"
    )


@pytest.mark.parametrize("name", POLICIES)
@pytest.mark.parametrize("seed", range(5))
def test_requeue_is_invisible_under_random_interleavings(name, seed):
    _run_differential(name, seed)


@pytest.mark.parametrize("name", POLICIES)
def test_no_item_lost_or_duplicated(name):
    """Conservation: drained items == accepted pushes minus delivered pops,
    with no duplicates, even under heavy injected undo churn."""
    rng = random.Random(99)
    clock = FrozenClock()
    policy = _make_policy(name, clock)
    max_k = MAX_UNDO_K.get(name, 3)

    accepted: set[int] = set()
    delivered: list[int] = []
    for uid in range(200):
        job = Job(
            uid=uid,
            priority=float(rng.randint(0, 2)),
            deadline=float(rng.randint(100, 200)),
            flow=f"f{rng.randint(0, 2)}",
        )
        if policy.push(job) is not False:
            accepted.add(uid)
        if len(policy) and rng.random() < 0.5:
            k = min(len(policy), rng.randint(1, max_k))
            popped = [policy.pop() for _ in range(k)]
            if rng.random() < 0.5:
                for item in popped:
                    policy.requeue(item)
            else:
                delivered.extend(item.uid for item in popped)
        clock.now_s += 0.01

    remaining = [job.uid for job in _drain(policy)]
    assert sorted(remaining + delivered) == sorted(accepted), (
        f"{name}: items lost or duplicated under requeue churn"
    )
    assert len(set(remaining)) == len(remaining)


def test_wfq_delivered_pop_blocks_virtual_clock_rewind():
    """pop A, pop B, deliver B, requeue A: B's pop legitimately advanced
    the virtual clock (B is gone), so the requeue must NOT rewind below
    B's finish — a rewind would hand a new flow a finish tag that jumps
    items queued before it."""
    wfq = WeightedFairQueue(flow_key=lambda job: job.flow)
    early = Job(uid=0, flow="a")
    late = Job(uid=1, flow="a")  # same flow: finish 1.0 then 2.0
    queued_first = Job(uid=2, flow="b")  # finish 1.0, pushed after early
    wfq.push(early)
    wfq.push(late)
    popped_early = wfq.pop()  # finish 1.0, vnow 0 -> 1
    popped_late = wfq.pop()  # finish 2.0, vnow -> 2; stays delivered
    assert popped_early is early and popped_late is late
    wfq.requeue(early)  # NOT a full suffix undo: late stays consumed
    wfq.push(queued_first)
    # Without the suffix guard vnow would have rewound to 0 and
    # queued_first's finish (1.0 from vnow 0) would TIE early's restored
    # tag; with vnow still 2.0 its finish is 3.0 and early pops first.
    assert wfq.pop() is early
    assert wfq.pop() is queued_first


def test_wfq_full_undo_batch_rewinds_virtual_clock():
    """pop A, pop B, requeue A, requeue B (the driver's same-instant race,
    in pop order) is a COMPLETE suffix undo: the virtual clock returns to
    its pre-batch value, so future pushes get the tags of an untouched
    queue."""
    wfq = WeightedFairQueue(flow_key=lambda job: job.flow)
    a = Job(uid=0, flow="a")
    b = Job(uid=1, flow="b")
    wfq.push(a)
    wfq.push(b)
    first, second = wfq.pop(), wfq.pop()
    wfq.requeue(first)
    wfq.requeue(second)
    assert wfq._virtual_now == 0.0
    fresh = Job(uid=2, flow="c")
    wfq.push(fresh)  # tag computed from the restored clock
    assert [wfq.pop().uid for _ in range(3)] == [0, 1, 2]


@pytest.mark.parametrize("name", POLICIES)
def test_single_pop_requeue_roundtrip_preserves_head(name):
    """The k=1 contract at every reachable state: pop + requeue, then the
    next pop returns the SAME item."""
    rng = random.Random(5)
    clock = FrozenClock()
    policy = _make_policy(name, clock)
    for uid in range(60):
        policy.push(
            Job(
                uid=uid,
                priority=float(rng.randint(0, 2)),
                deadline=float(rng.randint(100, 200)),
                flow=f"f{rng.randint(0, 2)}",
            )
        )
        if rng.random() < 0.7:
            head = policy.pop()
            policy.requeue(head)
            again = policy.pop()
            assert again is head, f"{name}: requeue did not restore the head"
            policy.requeue(again)
        clock.now_s += 0.01
