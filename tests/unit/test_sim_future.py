"""Unit tests: SimFuture park/resolve and combinators."""

import pytest

from happysim_tpu import Entity, Event, Instant, SimFuture, Simulation, all_of, any_of


class Requester(Entity):
    """Sends a request, awaits the response future."""

    def __init__(self, name, responder):
        super().__init__(name)
        self.responder = responder
        self.result = None
        self.resolved_at = None

    def handle_event(self, event):
        future = SimFuture()
        request = Event(self.now, "request", target=self.responder)
        request.context["reply_to"] = future
        value = yield future, [request]  # park + send the request
        self.result = value
        self.resolved_at = self.now.to_seconds()


class Responder(Entity):
    def __init__(self, name, delay_s=1.0):
        super().__init__(name)
        self.delay_s = delay_s

    def handle_event(self, event):
        future = event.context["reply_to"]
        yield self.delay_s
        future.resolve("pong")


class FanOut(Entity):
    """Awaits a combinator over two futures resolved at different times."""

    def __init__(self, name, combinator):
        super().__init__(name)
        self.combinator = combinator
        self.result = None
        self.when = None

    def handle_event(self, event):
        f1, f2 = SimFuture(), SimFuture()
        resolver1 = Event.once(self.now + 1.0, lambda: f1.resolve("one"))
        resolver2 = Event.once(self.now + 2.0, lambda: f2.resolve("two"))
        value = yield self.combinator(f1, f2), [resolver1, resolver2]
        self.result = value
        self.when = self.now.to_seconds()


def _request_response_world():
    responder = Responder("responder", delay_s=1.5)
    requester = Requester("requester", responder)
    sim = Simulation(entities=[requester, responder])
    sim.schedule(Event(Instant.Epoch, "go", target=requester))
    return sim, requester


def test_request_response_roundtrip():
    sim, requester = _request_response_world()
    sim.run()
    assert requester.result == "pong"
    assert requester.resolved_at == 1.5


def test_any_of_resolves_with_first():
    entity = FanOut("fan", any_of)
    sim = Simulation(entities=[entity])
    sim.schedule(Event(Instant.Epoch, "go", target=entity))
    sim.run()
    assert entity.result == (0, "one")
    assert entity.when == 1.0


def test_all_of_waits_for_all():
    entity = FanOut("fan", all_of)
    sim = Simulation(entities=[entity])
    sim.schedule(Event(Instant.Epoch, "go", target=entity))
    sim.run()
    assert entity.result == ["one", "two"]
    assert entity.when == 2.0


def test_double_park_raises():
    future = SimFuture()

    class Fake:
        pass

    future._continuation = object()
    with pytest.raises(RuntimeError):
        future._park(object())


def test_resolve_outside_sim_raises():
    future = SimFuture()
    future._continuation = object()
    future._resolved = True

    with pytest.raises(RuntimeError):
        future._resume()


def test_pre_resolved_future_resumes_immediately():
    class Immediate(Entity):
        def __init__(self):
            super().__init__("imm")
            self.value = None
            self.when = None

        def handle_event(self, event):
            future = SimFuture()
            future.resolve(42)
            self.value = yield future
            self.when = self.now.to_seconds()

    entity = Immediate()
    sim = Simulation(entities=[entity])
    sim.schedule(Event(Instant.from_seconds(3), "go", target=entity))
    sim.run()
    assert entity.value == 42
    assert entity.when == 3.0
