"""Sketch accuracy guarantees, checked against exact computations.

Each sketch advertises an error bound (CMS overestimate-only within
eps*N, bloom no-false-negatives, HLL ~1.04/sqrt(m), reservoir
uniformity, t-digest tail accuracy). These tests measure the bound
against brute-force ground truth on adversarial-ish workloads — a
hashing regression shows up here as a blown bound, not a flaky test.

Parity target: ``happysimulator/tests/unit/test_sketches.py``.
"""

from __future__ import annotations

import math
import random
from collections import Counter

import pytest

from happysim_tpu.sketching import (
    BloomFilter,
    CountMinSketch,
    HyperLogLog,
    ReservoirSampler,
    TDigest,
    TopK,
)


def zipf_stream(n_items, n_draws, seed, exponent=1.2):
    rng = random.Random(seed)
    weights = [1.0 / (k + 1) ** exponent for k in range(n_items)]
    total = sum(weights)
    cdf, acc = [], 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    out = []
    for _ in range(n_draws):
        u = rng.random()
        lo, hi = 0, n_items - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if cdf[mid] < u:
                lo = mid + 1
            else:
                hi = mid
        out.append(f"item{lo}")
    return out


class TestCountMinSketch:
    def test_never_underestimates(self):
        stream = zipf_stream(500, 20_000, seed=1)
        truth = Counter(stream)
        sketch = CountMinSketch(width=512, depth=5, seed=2)
        for item in stream:
            sketch.add(item)
        for item, count in truth.items():
            assert sketch.estimate(item) >= count

    def test_overestimate_within_eps_n(self):
        stream = zipf_stream(500, 20_000, seed=3)
        truth = Counter(stream)
        width = 1024
        sketch = CountMinSketch(width=width, depth=5, seed=4)
        for item in stream:
            sketch.add(item)
        # CMS guarantee: error <= e/width * N with prob 1 - e^-depth.
        bound = math.e / width * len(stream)
        violations = sum(
            sketch.estimate(item) - count > bound for item, count in truth.items()
        )
        assert violations <= len(truth) * 0.01

    def test_unseen_item_estimate_is_small(self):
        sketch = CountMinSketch(width=2048, depth=5, seed=5)
        for item in zipf_stream(100, 5_000, seed=6):
            sketch.add(item)
        assert sketch.estimate("never-added") <= math.e / 2048 * 5_000 + 1

    def test_top_k_finds_the_head(self):
        stream = zipf_stream(300, 30_000, seed=7)
        truth = Counter(stream)
        sketch = CountMinSketch(width=2048, depth=5, seed=8, track_top=32)
        for item in stream:
            sketch.add(item)
        top_true = {item for item, _ in truth.most_common(5)}
        top_sketch = {est.item for est in sketch.top(5)}
        assert len(top_true & top_sketch) >= 4

    def test_weighted_adds(self):
        sketch = CountMinSketch(width=512, depth=5, seed=9)
        sketch.add("x", count=50)
        sketch.add("x", count=25)
        assert sketch.estimate("x") >= 75


class TestBloomFilter:
    def test_no_false_negatives_ever(self):
        items = [f"key{i}" for i in range(5_000)]
        bloom = BloomFilter(size_bits=64_000, num_hashes=5, seed=1)
        for item in items:
            bloom.add(item)
        assert all(bloom.contains(item) for item in items)

    def test_false_positive_rate_near_theory(self):
        n, bits, hashes = 2_000, 32_768, 5
        bloom = BloomFilter(size_bits=bits, num_hashes=hashes, seed=2)
        for i in range(n):
            bloom.add(f"present{i}")
        theory = (1 - math.exp(-hashes * n / bits)) ** hashes
        hits = sum(bloom.contains(f"absent{i}") for i in range(10_000))
        assert hits / 10_000 < max(theory * 3, 0.02)

    def test_saturated_filter_degrades_not_breaks(self):
        bloom = BloomFilter(size_bits=256, num_hashes=3, seed=3)
        for i in range(5_000):
            bloom.add(f"k{i}")
        # Saturated: everything looks present, but no negatives appear.
        assert all(bloom.contains(f"k{i}") for i in range(0, 5_000, 97))


class TestHyperLogLog:
    @pytest.mark.parametrize("true_n", [100, 5_000, 100_000])
    def test_relative_error_within_bound(self, true_n):
        hll = HyperLogLog(precision=12, seed=1)
        for i in range(true_n):
            hll.add(f"user{i}")
        sigma = 1.04 / math.sqrt(2**12)
        assert hll.cardinality() == pytest.approx(true_n, rel=4 * sigma)

    def test_duplicates_do_not_inflate(self):
        hll = HyperLogLog(precision=12, seed=2)
        for _ in range(50):
            for i in range(1_000):
                hll.add(f"user{i}")
        assert hll.cardinality() == pytest.approx(1_000, rel=0.1)

    def test_empty_is_zero(self):
        assert HyperLogLog(precision=10).cardinality() == 0

    def test_higher_precision_tightens(self):
        errors = {}
        for precision in (8, 14):
            hll = HyperLogLog(precision=precision, seed=3)
            for i in range(50_000):
                hll.add(f"k{i}")
            errors[precision] = abs(hll.cardinality() - 50_000) / 50_000
        assert errors[14] < max(errors[8], 0.02)


class TestReservoir:
    def test_caps_at_capacity(self):
        sampler = ReservoirSampler(capacity=50, seed=1)
        for i in range(10_000):
            sampler.add(i)
        assert sampler.sample_size == 50

    def test_below_capacity_keeps_everything(self):
        sampler = ReservoirSampler(capacity=100, seed=2)
        for i in range(30):
            sampler.add(i)
        assert sorted(sampler.sample()) == list(range(30))

    def test_uniform_inclusion_probability(self):
        """Every stream position must be retained ~capacity/n of the
        time — early items must not be favored (the classic bug)."""
        hits = Counter()
        for trial in range(300):
            sampler = ReservoirSampler(capacity=20, seed=trial)
            for i in range(400):
                sampler.add(i)
            hits.update(sampler.sample())
        # Expected hits per item: 300 * 20/400 = 15.
        first_half = sum(hits[i] for i in range(200))
        second_half = sum(hits[i] for i in range(200, 400))
        assert first_half == pytest.approx(second_half, rel=0.15)


class TestTDigestTails:
    def test_extreme_quantiles_tighter_than_middle_rank_error(self):
        rng = random.Random(5)
        values = sorted(rng.expovariate(1.0) for _ in range(50_000))
        digest = TDigest(compression=100.0, seed=6)
        for v in values:
            digest.add(v)
        for q in (0.001, 0.5, 0.999):
            exact = values[int(q * (len(values) - 1))]
            estimate = digest.quantile(q)
            # Rank error: where does the estimate fall in the sorted data?
            import bisect

            rank = bisect.bisect_left(values, estimate) / len(values)
            tolerance = 0.005 if q in (0.001, 0.999) else 0.02
            assert abs(rank - q) < tolerance, (q, rank, exact, estimate)

    def test_min_max_are_exact(self):
        digest = TDigest(compression=50.0, seed=7)
        for v in (5.0, 1.0, 9.0, 3.0):
            digest.add(v)
        assert digest.quantile(0.0) == pytest.approx(1.0)
        assert digest.quantile(1.0) == pytest.approx(9.0)


class TestTopK:
    def test_tracks_the_true_head_exactly(self):
        stream = zipf_stream(1_000, 40_000, seed=9)
        truth = Counter(stream)
        topk = TopK(k=32, seed=10)
        for item in stream:
            topk.add(item)
        top_true = [item for item, _ in truth.most_common(5)]
        top_est = [est.item for est in topk.top(5)]
        assert set(top_true) <= set(top_est) | set(top_true[-1:])
        # The single heaviest item is always found.
        assert top_est[0] == top_true[0]
