"""Depth tests for the fault machinery: event builders, handles, contexts,
capacity faults, and multi-paxos failover (ref faults/fault.py:25-135,
faults/resource_faults.py:23, components/consensus/multi_paxos.py)."""

import pytest

from happysim_tpu import (
    FaultSchedule,
    Instant,
    Network,
    ReduceCapacity,
    Resource,
    Simulation,
)
from happysim_tpu.components.consensus import MultiPaxosNode
from happysim_tpu.core.callback_entity import CallbackEntity
from happysim_tpu.faults.fault import FaultContext, FaultHandle, one_shot, window


class TestEventBuilders:
    def test_one_shot_is_daemon(self):
        ev = one_shot(2.0, "fault.test", lambda e: None)
        assert ev.daemon
        assert ev.time == Instant.from_seconds(2.0)
        assert ev.event_type == "fault.test"

    def test_window_brackets_half_open_span(self):
        calls = []
        events = window(1.0, 3.0, "f", lambda e: calls.append("on"), lambda e: calls.append("off"))
        assert [e.time.to_seconds() for e in events] == [1.0, 3.0]
        assert [e.event_type for e in events] == ["f.activate", "f.deactivate"]

    def test_one_shot_fires_in_simulation(self):
        fired = []
        ev = one_shot(1.5, "f", lambda e: fired.append(e.time.to_seconds()))
        anchor = CallbackEntity("anchor", lambda: None)
        sim = Simulation(entities=[anchor], end_time=Instant.from_seconds(5))
        sim.schedule(ev)
        # A lone daemon event does not hold the sim open: add a primary event.
        from happysim_tpu.core.event import Event

        sim.schedule(Event(Instant.from_seconds(2), "Keep", target=anchor))
        sim.run()
        assert fired == [1.5]


class TestFaultHandle:
    class _Fault:
        def generate_events(self, ctx):
            return []

    def test_cancel_counts_live_events(self):
        handle = FaultHandle(self._Fault())
        events = [one_shot(1.0, "a", lambda e: None), one_shot(2.0, "b", lambda e: None)]
        events[0].cancel()
        handle.attach(events)
        assert handle.cancel() == 1
        assert handle.cancelled
        assert all(e.cancelled for e in events)

    def test_double_cancel_is_zero(self):
        handle = FaultHandle(self._Fault())
        handle.attach([one_shot(1.0, "a", lambda e: None)])
        assert handle.cancel() == 1
        assert handle.cancel() == 0

    def test_attach_aliases_list(self):
        handle = FaultHandle(self._Fault())
        chain = [one_shot(1.0, "a", lambda e: None)]
        handle.attach(chain)
        late = one_shot(2.0, "b", lambda e: None)
        chain.append(late)  # self-scheduled follow-up
        handle.cancel()
        assert late.cancelled


class TestFaultContext:
    def test_resolve_named_network(self):
        net = Network("net")
        ctx = FaultContext(entities={}, networks={"net": net}, resources={}, start_time=Instant.Epoch)
        assert ctx.resolve_network("net") is net

    def test_resolve_default_single_network(self):
        net = Network("only")
        ctx = FaultContext(entities={}, networks={"only": net}, resources={}, start_time=Instant.Epoch)
        assert ctx.resolve_network(None) is net

    def test_resolve_without_networks_raises(self):
        ctx = FaultContext(entities={}, networks={}, resources={}, start_time=Instant.Epoch)
        with pytest.raises(ValueError, match="No networks"):
            ctx.resolve_network(None)


class TestReduceCapacity:
    def test_validation(self):
        with pytest.raises(ValueError, match="factor"):
            ReduceCapacity("r", factor=-0.1, start=0.0, end=1.0)
        with pytest.raises(ValueError, match="window is empty"):
            ReduceCapacity("r", factor=0.5, start=2.0, end=2.0)

    def test_capacity_squeezed_then_restored(self):
        pool = Resource("pool", capacity=4.0)
        observed = {}

        def probe_mid(event):
            observed["mid"] = pool.capacity

        def probe_late(event):
            observed["late"] = pool.capacity

        faults = FaultSchedule()
        faults.add(ReduceCapacity("pool", factor=0.25, start=1.0, end=3.0))
        anchor = CallbackEntity("anchor", lambda: None)
        sim = Simulation(
            entities=[pool, anchor], fault_schedule=faults, end_time=Instant.from_seconds(5)
        )
        sim.schedule(one_shot(2.0, "probe.mid", probe_mid))
        sim.schedule(one_shot(4.0, "probe.late", probe_late))
        from happysim_tpu.core.event import Event

        sim.schedule(Event(Instant.from_seconds(4.5), "Keep", target=anchor))
        sim.run()
        assert observed["mid"] == 1.0
        assert observed["late"] == 4.0

    def test_restore_wakes_fitting_waiter(self):
        """A waiter parked because degraded capacity is exhausted must be
        woken when capacity is restored, not stranded until the next release."""
        pool = Resource("pool", capacity=2.0)
        granted = []

        def hold(event):
            def _run():
                yield pool.acquire(1.0)  # hold forever

            return _run()

        def wait(event):
            def _run():
                grant = yield pool.acquire(1.0)
                granted.append(grant.acquired_at.to_seconds())
                grant.release()

            return _run()

        holder = CallbackEntity("holder", hold)
        waiter = CallbackEntity("waiter", wait)
        faults = FaultSchedule()
        faults.add(ReduceCapacity("pool", factor=0.5, start=0.0, end=3.0))
        sim = Simulation(
            entities=[pool, holder, waiter],
            fault_schedule=faults,
            end_time=Instant.from_seconds(6),
        )
        from happysim_tpu.core.event import Event

        sim.schedule(Event(Instant.from_seconds(1), "Go", target=holder))
        sim.schedule(Event(Instant.from_seconds(1.5), "Go", target=waiter))
        # Keep a primary event past the restore so auto-termination does not
        # end the run while only the daemon restore event remains.
        keep = CallbackEntity("keep", lambda: None)
        sim.schedule(Event(Instant.from_seconds(5), "Keep", target=keep))
        sim.run()
        # Degraded capacity 1.0 fully held; restored to 2.0 at t=3 -> grant.
        assert granted == [3.0]


class TestMultiPaxosFailover:
    def _cluster(self, n=3):
        from happysim_tpu import ConstantLatency, NetworkLink

        network = Network(
            "net", default_link=NetworkLink("link", latency=ConstantLatency(0.01))
        )
        nodes = [MultiPaxosNode(f"mp{i}", network) for i in range(n)]
        for node in nodes:
            node.set_peers(nodes)
        return network, nodes

    def test_leader_crash_then_manual_failover(self):
        """Failover is caller-driven (as in the reference): after the leader
        crashes, a follower re-runs start() and takes over."""
        network, nodes = self._cluster()
        sim = Simulation(entities=[network, *nodes], end_time=Instant.from_seconds(120))
        for ev in nodes[0].start():
            sim.schedule(ev)

        follower = nodes[1]

        def crash_leader(event):
            leaders = [n for n in nodes if n.is_leader]
            assert leaders, "no leader by t=10"
            leaders[0]._crashed = True
            return None

        def promote_follower(event):
            return follower.start()

        sim.schedule(one_shot(10.0, "crash", crash_leader))
        anchor = CallbackEntity("promote", promote_follower)
        from happysim_tpu.core.event import Event

        sim.schedule(Event(Instant.from_seconds(11.0), "Promote", target=anchor))
        sim.run()
        alive = [n for n in nodes if not getattr(n, "_crashed", False)]
        alive_leaders = [n for n in alive if n.is_leader]
        assert alive_leaders == [follower]
        # The other alive follower learned the new leader from heartbeats.
        other = next(n for n in alive if n is not follower)
        assert other.leader == follower.name

    def test_deposed_leader_fails_inflight_submissions(self):
        """Step-down must resolve pending client futures to None — an
        unknown outcome must never be left to be falsely acked later."""
        from happysim_tpu.core.event import Event

        network, nodes = self._cluster()
        sim = Simulation(entities=[network, *nodes], end_time=Instant.from_seconds(40))
        for ev in nodes[0].start():
            sim.schedule(ev)
        futures = {}

        def submit_then_depose(event):
            leader = next(n for n in nodes if n.is_leader)
            futures["f"] = leader.submit({"op": "set", "key": "z", "value": 9})
            # Superior heartbeat lands before any phase-2 ack round-trip.
            leader.handle_event(
                Event(
                    leader.now,
                    "MultiPaxosHeartbeat",
                    target=leader,
                    context={"metadata": {"leader": "mp9", "ballot_number": 99}},
                )
            )
            return None

        client = CallbackEntity("client", submit_then_depose)
        sim.schedule(Event(Instant.from_seconds(5), "Go", target=client))
        sim.run()
        assert futures["f"].is_resolved and futures["f"].value is None

    def test_stale_candidate_cannot_promote_after_superior_promise(self):
        """A candidate that promised a superior ballot mid-phase-1 must
        ignore late promises for its own stale ballot."""
        from happysim_tpu.core.event import Event

        network, nodes = self._cluster()
        # Construction injects clocks; we drive handlers directly.
        Simulation(entities=[network, *nodes], end_time=Instant.from_seconds(10))
        candidate = nodes[0]
        candidate.start()  # ballot (1, mp0); phase-1 in flight
        # Superior leader's heartbeat arrives before peer promises.
        candidate.handle_event(
            Event(
                Instant.from_seconds(1),
                "MultiPaxosHeartbeat",
                target=candidate,
                context={"metadata": {"leader": "mp9", "ballot_number": 99}},
            )
        )
        # Two late promises for the stale ballot would have been quorum.
        for peer_name in ("mp1", "mp2"):
            candidate.handle_event(
                Event(
                    Instant.from_seconds(2),
                    "MultiPaxosPromise",
                    target=candidate,
                    context={
                        "metadata": {
                            "ballot_number": 1,
                            "from": peer_name,
                            "accepted": {},
                        }
                    },
                )
            )
        assert not candidate.is_leader
        assert candidate.leader == "mp9"

    def test_failover_candidate_outbids_dead_leaders_ballot(self):
        """start() must supersede the promised ballot, or every acceptor
        that promised the dead leader would nack the candidate forever."""
        network, nodes = self._cluster()
        sim = Simulation(entities=[network, *nodes], end_time=Instant.from_seconds(30))
        for ev in nodes[2].start():
            sim.schedule(ev)
        sim.run()
        assert nodes[2].is_leader
        # nodes[0] promised nodes[2]'s ballot via phase 1/heartbeats.
        assert nodes[0]._promised_ballot.node_id == "mp2"
        promised_number = nodes[0]._promised_ballot.number
        nodes[2]._crashed = True
        sim_b = Simulation(entities=[network, *nodes], end_time=Instant.from_seconds(60))
        events = nodes[0].start()
        assert nodes[0]._ballot.number > promised_number, "candidate outbids"
        for ev in events:
            sim_b.schedule(ev)
        sim_b.run()
        assert nodes[0].is_leader

    def test_superior_accept_deposes_stale_leader(self):
        """An Accept at a higher ballot from another leader must depose a
        sitting leader, not leave it assigning slots at its stale ballot."""
        from happysim_tpu.core.event import Event

        network, nodes = self._cluster()
        sim = Simulation(entities=[network, *nodes], end_time=Instant.from_seconds(30))
        for ev in nodes[0].start():
            sim.schedule(ev)
        sim.run()
        assert nodes[0].is_leader
        nodes[0].handle_event(
            Event(
                Instant.from_seconds(31),
                "MultiPaxosAccept",
                target=nodes[0],
                context={
                    "metadata": {
                        "ballot_number": 500,
                        "ballot_node": "mp1",
                        "source": "mp1",
                        "slot": 1,
                        "value": {"op": "set", "key": "x", "value": 1},
                    }
                },
            )
        )
        assert not nodes[0].is_leader
        assert nodes[0]._accepted[1][0].number == 500

    def test_nack_adopts_higher_ballot_for_next_attempt(self):
        from happysim_tpu.core.event import Event

        network, nodes = self._cluster()
        Simulation(entities=[network, *nodes], end_time=Instant.from_seconds(10))
        candidate = nodes[0]
        candidate.start()
        candidate.handle_event(
            Event(
                Instant.from_seconds(1),
                "MultiPaxosNack",
                target=candidate,
                context={"metadata": {"highest_ballot_number": 77}},
            )
        )
        assert candidate._ballot.number == 77
        assert not candidate.is_leader
        # The next start() outbids the nacker.
        candidate.start()
        assert candidate._ballot.number == 78

    def test_nack_with_equal_number_higher_node_deposes(self):
        """Two failover candidates can race to the same ballot number; the
        node-id tie-break loser must honor nacks from the winner's
        acceptors, not shrug them off as equal-numbered."""
        from happysim_tpu.core.event import Event

        network, nodes = self._cluster()
        Simulation(entities=[network, *nodes], end_time=Instant.from_seconds(10))
        loser = nodes[0]  # "mp0" loses the tie-break to "mp2"
        loser.start()     # ballot (1, mp0)
        number = loser._ballot.number
        # One promise reaches quorum: the loser thinks it is leader...
        loser.handle_event(
            Event(
                Instant.from_seconds(0.5),
                "MultiPaxosPromise",
                target=loser,
                context={
                    "metadata": {"ballot_number": number, "from": "mp1", "accepted": {}}
                },
            )
        )
        assert loser.is_leader
        # ...until an acceptor promised to the equal-number rival nacks it.
        loser.handle_event(
            Event(
                Instant.from_seconds(1),
                "MultiPaxosNack",
                target=loser,
                context={
                    "metadata": {
                        "highest_ballot_number": number,
                        "highest_ballot_node": "mp2",
                    }
                },
            )
        )
        assert not loser.is_leader
        loser.start()
        assert loser._ballot.number == number + 1  # outbids the rival

    def test_heartbeat_from_superior_leader_deposes(self):
        from happysim_tpu.core.event import Event

        network, nodes = self._cluster()
        sim = Simulation(entities=[network, *nodes], end_time=Instant.from_seconds(30))
        for ev in nodes[0].start():
            sim.schedule(ev)
        sim.run()
        assert nodes[0].is_leader
        # A heartbeat carrying a strictly higher ballot arrives (its prepare
        # was partitioned away): the sitting leader must step down.
        hb = Event(
            Instant.from_seconds(31),
            "MultiPaxosHeartbeat",
            target=nodes[0],
            context={"metadata": {"leader": "mp9", "ballot_number": 10_000}},
        )
        nodes[0].handle_event(hb)
        assert not nodes[0].is_leader
        assert nodes[0].leader == "mp9"
