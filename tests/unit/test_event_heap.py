"""Unit tests: event ordering, cancellation, heap invariants."""

from happysim_tpu import Entity, Event, EventHeap, Instant
from happysim_tpu.core.event import reset_event_counter


class Collector(Entity):
    def __init__(self, name="collector"):
        super().__init__(name)
        self.seen = []

    def handle_event(self, event):
        self.seen.append(event.event_type)


def test_events_pop_in_time_order():
    reset_event_counter()
    target = Collector()
    heap = EventHeap()
    heap.push(Event(Instant.from_seconds(3), "c", target))
    heap.push(Event(Instant.from_seconds(1), "a", target))
    heap.push(Event(Instant.from_seconds(2), "b", target))
    assert [heap.pop().event_type for _ in range(3)] == ["a", "b", "c"]


def test_same_time_is_fifo_by_insertion():
    reset_event_counter()
    target = Collector()
    heap = EventHeap()
    t = Instant.from_seconds(1)
    for name in ["first", "second", "third"]:
        heap.push(Event(t, name, target))
    assert [heap.pop().event_type for _ in range(3)] == ["first", "second", "third"]


def test_primary_count_excludes_daemons():
    target = Collector()
    heap = EventHeap()
    heap.push(Event(Instant.Epoch, "d", target, daemon=True))
    assert heap.has_events()
    assert not heap.has_primary_events()
    heap.push(Event(Instant.Epoch, "p", target))
    assert heap.has_primary_events()
    popped = [heap.pop(), heap.pop()]
    assert not heap.has_primary_events()
    assert not heap.has_events()


def test_cancellation_is_lazy():
    target = Collector()
    heap = EventHeap()
    event = Event(Instant.Epoch, "x", target)
    heap.push(event)
    event.cancel()
    assert heap.size() == 1  # still in heap
    assert heap.pop().cancelled


def test_event_requires_target():
    import pytest

    with pytest.raises(ValueError):
        Event(Instant.Epoch, "orphan")


def test_completion_hooks_run_once():
    target = Collector()
    calls = []
    event = Event(Instant.Epoch, "x", target)
    event.add_completion_hook(lambda t: calls.append(t))
    event.invoke()
    event._run_completion_hooks(Instant.Epoch)
    assert len(calls) == 1


def test_event_context_defaults():
    target = Collector()
    event = Event(Instant.from_seconds(2), "x", target)
    assert event.context["created_at"] == Instant.from_seconds(2)
    assert "id" in event.context
