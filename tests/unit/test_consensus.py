"""Unit tests: consensus (Raft, Paxos family, elections, membership, locks).

Multi-node protocols run inside real simulations over a simulated Network
with latency — the DES itself is the test cluster (SURVEY.md §4).
"""

import pytest

from happysim_tpu import ConstantLatency, Entity, Event, Instant, Network, NetworkLink, Simulation
from happysim_tpu.components.consensus import (
    Ballot,
    BullyStrategy,
    DistributedLock,
    FlexiblePaxosNode,
    KVStateMachine,
    LeaderElection,
    Log,
    MemberState,
    MembershipProtocol,
    MultiPaxosNode,
    PaxosNode,
    PhiAccrualDetector,
    RaftNode,
    RaftState,
    RandomizedStrategy,
    RingStrategy,
)


def t(seconds):
    return Instant.from_seconds(seconds)


def make_network(latency=0.01):
    return Network("net", default_link=NetworkLink("link", latency=ConstantLatency(latency)))


def wire(nodes):
    for node in nodes:
        node.set_peers(nodes)


# -------------------------------------------------------------------- Log ----
class TestLog:
    def test_append_get_truncate(self):
        log = Log()
        log.append(1, "a")
        log.append(1, "b")
        log.append(2, "c")
        assert log.last_index == 3
        assert log.last_term == 2
        assert log.get(2).command == "b"
        assert log.truncate_from(2) == 2
        assert log.last_index == 1

    def test_advance_commit(self):
        log = Log()
        for i in range(5):
            log.append(1, i)
        newly = log.advance_commit(3)
        assert [e.command for e in newly] == [0, 1, 2]
        assert log.advance_commit(2) == []  # no regress
        assert log.commit_index == 3


# ---------------------------------------------------------- PhiAccrual ----
class TestPhiAccrual:
    def test_phi_grows_with_silence(self):
        det = PhiAccrualDetector(threshold=3.0)
        for i in range(10):
            det.heartbeat(float(i))  # steady 1s heartbeats
        assert det.phi(9.5) < 1.0  # mid-interval: on schedule
        assert det.phi(15.0) > 3.0  # 5s of silence
        assert det.is_available(9.5)
        assert not det.is_available(15.0)

    def test_insufficient_data(self):
        det = PhiAccrualDetector()
        assert det.phi(10.0) == 0.0


# --------------------------------------------------------------- Raft ----
def _raft_cluster(n=3, seed_base=100):
    network = make_network(0.01)
    nodes = [
        RaftNode(
            f"node{chr(ord('a') + i)}",
            network,
            election_timeout_min=1.0 + 0.3 * i,  # staggered: node-a wins
            election_timeout_max=1.1 + 0.3 * i,
            heartbeat_interval=0.3,
            seed=seed_base + i,
        )
        for i in range(n)
    ]
    wire(nodes)
    return network, nodes


class TestRaft:
    def test_elects_exactly_one_leader(self):
        network, nodes = _raft_cluster(3)
        sim = Simulation(entities=[network, *nodes], duration=10.0)
        for node in nodes:
            sim.schedule(node.start())
        sim.run()
        leaders = [n for n in nodes if n.is_leader]
        assert len(leaders) == 1
        leader = leaders[0]
        assert all(n.current_leader == leader.name for n in nodes)
        assert all(n.current_term == leader.current_term for n in nodes)

    def test_replicates_and_commits_commands(self):
        network, nodes = _raft_cluster(3)
        results = {}

        class Client(Entity):
            def handle_event(self, event):
                leader = next((n for n in nodes if n.is_leader), None)
                if leader is None:
                    return None
                future = leader.submit({"op": "set", "key": "x", "value": 42})
                outcome = yield future
                results["outcome"] = outcome

        client = Client("client")
        sim = Simulation(entities=[network, client, *nodes], duration=30.0)
        for node in nodes:
            sim.schedule(node.start())
        sim.schedule(Event(t(5.0), "go", target=client))
        sim.run()
        index, value = results["outcome"]
        assert value == 42
        # The command reached every node's state machine.
        committed = [n for n in nodes if n.state_machine.get("x") == 42]
        assert len(committed) == 3
        assert all(n.log.commit_index >= index for n in nodes)

    def test_submit_to_follower_rejects(self):
        network, nodes = _raft_cluster(3)
        sim = Simulation(entities=[network, *nodes], duration=8.0)
        for node in nodes:
            sim.schedule(node.start())
        sim.run()
        follower = next(n for n in nodes if not n.is_leader)
        future = follower.submit({"op": "set", "key": "y", "value": 1})
        assert future.is_resolved and future.value is None

    def test_reelection_after_leader_crash(self):
        network, nodes = _raft_cluster(3)

        class Crasher(Entity):
            def handle_event(self, event):
                leader = next((n for n in nodes if n.is_leader), None)
                if leader is not None:
                    leader._crashed = True  # CrashNode semantics
                return None

        crasher = Crasher("crasher")
        sim = Simulation(entities=[network, crasher, *nodes], duration=30.0)
        for node in nodes:
            sim.schedule(node.start())
        sim.schedule(Event(t(6.0), "crash", target=crasher))
        sim.run()
        alive = [n for n in nodes if not getattr(n, "_crashed", False)]
        live_leaders = [n for n in alive if n.is_leader]
        assert len(live_leaders) == 1  # survivors elected a new leader


# -------------------------------------------------------------- Paxos ----
class TestPaxos:
    def test_single_proposer_decides(self):
        network = make_network(0.01)
        nodes = [PaxosNode(f"p{i}", network, seed=i) for i in range(3)]
        wire(nodes)

        class Proposer(Entity):
            def handle_event(self, event):
                future = nodes[0].propose("value-A")
                decided = yield future, nodes[0].start_phase1()
                self.decided = decided

        proposer = Proposer("proposer")
        sim = Simulation(entities=[network, proposer, *nodes], duration=10.0)
        sim.schedule(Event(t(0.0), "go", target=proposer))
        sim.run()
        assert proposer.decided == "value-A"
        assert all(n.is_decided for n in nodes)
        assert all(n.decided_value == "value-A" for n in nodes)

    def test_competing_proposers_agree(self):
        network = make_network(0.01)
        nodes = [PaxosNode(f"p{i}", network, retry_delay=0.2, seed=i) for i in range(3)]
        wire(nodes)
        outcomes = []

        class Proposer(Entity):
            def __init__(self, name, node, value):
                super().__init__(name)
                self.node = node
                self.value = value

            def handle_event(self, event):
                future = self.node.propose(self.value)
                decided = yield future, self.node.start_phase1()
                outcomes.append(decided)

        p1 = Proposer("pr1", nodes[0], "A")
        p2 = Proposer("pr2", nodes[1], "B")
        sim = Simulation(entities=[network, p1, p2, *nodes], duration=30.0)
        sim.schedule(Event(t(0.0), "go", target=p1))
        sim.schedule(Event(t(0.001), "go", target=p2))
        sim.run()
        # Safety: everyone decided the SAME value.
        decided_values = {n.decided_value for n in nodes if n.is_decided}
        assert len(decided_values) == 1
        assert decided_values.pop() in {"A", "B"}
        assert len(outcomes) == 2
        assert outcomes[0] == outcomes[1]

    def test_ballot_ordering(self):
        assert Ballot(2, "a") > Ballot(1, "z")
        assert Ballot(1, "b") > Ballot(1, "a")


# --------------------------------------------------------- Multi-Paxos ----
class TestMultiPaxos:
    def _cluster(self, n=3):
        network = make_network(0.01)
        nodes = [MultiPaxosNode(f"mp{i}", network) for i in range(n)]
        wire(nodes)
        return network, nodes

    def test_leader_decides_slot_sequence(self):
        network, nodes = self._cluster()
        results = []

        class Client(Entity):
            def handle_event(self, event):
                for i in range(3):
                    future = nodes[0].submit({"op": "set", "key": f"k{i}", "value": i})
                    outcome = yield future
                    results.append(outcome)

        client = Client("client")
        sim = Simulation(entities=[network, client, *nodes], duration=30.0)
        sim.schedule(nodes[0].start())
        sim.schedule(Event(t(1.0), "go", target=client))
        sim.run()
        assert [slot for slot, _ in results] == [1, 2, 3]
        assert nodes[0].stats.slots_decided == 3
        # All nodes learned and applied.
        for node in nodes:
            assert node.state_machine.get("k2") == 2

    def test_follower_forwards_to_leader(self):
        network, nodes = self._cluster()
        results = []

        class Client(Entity):
            def handle_event(self, event):
                future = nodes[1].submit({"op": "set", "key": "fwd", "value": "ok"})
                outcome = yield future
                results.append(outcome)

        client = Client("client")
        sim = Simulation(entities=[network, client, *nodes], duration=30.0)
        sim.schedule(nodes[0].start())
        sim.schedule(Event(t(2.0), "go", target=client))
        sim.run()
        assert results and results[0] is not None
        assert nodes[1].stats.forwards == 1
        assert nodes[0].state_machine.get("fwd") == "ok"


# ------------------------------------------------------ Flexible Paxos ----
class TestFlexiblePaxos:
    def test_quorum_invariant_enforced(self):
        network = make_network()
        nodes = [FlexiblePaxosNode(f"f{i}", network) for i in range(3)]
        with pytest.raises(ValueError):
            bad = FlexiblePaxosNode("bad", network, phase1_quorum=1, phase2_quorum=1)
            bad.set_peers(nodes)

    def test_small_phase2_quorum_commits(self):
        network = make_network(0.01)
        nodes = [
            FlexiblePaxosNode(f"f{i}", network, phase1_quorum=4, phase2_quorum=2)
            for i in range(5)
        ]
        wire(nodes)
        results = []

        class Client(Entity):
            def handle_event(self, event):
                future = nodes[0].submit({"op": "set", "key": "k", "value": 7})
                outcome = yield future
                results.append(outcome)

        client = Client("client")
        sim = Simulation(entities=[network, client, *nodes], duration=30.0)
        sim.schedule(nodes[0].start())
        sim.schedule(Event(t(1.0), "go", target=client))
        sim.run()
        assert results and results[0][1] == 7
        assert nodes[0].phase2_quorum == 2


# ----------------------------------------------------- Leader election ----
class TestLeaderElection:
    def _cluster(self, strategy_factory, n=3):
        network = make_network(0.01)
        electors = [
            LeaderElection(
                f"n{i}",
                network,
                strategy=strategy_factory(i),
                election_timeout=1.0,
                heartbeat_interval=0.3,
            )
            for i in range(n)
        ]
        for elector in electors:
            for other in electors:
                if other is not elector:
                    elector.add_member(other)
        return network, electors

    def test_bully_highest_id_wins(self):
        network, electors = self._cluster(lambda i: BullyStrategy())
        sim = Simulation(entities=[network, *electors], duration=15.0)
        for e in electors:
            sim.schedule(e.start())
        sim.run()
        # n2 (highest name) must be the agreed leader.
        assert all(e.current_leader == "n2" for e in electors)

    def test_ring_elects_max(self):
        network, electors = self._cluster(lambda i: RingStrategy())
        sim = Simulation(entities=[network, *electors], duration=15.0)
        for e in electors:
            sim.schedule(e.start())
        sim.run()
        leaders = {e.current_leader for e in electors}
        assert leaders == {"n2"}

    def test_randomized_converges(self):
        network, electors = self._cluster(lambda i: RandomizedStrategy(seed=i))
        sim = Simulation(entities=[network, *electors], duration=20.0)
        for e in electors:
            sim.schedule(e.start())
        sim.run()
        leaders = {e.current_leader for e in electors}
        assert len(leaders) == 1 and None not in leaders


# --------------------------------------------------------- Membership ----
class TestMembership:
    def test_all_alive_under_steady_probing(self):
        network = make_network(0.005)
        protos = [
            MembershipProtocol(f"m{i}", network, probe_interval=0.5, seed=i)
            for i in range(3)
        ]
        for p in protos:
            for other in protos:
                p.add_member(other)
        sim = Simulation(entities=[network, *protos], duration=20.0)
        for p in protos:
            sim.schedule(p.start())
        sim.run()
        for p in protos:
            assert len(p.alive_members) == 2
            assert p.stats.dead_count == 0
            assert p.stats.probes_sent > 10

    def test_crashed_member_declared_dead(self):
        network = make_network(0.005)
        protos = [
            MembershipProtocol(
                f"m{i}", network, probe_interval=0.5, suspicion_timeout=2.0,
                phi_threshold=3.0, seed=i,
            )
            for i in range(3)
        ]
        for p in protos:
            for other in protos:
                p.add_member(other)

        class Crasher(Entity):
            def handle_event(self, event):
                protos[2]._crashed = True
                return None

        crasher = Crasher("crasher")
        sim = Simulation(entities=[network, crasher, *protos], duration=60.0)
        for p in protos:
            sim.schedule(p.start())
        sim.schedule(Event(t(10.0), "crash", target=crasher))
        sim.run()
        # The two survivors eventually declare m2 dead.
        assert protos[0].get_member_state("m2") == MemberState.DEAD
        assert protos[1].get_member_state("m2") == MemberState.DEAD


# ---------------------------------------------------- Distributed lock ----
class TestDistributedLock:
    def test_fencing_tokens_increase(self):
        lock = DistributedLock("locks", lease_duration=10.0)

        class Worker(Entity):
            def __init__(self, name):
                super().__init__(name)
                self.tokens = []

            def handle_event(self, event):
                grant = yield lock.acquire("resource", self.name)
                self.tokens.append(grant.fencing_token)
                yield 0.5
                lock.release("resource", grant.fencing_token)

        w1, w2 = Worker("w1"), Worker("w2")
        sim = Simulation(entities=[lock, w1, w2], duration=30.0)
        sim.schedule(Event(t(0.0), "go", target=w1))
        sim.schedule(Event(t(0.1), "go", target=w2))
        sim.run()
        assert w1.tokens == [1]
        assert w2.tokens == [2]  # strictly increasing across grants

    def test_lease_expiry_hands_over(self):
        lock = DistributedLock("locks", lease_duration=1.0)
        grants = {}

        class Hog(Entity):
            def handle_event(self, event):
                grant = yield lock.acquire("resource", self.name)
                grants["hog"] = grant
                yield 60.0  # never releases — lease must expire
                return None

        class Waiter(Entity):
            def handle_event(self, event):
                grant = yield lock.acquire("resource", self.name)
                grants["waiter"] = (grant, round(self.now.to_seconds(), 2))

        hog, waiter = Hog("hog"), Waiter("waiter")
        sim = Simulation(entities=[lock, hog, waiter], duration=120.0)
        sim.schedule(Event(t(0.0), "go", target=hog))
        sim.schedule(Event(t(0.1), "go", target=waiter))
        sim.run()
        grant, at = grants["waiter"]
        assert at == pytest.approx(1.0, abs=0.01)  # handover at lease expiry
        assert grant.fencing_token > grants["hog"].fencing_token
        # Hog's lease expired (handover), and the waiter's own unreleased
        # lease expires later too.
        assert lock.stats.expirations >= 1

    def test_reentrant_and_stale_release(self):
        lock = DistributedLock("locks", lease_duration=100.0)
        g1 = lock.try_acquire("r", "me")
        g2 = lock.try_acquire("r", "me")  # reentrant: same token
        assert g1.fencing_token == g2.fencing_token
        assert lock.try_acquire("r", "other") is None
        assert not lock.release("r", 999)  # stale token rejected
        assert lock.release("r", g1.fencing_token)

    def test_max_waiters_rejection(self):
        lock = DistributedLock("locks", max_waiters=1)
        lock.try_acquire("r", "holder")
        f1 = lock.acquire("r", "w1")  # queued
        f2 = lock.acquire("r", "w2")  # rejected
        assert not f1.is_resolved
        assert f2.is_resolved and f2.value is None
        assert lock.stats.rejections == 1


class TestConsensusSafetyRegressions:
    def test_raft_no_double_vote_same_term(self):
        """An AppendEntries at the CURRENT term must not clear voted_for
        (a node could otherwise vote for two candidates in one term)."""
        network = make_network(0.01)
        node = RaftNode("n", network, seed=1)
        node._current_term = 5
        node._voted_for = "candidate_a"
        node._step_down(5)  # same term: heartbeat from the term-5 leader
        assert node._voted_for == "candidate_a"
        node._step_down(6)  # term advance: vote resets
        assert node._voted_for is None

    def test_raft_match_index_excludes_stale_suffix(self):
        """A follower with stale extra entries must not report them as
        matched — the leader would commit entries the follower lacks."""
        network = make_network(0.01)
        nodes = [RaftNode(f"n{i}", network, seed=i) for i in range(2)]
        wire(nodes)
        follower = nodes[0]
        leader_peer = nodes[1]
        # Follower has 3 entries; 2-3 from a stale term.
        follower._log.append(1, "a")
        follower._log.append(2, "stale1")
        follower._log.append(2, "stale2")
        follower._current_term = 3
        # Leader (term 4) sends an empty heartbeat consistent at prefix 1.
        event = Event(
            t(0.0),
            "RaftAppendEntries",
            target=follower,
            context={
                "metadata": {
                    "term": 4,
                    "leader_id": "n1",
                    "source": "n1",
                    "prev_log_index": 1,
                    "prev_log_term": 1,
                    "entries": [],
                    "leader_commit": 0,
                }
            },
        )
        sim = Simulation(entities=[network, *nodes], duration=1.0)
        sim.schedule(event)
        sim.run()
        # The response's match_index must be 1 (verified prefix), not 3.
        # We can't intercept the message easily; assert via leader's view:
        # replay the handler directly for a white-box check.
        produced = follower._on_append_entries(event)
        response = [e for e in produced if e.event_type == "RaftAppendEntriesResponse"]
        assert response
        assert response[0].context["metadata"]["match_index"] == 1

    def test_paxos_late_promise_does_not_change_value(self):
        """A promise arriving after Phase 2 started must not rewrite the
        proposed value for that ballot."""
        from happysim_tpu.core.clock import Clock

        network = make_network(0.01)
        nodes = [PaxosNode(f"p{i}", network, seed=i) for i in range(5)]
        wire(nodes)
        clock = Clock()
        for entity in (network, *nodes):
            entity.set_clock(clock)
        proposer = nodes[0]
        future = proposer.propose("X")
        ballot_number = proposer._current_ballot.number
        # Simulate quorum of empty promises -> phase 2 starts with X.
        proposer._phase1_responses[ballot_number] = [
            {"from": f"p{i}", "accepted_ballot": None, "accepted_value": None}
            for i in range(3)
        ]
        proposer._start_phase2(ballot_number)
        assert proposer._proposed_values[ballot_number] == "X"
        # Late promise reports a previously accepted value Y.
        late = Event(
            t(0.0),
            "PaxosPromise",
            target=proposer,
            context={
                "metadata": {
                    "ballot_number": ballot_number,
                    "from": "p4",
                    "accepted_ballot_number": 99,
                    "accepted_ballot_node": "p4",
                    "accepted_value": "Y",
                }
            },
        )
        produced = proposer._handle_promise(late)
        assert produced == []  # ignored
        assert proposer._proposed_values[ballot_number] == "X"  # unchanged

    def test_swim_indirect_probe_saves_reachable_member(self):
        """A member unreachable directly but reachable via delegates must
        NOT be declared dead (indirect probing actually works)."""
        network = make_network(0.005)
        protos = [
            MembershipProtocol(
                f"m{i}", network, probe_interval=0.5, suspicion_timeout=2.0,
                phi_threshold=8.0, seed=i,
            )
            for i in range(3)
        ]
        for p in protos:
            for other in protos:
                p.add_member(other)
        # Partition ONLY the m0 <-> m2 path; m1 can reach both.
        network.partition([protos[0]], [protos[2]])
        sim = Simulation(entities=[network, *protos], duration=40.0)
        for p in protos:
            sim.schedule(p.start())
        sim.run()
        # m0 cannot ping m2 directly, but delegate m1 relays: m2 stays alive.
        assert protos[0].get_member_state("m2") != MemberState.DEAD
        assert protos[0].stats.indirect_probes_sent > 0

    def test_bully_contested_startup_converges_on_heartbeats(self):
        """Simultaneous elections must not leave a follower with a term
        above the leader's (it would reject heartbeats forever)."""
        network = make_network(0.01)
        electors = [
            LeaderElection(f"n{i}", network, strategy=BullyStrategy(),
                           election_timeout=1.0, heartbeat_interval=0.3)
            for i in range(3)
        ]
        for e in electors:
            for o in electors:
                if o is not e:
                    e.add_member(o)
        sim = Simulation(entities=[network, *electors], duration=30.0)
        for e in electors:
            sim.schedule(e.start())
        sim.run()
        assert all(e.current_leader == "n2" for e in electors)
        # Followers stay in sync with the leader's term (no runaway).
        leader_term = next(e.current_term for e in electors if e.is_leader)
        assert all(abs(e.current_term - leader_term) <= 1 for e in electors)


class TestAdvisorRegressions:
    def test_deposed_leader_fails_pending_submissions(self):
        """A leader stepping down must resolve its in-flight client futures
        to None — never leave them to be falsely acked by a different
        command committed at the same index by a newer leader."""
        network, nodes = _raft_cluster(3)
        futures = {}

        class Client(Entity):
            def handle_event(self, event):
                leader = next((n for n in nodes if n.is_leader), None)
                if leader is not None:
                    futures["f"] = leader.submit(
                        {"op": "set", "key": "z", "value": 9}
                    )
                    # Depose before any replication round-trip completes.
                    leader._step_down(leader.current_term + 1)
                return None

        client = Client("client")
        sim = Simulation(entities=[network, client, *nodes], duration=12.0)
        for node in nodes:
            sim.schedule(node.start())
        sim.schedule(Event(t(5.0), "submit", target=client))
        sim.run()
        future = futures["f"]
        assert future.is_resolved and future.value is None

    def test_commit_with_different_term_does_not_ack_old_submitter(self):
        """White-box: a pending future whose slot is filled by another
        term's entry resolves None, not the new entry's result."""
        network, nodes = _raft_cluster(1)
        node = nodes[0]
        sim = Simulation(entities=[network, *nodes], duration=3.0)
        sim.schedule(node.start())
        sim.run()  # single node elects itself leader
        assert node.is_leader
        future = node.submit({"op": "set", "key": "a", "value": 1})
        index = node.log.last_index
        # Simulate conflict truncation + a new leader's entry in the slot.
        submit_term = node.current_term
        node._log.truncate_from(index)
        entry = node._log.append(submit_term + 1, {"op": "set", "key": "a", "value": 2})
        node._current_term = submit_term + 1
        node._apply_committed(node._log.advance_commit(entry.index))
        assert future.is_resolved and future.value is None
