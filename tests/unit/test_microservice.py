"""Unit tests: microservice components (saga, gateway, sidecar, idempotency,
outbox) — including regression tests for the hook double-fire family, retry
stat inflation, retry metadata aliasing, and duplicate sweep chains.
"""

import pytest

from happysim_tpu import (
    ConstantLatency,
    Counter,
    Event,
    Instant,
    Server,
    Simulation,
    Sink,
    TokenBucketPolicy,
)
from happysim_tpu.components.microservice import (
    APIGateway,
    IdempotencyStore,
    OutboxRelay,
    RouteConfig,
    Saga,
    SagaState,
    SagaStep,
    Sidecar,
)
from happysim_tpu.core.entity import Entity


class HookRecorder:
    """Counts completion-hook firings and whether they were drops."""

    def __init__(self):
        self.fired = []

    def hook(self, event):
        def _fire(time):
            self.fired.append(
                (time.to_seconds(), event.context.get("metadata", {}).get("dropped_by"))
            )
            return None

        event.add_completion_hook(_fire)
        return event


class StepService(Entity):
    """Records received events; optionally sleeps longer than any timeout."""

    def __init__(self, name, delay_s=0.01, stall=False):
        super().__init__(name)
        self.delay_s = delay_s
        self.stall = stall
        self.received = []

    def handle_event(self, event):
        self.received.append((self.now.to_seconds(), event.event_type))
        if self.stall:
            yield 1e6  # never completes within any test horizon
            return None
        yield self.delay_s
        return None


def run(entities, events, end_s=None):
    sim = Simulation(
        entities=entities,
        end_time=Instant.from_seconds(end_s) if end_s is not None else None,
    )
    sim.schedule(events)
    sim.run()
    return sim


def keepalive(until_s):
    return Event(Instant.from_seconds(until_s), "Keepalive", target=Counter("ka"))


# ---------------------------------------------------------------------------
# Saga
# ---------------------------------------------------------------------------


def make_saga(stall_step=None, timeout=0.5, n_steps=3):
    services, compensators, steps = [], [], []
    for i in range(n_steps):
        service = StepService(f"svc{i}", stall=(i == stall_step))
        comp = StepService(f"comp{i}")
        services.append(service)
        compensators.append(comp)
        steps.append(
            SagaStep(
                name=f"step{i}",
                action_target=service,
                action_event_type=f"Do{i}",
                compensation_target=comp,
                compensation_event_type=f"Undo{i}",
                timeout=timeout,
            )
        )
    saga = Saga("saga", steps)
    return saga, services, compensators


class TestSaga:
    def test_happy_path_completes_all_steps(self):
        saga, services, compensators = make_saga()
        run(
            [saga, *services, *compensators],
            [Event(Instant.Epoch, "Order", target=saga), keepalive(10.0)],
        )
        assert saga.get_instance_state(1) is SagaState.COMPLETED
        assert all(len(s.received) == 1 for s in services)
        assert all(len(c.received) == 0 for c in compensators)
        stats = saga.stats
        assert stats.sagas_completed == 1
        assert stats.steps_executed == 3
        assert stats.compensations_executed == 0

    def test_step_timeout_compensates_in_reverse(self):
        saga, services, compensators = make_saga(stall_step=2)
        run(
            [saga, *services, *compensators],
            [Event(Instant.Epoch, "Order", target=saga), keepalive(10.0)],
        )
        assert saga.get_instance_state(1) is SagaState.COMPENSATED
        # Steps 0 and 1 completed then were compensated, newest first.
        assert len(compensators[1].received) == 1
        assert len(compensators[0].received) == 1
        assert len(compensators[2].received) == 0  # the failed step isn't undone
        assert compensators[1].received[0][0] < compensators[0].received[0][0]
        assert saga.stats.sagas_compensated == 1
        assert saga.stats.steps_failed == 1
        assert saga.stats.compensations_executed == 2

    def test_first_step_timeout_compensates_nothing(self):
        saga, services, compensators = make_saga(stall_step=0)
        run(
            [saga, *services, *compensators],
            [Event(Instant.Epoch, "Order", target=saga), keepalive(10.0)],
        )
        assert saga.get_instance_state(1) is SagaState.COMPENSATED
        assert saga.stats.compensations_executed == 0

    def test_trigger_hooks_fire_once_at_commit(self):
        saga, services, compensators = make_saga()
        recorder = HookRecorder()
        trigger = recorder.hook(Event(Instant.Epoch, "Order", target=saga))
        run([saga, *services, *compensators], [trigger, keepalive(10.0)])
        assert len(recorder.fired) == 1
        fired_at, dropped_by = recorder.fired[0]
        assert dropped_by is None  # success, not a drop
        # Commit time = 3 steps x 10ms, not the launch time.
        assert fired_at == pytest.approx(0.03, abs=1e-3)

    def test_trigger_hooks_unwind_as_drop_on_compensation(self):
        saga, services, compensators = make_saga(stall_step=1)
        recorder = HookRecorder()
        trigger = recorder.hook(Event(Instant.Epoch, "Order", target=saga))
        run([saga, *services, *compensators], [trigger, keepalive(10.0)])
        assert len(recorder.fired) == 1
        _, dropped_by = recorder.fired[0]
        assert dropped_by == "saga"

    def test_concurrent_instances_are_independent(self):
        saga, services, compensators = make_saga()
        run(
            [saga, *services, *compensators],
            [
                Event(Instant.Epoch, "Order", target=saga),
                Event(Instant.from_seconds(0.001), "Order", target=saga),
                keepalive(10.0),
            ],
        )
        assert saga.stats.sagas_started == 2
        assert saga.stats.sagas_completed == 2
        assert saga.active_instances == 0

    def test_late_timeout_after_completion_is_ignored(self):
        # Steps finish in 10ms; the 500ms timeouts fire long after and
        # must not flip a completed saga into compensation.
        saga, services, compensators = make_saga(timeout=0.5)
        run(
            [saga, *services, *compensators],
            [Event(Instant.Epoch, "Order", target=saga), keepalive(10.0)],
        )
        assert saga.get_instance_state(1) is SagaState.COMPLETED
        assert saga.stats.sagas_compensated == 0


# ---------------------------------------------------------------------------
# API gateway
# ---------------------------------------------------------------------------


def gw_request(gateway, route, at_s=0.0):
    return Event(
        Instant.from_seconds(at_s),
        "Request",
        target=gateway,
        context={"metadata": {"route": route}},
    )


class TestAPIGateway:
    def test_round_robin_across_backends(self):
        a, b = Counter("a"), Counter("b")
        gateway = APIGateway(
            "gw",
            routes={"orders": RouteConfig("orders", backends=[a, b], auth_required=False)},
        )
        run([gateway, a, b], [gw_request(gateway, "orders", i * 0.01) for i in range(4)])
        assert a.count == 2
        assert b.count == 2
        assert gateway.stats.requests_routed == 4

    def test_no_route_drops_with_hook_unwind(self):
        backend = Counter("a")
        gateway = APIGateway(
            "gw", routes={"orders": RouteConfig("orders", backends=[backend])}
        )
        recorder = HookRecorder()
        request = recorder.hook(gw_request(gateway, "unknown"))
        run([gateway, backend], [request])
        assert gateway.stats.requests_no_route == 1
        assert recorder.fired[0][1] == "gw"

    def test_auth_latency_and_rejection(self):
        backend = Counter("a")
        gateway = APIGateway(
            "gw",
            routes={"r": RouteConfig("r", backends=[backend], auth_required=True)},
            auth_latency=0.005,
            auth_failure_rate=1.0,
            seed=1,
        )
        recorder = HookRecorder()
        request = recorder.hook(gw_request(gateway, "r"))
        run([gateway, backend], [request])
        assert gateway.stats.requests_rejected_auth == 1
        assert backend.count == 0
        # Rejection happens after the auth latency elapsed.
        assert recorder.fired[0][0] == pytest.approx(0.005, abs=1e-6)

    def test_rate_limit_rejects_beyond_budget(self):
        backend = Counter("a")
        gateway = APIGateway(
            "gw",
            routes={
                "r": RouteConfig(
                    "r",
                    backends=[backend],
                    auth_required=False,
                    rate_limit_policy=TokenBucketPolicy(capacity=2.0, refill_rate=0.001),
                )
            },
        )
        run([gateway, backend], [gw_request(gateway, "r", i * 0.001) for i in range(5)])
        assert backend.count == 2
        assert gateway.stats.requests_rejected_rate_limit == 3

    def test_backend_hooks_fire_once_at_backend_completion(self):
        backend = Server("backend", service_time=ConstantLatency(0.05))
        gateway = APIGateway(
            "gw", routes={"r": RouteConfig("r", backends=[backend], auth_required=False)}
        )
        recorder = HookRecorder()
        request = recorder.hook(gw_request(gateway, "r"))
        run([gateway, backend], [request])
        assert len(recorder.fired) == 1
        assert recorder.fired[0][0] == pytest.approx(0.05, abs=1e-3)

    def test_timeout_settles_pending(self):
        stalled = StepService("slow", stall=True)
        gateway = APIGateway(
            "gw",
            routes={"r": RouteConfig("r", backends=[stalled], auth_required=False,
                                     timeout=0.1)},
        )
        run([gateway, stalled], [gw_request(gateway, "r"), keepalive(1.0)], end_s=1.0)
        assert gateway.in_flight == 0


# ---------------------------------------------------------------------------
# Sidecar
# ---------------------------------------------------------------------------


class TestSidecar:
    def test_success_path(self):
        target = Server("svc", service_time=ConstantLatency(0.01))
        sidecar = Sidecar("mesh", target, request_timeout=1.0)
        recorder = HookRecorder()
        request = recorder.hook(Event(Instant.Epoch, "Call", target=sidecar))
        run([sidecar, target], [request, keepalive(5.0)])
        stats = sidecar.stats
        assert stats.total_requests == 1
        assert stats.successful_requests == 1
        assert stats.retries == 0
        assert len(recorder.fired) == 1
        assert recorder.fired[0][0] == pytest.approx(0.01, abs=1e-3)

    def test_timeout_retries_with_backoff_then_fails(self):
        stalled = StepService("svc", stall=True)
        sidecar = Sidecar(
            "mesh", stalled, request_timeout=0.1, max_retries=2, retry_base_delay=0.1
        )
        run([sidecar, stalled], [Event(Instant.Epoch, "Call", target=sidecar),
                                 keepalive(5.0)])
        stats = sidecar.stats
        # One logical request: attempts at 0, 0.2 (0.1 timeout + 0.1 backoff),
        # and 0.5 (0.3 timeout + 0.2 backoff); then terminal failure.
        assert stats.total_requests == 1  # regression: retries inflated this
        assert stats.retries == 2
        assert stats.timed_out == 3
        assert stats.failed_requests == 1
        assert [t for t, _ in stalled.received] == pytest.approx(
            [0.0, 0.2, 0.5], abs=1e-3
        )

    def test_retry_metadata_does_not_alias_origin(self):
        stalled = StepService("svc", stall=True)
        sidecar = Sidecar("mesh", stalled, request_timeout=0.1, max_retries=1)
        origin = Event(Instant.Epoch, "Call", target=sidecar)
        original_metadata = origin.context["metadata"]
        run([sidecar, stalled], [origin, keepalive(2.0)])
        # Regression: the retry's attempt counter must not leak back.
        assert "_sc_retry_attempt" not in original_metadata

    def test_rate_limit_rejection_unwinds_hooks(self):
        target = Server("svc", service_time=ConstantLatency(0.01))
        sidecar = Sidecar(
            "mesh", target, rate_limit_policy=TokenBucketPolicy(capacity=1.0, refill_rate=0.001)
        )
        recorder = HookRecorder()
        first = Event(Instant.Epoch, "Call", target=sidecar)
        second = recorder.hook(Event(Instant.from_seconds(0.001), "Call", target=sidecar))
        run([sidecar, target], [first, second, keepalive(2.0)])
        assert sidecar.stats.rate_limited == 1
        assert recorder.fired[0][1] == "mesh"

    def test_circuit_opens_after_threshold_and_recovers(self):
        stalled = StepService("svc", stall=True)
        sidecar = Sidecar(
            "mesh",
            stalled,
            circuit_failure_threshold=2,
            circuit_timeout=10.0,
            request_timeout=0.1,
            max_retries=0,
        )
        events = [
            Event(Instant.from_seconds(i * 0.5), "Call", target=sidecar) for i in range(3)
        ]
        sim = Simulation(entities=[sidecar, stalled], end_time=Instant.from_seconds(60))
        sim.schedule(events + [keepalive(30.0)])
        sim.run()
        stats = sidecar.stats
        # Two timeouts trip the breaker; the third call is refused outright.
        assert stats.failed_requests == 2
        assert stats.circuit_broken == 1
        # After circuit_timeout the breaker probes half-open.
        assert sidecar.circuit_state == "half_open"

    def test_half_open_success_closes_circuit(self):
        flaky = StepService("svc", stall=True)
        sidecar = Sidecar(
            "mesh",
            flaky,
            circuit_failure_threshold=1,
            circuit_success_threshold=1,
            circuit_timeout=1.0,
            request_timeout=0.1,
            max_retries=0,
        )
        sim = Simulation(entities=[sidecar, flaky], end_time=Instant.from_seconds(60))
        sim.schedule([Event(Instant.Epoch, "Call", target=sidecar), keepalive(30.0)])
        # Heal the service before the probe call.
        heal = Event(Instant.from_seconds(2.0), "Call", target=sidecar)
        sim.schedule(heal)
        flaky_heals_at = 1.5

        class Healer(Entity):
            def handle_event(self, event):
                flaky.stall = False
                return None

        healer = Healer("healer")
        sim.schedule(Event(Instant.from_seconds(flaky_heals_at), "Heal", target=healer))
        sim.run()
        assert sidecar.circuit_state == "closed"
        assert sidecar.stats.successful_requests == 1


# ---------------------------------------------------------------------------
# Idempotency store
# ---------------------------------------------------------------------------


def keyed_request(store, key, at_s=0.0):
    return Event(
        Instant.from_seconds(at_s),
        "Write",
        target=store,
        context={"metadata": {"idempotency_key": key}},
    )


def key_of(event):
    return event.context.get("metadata", {}).get("idempotency_key")


class TestIdempotencyStore:
    def test_unique_keys_forward_duplicates_suppressed(self):
        backend = Server("db", service_time=ConstantLatency(0.01))
        store = IdempotencyStore("idem", backend, key_extractor=key_of)
        run(
            [store, backend],
            [
                keyed_request(store, "a", 0.0),
                keyed_request(store, "a", 0.5),  # cached by now
                keyed_request(store, "b", 0.5),
                keepalive(2.0),
            ],
            end_s=2.0,
        )
        stats = store.stats
        assert stats.cache_misses == 2
        assert stats.cache_hits == 1
        assert backend.requests_completed == 2

    def test_in_flight_duplicate_suppressed(self):
        backend = Server("db", service_time=ConstantLatency(0.5))
        store = IdempotencyStore("idem", backend, key_extractor=key_of)
        run(
            [store, backend],
            [keyed_request(store, "a", 0.0), keyed_request(store, "a", 0.1),
             keepalive(2.0)],
            end_s=2.0,
        )
        assert store.stats.cache_hits == 1
        assert backend.requests_completed == 1

    def test_keyless_requests_opt_out(self):
        backend = Server("db", service_time=ConstantLatency(0.01))
        store = IdempotencyStore("idem", backend, key_extractor=key_of)
        run(
            [store, backend],
            [Event(Instant.from_seconds(i * 0.1), "Write", target=store) for i in range(3)]
            + [keepalive(1.0)],
            end_s=1.0,
        )
        assert backend.requests_completed == 3
        assert store.stats.cache_hits == 0

    def test_ttl_expiry_allows_replay(self):
        backend = Server("db", service_time=ConstantLatency(0.01))
        store = IdempotencyStore(
            "idem", backend, key_extractor=key_of, ttl=1.0, cleanup_interval=0.5
        )
        run(
            [store, backend],
            [keyed_request(store, "a", 0.0), keyed_request(store, "a", 3.0),
             keepalive(5.0)],
            end_s=5.0,
        )
        assert store.stats.cache_misses == 2
        assert store.stats.entries_expired >= 1
        assert backend.requests_completed == 2

    def test_capacity_eviction_oldest_first(self):
        backend = Server("db", service_time=ConstantLatency(0.001))
        store = IdempotencyStore("idem", backend, key_extractor=key_of, max_entries=2)
        run(
            [store, backend],
            [
                keyed_request(store, "a", 0.0),
                keyed_request(store, "b", 0.2),
                keyed_request(store, "c", 0.4),  # evicts "a"
                keyed_request(store, "a", 0.6),  # forwards again
                keepalive(2.0),
            ],
            end_s=2.0,
        )
        assert store.stats.cache_misses == 4
        assert backend.requests_completed == 4

    def test_single_sweep_chain(self):
        """Regression: multiple requests through an idle store must arm at
        most one sweep chain, not one per request."""
        sweeps = []

        class CountingStore(IdempotencyStore):
            def _sweep(self, event):
                sweeps.append(self.now.to_seconds())
                return super()._sweep(event)

        backend = Server("db", service_time=ConstantLatency(0.001))
        store = CountingStore(
            "idem", backend, key_extractor=key_of, ttl=100.0, cleanup_interval=1.0
        )
        run(
            [store, backend],
            [keyed_request(store, k, 0.0) for k in ("a", "b", "c")] + [keepalive(5.5)],
            end_s=5.5,
        )
        # One chain: sweeps at ~1,2,3,4,5 — not three interleaved chains.
        assert len(sweeps) == 5

    def test_forward_hooks_fire_once(self):
        backend = Server("db", service_time=ConstantLatency(0.02))
        store = IdempotencyStore("idem", backend, key_extractor=key_of)
        recorder = HookRecorder()
        request = recorder.hook(keyed_request(store, "a"))
        run([store, backend], [request, keepalive(1.0)], end_s=1.0)
        assert len(recorder.fired) == 1
        assert recorder.fired[0][0] == pytest.approx(0.02, abs=1e-3)


# ---------------------------------------------------------------------------
# Drop-vs-success discrimination (crashed / load-shedding downstream)
# ---------------------------------------------------------------------------


class TestDropDiscrimination:
    def test_sidecar_counts_crashed_backend_as_failure(self):
        """Regression: a crashed target's dropped relay must not read as a
        success (which would keep the breaker closed forever)."""
        target = Server("svc", service_time=ConstantLatency(0.01))
        target._crashed = True
        sidecar = Sidecar(
            "mesh", target, circuit_failure_threshold=2, max_retries=0,
            request_timeout=5.0,
        )
        run(
            [sidecar, target],
            [Event(Instant.from_seconds(i * 0.1), "Call", target=sidecar)
             for i in range(3)] + [keepalive(2.0)],
            end_s=2.0,
        )
        stats = sidecar.stats
        assert stats.successful_requests == 0
        assert stats.dropped_downstream >= 2
        assert stats.failed_requests == 2
        # Two drops tripped the breaker; the third call was refused.
        assert stats.circuit_broken == 1

    def test_sidecar_retries_after_drop_then_succeeds(self):
        target = Server("svc", service_time=ConstantLatency(0.01))
        target._crashed = True
        sidecar = Sidecar(
            "mesh", target, max_retries=3, retry_base_delay=0.5, request_timeout=5.0
        )

        class Healer(Entity):
            def handle_event(self, event):
                target._crashed = False
                return None

        healer = Healer("healer")
        recorder = HookRecorder()
        request = recorder.hook(Event(Instant.Epoch, "Call", target=sidecar))
        run(
            [sidecar, target, healer],
            [request, Event(Instant.from_seconds(0.2), "Heal", target=healer),
             keepalive(5.0)],
            end_s=5.0,
        )
        stats = sidecar.stats
        assert stats.successful_requests == 1
        assert stats.retries == 1
        # The caller's hook fired exactly once, as a success, at the
        # retry's completion — not at the first attempt's drop.
        assert len(recorder.fired) == 1
        assert recorder.fired[0][1] is None
        assert recorder.fired[0][0] == pytest.approx(0.51, abs=1e-2)

    def test_saga_step_drop_triggers_compensation(self):
        saga, services, compensators = make_saga(n_steps=2, timeout=None)
        services[1]._crashed = True
        run(
            [saga, *services, *compensators],
            [Event(Instant.Epoch, "Order", target=saga), keepalive(5.0)],
            end_s=5.0,
        )
        assert saga.get_instance_state(1) is SagaState.COMPENSATED
        assert len(compensators[0].received) == 1

    def test_saga_compensation_drop_marks_failed(self):
        saga, services, compensators = make_saga(stall_step=1, n_steps=2)
        compensators[0]._crashed = True
        run(
            [saga, *services, *compensators],
            [Event(Instant.Epoch, "Order", target=saga), keepalive(5.0)],
            end_s=5.0,
        )
        assert saga.get_instance_state(1) is SagaState.FAILED
        assert saga.stats.sagas_failed == 1

    def test_idempotency_drop_leaves_key_replayable(self):
        """Regression: a dropped forward must not cache its key as done."""
        backend = Server("db", service_time=ConstantLatency(0.01))
        backend._crashed = True
        store = IdempotencyStore("idem", backend, key_extractor=key_of)

        class Healer(Entity):
            def handle_event(self, event):
                backend._crashed = False
                return None

        healer = Healer("healer")
        run(
            [store, backend, healer],
            [
                keyed_request(store, "a", 0.0),  # dropped by crashed backend
                Event(Instant.from_seconds(0.5), "Heal", target=healer),
                keyed_request(store, "a", 1.0),  # must forward again
                keepalive(3.0),
            ],
            end_s=3.0,
        )
        assert store.stats.cache_hits == 0
        assert store.stats.cache_misses == 2


# ---------------------------------------------------------------------------
# Outbox relay
# ---------------------------------------------------------------------------


class TestOutboxRelay:
    def test_writes_drain_in_batches(self):
        sink = Counter("consumer")
        outbox = OutboxRelay("outbox", sink, poll_interval=0.1, batch_size=2,
                             relay_latency=0.0)
        sim = Simulation(entities=[outbox, sink], end_time=Instant.from_seconds(1.0))
        for i in range(5):
            outbox.write({"n": i})
        sim.schedule([outbox.prime_poll(), keepalive(1.0)])
        sim.run()
        stats = outbox.stats
        assert stats.entries_written == 5
        assert stats.entries_relayed == 5
        assert sink.count == 5
        # 5 entries at batch_size 2 need 3 polls (2+2+1); later polls idle.
        assert stats.poll_cycles >= 3

    def test_relay_lag_tracked(self):
        sink = Counter("consumer")
        outbox = OutboxRelay("outbox", sink, poll_interval=0.5, relay_latency=0.0)
        sim = Simulation(entities=[outbox, sink], end_time=Instant.from_seconds(2.0))
        outbox.write({"n": 1})
        sim.schedule([outbox.prime_poll(), keepalive(2.0)])
        sim.run()
        stats = outbox.stats
        assert stats.entries_relayed == 1
        # Written at epoch, relayed at the first 0.5s poll.
        assert stats.relay_lag_max == pytest.approx(0.5, abs=1e-3)
        assert stats.avg_relay_lag == pytest.approx(0.5, abs=1e-3)

    def test_any_event_kicks_poll_loop(self):
        sink = Counter("consumer")
        outbox = OutboxRelay("outbox", sink, poll_interval=0.1, relay_latency=0.0)

        class Writer(Entity):
            def handle_event(self, event):
                outbox.write({"from": "writer"})
                return [Event(self.now, "Kick", target=outbox)]

        writer = Writer("writer")
        run(
            [outbox, sink, writer],
            [Event(Instant.Epoch, "Go", target=writer), keepalive(1.0)],
            end_s=1.0,
        )
        assert sink.count == 1

    def test_relay_latency_orders_emissions(self):
        sink = Sink("consumer")
        outbox = OutboxRelay("outbox", sink, poll_interval=0.1, batch_size=10,
                             relay_latency=0.01)
        sim = Simulation(entities=[outbox, sink], end_time=Instant.from_seconds(1.0))
        for i in range(3):
            outbox.write({"n": i})
        sim.schedule([outbox.prime_poll(), keepalive(1.0)])
        sim.run()
        times = [t.to_seconds() for t in sink.completion_times]
        assert times == sorted(times)
        assert len(times) == 3
