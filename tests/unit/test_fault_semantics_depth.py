"""Crash/pause fault semantics driven end-to-end against real servers,
and the WAL sync-policy decision matrix.

Pins what a crashed/paused flag actually DOES to traffic (requests die
with hooks unwound, recovery restores service, pause == bounded crash)
and the exact fsync cadence each WAL policy promises.

Parity target: ``happysimulator/tests/unit/test_node_faults.py`` and
``test_wal.py`` policy cases.
"""

from __future__ import annotations

import pytest

from happysim_tpu import (
    ConstantLatency,
    FaultSchedule,
    Instant,
    Server,
    Simulation,
    Sink,
    Source,
)
from happysim_tpu.components.storage import SyncEveryWrite, SyncOnBatch, SyncPeriodic
from happysim_tpu.faults import CrashNode, PauseNode


def schedule_of(faults):
    schedule = FaultSchedule()
    for fault in faults:
        schedule.add(fault)
    return schedule


def world(*faults, rate=20.0, stop=4.0, horizon=6.0):
    sink = Sink("sink")
    server = Server(
        "server", service_time=ConstantLatency(0.001), downstream=sink
    )
    source = Source.poisson(rate=rate, target=server, stop_after=stop, seed=5)
    sim = Simulation(
        sources=[source],
        entities=[server, sink],
        end_time=Instant.from_seconds(horizon),
        fault_schedule=schedule_of(faults),
    )
    sim.run()
    return server, sink


class TestCrashNode:
    def test_permanent_crash_stops_service(self):
        server, sink = world(CrashNode("server", at=2.0))
        # ~2s of a 4s arrival window served, the rest dead.
        assert 0 < sink.events_received < 20.0 * 4.0 * 0.75
        baseline_server, baseline_sink = world()
        assert sink.events_received < baseline_sink.events_received

    def test_restart_resumes_service(self):
        server, sink = world(CrashNode("server", at=1.0, restart_at=2.0))
        _, baseline = world()
        # Roughly the 1s outage's worth of traffic is lost, no more.
        lost = baseline.events_received - sink.events_received
        assert 20.0 * 0.5 < lost < 20.0 * 2.0

    def test_crashed_requests_unwind_not_hang(self):
        """Requests arriving during the crash complete as dropped — their
        completion hooks fire (metadata marked) instead of leaking."""
        outcomes = []
        sink = Sink("sink")
        server = Server("server", service_time=ConstantLatency(0.001), downstream=sink)
        sim = Simulation(
            sources=[],
            entities=[server, sink],
            end_time=Instant.from_seconds(5.0),
            fault_schedule=schedule_of([CrashNode("server", at=1.0)]),
        )
        from happysim_tpu.core.event import Event

        for at in (0.5, 2.0):
            request = Event(Instant.from_seconds(at), "req", target=server)
            request.add_completion_hook(
                lambda t, r=request: outcomes.append(r.dropped_by) or None
            )
            sim.schedule(request)
        sim.run()
        assert len(outcomes) == 2
        assert outcomes[0] is None  # before the crash: clean completion
        assert outcomes[1] is not None  # during: dropped with a reason

    def test_pause_equals_bounded_crash(self):
        _, paused = world(PauseNode("server", start=1.0, end=2.0))
        _, crashed = world(CrashNode("server", at=1.0, restart_at=2.0))
        assert paused.events_received == crashed.events_received


class TestWALSyncPolicies:
    def test_every_write_always_syncs(self):
        policy = SyncEveryWrite()
        assert policy.should_sync(1, 0.0)
        assert policy.should_sync(0, 0.0)

    def test_batch_boundary_exact(self):
        policy = SyncOnBatch(batch_size=8)
        assert not policy.should_sync(7, 100.0)  # time is irrelevant
        assert policy.should_sync(8, 0.0)
        assert policy.should_sync(9, 0.0)

    def test_periodic_boundary_exact(self):
        policy = SyncPeriodic(interval_s=5.0)
        assert not policy.should_sync(10_000, 4.999)  # count is irrelevant
        assert policy.should_sync(0, 5.0)

    def test_validation(self):
        with pytest.raises(ValueError):
            SyncOnBatch(batch_size=0)
        with pytest.raises(ValueError):
            SyncPeriodic(interval_s=0.0)

    @pytest.mark.parametrize(
        "policy,writes,elapsed,expected",
        [
            (SyncEveryWrite(), 1, 0.0, True),
            (SyncOnBatch(4), 3, 9.0, False),
            (SyncOnBatch(4), 4, 0.0, True),
            (SyncPeriodic(2.0), 99, 1.9, False),
            (SyncPeriodic(2.0), 0, 2.1, True),
        ],
        ids=["every", "batch-under", "batch-at", "periodic-under", "periodic-over"],
    )
    def test_matrix(self, policy, writes, elapsed, expected):
        assert policy.should_sync(writes, elapsed) is expected
