"""utils tier: ids, humanize, filename sanitization."""

import threading

import pytest

from happysim_tpu.core.temporal import Duration
from happysim_tpu.utils import (
    get_id,
    humanize_count,
    humanize_duration,
    humanize_rate,
    sanitize_filename,
)


class TestIds:
    def test_monotone_and_sortable(self):
        ids = [get_id() for _ in range(100)]
        assert ids == sorted(ids)
        assert len(set(ids)) == 100
        assert all(len(i) == 12 for i in ids)
        int(ids[0], 16)  # valid hex

    def test_thread_safety(self):
        collected = []

        def grab():
            collected.extend(get_id() for _ in range(500))

        threads = [threading.Thread(target=grab) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(set(collected)) == len(collected) == 4000


class TestHumanize:
    def test_duration_units(self):
        assert humanize_duration(0) == "0s"
        assert humanize_duration(3.5e-9) == "3.5ns"
        assert humanize_duration(42e-6) == "42us"
        assert humanize_duration(0.0123) == "12.3ms"
        assert humanize_duration(1.5) == "1.5s"
        assert humanize_duration(123.4) == "2m 3.4s"
        assert humanize_duration(3721) == "1h 02m"
        assert humanize_duration(-0.25).startswith("-250")

    def test_duration_accepts_temporal_types(self):
        assert humanize_duration(Duration.from_seconds(0.5)) == "500ms"

    def test_count_and_rate(self):
        assert humanize_count(950) == "950"
        assert humanize_count(1234) == "1.23k"
        assert humanize_count(18_700_000) == "18.7M"
        assert humanize_count(3_000_000_000) == "3B"
        assert humanize_rate(134_580) == "135k/s"

    def test_decade_boundaries_promote_units(self):
        """Values just under a boundary must round UP a unit, never print
        scientific notation ('1e+03ms')."""
        assert humanize_duration(0.9999) == "1s"
        assert humanize_duration(9.999e-7) == "1us"
        assert humanize_duration(999.6e-9) == "1us"
        assert humanize_count(999_999) == "1M"
        assert humanize_count(999_999_999) == "1B"

    def test_minute_and_hour_boundaries_carry(self):
        """The post-rounding promotion applies at EVERY unit step: a
        remainder that formats as '60' carries into the next unit — never
        '1m 60s' / '59m 60s' / '60s'."""
        assert humanize_duration(59.96) == "1m 0s"
        assert humanize_duration(119.96) == "2m 0s"
        assert humanize_duration(3599.98) == "1h 00m"
        assert humanize_duration(119.4) == "1m 59.4s"


class TestSanitizeFilename:
    def test_replaces_unsafe_runs_with_one_underscore(self):
        assert sanitize_filename("a b/c:d*e") == "a_b_c_d_e"

    def test_strips_hiding_dots_and_edges(self):
        assert sanitize_filename("..hidden..") == "hidden"
        assert sanitize_filename("_x_") == "x"

    def test_never_empty_and_bounded(self):
        assert sanitize_filename("///") == "unnamed"
        assert len(sanitize_filename("x" * 1000)) == 255

    def test_keeps_safe_names_verbatim(self):
        assert sanitize_filename("run-01.checkpoint.npz") == "run-01.checkpoint.npz"


@pytest.mark.parametrize(
    "seconds,expected",
    [
        (0, "0s"),
        (1e-9, "1ns"),
        (999e-9, "999ns"),
        (1e-6, "1us"),
        (2.5e-3, "2.5ms"),
        (1.0, "1s"),
        (59.4, "59.4s"),
        (60.0, "1m 0s"),
        (61.0, "1m 1s"),
        (3599.0, "59m 59s"),
        (3600.0, "1h 00m"),
        (3660.0, "1h 01m"),
        (7322.0, "2h 02m"),
        (-1.5, "-1.5s"),
    ],
)
def test_humanize_duration_matrix(seconds, expected):
    assert humanize_duration(seconds) == expected


@pytest.mark.parametrize(
    "count,expected",
    [
        (0, "0"),
        (999, "999"),
        (1000, "1k"),
        (1500, "1.5k"),
        (2_000_000, "2M"),
        (3_200_000_000, "3.2B"),
        (-1500, "-1.5k"),
    ],
)
def test_humanize_count_matrix(count, expected):
    assert humanize_count(count) == expected


@pytest.mark.parametrize(
    "raw,expected_safe",
    [
        ("plain-name_01", "plain-name_01"),
        ("a b", "a_b"),
        ("a/b\\c", "a_b_c"),
        ("..hidden", "hidden"),
        ("trailing...", "trailing"),
        ("", "unnamed"),
    ],
)
def test_sanitize_filename_matrix(raw, expected_safe):
    result = sanitize_filename(raw)
    assert result == expected_safe
    assert "/" not in result and "\\" not in result
    assert not result.startswith(".")
