"""Unit tests: infrastructure components (disk, page cache, CPU, GC, TCP, DNS).

Mirrors the reference's coverage
(tests/unit/components/infrastructure/) with tiny real simulations.
"""

import pytest

from happysim_tpu import (
    AIMD,
    BBR,
    ConcurrentGC,
    CPUScheduler,
    Cubic,
    DiskIO,
    DNSRecord,
    DNSResolver,
    Event,
    FairShare,
    GarbageCollector,
    GenerationalGC,
    HDD,
    Instant,
    NVMe,
    PageCache,
    PriorityPreemptive,
    Simulation,
    SSD,
    StopTheWorld,
    TCPConnection,
)
from happysim_tpu.core.entity import Entity


class _Caller(Entity):
    """Drives a generator-method infrastructure component and records."""

    def __init__(self, name, script):
        super().__init__(name)
        self.script = script
        self.results = []
        self.finish_times = []

    def handle_event(self, event):
        result = yield from self.script()
        self.results.append(result)
        self.finish_times.append(self.now.to_seconds())
        return None


def drive(component, script, n_calls=1, at_times=None, end_s=None):
    caller = _Caller("caller", script)
    sim = Simulation(
        entities=[component, caller],
        end_time=Instant.from_seconds(end_s) if end_s is not None else None,
    )
    times = at_times if at_times is not None else [0.0] * n_calls
    sim.schedule(
        [Event(Instant.from_seconds(t), "Go", target=caller) for t in times]
    )
    sim.run()
    return caller


class TestDiskIO:
    def test_ssd_read_write_latency(self):
        disk = DiskIO("disk", profile=SSD())
        caller = drive(disk, lambda: (yield from disk.read(4096)))
        stats = disk.stats()
        assert stats.reads == 1
        assert stats.avg_read_latency_s > 0
        # Simulated time is integer-ns, so the finish time is quantized.
        assert caller.finish_times[0] == pytest.approx(stats.total_read_latency_s, abs=1e-6)

    def test_profiles_are_ordered_by_speed(self):
        depth, size = 1, 4096
        hdd = HDD(seed=0).read_latency_s(size, depth)
        ssd = SSD().read_latency_s(size, depth)
        nvme = NVMe().read_latency_s(size, depth)
        assert nvme < ssd < hdd

    def test_queue_depth_raises_latency(self):
        profile = SSD()
        assert profile.read_latency_s(4096, 8) > profile.read_latency_s(4096, 1)
        nvme = NVMe(native_queue_depth=4)
        assert nvme.read_latency_s(4096, 3) == nvme.read_latency_s(4096, 1)
        assert nvme.read_latency_s(4096, 10) > nvme.read_latency_s(4096, 4)

    def test_concurrent_io_tracks_peak_depth(self):
        disk = DiskIO("disk", profile=SSD())
        drive(disk, lambda: (yield from disk.write(8192)), n_calls=4)
        assert disk.stats().writes == 4
        assert disk.stats().peak_queue_depth == 4
        assert disk.queue_depth == 0

    def test_hdd_seek_jitter_is_seeded(self):
        a = HDD(seed=5).read_latency_s(4096, 1)
        b = HDD(seed=5).read_latency_s(4096, 1)
        assert a == b


class TestPageCache:
    def test_hit_after_miss(self):
        cache = PageCache("cache", capacity_pages=10)
        caller = drive(
            cache,
            lambda: (yield from cache.read_page(1)),
            n_calls=2,
            at_times=[0.0, 1.0],
        )
        stats = cache.stats()
        assert stats.misses == 1
        assert stats.hits == 1
        # Second read was free (cache hit, no yield).
        assert caller.finish_times[1] == pytest.approx(1.0)

    def test_lru_eviction(self):
        cache = PageCache("cache", capacity_pages=2)

        def script():
            yield from cache.read_page(1)
            yield from cache.read_page(2)
            yield from cache.read_page(3)  # evicts 1
            yield from cache.read_page(1)  # miss again

        drive(cache, script)
        assert cache.stats().evictions == 2
        assert cache.stats().misses == 4

    def test_dirty_eviction_pays_writeback(self):
        cache = PageCache("cache", capacity_pages=1)

        def script():
            yield from cache.write_page(1)
            yield from cache.read_page(2)  # evicts dirty page 1

        drive(cache, script)
        assert cache.stats().dirty_writebacks == 1
        assert cache.stats().evictions == 1

    def test_readahead_prefetches(self):
        cache = PageCache("cache", capacity_pages=10, readahead_pages=2)
        drive(cache, lambda: (yield from cache.read_page(5)))
        assert cache.stats().readaheads == 2
        assert cache.pages_cached == 3

    def test_flush_cleans_all_dirty(self):
        cache = PageCache("cache", capacity_pages=10)

        def script():
            yield from cache.write_page(1)
            yield from cache.write_page(2)
            return (yield from cache.flush())

        caller = drive(cache, script)
        assert caller.results[0] == 2
        assert cache.dirty_pages == 0


class TestCPUScheduler:
    def test_single_task_runs_to_completion(self):
        cpu = CPUScheduler("cpu", policy=FairShare(quantum_s=0.01))
        caller = drive(cpu, lambda: (yield from cpu.execute("t1", cpu_time_s=0.05)))
        assert cpu.stats().tasks_completed == 1
        assert cpu.stats().total_cpu_time_s == pytest.approx(0.05)
        assert caller.finish_times[0] == pytest.approx(0.05)

    def test_fair_share_interleaves(self):
        cpu = CPUScheduler("cpu", policy=FairShare(quantum_s=0.01), context_switch_s=0.0)

        class Worker(Entity):
            def __init__(self, name):
                super().__init__(name)
                self.done_at = None

            def handle_event(self, event):
                yield from cpu.execute(self.name, cpu_time_s=0.05)
                self.done_at = self.now.to_seconds()
                return None

        w1, w2 = Worker("w1"), Worker("w2")
        sim = Simulation(entities=[cpu, w1, w2])
        sim.schedule(
            [
                Event(Instant.Epoch, "Go", target=w1),
                Event(Instant.Epoch, "Go", target=w2),
            ]
        )
        sim.run()
        assert cpu.stats().tasks_completed == 2
        # True round-robin: quanta alternate, so both 50ms tasks finish
        # near the 100ms mark instead of serializing at 50/100.
        assert w1.done_at > 0.05
        assert w2.done_at > 0.05
        assert max(w1.done_at, w2.done_at) == pytest.approx(0.10, abs=2e-3)
        assert cpu.stats().total_cpu_time_s == pytest.approx(0.10)

    def test_priority_preemptive_prefers_high_priority(self):
        cpu = CPUScheduler("cpu", policy=PriorityPreemptive(quantum_s=0.01), context_switch_s=0.0)

        class Worker(Entity):
            def __init__(self, name, priority):
                super().__init__(name)
                self.priority = priority
                self.done_at = None

            def handle_event(self, event):
                yield from cpu.execute(self.name, cpu_time_s=0.03, priority=self.priority)
                self.done_at = self.now.to_seconds()
                return None

        low, high = Worker("low", 0), Worker("high", 10)
        sim = Simulation(entities=[cpu, low, high])
        sim.schedule(
            [
                Event(Instant.Epoch, "Go", target=low),
                Event(Instant.Epoch, "Go", target=high),
            ]
        )
        sim.run()
        assert high.done_at < low.done_at

    def test_context_switch_overhead_accounted(self):
        cpu = CPUScheduler("cpu", policy=FairShare(quantum_s=0.01), context_switch_s=0.001)
        drive(cpu, lambda: (yield from cpu.execute("t", cpu_time_s=0.02)), n_calls=2)
        stats = cpu.stats()
        assert stats.context_switches > 0
        assert stats.total_context_switch_overhead_s == pytest.approx(
            stats.context_switches * 0.001
        )
        assert 0 < stats.overhead_fraction < 1


class TestGarbageCollector:
    def test_pause_injection_at_call_site(self):
        gc = GarbageCollector("gc", strategy=StopTheWorld(base_pause_s=0.05, seed=1),
                              heap_pressure=0.5)
        caller = drive(gc, lambda: (yield from gc.pause()))
        assert gc.collection_count == 1
        stats = gc.stats()
        assert stats.total_pause_s > 0
        assert caller.finish_times[0] == pytest.approx(stats.total_pause_s)
        # StopTheWorld scales with pressure: base * (1 + 3*0.5) in [0.8, 1.2] jitter
        assert 0.05 * 2.5 * 0.8 <= stats.total_pause_s <= 0.05 * 2.5 * 1.2

    def test_generational_minor_vs_major(self):
        strategy = GenerationalGC(seed=2)
        gc = GarbageCollector("gc", strategy=strategy, heap_pressure=0.9)
        drive(gc, lambda: (yield from gc.pause()), n_calls=3)
        assert gc.major_collections == 3
        gc_low = GarbageCollector("gc2", strategy=GenerationalGC(seed=2), heap_pressure=0.1)
        drive(gc_low, lambda: (yield from gc_low.pause()), n_calls=3)
        assert gc_low.minor_collections == 3

    def test_scheduled_cycle_via_prime(self):
        gc = GarbageCollector("gc", strategy=ConcurrentGC(interval_s=1.0, seed=0))

        class Primer(Entity):
            def handle_event(self, event):
                return [gc.prime()]

        primer = Primer("primer")
        keeper = _Caller("keeper", lambda: iter(()))
        sim = Simulation(entities=[gc, primer, keeper], end_time=Instant.from_seconds(5.5))
        sim.schedule(Event(Instant.Epoch, "Start", target=primer))
        sim.schedule(Event(Instant.from_seconds(5.4), "Keep", target=keeper))
        sim.run()
        # Collections at ~0, 1, 2, 3, 4, 5 (plus pause drift).
        assert 4 <= gc.collection_count <= 7


class TestTCPConnection:
    def test_lossless_send_completes(self):
        tcp = TCPConnection("conn", congestion_control=AIMD(), loss_rate=0.0, seed=0)
        caller = drive(tcp, lambda: (yield from tcp.send(1460 * 100)))
        stats = tcp.stats()
        assert stats.segments_sent == 100
        assert stats.segments_acked == 100
        assert stats.retransmissions == 0
        assert caller.finish_times[0] > 0

    def test_slow_start_grows_window(self):
        tcp = TCPConnection("conn", initial_cwnd=2.0, initial_ssthresh=64.0, loss_rate=0.0)
        drive(tcp, lambda: (yield from tcp.send(1460 * 50)))
        assert tcp.cwnd > 2.0

    def test_loss_triggers_retransmit_and_backoff(self):
        tcp = TCPConnection(
            "conn", congestion_control=AIMD(), loss_rate=0.3,
            initial_cwnd=10.0, seed=3,
        )
        drive(tcp, lambda: (yield from tcp.send(1460 * 200)))
        stats = tcp.stats()
        assert stats.retransmissions > 0
        assert stats.algorithm == "AIMD"

    def test_cubic_and_bbr_complete(self):
        for cc in (Cubic(), BBR()):
            tcp = TCPConnection("conn", congestion_control=cc, loss_rate=0.01, seed=1)
            drive(tcp, lambda: (yield from tcp.send(1460 * 500)))
            assert tcp.segments_acked > 0

    def test_seeded_loss_reproducible(self):
        def run(seed):
            tcp = TCPConnection("conn", loss_rate=0.1, seed=seed)
            drive(tcp, lambda: (yield from tcp.send(1460 * 100)))
            return tcp.retransmissions

        assert run(9) == run(9)


class TestDNSResolver:
    def test_miss_then_hit(self):
        dns = DNSResolver(
            "dns",
            records={"api.example.com": DNSRecord("api.example.com", "10.0.0.1", ttl_s=60)},
        )
        caller = drive(
            dns,
            lambda: (yield from dns.resolve("api.example.com")),
            n_calls=2,
            at_times=[0.0, 1.0],
        )
        assert caller.results == ["10.0.0.1", "10.0.0.1"]
        stats = dns.stats()
        assert stats.cache_misses == 1
        assert stats.cache_hits == 1
        # Miss pays root+tld+auth = 45ms; hit is instant.
        assert caller.finish_times[0] == pytest.approx(0.045)
        assert caller.finish_times[1] == pytest.approx(1.0)

    def test_ttl_expiry_forces_relookup(self):
        dns = DNSResolver(
            "dns",
            records={"a.com": DNSRecord("a.com", "1.2.3.4", ttl_s=5.0)},
        )
        drive(
            dns,
            lambda: (yield from dns.resolve("a.com")),
            n_calls=2,
            at_times=[0.0, 10.0],
        )
        stats = dns.stats()
        assert stats.cache_misses == 2
        assert stats.cache_expirations == 1

    def test_nxdomain_returns_none(self):
        dns = DNSResolver("dns")
        caller = drive(dns, lambda: (yield from dns.resolve("missing.example")))
        assert caller.results == [None]

    def test_capacity_eviction(self):
        dns = DNSResolver(
            "dns",
            cache_capacity=1,
            records={
                "a.com": DNSRecord("a.com", "1.1.1.1"),
                "b.com": DNSRecord("b.com", "2.2.2.2"),
            },
        )

        def script():
            yield from dns.resolve("a.com")
            yield from dns.resolve("b.com")

        drive(dns, script)
        assert dns.stats().cache_evictions == 1
        assert dns.cache_size == 1
