"""Unit tests: client retries/timeouts, connection pool, pooled client."""

import pytest

from happysim_tpu import (
    ConstantLatency,
    Event,
    Instant,
    Server,
    Simulation,
    Sink,
)
from happysim_tpu.components.client import (
    Client,
    ConnectionPool,
    DecorrelatedJitter,
    ExponentialBackoff,
    FixedRetry,
    NoRetry,
    PooledClient,
)
from happysim_tpu.core.entity import Entity


class _BlackHole(Entity):
    """Swallows requests without completing them (forces client timeouts)."""

    def __init__(self):
        super().__init__("blackhole")
        self.received = 0

    def handle_event(self, event):
        self.received += 1
        yield 1e9  # never finishes within any test horizon


class TestRetryPolicies:
    def test_no_retry(self):
        p = NoRetry()
        assert not p.should_retry(1)

    def test_fixed(self):
        p = FixedRetry(max_attempts=3, delay_s=0.5)
        assert p.should_retry(1) and p.should_retry(2) and not p.should_retry(3)
        assert p.delay(1) == 0.5

    def test_exponential(self):
        p = ExponentialBackoff(max_attempts=5, initial_delay=0.1, max_delay=0.5)
        assert p.delay(1) == pytest.approx(0.1)
        assert p.delay(2) == pytest.approx(0.2)
        assert p.delay(4) == pytest.approx(0.5)  # capped

    def test_exponential_jitter_seeded(self):
        a = ExponentialBackoff(jitter=True, seed=7)
        b = ExponentialBackoff(jitter=True, seed=7)
        assert [a.delay(i) for i in (1, 2)] == [b.delay(i) for i in (1, 2)]

    def test_decorrelated_jitter_bounded(self):
        p = DecorrelatedJitter(max_attempts=10, base_delay=0.1, max_delay=1.0, seed=1)
        for attempt in range(1, 9):
            assert 0.1 <= p.delay(attempt) <= 1.0


class TestClient:
    def test_success_response(self):
        server = Server("s", concurrency=1, service_time=ConstantLatency(0.2))
        client = Client("c", target=server, timeout=5.0)
        sim = Simulation(entities=[server, client])
        sim.schedule(client.send_request(payload={"k": 1}, at=Instant.Epoch))
        sim.run()
        assert client.responses_received == 1
        assert client.timeouts == 0
        assert client.in_flight_count == 0
        assert client.average_response_time == pytest.approx(0.2)

    def test_timeout_no_retry_fails(self):
        hole = _BlackHole()
        failures = []
        client = Client(
            "c",
            target=hole,
            timeout=1.0,
            on_failure=lambda req, reason: failures.append(reason),
        )
        sim = Simulation(entities=[hole, client], duration=10.0)
        sim.schedule(client.send_request(at=Instant.Epoch))
        sim.run()
        assert client.timeouts == 1
        assert client.failures == 1
        assert failures == ["timeout"]

    def test_timeout_retries_then_fails(self):
        hole = _BlackHole()
        client = Client(
            "c", target=hole, timeout=1.0, retry_policy=FixedRetry(max_attempts=3, delay_s=0.1)
        )
        sim = Simulation(entities=[hole, client], duration=30.0)
        sim.schedule(client.send_request(at=Instant.Epoch))
        sim.run()
        assert client.requests_sent == 3
        assert client.retries == 2
        assert client.timeouts == 3
        assert client.failures == 1
        assert hole.received == 3

    def test_percentiles(self):
        server = Server("s", concurrency=10, service_time=ConstantLatency(0.1))
        client = Client("c", target=server)
        sim = Simulation(entities=[server, client])
        sim.schedule([client.send_request(at=Instant.Epoch) for _ in range(10)])
        sim.run()
        assert client.response_time_percentile(0.5) == pytest.approx(0.1)


class TestConnectionPool:
    def test_dial_then_reuse(self):
        sink = Sink()
        pool = ConnectionPool(
            "pool", target=sink, max_connections=2, connect_latency=ConstantLatency(0.05)
        )
        client = PooledClient("pc", connection_pool=pool)
        sim = Simulation(entities=[sink, pool, client])
        sim.schedule(client.send_request(at=Instant.Epoch))
        sim.run()
        assert client.responses_received == 1
        assert pool.connections_created == 1
        assert pool.idle_connections == 1
        # Second request at a later time reuses the idle connection.
        sim2_sink = Sink()
        pool2 = ConnectionPool(
            "pool2", target=sim2_sink, max_connections=2, connect_latency=ConstantLatency(0.05)
        )
        client2 = PooledClient("pc2", connection_pool=pool2)
        sim2 = Simulation(entities=[sim2_sink, pool2, client2])
        sim2.schedule(
            [client2.send_request(at=Instant.Epoch), client2.send_request(at=Instant.from_seconds(1.0))]
        )
        sim2.run()
        assert pool2.connections_created == 1
        assert pool2.reuses == 1

    def test_pool_exhaustion_queues_waiters(self):
        server = Server("s", concurrency=10, service_time=ConstantLatency(0.5))
        pool = ConnectionPool("pool", target=server, max_connections=1)
        client = PooledClient("pc", connection_pool=pool)
        sim = Simulation(entities=[server, pool, client])
        sim.schedule([client.send_request(at=Instant.Epoch) for _ in range(3)])
        sim.run()
        # One connection serializes the three 0.5s requests.
        assert client.responses_received == 3
        assert pool.connections_created == 1
        assert pool.waits == 2
        assert sim.now.to_seconds() == pytest.approx(1.5)

    def test_pooled_client_timeout_closes_connection(self):
        hole = _BlackHole()
        pool = ConnectionPool("pool", target=hole, max_connections=1)
        client = PooledClient("pc", connection_pool=pool, timeout=0.5)
        sim = Simulation(entities=[hole, pool, client], duration=5.0)
        sim.schedule(client.send_request(at=Instant.Epoch))
        sim.run()
        assert client.timeouts == 1
        assert pool.stats.connections_closed == 1
        assert pool.total_connections == 0

    def test_warmup(self):
        sink = Sink()
        pool = ConnectionPool(
            "pool",
            target=sink,
            min_connections=3,
            max_connections=5,
            connect_latency=ConstantLatency(0.01),
        )
        sim = Simulation(entities=[sink, pool], duration=1.0)
        sim.schedule(pool.warmup())
        sim.run()
        assert pool.idle_connections == 3
