"""Unit tests: Instant/Duration integer-nanosecond time."""

import pytest

from happysim_tpu import Duration, Instant


class TestDuration:
    def test_from_seconds_roundtrip(self):
        d = Duration.from_seconds(1.5)
        assert d.nanoseconds == 1_500_000_000
        assert d.to_seconds() == 1.5

    def test_arithmetic_with_numbers_is_seconds(self):
        d = Duration.from_seconds(1.0) + 0.5
        assert d == Duration.from_seconds(1.5)
        assert Duration.from_seconds(2.0) - 1 == Duration.from_seconds(1.0)

    def test_scaling(self):
        assert Duration.from_seconds(2.0) * 3 == Duration.from_seconds(6.0)
        assert 3 * Duration.from_seconds(2.0) == Duration.from_seconds(6.0)
        assert Duration.from_seconds(6.0) / 3 == Duration.from_seconds(2.0)
        assert Duration.from_seconds(6.0) / Duration.from_seconds(2.0) == 3.0

    def test_comparisons(self):
        assert Duration.from_seconds(1) < Duration.from_seconds(2)
        assert Duration.from_seconds(2) >= Duration.from_seconds(2)
        assert Duration.from_millis(1) == Duration.from_micros(1000)

    def test_hashable(self):
        assert hash(Duration(5)) == hash(Duration(5))


class TestInstant:
    def test_add_duration(self):
        t = Instant.from_seconds(1.0) + Duration.from_seconds(0.5)
        assert t == Instant.from_seconds(1.5)

    def test_add_float_seconds(self):
        assert Instant.Epoch + 2.5 == Instant.from_seconds(2.5)

    def test_subtract_instant_gives_duration(self):
        d = Instant.from_seconds(3.0) - Instant.from_seconds(1.0)
        assert isinstance(d, Duration)
        assert d == Duration.from_seconds(2.0)

    def test_subtract_duration_gives_instant(self):
        t = Instant.from_seconds(3.0) - Duration.from_seconds(1.0)
        assert isinstance(t, Instant)
        assert t == Instant.from_seconds(2.0)

    def test_ordering(self):
        assert Instant.Epoch < Instant.from_seconds(1)
        assert Instant.from_seconds(1) <= Instant.from_seconds(1)


class TestInfinity:
    def test_after_everything(self):
        assert Instant.Infinity > Instant.from_seconds(1e18)
        assert Instant.from_seconds(1e18) < Instant.Infinity
        assert Instant.Infinity >= Instant.Infinity
        assert not (Instant.Infinity < Instant.Infinity)

    def test_arithmetic_saturates(self):
        assert (Instant.Infinity + 100).is_infinite()
        assert (Instant.Infinity - Duration.from_seconds(5)).is_infinite()

    def test_equality(self):
        assert Instant.Infinity == Instant.Infinity
        assert Instant.Infinity != Instant.from_seconds(0)

    def test_to_seconds_is_inf(self):
        assert Instant.Infinity.to_seconds() == float("inf")
