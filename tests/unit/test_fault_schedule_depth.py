"""FaultSchedule / ledger edge cases: overlapping windows, revocation
before activation, and what FaultStats reports after the run unwinds.

Sister files: test_fault_depth.py (builders, handles, context,
ReduceCapacity mechanics) and test_fault_semantics_depth.py (crash/pause
equivalences). This file pins the SCHEDULE's bookkeeping contract:
stats count transitions that actually fired, never armed-but-revoked
ones, and overlapping flag-flip windows keep their documented
last-write-wins semantics.
"""

import pytest

from happysim_tpu import (
    ConstantLatency,
    CrashNode,
    FaultSchedule,
    PauseNode,
    ReduceCapacity,
    Resource,
    Simulation,
    Source,
)
from happysim_tpu.core.callback_entity import CallbackEntity


def record_sim(faults, duration=5.0, rate=10.0):
    """Constant 10/s stream into a recording node; returns receipt times."""
    received = []

    def record(event):
        received.append(event.time.to_seconds())

    node = CallbackEntity("node", record)
    source = Source.constant(rate=rate, target=node, stop_after=duration)
    sim = Simulation(
        sources=[source], entities=[node], fault_schedule=faults, duration=duration
    )
    return sim, received


class TestOverlappingWindows:
    def test_overlap_is_last_write_wins_not_union(self):
        """Two PauseNode windows [1, 3) and [2, 4): the first deactivate
        at t=3 re-enables the node even though the second window is
        still open — flag-flip semantics, documented, not a union."""
        faults = FaultSchedule()
        faults.add(PauseNode("node", start=1.0, end=3.0))
        faults.add(PauseNode("node", start=2.0, end=4.0))
        sim, received = record_sim(faults)
        sim.run()
        assert not [t for t in received if 1.0 <= t < 3.0]
        # Re-enabled by the earlier window's end despite the open overlap.
        assert [t for t in received if 3.0 <= t < 4.0]
        stats = faults.stats
        assert stats.faults_scheduled == 2
        assert stats.faults_activated == 2
        assert stats.faults_deactivated == 2

    def test_nested_window_swallowed_by_outer(self):
        """[1, 4) containing [2, 3): the inner deactivate at t=3 wakes
        the node a second early — same last-write-wins contract."""
        faults = FaultSchedule()
        faults.add(PauseNode("node", start=1.0, end=4.0))
        faults.add(PauseNode("node", start=2.0, end=3.0))
        sim, received = record_sim(faults)
        sim.run()
        assert not [t for t in received if 1.0 <= t < 3.0]
        assert [t for t in received if 3.0 <= t < 4.0]

    def test_overlapping_capacity_windows_restore_healthy_value(self):
        """Both ReduceCapacity windows captured the healthy capacity at
        bootstrap, so whichever restore runs last lands on it."""
        resource = Resource("pool", capacity=8.0)
        faults = FaultSchedule()
        faults.add(ReduceCapacity("pool", factor=0.5, start=1.0, end=3.0))
        faults.add(ReduceCapacity("pool", factor=0.25, start=2.0, end=4.0))
        node = CallbackEntity("node", lambda event: None)
        source = Source.constant(rate=10.0, target=node, stop_after=6.0)
        sim = Simulation(
            sources=[source],
            entities=[node, resource],
            fault_schedule=faults,
            duration=6.0,
        )
        sim.run()
        assert resource.capacity == 8.0


class TestRevokeBeforeFire:
    def test_cancel_before_start_suppresses_everything(self):
        faults = FaultSchedule()
        handle = faults.add(PauseNode("node", start=1.0, end=3.0))
        handle.cancel()
        sim, received = record_sim(faults)
        sim.run()
        # The window never fired: the stream is uninterrupted.
        assert [t for t in received if 1.0 <= t < 3.0]
        stats = faults.stats
        assert stats.faults_scheduled == 1
        assert stats.faults_cancelled == 1
        assert stats.faults_activated == 0
        assert stats.faults_deactivated == 0

    def test_cancel_after_activation_freezes_the_fault(self):
        """Revoking between activate and deactivate cancels the pending
        deactivate: the node stays dark and the ledger shows the
        asymmetry (activated=1, deactivated=0)."""
        faults = FaultSchedule()
        handle = faults.add(PauseNode("node", start=1.0, end=3.0))
        received = []

        def record(event):
            received.append(event.time.to_seconds())

        node = CallbackEntity("node", record)
        source = Source.constant(rate=10.0, target=node, stop_after=5.0)
        from happysim_tpu.faults.fault import one_shot

        sim = Simulation(
            sources=[source],
            entities=[node],
            fault_schedule=faults,
            duration=5.0,
        )
        cancel_event = one_shot(2.0, "test.revoke", lambda event: handle.cancel())
        sim.schedule(cancel_event)
        sim.run()
        # Paused at 1.0 and NEVER resumed (the deactivate was revoked).
        assert not [t for t in received if t >= 1.0]
        stats = faults.stats
        assert stats.faults_activated == 1
        assert stats.faults_deactivated == 0
        assert stats.faults_cancelled == 1


class TestStatsAfterUnwind:
    def test_full_window_lifecycle_counts(self):
        faults = FaultSchedule()
        faults.add(PauseNode("node", start=1.0, end=2.0))
        sim, _ = record_sim(faults)
        sim.run()
        stats = faults.stats
        assert (
            stats.faults_scheduled,
            stats.faults_activated,
            stats.faults_deactivated,
            stats.faults_cancelled,
        ) == (1, 1, 1, 0)

    def test_window_open_at_end_of_run_never_deactivates(self):
        faults = FaultSchedule()
        faults.add(PauseNode("node", start=1.0, end=99.0))
        sim, _ = record_sim(faults, duration=5.0)
        sim.run()
        stats = faults.stats
        assert stats.faults_activated == 1
        assert stats.faults_deactivated == 0

    def test_one_shot_crash_is_not_a_window_transition(self):
        """CrashNode events carry no .activate/.deactivate labels — the
        window ledger ignores them by design (scheduled still counts)."""
        faults = FaultSchedule()
        faults.add(CrashNode("node", at=1.0, restart_at=2.0))
        sim, _ = record_sim(faults)
        sim.run()
        stats = faults.stats
        assert stats.faults_scheduled == 1
        assert stats.faults_activated == 0
        assert stats.faults_deactivated == 0

    def test_stats_before_start_are_all_armed(self):
        faults = FaultSchedule()
        faults.add(PauseNode("node", start=1.0, end=2.0))
        faults.add(PauseNode("node", start=3.0, end=4.0))
        stats = faults.stats
        assert stats.faults_scheduled == 2
        assert stats.faults_activated == 0
        assert stats.faults_deactivated == 0
        assert stats.faults_cancelled == 0
