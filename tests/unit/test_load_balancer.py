"""Unit tests: load-balancing strategies, LB routing, health checking."""

import pytest

from happysim_tpu import (
    ConstantLatency,
    Event,
    Instant,
    Server,
    Simulation,
    Sink,
)
from happysim_tpu.components.load_balancer import (
    BackendInfo,
    ConsistentHash,
    HealthChecker,
    IPHash,
    LeastConnections,
    LeastResponseTime,
    LoadBalancer,
    PowerOfTwoChoices,
    Random,
    RoundRobin,
    WeightedLeastConnections,
    WeightedRoundRobin,
)
from happysim_tpu.core.entity import Entity


def _request(key=None, at=0.0):
    context = {"metadata": {}}
    if key is not None:
        context["metadata"]["client_ip"] = key
    return Event(Instant.from_seconds(at), "request", target=_NULL, context=context)


class _Null(Entity):
    def __init__(self):
        super().__init__("null")

    def handle_event(self, event):
        return None


_NULL = _Null()


def _infos(n, **kwargs):
    return [BackendInfo(backend=_NamedEntity(f"b{i}"), **kwargs) for i in range(n)]


class _NamedEntity(Entity):
    def handle_event(self, event):
        return None


class TestStrategies:
    def test_round_robin_cycles(self):
        s = RoundRobin()
        infos = _infos(3)
        picks = [s.select(infos, _request()).name for _ in range(6)]
        assert picks == ["b0", "b1", "b2", "b0", "b1", "b2"]

    def test_weighted_round_robin_proportional(self):
        s = WeightedRoundRobin()
        infos = _infos(2)
        infos[0].weight = 3.0
        infos[1].weight = 1.0
        picks = [s.select(infos, _request()).name for _ in range(8)]
        assert picks.count("b0") == 6
        assert picks.count("b1") == 2

    def test_random_seeded_deterministic(self):
        infos = _infos(4)
        a = [Random(seed=3).select(infos, _request()).name for _ in range(5)]
        b = [Random(seed=3).select(infos, _request()).name for _ in range(5)]
        assert a == b

    def test_least_connections(self):
        infos = _infos(3)
        infos[0].in_flight = 5
        infos[1].in_flight = 1
        infos[2].in_flight = 3
        assert LeastConnections().select(infos, _request()).name == "b1"

    def test_weighted_least_connections(self):
        infos = _infos(2)
        infos[0].in_flight = 4
        infos[0].weight = 4.0  # score 1.0
        infos[1].in_flight = 2
        infos[1].weight = 1.0  # score 2.0
        assert WeightedLeastConnections().select(infos, _request()).name == "b0"

    def test_least_response_time_prefers_cold_then_fast(self):
        s = LeastResponseTime()
        infos = _infos(2)
        infos[0].total_requests = 1
        infos[0].record_response_time(0.5)
        assert s.select(infos, _request()).name == "b1"  # cold backend first
        infos[1].total_requests = 1
        infos[1].record_response_time(0.1)
        assert s.select(infos, _request()).name == "b1"

    def test_ip_hash_stable(self):
        s = IPHash()
        infos = _infos(5)
        picks = {s.select(infos, _request(key="10.0.0.7")).name for _ in range(10)}
        assert len(picks) == 1

    def test_consistent_hash_minimal_remap(self):
        s = ConsistentHash(virtual_nodes=100)
        infos = _infos(5)
        keys = [f"user-{i}" for i in range(200)]
        before = {k: s.select(infos, _request(key=k)).name for k in keys}
        # Remove one backend: only its keys should move.
        survivors = [i for i in infos if i.name != "b2"]
        after = {k: s.select(survivors, _request(key=k)).name for k in keys}
        moved = [k for k in keys if before[k] != after[k]]
        assert all(before[k] == "b2" for k in moved)
        assert any(before[k] == "b2" for k in keys)

    def test_power_of_two_choices_prefers_less_loaded(self):
        s = PowerOfTwoChoices(seed=0)
        infos = _infos(2)
        infos[0].in_flight = 100
        for _ in range(10):
            assert s.select(infos, _request()).name == "b1"

    def test_empty_backends(self):
        for s in [RoundRobin(), Random(seed=0), LeastConnections(), IPHash()]:
            assert s.select([], _request()) is None


class TestLoadBalancer:
    def _fleet(self, n=3, service=0.1, strategy=None):
        sink = Sink()
        servers = [
            Server(f"s{i}", concurrency=4, service_time=ConstantLatency(service), downstream=sink)
            for i in range(n)
        ]
        lb = LoadBalancer("lb", backends=servers, strategy=strategy or RoundRobin())
        return sink, servers, lb

    def test_round_robin_distribution(self):
        sink, servers, lb = self._fleet()
        sim = Simulation(entities=[lb, sink, *servers])
        sim.schedule([
            Event(Instant.from_seconds(i * 0.01), "request", target=lb) for i in range(9)
        ])
        sim.run()
        assert sink.events_received == 9
        assert [s.requests_completed for s in servers] == [3, 3, 3]
        assert lb.stats.requests_forwarded == 9

    def test_unhealthy_backend_skipped(self):
        sink, servers, lb = self._fleet()
        lb.mark_unhealthy(servers[1])
        sim = Simulation(entities=[lb, sink, *servers])
        sim.schedule([
            Event(Instant.from_seconds(i * 0.01), "request", target=lb) for i in range(8)
        ])
        sim.run()
        assert servers[1].requests_completed == 0
        assert sink.events_received == 8

    def test_no_backends_rejects(self):
        lb = LoadBalancer("lb", backends=[])
        sim = Simulation(entities=[lb])
        sim.schedule(Event(Instant.Epoch, "request", target=lb))
        sim.run()
        assert lb.stats.no_backend_available == 1

    def test_in_flight_tracked_through_completion(self):
        sink, servers, lb = self._fleet(n=2, service=1.0, strategy=LeastConnections())
        sim = Simulation(entities=[lb, sink, *servers])
        sim.schedule([
            Event(Instant.from_seconds(i * 0.1), "request", target=lb) for i in range(4)
        ])
        sim.run()
        # LeastConnections alternates between the two idle-then-busy servers.
        assert [s.requests_completed for s in servers] == [2, 2]
        for s in servers:
            assert lb.backend_info(s).in_flight == 0

    def test_response_time_ewma_recorded(self):
        sink, servers, lb = self._fleet(n=2, service=0.25)
        sim = Simulation(entities=[lb, sink, *servers])
        sim.schedule([
            Event(Instant.from_seconds(i * 1.0), "request", target=lb) for i in range(4)
        ])
        sim.run()
        for s in servers:
            assert lb.backend_info(s).response_time_ewma_s == pytest.approx(0.25)


class TestHealthChecker:
    def test_crash_detected_and_recovers(self):
        sink, servers, lb = (None, None, None)
        sink = Sink()
        servers = [
            Server(f"s{i}", concurrency=1, service_time=ConstantLatency(0.01), downstream=sink)
            for i in range(2)
        ]
        lb = LoadBalancer("lb", backends=servers)
        checker = HealthChecker(
            "hc", lb, interval=0.5, unhealthy_threshold=2, healthy_threshold=2
        )
        sim = Simulation(entities=[lb, sink, *servers, checker], probes=[checker], duration=10.0)
        # Crash s0 at t=1, revive at t=5 (via scheduled callbacks).
        sim.schedule(
            [
                Event.once(Instant.from_seconds(1.0), lambda _: setattr(servers[0], "_crashed", True), "crash"),
                Event.once(Instant.from_seconds(5.0), lambda _: setattr(servers[0], "_crashed", False), "revive"),
                # Keep a primary event pending so the daemon-only
                # auto-terminate doesn't end the run right after the revive.
                Event.once(Instant.from_seconds(9.5), lambda _: None, "keepalive"),
            ]
        )
        sim.run()
        assert checker.stats.transitions_to_unhealthy == 1
        assert checker.stats.transitions_to_healthy == 1
        assert lb.backend_info(servers[0]).healthy
