"""Sharding-strategy math: determinism, balance, range boundaries, and
consistent-hash stability under resharding.

Parity target: the strategy cases of
``happysimulator/tests/unit/test_sharded_store.py``.
"""

from __future__ import annotations

from collections import Counter

import pytest

from happysim_tpu.components.datastore import (
    ConsistentHashSharding,
    HashSharding,
    RangeSharding,
)

KEYS = [f"user:{i:05d}" for i in range(2000)]


class TestHashSharding:
    def test_deterministic(self):
        strategy = HashSharding()
        assert [strategy.get_shard(k, 8) for k in KEYS[:50]] == [
            strategy.get_shard(k, 8) for k in KEYS[:50]
        ]

    def test_all_shards_in_range(self):
        strategy = HashSharding()
        assert all(0 <= strategy.get_shard(k, 5) < 5 for k in KEYS)

    def test_roughly_balanced(self):
        strategy = HashSharding()
        counts = Counter(strategy.get_shard(k, 8) for k in KEYS)
        assert len(counts) == 8
        assert max(counts.values()) < 2 * min(counts.values())

    def test_full_reshard_on_count_change(self):
        """The failure mode consistent hashing fixes: changing the shard
        count moves MOST keys under plain modulo hashing."""
        strategy = HashSharding()
        moved = sum(
            strategy.get_shard(k, 8) != strategy.get_shard(k, 9) for k in KEYS
        )
        assert moved > len(KEYS) * 0.6


class TestRangeSharding:
    def test_explicit_boundaries_partition_the_keyspace(self):
        strategy = RangeSharding(boundaries=["g", "p"])
        assert strategy.get_shard("apple", 3) == 0
        assert strategy.get_shard("grape", 3) == 1
        assert strategy.get_shard("zebra", 3) == 2

    def test_boundary_key_goes_right(self):
        strategy = RangeSharding(boundaries=["m"])
        assert strategy.get_shard("m", 2) == 1
        assert strategy.get_shard("lzzz", 2) == 0

    def test_preserves_order_locality(self):
        """Adjacent keys land in the same or adjacent shards — the whole
        point of range sharding (scans touch few shards)."""
        strategy = RangeSharding(boundaries=["b", "c", "d"])
        ordered = sorted(KEYS[:100])
        shards = [strategy.get_shard(k, 4) for k in ordered]
        assert shards == sorted(shards)

    def test_default_boundaries_cover_alphabet(self):
        strategy = RangeSharding()
        shards = {strategy.get_shard(k, 4) for k in ("apple", "mango", "zebra")}
        assert all(0 <= s < 4 for s in shards)


class TestConsistentHashSharding:
    def test_deterministic_with_seed(self):
        a = ConsistentHashSharding(virtual_nodes=50, seed=3)
        b = ConsistentHashSharding(virtual_nodes=50, seed=3)
        assert [a.get_shard(k, 8) for k in KEYS[:100]] == [
            b.get_shard(k, 8) for k in KEYS[:100]
        ]

    def test_minimal_movement_on_growth(self):
        """Adding one shard must move only ~1/(n+1) of keys — the
        property plain modulo hashing lacks."""
        strategy = ConsistentHashSharding(virtual_nodes=100, seed=5)
        before = [strategy.get_shard(k, 8) for k in KEYS]
        after = [strategy.get_shard(k, 9) for k in KEYS]
        moved = sum(a != b for a, b in zip(before, after))
        assert moved < len(KEYS) * 0.3  # ~1/9 expected, generous bound
        # And every moved key went TO the new shard, not reshuffled.
        assert all(b == 8 for a, b in zip(before, after) if a != b)

    def test_balance_with_enough_vnodes(self):
        strategy = ConsistentHashSharding(virtual_nodes=200, seed=7)
        counts = Counter(strategy.get_shard(k, 6) for k in KEYS)
        assert len(counts) == 6
        assert max(counts.values()) < 3 * min(counts.values())

    def test_few_vnodes_imbalance_is_real(self):
        """With 1 vnode per shard the ring is lumpy — documents why the
        default is 100."""
        lumpy = ConsistentHashSharding(virtual_nodes=1, seed=2)
        counts = Counter(lumpy.get_shard(k, 6) for k in KEYS)
        smooth = ConsistentHashSharding(virtual_nodes=200, seed=2)
        smooth_counts = Counter(smooth.get_shard(k, 6) for k in KEYS)

        def spread(c):
            return max(c.values()) / max(min(c.values()), 1)

        assert spread(counts) > spread(smooth_counts)
