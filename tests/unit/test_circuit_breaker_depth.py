"""Circuit-breaker state-machine depth: the full transition matrix,
half-open probe limiting, lazy recovery, and counter hygiene.

The composite resilience tests drive one happy path; these pin every
edge of CLOSED -> OPEN -> HALF_OPEN -> {CLOSED, OPEN} where breaker
bugs live (stale-era outcomes, probe floods, counter leaks across
transitions).

Parity target: ``happysimulator/tests/unit/test_circuit_breaker.py``.
"""

from __future__ import annotations

import pytest

from happysim_tpu import Instant, Simulation, Sink
from happysim_tpu.components.resilience.circuit_breaker import (
    CircuitBreaker,
    CircuitState,
)


def make(failure_threshold=3, success_threshold=2, recovery_timeout=10.0,
         half_open_max_probes=1):
    breaker = CircuitBreaker(
        "breaker",
        downstream=Sink("backend"),
        failure_threshold=failure_threshold,
        success_threshold=success_threshold,
        recovery_timeout=recovery_timeout,
        half_open_max_probes=half_open_max_probes,
    )
    sim = Simulation(entities=[breaker], end_time=Instant.from_seconds(1000.0))
    return breaker, sim


def advance(sim, seconds: float) -> None:
    sim._clock.update(sim.now + seconds)


class TestClosedToOpen:
    def test_opens_exactly_at_threshold(self):
        breaker, _ = make(failure_threshold=3)
        breaker.record_failure()
        breaker.record_failure()
        assert breaker.state is CircuitState.CLOSED
        breaker.record_failure()
        assert breaker.state is CircuitState.OPEN

    def test_success_resets_the_failure_streak(self):
        breaker, _ = make(failure_threshold=3)
        breaker.record_failure()
        breaker.record_failure()
        breaker.record_success()  # consecutive-failure counter resets
        breaker.record_failure()
        breaker.record_failure()
        assert breaker.state is CircuitState.CLOSED
        breaker.record_failure()
        assert breaker.state is CircuitState.OPEN

    def test_transition_counter_increments_once(self):
        breaker, _ = make(failure_threshold=1)
        breaker.record_failure()
        assert breaker.state_transitions == 1
        breaker.record_failure()  # already open: no double transition
        assert breaker.state_transitions == 1


class TestRecovery:
    def test_half_open_exactly_at_timeout(self):
        breaker, sim = make(failure_threshold=1, recovery_timeout=10.0)
        breaker.record_failure()
        advance(sim, 9.999)
        assert breaker.state is CircuitState.OPEN
        advance(sim, 0.002)
        assert breaker.state is CircuitState.HALF_OPEN

    def test_half_open_success_threshold_closes(self):
        breaker, sim = make(
            failure_threshold=1, success_threshold=2, recovery_timeout=1.0
        )
        breaker.record_failure()
        advance(sim, 1.1)
        assert breaker.state is CircuitState.HALF_OPEN
        breaker.record_success()
        assert breaker.state is CircuitState.HALF_OPEN
        breaker.record_success()
        assert breaker.state is CircuitState.CLOSED

    def test_half_open_failure_reopens_immediately(self):
        breaker, sim = make(failure_threshold=1, recovery_timeout=1.0)
        breaker.record_failure()
        advance(sim, 1.1)
        assert breaker.state is CircuitState.HALF_OPEN
        breaker.record_failure()
        assert breaker.state is CircuitState.OPEN
        # And the recovery clock restarted: still open at +0.9.
        advance(sim, 0.9)
        assert breaker.state is CircuitState.OPEN
        advance(sim, 0.2)
        assert breaker.state is CircuitState.HALF_OPEN

    def test_reopen_clears_success_progress(self):
        breaker, sim = make(
            failure_threshold=1, success_threshold=2, recovery_timeout=1.0
        )
        breaker.record_failure()
        advance(sim, 1.1)
        breaker.record_success()  # 1 of 2
        breaker.record_failure()  # reopen
        advance(sim, 1.1)
        assert breaker.state is CircuitState.HALF_OPEN
        breaker.record_success()  # progress must restart at 1 of 2
        assert breaker.state is CircuitState.HALF_OPEN
        breaker.record_success()
        assert breaker.state is CircuitState.CLOSED


class TestForcedTransitions:
    def test_force_open_rejects(self):
        breaker, sim = make()
        breaker.force_open()
        assert breaker.state is CircuitState.OPEN

    def test_force_close_from_open(self):
        breaker, sim = make(failure_threshold=1)
        breaker.record_failure()
        breaker.force_close()
        assert breaker.state is CircuitState.CLOSED

    def test_reset_clears_counters(self):
        breaker, sim = make(failure_threshold=3)
        breaker.record_failure()
        breaker.record_failure()
        breaker.reset()
        assert breaker.failure_count == 0
        breaker.record_failure()
        breaker.record_failure()
        assert breaker.state is CircuitState.CLOSED


class TestEventFlow:
    def _wire(self, **kwargs):
        sink = Sink("backend")
        breaker = CircuitBreaker("breaker", downstream=sink, **kwargs)
        sim = Simulation(
            entities=[breaker, sink], end_time=Instant.from_seconds(1000.0)
        )
        return breaker, sink, sim

    def _request(self, sim, breaker, at):
        from happysim_tpu.core.event import Event

        sim.schedule(Event(Instant.from_seconds(at), "req", target=breaker))

    def test_open_circuit_drops_requests(self):
        breaker, sink, sim = self._wire(failure_threshold=1, call_timeout=None)
        breaker.force_open()
        self._request(sim, breaker, 0.5)
        sim.run()
        assert sink.events_received == 0
        assert breaker.requests_rejected == 1

    def test_closed_circuit_forwards(self):
        breaker, sink, sim = self._wire(call_timeout=None)
        self._request(sim, breaker, 0.5)
        sim.run()
        assert sink.events_received == 1
        assert breaker.requests_allowed == 1

    def test_half_open_probe_cap(self):
        breaker, sink, sim = self._wire(
            failure_threshold=1,
            recovery_timeout=1.0,
            call_timeout=None,
            half_open_max_probes=1,
        )
        breaker.record_failure()
        # Two same-instant requests after recovery: only ONE probes.
        self._request(sim, breaker, 1.5)
        self._request(sim, breaker, 1.5)
        sim.run()
        assert breaker.requests_allowed == 1
        assert breaker.requests_rejected == 1
