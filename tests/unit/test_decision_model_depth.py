"""Decision-model contracts: argmax vs softmax, rule priority, the
satisficing scan, conformity blending, and composite voting.

Parity target: the per-model cases of
``happysimulator/tests/unit/test_behavior_decision.py``.
"""

from __future__ import annotations

import random
from collections import Counter

import pytest

from happysim_tpu.components.behavior import (
    BoundedRationalityModel,
    Choice,
    CompositeModel,
    DecisionContext,
    PersonalityTraits,
    Rule,
    RuleBasedModel,
    SocialInfluenceModel,
    UtilityModel,
)
from happysim_tpu.components.behavior.state import AgentState


def context(choices, *, traits=None, social=None, stimulus=None):
    return DecisionContext(
        traits=PersonalityTraits.big_five(**(traits or {})),
        state=AgentState(),
        choices=[Choice(c) if isinstance(c, str) else c for c in choices],
        stimulus=stimulus or {},
        social_context=social or {},
    )


PRICE = {"cheap": 0.9, "mid": 0.5, "pricey": 0.1}


def utility(choice, _context):
    return PRICE[choice.action]


class TestUtilityModel:
    def test_zero_temperature_is_argmax(self):
        model = UtilityModel(utility)
        rng = random.Random(1)
        for _ in range(10):
            assert model.decide(context(PRICE), rng).action == "cheap"

    def test_softmax_spreads_with_temperature(self):
        model = UtilityModel(utility, temperature=2.0)
        rng = random.Random(2)
        picks = Counter(model.decide(context(PRICE), rng).action for _ in range(500))
        assert set(picks) == set(PRICE)  # high temperature: all explored
        assert picks["cheap"] > picks["pricey"]  # ...still biased by utility

    def test_low_temperature_concentrates(self):
        cold = UtilityModel(utility, temperature=0.05)
        rng = random.Random(3)
        picks = Counter(cold.decide(context(PRICE), rng).action for _ in range(300))
        assert picks["cheap"] > 290

    def test_empty_choices_abstains(self):
        assert UtilityModel(utility).decide(context([]), random.Random(1)) is None


class TestRuleBasedModel:
    RULES = [
        Rule(condition=lambda ctx: ctx.stimulus.get("sale", False), action="cheap",
             priority=10),
        Rule(condition=lambda ctx: True, action="mid", priority=1),
    ]

    def test_highest_priority_match_wins(self):
        model = RuleBasedModel(self.RULES)
        picked = model.decide(
            context(PRICE, stimulus={"sale": True}), random.Random(1)
        )
        assert picked.action == "cheap"

    def test_falls_through_to_lower_priority(self):
        model = RuleBasedModel(self.RULES)
        assert model.decide(context(PRICE), random.Random(1)).action == "mid"

    def test_default_action_when_nothing_matches(self):
        model = RuleBasedModel(
            [Rule(condition=lambda ctx: False, action="cheap")],
            default_action="pricey",
        )
        assert model.decide(context(PRICE), random.Random(1)).action == "pricey"

    def test_no_match_no_default_abstains(self):
        model = RuleBasedModel([Rule(condition=lambda ctx: False, action="cheap")])
        assert model.decide(context(PRICE), random.Random(1)) is None

    def test_fired_rule_with_absent_action_abstains(self):
        """Documented short-circuit: a rule that fires but names an
        action outside the choice set abstains — no fall-through to
        lower rules or the default."""
        model = RuleBasedModel(
            [Rule(condition=lambda ctx: True, action="not_offered")],
            default_action="mid",
        )
        assert model.decide(context(PRICE), random.Random(1)) is None


class TestBoundedRationality:
    def test_high_aspiration_degenerates_to_best(self):
        model = BoundedRationalityModel(utility, aspiration=5.0)  # unreachable
        assert model.decide(context(PRICE), random.Random(4)).action == "cheap"

    def test_low_aspiration_takes_first_good_enough(self):
        model = BoundedRationalityModel(utility, aspiration=0.4)
        picks = Counter(
            model.decide(context(PRICE), random.Random(seed)).action
            for seed in range(200)
        )
        # cheap and mid both clear 0.4; scan order is random, so both
        # appear — the satisficer does NOT always find the optimum.
        assert picks["mid"] > 0 and picks["cheap"] > 0
        assert picks["pricey"] == 0

    def test_zero_aspiration_is_random_first_hit(self):
        model = BoundedRationalityModel(utility, aspiration=0.0)
        picks = Counter(
            model.decide(context(PRICE), random.Random(seed)).action
            for seed in range(300)
        )
        assert all(picks[a] > 50 for a in PRICE)


class TestSocialInfluence:
    def test_unanimous_peers_pull_an_agreeable_agent(self):
        model = SocialInfluenceModel(utility, conformity_weight=1.0)
        social = {"peer_actions": {"pricey": 50}}
        picks = Counter(
            model.decide(
                context(PRICE, traits={"agreeableness": 1.0}, social=social),
                random.Random(seed),
            ).action
            for seed in range(300)
        )
        assert picks["pricey"] > 250  # pressure 1.0: peers dominate

    def test_disagreeable_agent_ignores_peers(self):
        model = SocialInfluenceModel(utility, conformity_weight=1.0)
        social = {"peer_actions": {"pricey": 50}}
        picks = Counter(
            model.decide(
                context(PRICE, traits={"agreeableness": 0.0}, social=social),
                random.Random(seed),
            ).action
            for seed in range(300)
        )
        assert picks["cheap"] > picks["pricey"]

    def test_no_peer_signal_reduces_to_utility_sampling(self):
        model = SocialInfluenceModel(utility, conformity_weight=0.5)
        picks = Counter(
            model.decide(
                context(PRICE, traits={"agreeableness": 0.5}), random.Random(seed)
            ).action
            for seed in range(300)
        )
        assert picks["cheap"] > picks["pricey"]


class TestCompositeModel:
    def test_weighted_vote_wins(self):
        always_cheap = UtilityModel(lambda c, _: 1.0 if c.action == "cheap" else 0.0)
        always_mid = UtilityModel(lambda c, _: 1.0 if c.action == "mid" else 0.0)
        model = CompositeModel([(always_cheap, 1.0), (always_mid, 2.0)])
        assert model.decide(context(PRICE), random.Random(1)).action == "mid"

    def test_tie_goes_to_first_voter(self):
        always_cheap = UtilityModel(lambda c, _: 1.0 if c.action == "cheap" else 0.0)
        always_mid = UtilityModel(lambda c, _: 1.0 if c.action == "mid" else 0.0)
        model = CompositeModel([(always_cheap, 1.0), (always_mid, 1.0)])
        assert model.decide(context(PRICE), random.Random(1)).action == "cheap"

    def test_abstaining_submodel_casts_no_vote(self):
        abstainer = RuleBasedModel([Rule(condition=lambda ctx: False, action="x")])
        always_mid = UtilityModel(lambda c, _: 1.0 if c.action == "mid" else 0.0)
        model = CompositeModel([(abstainer, 5.0), (always_mid, 1.0)])
        assert model.decide(context(PRICE), random.Random(1)).action == "mid"
