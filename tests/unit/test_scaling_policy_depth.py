"""Auto-scaling policy math at the boundaries.

The deployment tests drive a whole AutoScaler loop; these pin the pure
``evaluate`` contracts where sizing bugs live: clamping, rounding,
empty fleets, threshold equality, and one-at-a-time queue scaling.

Parity target: the policy cases of
``happysimulator/tests/unit/test_auto_scaler.py``.
"""

from __future__ import annotations

import pytest

from happysim_tpu.components.deployment import (
    QueueDepthScaling,
    StepScaling,
    TargetUtilization,
)


class FakeBackend:
    def __init__(self, utilization=None, depth=None):
        if utilization is not None:
            self.utilization = utilization
        if depth is not None:
            self.depth = depth


def fleet(*utilizations):
    return [FakeBackend(utilization=u) for u in utilizations]


class TestTargetUtilization:
    def test_scales_out_proportionally(self):
        policy = TargetUtilization(target=0.5)
        # 4 instances at 100%: the load needs 8 at 50%.
        assert policy.evaluate(fleet(1.0, 1.0, 1.0, 1.0), 4, 1, 100) == 8

    def test_scales_in_proportionally(self):
        policy = TargetUtilization(target=0.8)
        # 8 instances at 20%: 0.2*8/0.8 = 2 carry the load at target.
        assert policy.evaluate(fleet(*[0.2] * 8), 8, 1, 100) == 2

    def test_at_target_holds(self):
        policy = TargetUtilization(target=0.7)
        assert policy.evaluate(fleet(0.7, 0.7), 2, 1, 10) == 2

    def test_rounds_half_up(self):
        policy = TargetUtilization(target=0.5)
        # 3 * 0.75/0.5 = 4.5 exactly (binary-exact operands) -> 5.
        assert policy.evaluate(fleet(0.75, 0.75, 0.75), 3, 1, 10) == 5

    def test_clamps_to_bounds(self):
        policy = TargetUtilization(target=0.1)
        assert policy.evaluate(fleet(1.0), 1, 1, 5) == 5
        policy = TargetUtilization(target=1.0)
        assert policy.evaluate(fleet(0.01), 10, 3, 20) == 3

    def test_empty_fleet_returns_min(self):
        assert TargetUtilization(0.5).evaluate([], 0, 2, 10) == 2

    def test_rejects_bad_target(self):
        with pytest.raises(ValueError):
            TargetUtilization(target=0.0)
        with pytest.raises(ValueError):
            TargetUtilization(target=1.5)


class TestStepScaling:
    POLICY = StepScaling(steps=[(0.9, 3), (0.7, 1), (0.2, 0), (0.0, -1)])

    def test_highest_crossed_step_wins(self):
        assert self.POLICY.evaluate(fleet(0.95), 5, 1, 20) == 8  # +3
        assert self.POLICY.evaluate(fleet(0.75), 5, 1, 20) == 6  # +1

    def test_threshold_equality_crosses(self):
        assert self.POLICY.evaluate(fleet(0.9), 5, 1, 20) == 8

    def test_idle_band_scales_in(self):
        assert self.POLICY.evaluate(fleet(0.05), 5, 1, 20) == 4  # -1

    def test_hold_band_holds(self):
        assert self.POLICY.evaluate(fleet(0.4), 5, 1, 20) == 5  # the 0-step

    def test_clamps_to_bounds(self):
        assert self.POLICY.evaluate(fleet(0.99), 19, 1, 20) == 20
        assert self.POLICY.evaluate(fleet(0.01), 1, 1, 20) == 1

    def test_mean_over_fleet_not_max(self):
        # One hot + three idle: mean 0.25 sits in the hold band.
        assert self.POLICY.evaluate(fleet(1.0, 0.0, 0.0, 0.0), 4, 1, 20) == 4


class TestQueueDepthScaling:
    POLICY = QueueDepthScaling(scale_out_threshold=100, scale_in_threshold=10)

    def backlog(self, *depths):
        return [FakeBackend(depth=d) for d in depths]

    def test_scale_out_one_at_a_time(self):
        assert self.POLICY.evaluate(self.backlog(60, 50), 4, 1, 10) == 5

    def test_scale_out_threshold_is_inclusive(self):
        assert self.POLICY.evaluate(self.backlog(100), 4, 1, 10) == 5
        assert self.POLICY.evaluate(self.backlog(99), 4, 1, 10) == 4

    def test_scale_in_threshold_is_inclusive(self):
        assert self.POLICY.evaluate(self.backlog(10), 4, 1, 10) == 3
        assert self.POLICY.evaluate(self.backlog(11), 4, 1, 10) == 4

    def test_respects_bounds(self):
        assert self.POLICY.evaluate(self.backlog(1000), 10, 1, 10) == 10
        assert self.POLICY.evaluate(self.backlog(0), 1, 1, 10) == 1

    def test_backends_without_depth_are_ignored(self):
        mixed = [FakeBackend(depth=200), FakeBackend(utilization=0.5)]
        assert self.POLICY.evaluate(mixed, 2, 1, 10) == 3
