"""B-tree structural edge cases: split cascades, depth growth, delete
bookkeeping, scan boundaries, and cost accounting.

Parity target: ``happysimulator/components/storage/btree.py`` (order-based
splits, per-level page costs); complements the happy-path coverage in
``tests/unit/test_storage.py``.
"""

from __future__ import annotations

import random

import pytest

from happysim_tpu.components.storage import BTree


def scan_sync(tree: BTree, **kwargs) -> list:
    """Drive the cost-yielding scan generator to its return value."""
    gen = tree.scan(**kwargs)
    try:
        while True:
            next(gen)
    except StopIteration as stop:
        return stop.value


def fill(tree: BTree, n: int, *, shuffled: bool = False, seed: int = 0):
    keys = [f"k{i:05d}" for i in range(n)]
    if shuffled:
        random.Random(seed).shuffle(keys)
    for key in keys:
        tree.put_sync(key, key.upper())
    return sorted(keys)


class TestSplitsAndDepth:
    def test_root_splits_exactly_at_order(self):
        tree = BTree("t", order=4)
        for i in range(3):  # order-1 keys fit in the root
            tree.put_sync(f"k{i}", i)
        assert tree.depth == 1 and tree.stats.node_splits == 0
        tree.put_sync("k3", 3)  # the order-th key forces the first split
        assert tree.depth == 2
        assert tree.stats.node_splits >= 1

    def test_depth_grows_logarithmically(self):
        tree = BTree("t", order=4)
        fill(tree, 500, shuffled=True)
        # order 4 => depth bounded by ~log2(500) + slack; a linear-depth
        # bug (split not reattaching children) blows way past this.
        assert tree.depth <= 12
        assert tree.size == 500

    def test_sorted_and_shuffled_inserts_agree(self):
        a, b = BTree("a", order=6), BTree("b", order=6)
        keys = fill(a, 300)
        fill(b, 300, shuffled=True, seed=7)
        assert [k for k, _ in scan_sync(a)] == keys
        assert [k for k, _ in scan_sync(b)] == keys

    def test_min_order_three(self):
        tree = BTree("t", order=3)
        keys = fill(tree, 100, shuffled=True)
        assert [k for k, _ in scan_sync(tree)] == keys
        with pytest.raises(ValueError):
            BTree("bad", order=2)


class TestUpdatesAndDeletes:
    def test_update_does_not_grow_size(self):
        tree = BTree("t", order=4)
        tree.put_sync("k", 1)
        tree.put_sync("k", 2)
        assert tree.size == 1
        assert tree.get_sync("k") == 2

    def test_delete_internal_routing_finds_leaf_copy(self):
        """Separator keys are routing copies; deleting a key that also
        appears as a separator must remove the LEAF record."""
        tree = BTree("t", order=4)
        keys = fill(tree, 64)
        for key in keys:
            assert tree.delete_sync(key), key
        assert tree.size == 0
        assert scan_sync(tree) == []

    def test_delete_missing_returns_false_and_counts(self):
        tree = BTree("t", order=4)
        tree.put_sync("a", 1)
        assert not tree.delete_sync("zz")
        assert tree.size == 1
        assert tree.stats.deletes == 1

    def test_reinsert_after_delete(self):
        tree = BTree("t", order=4)
        fill(tree, 32)
        tree.delete_sync("k00010")
        assert tree.get_sync("k00010") is None
        tree.put_sync("k00010", "back")
        assert tree.get_sync("k00010") == "back"

    def test_random_interleaved_ops_match_dict(self):
        tree = BTree("t", order=5)
        oracle: dict[str, int] = {}
        rng = random.Random(3)
        for step in range(800):
            key = f"k{rng.randint(0, 120):04d}"
            action = rng.random()
            if action < 0.55:
                oracle[key] = step
                tree.put_sync(key, step)
            elif action < 0.8:
                existed = key in oracle
                oracle.pop(key, None)
                assert tree.delete_sync(key) == existed
            else:
                assert tree.get_sync(key) == oracle.get(key)
        assert tree.size == len(oracle)
        assert [k for k, _ in scan_sync(tree)] == sorted(oracle)


class TestScanBoundaries:
    def test_scan_range_is_inclusive_exclusive(self):
        tree = BTree("t", order=4)
        fill(tree, 20)
        keys = [k for k, _ in scan_sync(tree, start_key="k00005", end_key="k00010")]
        assert keys == [f"k{i:05d}" for i in range(5, 10)]

    def test_scan_open_ends(self):
        tree = BTree("t", order=4)
        all_keys = fill(tree, 10)
        assert [k for k, _ in scan_sync(tree, start_key="k00007")] == all_keys[7:]
        assert [k for k, _ in scan_sync(tree, end_key="k00003")] == all_keys[:3]

    def test_scan_empty_tree(self):
        assert scan_sync(BTree("t", order=4)) == []

    def test_scan_range_outside_keys(self):
        tree = BTree("t", order=4)
        fill(tree, 5)
        assert scan_sync(tree, start_key="zzz") == []


class TestCostModel:
    def test_get_latency_tracks_depth(self):
        tree = BTree("t", order=4, page_read_latency=0.001)
        fill(tree, 200, shuffled=True)
        gen = tree.get("k00100")
        first_cost = next(gen)
        assert first_cost == pytest.approx(tree.depth * 0.001)

    def test_put_pays_write_after_read(self):
        tree = BTree("t", order=4, page_read_latency=0.001, page_write_latency=0.004)
        costs = list(tree.put("a", 1))
        assert costs[0] == pytest.approx(tree.depth * 0.001, abs=1e-9) or costs
        assert any(c == pytest.approx(0.004) or c >= 0.004 for c in costs)

    def test_hit_miss_accounting(self):
        tree = BTree("t", order=4)
        tree.put_sync("a", 1)
        tree.get_sync("a")
        tree.get_sync("missing")
        assert tree.stats.hits == 1
        assert tree.stats.misses == 1
