"""Event-lifecycle edges: hook ordering, one-shot firing, cancellation,
and drop-unwind interactions.

The composite tests (`test_hook_composition.py`) wire whole component
stacks; these pin the CORE contracts those stacks rely on — hooks fire in
registration order, exactly once, at the finish time (not schedule time);
cancellation is lazy and idempotent; transfer moves rather than copies;
a drop fires deferred hooks ahead of live ones.

Parity target: ``happysimulator/core/event.py`` hook/cancel semantics and
``happysimulator/tests/unit/test_event.py``.
"""

from __future__ import annotations

from happysim_tpu import ConstantLatency, Instant, Server, Simulation, Sink, Source
from happysim_tpu.core.event import Event


def _instant(seconds: float) -> Instant:
    return Instant.from_seconds(seconds)


class TestHookOrdering:
    def test_hooks_fire_in_registration_order(self):
        order = []
        event = Event(_instant(1.0), "op", target=Sink("s"))
        event.add_completion_hook(lambda t: order.append("first") or None)
        event.add_completion_hook(lambda t: order.append("second") or None)
        event.add_completion_hook(lambda t: order.append("third") or None)
        event._finish(None)
        assert order == ["first", "second", "third"]

    def test_hooks_fire_exactly_once(self):
        calls = []
        event = Event(_instant(1.0), "op", target=Sink("s"))
        event.add_completion_hook(lambda t: calls.append(t) or None)
        event._finish(None)
        event._finish(None)  # one-shot: list was swapped out
        assert len(calls) == 1

    def test_hooks_receive_finish_time_not_schedule_time(self):
        """A generator handler finishes LATER than the event's time; hooks
        must see the completion instant (latency accounting depends on it)."""
        seen = []
        sink = Sink("sink")
        server = Server(
            "srv", service_time=ConstantLatency(0.25), downstream=sink
        )
        request = Event(_instant(0.0), "req", target=server)
        request.add_completion_hook(lambda t: seen.append(t.to_seconds()) or None)
        sim = Simulation(entities=[server, sink], end_time=_instant(2.0))
        sim.schedule(request)
        sim.run()
        assert seen == [0.25]

    def test_hook_produced_events_are_scheduled(self):
        sink = Sink("sink")
        event = Event(_instant(0.5), "op", target=Sink("other"))
        event.add_completion_hook(
            lambda t: Event(t, "follow_up", target=sink)
        )
        produced = event._finish(None)
        assert [e.event_type for e in produced] == ["follow_up"]
        assert produced[0].target is sink

    def test_later_hook_sees_earlier_hooks_side_effects(self):
        state = {}
        event = Event(_instant(1.0), "op", target=Sink("s"))
        event.add_completion_hook(lambda t: state.update(a=1) or None)
        event.add_completion_hook(
            lambda t: state.update(saw_a=("a" in state)) or None
        )
        event._finish(None)
        assert state["saw_a"] is True


class TestTransferHooks:
    def test_transfer_moves_not_copies(self):
        calls = []
        inbound = Event(_instant(1.0), "in", target=Sink("a"))
        inbound.add_completion_hook(lambda t: calls.append("x") or None)
        relay = Event(_instant(1.0), "out", target=Sink("b"))
        inbound.transfer_hooks(relay)
        inbound._finish(None)  # must NOT fire the moved hook
        assert calls == []
        relay._finish(None)
        assert calls == ["x"]

    def test_transfer_preserves_order_after_recipients_own_hooks(self):
        order = []
        inbound = Event(_instant(1.0), "in", target=Sink("a"))
        inbound.add_completion_hook(lambda t: order.append("moved") or None)
        relay = Event(_instant(1.0), "out", target=Sink("b"))
        relay.add_completion_hook(lambda t: order.append("own") or None)
        inbound.transfer_hooks(relay)
        relay._finish(None)
        assert order == ["own", "moved"]


class TestCancellation:
    def test_cancelled_event_is_skipped_by_the_loop(self):
        sink = Sink("sink")
        sim = Simulation(entities=[sink], end_time=_instant(1.0))
        event = Event(_instant(0.5), "op", target=sink)
        sim.schedule(event)
        event.cancel()
        sim.run()
        assert sink.events_received == 0

    def test_cancel_is_idempotent(self):
        event = Event(_instant(1.0), "op", target=Sink("s"))
        event.cancel()
        event.cancel()
        assert event.cancelled

    def test_cancelled_events_hooks_do_not_fire_via_loop(self):
        calls = []
        sink = Sink("sink")
        sim = Simulation(entities=[sink], end_time=_instant(1.0))
        event = Event(_instant(0.5), "op", target=sink)
        event.add_completion_hook(lambda t: calls.append(t) or None)
        sim.schedule(event)
        event.cancel()
        sim.run()
        assert calls == []

    def test_cancel_after_completion_changes_nothing(self):
        calls = []
        sink = Sink("sink")
        sim = Simulation(entities=[sink], end_time=_instant(1.0))
        event = Event(_instant(0.2), "op", target=sink)
        event.add_completion_hook(lambda t: calls.append(t) or None)
        sim.schedule(event)
        sim.run()
        event.cancel()
        assert len(calls) == 1
        assert sink.events_received == 1


class TestDropUnwind:
    def test_drop_marks_metadata_and_fires_hooks(self):
        seen = []
        event = Event(_instant(1.0), "op", target=Sink("s"))
        event.add_completion_hook(lambda t: seen.append(event.dropped_by) or None)
        event.complete_as_dropped(_instant(2.0), "queue_full")
        assert seen == ["queue_full"]
        assert event.dropped_by == "queue_full"

    def test_deferred_hooks_fire_before_live_ones_on_drop(self):
        order = []
        event = Event(_instant(1.0), "op", target=Sink("s"))
        event.context["_deferred_hooks"] = [
            lambda t: order.append("deferred") or None
        ]
        event.add_completion_hook(lambda t: order.append("live") or None)
        event.complete_as_dropped(_instant(2.0), "drop")
        assert order == ["deferred", "live"]

    def test_untouched_event_reports_not_dropped(self):
        event = Event(_instant(1.0), "op", target=Sink("s"))
        assert event.dropped_by is None

    def test_drop_hooks_fire_once_even_if_finished_later(self):
        calls = []
        event = Event(_instant(1.0), "op", target=Sink("s"))
        event.add_completion_hook(lambda t: calls.append("hook") or None)
        event.complete_as_dropped(_instant(2.0), "drop")
        event._finish(None)
        assert calls == ["hook"]
