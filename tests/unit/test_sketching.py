"""Sketching package tests (SURVEY §2.3 parity: mergeable bounded-memory
summaries with seeded reproducibility)."""

import math
import random

import pytest

from happysim_tpu.sketching import (
    BloomFilter,
    CountMinSketch,
    HyperLogLog,
    KeyRange,
    MerkleTree,
    ReservoirSampler,
    TDigest,
    TopK,
)


class TestTDigest:
    def test_quantiles_of_uniform(self):
        rng = random.Random(7)
        td = TDigest(compression=100)
        for _ in range(20_000):
            td.add(rng.random())
        assert td.quantile(0.5) == pytest.approx(0.5, abs=0.02)
        assert td.quantile(0.99) == pytest.approx(0.99, abs=0.01)
        assert td.percentile(95) == pytest.approx(0.95, abs=0.01)
        assert td.min == pytest.approx(0.0, abs=0.01)
        assert td.max == pytest.approx(1.0, abs=0.01)

    def test_cdf_roundtrip(self):
        rng = random.Random(3)
        td = TDigest()
        for _ in range(10_000):
            td.add(rng.expovariate(1.0))
        q = td.quantile(0.9)
        assert td.cdf(q) == pytest.approx(0.9, abs=0.03)

    def test_merge_matches_union(self):
        rng = random.Random(11)
        a, b, both = TDigest(), TDigest(), TDigest()
        for _ in range(5000):
            x, y = rng.gauss(0, 1), rng.gauss(1, 1)
            a.add(x)
            b.add(y)
            both.add(x)
            both.add(y)
        a.merge(b)
        assert a.item_count == both.item_count
        for q in (0.1, 0.5, 0.9):
            assert a.quantile(q) == pytest.approx(both.quantile(q), abs=0.15)

    def test_bounded_memory(self):
        td = TDigest(compression=50)
        for i in range(100_000):
            td.add(float(i))
        assert td.centroid_count < 200

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            TDigest().quantile(0.5)

    def test_weighted_add(self):
        td = TDigest()
        td.add(1.0, count=99)
        td.add(100.0, count=1)
        assert td.item_count == 100
        assert td.quantile(0.5) == pytest.approx(1.0, abs=0.5)


class TestHyperLogLog:
    def test_cardinality_within_error(self):
        hll = HyperLogLog(precision=12, seed=1)
        n = 50_000
        for i in range(n):
            hll.add(f"item-{i}")
        assert hll.cardinality() == pytest.approx(n, rel=5 * hll.standard_error)

    def test_duplicates_ignored(self):
        hll = HyperLogLog(precision=10)
        for _ in range(1000):
            hll.add("same")
        assert hll.cardinality() == 1
        assert hll.item_count == 1000

    def test_merge_is_union(self):
        a, b = HyperLogLog(precision=12, seed=2), HyperLogLog(precision=12, seed=2)
        for i in range(10_000):
            a.add(f"a-{i}")
            b.add(f"b-{i}")
        a.merge(b)
        assert a.cardinality() == pytest.approx(20_000, rel=0.05)

    def test_merge_incompatible(self):
        with pytest.raises(ValueError):
            HyperLogLog(precision=10).merge(HyperLogLog(precision=12))

    def test_small_range_exact(self):
        hll = HyperLogLog(precision=14)
        for i in range(100):
            hll.add(i)
        assert hll.cardinality() == pytest.approx(100, abs=3)


class TestCountMinSketch:
    def test_never_undercounts(self):
        cms = CountMinSketch(width=256, depth=4, seed=5)
        rng = random.Random(5)
        truth: dict[int, int] = {}
        for _ in range(10_000):
            item = rng.randrange(500)
            truth[item] = truth.get(item, 0) + 1
            cms.add(item)
        for item, count in truth.items():
            assert cms.estimate(item) >= count

    def test_heavy_hitter_top(self):
        cms = CountMinSketch(width=1024, depth=5)
        for i in range(100):
            cms.add("rare-%d" % i)
        cms.add("hot", count=500)
        top = cms.top(1)
        assert top[0].item == "hot"
        assert top[0].count >= 500

    def test_from_error_rate(self):
        cms = CountMinSketch.from_error_rate(epsilon=0.01, delta=0.05)
        assert cms.epsilon <= 0.01
        assert cms.delta <= 0.05

    def test_merge_adds_counts(self):
        a = CountMinSketch(width=128, depth=3, seed=9)
        b = CountMinSketch(width=128, depth=3, seed=9)
        a.add("x", 5)
        b.add("x", 7)
        a.merge(b)
        assert a.estimate("x") >= 12
        assert a.item_count == 12

    def test_inner_product(self):
        a = CountMinSketch(width=2048, depth=5, seed=1)
        b = CountMinSketch(width=2048, depth=5, seed=1)
        a.add("k", 10)
        b.add("k", 3)
        assert a.inner_product(b) >= 30


class TestBloomFilter:
    def test_no_false_negatives(self):
        bf = BloomFilter.from_expected_items(1000, 0.01, seed=4)
        for i in range(1000):
            bf.add(f"key-{i}")
        for i in range(1000):
            assert bf.contains(f"key-{i}")
            assert f"key-{i}" in bf

    def test_false_positive_rate_near_target(self):
        bf = BloomFilter.from_expected_items(2000, 0.01, seed=8)
        for i in range(2000):
            bf.add(f"in-{i}")
        fps = sum(bf.contains(f"out-{i}") for i in range(10_000))
        assert fps / 10_000 < 0.03
        assert bf.false_positive_rate < 0.03

    def test_merge_is_union(self):
        a = BloomFilter(size_bits=4096, num_hashes=4, seed=2)
        b = BloomFilter(size_bits=4096, num_hashes=4, seed=2)
        a.add("only-a")
        b.add("only-b")
        a.merge(b)
        assert a.contains("only-a") and a.contains("only-b")

    def test_clear(self):
        bf = BloomFilter(size_bits=512, num_hashes=3)
        bf.add("x")
        bf.clear()
        assert not bf.contains("x")
        assert bf.fill_ratio == 0.0


class TestTopK:
    def test_exact_when_under_k(self):
        tk = TopK(k=10)
        tk.add("a", 5)
        tk.add("b", 3)
        top = tk.top()
        assert [(e.item, e.count, e.error) for e in top] == [("a", 5, 0), ("b", 3, 0)]

    def test_space_saving_eviction(self):
        tk = TopK(k=2)
        tk.add("a", 10)
        tk.add("b", 5)
        tk.add("c")  # evicts b, inherits count 5
        assert tk.tracked_count == 2
        est = tk.estimate_with_error("c")
        assert est.count == 6 and est.error == 5

    def test_finds_zipf_head(self):
        rng = random.Random(13)
        tk = TopK(k=20)
        for _ in range(50_000):
            # Zipf-ish: item i with probability ~ 1/(i+1)
            item = min(int(1 / max(rng.random(), 1e-9)) - 1, 999)
            tk.add(item)
        head = [e.item for e in tk.top(3)]
        assert 0 in head and 1 in head

    def test_merge(self):
        a, b = TopK(k=5), TopK(k=5)
        a.add("x", 10)
        b.add("x", 7)
        b.add("y", 3)
        a.merge(b)
        assert a.estimate("x") == 17
        assert a.estimate("y") == 3
        assert a.item_count == 20


class TestReservoirSampler:
    def test_uniformity(self):
        counts = [0] * 10
        for trial in range(300):
            rs = ReservoirSampler(capacity=3, seed=trial)
            for i in range(10):
                rs.add(i)
            for x in rs:
                counts[x] += 1
        # each of 10 items should appear ~ 300*3/10 = 90 times
        assert all(50 < c < 140 for c in counts)

    def test_under_capacity_keeps_all(self):
        rs = ReservoirSampler(capacity=100, seed=1)
        for i in range(5):
            rs.add(i)
        assert sorted(rs.sample()) == [0, 1, 2, 3, 4]
        assert not rs.is_full

    def test_merge_total_and_size(self):
        a = ReservoirSampler(capacity=10, seed=1)
        b = ReservoirSampler(capacity=10, seed=2)
        for i in range(100):
            a.add(("a", i))
            b.add(("b", i))
        a.merge(b)
        assert a.item_count == 200
        assert a.sample_size == 10


class TestMerkleTree:
    def test_identical_trees_no_diff(self):
        data = {f"k{i}": i for i in range(20)}
        a, b = MerkleTree.build(data), MerkleTree.build(dict(data))
        assert a.root_hash == b.root_hash
        assert a.diff(b) == []

    def test_diff_locates_divergence(self):
        data = {f"k{i:02d}": i for i in range(32)}
        a, b = MerkleTree.build(data), MerkleTree.build(dict(data))
        b.update("k07", 999)
        assert a.root_hash != b.root_hash
        ranges = a.diff(b)
        assert any(r.contains("k07") for r in ranges)
        # diff should be localized, not the whole keyspace
        covered = sum(1 for k in data if any(r.contains(k) for r in ranges))
        assert covered < len(data)

    def test_update_remove_get(self):
        t = MerkleTree()
        t.update("a", 1)
        t.update("b", 2)
        assert t.get("a") == 1
        assert t.remove("a") and not t.remove("a")
        assert t.size == 1
        assert t.keys() == ["b"]

    def test_missing_key_side(self):
        a = MerkleTree.build({"x": 1})
        b = MerkleTree.build({})
        ranges = a.diff(b)
        assert ranges and ranges[0].contains("x")

    def test_key_range(self):
        r = KeyRange(start="b", end="d")
        assert r.contains("c") and not r.contains("e")
