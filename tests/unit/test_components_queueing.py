"""Unit tests: queue policies, driver back-pressure, burst concurrency."""

import pytest

from happysim_tpu import (
    ConstantLatency,
    Event,
    FIFOQueue,
    Instant,
    LIFOQueue,
    PriorityQueue,
    Resource,
    Server,
    Simulation,
    Sink,
)


class TestPolicies:
    def test_fifo(self):
        q = FIFOQueue()
        for x in [1, 2, 3]:
            q.push(x)
        assert [q.pop() for _ in range(3)] == [1, 2, 3]

    def test_lifo(self):
        q = LIFOQueue()
        for x in [1, 2, 3]:
            q.push(x)
        assert [q.pop() for _ in range(3)] == [3, 2, 1]

    def test_priority_with_key(self):
        q = PriorityQueue(key=lambda x: x["p"])
        q.push({"p": 3, "v": "c"})
        q.push({"p": 1, "v": "a"})
        q.push({"p": 2, "v": "b"})
        assert [q.pop()["v"] for _ in range(3)] == ["a", "b", "c"]

    def test_priority_fifo_within_equal(self):
        q = PriorityQueue(key=lambda x: 0)
        for x in ["x", "y", "z"]:
            q.push(x)
        assert [q.pop() for _ in range(3)] == ["x", "y", "z"]


class TestBurstConcurrency:
    def test_simultaneous_burst_fills_all_slots(self):
        """Regression: a burst of 4 requests at t=0 into Server(concurrency=2,
        service=1s) must complete at 1,1,2,2 — not serialized 1,2,3,4."""
        sink = Sink()
        server = Server(
            "s2", concurrency=2, service_time=ConstantLatency(1.0), downstream=sink
        )
        sim = Simulation(entities=[server, sink])
        sim.schedule(
            [Event(Instant.Epoch, "Request", target=server) for _ in range(4)]
        )
        sim.run()
        done = sorted(t.to_seconds() for t in sink.completion_times)
        assert done == pytest.approx([1.0, 1.0, 2.0, 2.0])

    def test_burst_larger_than_capacity_no_overflow(self):
        sink = Sink()
        server = Server(
            "s3", concurrency=3, service_time=ConstantLatency(0.5), downstream=sink
        )
        sim = Simulation(entities=[server, sink])
        sim.schedule(
            [Event(Instant.Epoch, "Request", target=server) for _ in range(10)]
        )
        sim.run()
        assert sink.events_received == 10
        done = sorted(t.to_seconds() for t in sink.completion_times)
        # 3 at a time: waves at 0.5, 1.0, 1.5, 2.0
        assert done == pytest.approx([0.5] * 3 + [1.0] * 3 + [1.5] * 3 + [2.0])

    def test_queue_capacity_drops(self):
        sink = Sink()
        server = Server(
            "bounded",
            concurrency=1,
            service_time=ConstantLatency(1.0),
            queue_capacity=2,
            downstream=sink,
        )
        sim = Simulation(entities=[server, sink])
        sim.schedule(
            [Event(Instant.Epoch, "Request", target=server) for _ in range(5)]
        )
        sim.run()
        # capacity 2 in queue + the burst drain chain pulls 1 into service.
        assert server.queue.dropped > 0
        assert sink.events_received + server.queue.dropped == 5


class TestResource:
    def test_grant_and_release(self):
        from happysim_tpu import Entity

        resource = Resource("lock", capacity=1)
        order = []

        class Worker(Entity):
            def __init__(self, name, hold_s):
                super().__init__(name)
                self.hold_s = hold_s

            def handle_event(self, event):
                grant = yield resource.acquire()
                order.append((self.name, "got", self.now.to_seconds()))
                yield self.hold_s
                grant.release()
                order.append((self.name, "rel", self.now.to_seconds()))

        w1, w2 = Worker("w1", 1.0), Worker("w2", 1.0)
        sim = Simulation(entities=[w1, w2, resource])
        sim.schedule(Event(Instant.Epoch, "go", target=w1))
        sim.schedule(Event(Instant.Epoch, "go", target=w2))
        sim.run()
        assert order == [
            ("w1", "got", 0.0),
            ("w1", "rel", 1.0),
            ("w2", "got", 1.0),
            ("w2", "rel", 2.0),
        ]
        assert resource.stats().total_acquired == 2

    def test_try_acquire(self):
        resource = Resource("r", capacity=2.0)
        resource.set_clock(__import__("happysim_tpu").Clock())
        g1 = resource.try_acquire(1.5)
        assert g1 is not None
        assert resource.try_acquire(1.0) is None
        g1.release()
        assert resource.try_acquire(1.0) is not None
