"""Unit tests: rate-limit policies, limiter entities, inductor, distributed."""

import pytest

from happysim_tpu import ConstantLatency, Event, Instant, Simulation, Sink
from happysim_tpu.components.rate_limiter import (
    AdaptivePolicy,
    DistributedRateLimiter,
    FixedWindowPolicy,
    Inductor,
    LeakyBucketPolicy,
    NullRateLimiter,
    RateLimitedEntity,
    SharedCounterStore,
    SlidingWindowPolicy,
    TokenBucketPolicy,
)


def t(seconds: float) -> Instant:
    return Instant.from_seconds(seconds)


class TestTokenBucket:
    def test_burst_then_throttle(self):
        p = TokenBucketPolicy(capacity=3, refill_rate=1.0)
        assert all(p.try_acquire(t(0)) for _ in range(3))
        assert not p.try_acquire(t(0))
        assert p.time_until_available(t(0)).to_seconds() == pytest.approx(1.0)
        assert p.try_acquire(t(1.0))

    def test_refill_caps_at_capacity(self):
        p = TokenBucketPolicy(capacity=2, refill_rate=10.0)
        p.try_acquire(t(0))
        assert p.tokens <= 2.0
        p._refill(t(100.0))
        assert p.tokens == pytest.approx(2.0)


class TestLeakyBucket:
    def test_spaced_admission(self):
        p = LeakyBucketPolicy(leak_rate=2.0)  # one per 0.5s
        assert p.try_acquire(t(0))
        assert not p.try_acquire(t(0.2))
        assert p.try_acquire(t(0.5))


class TestSlidingWindow:
    def test_window_slides(self):
        p = SlidingWindowPolicy(window_size_seconds=1.0, max_requests=2)
        assert p.try_acquire(t(0.0))
        assert p.try_acquire(t(0.4))
        assert not p.try_acquire(t(0.9))
        assert p.try_acquire(t(1.05))  # first admission aged out


class TestFixedWindow:
    def test_resets_at_boundary(self):
        p = FixedWindowPolicy(requests_per_window=2, window_size=1.0)
        assert p.try_acquire(t(0.1)) and p.try_acquire(t(0.2))
        assert not p.try_acquire(t(0.9))
        assert p.try_acquire(t(1.0))


class TestAdaptive:
    def test_aimd(self):
        p = AdaptivePolicy(initial_rate=10.0, min_rate=1.0, max_rate=20.0)
        p.record_backpressure(t(1.0))
        assert p.current_rate == pytest.approx(5.0)
        for i in range(30):
            p.record_success(t(2.0 + i))
        assert p.current_rate == pytest.approx(20.0)  # capped
        assert len(p.history) == 31


class TestRateLimitedEntity:
    def test_drop_mode(self):
        sink = Sink()
        rl = RateLimitedEntity(
            "rl", sink, TokenBucketPolicy(capacity=2, refill_rate=0.001), mode="drop"
        )
        sim = Simulation(entities=[sink, rl], duration=1.0)
        sim.schedule([Event(t(0.01 * i), "req", target=rl) for i in range(5)])
        sim.run()
        assert rl.stats.admitted == 2
        assert rl.stats.rejected == 3
        assert sink.events_received == 2

    def test_delay_mode_shapes_traffic(self):
        sink = Sink()
        rl = RateLimitedEntity(
            "rl", sink, LeakyBucketPolicy(leak_rate=2.0), mode="delay"
        )
        sim = Simulation(entities=[sink, rl], duration=10.0)
        sim.schedule([Event(t(0.0), "req", target=rl) for _ in range(4)])
        sim.run()
        assert sink.events_received == 4
        arrivals = sorted(i.to_seconds() for i in sink.completion_times)
        assert arrivals == pytest.approx([0.0, 0.5, 1.0, 1.5])

    def test_null_passthrough(self):
        sink = Sink()
        null = NullRateLimiter("n", sink)
        sim = Simulation(entities=[sink, null], duration=1.0)
        sim.schedule([Event(t(0), "req", target=null) for _ in range(3)])
        sim.run()
        assert sink.events_received == 3


class TestInductor:
    def test_steady_traffic_passes(self):
        sink = Sink()
        inductor = Inductor("ind", sink, time_constant=1.0)
        sim = Simulation(entities=[sink, inductor], duration=30.0)
        sim.schedule([Event(t(i * 0.1), "req", target=inductor) for i in range(100)])
        sim.run()
        assert inductor.stats.forwarded == 100
        assert inductor.stats.dropped == 0

    def test_burst_is_smoothed(self):
        sink = Sink()
        inductor = Inductor("ind", sink, time_constant=5.0)
        sim = Simulation(entities=[sink, inductor], duration=120.0)
        # Steady 10/s for 5s, then a same-instant burst of 50.
        events = [Event(t(i * 0.1), "req", target=inductor) for i in range(50)]
        events += [Event(t(5.0), "burst", target=inductor) for _ in range(50)]
        sim.schedule(events)
        sim.run()
        assert inductor.stats.queued > 0  # burst got buffered
        assert inductor.stats.forwarded == 100  # ...but eventually drained
        out_times = sorted(i.to_seconds() for i in sink.completion_times)
        # The burst must NOT all exit at t=5: it drains over the smoothed
        # interval (~0.1s spacing), so the last departure lands well after.
        assert out_times[-1] > 7.0

    def test_estimated_rate_tracks_input(self):
        sink = Sink()
        inductor = Inductor("ind", sink, time_constant=0.5)
        sim = Simulation(entities=[sink, inductor], duration=60.0)
        sim.schedule([Event(t(i * 0.25), "req", target=inductor) for i in range(200)])
        sim.run()
        assert inductor.estimated_rate == pytest.approx(4.0, rel=0.05)


class TestDistributedRateLimiter:
    def test_global_limit_enforced_across_nodes(self):
        sink = Sink()
        store = SharedCounterStore()
        nodes = [
            DistributedRateLimiter(
                f"node{i}",
                sink,
                store,
                global_limit=20,
                window_size=100.0,
                sync_interval=5,
            )
            for i in range(2)
        ]
        sim = Simulation(entities=[sink, *nodes], duration=50.0)
        events = []
        for i in range(30):
            events.append(Event(t(0.1 + i * 0.05), "req", target=nodes[i % 2]))
        sim.schedule(events)
        sim.run()
        total_admitted = sum(n.stats.admitted for n in nodes)
        # Batched sync admits can overshoot by < sync_interval per node.
        assert total_admitted <= 20 + 2 * 5
        assert sum(n.stats.rejected for n in nodes) >= 30 - (20 + 2 * 5)
        assert all(n.stats.store_syncs >= 1 for n in nodes)


    def test_overlapping_syncs_do_not_double_count(self):
        """Two sync round-trips in flight at once must not push overlapping
        pending counts into the shared store."""
        sink = Sink()
        store = SharedCounterStore()
        node = DistributedRateLimiter(
            "node0",
            sink,
            store,
            global_limit=1000,  # high limit: isolate the accounting
            window_size=100.0,
            sync_interval=3,
            store_latency=ConstantLatency(0.5),  # long round-trip
        )
        sim = Simulation(entities=[sink, node], duration=50.0)
        # 12 rapid requests: syncs overlap because the store is slow.
        sim.schedule([Event(t(0.01 * i), "req", target=node) for i in range(12)])
        sim.run()
        window = node._window_of(t(0.2))
        # The store total must equal exactly the admissions that synced
        # (multiples of sync_interval), never more than total admissions.
        assert store.get(window) <= node.stats.admitted
        assert store.get(window) == 12  # 4 syncs x 3 pending, no overlap

    def test_cached_rejection_unwinds_hooks_as_drop(self):
        sink = Sink()
        store = SharedCounterStore()
        node = DistributedRateLimiter(
            "node0", sink, store, global_limit=2, window_size=100.0, sync_interval=1
        )
        sim = Simulation(entities=[sink, node], duration=10.0)
        outcomes = []
        events = []
        for i in range(6):
            req = Event(t(0.1 + i * 0.1), "req", target=node)
            req.add_completion_hook(
                lambda at, r=req: outcomes.append(r.context["metadata"].get("dropped_by"))
                or None
            )
            events.append(req)
        sim.schedule(events)
        sim.run()
        drops = [o for o in outcomes if o is not None]
        assert len(outcomes) == 6  # every request's hooks fired exactly once
        assert len(drops) == node.stats.rejected
        assert node.stats.rejected >= 1
