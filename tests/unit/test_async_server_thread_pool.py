"""Unit tests: AsyncServer (event-loop semantics) and ThreadPool."""

import pytest

from happysim_tpu import (
    AsyncServer,
    ConstantLatency,
    Event,
    Instant,
    Simulation,
    Sink,
    ThreadPool,
)


def burst(target, n, at_s=0.0):
    return [Event(Instant.from_seconds(at_s), "Request", target=target) for _ in range(n)]


class TestAsyncServer:
    def test_cpu_work_serializes(self):
        """Four simultaneous requests with 0.1s CPU each: the single event
        loop finishes them at 0.1, 0.2, 0.3, 0.4 — not all at 0.1."""
        sink = Sink("sink")
        server = AsyncServer("api", cpu_work=ConstantLatency(0.1), downstream=sink)
        sim = Simulation(entities=[server, sink])
        sim.schedule(burst(server, 4))
        sim.run()
        done = sorted(t.to_seconds() for t in sink.completion_times)
        assert done == pytest.approx([0.1, 0.2, 0.3, 0.4])
        assert server.requests_completed == 4
        assert server.stats().total_cpu_time_s == pytest.approx(0.4)

    def test_io_overlaps(self):
        """0.01s CPU + 0.5s I/O x4: CPU serializes (~0.04 total) but the
        I/O waits overlap, so the batch finishes near 0.54, not 2.0."""
        sink = Sink("sink")

        def io_wait(event):
            yield 0.5

        server = AsyncServer(
            "api", cpu_work=ConstantLatency(0.01), io_handler=io_wait, downstream=sink
        )
        sim = Simulation(entities=[server, sink])
        sim.schedule(burst(server, 4))
        sim.run()
        finished = max(t.to_seconds() for t in sink.completion_times)
        assert finished == pytest.approx(0.54, abs=1e-3)
        assert server.stats().total_io_time_s == pytest.approx(2.0, abs=1e-2)

    def test_connection_cap_rejects(self):
        server = AsyncServer("api", max_connections=2, cpu_work=ConstantLatency(1.0))
        sim = Simulation(entities=[server])
        sim.schedule(burst(server, 5))
        sim.run()
        assert server.requests_completed == 2
        assert server.requests_rejected == 3
        assert server.peak_connections == 2

    def test_back_pressure_signal(self):
        server = AsyncServer("api", max_connections=1)
        assert server.has_capacity()
        server.active_connections = 1
        assert not server.has_capacity()


class TestThreadPool:
    def test_per_task_processing_times(self):
        sink = Sink("sink")
        pool = ThreadPool("pool", num_workers=1, downstream=sink)
        sim = Simulation(entities=[pool, sink])
        for duration in (0.3, 0.1):
            sim.schedule(
                Event(
                    Instant.Epoch, "Task", target=pool,
                    context={"metadata": {"processing_time": duration}},
                )
            )
        sim.run()
        done = sorted(t.to_seconds() for t in sink.completion_times)
        # FIFO: 0.3s task first, then the 0.1s task.
        assert done == pytest.approx([0.3, 0.4])
        assert pool.stats().total_processing_time_s == pytest.approx(0.4)

    def test_workers_run_in_parallel(self):
        sink = Sink("sink")
        pool = ThreadPool(
            "pool", num_workers=3, default_processing_time=0.5, downstream=sink
        )
        sim = Simulation(entities=[pool, sink])
        sim.schedule(burst(pool, 3))
        sim.run()
        done = [t.to_seconds() for t in sink.completion_times]
        assert done == pytest.approx([0.5, 0.5, 0.5])

    def test_queue_capacity_rejects(self):
        pool = ThreadPool(
            "pool", num_workers=1, queue_capacity=1, default_processing_time=1.0
        )
        sim = Simulation(entities=[pool])
        sim.schedule(burst(pool, 4))
        sim.run()
        # A same-instant burst: the first task is still queued when the
        # rest arrive, so capacity 1 admits exactly one.
        assert pool.tasks_completed == 1
        assert pool.stats().tasks_rejected == 3

    def test_custom_extractor(self):
        sink = Sink("sink")
        pool = ThreadPool(
            "pool",
            num_workers=1,
            processing_time_extractor=lambda e: 0.25,
            downstream=sink,
        )
        sim = Simulation(entities=[pool, sink])
        sim.schedule(burst(pool, 1))
        sim.run()
        assert sink.completion_times[0].to_seconds() == pytest.approx(0.25)

    def test_utilization_snapshot(self):
        pool = ThreadPool("pool", num_workers=4)
        assert pool.worker_utilization == 0.0
        assert pool.idle_workers == 4

    def test_negative_processing_time_falls_back(self):
        sink = Sink("sink")
        pool = ThreadPool(
            "pool", num_workers=1, default_processing_time=0.2, downstream=sink
        )
        sim = Simulation(entities=[pool, sink])
        sim.schedule(
            Event(
                Instant.Epoch, "Task", target=pool,
                context={"metadata": {"processing_time": -5.0}},
            )
        )
        sim.run()
        # Regression: a negative duration used to schedule the completion
        # in the past and silently lose the task.
        assert pool.tasks_completed == 1
        assert sink.completion_times[0].to_seconds() == pytest.approx(0.2)


class TestCrashRecovery:
    def test_crash_does_not_wedge_event_loop(self):
        """Regression: a Grant resolved to a waiter closed by a crash must
        be released, or the capacity-1 loop wedges forever."""
        from happysim_tpu import CrashNode, FaultSchedule

        sink = Sink("sink")
        server = AsyncServer("api", cpu_work=ConstantLatency(1.0), downstream=sink)
        faults = FaultSchedule()
        faults.add(CrashNode(entity_name="api", at=0.5, restart_at=3.0))
        sim = Simulation(
            entities=[server, sink], fault_schedule=faults,
            end_time=Instant.from_seconds(20.0),
        )
        # Two requests before the crash (one holds the loop, one waits),
        # three after the restart.
        sim.schedule(burst(server, 2, at_s=0.0))
        sim.schedule(burst(server, 3, at_s=5.0))
        sim.run()
        assert server.requests_completed == 3
        assert server._event_loop.in_use == 0.0
