"""EnsembleResult.summary() must surface the whole-model chaos ledger.

Regression guard for the accounting gap where ``network_lost`` and the
fault/hedge totals were computed by the engine but never reached the
:class:`~happysim_tpu.instrumentation.summary.SimulationSummary` — a
chaos run's summary looked identical to a clean run's.
"""

import numpy as np

from happysim_tpu.tpu.engine import HIST_BINS, EnsembleResult


def _result(**overrides) -> EnsembleResult:
    base = dict(
        n_replicas=4,
        horizon_s=10.0,
        simulated_events=100,
        wall_seconds=0.5,
        events_per_second=200.0,
        sink_count=[40],
        sink_mean_latency_s=[0.2],
        sink_p50_s=[0.1],
        sink_p99_s=[0.9],
        sink_hist=np.zeros((1, HIST_BINS), np.int32),
        server_completed=[42],
        server_dropped=[1],
        server_outage_dropped=[0],
        server_utilization=[0.5],
        server_mean_wait_s=[0.05],
        server_mean_queue_len=[0.4],
        server_timed_out=[0],
        server_retried=[0],
        transit_dropped=[0],
        limiter_admitted=[],
        limiter_dropped=[],
    )
    base.update(overrides)
    return EnsembleResult(**base)


def _chaos_entities(summary):
    return [e for e in summary.entities if e.kind == "Chaos"]


def test_clean_run_has_no_chaos_entity():
    assert _chaos_entities(_result().summary()) == []


def test_network_lost_reaches_summary():
    summary = _result(network_lost=257).summary()
    (chaos,) = _chaos_entities(summary)
    assert chaos.extra["network_lost"] == 257
    # And it survives the dict serialization the analysis layer uses.
    assert any(
        entity.get("network_lost") == 257
        for entity in summary.to_dict()["entities"]
    )


def test_fault_and_hedge_totals_reach_summary():
    summary = _result(
        server_fault_dropped=[3, 5],
        server_fault_retried=[7, 0],
        server_hedged=[2, 2],
        server_hedge_wins=[1, 0],
        server_completed=[42, 10],
        server_dropped=[1, 0],
        server_outage_dropped=[0, 0],
        server_utilization=[0.5, 0.1],
        server_mean_wait_s=[0.05, 0.0],
        server_mean_queue_len=[0.4, 0.0],
        server_timed_out=[0, 0],
        server_retried=[0, 0],
        transit_dropped=[0, 4],
    ).summary()
    (chaos,) = _chaos_entities(summary)
    assert chaos.extra == {
        "total_fault_dropped": 8,
        "total_fault_retried": 7,
        "total_hedged": 4,
        "total_hedge_wins": 1,
        "total_transit_dropped": 4,
    }


def test_zero_totals_stay_silent():
    summary = _result(
        server_fault_dropped=[0],
        server_hedged=[0],
        network_lost=0,
    ).summary()
    assert _chaos_entities(summary) == []


# -- engine provenance entity (PR 6) ----------------------------------------


def _engine_entities(summary):
    return [e for e in summary.entities if e.kind == "Engine"]


def test_engine_entity_always_present_with_path():
    (engine,) = _engine_entities(_result(engine_path="chain").summary())
    assert engine.extra["engine_path"] == "chain"
    assert "kernel_decline" not in engine.extra


def test_engine_entity_names_escape_hatches_on_decline():
    summary = _result(
        engine_path="scan",
        # A current per-feature reason (the blanket "model has routers"
        # decline was removed in ISSUE 11 — fan-outs run the kernel now).
        kernel_decline=(
            "Pallas kernel declined (router policy 'least_outstanding' "
            "is adaptive); ..."
        ),
        blocks_total=96,
    ).summary()
    (engine,) = _engine_entities(summary)
    assert engine.extra["macro_blocks_run"] == 96
    assert "router" in engine.extra["kernel_decline"]
    assert "HS_TPU_PALLAS" in engine.extra["escape_hatches"]
    assert "HS_TPU_EARLY_EXIT" in engine.extra["escape_hatches"]


def test_engine_report_exposes_occupancy_and_hatches():
    result = _result(
        engine_path="scan+pallas",
        macro_block=32,
        max_blocks=25,
        blocks_total=80,
        block_occupancy={20: 4},
        padded_replicas=8,
    )
    report = result.engine_report()
    assert report["engine_path"] == "scan+pallas"
    assert report["block_occupancy"] == {20: 4}
    assert report["events_per_block"] == 100 / 80
    assert report["early_exit_occupancy"] == 80 / (25 * 4)
    assert report["padded_lane_fraction"] == 0.5
    assert "escape_hatches" not in report  # kernel ran: nothing declined
    declined = _result(kernel_decline="declined (whatever)")
    assert set(declined.engine_report()["escape_hatches"]) == {
        "HS_TPU_PALLAS",
        "HS_TPU_EARLY_EXIT",
    }
