"""Unit tests: analysis pipeline (phases/anomalies/causal chains) and the
AI layer (results, recommendations, comparisons, sweeps, MCP tools)."""

import json

import pytest

from happysim_tpu import (
    ConstantLatency,
    Event,
    ExponentialLatency,
    Instant,
    Probe,
    Server,
    Simulation,
    SimulationResult,
    Source,
    analyze,
    detect_phases,
    generate_recommendations,
    list_event_lifecycles,
    trace_event_lifecycle,
)
from happysim_tpu.instrumentation.collectors import LatencyTracker
from happysim_tpu.instrumentation.data import Data
from happysim_tpu.instrumentation.recorder import InMemoryTraceRecorder


def series(values_by_window, window_s=5.0, samples_per_window=10):
    """Data with `samples_per_window` points at each window's level."""
    data = Data("metric")
    t = 0.0
    for level in values_by_window:
        for _ in range(samples_per_window):
            data.add(Instant.from_seconds(t), level)
            t += window_s / samples_per_window
    return data


class TestDetectPhases:
    def test_constant_series_is_one_stable_phase(self):
        phases = detect_phases(series([1.0, 1.0, 1.0, 1.0]))
        assert len(phases) == 1
        assert phases[0].label == "stable"
        assert phases[0].mean == pytest.approx(1.0)

    def test_step_change_splits_phases(self):
        phases = detect_phases(series([1.0, 1.0, 1.0, 10.0, 10.0, 10.0]))
        assert len(phases) == 2
        assert phases[0].label == "stable"
        assert phases[1].label == "overloaded"
        assert phases[1].start_s == pytest.approx(15.0)

    def test_moderate_rise_is_degraded(self):
        phases = detect_phases(series([1.0, 1.0, 1.0, 2.0, 2.0, 2.0]))
        assert len(phases) == 2
        assert phases[1].label == "degraded"

    def test_empty_and_tiny_data(self):
        assert detect_phases(Data("empty")) == []
        single = Data("single")
        single.add(Instant.from_seconds(0.0), 1.0)
        assert detect_phases(single) == []

    def test_phase_dict_roundtrip(self):
        phases = detect_phases(series([1.0, 1.0, 5.0, 5.0]))
        as_dict = phases[0].to_dict()
        assert set(as_dict) == {
            "start_s", "end_s", "duration_s", "mean", "std", "label"
        }


def run_mm1(lam, mu, duration=60.0, seed=7):
    tracker = LatencyTracker("Sink")
    server = Server(
        "Server",
        service_time=ExponentialLatency(1.0 / mu, seed=seed),
        downstream=tracker,
    )
    source = Source.poisson(rate=lam, target=server, seed=seed)
    probe = Probe.on(server, "queue_depth", interval_s=0.5)
    summary = Simulation(
        duration=duration, sources=[source], entities=[server, tracker], probes=[probe]
    ).run()
    return summary, tracker.data, probe.data


class TestAnalyze:
    def test_healthy_mm1_analysis(self):
        summary, latency, depth = run_mm1(lam=5.0, mu=10.0)
        analysis = analyze(summary, latency=latency, queue_depth=depth)
        assert "latency" in analysis.metrics
        assert analysis.metrics["latency"].count == latency.count()
        assert analysis.metrics["latency"].mean == pytest.approx(latency.mean())

    def test_deterministic_run_is_one_stable_phase(self):
        # Constant service + constant arrivals -> flat latency -> stable.
        tracker = LatencyTracker("Sink")
        server = Server("Server", service_time=ConstantLatency(0.05), downstream=tracker)
        source = Source.constant(rate=4.0, target=server)
        summary = Simulation(
            duration=60.0, sources=[source], entities=[server, tracker]
        ).run()
        analysis = analyze(summary, latency=tracker.data)
        for phases in analysis.phases.values():
            assert all(p.label == "stable" for p in phases)

    def test_prompt_context_sections_and_budget(self):
        summary, latency, depth = run_mm1(lam=5.0, mu=10.0)
        analysis = analyze(summary, latency=latency, queue_depth=depth)
        text = analysis.to_prompt_context(max_tokens=2000)
        assert "## Simulation Summary" in text
        assert len(text) <= 2000 * 4
        tiny = analysis.to_prompt_context(max_tokens=100)
        assert len(tiny) <= 100 * 4

    def test_anomaly_detection_flags_spike(self):
        data = series([1.0] * 10 + [50.0] + [1.0] * 10)
        summary, _, _ = run_mm1(lam=1.0, mu=10.0, duration=5.0)
        analysis = analyze(summary, spiky=data)
        assert any(a.metric == "spiky" for a in analysis.anomalies)
        spike = next(a for a in analysis.anomalies if a.metric == "spiky")
        assert spike.severity in ("warning", "critical")

    def test_causal_chain_queue_then_latency(self):
        # Both metrics degrade at t=25s: one causal episode.
        latency = series([0.01] * 5 + [0.2] * 5)
        depth = series([1.0] * 5 + [40.0] * 5)
        summary, _, _ = run_mm1(lam=1.0, mu=10.0, duration=5.0)
        analysis = analyze(summary, latency=latency, queue_depth=depth)
        assert len(analysis.causal_chains) >= 1
        chain = analysis.causal_chains[0]
        assert "degradation" in chain.trigger_description
        assert len(chain.effects) == 2


class TestSimulationResult:
    def test_from_run_attaches_recommendations(self):
        summary, latency, depth = run_mm1(lam=9.5, mu=10.0, duration=120.0)
        result = SimulationResult.from_run(
            summary, latency=latency, queue_depth={"Server": depth}
        )
        assert result.analysis is not None
        assert isinstance(result.recommendations, list)
        payload = result.to_dict()
        assert "summary" in payload and "metrics" in payload

    def test_saturated_system_flagged(self):
        """The round-trip oracle: rho>1 must produce a saturation warning."""
        summary, latency, depth = run_mm1(lam=20.0, mu=10.0, duration=120.0)
        result = SimulationResult.from_run(
            summary, latency=latency, queue_depth={"Server": depth}
        )
        categories = {r.category for r in result.recommendations}
        assert "capacity" in categories
        text = result.to_prompt_context()
        assert "Recommendations" in text

    def test_healthy_underutilized_system_flagged_low(self):
        summary, latency, depth = run_mm1(lam=0.5, mu=100.0, duration=120.0)
        result = SimulationResult.from_run(
            summary, latency=latency, queue_depth={"Server": depth}
        )
        assert any(r.confidence == "low" for r in result.recommendations)

    def test_compare_detects_latency_shift(self):
        summary_a, latency_a, depth_a = run_mm1(lam=5.0, mu=10.0)
        summary_b, latency_b, depth_b = run_mm1(lam=9.0, mu=10.0)
        result_a = SimulationResult.from_run(
            summary_a, latency=latency_a, queue_depth={"Server": depth_a}
        )
        result_b = SimulationResult.from_run(
            summary_b, latency=latency_b, queue_depth={"Server": depth_b}
        )
        comparison = result_a.compare(result_b)
        assert "latency" in comparison.metric_diffs
        assert comparison.metric_diffs["latency"].mean_b > comparison.metric_diffs["latency"].mean_a
        text = comparison.to_prompt_context()
        assert "Simulation Comparison" in text

    def test_sweep_result_best_by_and_saturation(self):
        from happysim_tpu import SweepResult

        results, values = [], []
        for lam in (5.0, 8.0, 9.9):
            summary, latency, depth = run_mm1(lam=lam, mu=10.0, duration=60.0)
            results.append(
                SimulationResult.from_run(
                    summary, latency=latency, queue_depth={"Server": depth}
                )
            )
            values.append(lam)
        sweep = SweepResult(
            parameter_name="arrival_rate", parameter_values=values, results=results
        )
        best = sweep.best_by("latency", "p99")
        assert best is results[0]
        assert "Parameter Sweep" in sweep.to_prompt_context()


class TestTraceAnalysis:
    def test_lifecycle_reconstruction(self):
        recorder = InMemoryTraceRecorder()
        tracker = LatencyTracker("Sink")
        server = Server(
            "Server", service_time=ConstantLatency(0.05), downstream=tracker
        )
        sim = Simulation(
            duration=1.0,
            entities=[server, tracker],
            trace_recorder=recorder,
        )
        sim.schedule(Event(Instant.Epoch, "Request", target=server))
        sim.run()
        lifecycles = list_event_lifecycles(recorder)
        assert lifecycles
        request = next(
            (lc for lc in lifecycles if lc.event_type == "Request"), None
        )
        assert request is not None
        assert request.dequeued_at is not None
        assert trace_event_lifecycle(recorder, request.event_id).event_id == request.event_id
        assert trace_event_lifecycle(recorder, 10**9) is None


class TestMCP:
    def test_run_queue_simulation_tool(self):
        from happysim_tpu.mcp import run_queue_simulation

        result = run_queue_simulation(
            arrival_rate=5.0, service_rate=10.0, duration=30.0, seed=3
        )
        assert result.latency is not None
        assert result.latency.count() > 50
        assert result.summary.events_processed > 0

    def test_run_pipeline_simulation_tool(self):
        from happysim_tpu.mcp import run_pipeline_simulation

        result = run_pipeline_simulation(
            stages=[
                {"name": "web", "service_time": 0.01},
                {"name": "db", "service_time": 0.02, "concurrency": 2},
            ],
            source_rate=10.0,
            duration=30.0,
            seed=3,
        )
        assert set(result.queue_depth) == {"web", "db"}
        assert result.latency.count() > 100

    def test_jsonrpc_protocol_round_trip(self):
        from happysim_tpu.mcp import handle_request

        init = handle_request(
            {"jsonrpc": "2.0", "id": 1, "method": "initialize", "params": {}}
        )
        assert init["result"]["serverInfo"]["name"] == "happysim_tpu"
        tools = handle_request({"jsonrpc": "2.0", "id": 2, "method": "tools/list"})
        names = {t["name"] for t in tools["result"]["tools"]}
        assert {"simulate_queue", "simulate_pipeline"} <= names
        call = handle_request(
            {
                "jsonrpc": "2.0",
                "id": 3,
                "method": "tools/call",
                "params": {
                    "name": "simulate_queue",
                    "arguments": {
                        "arrival_rate": 4.0,
                        "service_rate": 10.0,
                        "duration": 20.0,
                        "seed": 1,
                    },
                },
            }
        )
        payload = json.loads(call["result"]["content"][0]["text"])
        assert "prompt_context" in payload and "data" in payload
        # Notifications produce no response; unknown methods error.
        assert handle_request({"jsonrpc": "2.0", "method": "notifications/initialized"}) is None
        missing = handle_request({"jsonrpc": "2.0", "id": 4, "method": "nope"})
        assert missing["error"]["code"] == -32601

    def test_tool_error_flows_in_band(self):
        from happysim_tpu.mcp import handle_request

        bad = handle_request(
            {
                "jsonrpc": "2.0",
                "id": 5,
                "method": "tools/call",
                "params": {"name": "unknown_tool", "arguments": {}},
            }
        )
        assert bad["result"]["isError"] is True
