"""Depth tests for cache write policies (ref
components/datastore/write_policies.py:20-172)."""

import pytest

from happysim_tpu.components.datastore.write_policies import (
    WriteAround,
    WriteBack,
    WriteThrough,
)


class TestWriteThrough:
    def test_synchronous_and_stateless(self):
        p = WriteThrough()
        assert p.should_write_through()
        p.on_write("a", 1)
        assert not p.should_flush()
        assert p.get_keys_to_flush() == []
        p.on_flush(["a"])  # no-op, must not raise


class TestWriteBack:
    def test_writes_stay_dirty_until_flush(self):
        p = WriteBack(flush_interval=10.0, max_dirty=100)
        assert not p.should_write_through()
        p.on_write("a", 1)
        p.on_write("b", 2)
        p.on_write("a", 3)  # rewrite dedupes
        assert p.dirty_count == 2
        assert sorted(p.get_keys_to_flush()) == ["a", "b"]

    def test_max_dirty_triggers_flush(self):
        p = WriteBack(flush_interval=1e9, max_dirty=3)
        for k in "abc":
            p.on_write(k, 0)
        assert p.should_flush()
        p.on_flush(p.get_keys_to_flush())
        assert p.dirty_count == 0
        assert not p.should_flush()

    def test_interval_triggers_flush_via_clock(self):
        t = {"now": 0.0}
        p = WriteBack(flush_interval=5.0, max_dirty=100, clock_func=lambda: t["now"])
        p.on_write("a", 1)
        t["now"] = 4.9
        assert not p.should_flush()
        t["now"] = 5.0
        assert p.should_flush()
        p.on_flush(["a"])
        # last_flush advanced: next interval starts from now.
        p.on_write("b", 1)
        t["now"] = 9.9
        assert not p.should_flush()
        t["now"] = 10.0
        assert p.should_flush()

    def test_empty_dirty_set_never_interval_flushes(self):
        t = {"now": 100.0}
        p = WriteBack(flush_interval=1.0, clock_func=lambda: t["now"])
        assert not p.should_flush()

    def test_set_clock_func_late(self):
        p = WriteBack(flush_interval=1.0)
        p.on_write("a", 1)
        p.set_clock_func(lambda: 50.0)
        assert p.should_flush()

    def test_partial_flush_keeps_remainder_dirty(self):
        p = WriteBack(flush_interval=10.0)
        p.on_write("a", 1)
        p.on_write("b", 2)
        p.on_flush(["a"])
        assert p.get_keys_to_flush() == ["b"]

    def test_validation(self):
        with pytest.raises(ValueError):
            WriteBack(flush_interval=0.0)
        with pytest.raises(ValueError):
            WriteBack(max_dirty=0)

    def test_accessors(self):
        p = WriteBack(flush_interval=2.5, max_dirty=7)
        assert p.flush_interval == 2.5
        assert p.max_dirty == 7


class TestWriteAround:
    def test_bypasses_cache_and_invalidates(self):
        p = WriteAround()
        assert p.should_write_through()
        p.on_write("a", 1)
        p.on_write("b", 2)
        assert p.get_keys_to_invalidate() == ["a", "b"]
        # The invalidation list drains on read.
        assert p.get_keys_to_invalidate() == []

    def test_never_flushes(self):
        p = WriteAround()
        p.on_write("a", 1)
        assert not p.should_flush()
        assert p.get_keys_to_flush() == []
