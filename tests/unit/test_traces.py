"""TraceSpec validation, paging math, synthesizers, and model wiring
(ISSUE 18 tentpole surface)."""

import numpy as np
import pytest

from happysim_tpu.tpu.model import EnsembleModel
from happysim_tpu.tpu.traces import (
    DEFAULT_CHUNK_LEN,
    TraceSpec,
    diurnal_trace,
    flash_crowd_trace,
    zipf_tenant_trace,
)


def _spec(times, **kwargs):
    kwargs.setdefault("tenants", None)
    return TraceSpec(times=np.asarray(times, np.float32), **kwargs)


class TestTraceSpecValidation:
    def test_accepts_sane_trace(self):
        _spec([0.0, 0.5, 0.5, 2.0], chunk_len=2).validate()

    def test_rejects_empty(self):
        with pytest.raises(ValueError, match="non-empty"):
            _spec([]).validate()

    def test_rejects_non_finite(self):
        with pytest.raises(ValueError, match="finite"):
            _spec([0.0, np.inf]).validate()

    def test_rejects_negative_start(self):
        with pytest.raises(ValueError, match=">= 0"):
            _spec([-1.0, 0.5]).validate()

    def test_rejects_decreasing_with_index(self):
        with pytest.raises(ValueError, match=r"times\[2\] < times\[1\]"):
            _spec([0.0, 1.0, 0.5]).validate()

    def test_rejects_tenant_shape_mismatch(self):
        spec = _spec([0.0, 1.0])
        spec.tenants = np.zeros(3, np.int32)
        with pytest.raises(ValueError, match="shape"):
            spec.validate()

    def test_rejects_tenant_out_of_range(self):
        spec = TraceSpec(
            times=np.asarray([0.0, 1.0], np.float32),
            tenants=np.asarray([0, 5], np.int32),
            n_tenants=2,
        )
        with pytest.raises(ValueError, match=r"\[0, 2\)"):
            spec.validate()

    def test_rejects_bad_chunk_len(self):
        with pytest.raises(ValueError, match="chunk_len"):
            _spec([0.0], chunk_len=0).validate()


class TestPagingMath:
    def test_page_count_rounds_up(self):
        spec = _spec(np.linspace(0, 1, 10), chunk_len=4)
        assert spec.n_arrivals == 10
        assert spec.n_chunks == 3

    def test_padding_is_inf_and_zero(self):
        spec = _spec([0.0, 1.0, 2.0], chunk_len=4)
        times = spec.padded_times()
        tenants = spec.padded_tenants()
        assert times.shape == (4,) and tenants.shape == (4,)
        assert times.dtype == np.float32 and tenants.dtype == np.int32
        np.testing.assert_array_equal(times[:3], [0.0, 1.0, 2.0])
        assert np.isinf(times[3]) and tenants[3] == 0

    def test_default_chunk_len_covers_default_macro(self):
        # The engine validates chunk_len >= macro_block at run time; the
        # DEFAULT must clear the default RNG_CHUNK comfortably.
        from happysim_tpu.tpu.engine import RNG_CHUNK

        assert DEFAULT_CHUNK_LEN >= RNG_CHUNK


class TestSignature:
    def test_signature_is_stable_and_content_sensitive(self):
        a = _spec([0.0, 1.0], chunk_len=8)
        b = _spec([0.0, 1.0], chunk_len=8)
        assert a.signature() == b.signature()
        assert a.signature() != _spec([0.0, 1.5], chunk_len=8).signature()
        assert a.signature() != _spec([0.0, 1.0], chunk_len=4).signature()

    def test_fingerprint_carries_the_trace(self):
        from happysim_tpu.tpu.engine import model_fingerprint

        def build(times):
            model = EnsembleModel(horizon_s=2.0)
            src = model.trace_arrivals(_spec(times, chunk_len=8))
            srv = model.server(service_mean=0.1)
            snk = model.sink()
            model.connect(src, srv)
            model.connect(srv, snk)
            return model

        assert model_fingerprint(build([0.0, 1.0])) == model_fingerprint(
            build([0.0, 1.0])
        )
        assert model_fingerprint(build([0.0, 1.0])) != model_fingerprint(
            build([0.0, 1.5])
        )


class TestSynthesizers:
    def test_same_seed_same_trace(self):
        a = diurnal_trace(50.0, 0.5, 10.0, 20.0, seed=7)
        b = diurnal_trace(50.0, 0.5, 10.0, 20.0, seed=7)
        np.testing.assert_array_equal(a.times, b.times)
        assert a.signature() == b.signature()
        assert a.times.size != diurnal_trace(50.0, 0.5, 10.0, 20.0, seed=8).times.size or not np.array_equal(
            a.times, diurnal_trace(50.0, 0.5, 10.0, 20.0, seed=8).times
        )

    def test_diurnal_rate_modulation(self):
        # amplitude 1.0: the rate dips to ~0 in the trough half-period.
        trace = diurnal_trace(200.0, 1.0, 10.0, 10.0, seed=1)
        trace.validate()
        peak = np.sum((trace.times >= 1.5) & (trace.times < 3.5))
        trough = np.sum((trace.times >= 6.5) & (trace.times < 8.5))
        assert peak > 4 * max(trough, 1)
        with pytest.raises(ValueError, match="amplitude"):
            diurnal_trace(200.0, 1.5, 10.0, 10.0)

    def test_flash_crowd_burst(self):
        trace = flash_crowd_trace(20.0, 400.0, 2.0, 3.0, 6.0, seed=2)
        trace.validate()
        in_spike = np.sum((trace.times >= 2.0) & (trace.times < 3.0))
        before = np.sum(trace.times < 2.0)
        assert in_spike > 4 * before
        with pytest.raises(ValueError, match="spike_rate"):
            flash_crowd_trace(20.0, 10.0, 2.0, 3.0, 6.0)
        with pytest.raises(ValueError, match="spike_start_s"):
            flash_crowd_trace(20.0, 40.0, 3.0, 2.0, 6.0)

    def test_zipf_tenant_skew(self):
        trace = zipf_tenant_trace(100.0, 4, 1.5, 30.0, seed=3)
        trace.validate()
        counts = np.bincount(trace.tenants, minlength=4)
        assert counts[0] > counts[1] > counts[3]
        assert trace.n_tenants == 4

    def test_synthesizer_kind_and_params_recorded(self):
        trace = flash_crowd_trace(20.0, 40.0, 1.0, 2.0, 4.0, seed=9)
        assert trace.kind == "flash_crowd"
        assert trace.params == (20.0, 40.0, 1.0, 2.0, 4.0, 9)


class TestModelWiring:
    def _traced(self, **kwargs):
        model = EnsembleModel(horizon_s=2.0)
        src = model.trace_arrivals(_spec([0.1, 0.5, 1.2], chunk_len=8), **kwargs)
        srv = model.server(service_mean=0.1)
        snk = model.sink()
        model.connect(src, srv)
        model.connect(srv, snk)
        return model

    def test_trace_arrivals_requires_a_trace_spec(self):
        model = EnsembleModel(horizon_s=2.0)
        with pytest.raises(TypeError, match="TraceSpec"):
            model.trace_arrivals([0.1, 0.5])

    def test_traced_source_index_and_chaos_feature(self):
        model = self._traced()
        assert model.traced_source_index() == 0
        assert "trace_arrivals" in model.chaos_features()
        model.validate()

    def test_at_most_one_traced_source(self):
        model = self._traced()
        model.trace_arrivals(_spec([0.2], chunk_len=8))
        with pytest.raises(ValueError, match="at most one traced source"):
            model.validate()

    def test_chunk_len_smaller_than_macro_block_raises(self):
        from happysim_tpu.tpu import run_ensemble

        model = EnsembleModel(horizon_s=2.0, macro_block=16)
        src = model.trace_arrivals(_spec([0.1, 0.5], chunk_len=4))
        srv = model.server(service_mean=0.1)
        snk = model.sink()
        model.connect(src, srv)
        model.connect(srv, snk)
        with pytest.raises(ValueError, match="chunk_len=4"):
            run_ensemble(model, n_replicas=2, seed=0, max_events=32)
