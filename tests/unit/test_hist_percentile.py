"""Edge cases for the log-spaced histogram percentile estimator
(engine.hist_percentile): empty histograms, the q=0 / q=1 endpoints,
and out-of-range q."""

import numpy as np
import pytest

from happysim_tpu.tpu.engine import (
    HIST_BINS,
    HIST_DECADES,
    HIST_LO_LOG10,
    hist_percentile,
)


def _bin_center(index: int) -> float:
    frac = (index + 0.5) / HIST_BINS
    return float(10 ** (HIST_LO_LOG10 + frac * HIST_DECADES))


class TestHistPercentile:
    def test_empty_histogram_is_zero(self):
        hist = np.zeros(HIST_BINS, np.int32)
        for q in (0.0, 0.5, 0.99, 1.0):
            assert hist_percentile(hist, q) == 0.0

    def test_q_one_hits_last_occupied_bin(self):
        hist = np.zeros(HIST_BINS, np.int32)
        hist[10] = 90
        hist[63] = 10
        assert hist_percentile(hist, 1.0) == pytest.approx(_bin_center(63))

    def test_q_one_all_mass_in_final_bin_no_index_error(self):
        hist = np.zeros(HIST_BINS, np.int32)
        hist[HIST_BINS - 1] = 5
        value = hist_percentile(hist, 1.0)
        assert value == pytest.approx(_bin_center(HIST_BINS - 1))
        assert np.isfinite(value)

    def test_q_zero_hits_first_occupied_bin(self):
        """q=0 must resolve to where the mass STARTS, not bin 0: before
        the clamp fix, searchsorted matched target=0 against the leading
        zero-count bins and returned the lowest decade regardless."""
        hist = np.zeros(HIST_BINS, np.int32)
        hist[42] = 7
        assert hist_percentile(hist, 0.0) == pytest.approx(_bin_center(42))

    def test_single_sample_all_quantiles_agree(self):
        hist = np.zeros(HIST_BINS, np.int32)
        hist[17] = 1
        for q in (0.0, 0.25, 0.5, 0.99, 1.0):
            assert hist_percentile(hist, q) == pytest.approx(_bin_center(17))

    def test_median_of_two_bins(self):
        hist = np.zeros(HIST_BINS, np.int32)
        hist[20] = 50
        hist[60] = 50
        assert hist_percentile(hist, 0.5) == pytest.approx(_bin_center(20))
        assert hist_percentile(hist, 0.51) == pytest.approx(_bin_center(60))

    @pytest.mark.parametrize("q", [-0.01, 1.01, 2.0, float("nan")])
    def test_out_of_range_q_rejected(self, q):
        hist = np.ones(HIST_BINS, np.int32)
        with pytest.raises(ValueError, match="q must be in"):
            hist_percentile(hist, q)

    def test_monotone_in_q(self):
        rng = np.random.default_rng(0)
        hist = rng.integers(0, 100, HIST_BINS).astype(np.int64)
        qs = np.linspace(0.0, 1.0, 21)
        values = [hist_percentile(hist, float(q)) for q in qs]
        assert values == sorted(values)
