"""Server x queue-policy conformance matrix, in real simulations.

Every ordering discipline must compose with the Server/Queue/driver
stack without losing or duplicating work: conservation, capacity-drop
accounting, hook unwinding on drops, and saturation draining — the
same four invariants across all nine policies.

Parity target: the policy-matrix cases of
``happysimulator/tests/unit/test_server.py``.
"""

from __future__ import annotations

import pytest

from happysim_tpu import (
    ConstantLatency,
    Event,
    Instant,
    Server,
    Simulation,
    Sink,
    Source,
)
from happysim_tpu.components.queue_policies import (
    AdaptiveLIFO,
    CoDelQueue,
    DeadlineQueue,
    FairQueue,
    REDQueue,
    WeightedFairQueue,
)
from happysim_tpu.components.queue_policy import (
    FIFOQueue,
    LIFOQueue,
    PriorityQueue,
)

POLICY_FACTORIES = {
    "fifo": FIFOQueue,
    "lifo": LIFOQueue,
    "priority": PriorityQueue,
    "deadline": lambda: DeadlineQueue(drop_expired=False),
    "codel": lambda: CoDelQueue(target_delay=1e9, interval=1e9),
    "red": lambda: REDQueue(min_threshold=10_000, max_threshold=20_000),
    "adaptive_lifo": lambda: AdaptiveLIFO(congestion_threshold=1_000),
    "fair": FairQueue,
    "wfq": WeightedFairQueue,
}

IDS = sorted(POLICY_FACTORIES)


def run_world(policy, *, rate=40.0, stop=3.0, service=0.01, capacity=None,
              concurrency=1, horizon=20.0):
    sink = Sink("sink")
    server = Server(
        "server",
        concurrency=concurrency,
        service_time=ConstantLatency(service),
        queue_policy=policy,
        queue_capacity=capacity,
        downstream=sink,
    )
    source = Source.poisson(rate=rate, target=server, stop_after=stop, seed=13)
    sim = Simulation(
        sources=[source],
        entities=[server, sink],
        end_time=Instant.from_seconds(horizon),
    )
    summary = sim.run()
    return server, sink, summary


@pytest.mark.parametrize("name", IDS, ids=IDS)
class TestPolicyMatrix:
    def test_conservation_under_light_load(self, name):
        server, sink, _ = run_world(POLICY_FACTORIES[name](), rate=10.0,
                                    service=0.001)
        assert sink.events_received == server.requests_completed
        assert server.queue.dropped == 0
        assert server.queue_depth == 0  # drained at the end

    def test_saturation_drains_completely(self, name):
        """Offered 3x service rate for 3s, then the horizon lets the
        backlog drain: everything admitted must eventually complete."""
        server, sink, _ = run_world(
            POLICY_FACTORIES[name](), rate=120.0, service=0.025, horizon=40.0
        )
        admitted = server.queue.enqueued
        assert server.requests_completed == admitted
        assert sink.events_received == admitted
        assert server.queue_depth == 0

    def test_capacity_drops_are_accounted(self, name):
        server, sink, _ = run_world(
            POLICY_FACTORIES[name](), rate=200.0, service=0.05, capacity=5,
            horizon=60.0,
        )
        assert server.queue.dropped > 0
        # One fate per arrival: enqueued+dropped = arrivals; completed = enqueued.
        assert server.requests_completed == server.queue.enqueued
        assert sink.events_received == server.requests_completed

    def test_dropped_requests_unwind_hooks(self, name):
        """A capacity drop must fire the request's completion hooks with
        the drop marker, so clients and wrappers never leak."""
        policy = POLICY_FACTORIES[name]()
        sink = Sink("sink")
        server = Server(
            "server",
            service_time=ConstantLatency(1.0),
            queue_policy=policy,
            queue_capacity=1,
            downstream=sink,
        )
        sim = Simulation(
            entities=[server, sink], end_time=Instant.from_seconds(10.0)
        )
        fates = []
        # All four arrive in the same instant; (time, insertion) ordering
        # processes every enqueue before the first poll, so exactly one
        # fits the capacity-1 queue and three drop.
        for i in range(4):
            request = Event(Instant.Epoch, "req", target=server)
            request.add_completion_hook(
                lambda t, r=request: fates.append(r.dropped_by) or None
            )
            sim.schedule(request)
        sim.run()
        assert len(fates) == 4, "every request's hooks fired exactly once"
        drops = [fate for fate in fates if fate is not None]
        assert len(drops) == 3
        assert sink.events_received == 1
