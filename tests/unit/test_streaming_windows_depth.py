"""Window-assignment edge cases for the stream processor's window types.

The integration tests drive whole pipelines; these pin the pure window
math — boundary membership, overlap counts, float-boundary behavior,
watermark close conditions — where off-by-one-slide bugs live.

Parity target: ``happysimulator/components/streaming/stream_processor.py``
window semantics (tumbling/sliding/session assign + close).
"""

from __future__ import annotations

import pytest

from happysim_tpu.components.streaming import (
    SessionWindow,
    SlidingWindow,
    TumblingWindow,
)


class TestTumblingAssignment:
    def test_event_on_boundary_joins_the_later_window(self):
        window = TumblingWindow(size_s=10.0)
        assert window.assign_windows(10.0) == [(10.0, 20.0)]

    def test_event_just_before_boundary_stays_in_earlier_window(self):
        window = TumblingWindow(size_s=10.0)
        assert window.assign_windows(9.999) == [(0.0, 10.0)]

    def test_zero_time_event(self):
        window = TumblingWindow(size_s=5.0)
        assert window.assign_windows(0.0) == [(0.0, 5.0)]

    def test_every_event_gets_exactly_one_window(self):
        window = TumblingWindow(size_s=3.0)
        for t in [0.0, 1.5, 2.999, 3.0, 7.2, 29.9]:
            assigned = window.assign_windows(t)
            assert len(assigned) == 1
            start, end = assigned[0]
            assert start <= t < end
            assert end - start == pytest.approx(3.0)

    def test_fractional_size(self):
        window = TumblingWindow(size_s=0.25)
        (start, end), = window.assign_windows(1.1)
        assert start == pytest.approx(1.0)
        assert end == pytest.approx(1.25)

    def test_rejects_nonpositive_size(self):
        with pytest.raises(ValueError):
            TumblingWindow(size_s=0.0)


class TestSlidingAssignment:
    def test_overlap_count_is_size_over_slide(self):
        window = SlidingWindow(size_s=10.0, slide_s=2.0)
        # Mid-stream events belong to exactly size/slide = 5 windows.
        assert len(window.assign_windows(20.0)) == 5
        assert len(window.assign_windows(21.7)) == 5

    def test_early_events_have_fewer_windows(self):
        window = SlidingWindow(size_s=10.0, slide_s=2.0)
        # Windows never start before 0 is not required — but starts are
        # spaced by slide and each contains the event.
        for start, end in window.assign_windows(1.0):
            assert start <= 1.0 < end

    def test_windows_are_sorted_and_spaced_by_slide(self):
        window = SlidingWindow(size_s=6.0, slide_s=2.0)
        assigned = window.assign_windows(13.0)
        starts = [start for start, _ in assigned]
        assert starts == sorted(starts)
        diffs = {round(b - a, 9) for a, b in zip(starts, starts[1:])}
        assert diffs == {2.0}

    def test_boundary_event_excluded_from_ending_window(self):
        window = SlidingWindow(size_s=4.0, slide_s=2.0)
        # Window (8, 12) ends at 12; an event AT 12 must not join a window
        # that ends at 12 (half-open [start, end)).
        for start, end in window.assign_windows(12.0):
            assert end > 12.0

    def test_slide_equal_size_degenerates_to_tumbling(self):
        sliding = SlidingWindow(size_s=5.0, slide_s=5.0)
        tumbling = TumblingWindow(size_s=5.0)
        for t in [0.0, 2.5, 4.999, 5.0, 12.0]:
            assert sliding.assign_windows(t) == tumbling.assign_windows(t)

    def test_rejects_slide_larger_than_size(self):
        with pytest.raises(ValueError):
            SlidingWindow(size_s=2.0, slide_s=3.0)


class TestSessionAssignment:
    def test_window_spans_gap_after_event(self):
        window = SessionWindow(gap_s=30.0)
        assert window.assign_windows(100.0) == [(100.0, 130.0)]

    def test_rejects_nonpositive_gap(self):
        with pytest.raises(ValueError):
            SessionWindow(gap_s=0.0)


@pytest.mark.parametrize(
    "window",
    [TumblingWindow(10.0), SlidingWindow(10.0, 5.0), SessionWindow(10.0)],
    ids=["tumbling", "sliding", "session"],
)
class TestCloseCondition:
    def test_closes_exactly_at_watermark(self, window):
        assert not window.should_close(window_end=50.0, watermark_s=49.999)
        assert window.should_close(window_end=50.0, watermark_s=50.0)
        assert window.should_close(window_end=50.0, watermark_s=50.001)
