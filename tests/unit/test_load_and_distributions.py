"""Unit tests: sources, profiles, arrival solvers, distributions."""

import math

import pytest

from happysim_tpu import (
    ConstantLatency,
    ConstantRateProfile,
    Duration,
    ExponentialLatency,
    Instant,
    LinearRampProfile,
    PercentileFittedLatency,
    Simulation,
    Sink,
    Source,
    SpikeProfile,
    UniformDistribution,
    ZipfDistribution,
)
from happysim_tpu.load.providers.poisson_arrival import PoissonArrivalTimeProvider
from happysim_tpu.numerics import brentq, integrate_adaptive_simpson


class TestNumerics:
    def test_simpson_polynomial(self):
        result = integrate_adaptive_simpson(lambda x: 3 * x**2, 0.0, 2.0)
        assert result == pytest.approx(8.0, rel=1e-9)

    def test_simpson_reversed_bounds(self):
        assert integrate_adaptive_simpson(lambda x: x, 2.0, 0.0) == pytest.approx(-2.0)

    def test_brentq_finds_root(self):
        root = brentq(lambda x: x**2 - 4, 0.0, 10.0)
        assert root == pytest.approx(2.0, abs=1e-10)

    def test_brentq_requires_bracket(self):
        with pytest.raises(ValueError):
            brentq(lambda x: x**2 + 1, -1, 1)


class TestProfiles:
    def test_linear_ramp(self):
        profile = LinearRampProfile(0.0, 100.0, 10.0)
        assert profile.rate(Instant.Epoch) == 0.0
        assert profile.rate(Instant.from_seconds(5)) == 50.0
        assert profile.rate(Instant.from_seconds(20)) == 100.0

    def test_spike(self):
        profile = SpikeProfile(10.0, 1000.0, spike_start_s=5.0, spike_duration_s=1.0)
        assert profile.rate(Instant.from_seconds(4.9)) == 10.0
        assert profile.rate(Instant.from_seconds(5.5)) == 1000.0
        assert profile.rate(Instant.from_seconds(6.1)) == 10.0


class TestArrivals:
    def test_constant_arrivals_evenly_spaced(self):
        sink = Sink()
        source = Source.constant(rate=4.0, target=sink, stop_after=1.0)
        sim = Simulation(sources=[source], entities=[sink])
        sim.run()
        assert sink.events_received == 4
        times = [t.to_seconds() for t in sink.completion_times]
        assert times == pytest.approx([0.25, 0.5, 0.75, 1.0])

    def test_poisson_seeded_reproducible(self):
        provider_a = PoissonArrivalTimeProvider(10.0, seed=42)
        provider_b = PoissonArrivalTimeProvider(10.0, seed=42)
        times_a = []
        times_b = []
        t = Instant.Epoch
        for _ in range(20):
            t = provider_a.next_arrival_time(t)
            times_a.append(t.nanoseconds)
        t = Instant.Epoch
        for _ in range(20):
            t = provider_b.next_arrival_time(t)
            times_b.append(t.nanoseconds)
        assert times_a == times_b

    def test_poisson_mean_rate(self):
        provider = PoissonArrivalTimeProvider(100.0, seed=7)
        t = Instant.Epoch
        n = 5000
        for _ in range(n):
            t = provider.next_arrival_time(t)
        observed_rate = n / t.to_seconds()
        assert observed_rate == pytest.approx(100.0, rel=0.05)

    def test_ramp_profile_arrivals_integrate_rate(self):
        # rate(t) = 10t over [0,2]; expected arrivals = ∫ = 20
        profile = LinearRampProfile(0.0, 20.0, 2.0)
        sink = Sink()
        source = Source.with_profile(profile, target=sink, poisson=False, stop_after=2.0)
        sim = Simulation(sources=[source], entities=[sink], end_time=Instant.from_seconds(2))
        sim.run()
        assert sink.events_received == pytest.approx(20, abs=2)


class TestLatencyDistributions:
    def test_constant(self):
        dist = ConstantLatency(0.1)
        assert dist.get_latency(Instant.Epoch) == Duration.from_seconds(0.1)
        assert dist.mean() == Duration.from_seconds(0.1)

    def test_exponential_mean(self):
        dist = ExponentialLatency(0.05, seed=3)
        samples = [dist.get_latency(Instant.Epoch).to_seconds() for _ in range(20000)]
        assert sum(samples) / len(samples) == pytest.approx(0.05, rel=0.05)

    def test_mean_shift(self):
        shifted = ConstantLatency(0.1) + 0.05
        assert shifted.get_latency(Instant.Epoch) == Duration.from_seconds(0.15)
        clamped = ConstantLatency(0.1) - 0.2
        assert clamped.get_latency(Instant.Epoch) == Duration.ZERO

    def test_percentile_fitted_recovers_exponential(self):
        mean = 0.1
        points = {p: -mean * math.log1p(-p) for p in (0.5, 0.9, 0.99)}
        dist = PercentileFittedLatency(points, seed=1)
        assert dist.fitted_mean_seconds == pytest.approx(mean, rel=1e-6)


class TestValueDistributions:
    def test_zipf_rank_ordering(self):
        dist = ZipfDistribution(100, exponent=1.2, seed=5)
        counts = {}
        for _ in range(20000):
            key = dist.sample()
            counts[key] = counts.get(key, 0) + 1
        assert counts[0] > counts.get(10, 0) > counts.get(90, 0)

    def test_zipf_cdf_monotone(self):
        cdf = ZipfDistribution(10, exponent=1.0).cdf
        assert cdf == sorted(cdf)
        assert cdf[-1] == pytest.approx(1.0)

    def test_uniform_choice_seeded(self):
        a = UniformDistribution(items=list(range(10)), seed=9)
        b = UniformDistribution(items=list(range(10)), seed=9)
        assert [a.sample() for _ in range(50)] == [b.sample() for _ in range(50)]

    def test_uniform_range(self):
        dist = UniformDistribution(low=5.0, high=6.0, seed=2)
        for _ in range(100):
            assert 5.0 <= dist.sample() < 6.0
