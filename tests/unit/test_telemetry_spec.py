"""Window-edge semantics and validation for the telemetry spec.

The device-side window assignment is ``floor(t / window_s)`` in float32,
start-inclusive, clipped into ``[0, n_windows)``;
:func:`~happysim_tpu.tpu.telemetry.window_index` is the host twin of
exactly that arithmetic, so these tests pin the boundary contract the
compiled scatter-adds follow without compiling anything.
"""

import numpy as np
import pytest

from happysim_tpu.tpu.model import EnsembleModel, mm1_model
from happysim_tpu.tpu.telemetry import (
    DEFAULT_METRICS,
    MAX_WINDOWS,
    TelemetrySpec,
    measured_window_lengths,
    window_edges,
    window_index,
)


class TestValidation:
    def test_rejects_nonpositive_window(self):
        for bad in (0.0, -1.0):
            with pytest.raises(ValueError, match="window_s"):
                TelemetrySpec(window_s=bad).validate(10.0)

    def test_rejects_single_window_degenerate_spec(self):
        """window_s >= horizon yields one window — that is just the
        whole-run aggregate the engine already reports, so it is
        rejected rather than silently duplicating it."""
        with pytest.raises(ValueError, match="single window"):
            TelemetrySpec(window_s=10.0).validate(10.0)
        with pytest.raises(ValueError, match="single window"):
            TelemetrySpec(window_s=50.0).validate(10.0)

    def test_rejects_more_than_max_windows(self):
        too_fine = 10.0 / (MAX_WINDOWS + 1)
        with pytest.raises(ValueError, match="windows"):
            TelemetrySpec(window_s=too_fine).validate(10.0)
        # Exactly MAX_WINDOWS is fine.
        TelemetrySpec(window_s=10.0 / MAX_WINDOWS).validate(10.0)

    def test_rejects_unknown_and_empty_metrics(self):
        with pytest.raises(ValueError, match="unknown telemetry metrics"):
            TelemetrySpec(window_s=1.0, metrics=("latency", "bogus")).validate(10.0)
        with pytest.raises(ValueError, match="empty"):
            TelemetrySpec(window_s=1.0, metrics=()).validate(10.0)

    def test_model_telemetry_validates_at_call(self):
        model = mm1_model(horizon_s=10.0)
        with pytest.raises(ValueError):
            model.telemetry(window_s=0.0)
        assert model.telemetry_spec is None
        spec = model.telemetry(window_s=2.0)
        assert model.telemetry_spec is spec
        assert spec.metrics == DEFAULT_METRICS

    def test_model_validate_checks_spec(self):
        """A spec smuggled past the builder (set directly) still fails
        model.validate(), which the engine calls before compiling."""
        model = mm1_model(horizon_s=10.0)
        model.telemetry_spec = TelemetrySpec(window_s=-1.0)
        with pytest.raises(ValueError, match="window_s"):
            model.validate()


class TestWindowMath:
    def test_n_windows_ceils_indivisible_horizon(self):
        # 10 / 3 -> 4 windows, the last one 1s short.
        assert TelemetrySpec(window_s=3.0).n_windows(10.0) == 4
        assert TelemetrySpec(window_s=2.5).n_windows(10.0) == 4
        # Float-noise guard: 0.1 * 100 must be 100 windows, not 101.
        assert TelemetrySpec(window_s=0.1).n_windows(10.0) == 100

    def test_boundary_event_belongs_to_later_window(self):
        """Window w covers [w*window_s, (w+1)*window_s): an event landing
        exactly on an edge is start-inclusive."""
        assert window_index(0.0, 1.0, 8) == 0
        assert window_index(3.0, 1.0, 8) == 3
        assert window_index(2.999999, 1.0, 8) == 2
        # Power-of-two window: boundary products are exact in float32.
        assert window_index(1.5, 0.5, 8) == 3

    def test_horizon_end_event_clips_into_last_window(self):
        # t == horizon (the inclusive measurement end) must not index
        # out of range when the horizon is a window multiple.
        assert window_index(8.0, 1.0, 8) == 7
        assert window_index(1e9, 1.0, 8) == 7
        assert window_index(-0.5, 1.0, 8) == 0

    def test_edges_last_window_open_then_clamped(self):
        lo, hi = window_edges(3.0, 4)
        np.testing.assert_allclose(lo, [0.0, 3.0, 6.0, 9.0])
        assert np.isinf(hi[-1]) and hi[2] == 9.0
        lo_c, hi_c = window_edges(3.0, 4, horizon_s=10.0)
        assert hi_c[-1] == np.float32(10.0)  # short last window

    def test_measured_lengths_respect_warmup_and_horizon(self):
        # horizon 10, warmup 2, window 3: [0,3) has 1 measured second,
        # the full windows 3, and the short last window [9,10) has 1.
        lengths = measured_window_lengths(3.0, 4, horizon_s=10.0, warmup_s=2.0)
        np.testing.assert_allclose(lengths, [1.0, 3.0, 3.0, 1.0])

    def test_signature_roundtrip_identity(self):
        a = TelemetrySpec(window_s=1.5, metrics=("latency", "rates"))
        b = TelemetrySpec(window_s=1.5, metrics=("latency", "rates"))
        c = TelemetrySpec(window_s=1.5, metrics=("rates", "latency"))
        assert a.signature() == b.signature()
        assert a.signature() != c.signature()
        assert EnsembleModel(horizon_s=4.0).telemetry_spec is None
