"""Boundary conformance for every rate-limiter policy.

The composite tests drive limiters inside simulations; these pin the
pure admission math at the edges where limiter bugs live: exact counts
at window boundaries, fractional refill, capacity clamping,
time_until_available honesty, and burst-vs-steady equivalence.

Parity target: the per-policy cases of
``happysimulator/tests/unit/test_rate_limiter.py``.
"""

from __future__ import annotations

import pytest

from happysim_tpu.components.rate_limiter import (
    AdaptivePolicy,
    FixedWindowPolicy,
    LeakyBucketPolicy,
    SlidingWindowPolicy,
    TokenBucketPolicy,
)
from happysim_tpu.core.temporal import Instant


def t(seconds: float) -> Instant:
    return Instant.from_seconds(seconds)


def admitted(policy, times) -> list[bool]:
    return [policy.try_acquire(t(moment)) for moment in times]


class TestTokenBucket:
    def test_burst_exactly_capacity(self):
        policy = TokenBucketPolicy(capacity=5.0, refill_rate=1.0)
        results = admitted(policy, [0.0] * 6)
        assert results == [True] * 5 + [False]

    def test_fractional_refill_accumulates(self):
        policy = TokenBucketPolicy(capacity=1.0, refill_rate=0.5)
        assert policy.try_acquire(t(0.0))
        assert not policy.try_acquire(t(1.0))  # only 0.5 tokens back
        assert policy.try_acquire(t(2.0))  # 1.0 token at 2s

    def test_refill_clamps_at_capacity(self):
        policy = TokenBucketPolicy(capacity=3.0, refill_rate=100.0)
        admitted(policy, [0.0, 0.0, 0.0])
        # A long idle period cannot bank more than capacity.
        results = admitted(policy, [1000.0] * 4)
        assert results == [True, True, True, False]

    def test_time_until_available_is_exact(self):
        policy = TokenBucketPolicy(capacity=1.0, refill_rate=2.0)
        policy.try_acquire(t(0.0))
        wait = policy.time_until_available(t(0.0)).to_seconds()
        assert wait == pytest.approx(0.5)
        # And the promise holds: admission succeeds exactly then.
        assert policy.try_acquire(t(wait))

    def test_zero_wait_when_token_present(self):
        policy = TokenBucketPolicy(capacity=1.0, refill_rate=1.0)
        assert policy.time_until_available(t(0.0)).to_seconds() == 0.0

    def test_steady_rate_matches_refill(self):
        policy = TokenBucketPolicy(capacity=1.0, refill_rate=4.0)
        times = [i * 0.05 for i in range(200)]  # 20/s offered for 10s
        count = sum(admitted(policy, times))
        assert count == pytest.approx(41, abs=2)  # 4/s + initial token


class TestLeakyBucket:
    def test_paces_at_leak_rate(self):
        policy = LeakyBucketPolicy(leak_rate=2.0)
        times = [i * 0.1 for i in range(100)]  # 10/s offered for 10s
        count = sum(admitted(policy, times))
        assert count == pytest.approx(20, abs=2)

    def test_no_burst_banking(self):
        """Unlike a token bucket, idle time banks nothing."""
        policy = LeakyBucketPolicy(leak_rate=1.0)
        policy.try_acquire(t(0.0))
        results = admitted(policy, [100.0] * 3)
        assert results == [True, False, False]

    def test_time_until_available_honest(self):
        policy = LeakyBucketPolicy(leak_rate=4.0)
        assert policy.try_acquire(t(0.0))
        wait = policy.time_until_available(t(0.0)).to_seconds()
        assert 0.0 < wait <= 0.25 + 1e-9
        assert policy.try_acquire(t(wait))


class TestSlidingWindow:
    def test_admits_exactly_max_in_any_window(self):
        policy = SlidingWindowPolicy(window_size_seconds=1.0, max_requests=3)
        assert admitted(policy, [0.0, 0.1, 0.2, 0.3]) == [True, True, True, False]

    def test_slides_continuously_not_in_steps(self):
        policy = SlidingWindowPolicy(window_size_seconds=1.0, max_requests=2)
        assert policy.try_acquire(t(0.0))
        assert policy.try_acquire(t(0.6))
        assert not policy.try_acquire(t(0.9))
        # At 1.001 the t=0 admission has left the window; one slot opens.
        assert policy.try_acquire(t(1.001))
        # But the 0.6 admission still occupies until 1.6.
        assert not policy.try_acquire(t(1.5))
        assert policy.try_acquire(t(1.601))

    def test_no_boundary_double_burst(self):
        """The fixed-window failure mode the sliding window exists to
        prevent: 2x max around a boundary must NOT be admitted."""
        policy = SlidingWindowPolicy(window_size_seconds=1.0, max_requests=4)
        times = [0.7, 0.8, 0.9, 0.95, 1.05, 1.1, 1.2, 1.3]
        assert sum(admitted(policy, times)) == 4


class TestFixedWindow:
    def test_resets_exactly_at_boundary(self):
        policy = FixedWindowPolicy(requests_per_window=2, window_size=1.0)
        assert admitted(policy, [0.0, 0.5, 0.9]) == [True, True, False]
        assert policy.try_acquire(t(1.0))  # fresh window

    def test_boundary_double_burst_is_the_known_tradeoff(self):
        policy = FixedWindowPolicy(requests_per_window=4, window_size=1.0)
        times = [0.7, 0.8, 0.9, 0.95, 1.05, 1.1, 1.2, 1.3]
        # 2x max straddles the boundary — fixed windows allow it.
        assert sum(admitted(policy, times)) == 8

    def test_empty_windows_do_not_bank(self):
        policy = FixedWindowPolicy(requests_per_window=1, window_size=1.0)
        policy.try_acquire(t(0.0))
        results = admitted(policy, [10.0, 10.1])
        assert results == [True, False]


class TestAdaptive:
    def test_backpressure_halves_success_grows(self):
        policy = AdaptivePolicy(initial_rate=8.0, min_rate=1.0, max_rate=16.0)
        before = policy.current_rate
        policy.record_backpressure(t(1.0))
        halved = policy.current_rate
        assert halved == pytest.approx(before / 2)
        for i in range(50):
            policy.record_success(t(2.0 + i))
        assert policy.current_rate > halved

    def test_rate_floor_and_ceiling(self):
        policy = AdaptivePolicy(initial_rate=4.0, min_rate=2.0, max_rate=6.0)
        for i in range(10):
            policy.record_backpressure(t(float(i)))
        assert policy.current_rate == pytest.approx(2.0)
        for i in range(1000):
            policy.record_success(t(20.0 + i * 0.01))
        assert policy.current_rate <= 6.0 + 1e-9

    def test_admission_follows_current_rate(self):
        policy = AdaptivePolicy(initial_rate=2.0, min_rate=1.0, max_rate=4.0)
        times = [i * 0.1 for i in range(100)]  # 10/s offered for 10s
        count = sum(admitted(policy, times))
        assert count <= 2.0 * 10 * 1.6  # bounded by ~current_rate x horizon


@pytest.mark.parametrize(
    "policy_factory",
    [
        lambda: TokenBucketPolicy(capacity=2.0, refill_rate=1.0),
        lambda: LeakyBucketPolicy(leak_rate=1.0),
        lambda: SlidingWindowPolicy(window_size_seconds=1.0, max_requests=2),
        lambda: FixedWindowPolicy(requests_per_window=2, window_size=1.0),
        lambda: AdaptivePolicy(initial_rate=2.0, min_rate=1.0, max_rate=4.0),
    ],
    ids=["token", "leaky", "sliding", "fixed", "adaptive"],
)
class TestPolicyConformance:
    def test_long_run_rate_bounded_by_configured_limit(self, policy_factory):
        """No policy may admit meaningfully above its configured rate
        over a long horizon (2/s here), whatever the burst pattern."""
        policy = policy_factory()
        times = []
        for second in range(30):
            times.extend(second + i * 0.02 for i in range(20))  # bursts
        count = sum(admitted(policy, times))
        assert count <= 2.0 * 30 + 3, count

    def test_time_until_available_nonnegative(self, policy_factory):
        policy = policy_factory()
        for moment in (0.0, 0.3, 1.7):
            policy.try_acquire(t(moment))
            assert policy.time_until_available(t(moment)).to_seconds() >= 0.0

    def test_denial_then_promised_wait_admits(self, policy_factory):
        policy = policy_factory()
        now = 0.0
        while policy.try_acquire(t(now)):
            now += 1e-6
        wait = policy.time_until_available(t(now)).to_seconds()
        assert policy.try_acquire(t(now + wait + 1e-6)), (
            "time_until_available under-promised"
        )
