"""Unit tests: storage (SSTable, Memtable, WAL, BTree, LSMTree, transactions)."""

import pytest

from happysim_tpu import Entity, Event, Instant, Simulation
from happysim_tpu.components.storage import (
    BTree,
    FIFOCompaction,
    IsolationLevel,
    LSMTree,
    LeveledCompaction,
    Memtable,
    SSTable,
    SizeTieredCompaction,
    SyncEveryWrite,
    SyncOnBatch,
    SyncPeriodic,
    TransactionManager,
    WriteAheadLog,
)


def t(seconds):
    return Instant.from_seconds(seconds)


class Driver(Entity):
    def __init__(self, name, script):
        super().__init__(name)
        self.script = script
        self.results = []
        self.done_at = None

    def handle_event(self, event):
        result = yield from self.script(self)
        self.results.append(result)
        self.done_at = self.now.to_seconds()


def run_script(script, entities, duration=600.0):
    driver = Driver("driver", script)
    sim = Simulation(entities=[driver, *entities], duration=duration)
    sim.schedule([Event(t(0.0), "go", target=driver)])
    sim.run()
    return driver


# ----------------------------------------------------------------- SSTable ----
class TestSSTable:
    def test_sorted_get_scan(self):
        sst = SSTable([("c", 3), ("a", 1), ("b", 2)])
        assert sst.min_key == "a" and sst.max_key == "c"
        assert sst.get("b") == 2
        assert sst.get("z") is None
        assert sst.scan("a", "c") == [("a", 1), ("b", 2)]
        assert len(sst) == 3

    def test_bloom_filter_saves_reads(self):
        sst = SSTable([(f"key{i:04d}", i) for i in range(100)])
        assert sst.page_reads_for_get("key0050") == 2
        # A definitely-absent key is usually bloom-filtered to 0 pages.
        absent_zero = sum(
            1 for i in range(100) if sst.page_reads_for_get(f"zzz{i}") == 0
        )
        assert absent_zero > 90  # 1% nominal FP rate

    def test_overlaps(self):
        a = SSTable([("a", 1), ("m", 2)])
        b = SSTable([("k", 1), ("z", 2)])
        c = SSTable([("n", 1), ("z", 2)])
        assert a.overlaps(b)
        assert not a.overlaps(c)

    def test_sparse_index_consistency(self):
        data = [(f"k{i:05d}", i) for i in range(1000)]
        sst = SSTable(data, index_interval=16)
        for i in (0, 1, 15, 16, 17, 500, 998, 999):
            assert sst.get(f"k{i:05d}") == i


# ---------------------------------------------------------------- Memtable ----
class TestMemtable:
    def test_put_until_full_then_flush(self):
        mem = Memtable("m", size_threshold=3)
        assert not mem.put_sync("a", 1)
        assert not mem.put_sync("b", 2)
        assert mem.put_sync("c", 3)  # now full
        sst = mem.flush()
        assert mem.size == 0
        assert sst.key_count == 3
        assert sst.get("b") == 2
        assert mem.stats.flushes == 1


# --------------------------------------------------------------------- WAL ----
class TestWAL:
    def test_sync_every_write_durability(self):
        wal = WriteAheadLog("wal", sync_policy=SyncEveryWrite())

        def script(self):
            yield from wal.append("a", 1)
            yield from wal.append("b", 2)
            return wal.synced_up_to

        driver = run_script(script, [wal])
        assert driver.results == [2]
        assert wal.crash() == 0  # everything synced, nothing lost

    def test_sync_on_batch_loses_unsynced_on_crash(self):
        wal = WriteAheadLog("wal", sync_policy=SyncOnBatch(batch_size=3))

        def script(self):
            for i in range(5):  # syncs after 3; entries 4-5 unsynced
                yield from wal.append(f"k{i}", i)
            return wal.synced_up_to

        driver = run_script(script, [wal])
        assert driver.results == [3]
        lost = wal.crash()
        assert lost == 2
        assert [e.key for e in wal.recover()] == ["k0", "k1", "k2"]

    def test_sync_periodic(self):
        wal = WriteAheadLog("wal", sync_policy=SyncPeriodic(interval_s=1.0))

        def script(self):
            yield from wal.append("a", 1)  # t~0: 0 >= 1.0? no... but first
            yield 2.0
            yield from wal.append("b", 2)  # 2s since last sync -> syncs
            return wal.stats.syncs

        driver = run_script(script, [wal])
        assert driver.results[0] >= 1

    def test_truncate(self):
        wal = WriteAheadLog("wal")
        wal.append_sync("a", 1)
        wal.append_sync("b", 2)
        wal.truncate(1)
        assert [e.key for e in wal.recover()] == ["b"]


# ------------------------------------------------------------------- BTree ----
class TestBTree:
    def test_put_get_delete(self):
        tree = BTree("bt", order=4)
        for i in range(100):
            tree.put_sync(f"k{i:03d}", i)
        assert tree.size == 100
        assert tree.depth > 1  # splits happened
        assert tree.stats.node_splits > 0
        for i in (0, 37, 99):
            assert tree.get_sync(f"k{i:03d}") == i
        assert tree.get_sync("nope") is None
        assert tree.delete_sync("k037")
        assert tree.get_sync("k037") is None
        assert not tree.delete_sync("k037")
        assert tree.size == 99

    def test_update_in_place(self):
        tree = BTree("bt", order=4)
        tree.put_sync("a", 1)
        tree.put_sync("a", 2)
        assert tree.size == 1
        assert tree.get_sync("a") == 2

    def test_latency_scales_with_depth(self):
        tree = BTree("bt", order=4, page_read_latency=0.001, page_write_latency=0.0)
        for i in range(200):
            tree.put_sync(f"k{i:03d}", i)
        depth = tree.depth

        def script(self):
            value = yield from tree.get("k100")
            return value

        driver = run_script(script, [tree])
        assert driver.results == [100]
        assert driver.done_at == pytest.approx(depth * 0.001)

    def test_scan_range(self):
        tree = BTree("bt", order=8)
        for i in range(50):
            tree.put_sync(f"k{i:02d}", i)

        def script(self):
            result = yield from tree.scan("k10", "k15")
            return result

        driver = run_script(script, [tree])
        assert driver.results[0] == [(f"k{i}", i) for i in range(10, 15)]


# ------------------------------------------------------------------ LSMTree ----
class TestLSMTree:
    def test_write_flush_read_path(self):
        lsm = LSMTree("db", memtable_size=10,
                      compaction_strategy=SizeTieredCompaction(min_sstables=100))

        def script(self):
            for i in range(25):  # 2 flushes + 5 in memtable
                yield from lsm.put(f"k{i:02d}", i)
            values = []
            for i in (0, 12, 24):
                v = yield from lsm.get(f"k{i:02d}")
                values.append(v)
            missing = yield from lsm.get("nope")
            return (values, missing)

        driver = run_script(script, [lsm])
        assert driver.results == [([0, 12, 24], None)]
        assert lsm.stats.memtable_flushes == 2
        assert lsm.stats.total_sstables == 2
        assert lsm.stats.bloom_filter_saves > 0  # "nope" skipped via bloom

    def test_delete_tombstone(self):
        lsm = LSMTree("db", memtable_size=5)

        def script(self):
            yield from lsm.put("a", 1)
            yield from lsm.delete("a")
            value = yield from lsm.get("a")
            return value

        driver = run_script(script, [lsm])
        assert driver.results == [None]

    def test_compaction_merges_levels(self):
        lsm = LSMTree("db", memtable_size=4,
                      compaction_strategy=SizeTieredCompaction(min_sstables=3))

        def script(self):
            for i in range(24):
                yield from lsm.put(f"k{i:02d}", i)
            v = yield from lsm.get("k00")
            return v

        driver = run_script(script, [lsm])
        assert driver.results == [0]
        assert lsm.stats.compactions >= 1
        # Newer values must win after compaction
        assert lsm.get_sync("k23") == 23

    def test_compaction_newest_value_wins(self):
        lsm = LSMTree("db", memtable_size=2,
                      compaction_strategy=SizeTieredCompaction(min_sstables=2))

        def script(self):
            yield from lsm.put("x", "old")
            yield from lsm.put("pad1", 1)  # flush 1: {x:old, pad1}
            yield from lsm.put("x", "new")
            yield from lsm.put("pad2", 2)  # flush 2 -> compaction of L0
            value = yield from lsm.get("x")
            return value

        driver = run_script(script, [lsm])
        assert driver.results == ["new"]

    def test_scan_merges_all_sources(self):
        lsm = LSMTree("db", memtable_size=4)

        def script(self):
            for i in range(10):
                yield from lsm.put(f"k{i:02d}", i)
            yield from lsm.delete("k03")
            result = yield from lsm.scan("k00", "k06")
            return result

        driver = run_script(script, [lsm])
        assert driver.results[0] == [(f"k{i:02d}", i) for i in (0, 1, 2, 4, 5)]

    def test_crash_loses_unsynced_recovers_wal(self):
        wal = WriteAheadLog("wal", sync_policy=SyncEveryWrite())
        lsm = LSMTree("db", memtable_size=100, wal=wal)

        def script(self):
            for i in range(10):
                yield from lsm.put(f"k{i}", i)
            lost = lsm.crash()
            recovered = lsm.recover_from_crash()
            value = yield from lsm.get("k5")
            return (lost["memtable_entries_lost"], recovered["wal_entries_replayed"], value)

        driver = run_script(script, [lsm, wal])
        lost_count, replayed, value = driver.results[0]
        assert lost_count == 10  # memtable was volatile
        assert replayed == 10  # but every write was WAL-synced
        assert value == 5  # fully recovered

    def test_fifo_compaction_drops_oldest(self):
        lsm = LSMTree("db", memtable_size=2,
                      compaction_strategy=FIFOCompaction(max_total_sstables=3))

        def script(self):
            for i in range(16):
                yield from lsm.put(f"k{i:02d}", i)
            return lsm.stats.total_sstables

        driver = run_script(script, [lsm])
        # 8 flushes happened; FIFO compaction keeps merging the deepest
        # level, so far fewer than 8 sstables remain.
        assert driver.results[0] < 8
        assert lsm.stats.compactions >= 1


# ------------------------------------------------------------- Transactions ----
class TestTransactionManager:
    def _setup(self, isolation):
        lsm = LSMTree("db", memtable_size=1000)
        tm = TransactionManager("tm", store=lsm, isolation=isolation)
        return lsm, tm

    def test_commit_applies_buffered_writes(self):
        lsm, tm = self._setup(IsolationLevel.SNAPSHOT_ISOLATION)

        def script(self):
            tx = yield from tm.begin()
            yield from tx.write("a", 1)
            assert lsm.get_sync("a") is None  # buffered, not applied
            ok = yield from tx.commit()
            return (ok, lsm.get_sync("a"))

        driver = run_script(script, [lsm, tm])
        assert driver.results == [(True, 1)]
        assert tm.stats.transactions_committed == 1

    def test_snapshot_isolation_write_write_conflict(self):
        lsm, tm = self._setup(IsolationLevel.SNAPSHOT_ISOLATION)

        def script(self):
            tx1 = yield from tm.begin()
            tx2 = yield from tm.begin()
            yield from tx1.write("k", "tx1")
            yield from tx2.write("k", "tx2")
            ok1 = yield from tx1.commit()  # first committer wins
            ok2 = yield from tx2.commit()  # write-write conflict -> abort
            return (ok1, ok2, lsm.get_sync("k"))

        driver = run_script(script, [lsm, tm])
        assert driver.results == [(True, False, "tx1")]
        assert tm.stats.conflicts_detected == 1

    def test_serializable_read_write_conflict(self):
        lsm, tm = self._setup(IsolationLevel.SERIALIZABLE)
        lsm.put_sync("k", "initial")

        def script(self):
            tx1 = yield from tm.begin()
            tx2 = yield from tm.begin()
            _ = yield from tx2.read("k")  # tx2 reads k
            yield from tx1.write("k", "tx1")
            ok1 = yield from tx1.commit()
            yield from tx2.write("other", 1)
            ok2 = yield from tx2.commit()  # read-write conflict -> abort
            return (ok1, ok2)

        driver = run_script(script, [lsm, tm])
        assert driver.results == [(True, False)]

    def test_read_committed_never_conflicts(self):
        lsm, tm = self._setup(IsolationLevel.READ_COMMITTED)

        def script(self):
            tx1 = yield from tm.begin()
            tx2 = yield from tm.begin()
            yield from tx1.write("k", "tx1")
            yield from tx2.write("k", "tx2")
            ok1 = yield from tx1.commit()
            ok2 = yield from tx2.commit()  # last writer wins, no abort
            return (ok1, ok2, lsm.get_sync("k"))

        driver = run_script(script, [lsm, tm])
        assert driver.results == [(True, True, "tx2")]

    def test_read_your_own_writes(self):
        lsm, tm = self._setup(IsolationLevel.SNAPSHOT_ISOLATION)

        def script(self):
            tx = yield from tm.begin()
            yield from tx.write("a", 42)
            value = yield from tx.read("a")
            yield from tx.commit()
            return value

        driver = run_script(script, [lsm, tm])
        assert driver.results == [42]

    def test_abort_discards_writes(self):
        lsm, tm = self._setup(IsolationLevel.SNAPSHOT_ISOLATION)

        def script(self):
            tx = yield from tm.begin()
            yield from tx.write("a", 1)
            tx.abort()
            return lsm.get_sync("a")

        driver = run_script(script, [lsm, tm])
        assert driver.results == [None]
        assert tm.stats.transactions_aborted == 1


class TestLSMConcurrencyRegressions:
    def test_interleaved_wal_write_survives_flush_truncate(self):
        """A WAL-synced write landing DURING another entity's flush must
        survive the post-flush truncate and be recoverable."""
        wal = WriteAheadLog("wal", sync_policy=SyncEveryWrite())
        lsm = LSMTree("db", memtable_size=3, wal=wal,
                      compaction_strategy=SizeTieredCompaction(min_sstables=100),
                      sstable_write_latency=1.0)  # long flush window
        order = []

        class Flusher(Entity):
            def handle_event(self, event):
                for i in range(3):  # 3rd put triggers the slow flush
                    yield from lsm.put(f"a{i}", i)
                order.append(("flusher_done", self.now.to_seconds()))

        class Interleaver(Entity):
            def handle_event(self, event):
                yield from lsm.put("interleaved", "precious")
                order.append(("interleave_done", self.now.to_seconds()))

        flusher, inter = Flusher("f"), Interleaver("i")
        sim = Simulation(entities=[wal, lsm, flusher, inter], duration=60.0)
        sim.schedule([Event(t(0.0), "go", target=flusher)])
        sim.schedule([Event(t(0.01), "go", target=inter)])  # mid-flush
        sim.run()
        lsm.crash()
        recovered = lsm.recover_from_crash()
        # The interleaved WAL-synced write must be recovered.
        assert recovered["wal_entries_replayed"] >= 1
        assert lsm.get_sync("interleaved") == "precious"

    def test_reads_during_flush_see_immutable_memtable(self):
        """Keys being flushed stay readable throughout the flush window."""
        lsm = LSMTree("db", memtable_size=3,
                      compaction_strategy=SizeTieredCompaction(min_sstables=100),
                      sstable_write_latency=1.0)
        seen = {}

        class Writer(Entity):
            def handle_event(self, event):
                for i in range(3):
                    yield from lsm.put(f"k{i}", i)

        class MidFlushReader(Entity):
            def handle_event(self, event):
                value = yield from lsm.get("k0")
                seen["value"] = value
                seen["at"] = self.now.to_seconds()

        writer, reader = Writer("w"), MidFlushReader("r")
        sim = Simulation(entities=[lsm, writer, reader], duration=60.0)
        sim.schedule([Event(t(0.0), "go", target=writer)])
        sim.schedule([Event(t(0.5), "go", target=reader)])  # during flush
        sim.run()
        assert seen["value"] == 0
        assert seen["at"] < 1.1  # answered from memory, not post-flush

    def test_fifo_compaction_reclaims_space(self):
        lsm = LSMTree("db", memtable_size=2,
                      compaction_strategy=FIFOCompaction(max_total_sstables=3))
        for i in range(40):
            lsm.put_sync(f"k{i:02d}", i)
        # Old keys actually discarded (retention), not merged downward.
        total_keys = sum(s.key_count for level in lsm._levels for s in level)
        assert total_keys < 40
        assert lsm.get_sync("k39") == 39  # newest survive


class TestAdvisorRegressions:
    def test_overlapping_flushes_truncate_only_durable_prefix(self):
        """A later flush finishing first must not truncate WAL entries that
        an earlier, still-in-flight flush has yet to make durable."""
        wal = WriteAheadLog("wal", sync_policy=SyncEveryWrite())
        lsm = LSMTree("db", memtable_size=1000, wal=wal)

        def drain(gen):
            try:
                while True:
                    next(gen)
            except StopIteration:
                pass

        for i in range(5):
            drain(lsm.put(f"a{i}", i))  # WAL seq 1-5
        flush_a = lsm._flush_memtable()
        next(flush_a)  # A in flight, covers seq 1-5
        for i in range(5):
            drain(lsm.put(f"b{i}", i))  # WAL seq 6-10
        flush_b = lsm._flush_memtable()
        next(flush_b)  # B in flight, covers seq 6-10
        drain(flush_b)  # B completes FIRST
        # A's entries (1-5) are not yet in any SSTable: nothing may go.
        assert wal.size == 10
        drain(flush_a)  # A completes: whole prefix is durable now
        assert wal.size == 0

    def test_crash_mid_flush_does_not_pin_wal_truncation(self):
        """A flush interrupted by a crash must not leave a ticket that
        blocks WAL truncation forever."""
        wal = WriteAheadLog("wal", sync_policy=SyncEveryWrite())
        lsm = LSMTree("db", memtable_size=1000, wal=wal)

        def drain(gen):
            try:
                while True:
                    next(gen)
            except StopIteration:
                pass

        for i in range(4):
            drain(lsm.put(f"a{i}", i))
        interrupted = lsm._flush_memtable()
        next(interrupted)  # in flight when the node dies
        lsm.crash()
        lsm.recover_from_crash()
        for i in range(4):
            drain(lsm.put(f"b{i}", i))
        drain(lsm._flush_memtable())  # a post-recovery flush completes
        assert wal.size == 0  # truncation advanced; nothing pinned
