"""Unit tier for the fused Pallas event-step kernel (tpu/kernels/).

Interpret-mode equivalence on CPU: one kernel invocation (a macro-block
of fused event steps on a replica tile) must be BIT-IDENTICAL to the lax
path's ``lax.scan`` over the same step closure and the same uniform
block. Plus the pure-host pieces: tile selection, replica padding, and
the sound-decline predicate.

CI runs this file as its own gate step with ``HS_TPU_PALLAS=1`` (see
.github/workflows/tests.yml); it must skip cleanly when
``jax.experimental.pallas`` is unavailable.
"""

import numpy as np
import pytest

pytest.importorskip("jax.experimental.pallas")

import jax
import jax.numpy as jnp
from jax import lax

from happysim_tpu.tpu.engine import _Compiled
from happysim_tpu.tpu.kernels import (
    build_block_step,
    choose_tile,
    kernel_plan,
    pad_replicas,
    replica_tile_bytes,
    replica_working_set_bytes,
    shared_const_bytes,
)
from happysim_tpu.tpu.kernels.event_step import padded_replica_count
from happysim_tpu.tpu.model import EnsembleModel, FaultSpec, mm1_model


def _mm1(horizon=3.0):
    return mm1_model(lam=5.0, mu=9.0, horizon_s=horizon, queue_capacity=8)


def _chain_with_transit():
    model = EnsembleModel(horizon_s=2.0)
    src = model.source(rate=4.0)
    first = model.server(service_mean=0.05, queue_capacity=8)
    second = model.server(service_mean=0.07, queue_capacity=8, service="erlang")
    snk = model.sink()
    model.connect(src, first, latency_s=0.02, latency_kind="exponential")
    model.connect(first, second, latency_s=0.01)
    model.connect(second, snk)
    return model


def _faulted_telemetry_chain():
    """The production shape this PR moves onto the fast path: stochastic
    fault windows (outage + degrade) AND windowed telemetry buffers,
    both riding the VMEM tile as ordinary state leaves."""
    model = _chain_with_transit()
    model.servers[0].fault = FaultSpec(rate=0.8, mean_duration_s=0.2)
    model.servers[1].fault = FaultSpec(
        rate=0.5, mean_duration_s=0.3, mode="degrade", latency_factor=2.0
    )
    model.telemetry(window_s=0.5)
    return model


def _router_fanout(policy="random", weights=None, n_servers=4):
    """ISSUE-11 load-balancer shape: 1 source -> router -> N servers ->
    fan-in -> 1 sink, with per-target latency edges (constant AND
    exponential, plus a latency-free sibling — the transit-forcing mix).
    Tiny shapes: interpret-mode compile scales with the unroll, and the
    fan-out already multiplies nV."""
    model = EnsembleModel(horizon_s=2.0, transit_capacity=8)
    src = model.source(rate=6.0)
    servers = [
        model.server(service_mean=0.05, queue_capacity=8)
        for _ in range(n_servers)
    ]
    router = model.router(policy=policy, weights=weights)
    snk = model.sink()
    model.connect(src, router)
    edge_mix = [(0.01, "constant"), (0.02, "exponential"), (0.0, "constant")]
    for index, server in enumerate(servers):
        latency_s, kind = edge_mix[index % len(edge_mix)]
        model.connect(router, server, latency_s=latency_s, latency_kind=kind)
        model.connect(server, snk)
    return model


def _router_random():
    return _router_fanout("random")


def _router_round_robin():
    return _router_fanout("round_robin")


def _router_weighted():
    return _router_fanout("weighted", weights=(1.0, 2.0, 3.0, 4.0))


def _router_faulted_telemetry():
    """Fan-out + chaos + telemetry: the full "load-balanced production
    model" register file (rr_next cursor, per-server rings, transit
    registers, fault windows, telemetry buffers) resident in one tile."""
    model = _router_fanout("round_robin")
    model.servers[0].fault = FaultSpec(rate=0.8, mean_duration_s=0.2)
    model.telemetry(window_s=0.5)
    return model


def _chaos_fanout():
    """ISSUE 14's whole chaos stack in one tile: limiter admission,
    backoff+jitter client retries, hedged requests, correlated
    (shared-Bernoulli) outages, a deterministic brownout window, and
    per-target packet loss on top of the faulted+telemetry fan-out —
    every remaining chaos decline flipped to approved, block-identical
    by the same argument (the chaos machinery lives inside the traced
    step closure the kernel drives)."""
    model = EnsembleModel(horizon_s=2.0, transit_capacity=8)
    src = model.source(rate=6.0)
    lim = model.limiter(refill_rate=8.0, capacity=4.0)
    servers = []
    for index in range(4):
        servers.append(
            model.server(
                service_mean=0.05,
                queue_capacity=8,
                deadline_s=0.6,
                max_retries=2,
                retry_backoff_s=0.05,
                retry_jitter=0.5,
                hedge_delay_s=0.15 if index % 2 == 0 else None,
                fault=FaultSpec(
                    rate=0.4, mean_duration_s=0.3, correlated=True
                )
                if index < 2
                else None,
                outage=(0.8, 1.1) if index == 3 else None,
            )
        )
    model.correlated_outages(rate=0.3, mean_duration_s=0.3, trigger_p=0.5)
    router = model.router(policy="round_robin")
    snk = model.sink()
    model.connect(src, lim)
    model.connect(lim, router)
    edge_mix = [(0.01, "constant"), (0.02, "exponential"), (0.0, "constant")]
    for index, server in enumerate(servers):
        latency_s, kind = edge_mix[index % len(edge_mix)]
        model.connect(
            router,
            server,
            latency_s=latency_s,
            latency_kind=kind,
            loss_p=0.05 if index % 2 == 0 else 0.0,
        )
        model.connect(server, snk)
    model.telemetry(window_s=0.5)
    return model


def _resilience_fanout():
    """ISSUE 15's defense layer on top of the full chaos fan-out: every
    server carries a circuit breaker state machine (the block-level
    breaker matrix), admission-control load shedding with a priority
    fraction (its Bernoulli is an ordinary uniform slot), and a retry
    budget gating the backoff/hedge launch sites — all per-lane state
    columns inside the traced step closure, so the fused block stays
    bit-identical by the same argument as the chaos stack."""
    model = _chaos_fanout()
    model.circuit_breaker(
        failure_threshold=2, window_s=0.5, cooldown_s=0.3, half_open_probes=1
    )
    model.load_shed(policy="queue_depth", threshold=2, priority_fraction=0.25)
    model.retry_budget(ratio=0.2, min_per_s=0.5, burst=2.0)
    return model


def _profiled_chain():
    """ISSUE 17: a ramp-profiled source on the transit chain — the
    profile's inverse-integral lookup tables ride the kernel as
    tile-shared constants, so block identity must hold with the
    "source has a rate profile" decline gone. Chain-shaped so this
    leg stays inside the tier-1 compile envelope."""
    model = _chain_with_transit()
    model.sources[0].profile = __import__(
        "happysim_tpu.tpu.model", fromlist=["RateProfile"]
    ).RateProfile(kind="ramp", end_rate=9.0, ramp_duration_s=1.0)
    return model


def _graph_lo_fanout():
    """The adaptive fan-out: least_outstanding over the 4-server mix —
    the outstanding-count gather (in-service + queued) runs inside the
    traced closure, so the fused block must agree bit for bit."""
    return _router_fanout("least_outstanding")


def _graph_shared_backend():
    """ISSUE 17's acceptance DAG: ramp-profiled source -> adaptive
    front tier -> both front servers feed the back router -> adaptive
    back tier -> sink (2 routers, shared backends, kernel_shape
    "graph")."""
    model = EnsembleModel(horizon_s=2.0, transit_capacity=8)
    src = model.ramp_source(start_rate=3.0, end_rate=9.0, ramp_duration_s=1.5)
    front = [model.server(service_mean=0.05, queue_capacity=8) for _ in range(2)]
    back = [model.server(service_mean=0.04, queue_capacity=8) for _ in range(2)]
    front_lb = model.router(policy="least_outstanding")
    back_lb = model.router(policy="least_outstanding")
    snk = model.sink()
    model.connect(src, front_lb)
    for server in front:
        model.connect(front_lb, server)
        model.connect(server, back_lb)
    for server in back:
        model.connect(back_lb, server)
        model.connect(server, snk)
    return model


def _graph_router_tier():
    """DIRECT router->router chaining: a random front router picks a
    weighted back router or a server, exercising the depth-indexed
    route-draw slots (U_ROUTE_HOPS) that only exist on tiered graphs."""
    model = EnsembleModel(horizon_s=2.0, transit_capacity=8)
    src = model.source(rate=6.0)
    direct = model.server(service_mean=0.05, queue_capacity=8)
    tiered = [model.server(service_mean=0.04, queue_capacity=8) for _ in range(2)]
    front = model.router(policy="random")
    back = model.router(policy="weighted", weights=(1.0, 2.0))
    snk = model.sink()
    model.connect(src, front)
    model.connect(front, back)
    model.connect(front, direct)
    for server in tiered:
        model.connect(back, server)
    for server in (direct, *tiered):
        model.connect(server, snk)
    return model


def _init_batch(compiled, n_replicas, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), n_replicas)
    params = {
        "src_rate": jnp.broadcast_to(
            jnp.asarray([s.rate for s in compiled.model.sources], jnp.float32),
            (n_replicas, compiled.nS),
        ),
        "srv_mean": jnp.broadcast_to(
            jnp.asarray(
                [s.service_mean_s for s in compiled.model.servers] or [1.0],
                jnp.float32,
            ),
            (n_replicas, compiled.nV),
        ),
    }
    state = jax.vmap(compiled.init_state)(keys, params)
    state.pop("key")
    return keys, params, state


def _lax_block(compiled, horizon, state, U, params):
    """The reference macro-block: scan of the SAME step closure per lane."""
    step = compiled.make_step(horizon, external_u=True)

    def one(state_row, u_rows, params_row):
        (out, _), _ = lax.scan(step, (state_row, params_row), u_rows)
        return out

    return jax.vmap(one)(state, U, params)


# Two fused steps are enough to prove in-kernel chaining; interpret-mode
# XLA build time scales with the unroll, and tier-1 wall time is tight.
MACRO = 2


# Twelve topologies: the transit chain exercises the superset of the
# base state leaves (two servers, erlang family, transit registers)
# WITHOUT telemetry, and the faulted+telemetry chain adds the fault
# registers + windowed buffers — so bit-identity is asserted with
# telemetry off AND on at block level. The profiled chain (ISSUE 17)
# keeps the rate-profile lookup tables (tile-shared consts) in the
# tier-1 block matrix. The router fan-outs (ISSUE 11/17) cover all
# FOUR kernel-approved policies over mixed per-target edges, the
# faulted+telemetry fan-out pins the full load-balanced production
# register file in one tile, the chaos fan-out (ISSUE 14) layers the
# whole resilience stack on top (limiter, backoff retries, hedging,
# correlated outages, brownout, packet loss), and the graph matrix
# (ISSUE 17) adds the 2-router shared-backend DAG and the DIRECT
# router->router tier with its depth-indexed route draws. The fan-out
# and graph legs are slow-marked (each multi-server build is ~20-35s
# of interpret-mode XLA, beyond the tier-1 envelope) and run in the CI
# kernel-equivalence gate + the nightly tier instead. The M/M/1 shape
# gets block-level coverage from the consecutive-blocks test below and
# full-run coverage from the integration + regression tiers.
@pytest.mark.parametrize(
    "build",
    [
        _chain_with_transit,
        _faulted_telemetry_chain,
        _profiled_chain,
        pytest.param(_router_random, marks=pytest.mark.slow),
        pytest.param(_router_round_robin, marks=pytest.mark.slow),
        pytest.param(_router_weighted, marks=pytest.mark.slow),
        pytest.param(_graph_lo_fanout, marks=pytest.mark.slow),
        pytest.param(_router_faulted_telemetry, marks=pytest.mark.slow),
        pytest.param(_chaos_fanout, marks=pytest.mark.slow),
        pytest.param(_resilience_fanout, marks=pytest.mark.slow),
        pytest.param(_graph_shared_backend, marks=pytest.mark.slow),
        pytest.param(_graph_router_tier, marks=pytest.mark.slow),
    ],
)
def test_block_kernel_bit_identical_to_lax_scan(build):
    """One fused kernel call == the lax scan, leaf by leaf, bit for bit."""
    model = build()
    compiled = _Compiled(model)
    horizon = float(model.horizon_s)
    n_replicas = 4
    keys, params, state = _init_batch(compiled, n_replicas)
    U = jax.vmap(
        lambda k: jax.random.uniform(
            jax.random.fold_in(k, 0),
            (MACRO, compiled.n_draws),
            minval=1e-12,
            maxval=1.0,
        )
    )(keys)

    block_fn, meta = build_block_step(
        compiled, horizon, MACRO, n_replicas, interpret=True
    )
    assert meta["padded_replicas"] == n_replicas  # power-of-two count
    kernel_out = block_fn(state, U, params)
    lax_out = _lax_block(compiled, horizon, state, U, params)

    assert set(kernel_out) == set(lax_out)
    for name in sorted(lax_out):
        np.testing.assert_array_equal(
            np.asarray(kernel_out[name]),
            np.asarray(lax_out[name]),
            err_msg=f"leaf {name} diverged",
        )


@pytest.mark.slow
@pytest.mark.parametrize(
    "breaker_kwargs",
    [
        dict(failure_threshold=1, window_s=0.2, cooldown_s=0.2, half_open_probes=1),
        dict(failure_threshold=3, window_s=0.5, cooldown_s=0.3, half_open_probes=2),
        dict(failure_threshold=5, window_s=1.0, cooldown_s=0.5, half_open_probes=4),
    ],
    ids=["trip-on-first", "sliding-3", "wide-5"],
)
def test_block_kernel_breaker_matrix(breaker_kwargs):
    """ISSUE-15 breaker matrix: the closed->open->half-open machine is
    block-identical kernel-vs-lax across threshold/window/cooldown/probe
    corners — the sliding-window ring write, the lazy cooldown
    transition, and the probe quota are all per-lane ops inside the
    traced closure, so every corner must agree bit for bit."""
    model = _faulted_telemetry_chain()
    model.servers[0].deadline_s = 0.3
    model.circuit_breaker(**breaker_kwargs)
    compiled = _Compiled(model)
    horizon = float(model.horizon_s)
    n_replicas = 4
    keys, params, state = _init_batch(compiled, n_replicas)
    U = jax.vmap(
        lambda k: jax.random.uniform(
            jax.random.fold_in(k, 0),
            (MACRO, compiled.n_draws),
            minval=1e-12,
            maxval=1.0,
        )
    )(keys)
    block_fn, _meta = build_block_step(
        compiled, horizon, MACRO, n_replicas, interpret=True
    )
    kernel_out = block_fn(state, U, params)
    lax_out = _lax_block(compiled, horizon, state, U, params)
    assert set(kernel_out) == set(lax_out)
    assert any(name.startswith("brk_") for name in kernel_out)
    for name in sorted(lax_out):
        np.testing.assert_array_equal(
            np.asarray(kernel_out[name]),
            np.asarray(lax_out[name]),
            err_msg=f"leaf {name} diverged",
        )


def test_block_kernel_consecutive_blocks_stay_identical():
    """Chaining kernel blocks (state fed back in) tracks the lax chain."""
    model = _mm1()
    compiled = _Compiled(model)
    horizon = float(model.horizon_s)
    keys, params, state = _init_batch(compiled, 4, seed=9)
    block_fn, _ = build_block_step(compiled, horizon, MACRO, 4, interpret=True)
    k_state, l_state = state, state
    for block_index in range(2):
        U = jax.vmap(
            lambda k, _c=block_index: jax.random.uniform(
                jax.random.fold_in(k, _c),
                (MACRO, compiled.n_draws),
                minval=1e-12,
                maxval=1.0,
            )
        )(keys)
        k_state = block_fn(k_state, U, params)
        l_state = _lax_block(compiled, horizon, l_state, U, params)
    for name in sorted(l_state):
        np.testing.assert_array_equal(
            np.asarray(k_state[name]), np.asarray(l_state[name]), err_msg=name
        )


def test_padded_replicas_slice_back_exactly():
    """A non-tile-multiple replica count edge-pads, runs, and slices back
    to per-replica results identical to the unpadded lax block."""
    model = _mm1()
    compiled = _Compiled(model)
    horizon = float(model.horizon_s)
    n_replicas = 5  # tile 4 -> padded 8
    keys, params, state = _init_batch(compiled, n_replicas, seed=2)
    U = jax.vmap(
        lambda k: jax.random.uniform(
            jax.random.fold_in(k, 0),
            (MACRO, compiled.n_draws),
            minval=1e-12,
            maxval=1.0,
        )
    )(keys)
    block_fn, meta = build_block_step(
        compiled, horizon, MACRO, n_replicas, interpret=True
    )
    assert meta["tile"] == 4 and meta["padded_replicas"] == 8
    padded_state = pad_replicas(state, 8)
    padded_U = pad_replicas(U, 8)
    padded_params = pad_replicas(params, 8)
    out = block_fn(padded_state, padded_U, padded_params)
    sliced = {k: np.asarray(v)[:n_replicas] for k, v in out.items()}
    lax_out = _lax_block(compiled, horizon, state, U, params)
    for name in sorted(lax_out):
        np.testing.assert_array_equal(
            sliced[name], np.asarray(lax_out[name]), err_msg=name
        )


def test_block_kernel_rejects_unpadded_inputs():
    model = _mm1()
    compiled = _Compiled(model)
    keys, params, state = _init_batch(compiled, 5)
    U = jnp.zeros((5, MACRO, compiled.n_draws), jnp.float32)
    block_fn, _ = build_block_step(
        compiled, float(model.horizon_s), MACRO, 5, interpret=True
    )
    with pytest.raises(ValueError, match="padded"):
        block_fn(state, U, params)


class TestTiling:
    def test_replica_tile_bytes_sums_per_replica_leaves(self):
        leaves = [
            jnp.zeros((4, 8), jnp.float32),  # 128 B
            jnp.zeros((), jnp.int32),  # 4 B (scalar state leaf, e.g. "t")
            jnp.zeros((80,), jnp.int32),  # 320 B (one histogram row)
        ]
        assert replica_tile_bytes(leaves) == 128 + 4 + 320

    def test_choose_tile_power_of_two_within_budget(self):
        assert choose_tile(1024, 1000, budget=10_000) == 8
        assert choose_tile(1024, 1, budget=1 << 30) == 512  # MAX_TILE cap
        assert choose_tile(6, 1, budget=1 << 30) == 4
        assert choose_tile(1, 10**9, budget=1) == 1  # never below one

    def test_choose_tile_rejects_empty_ensembles(self):
        with pytest.raises(ValueError):
            choose_tile(0, 100)

    def test_padded_replica_count(self):
        assert padded_replica_count(8, 4) == 8
        assert padded_replica_count(9, 4) == 12
        assert padded_replica_count(1, 1) == 1

    def test_pad_replicas_edge_duplicates_last_row(self):
        tree = {"a": jnp.arange(6.0).reshape(3, 2), "b": jnp.arange(3)}
        padded = pad_replicas(tree, 5)
        assert padded["a"].shape == (5, 2)
        np.testing.assert_array_equal(np.asarray(padded["a"][3:]), [[4, 5], [4, 5]])
        np.testing.assert_array_equal(np.asarray(padded["b"][3:]), [2, 2])

    def test_pad_replicas_noop_when_aligned(self):
        tree = {"a": jnp.arange(4.0)}
        padded = pad_replicas(tree, 4)
        np.testing.assert_array_equal(np.asarray(padded["a"]), np.arange(4.0))


class TestVmemBudgetSizing:
    """PR-6 rider: the tile choice must account for the telemetry
    buffers, and a register file that cannot fit even one replica in the
    budget DECLINES (naming the budget) instead of silently spilling."""

    def test_working_set_grows_with_telemetry_windows(self):
        base = replica_working_set_bytes(_Compiled(_mm1()), MACRO)
        small = _mm1()
        small.telemetry(window_s=small.horizon_s / 4)
        big = _mm1()
        big.telemetry(window_s=big.horizon_s / 64)
        small_bytes = replica_working_set_bytes(_Compiled(small), MACRO)
        big_bytes = replica_working_set_bytes(_Compiled(big), MACRO)
        assert base < small_bytes < big_bytes
        # The latency histogram dominates: 64 windows x 80 bins x int32,
        # counted twice (aliased outputs occupy their own tile).
        assert big_bytes - base >= 2 * 64 * 80 * 4

    def test_tile_choice_pinned_at_the_budget_boundary(self):
        """The chosen tile is exactly choose_tile() of the
        telemetry-inclusive working set — pinned on both sides of a
        power-of-two budget boundary via an explicit budget."""
        model = _mm1()
        model.telemetry(window_s=model.horizon_s / 16)
        compiled = _Compiled(model)
        per_replica = replica_working_set_bytes(compiled, MACRO)
        # Budget exactly 8 working sets -> tile 8; one byte less -> 4.
        assert choose_tile(512, per_replica, budget=8 * per_replica) == 8
        assert choose_tile(512, per_replica, budget=8 * per_replica - 1) == 4
        # And build_block_step's default-budget tile matches the shared
        # sizing primitive (telemetry buffers included, not forgotten).
        _fn, meta = build_block_step(
            compiled, float(model.horizon_s), MACRO, 512, interpret=True
        )
        assert meta["bytes_per_replica"] == per_replica
        assert meta["tile"] == choose_tile(512, per_replica)

    def test_over_budget_telemetry_declines_naming_the_budget(self, monkeypatch):
        from happysim_tpu.tpu.kernels import event_step, kernel_decision
        from happysim_tpu.tpu.mesh import replica_mesh

        model = _mm1()
        model.telemetry(window_s=model.horizon_s / 64)
        compiled = _Compiled(model)
        per_replica = replica_working_set_bytes(compiled, 32)
        mesh = replica_mesh(jax.devices("cpu")[:1])
        monkeypatch.setenv("HS_TPU_PALLAS", "1")
        # Under the real budget this shape is accepted...
        use, note = kernel_decision(
            model, mesh=mesh, checkpointing=False, macro=32, compiled=compiled
        )
        assert use and note == ""
        # ...and with the budget pinched below one working set it
        # declines, naming the budget and the telemetry shape.
        monkeypatch.setattr(
            event_step, "VMEM_TILE_BUDGET_BYTES", per_replica - 1
        )
        use, note = kernel_decision(
            model, mesh=mesh, checkpointing=False, macro=32, compiled=compiled
        )
        assert not use
        assert "VMEM" in note and "budget" in note and "tile=1" in note
        assert "nW=64" in note  # the decline names the telemetry shape
        # ...and the offending leaves, biggest first, with their bytes
        # (the 64-window latency histogram dominates this shape).
        assert "largest state leaves" in note
        assert "tel_sink_hist" in note and "B" in note

    def test_profile_tables_count_as_tile_shared_consts(self):
        """ISSUE 17: a profiled source's inverse-integral lookup tables
        ride the tile as CONSTANTS (paid once per tile, not per
        replica). shared_const_bytes sizes them exactly — times + cum
        grids at f32 plus the two scalar anchors — and build_block_step
        subtracts them from the per-tile budget before choosing the
        tile."""
        from happysim_tpu.tpu.kernels.event_step import (
            VMEM_TILE_BUDGET_BYTES,
        )

        plain = _Compiled(_mm1())
        assert shared_const_bytes(plain) == 0

        model = _profiled_chain()
        compiled = _Compiled(model)
        n_grid = int(compiled.profile_times.shape[1])
        expected = 1 * (2 * n_grid * 4 + 16)
        assert shared_const_bytes(compiled) == expected
        assert expected == 4112  # 512-point grid, one profiled source

        per_replica = replica_working_set_bytes(compiled, MACRO)
        _fn, meta = build_block_step(
            compiled, float(model.horizon_s), MACRO, 512, interpret=True
        )
        assert meta["shared_const_bytes"] == expected
        assert meta["tile"] == choose_tile(
            512, per_replica, VMEM_TILE_BUDGET_BYTES - expected
        )

    def test_budget_pinch_decline_names_the_profile_tables(self, monkeypatch):
        """With the budget pinched between the bare working set and
        working set + tables, the tile=1 decline fires BECAUSE of the
        tile-shared consts — and the sizes list says so by name."""
        from happysim_tpu.tpu.kernels import event_step, kernel_decision
        from happysim_tpu.tpu.mesh import replica_mesh

        model = _profiled_chain()
        compiled = _Compiled(model)
        per_replica = replica_working_set_bytes(compiled, 32)
        shared = shared_const_bytes(compiled)
        assert shared > 0
        mesh = replica_mesh(jax.devices("cpu")[:1])
        monkeypatch.setenv("HS_TPU_PALLAS", "1")
        monkeypatch.setattr(
            event_step, "VMEM_TILE_BUDGET_BYTES", per_replica + shared - 1
        )
        use, note = kernel_decision(
            model, mesh=mesh, checkpointing=False, macro=32, compiled=compiled
        )
        assert not use
        assert "tile=1" in note and "tile-shared consts" in note
        assert "profile tables [tile-shared]" in note
        # One byte more and the shape fits again at tile=1.
        monkeypatch.setattr(
            event_step, "VMEM_TILE_BUDGET_BYTES", per_replica + shared
        )
        use, note = kernel_decision(
            model, mesh=mesh, checkpointing=False, macro=32, compiled=compiled
        )
        assert use and note == ""


class TestDeclinePredicate:
    def test_mm1_and_chain_are_supported(self):
        plan, reason = kernel_plan(_mm1())
        assert plan == {"shape": "mm1", "servers": [0], "chaos": ()}
        assert reason == ""
        plan, reason = kernel_plan(_chain_with_transit())
        assert plan == {"shape": "chain", "servers": [0, 1], "chaos": ()}
        assert reason == ""

    def test_deadline_retry_chain_is_supported(self):
        model = EnsembleModel(horizon_s=5.0)
        src = model.source(rate=4.0)
        srv = model.server(service_mean=0.1, deadline_s=2.0, max_retries=1)
        snk = model.sink()
        model.connect(src, srv)
        model.connect(srv, snk)
        plan, _ = kernel_plan(model)
        assert plan is not None

    @pytest.mark.parametrize(
        "mutate, fragment",
        [
            (lambda m: m.router(targets=[]), "router"),
            # An orphan limiter (never wired into the source->sink
            # path) still declines — WIRED limiters are approved.
            (
                lambda m: m.limiter(refill_rate=5.0, capacity=5.0),
                "limiter[0] is outside",
            ),
            (lambda m: m.sink(), "sinks"),
            (
                lambda m: m.source(rate=1.0),
                "sources",
            ),
        ],
    )
    def test_declines_unsupported_features(self, mutate, fragment):
        model = _mm1()
        mutate(model)
        plan, reason = kernel_plan(model)
        assert plan is None
        assert fragment in reason
        # Every decline names the engine path that ran and its flag.
        assert "HS_TPU_PALLAS" in reason and "lax" in reason

    def test_decline_collects_every_reason(self):
        """ISSUE 14 satellite: the decline surfaces the FULL reason
        list (``; ``-joined, first reason first), so a user fixes the
        model in one pass instead of replaying whack-a-mole. (The old
        three-reason fixture — adaptive policy + rate profile + second
        sink — lost two reasons to ISSUE 17's graph planner, so the
        independent reasons here are a consensus feature, the sink
        count, and an orphan limiter.)"""
        from happysim_tpu.tpu.model import SERVER, NodeRef

        from happysim_tpu.tpu.model import SINK

        model = _router_fanout("least_outstanding")
        model.network_partition(
            group=[NodeRef(SERVER, 0)], windows=((0.5, 1.0),)
        )
        model.sink()  # second sink: an independent reason
        # A limiter wired to a sink but never fed: outside the walk.
        orphan = model.limiter(refill_rate=5.0, capacity=5.0)
        model.connect(orphan, NodeRef(SINK, 0))
        plan, reason = kernel_plan(model)
        assert plan is None
        inner = reason.split("(", 1)[1].rsplit(");", 1)[0]
        parts = inner.split("; ")
        assert len(parts) == 3, parts
        # Feature reasons lead, then structural counts, then the walk's
        # membership checks — the joined order is stable for pinning.
        assert "network partitions" in parts[0]
        assert "sinks" in parts[1]
        assert "limiter[0] is outside" in parts[2]
        # The flag note appears ONCE, after the joined list.
        assert reason.count("HS_TPU_PALLAS") == 2  # =1 forces / =0 silences

    def test_telemetry_and_faulted_chains_are_supported(self):
        """The two PR-6 removals: "model has windowed telemetry" and
        "has a stochastic fault schedule" are no longer decline reasons
        — the buffers ride the VMEM tile instead."""
        telemetry_model = _mm1()
        telemetry_model.telemetry(window_s=1.0)
        plan, reason = kernel_plan(telemetry_model)
        assert plan is not None and reason == ""

        plan, reason = kernel_plan(_faulted_telemetry_chain())
        assert plan == {
            "shape": "chain",
            "servers": [0, 1],
            "chaos": ("faults", "telemetry"),
        }
        assert reason == ""

    def test_resilient_chaos_servers_are_supported(self):
        """ISSUE 14: the resilience semantics (backoff retries, hedging,
        correlated outages, brownouts) no longer decline — their state
        (transit retry registers, hedge race slots, trigger draws) rides
        the VMEM tile and their RNG slots live in the shared uniform
        chunk, so the traced step closure fuses them like any other
        per-lane work."""
        model = EnsembleModel(horizon_s=5.0)
        src = model.source(rate=4.0)
        srv = model.server(
            service_mean=0.1,
            fault=FaultSpec(rate=0.05, mean_duration_s=0.5, correlated=True),
            retry_backoff_s=0.1,
            max_retries=2,
            hedge_delay_s=0.3,
            outage=(1.0, 2.0),
        )
        model.correlated_outages(rate=0.1, mean_duration_s=1.0)
        snk = model.sink()
        model.connect(src, srv)
        model.connect(srv, snk)
        plan, reason = kernel_plan(model)
        assert reason == ""
        assert plan["shape"] == "mm1"
        assert plan["chaos"] == (
            "faults",
            "correlated_outages",
            "backoff_retries",
            "hedging",
            "brownouts",
        )

    def test_wired_limiter_and_packet_loss_are_supported(self):
        """ISSUE 14: token-bucket limiters on the source->sink path are
        pass-through hops in the topology walk, and lossy edges spend
        their Bernoulli from the shared uniform chunk — both approved."""
        model = EnsembleModel(horizon_s=5.0)
        src = model.source(rate=4.0)
        lim = model.limiter(refill_rate=5.0, capacity=5.0)
        srv = model.server(service_mean=0.1)
        snk = model.sink()
        model.connect(src, lim)
        model.connect(lim, srv, loss_p=0.1)
        model.connect(srv, snk)
        plan, reason = kernel_plan(model)
        assert reason == ""
        assert plan == {
            "shape": "mm1",
            "servers": [0],
            "chaos": ("packet_loss", "limiters"),
        }

    def test_resilience_layer_is_supported(self):
        """ISSUE 15: the defense layer (breaker, shed, budget) adds NO
        kernel_plan declines — its state columns and the shed priority
        Bernoulli are per-lane machinery inside the traced closure, so
        declines stay purely topological. The plan's chaos descriptor
        records the resilience names (engine_report provenance)."""
        model = _resilience_fanout()
        plan, reason = kernel_plan(model)
        assert reason == ""
        assert plan["shape"] == "router"
        assert set(
            ("circuit_breaker", "load_shed", "retry_budget")
        ) <= set(plan["chaos"])

    def test_resilience_on_unfused_shapes_collects_topology_reasons(self):
        """A resilience-laden model on a declined SHAPE surfaces every
        remaining reason via the PR-14 "; "-join — and no resilience
        feature is ever named as a decline (there are none)."""
        from happysim_tpu.tpu.model import SINK, NodeRef

        model = _router_fanout("least_outstanding")
        model.sink()  # second sink: a topological decline
        # A limiter wired to a sink but never fed: outside the walk.
        orphan = model.limiter(refill_rate=5.0, capacity=5.0)
        model.connect(orphan, NodeRef(SINK, 0))
        for index in range(4):
            model.servers[index].deadline_s = 0.3
            model.servers[index].max_retries = 1
        model.circuit_breaker()
        model.load_shed(policy="queue_depth", threshold=2)
        model.retry_budget(ratio=0.2)
        model.validate()
        plan, reason = kernel_plan(model)
        assert plan is None
        assert "sinks" in reason and "limiter[0] is outside" in reason
        assert reason.index("sinks") < reason.index("limiter[0]")
        for feature in ("circuit_breaker", "load_shed", "retry_budget"):
            assert feature not in reason

    def test_breaker_ring_counts_toward_the_vmem_budget(self, monkeypatch):
        """The tile=1 budget decline names the new state leaves: a
        pathological failure_threshold makes the (nV, F) failure-time
        ring dominate the working set, and kernel_decision's decline
        must name ``brk_fail_t`` so the user knows which knob to
        shrink."""
        from happysim_tpu.tpu.engine import _Compiled as Compiled
        from happysim_tpu.tpu.kernels import kernel_decision
        from happysim_tpu.tpu.mesh import replica_mesh

        monkeypatch.setenv("HS_TPU_PALLAS", "1")
        model = _mm1()
        model.servers[0].deadline_s = 0.5
        # 2^20 ring slots x 4 B x 2 (aliased in+out tiles) > 4 MiB alone.
        model.circuit_breaker(failure_threshold=1 << 20, window_s=1.0)
        use, note = kernel_decision(
            model,
            mesh=replica_mesh(jax.devices("cpu")[:1]),
            checkpointing=False,
            macro=2,
            compiled=Compiled(model),
        )
        assert not use
        assert "brk_fail_t" in note
        assert "tile=1" in note

    def test_profiled_sources_are_supported(self):
        """ISSUE 17: "source has a rate profile" is no longer a decline
        — ramp/spike profiles compile to inverse-integral lookup tables
        riding the tile as shared constants, so the profiled M/M/1 is
        approved as an ordinary mm1 plan."""
        ramped = EnsembleModel(horizon_s=5.0)
        src = ramped.ramp_source(1.0, 5.0, 2.0)
        snk = ramped.sink()
        srv = ramped.server(service_mean=0.1)
        ramped.connect(src, srv)
        ramped.connect(srv, snk)
        plan, reason = kernel_plan(ramped)
        assert reason == ""
        assert plan == {"shape": "mm1", "servers": [0], "chaos": ()}

        spiked = EnsembleModel(horizon_s=5.0)
        src = spiked.spike_source(
            base_rate=2.0, spike_rate=8.0, spike_start_s=1.0, spike_end_s=2.0
        )
        snk = spiked.sink()
        srv = spiked.server(service_mean=0.1)
        spiked.connect(src, srv)
        spiked.connect(srv, snk)
        plan, reason = kernel_plan(spiked)
        assert reason == "" and plan["shape"] == "mm1"

    def test_model_kernel_supported_mirror(self):
        ok, reason = _mm1().kernel_supported()
        assert ok and reason == ""
        model = _mm1()
        model.limiter(refill_rate=1.0, capacity=2.0)  # orphan: unwired
        ok, reason = model.kernel_supported()
        assert not ok and "HS_TPU_PALLAS" in reason


class TestRouterPlan:
    """ISSUE 11 removed the blanket "model has routers" decline; ISSUE
    17's topology walk approves EVERY router policy and any
    source->{routers, limiters, servers}->sink graph. The classic pure
    fan-out keeps its pinned "router" plan dict; richer graphs classify
    as "graph"; the remaining declines are membership checks that name
    the node left outside the walk."""

    @pytest.mark.parametrize(
        "build, policy, chaos",
        [
            (_router_random, "random", ()),
            (_router_round_robin, "round_robin", ()),
            (_router_weighted, "weighted", ()),
            (
                _router_faulted_telemetry,
                "round_robin",
                ("faults", "telemetry"),
            ),
            (
                _chaos_fanout,
                "round_robin",
                (
                    "faults",
                    "correlated_outages",
                    "backoff_retries",
                    "hedging",
                    "brownouts",
                    "packet_loss",
                    "limiters",
                    "telemetry",
                ),
            ),
        ],
    )
    def test_fanout_shapes_are_supported(self, build, policy, chaos):
        plan, reason = kernel_plan(build())
        assert reason == ""
        assert plan == {
            "shape": "router",
            "servers": [0, 1, 2, 3],
            "policy": policy,
            "chaos": chaos,
        }

    def test_adaptive_policy_is_supported(self):
        """ISSUE 17: least_outstanding no longer declines — the pure
        fan-out keeps the pinned "router" plan dict under the adaptive
        policy too (the outstanding gather is per-lane machinery inside
        the traced closure)."""
        plan, reason = kernel_plan(_router_fanout("least_outstanding"))
        assert reason == ""
        assert plan == {
            "shape": "router",
            "servers": [0, 1, 2, 3],
            "policy": "least_outstanding",
            "chaos": (),
        }

    def test_multi_router_graphs_are_supported(self):
        """ISSUE 17: ">1 router" is no longer a decline — the 2-router
        shared-backend DAG and the DIRECT router->router tier both plan
        as shape "graph" with BFS-ordered provenance."""
        plan, reason = kernel_plan(_graph_shared_backend())
        assert reason == ""
        assert plan["shape"] == "graph"
        assert plan["servers"] == [0, 1, 2, 3]
        assert plan["routers"] == [0, 1]
        assert plan["policies"] == (
            "least_outstanding",
            "least_outstanding",
        )

        plan, reason = kernel_plan(_graph_router_tier())
        assert reason == ""
        assert plan["shape"] == "graph"
        assert plan["routers"] == [0, 1]
        assert plan["policies"] == ("random", "weighted")

    def test_orphan_router_declines_naming_the_router(self):
        # A router the walk never reaches is a membership decline that
        # names the router index (the old blanket "2 routers" and
        # "router is not fed by the source" reasons are gone).
        model = _router_fanout("random")
        model.router(policy="random", targets=[])
        plan, reason = kernel_plan(model)
        assert plan is None
        assert "router[1] is outside the source->sink graph" in reason

        model = _mm1()
        model.router(targets=[])
        plan, reason = kernel_plan(model)
        assert plan is None
        assert "router[0] is outside the source->sink graph" in reason

    def test_mixed_sink_server_targets_supported_as_graph(self):
        """ISSUE 17: probabilistic server/sink exits ("done or
        continue") are approved — the mixed-target fan-out classifies
        as "graph", not "router" (the pure fan-out dict stays pinned
        to all-server targets)."""
        model = EnsembleModel(horizon_s=2.0)
        src = model.source(rate=4.0)
        srv = model.server(service_mean=0.05, queue_capacity=8)
        router = model.router(policy="random")
        snk = model.sink()
        model.connect(src, router)
        model.connect(router, srv)
        model.connect(router, snk)
        model.connect(srv, snk)
        plan, reason = kernel_plan(model)
        assert reason == ""
        assert plan["shape"] == "graph"
        assert plan["servers"] == [0] and plan["routers"] == [0]

    def test_chain_behind_fanout_supported_as_graph(self):
        from happysim_tpu.tpu.model import NodeRef

        # Rewire target server[0] -> tail server -> sink.
        model = _router_fanout("random", n_servers=2)
        tail = model.server(service_mean=0.05, queue_capacity=8)
        model.servers[0].downstream = tail
        model.connect(tail, NodeRef("sink", 0))
        plan, reason = kernel_plan(model)
        assert reason == ""
        assert plan["shape"] == "graph"
        # BFS order: the fan-out tier first, then the chained tail.
        assert plan["servers"] == [0, 1, 2]

    def test_server_feedback_into_router_supported_as_graph(self):
        """Server-mediated feedback (a fan-out server routing BACK to
        the router) is approved: the server arrival ends each delivery,
        so the traced closure stays finite — only DIRECT router->router
        cycles are degenerate, and model.validate() rejects those."""
        from happysim_tpu.tpu.model import NodeRef

        model = _router_fanout("random", n_servers=2)
        model.servers[1].downstream = NodeRef("router", 0)
        plan, reason = kernel_plan(model)
        assert reason == ""
        assert plan["shape"] == "graph"

    def test_servers_outside_graph_decline_by_name(self):
        from happysim_tpu.tpu.model import NodeRef

        model = _router_fanout("random", n_servers=2)
        extra = model.server(service_mean=0.05, queue_capacity=8)
        model.connect(extra, NodeRef("sink", 0))
        del extra
        plan, reason = kernel_plan(model)
        assert plan is None
        assert "servers outside the source->sink graph: server[2]" in reason

    def test_repeated_target_supported_as_graph(self):
        """A repeated fan-out target (a weighted-by-repetition random
        router) is approved but is NOT the pure fan-out shape, so it
        classifies as "graph"."""
        from happysim_tpu.tpu.model import NodeRef

        model = _router_fanout("random", n_servers=2)
        model.routers[0].targets.append(NodeRef("server", 0))
        model.routers[0].target_latencies.append(
            model.routers[0].target_latencies[0]
        )
        plan, reason = kernel_plan(model)
        assert reason == ""
        assert plan["shape"] == "graph"
        assert plan["servers"] == [0, 1]

    def test_no_path_to_sink_declines(self):
        """A graph whose every branch dead-ends (dangling downstream)
        declines with the no-path reason instead of a phantom
        membership list."""
        model = EnsembleModel(horizon_s=2.0)
        src = model.source(rate=4.0)
        srv = model.server(service_mean=0.05, queue_capacity=8)
        model.sink()
        model.connect(src, srv)
        # srv.downstream stays None: no branch reaches the sink.
        plan, reason = kernel_plan(model)
        assert plan is None
        assert "no path from the source reaches the sink" in reason

    def test_lossy_target_edge_is_supported(self):
        """ISSUE 14: per-target packet loss no longer declines — the
        loss Bernoulli is an ordinary slot in the shared uniform chunk."""
        model = _router_fanout("random")
        edge = model.routers[0].target_latencies[0]
        model.routers[0].target_latencies[0] = type(edge)(
            mean_s=edge.mean_s, kind=edge.kind, loss_p=0.1
        )
        plan, reason = kernel_plan(model)
        assert reason == ""
        assert plan["chaos"] == ("packet_loss",)

    def test_limiter_fed_router_is_supported(self):
        """source -> limiter -> router fan-out: admission is a
        pass-through hop in the topology walk."""
        from happysim_tpu.tpu.model import NodeRef

        model = _router_fanout("random")
        lim = model.limiter(refill_rate=8.0, capacity=4.0)
        model.sources[0].downstream = lim
        model.connect(lim, NodeRef("router", 0))
        plan, reason = kernel_plan(model)
        assert reason == ""
        assert plan["shape"] == "router"
        assert plan["chaos"] == ("limiters",)


class TestKernelDecision:
    def _mesh(self, n=1):
        import jax

        from happysim_tpu.tpu.mesh import replica_mesh

        return replica_mesh(jax.devices("cpu")[:n])

    def test_env_off(self, monkeypatch):
        from happysim_tpu.tpu.kernels import kernel_decision

        monkeypatch.setenv("HS_TPU_PALLAS", "0")
        use, note = kernel_decision(
            _mm1(), mesh=self._mesh(), checkpointing=False, macro=32
        )
        assert not use and "HS_TPU_PALLAS=0" in note

    def test_forced_on_cpu_uses_interpret(self, monkeypatch):
        from happysim_tpu.tpu.kernels import kernel_decision

        monkeypatch.setenv("HS_TPU_PALLAS", "1")
        use, note = kernel_decision(
            _mm1(), mesh=self._mesh(), checkpointing=False, macro=32
        )
        assert use and note == ""

    def test_auto_declines_off_tpu(self, monkeypatch):
        from happysim_tpu.tpu.kernels import kernel_decision

        monkeypatch.delenv("HS_TPU_PALLAS", raising=False)
        use, note = kernel_decision(
            _mm1(), mesh=self._mesh(), checkpointing=False, macro=32
        )
        assert not use and "auto-engages on TPU" in note

    def test_checkpointing_declines(self, monkeypatch):
        from happysim_tpu.tpu.kernels import kernel_decision

        monkeypatch.setenv("HS_TPU_PALLAS", "1")
        use, note = kernel_decision(
            _mm1(), mesh=self._mesh(), checkpointing=True, macro=32
        )
        assert not use and "checkpoint" in note

    def test_multi_device_replica_mesh_is_approved(self, monkeypatch):
        """Mesh-first (ISSUE 13): a 1-D multi-device replica mesh no
        longer declines — the engine shard_maps the kernel with a
        per-shard tile plan, so single-chip is just mesh.size == 1."""
        from happysim_tpu.tpu.kernels import kernel_decision

        monkeypatch.setenv("HS_TPU_PALLAS", "1")
        use, note = kernel_decision(
            _mm1(), mesh=self._mesh(8), checkpointing=False, macro=32
        )
        assert use and note == ""

    def test_host_replica_mesh_still_declines(self, monkeypatch):
        """The 2-D hosts/replicas layout is the one mesh shape the
        kernel does not claim; the decline names the 1-D mesh-first
        path instead of the old single-device-only advice."""
        import jax

        from happysim_tpu.tpu.kernels import kernel_decision
        from happysim_tpu.tpu.mesh import host_replica_mesh

        monkeypatch.setenv("HS_TPU_PALLAS", "1")
        mesh = host_replica_mesh(jax.devices("cpu")[:8], n_hosts=2)
        use, note = kernel_decision(
            _mm1(), mesh=mesh, checkpointing=False, macro=32
        )
        assert not use
        assert "hosts/replicas" in note and "1-D" in note
        assert "replica_mesh" in note

    def test_oversized_macro_block_declines(self, monkeypatch):
        from happysim_tpu.tpu.kernels import kernel_decision

        monkeypatch.setenv("HS_TPU_PALLAS", "1")
        use, note = kernel_decision(
            _mm1(), mesh=self._mesh(), checkpointing=False, macro=1024
        )
        assert not use and "macro_block" in note
