"""Depth tests for partitioned-run internals: router classification,
partition validation, links, aggregate summaries (ref parallel/routing.py:40,
parallel/validation.py:19-180, parallel/link.py:19, parallel/summary.py)."""

import pytest

from happysim_tpu import Duration, Instant
from happysim_tpu.core.entity import Entity
from happysim_tpu.core.event import Event
from happysim_tpu.instrumentation.summary import SimulationSummary
from happysim_tpu.parallel.link import PartitionLink
from happysim_tpu.parallel.partition import SimulationPartition
from happysim_tpu.parallel.routing import RoutingError, make_router
from happysim_tpu.parallel.summary import ParallelSimulationSummary
from happysim_tpu.parallel.validation import (
    PartitionValidationError,
    validate_partitions,
)


class _Node(Entity):
    def __init__(self, name):
        super().__init__(name)

    def handle_event(self, event):
        return None


def _ev(target):
    return Event(Instant.from_seconds(1), "X", target=target)


class TestRouter:
    def _setup(self):
        a_ent, b_ent = _Node("a"), _Node("b")
        part_a = SimulationPartition("A", entities=[a_ent])
        mapping = {id(a_ent): "A", id(b_ent): "B"}
        outbox = []
        return a_ent, b_ent, part_a, mapping, outbox

    def test_local_events_pass_through(self):
        a_ent, b_ent, part_a, mapping, outbox = self._setup()
        route = make_router(part_a, mapping, links_from={"B"}, outbox=outbox)
        ev = _ev(a_ent)
        assert route([ev]) == [ev]
        assert outbox == []

    def test_linked_cross_partition_goes_to_outbox(self):
        a_ent, b_ent, part_a, mapping, outbox = self._setup()
        route = make_router(part_a, mapping, links_from={"B"}, outbox=outbox)
        ev = _ev(b_ent)
        assert route([ev]) == []
        assert outbox == [ev]

    def test_unlinked_cross_partition_raises(self):
        a_ent, b_ent, part_a, mapping, outbox = self._setup()
        route = make_router(part_a, mapping, links_from=set(), outbox=outbox)
        with pytest.raises(RoutingError, match="no PartitionLink"):
            route([_ev(b_ent)])

    def test_unowned_target_treated_as_local(self):
        # Shared infrastructure (e.g. Event.once function targets) is not in
        # the ownership map and must stay on the producing partition.
        a_ent, b_ent, part_a, mapping, outbox = self._setup()
        route = make_router(part_a, {}, links_from=set(), outbox=outbox)
        ev = _ev(a_ent)
        assert route([ev]) == [ev]

    def test_mixed_batch_splits(self):
        a_ent, b_ent, part_a, mapping, outbox = self._setup()
        route = make_router(part_a, mapping, links_from={"B"}, outbox=outbox)
        local, remote = _ev(a_ent), _ev(b_ent)
        assert route([local, remote, local]) == [local, local]
        assert outbox == [remote]


class TestPartitionValidation:
    def test_duplicate_partition_names(self):
        with pytest.raises(PartitionValidationError, match="Duplicate partition names"):
            validate_partitions(
                [SimulationPartition("A"), SimulationPartition("A")], []
            )

    def test_entity_in_two_partitions(self):
        shared = _Node("shared")
        with pytest.raises(PartitionValidationError, match="appears in both"):
            validate_partitions(
                [
                    SimulationPartition("A", entities=[shared]),
                    SimulationPartition("B", entities=[shared]),
                ],
                [],
            )

    def test_link_to_unknown_partition(self):
        link = PartitionLink("A", "C", min_latency=Duration.from_seconds(0.1))
        with pytest.raises(PartitionValidationError, match="unknown partition"):
            validate_partitions([SimulationPartition("A")], [link])

    def test_duplicate_link_rejected(self):
        links = [
            PartitionLink("A", "B", min_latency=Duration.from_seconds(0.1)),
            PartitionLink("A", "B", min_latency=Duration.from_seconds(0.2)),
        ]
        with pytest.raises(PartitionValidationError, match="Duplicate link"):
            validate_partitions(
                [SimulationPartition("A"), SimulationPartition("B")], links
            )

    def test_cross_reference_without_link_rejected(self):
        a_ent, b_ent = _Node("a"), _Node("b")
        a_ent.peer = b_ent  # direct attribute reference crossing partitions
        with pytest.raises(PartitionValidationError):
            validate_partitions(
                [
                    SimulationPartition("A", entities=[a_ent]),
                    SimulationPartition("B", entities=[b_ent]),
                ],
                [],
            )

    def test_cross_reference_with_link_allowed(self):
        a_ent, b_ent = _Node("a"), _Node("b")
        a_ent.peer = b_ent
        validate_partitions(
            [
                SimulationPartition("A", entities=[a_ent]),
                SimulationPartition("B", entities=[b_ent]),
            ],
            [PartitionLink("A", "B", min_latency=Duration.from_seconds(0.1))],
        )

    def test_owns(self):
        e = _Node("e")
        p = SimulationPartition("P", entities=[e])
        assert p.owns(e)
        assert not p.owns(_Node("other"))


class TestPartitionLink:
    def test_zero_latency_rejected(self):
        with pytest.raises(ValueError, match="min_latency must be > 0"):
            PartitionLink("A", "B", min_latency=Duration.from_seconds(0.0))

    def test_bad_packet_loss_rejected(self):
        with pytest.raises(ValueError, match="packet_loss"):
            PartitionLink(
                "A", "B", min_latency=Duration.from_seconds(0.1), packet_loss=1.0
            )

    def test_float_latency_coerced(self):
        link = PartitionLink("A", "B", min_latency=0.25)
        assert link.min_latency == Duration.from_seconds(0.25)


class TestParallelSummary:
    def _inner(self, events=100):
        return SimulationSummary(
            start_time=Instant.Epoch,
            end_time=Instant.from_seconds(10),
            events_processed=events,
            wall_clock_seconds=0.5,
        )

    def test_events_per_second(self):
        s = ParallelSimulationSummary(
            partition_summaries={"A": self._inner()},
            total_events=100,
            wall_seconds=2.0,
        )
        assert s.events_per_second == 50.0

    def test_zero_wall_guard(self):
        s = ParallelSimulationSummary(
            partition_summaries={}, total_events=10, wall_seconds=0.0
        )
        assert s.events_per_second == 0.0

    def test_to_dict_round_trip(self):
        s = ParallelSimulationSummary(
            partition_summaries={"A": self._inner(40), "B": self._inner(60)},
            total_events=100,
            wall_seconds=1.0,
            total_windows=7,
            cross_partition_events=12,
            speedup=1.8,
        )
        d = s.to_dict()
        assert d["total_events"] == 100
        assert d["total_windows"] == 7
        assert d["cross_partition_events"] == 12
        assert set(d["partitions"]) == {"A", "B"}
        assert d["partitions"]["A"]["events_processed"] == 40
