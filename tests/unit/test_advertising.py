"""Unit tests: advertising economics (tiers, advertiser, platform, AAA effect)."""

import pytest

from happysim_tpu import (
    AdPlatform,
    Advertiser,
    AudienceTier,
    Event,
    Instant,
    Simulation,
)

NICHE = AudienceTier("Niche", base_monthly_sales=100, base_cpa=10.0)
BROAD = AudienceTier("Broad", base_monthly_sales=1000, base_cpa=40.0)


def build(sentiment_events=(), end_s=5.5, interval=1.0):
    platform = AdPlatform("Meta")
    advertiser = Advertiser(
        "PosterShop",
        product_price=100.0,
        production_cost=50.0,
        tiers=[NICHE, BROAD],
        platform=platform,
        evaluation_interval_s=interval,
    )
    sim = Simulation(entities=[platform, advertiser], end_time=Instant.from_seconds(end_s))
    sim.schedule(advertiser.start_events())
    for t, sentiment in sentiment_events:
        sim.schedule(
            Event(
                Instant.from_seconds(t),
                "SentimentChange",
                target=advertiser,
                context={"metadata": {"sentiment": sentiment}},
            )
        )
    sim.run()
    return advertiser, platform


class TestAudienceTier:
    def test_economics_at_full_sentiment(self):
        assert BROAD.monthly_ad_spend == 40_000
        assert BROAD.effective_cpa(1.0) == 40.0
        assert BROAD.monthly_sales(0.5) == 500.0

    def test_cpa_rises_as_sentiment_falls(self):
        assert BROAD.effective_cpa(0.5) == 80.0
        assert BROAD.effective_cpa(0.0) == float("inf")

    def test_breakeven_ordering(self):
        # Broad (outer ring) breaks even at higher sentiment than niche.
        margin = 50.0
        assert BROAD.breakeven_sentiment(margin) > NICHE.breakeven_sentiment(margin)
        assert BROAD.breakeven_sentiment(margin) == pytest.approx(0.8)
        assert NICHE.breakeven_sentiment(margin) == pytest.approx(0.2)

    def test_profit_zero_when_unprofitable(self):
        assert BROAD.tier_profit(0.5, 50.0) == 0.0
        assert BROAD.tier_platform_revenue(0.5, 50.0) == 0.0
        assert BROAD.tier_profit(1.0, 50.0) == pytest.approx(1000 * (50 - 40))


class TestAdvertiser:
    def test_steady_state_all_tiers_active(self):
        advertiser, platform = build()
        assert advertiser.periods_evaluated == 5
        assert len(advertiser.active_tiers) == 2
        assert advertiser.tier_shutoff_events == 0
        # Platform collects both tiers' spend each period.
        expected = 5 * (NICHE.monthly_ad_spend + BROAD.monthly_ad_spend)
        assert platform.total_revenue == pytest.approx(expected)

    def test_aaa_effect_broad_tier_shuts_off_first(self):
        """A modest sentiment drop (1.0 -> 0.7) kills the broad tier only,
        costing the platform most of its revenue — the AAA effect."""
        advertiser, platform = build(sentiment_events=[(2.5, 0.7)])
        assert advertiser.tier_shutoff_events == 1
        assert [t.name for t in advertiser.active_tiers] == ["Niche"]
        # Periods 1-2 at full revenue, 3-5 niche-only.
        full = NICHE.monthly_ad_spend + BROAD.monthly_ad_spend
        expected = 2 * full + 3 * NICHE.monthly_ad_spend
        assert platform.total_revenue == pytest.approx(expected)
        # Revenue drop (-49k of 50k/period) far exceeds the 30% sentiment drop.
        assert NICHE.monthly_ad_spend / full < 0.05

    def test_sentiment_clamped(self):
        advertiser, _ = build(sentiment_events=[(0.5, 5.0)])
        assert advertiser.sentiment == 1.0
        advertiser.sentiment = -3.0
        assert advertiser.sentiment == 0.0

    def test_time_series_recorded(self):
        advertiser, platform = build()
        assert advertiser.profit_data.count() == 5
        assert advertiser.sentiment_data.mean() == pytest.approx(1.0)
        assert platform.revenue_data.count() == 5

    def test_sensitivity_analysis_monotone_tiers(self):
        advertiser, _ = build(end_s=0.5)  # no evaluations needed
        rows = advertiser.sensitivity_analysis(steps=10)
        assert rows[0]["active_tiers"] == 0  # sentiment 0
        assert rows[-1]["active_tiers"] == 2  # sentiment 1
        active_counts = [r["active_tiers"] for r in rows]
        assert active_counts == sorted(active_counts)

    def test_stats_snapshot(self):
        advertiser, platform = build()
        stats = advertiser.stats()
        assert stats.periods_evaluated == 5
        assert stats.total_profit > 0
        assert platform.stats().revenue_events == 5
