"""Network-layer edges: every condition preset, partition semantics,
link impairment math, and clone determinism.

Parity target: the per-preset and partition cases of
``happysimulator/tests/unit/test_network.py`` /
``test_network_conditions.py``.
"""

from __future__ import annotations

import pytest

from happysim_tpu import Instant, Simulation, Sink
from happysim_tpu.components.network import (
    Network,
    NetworkLink,
    conditions,
)
from happysim_tpu.distributions.latency_distribution import ConstantLatency

PRESETS = {
    "local": (conditions.local_network, 0.0001, 0.0),
    "datacenter": (conditions.datacenter_network, 0.0005, 0.0),
    "cross_region": (conditions.cross_region_network, None, None),
    "internet": (conditions.internet_network, None, None),
    "satellite": (conditions.satellite_network, None, None),
    "mobile_3g": (conditions.mobile_3g_network, None, None),
    "mobile_4g": (conditions.mobile_4g_network, None, None),
}


@pytest.mark.parametrize("preset", sorted(PRESETS), ids=sorted(PRESETS))
class TestConditionPresets:
    def test_constructs_a_named_seeded_link(self, preset):
        factory, _, _ = PRESETS[preset]
        link = factory(seed=7)
        assert isinstance(link, NetworkLink)
        assert link.name
        assert 0.0 <= link.packet_loss_rate < 0.5

    def test_delivers_through_a_simulation(self, preset):
        factory, _, _ = PRESETS[preset]
        sink = Sink("sink")
        link = factory(seed=3)
        link.egress = sink
        sim = Simulation(entities=[link, sink], end_time=Instant.from_seconds(120.0))
        from happysim_tpu.core.event import Event

        for i in range(50):
            sim.schedule(Event(Instant.from_seconds(i * 0.5), "pkt", target=link))
        sim.run()
        delivered = sink.events_received
        assert delivered == 50 - link.packets_dropped
        if link.packet_loss_rate == 0.0:
            assert delivered == 50
        # Latency floor: nothing arrives faster than the base latency.
        if delivered:
            base = link.latency.get_latency(Instant.Epoch).to_seconds()
            assert min(sink.latencies_s) >= base * 0.5


class TestPresetOrdering:
    def test_latency_ladder_is_sane(self):
        """The presets' base latencies must preserve the physical
        ordering: local < datacenter < cross_region < satellite."""

        def base(factory):
            return factory(seed=1).latency.get_latency(Instant.Epoch).to_seconds()

        assert (
            base(conditions.local_network)
            < base(conditions.datacenter_network)
            < base(conditions.cross_region_network)
            < base(conditions.satellite_network)
        )

    def test_lossy_and_slow_wrappers(self):
        lossy = conditions.lossy_network(loss_rate=0.3, seed=1)
        assert lossy.packet_loss_rate == pytest.approx(0.3)
        slow = conditions.slow_network(latency_seconds=0.5, seed=1)
        assert slow.latency.get_latency(Instant.Epoch).to_seconds() >= 0.25


class TestLinkMath:
    def test_bandwidth_adds_serialization_delay(self):
        sink = Sink("sink")
        link = NetworkLink(
            "thin", latency=ConstantLatency(0.01), bandwidth_bps=8_000, egress=sink
        )
        sim = Simulation(entities=[link, sink], end_time=Instant.from_seconds(10.0))
        from happysim_tpu.core.event import Event

        event = Event(
            Instant.Epoch, "pkt", target=link,
            context={"metadata": {"payload_size": 1000}},  # 8000 bits / 8000 bps = 1s
        )
        sim.schedule(event)
        sim.run()
        assert sink.latencies_s[0] == pytest.approx(1.01, abs=1e-6)

    def test_zero_size_payload_pays_latency_only(self):
        sink = Sink("sink")
        link = NetworkLink(
            "fat", latency=ConstantLatency(0.02), bandwidth_bps=1e9, egress=sink
        )
        sim = Simulation(entities=[link, sink], end_time=Instant.from_seconds(1.0))
        from happysim_tpu.core.event import Event

        sim.schedule(Event(Instant.Epoch, "pkt", target=link))
        sim.run()
        assert sink.latencies_s[0] == pytest.approx(0.02, abs=1e-9)

    def test_loss_rate_statistics(self):
        sink = Sink("sink")
        link = NetworkLink(
            "lossy", latency=ConstantLatency(0.001), packet_loss_rate=0.25,
            egress=sink, seed=11,
        )
        sim = Simulation(entities=[link, sink], end_time=Instant.from_seconds(100.0))
        from happysim_tpu.core.event import Event

        for i in range(1000):
            sim.schedule(Event(Instant.from_seconds(i * 0.01), "pkt", target=link))
        sim.run()
        assert link.packets_dropped == pytest.approx(250, abs=50)
        assert sink.events_received == 1000 - link.packets_dropped

    def test_clone_derives_deterministic_seed(self):
        parent = NetworkLink(
            "parent", latency=ConstantLatency(0.001), packet_loss_rate=0.5, seed=9
        )
        a1 = parent.clone("reverse")
        a2 = parent.clone("reverse")
        # Same clone name, same derived stream.
        draws1 = [a1._rng.random() for _ in range(5)]
        draws2 = [a2._rng.random() for _ in range(5)]
        assert draws1 == draws2
        # Different name => different stream.
        b = parent.clone("other")
        assert [b._rng.random() for _ in range(5)] != draws1

    def test_clone_zeroes_stats(self):
        parent = NetworkLink("parent", latency=ConstantLatency(0.001))
        parent.packets_sent = 42
        clone = parent.clone("fresh")
        assert clone.packets_sent == 0


def _mesh():
    nodes = [Sink(name) for name in ("a", "b", "c")]
    network = Network("net", default_link=conditions.local_network(seed=1))
    sim = Simulation(
        entities=[network, *nodes], end_time=Instant.from_seconds(10.0)
    )
    return network, dict(zip("abc", nodes)), sim


class TestPartitions:
    def test_partition_blocks_both_directions(self):
        network, nodes, sim = _mesh()
        network.partition([nodes["a"]], [nodes["b"]])
        assert network.is_partitioned("a", "b")
        assert network.is_partitioned("b", "a")
        assert not network.is_partitioned("a", "c")

    def test_asymmetric_partition_blocks_one_direction(self):
        network, nodes, sim = _mesh()
        network.partition([nodes["a"]], [nodes["b"]], asymmetric=True)
        assert network.is_partitioned("a", "b")
        assert not network.is_partitioned("b", "a")

    def test_heal_restores_connectivity(self):
        network, nodes, sim = _mesh()
        partition = network.partition([nodes["a"]], [nodes["b"], nodes["c"]])
        assert partition.is_active
        partition.heal()
        assert not partition.is_active
        assert not network.is_partitioned("a", "b")

    def test_heal_partition_clears_everything(self):
        network, nodes, sim = _mesh()
        network.partition([nodes["a"]], [nodes["b"]])
        network.partition([nodes["b"]], [nodes["c"]], asymmetric=True)
        network.heal_partition()
        for src in "abc":
            for dst in "abc":
                assert not network.is_partitioned(src, dst)

    def test_partitioned_send_is_dropped_not_delivered(self):
        network, nodes, sim = _mesh()
        network.partition([nodes["a"]], [nodes["b"]])
        sim.schedule(network.send(nodes["a"], nodes["b"], "msg"))
        sim.schedule(network.send(nodes["a"], nodes["c"], "msg"))
        sim.run()
        assert nodes["b"].events_received == 0
        assert nodes["c"].events_received == 1

    def test_traffic_matrix_tracks_per_pair(self):
        network, nodes, sim = _mesh()
        sim.schedule(network.send(nodes["a"], nodes["b"], "msg"))
        sim.schedule(network.send(nodes["a"], nodes["b"], "msg"))
        sim.schedule(network.send(nodes["b"], nodes["c"], "msg"))
        sim.run()
        matrix = {
            (entry.source, entry.destination): entry.packets_sent
            for entry in network.traffic_matrix()
        }
        assert matrix[("a", "b")] == 2
        assert matrix[("b", "c")] == 1
