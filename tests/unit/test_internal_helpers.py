"""Direct tests for shared internal helpers: the settle-once call ledger
and the sync-primitive ambient-clock base."""

from happysim_tpu import Event, Instant, Mutex, Simulation
from happysim_tpu.components.microservice._tracking import PendingCalls
from happysim_tpu.components.sync._base import SyncPrimitive
from happysim_tpu.core.entity import Entity


class TestPendingCalls:
    def test_issue_settle_roundtrip(self):
        calls = PendingCalls()
        call_id = calls.issue(route="orders", attempt=1)
        assert len(calls) == 1
        info = calls.settle(call_id)
        assert info == {"route": "orders", "attempt": 1}
        assert len(calls) == 0

    def test_settle_exactly_once(self):
        """The response/timeout race: the loser must get None."""
        calls = PendingCalls()
        call_id = calls.issue(kind="call")
        assert calls.settle(call_id) is not None  # winner
        assert calls.settle(call_id) is None  # loser does nothing

    def test_unknown_and_none_ids(self):
        calls = PendingCalls()
        assert calls.settle(None) is None
        assert calls.settle(99) is None

    def test_ids_monotonic_across_settles(self):
        calls = PendingCalls()
        first = calls.issue()
        calls.settle(first)
        second = calls.issue()
        assert second > first  # ids never reused


class TestSyncPrimitiveClock:
    def test_outside_simulation_reads_zero(self):
        class Standalone(SyncPrimitive):
            def handle_event(self, event):
                return None

        assert Standalone("standalone")._now_ns() == 0

    def test_ambient_clock_inside_simulation(self):
        """A primitive never registered as an entity still reads sim time
        (wait-time accounting in Mutex/Semaphore relies on this)."""
        mutex = Mutex("m")  # NOT passed to Simulation(entities=...)
        seen = {}

        class Worker(Entity):
            def handle_event(self, event):
                grant = yield mutex.acquire()
                seen["t_ns"] = mutex._now_ns()
                mutex.release()
                return None

        worker = Worker("w")
        sim = Simulation(entities=[worker], end_time=Instant.from_seconds(10))
        sim.schedule(Event(Instant.from_seconds(2.5), "go", target=worker))
        sim.run()
        assert seen["t_ns"] == Instant.from_seconds(2.5).nanoseconds
