"""Moment conformance for every latency distribution family.

Each family promises a mean and a squared coefficient of variation
(SCV); queueing results computed from them (Pollaczek-Khinchine, the
TPU service twins) are only as right as these moments. Sampled mean and
SCV must match the configured values within Monte-Carlo tolerance for
EVERY family, plus each family's shape-specific signatures.

Parity target: ``happysimulator/tests/unit/test_distributions.py``.
"""

from __future__ import annotations

import math

import pytest

from happysim_tpu.core.temporal import Instant
from happysim_tpu.distributions.latency_distribution import (
    ConstantLatency,
    ErlangLatency,
    ExponentialLatency,
    HyperExponentialLatency,
    LogNormalLatency,
    ParetoLatency,
    PercentileFittedLatency,
    ShiftedLatency,
    UniformLatency,
)

N = 40_000
NOW = Instant.Epoch


def draw(dist, n=N):
    return [dist.get_latency(NOW).to_seconds() for _ in range(n)]


def moments(samples):
    mean = sum(samples) / len(samples)
    var = sum((s - mean) ** 2 for s in samples) / len(samples)
    return mean, (var / (mean * mean) if mean else 0.0)


CASES = {
    "constant": (lambda: ConstantLatency(0.2), 0.2, 0.0),
    "exponential": (lambda: ExponentialLatency(0.2, seed=1), 0.2, 1.0),
    "uniform": (lambda: UniformLatency(0.1, 0.3, seed=2), 0.2, 1.0 / 12.0),
    "erlang2": (lambda: ErlangLatency(0.2, k=2, seed=3), 0.2, 0.5),
    "erlang3": (lambda: ErlangLatency(0.2, k=3, seed=4), 0.2, 1.0 / 3.0),
    "hyperexp": (lambda: HyperExponentialLatency(0.2, scv=3.0, seed=5), 0.2, 3.0),
    "lognormal": (lambda: LogNormalLatency(0.2, scv=1.5, seed=6), 0.2, 1.5),
    "pareto": (lambda: ParetoLatency(0.2, alpha=3.5, seed=7), 0.2, None),
}
# uniform(0.1, 0.3): var = (0.3-0.1)^2/12 = 1/300; scv = var/0.04 = 1/12.


@pytest.mark.parametrize("family", sorted(CASES), ids=sorted(CASES))
class TestMoments:
    def test_mean_matches_configuration(self, family):
        factory, mean, _ = CASES[family]
        sampled_mean, _ = moments(draw(factory()))
        tolerance = 0.10 if family == "pareto" else 0.03  # heavy tail
        assert sampled_mean == pytest.approx(mean, rel=tolerance)

    def test_scv_matches_family(self, family):
        factory, _, scv = CASES[family]
        if scv is None:
            pytest.skip("pareto SCV checked separately (slow convergence)")
        _, sampled_scv = moments(draw(factory()))
        assert sampled_scv == pytest.approx(scv, abs=max(0.1 * scv, 0.02))

    def test_samples_are_positive(self, family):
        factory, _, _ = CASES[family]
        assert all(s >= 0.0 for s in draw(factory(), n=2000))

    def test_seeded_streams_reproduce(self, family):
        factory, _, _ = CASES[family]
        assert draw(factory(), n=50) == draw(factory(), n=50)


class TestShapeSignatures:
    def test_erlang_less_variable_than_exponential(self):
        _, scv_erl = moments(draw(ErlangLatency(0.2, k=3, seed=1)))
        _, scv_exp = moments(draw(ExponentialLatency(0.2, seed=1)))
        assert scv_erl < scv_exp * 0.6

    def test_hyperexp_more_variable_than_exponential(self):
        _, scv_hyp = moments(draw(HyperExponentialLatency(0.2, scv=4.0, seed=2)))
        assert scv_hyp > 2.0

    def test_pareto_tail_heavier_than_exponential(self):
        pareto = sorted(draw(ParetoLatency(0.2, alpha=2.2, seed=3)))
        expo = sorted(draw(ExponentialLatency(0.2, seed=3)))
        # Same mean, but the 99.9th percentile is far larger.
        index = int(0.999 * N)
        assert pareto[index] > expo[index] * 1.5

    def test_pareto_minimum_is_xm(self):
        alpha = 2.5
        dist = ParetoLatency(0.2, alpha=alpha, seed=4)
        x_m = 0.2 * (alpha - 1.0) / alpha
        samples = draw(dist, n=5000)
        assert min(samples) >= x_m * 0.999

    def test_uniform_bounds_are_hard(self):
        samples = draw(UniformLatency(0.1, 0.3, seed=5), n=5000)
        assert 0.1 <= min(samples) and max(samples) <= 0.3

    def test_lognormal_median_below_mean(self):
        samples = sorted(draw(LogNormalLatency(0.2, scv=2.0, seed=6)))
        median = samples[N // 2]
        assert median < 0.2  # right-skew signature


class TestWrappers:
    def test_shifted_adds_a_floor(self):
        base = ExponentialLatency(0.1, seed=7)
        shifted = ShiftedLatency(base, 0.05)
        samples = draw(shifted, n=5000)
        assert min(samples) >= 0.05
        mean, _ = moments(samples)
        assert mean == pytest.approx(0.15, rel=0.05)

    def test_percentile_fitted_single_point_exact(self):
        """One point pins the exponential exactly: the sampled quantile
        at that percentile matches the given value."""
        dist = PercentileFittedLatency({0.5: 0.010}, seed=8)
        expected_mean = 0.010 / math.log(2.0)
        assert dist.fitted_mean_seconds == pytest.approx(expected_mean)
        samples = sorted(draw(dist))
        assert samples[int(0.5 * N)] == pytest.approx(0.010, rel=0.05)

    def test_percentile_fitted_least_squares_compromises(self):
        """Multiple inconsistent points: the fit is the documented least
        squares over v = m * (-ln(1-p)), between the per-point means."""
        points = {0.5: 0.010, 0.99: 0.200}
        dist = PercentileFittedLatency(points, seed=9)
        per_point = [v / -math.log1p(-p) for p, v in points.items()]
        assert min(per_point) <= dist.fitted_mean_seconds <= max(per_point)
        mean, scv = moments(draw(dist))
        assert mean == pytest.approx(dist.fitted_mean_seconds, rel=0.03)
        assert scv == pytest.approx(1.0, abs=0.1)  # it samples an exponential
