"""Logical-clock law checks: Lamport monotonicity, vector-clock partial
order axioms, and the HLC receive algorithm's four branches.

The control/clock tests cover the happy paths; these pin the invariants
message-ordering protocols build on, with randomized message exchanges
as the oracle.

Parity target: ``happysimulator/tests/unit/test_logical_clocks.py``.
"""

from __future__ import annotations

import random

import pytest

from happysim_tpu.core.logical_clocks import (
    HLCTimestamp,
    HybridLogicalClock,
    LamportClock,
    VectorClock,
)
from happysim_tpu.core.temporal import Instant


def ts(seconds: float) -> Instant:
    return Instant.from_seconds(seconds)


class TestLamport:
    def test_tick_is_strictly_monotone(self):
        clock = LamportClock()
        values = [clock.tick() for _ in range(10)]
        assert values == sorted(set(values))

    def test_update_jumps_past_received(self):
        clock = LamportClock(start=3)
        assert clock.update(10) == 11
        assert clock.time == 11

    def test_update_with_stale_value_still_advances(self):
        clock = LamportClock(start=8)
        after = clock.update(2)
        assert after > 8

    def test_messages_order_causally(self):
        """Randomized exchange: a message's send time is always strictly
        below the receiver's clock after delivery."""
        rng = random.Random(5)
        clocks = [LamportClock() for _ in range(4)]
        for _ in range(200):
            sender, receiver = rng.sample(range(4), 2)
            sent_at = clocks[sender].tick()
            received_at = clocks[receiver].update(sent_at)
            assert received_at > sent_at


class TestVectorClockLaws:
    def test_happened_before_is_irreflexive(self):
        clock = VectorClock("a").increment()
        assert not clock.happened_before(clock)

    def test_happened_before_is_antisymmetric(self):
        a = VectorClock("a").increment()
        b = VectorClock("b")
        b.merge(a.copy())  # receive from a (mutates b only)
        assert a.happened_before(b)
        assert not b.happened_before(a)

    def test_happened_before_is_transitive(self):
        a = VectorClock("a").increment()
        b = VectorClock("b")
        b.merge(a.copy())
        c = VectorClock("c")
        c.merge(b.copy())
        assert a.happened_before(b) and b.happened_before(c)
        assert a.happened_before(c)

    def test_concurrency_is_symmetric(self):
        a = VectorClock("a").increment()
        b = VectorClock("b").increment()
        assert a.is_concurrent(b) and b.is_concurrent(a)

    def test_merge_dominates_both_inputs(self):
        a = VectorClock("a").increment().increment()
        b = VectorClock("b").increment()
        a_before, b_before = a.copy(), b.copy()
        merged = a.merge(b.copy())  # receive at a: max + own increment
        assert merged.clocks["a"] == 3 and merged.clocks["b"] == 1
        assert a_before.happened_before(merged)
        assert b_before.happened_before(merged)

    def test_randomized_exchange_never_misorders(self):
        """Fuzz the core theorem: if a message chain connects x to y,
        x.happened_before(y); disconnected updates stay concurrent."""
        rng = random.Random(11)
        nodes = {name: VectorClock(name) for name in "abcd"}
        history: list[tuple[str, VectorClock]] = []
        for _ in range(120):
            name = rng.choice("abcd")
            if history and rng.random() < 0.4:
                _, snapshot = rng.choice(history)
                nodes[name] = nodes[name].merge(snapshot)
            nodes[name] = nodes[name].increment()
            snapshot = nodes[name].copy()
            for _, earlier in history[-10:]:
                # No later snapshot may happen-before an earlier one.
                assert not snapshot.happened_before(earlier)
            history.append((name, snapshot))


class TestHLC:
    def test_physical_progress_resets_logical(self):
        clock = HybridLogicalClock()
        clock.now(ts(1.0))
        clock.now(ts(1.0))  # same wall: logical grows
        assert clock.timestamp.logical == 1
        stamp = clock.now(ts(2.0))
        assert stamp.logical == 0
        assert stamp.wall == ts(2.0).nanoseconds

    def test_stalled_wall_clock_still_orders_events(self):
        clock = HybridLogicalClock()
        stamps = [clock.now(ts(5.0)) for _ in range(5)]
        assert [s.logical for s in stamps] == [0, 1, 2, 3, 4]
        assert all(s.wall == ts(5.0).nanoseconds for s in stamps)

    def test_receive_from_the_future_adopts_remote(self):
        clock = HybridLogicalClock()
        clock.now(ts(1.0))
        remote = HLCTimestamp(wall=ts(9.0).nanoseconds, logical=7)
        stamp = clock.receive(remote, ts(2.0))
        assert stamp.wall == remote.wall
        assert stamp.logical == 8

    def test_receive_stale_remote_keeps_local_lead(self):
        clock = HybridLogicalClock()
        clock.now(ts(10.0))
        stamp = clock.receive(HLCTimestamp(ts(1.0).nanoseconds, 99), ts(2.0))
        assert stamp.wall == ts(10.0).nanoseconds
        assert stamp.logical == 1  # local wall unchanged: logical bumps

    def test_receive_with_fresh_physical_resets(self):
        clock = HybridLogicalClock()
        clock.now(ts(1.0))
        stamp = clock.receive(HLCTimestamp(ts(2.0).nanoseconds, 5), ts(8.0))
        assert stamp.wall == ts(8.0).nanoseconds
        assert stamp.logical == 0

    def test_receive_equal_walls_takes_max_logical(self):
        clock = HybridLogicalClock()
        clock.now(ts(3.0))  # local (3.0, 0)
        remote = HLCTimestamp(ts(3.0).nanoseconds, 9)
        stamp = clock.receive(remote, ts(3.0))
        assert stamp.logical == 10

    def test_happened_before_preserved_through_exchange(self):
        """The HLC theorem: message timestamps are strictly increasing
        along any causal chain, even with skewed physical clocks."""
        rng = random.Random(3)
        clocks = [HybridLogicalClock() for _ in range(3)]
        skews = [0.0, -0.5, 0.3]
        last: dict[int, HLCTimestamp] = {}
        physical = 1.0
        for _ in range(150):
            physical += rng.random() * 0.01
            sender, receiver = rng.sample(range(3), 2)
            sent = clocks[sender].now(ts(physical + skews[sender]))
            received = clocks[receiver].receive(sent, ts(physical + skews[receiver]))
            assert (received.wall, received.logical) > (sent.wall, sent.logical)
            if sender in last:
                previous = last[sender]
                assert (sent.wall, sent.logical) > (
                    previous.wall,
                    previous.logical,
                )
            last[sender] = sent
