"""Infrastructure-model math at the edges: disk latency curves, GC
pause scaling, congestion-control algebra.

Complements ``test_infrastructure.py`` (which drives the entities in
simulations) with the pure latency/windowing formulas where the
hardware models' shape lives.

Parity target: per-profile cases of
``happysimulator/tests/unit/test_disk_io.py`` / ``test_gc.py`` /
``test_tcp.py``.
"""

from __future__ import annotations

import pytest

from happysim_tpu.components.infrastructure import (
    AIMD,
    HDD,
    NVMe,
    SSD,
    ConcurrentGC,
    Cubic,
    GenerationalGC,
    StopTheWorld,
)


class TestDiskProfiles:
    def test_hdd_dominated_by_seek_not_transfer(self):
        hdd = HDD(seed=1)
        small = hdd.read_latency_s(4096, queue_depth=1)
        assert small > 0.004  # at least the rotational latency
        # A 4KB transfer at 150MB/s is ~27us — mechanics dominate 100x.
        assert small > 100 * (4096 / 150e6)

    def test_ssd_faster_than_hdd_slower_than_nvme(self):
        hdd, ssd, nvme = HDD(seed=1), SSD(), NVMe()
        size = 4096
        assert (
            nvme.read_latency_s(size, 1)
            < ssd.read_latency_s(size, 1)
            < hdd.read_latency_s(size, 1)
        )

    def test_ssd_write_slower_than_read(self):
        ssd = SSD()
        assert ssd.write_latency_s(4096, 1) > ssd.read_latency_s(4096, 1)

    def test_hdd_queue_penalty_is_linear(self):
        hdd = HDD(seed=2, seek_time_s=0.0)  # remove seek jitter
        base = hdd.read_latency_s(0, 1)
        assert hdd.read_latency_s(0, 11) == pytest.approx(base * 4.0)  # 1+0.3*10

    def test_nvme_flat_until_native_depth(self):
        nvme = NVMe(native_queue_depth=32)
        base = nvme.read_latency_s(4096, 1)
        assert nvme.read_latency_s(4096, 32) == pytest.approx(base)
        assert nvme.read_latency_s(4096, 64) > base

    def test_ssd_log_scaling_is_sublinear(self):
        ssd = SSD()
        base = ssd.read_latency_s(4096, 1)
        at_8 = ssd.read_latency_s(4096, 8)
        at_64 = ssd.read_latency_s(4096, 64)
        # Doubling depth 8->64 (8x) must cost less than 8x the depth-8 slope.
        assert (at_64 - base) < 8 * (at_8 - base)

    def test_transfer_term_scales_with_size(self):
        nvme = NVMe()
        small = nvme.read_latency_s(4096, 1)
        large = nvme.read_latency_s(64 * 1024 * 1024, 1)
        assert large > small * 100  # 64MB at 3.5GB/s ~ 18ms >> 10us

    def test_hdd_seek_jitter_is_seeded(self):
        a = [HDD(seed=9).read_latency_s(0, 1) for _ in range(3)]
        b = [HDD(seed=9).read_latency_s(0, 1) for _ in range(3)]
        assert a[0] == b[0]


class TestGCStrategies:
    def test_stop_the_world_pause_scales_with_pressure(self):
        gc = StopTheWorld()
        assert gc.pause_duration_s(0.9) > gc.pause_duration_s(0.1)

    def test_concurrent_pauses_are_shorter(self):
        stw, concurrent = StopTheWorld(), ConcurrentGC()
        for pressure in (0.2, 0.5, 0.9):
            assert concurrent.pause_duration_s(pressure) < stw.pause_duration_s(
                pressure
            )

    def test_generational_pressure_threshold_picks_the_class(self):
        gen = GenerationalGC(seed=4)
        minors = [gen.pause_duration_s(0.5) for _ in range(20)]
        majors = [gen.pause_duration_s(0.9) for _ in range(20)]
        # Below the threshold every pause is a cheap minor; at or above
        # it every pause is a major — an order of magnitude apart.
        assert max(minors) < min(majors)
        assert min(majors) > max(minors) * 3

    def test_intervals_positive(self):
        for strategy in (StopTheWorld(), ConcurrentGC(), GenerationalGC()):
            assert strategy.collection_interval_s() > 0


class TestCongestionControl:
    def test_aimd_slow_start_doubles_below_ssthresh(self):
        aimd = AIMD()
        assert aimd.on_ack(cwnd=4.0, ssthresh=16.0) == pytest.approx(5.0)

    def test_aimd_congestion_avoidance_above_ssthresh(self):
        aimd = AIMD(additive_increase=1.0)
        grown = aimd.on_ack(cwnd=16.0, ssthresh=8.0)
        assert grown == pytest.approx(16.0 + 1.0 / 16.0)

    def test_aimd_loss_halves(self):
        aimd = AIMD(multiplicative_decrease=0.5)
        cwnd, ssthresh = aimd.on_loss(cwnd=20.0)
        assert cwnd == pytest.approx(10.0)
        assert ssthresh == pytest.approx(10.0)

    def test_aimd_sawtooth_converges_to_band(self):
        aimd = AIMD()
        cwnd, ssthresh = 1.0, 16.0
        peaks = []
        for _ in range(400):
            cwnd = aimd.on_ack(cwnd, ssthresh)
            if cwnd > 32.0:  # "link capacity": loss
                peaks.append(cwnd)
                cwnd, ssthresh = aimd.on_loss(cwnd)
        # Sawtooth: every peak just above capacity, every trough at half.
        assert all(32.0 < peak < 34.0 for peak in peaks[1:])

    def test_cubic_reacts_less_than_aimd(self):
        cubic = Cubic()
        cwnd_cubic, _ = cubic.on_loss(cwnd=20.0)
        cwnd_aimd, _ = AIMD().on_loss(cwnd=20.0)
        assert cwnd_cubic > cwnd_aimd  # beta 0.7 vs 0.5

    def test_cubic_growth_bounded_and_monotone(self):
        cubic = Cubic()
        cwnd = 10.0
        previous = cwnd
        for _ in range(50):
            cwnd = cubic.on_ack(cwnd, ssthresh=5.0)
            assert cwnd >= previous
            previous = cwnd
