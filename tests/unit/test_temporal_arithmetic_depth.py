"""Temporal arithmetic at nanosecond resolution: closure, precision,
ordering under randomized op sequences, and clock-model composition.

Time bugs in a DES are silent data corruption; these pin the integer
nanosecond substrate (no float drift in accumulation), Duration algebra
closure, and the skew/drift clock models' exactness.

Parity target: ``happysimulator/tests/unit/test_temporal.py`` (extended
with the randomized closure fuzz).
"""

from __future__ import annotations

import random

import pytest

from happysim_tpu.core.node_clock import FixedSkew, LinearDrift, NodeClock
from happysim_tpu.core.temporal import Duration, Instant


class TestNanosecondExactness:
    def test_instants_are_integer_nanoseconds(self):
        instant = Instant.from_seconds(1.5)
        assert instant.nanoseconds == 1_500_000_000

    def test_accumulating_small_steps_does_not_drift(self):
        """1 million 1us steps must land EXACTLY on 1s — float
        accumulation would be off by hundreds of ns."""
        step = Duration.from_seconds(1e-6)
        t = Instant.Epoch
        for _ in range(1_000_000):
            t = t + step
        assert t.nanoseconds == 1_000_000_000

    def test_subnanosecond_rounds(self):
        assert Duration.from_seconds(0.4e-9).nanoseconds in (0, 1)

    def test_negative_duration_supported(self):
        span = Duration.from_seconds(-0.5)
        assert span.nanoseconds == -500_000_000
        assert (Instant.from_seconds(2.0) + span) == Instant.from_seconds(1.5)


class TestAlgebraClosure:
    def test_randomized_closure_and_types(self):
        """Instant/Duration algebra: I+D=I, I-I=D, D+D=D, D*k=D — types
        and values checked against integer-ns ground truth under fuzz."""
        rng = random.Random(7)
        for _ in range(300):
            a_ns = rng.randrange(-10**12, 10**12)
            b_ns = rng.randrange(-10**12, 10**12)
            instant = Instant(a_ns)
            span = Duration(b_ns)
            assert (instant + span).nanoseconds == a_ns + b_ns
            assert (instant - span).nanoseconds == a_ns - b_ns
            assert isinstance(instant + span, Instant)
            other = Instant(b_ns)
            delta = instant - other
            assert isinstance(delta, Duration)
            assert delta.nanoseconds == a_ns - b_ns

    def test_duration_scaling(self):
        span = Duration.from_seconds(0.25)
        assert (span * 4).to_seconds() == pytest.approx(1.0)
        assert (span / 2).to_seconds() == pytest.approx(0.125)

    def test_ordering_total_on_instants(self):
        rng = random.Random(9)
        values = [Instant(rng.randrange(0, 10**12)) for _ in range(100)]
        ordered = sorted(values)
        assert all(
            ordered[i].nanoseconds <= ordered[i + 1].nanoseconds
            for i in range(len(ordered) - 1)
        )

    def test_epoch_identity(self):
        assert (Instant.Epoch + Duration(0)) == Instant.Epoch
        assert (Instant.from_seconds(3.0) - Instant.Epoch).to_seconds() == 3.0


class _TrueClock:
    def __init__(self):
        self.now = Instant.Epoch

    def update(self, value):
        self.now = value


class TestClockModels:
    def test_fixed_skew_is_constant_offset(self):
        model = FixedSkew(Duration.from_seconds(0.25))
        for seconds in (0.0, 1.0, 1e6):
            true = Instant.from_seconds(seconds)
            assert (model.read(true) - true).to_seconds() == pytest.approx(0.25)

    def test_negative_skew(self):
        model = FixedSkew(Duration.from_seconds(-0.1))
        true = Instant.from_seconds(5.0)
        assert model.read(true) < true

    def test_linear_drift_grows_with_time(self):
        model = LinearDrift(rate_ppm=100.0)  # 100us per second
        at_1s = model.read(Instant.from_seconds(1.0))
        at_100s = model.read(Instant.from_seconds(100.0))
        drift_1 = (at_1s - Instant.from_seconds(1.0)).to_seconds()
        drift_100 = (at_100s - Instant.from_seconds(100.0)).to_seconds()
        assert drift_1 == pytest.approx(100e-6, rel=1e-6)
        assert drift_100 == pytest.approx(100 * 100e-6, rel=1e-6)

    def test_zero_drift_is_identity(self):
        model = LinearDrift(rate_ppm=0.0)
        true = Instant.from_seconds(42.0)
        assert model.read(true) == true

    def test_node_clock_reads_through_model(self):
        clock = _TrueClock()
        node = NodeClock(model=FixedSkew(Duration.from_seconds(1.0)))
        node.set_clock(clock)
        clock.update(Instant.from_seconds(10.0))
        assert node.now.to_seconds() == pytest.approx(11.0)

    def test_node_clock_without_model_is_true_time(self):
        clock = _TrueClock()
        node = NodeClock()
        node.set_clock(clock)
        clock.update(Instant.from_seconds(7.0))
        assert node.now == Instant.from_seconds(7.0)

    def test_two_skewed_nodes_disagree_consistently(self):
        clock = _TrueClock()
        fast = NodeClock(model=FixedSkew(Duration.from_seconds(0.5)))
        slow = NodeClock(model=FixedSkew(Duration.from_seconds(-0.5)))
        fast.set_clock(clock)
        slow.set_clock(clock)
        for seconds in (1.0, 2.5, 9.0):
            clock.update(Instant.from_seconds(seconds))
            gap = (fast.now - slow.now).to_seconds()
            assert gap == pytest.approx(1.0)
