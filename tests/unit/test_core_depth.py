"""Depth tests for core adapters: @simulatable, protocols, control state,
CallbackEntity dispatch (SURVEY §2.1; ref core/decorators.py:48,
core/protocols.py:58,98, core/callback_entity.py:15,39)."""

import functools

import pytest

from happysim_tpu import Instant, Simulation
from happysim_tpu.core.callback_entity import CallbackEntity, NullEntity
from happysim_tpu.core.clock import Clock
from happysim_tpu.core.control.state import BreakpointContext, SimulationState
from happysim_tpu.core.decorators import simulatable
from happysim_tpu.core.entity import Entity
from happysim_tpu.core.event import Event
from happysim_tpu.core.protocols import HasCapacity, Simulatable


class TestSimulatableDecorator:
    def test_requires_handle_event(self):
        with pytest.raises(TypeError, match="handle_event"):

            @simulatable
            class Broken:
                name = "broken"

    def test_injects_clock_plumbing(self):
        @simulatable
        class Plain:
            def __init__(self):
                self.name = "plain"
                self.seen = 0

            def handle_event(self, event):
                self.seen += 1
                return None

        p = Plain()
        assert p._clock is None
        assert p.has_capacity()
        assert p.downstream_entities() == []
        clock = Clock()
        p.set_clock(clock)
        assert p.now == clock.now

    def test_now_without_clock_raises(self):
        @simulatable
        class Plain:
            name = "p"

            def handle_event(self, event):
                return None

        with pytest.raises(RuntimeError, match="no clock"):
            Plain().now

    def test_decorated_class_satisfies_simulatable(self):
        @simulatable
        class Plain:
            name = "p"

            def handle_event(self, event):
                return None

        assert isinstance(Plain(), Simulatable)

    def test_existing_methods_not_overwritten(self):
        @simulatable
        class Custom:
            name = "c"

            def handle_event(self, event):
                return None

            def has_capacity(self):
                return False

        assert Custom().has_capacity() is False

    def test_runs_inside_simulation(self):
        @simulatable
        class Tally:
            def __init__(self):
                self.name = "tally"
                self.times = []

            def handle_event(self, event):
                self.times.append(self.now.to_seconds())
                return None

        t = Tally()
        sim = Simulation(entities=[t], end_time=Instant.from_seconds(10))
        sim.schedule(Event(Instant.from_seconds(1), "Ping", target=t))
        sim.schedule(Event(Instant.from_seconds(2), "Ping", target=t))
        sim.run()
        assert t.times == [1.0, 2.0]


class TestProtocols:
    def test_entity_satisfies_simulatable(self):
        class E(Entity):
            def handle_event(self, event):
                return None

        assert isinstance(E("e"), Simulatable)

    def test_plain_object_fails_simulatable(self):
        class NotAnActor:
            pass

        assert not isinstance(NotAnActor(), Simulatable)

    def test_has_capacity_structural(self):
        class Worker:
            def has_capacity(self):
                return True

        assert isinstance(Worker(), HasCapacity)
        assert not isinstance(object(), HasCapacity)


class TestControlState:
    def test_simulation_state_frozen(self):
        state = SimulationState(
            time=Instant.from_seconds(1),
            events_processed=3,
            pending_events=2,
            is_paused=False,
            is_completed=False,
        )
        with pytest.raises(AttributeError):
            state.events_processed = 4

    def test_breakpoint_context_frozen(self):
        sink = NullEntity
        ctx = BreakpointContext(
            simulation=None,
            next_event=Event(Instant.Epoch, "X", target=sink),
            time=Instant.Epoch,
            events_processed=0,
        )
        with pytest.raises(AttributeError):
            ctx.time = Instant.from_seconds(1)


class TestCallbackEntity:
    def test_zero_arg_function(self):
        calls = []
        e = CallbackEntity("cb", lambda: calls.append(1))
        e.handle_event(Event(Instant.Epoch, "X", target=e))
        assert calls == [1]

    def test_one_arg_function_gets_event(self):
        seen = []
        e = CallbackEntity("cb", lambda event: seen.append(event.event_type))
        e.handle_event(Event(Instant.Epoch, "Ping", target=e))
        assert seen == ["Ping"]

    def test_two_arg_function_gets_event_and_now(self):
        seen = []
        e = CallbackEntity("cb", lambda event, now: seen.append(now))
        t = Instant.from_seconds(3)
        e.handle_event(Event(t, "X", target=e))
        # No clock injected: the event's own time is "now".
        assert seen == [t]

    def test_two_arg_uses_clock_when_present(self):
        seen = []
        e = CallbackEntity("cb", lambda event, now: seen.append(now))
        clock = Clock()
        clock.update(Instant.from_seconds(9))
        e.set_clock(clock)
        e.handle_event(Event(Instant.from_seconds(3), "X", target=e))
        assert seen == [Instant.from_seconds(9)]

    def test_bound_method_arity(self):
        class Recorder:
            def __init__(self):
                self.events = []

            def record(self, event):
                self.events.append(event)

        r = Recorder()
        e = CallbackEntity("cb", r.record)
        e.handle_event(Event(Instant.Epoch, "X", target=e))
        assert len(r.events) == 1

    def test_callable_without_code_object(self):
        seen = []
        wrapped = functools.partial(lambda tag, event: seen.append((tag, event)), "t")
        e = CallbackEntity("cb", wrapped)
        e.handle_event(Event(Instant.Epoch, "X", target=e))
        assert seen and seen[0][0] == "t"

    def test_returned_events_scheduled(self):
        sink_hits = []
        sink = CallbackEntity("sink", lambda: sink_hits.append(1))

        def relay(event, now):
            return [Event(now + 1.0, "Fwd", target=sink)]

        e = CallbackEntity("relay", relay)
        sim = Simulation(entities=[e, sink], end_time=Instant.from_seconds(10))
        sim.schedule(Event(Instant.from_seconds(1), "X", target=e))
        sim.run()
        assert sink_hits == [1]

    def test_null_entity_absorbs(self):
        assert NullEntity.handle_event(Event(Instant.Epoch, "X", target=NullEntity)) is None
        assert NullEntity.name == "null"
