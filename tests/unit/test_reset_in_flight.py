"""The simulation-reset in-flight protocol.

``Simulation._reset`` clears the event heap, killing every in-flight
continuation and completion hook. Any entity bookkeeping that counts that
in-flight work (a server's occupied slot, a backend's in_flight, a held
mutex) would otherwise track ghosts forever — at capacity 1 that means a
post-reset run starves completely. Entities opt in via
``reset_in_flight()``: transient in-flight state clears, cumulative
counters survive (the reference's keep-entity-state reset semantics,
``happysimulator/core/simulation.py:240-282``).
"""

from __future__ import annotations

from happysim_tpu import (
    ConstantLatency,
    ExponentialLatency,
    Instant,
    Server,
    Simulation,
    Sink,
    Source,
)
from happysim_tpu.components.client.connection_pool import ConnectionPool
from happysim_tpu.components.load_balancer import LoadBalancer
from happysim_tpu.components.messaging import MessageQueue
from happysim_tpu.components.resilience.bulkhead import Bulkhead
from happysim_tpu.components.resilience.hedge import Hedge
from happysim_tpu.components.resource import Resource
from happysim_tpu.components.server.concurrency import (
    FixedConcurrency,
    WeightedConcurrency,
)
from happysim_tpu.components.sync import Mutex, RWLock, Semaphore
from happysim_tpu.core.event import Event


def _mm1(duration=1.0, concurrency=1):
    sink = Sink("sink")
    server = Server(
        "srv",
        concurrency=concurrency,
        service_time=ExponentialLatency(0.05, seed=3),
        downstream=sink,
    )
    source = Source.poisson(rate=30.0, target=server, stop_after=duration, seed=9)
    sim = Simulation(
        sources=[source],
        entities=[server, sink],
        end_time=Instant.from_seconds(duration),
    )
    return sim, server, sink


class TestServerGhostSlot:
    def test_reset_frees_midflight_concurrency_slot(self):
        """The bug this protocol exists for: a request in service when the
        horizon hits holds a slot; reset kills its continuation; without
        the hook the whole second run queues behind the ghost."""
        sim, server, sink = _mm1()
        sim.run()
        first_completed = server.requests_completed
        assert first_completed > 0
        sim.control.reset()
        assert server.concurrency.active == 0
        assert server.queue_depth == 0
        # Cumulative counters survived the reset.
        assert server.requests_completed == first_completed
        sim.run()
        assert server.requests_completed > first_completed
        assert sink.events_received > first_completed

    def test_reset_clears_buffered_queue_items(self):
        sim, server, _ = _mm1(concurrency=1)
        sim.control.pause()
        sim.run()
        sim.control.step(40)  # mid-burst: some arrivals are buffered
        sim.control.reset()
        assert server.queue_depth == 0
        summary = sim.run()
        assert summary.completed


class TestConcurrencyModels:
    def test_fixed_releases_all(self):
        model = FixedConcurrency(limit=3)
        model.acquire()
        model.acquire()
        model.reset_in_flight()
        assert model.active == 0
        assert model.has_capacity()

    def test_weighted_clamps_to_zero(self):
        model = WeightedConcurrency(capacity=4.0, cost_fn=lambda e: 2.5)
        model.acquire(object())
        model.reset_in_flight()
        assert model.active == 0


class TestLoadBalancerGhosts:
    def test_backend_in_flight_zeroes_but_totals_survive(self):
        sink_a, sink_b = Sink("a"), Sink("b")
        lb = LoadBalancer("lb")
        lb.add_backend(sink_a)
        lb.add_backend(sink_b)
        info = lb.backend_info("a")
        info.in_flight = 5  # ghosts of hooks that died with the heap
        info.total_requests = 7
        lb.reset_in_flight()
        assert info.in_flight == 0
        assert info.total_requests == 7


class TestPoolAndResource:
    def test_pool_closes_active_and_clears_dials(self):
        pool = ConnectionPool("pool", target=Sink("t"), max_connections=2)
        conn = object.__new__(type("C", (), {}))
        pool._active[1] = conn
        pool._dialing = 1
        closed_before = pool.connections_closed
        pool.reset_in_flight()
        assert pool.active_connections == 0
        assert pool._dialing == 0
        assert pool.connections_closed == closed_before + 1

    def test_resource_returns_held_capacity(self):
        resource = Resource("r", capacity=2.0)
        Simulation(entities=[resource], end_time=Instant.from_seconds(1.0))
        resource.acquire(2.0)  # grant resolves immediately
        assert resource.available == 0.0
        resource.reset_in_flight()
        assert resource.available == 2.0
        assert resource.waiting == 0

    def test_bulkhead_restores_permits(self):
        bulkhead = Bulkhead("b", downstream=Sink("s"), max_concurrent=2)
        bulkhead._active = 2
        bulkhead.reset_in_flight()
        assert bulkhead.available_permits == 2

    def test_hedge_forgets_races(self):
        hedge = Hedge("h", downstream=Sink("s"), hedge_delay=0.1)
        hedge._in_flight[1] = {"done": False}
        hedge.reset_in_flight()
        assert hedge.in_flight_count == 0


class TestSyncPrimitives:
    def test_mutex_unlocks(self):
        mutex = Mutex("m")
        Simulation(entities=[mutex], end_time=Instant.from_seconds(1.0))
        mutex.acquire("owner")
        assert mutex.is_locked
        mutex.reset_in_flight()
        assert not mutex.is_locked
        assert mutex.owner is None

    def test_semaphore_restores_permits(self):
        sem = Semaphore("s", initial_count=2)
        Simulation(entities=[sem], end_time=Instant.from_seconds(1.0))
        sem.acquire()
        sem.acquire()
        sem.reset_in_flight()
        assert sem.available == 2

    def test_rwlock_clears_readers_and_writer(self):
        lock = RWLock("rw")
        Simulation(entities=[lock], end_time=Instant.from_seconds(1.0))
        lock.acquire_read()
        lock.reset_in_flight()
        assert lock.active_readers == 0
        assert not lock.is_write_locked


class TestMessageQueue:
    def test_unacked_messages_return_to_pending_in_order(self):
        queue = MessageQueue("q", auto_redelivery=False)
        consumer = Sink("c")
        queue.subscribe(consumer)
        for i in range(3):
            queue.publish(Event(Instant.Epoch, f"m{i}", target=queue))
        first = queue.poll()
        second = queue.poll()
        assert queue.in_flight_count == 2
        assert first is not None and second is not None
        queue.reset_in_flight()
        assert queue.in_flight_count == 0
        # Stuck messages lead the pending queue, oldest first.
        redelivered = queue.poll()
        assert redelivered.context["metadata"]["message_id"].endswith("-1")


class TestMessageQueueRedeliveryPark:
    def test_redelivery_parked_message_is_rescued(self):
        """schedule_redelivery parks a message outside BOTH queues waiting
        on a timer; after reset the timer is gone — the message must come
        back to pending, not orphan forever against capacity."""
        queue = MessageQueue("q", auto_redelivery=False, redelivery_delay=1.0)
        queue.subscribe(Sink("c"))
        queue.publish(Event(Instant.Epoch, "m", target=queue))
        delivered = queue.poll()
        message_id = delivered.context["metadata"]["message_id"]
        timer = queue.schedule_redelivery(message_id)
        assert timer is not None
        assert queue.in_flight_count == 0 and queue.pending_count == 0
        queue.reset_in_flight()
        assert queue.pending_count == 1
        redelivered = queue.poll()
        assert redelivered.context["metadata"]["message_id"] == message_id


class TestPoolIdleReset:
    def test_idle_connections_close_on_reset(self):
        """Idle connections' reap timers died with the heap; keeping them
        would exempt them from idle_timeout forever."""
        pool = ConnectionPool(
            "pool", target=Sink("t"), max_connections=4, idle_timeout=5.0
        )
        conn = object()
        pool._idle.append(conn)
        closed_before = pool.connections_closed
        pool.reset_in_flight()
        assert pool.idle_connections == 0
        assert pool.total_connections == 0
        assert pool.connections_closed == closed_before + 1


class TestSimulationWiring:
    def test_reset_calls_hook_on_every_entity(self):
        calls = []

        class Probe(Sink):
            def reset_in_flight(self):
                calls.append(self.name)

        sim = Simulation(
            entities=[Probe("p1"), Probe("p2")],
            end_time=Instant.from_seconds(0.1),
        )
        sim.run()
        sim.control.reset()
        assert calls == ["p1", "p2"]
