"""Depth tests for the scipy-replacement numerics (ref
numerics/integration.py:10, numerics/root_finding.py:27)."""

import math

import pytest

from happysim_tpu.numerics.integration import integrate_adaptive_simpson
from happysim_tpu.numerics.root_finding import brentq


class TestAdaptiveSimpson:
    def test_polynomial_exact(self):
        # Simpson is exact for cubics.
        val = integrate_adaptive_simpson(lambda x: x**3 - 2 * x + 1, 0.0, 2.0)
        assert val == pytest.approx(2.0, abs=1e-10)

    def test_exponential(self):
        val = integrate_adaptive_simpson(math.exp, 0.0, 1.0)
        assert val == pytest.approx(math.e - 1.0, rel=1e-8)

    def test_oscillatory(self):
        val = integrate_adaptive_simpson(math.sin, 0.0, math.pi)
        assert val == pytest.approx(2.0, rel=1e-8)

    def test_sharp_peak_adaptivity(self):
        # Narrow Gaussian: uniform Simpson would need a fine grid everywhere.
        f = lambda x: math.exp(-((x - 0.5) ** 2) / 2e-4)
        val = integrate_adaptive_simpson(f, 0.0, 1.0)
        assert val == pytest.approx(math.sqrt(2 * math.pi * 1e-4), rel=1e-4)

    def test_zero_width_interval(self):
        assert integrate_adaptive_simpson(math.exp, 1.0, 1.0) == 0.0

    def test_reversed_interval_is_negative(self):
        fwd = integrate_adaptive_simpson(math.sin, 0.0, 1.0)
        rev = integrate_adaptive_simpson(math.sin, 1.0, 0.0)
        assert rev == pytest.approx(-fwd, rel=1e-9)


class TestBrentq:
    def test_simple_root(self):
        r = brentq(lambda x: x**2 - 4, 0.0, 10.0)
        assert r == pytest.approx(2.0, abs=1e-9)

    def test_transcendental_root(self):
        r = brentq(lambda x: math.cos(x) - x, 0.0, 1.0)
        assert r == pytest.approx(0.7390851332151607, abs=1e-9)

    def test_root_at_bracket_edge(self):
        assert brentq(lambda x: x, 0.0, 1.0) == pytest.approx(0.0, abs=1e-12)

    def test_no_sign_change_raises(self):
        with pytest.raises(ValueError):
            brentq(lambda x: x**2 + 1, -1.0, 1.0)

    def test_steep_function(self):
        r = brentq(lambda x: math.expm1(50 * (x - 0.3)), 0.0, 1.0)
        assert r == pytest.approx(0.3, abs=1e-8)

    def test_flat_then_steep(self):
        f = lambda x: 0.0 if x < 0.6 else (x - 0.6) ** 3
        # Root is the whole flat region boundary; any point with |f| ~ 0 works.
        r = brentq(lambda x: f(x) - 1e-9, 0.0, 1.0)
        assert 0.59 <= r <= 0.7

    def test_arrival_inversion_shape(self):
        # The actual use: solve integral(rate) = target for ramp profiles.
        # integral of rate(t)=2t from 0 to T is T^2; target 9 => T=3.
        r = brentq(lambda T: T * T - 9.0, 0.0, 10.0)
        assert r == pytest.approx(3.0, abs=1e-9)
