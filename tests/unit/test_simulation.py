"""Unit tests: the Simulation engine loop."""

import pytest

from happysim_tpu import (
    CallbackEntity,
    Entity,
    Event,
    Instant,
    Simulation,
    Sink,
    Source,
)


class Echo(Entity):
    """Re-emits n follow-up events with a delay via plain returns."""

    def __init__(self, name, delay_s=0.0, hops=0):
        super().__init__(name)
        self.delay_s = delay_s
        self.hops = hops
        self.received = []

    def handle_event(self, event):
        self.received.append((event.time, event.event_type))
        if self.hops > 0:
            self.hops -= 1
            return [Event(self.now + self.delay_s, event.event_type, target=self)]
        return None


class Yielder(Entity):
    """Generator behavior: two yields then a final event."""

    def __init__(self, name, sink):
        super().__init__(name)
        self.sink = sink
        self.steps = []

    def handle_event(self, event):
        self.steps.append(("start", self.now.to_seconds()))
        yield 0.5
        self.steps.append(("mid", self.now.to_seconds()))
        yield 0.25
        self.steps.append(("end", self.now.to_seconds()))
        return [self.forward(event, self.sink)]


def test_run_processes_events_in_order():
    echo = Echo("echo", delay_s=1.0, hops=3)
    sim = Simulation(entities=[echo])
    sim.schedule(Event(Instant.Epoch, "ping", target=echo))
    summary = sim.run()
    assert summary.events_processed == 4
    times = [t.to_seconds() for t, _ in echo.received]
    assert times == [0.0, 1.0, 2.0, 3.0]


def test_end_time_bounds_run():
    echo = Echo("echo", delay_s=1.0, hops=100)
    sim = Simulation(entities=[echo], end_time=Instant.from_seconds(5))
    sim.schedule(Event(Instant.Epoch, "ping", target=echo))
    summary = sim.run()
    assert summary.end_time == Instant.from_seconds(5)
    assert summary.events_processed == 6  # t=0..5


def test_duration_arg():
    echo = Echo("echo", delay_s=1.0, hops=100)
    sim = Simulation(entities=[echo], duration=3.0)
    sim.schedule(Event(Instant.Epoch, "ping", target=echo))
    assert sim.run().events_processed == 4


def test_duration_and_end_time_mutually_exclusive():
    with pytest.raises(ValueError):
        Simulation(end_time=Instant.from_seconds(1), duration=1.0)


def test_generator_yields_advance_time():
    sink = Sink()
    y = Yielder("y", sink)
    sim = Simulation(entities=[y, sink])
    sim.schedule(Event(Instant.Epoch, "job", target=y))
    sim.run()
    assert y.steps == [("start", 0.0), ("mid", 0.5), ("end", 0.75)]
    assert sink.events_received == 1
    assert sink.latencies_s == [0.75]


def test_auto_terminates_on_daemon_only_heap():
    seen = []
    recorder = CallbackEntity("cb", lambda e: seen.append(e.time.to_seconds()))

    class DaemonLoop(Entity):
        def handle_event(self, event):
            return [Event(self.now + 1.0, "tick", target=self, daemon=True)]

    loop = DaemonLoop("daemon")
    sim = Simulation(entities=[loop, recorder])
    sim.schedule(Event(Instant.Epoch, "tick", target=loop, daemon=True))
    sim.schedule(Event(Instant.from_seconds(2.5), "real", target=recorder))
    summary = sim.run()
    # Runs until the only primary event is done, then stops despite daemons.
    assert seen == [2.5]
    assert summary.events_processed <= 5


def test_cancelled_events_are_skipped():
    echo = Echo("echo")
    sim = Simulation(entities=[echo])
    event = Event(Instant.from_seconds(1), "x", target=echo)
    keep = Event(Instant.from_seconds(2), "y", target=echo)
    sim.schedule([event, keep])
    event.cancel()
    sim.run()
    assert [t for _, t in echo.received] == ["y"]


def test_source_feeds_sink_constant_rate():
    sink = Sink()
    source = Source.constant(rate=10.0, target=sink, stop_after=1.0)
    sim = Simulation(sources=[source], entities=[sink], end_time=Instant.from_seconds(5))
    sim.run()
    # 10/s for 1s: ticks at 0.1..1.0
    assert sink.events_received == 10


def test_summary_harvests_entities():
    sink = Sink("the-sink")
    source = Source.constant(rate=5.0, target=sink, stop_after=1.0)
    sim = Simulation(sources=[source], entities=[sink])
    summary = sim.run()
    names = {e.name for e in summary.entities}
    assert "the-sink" in names
    sink_summary = next(e for e in summary.entities if e.name == "the-sink")
    assert sink_summary.events_received == 5


def test_time_travel_event_skipped(caplog):
    class BadEntity(Entity):
        def __init__(self):
            super().__init__("bad")
            self.count = 0

        def handle_event(self, event):
            self.count += 1
            if self.count == 1:
                # schedules into the past
                return [Event(Instant.Epoch, "past", target=self)]
            return None

    bad = BadEntity()
    sim = Simulation(entities=[bad])
    sim.schedule(Event(Instant.from_seconds(1), "start", target=bad))
    sim.run()
    assert bad.count == 1  # past event skipped
