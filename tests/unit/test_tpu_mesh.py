"""Edge-case unit tier for tpu/mesh.py (ISSUE 4 satellite; ISSUE 13
extended it with the partition-rule table tier).

``pad_to_multiple`` boundary inputs, single-device mesh/sharding
construction, the padded-replica truncation accounting
(``truncated_replicas``) round-tripping through ``run_ensemble``, and
the ``STATE_PARTITION_RULES`` table contract: every state leaf of the
richest model shape gets a placement, unknown leaves fail loudly.
"""

import jax
import pytest

from happysim_tpu.tpu.mesh import (
    HOST_AXIS,
    REPLICA_AXIS,
    STATE_PARTITION_RULES,
    ensemble_state_shardings,
    ensemble_state_specs,
    host_replica_mesh,
    match_partition_rules,
    pad_to_multiple,
    replica_mesh,
    replica_sharding,
    replicated_sharding,
)
from happysim_tpu.tpu.model import mm1_model


class TestPadToMultiple:
    def test_already_aligned_is_identity(self):
        assert pad_to_multiple(8, 4) == 8
        assert pad_to_multiple(4, 4) == 4
        assert pad_to_multiple(65536, 8) == 65536

    def test_zero_remainder_degenerates(self):
        assert pad_to_multiple(0, 4) == 0
        assert pad_to_multiple(0, 1) == 0

    def test_single_device_never_pads(self):
        for n in (1, 3, 5, 17):
            assert pad_to_multiple(n, 1) == n

    def test_rounds_up_not_down(self):
        assert pad_to_multiple(5, 4) == 8
        assert pad_to_multiple(9, 8) == 16
        assert pad_to_multiple(1, 8) == 8


class TestSingleDeviceMesh:
    def test_replica_mesh_single_device(self):
        mesh = replica_mesh(jax.devices("cpu")[:1])
        assert mesh.size == 1
        assert mesh.axis_names == (REPLICA_AXIS,)
        assert HOST_AXIS not in mesh.axis_names

    def test_replica_sharding_single_device(self):
        mesh = replica_mesh(jax.devices("cpu")[:1])
        sharding = replica_sharding(mesh)
        assert sharding.spec == jax.sharding.PartitionSpec(REPLICA_AXIS)
        # On one device the sharding is trivially addressable-complete.
        assert sharding.is_fully_addressable

    def test_replicated_sharding_spec_is_empty(self):
        mesh = replica_mesh(jax.devices("cpu")[:1])
        assert replicated_sharding(mesh).spec == jax.sharding.PartitionSpec()


class TestPaddedTruncationRoundTrip:
    """Replica padding + event-budget truncation through run_ensemble:
    the padded lanes are REAL simulations, so the truncation census must
    count over the padded total, and an ample budget reports zero."""

    @pytest.fixture(scope="class")
    def mesh(self):
        return replica_mesh(jax.devices("cpu")[:4])

    def test_padded_count_and_full_truncation(self, mesh):
        from happysim_tpu.tpu import run_ensemble

        # 5 requested replicas pad to 8 on the 4-device mesh; a 2-event
        # budget truncates EVERY lane, padded ones included.
        result = run_ensemble(
            mm1_model(horizon_s=20.0),
            n_replicas=5,
            seed=0,
            mesh=mesh,
            max_events=2,
        )
        assert result.n_replicas == 8
        assert result.truncated_replicas == 8

    def test_ample_budget_reports_zero_truncation(self, mesh):
        from happysim_tpu.tpu import run_ensemble

        result = run_ensemble(
            mm1_model(lam=2.0, mu=10.0, horizon_s=2.0),
            n_replicas=5,
            seed=0,
            mesh=mesh,
            max_events=128,
        )
        assert result.n_replicas == 8
        assert result.truncated_replicas == 0
        # Explicit budget skips chain; either scan flavor is fine — the
        # CI mesh-execution gate re-runs this file with HS_TPU_PALLAS=1,
        # where the supported M/M/1 shape lands on the fused kernel.
        assert result.engine_path in ("scan", "scan+pallas")


def _rich_state_keys():
    """State leaf names of the richest compiled shape: a faulted +
    telemetry + router model (fan-out with a latency edge so the
    transit registers exist, deadline so the attempt columns exist,
    packet loss so net_lost exists)."""
    import jax.numpy as jnp

    from happysim_tpu.tpu.engine import _Compiled
    from happysim_tpu.tpu.model import EnsembleModel, FaultSpec

    model = EnsembleModel(horizon_s=4.0)
    src = model.source(rate=4.0)
    first = model.server(
        service_mean=0.05,
        queue_capacity=4,
        deadline_s=1.0,
        max_retries=1,
        fault=FaultSpec(rate=0.1, mean_duration_s=0.2),
    )
    second = model.server(service_mean=0.05, queue_capacity=4)
    router = model.router(policy="round_robin")
    snk = model.sink()
    model.connect(src, router)
    model.connect(router, first, latency_s=0.01)  # -> transit registers
    model.connect(router, second)
    model.connect(first, snk, loss_p=0.01)  # -> net_lost
    model.connect(second, snk)
    model.telemetry(window_s=1.0)
    # Resilience layer (ISSUE 15) -> breaker columns + budget bucket +
    # shed counter, all of which must match a partition rule.
    model.circuit_breaker(failure_threshold=2)
    model.load_shed(policy="queue_depth", threshold=2)
    model.retry_budget(ratio=0.2)
    compiled = _Compiled(model)
    struct = jax.eval_shape(
        compiled.init_state,
        jax.ShapeDtypeStruct((2,), jnp.uint32),
        {
            "src_rate": jax.ShapeDtypeStruct((compiled.nS,), jnp.float32),
            "srv_mean": jax.ShapeDtypeStruct((compiled.nV,), jnp.float32),
        },
    )
    return tuple(struct)


class TestPartitionRules:
    """The ISSUE-13 partition-rule table: state leaf -> placement via
    pattern matching, unknown leaves fail LOUDLY (a silent
    default-to-replicated would duplicate per-replica state onto every
    device and corrupt the on-device reductions)."""

    def test_every_rich_model_leaf_has_a_rule(self):
        keys = _rich_state_keys()
        # The fixture really is the rich shape: faults, telemetry,
        # transit, attempts, router cursor, and loss all present.
        for expected in (
            "flt_start", "tel_sink_count", "tr_time", "srv_q_attempt",
            "rr_next", "net_lost", "key", "t", "events",
            "brk_state", "brk_fail_t", "brk_open_time",
            "bud_tokens", "srv_shed_dropped", "srv_budget_dropped",
        ):
            assert expected in keys, f"fixture lost the {expected} leaf"
        specs = ensemble_state_specs(keys)
        assert set(specs) == set(keys)
        replica_spec = jax.sharding.PartitionSpec(REPLICA_AXIS)
        assert all(spec == replica_spec for spec in specs.values())

    def test_unknown_leaf_fails_loudly(self):
        with pytest.raises(ValueError, match="no partition rule matches"):
            match_partition_rules("mystery_buffer")
        with pytest.raises(ValueError, match="STATE_PARTITION_RULES"):
            ensemble_state_specs(("t", "mystery_buffer"))

    def test_rules_name_the_replica_placement(self):
        # The table itself is all-replica today; the test pins that a
        # future placement string must be threaded through the builder
        # (which raises on anything it does not know).
        assert all(
            placement == "replica" for _, placement in STATE_PARTITION_RULES
        )

    def test_host_mesh_spells_both_axes(self):
        mesh = host_replica_mesh(jax.devices("cpu")[:8], n_hosts=2)
        specs = ensemble_state_specs(("t", "srv_completed"), mesh)
        expected = jax.sharding.PartitionSpec((HOST_AXIS, REPLICA_AXIS))
        assert specs["t"] == expected

    def test_shardings_bind_the_mesh(self):
        mesh = replica_mesh(jax.devices("cpu")[:4])
        shardings = ensemble_state_shardings(mesh, ("t", "tel_sink_hist"))
        for sharding in shardings.values():
            assert sharding.mesh == mesh
            assert sharding.spec == jax.sharding.PartitionSpec(REPLICA_AXIS)
