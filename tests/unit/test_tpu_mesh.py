"""Edge-case unit tier for tpu/mesh.py (ISSUE 4 satellite).

``pad_to_multiple`` boundary inputs, single-device mesh/sharding
construction, and the padded-replica truncation accounting
(``truncated_replicas``) round-tripping through ``run_ensemble``.
"""

import jax
import pytest

from happysim_tpu.tpu.mesh import (
    HOST_AXIS,
    REPLICA_AXIS,
    pad_to_multiple,
    replica_mesh,
    replica_sharding,
    replicated_sharding,
)
from happysim_tpu.tpu.model import mm1_model


class TestPadToMultiple:
    def test_already_aligned_is_identity(self):
        assert pad_to_multiple(8, 4) == 8
        assert pad_to_multiple(4, 4) == 4
        assert pad_to_multiple(65536, 8) == 65536

    def test_zero_remainder_degenerates(self):
        assert pad_to_multiple(0, 4) == 0
        assert pad_to_multiple(0, 1) == 0

    def test_single_device_never_pads(self):
        for n in (1, 3, 5, 17):
            assert pad_to_multiple(n, 1) == n

    def test_rounds_up_not_down(self):
        assert pad_to_multiple(5, 4) == 8
        assert pad_to_multiple(9, 8) == 16
        assert pad_to_multiple(1, 8) == 8


class TestSingleDeviceMesh:
    def test_replica_mesh_single_device(self):
        mesh = replica_mesh(jax.devices("cpu")[:1])
        assert mesh.size == 1
        assert mesh.axis_names == (REPLICA_AXIS,)
        assert HOST_AXIS not in mesh.axis_names

    def test_replica_sharding_single_device(self):
        mesh = replica_mesh(jax.devices("cpu")[:1])
        sharding = replica_sharding(mesh)
        assert sharding.spec == jax.sharding.PartitionSpec(REPLICA_AXIS)
        # On one device the sharding is trivially addressable-complete.
        assert sharding.is_fully_addressable

    def test_replicated_sharding_spec_is_empty(self):
        mesh = replica_mesh(jax.devices("cpu")[:1])
        assert replicated_sharding(mesh).spec == jax.sharding.PartitionSpec()


class TestPaddedTruncationRoundTrip:
    """Replica padding + event-budget truncation through run_ensemble:
    the padded lanes are REAL simulations, so the truncation census must
    count over the padded total, and an ample budget reports zero."""

    @pytest.fixture(scope="class")
    def mesh(self):
        return replica_mesh(jax.devices("cpu")[:4])

    def test_padded_count_and_full_truncation(self, mesh):
        from happysim_tpu.tpu import run_ensemble

        # 5 requested replicas pad to 8 on the 4-device mesh; a 2-event
        # budget truncates EVERY lane, padded ones included.
        result = run_ensemble(
            mm1_model(horizon_s=20.0),
            n_replicas=5,
            seed=0,
            mesh=mesh,
            max_events=2,
        )
        assert result.n_replicas == 8
        assert result.truncated_replicas == 8

    def test_ample_budget_reports_zero_truncation(self, mesh):
        from happysim_tpu.tpu import run_ensemble

        result = run_ensemble(
            mm1_model(lam=2.0, mu=10.0, horizon_s=2.0),
            n_replicas=5,
            seed=0,
            mesh=mesh,
            max_events=128,
        )
        assert result.n_replicas == 8
        assert result.truncated_replicas == 0
        assert result.engine_path == "scan"  # explicit budget skips chain
