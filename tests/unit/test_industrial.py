"""Unit tests: industrial components (OR / manufacturing building blocks).

Mirrors the reference's coverage (tests/unit/components/industrial/ and
tests/integration/industrial/) with tiny real simulations, per the
unit≈micro-integration strategy (SURVEY.md §4).
"""

import pytest

from happysim_tpu import (
    AppointmentScheduler,
    BalkingQueue,
    BatchProcessor,
    BreakdownScheduler,
    ConditionalRouter,
    ConstantLatency,
    ConveyorBelt,
    Counter,
    Event,
    FIFOQueue,
    GateController,
    InspectionStation,
    Instant,
    InventoryBuffer,
    PerishableInventory,
    PooledCycleResource,
    PreemptibleResource,
    RenegingQueuedResource,
    Server,
    Shift,
    ShiftSchedule,
    ShiftedServer,
    Simulation,
    Sink,
    SplitMerge,
)
from happysim_tpu.core.entity import Entity
from happysim_tpu.core.sim_future import SimFuture


def run_sim(entities, events, end_s=None):
    sim = Simulation(
        entities=entities,
        end_time=Instant.from_seconds(end_s) if end_s is not None else None,
    )
    sim.schedule(events)
    sim.run()
    return sim


def keepalive(until_s):
    """A primary event that holds the sim open while daemon cycles run.

    Daemon events (breakdowns, spoilage sweeps, shift changes) never hold
    the simulation open by themselves — same semantics as the reference.
    """
    return Event(Instant.from_seconds(until_s), "Keepalive", target=Counter("keepalive"))


class TestBalkingQueue:
    def test_accepts_below_threshold(self):
        policy = BalkingQueue(threshold=2, balk_probability=1.0)
        policy.push("a")
        policy.push("b")
        assert len(policy) == 2
        assert policy.balked == 0

    def test_always_balks_at_threshold(self):
        policy = BalkingQueue(threshold=1, balk_probability=1.0)
        policy.push("a")
        assert policy.push("b") is False
        assert policy.balked == 1
        assert policy.pop() == "a"

    def test_probabilistic_balk_is_seeded(self):
        def balk_count(seed):
            policy = BalkingQueue(threshold=0, balk_probability=0.5, seed=seed)
            return sum(policy.push(i) is False for i in range(100))

        assert balk_count(1) == balk_count(1)
        assert 20 < balk_count(1) < 80

    def test_server_counts_balked_as_dropped(self):
        """A balking policy inside a Server surfaces as queue drops."""
        sink = Sink()
        server = Server(
            "teller",
            service_time=ConstantLatency(1.0),
            queue_policy=BalkingQueue(threshold=1, balk_probability=1.0),
            downstream=sink,
        )
        events = [Event(Instant.Epoch, "Customer", target=server) for _ in range(4)]
        run_sim([server, sink], events)
        # 1 in service, 1 queued, 2 balk (depth already at threshold).
        assert server.queue.dropped == 2
        assert sink.events_received == 2


class _ImpatientDesk(RenegingQueuedResource):
    def __init__(self, name, reneged_target, patience_s):
        super().__init__(name, reneged_target=reneged_target, default_patience_s=patience_s)
        self.service_time_s = 1.0
        self.active = 0

    def worker_has_capacity(self):
        return self.active < 1

    def handle_served_event(self, event):
        self.active += 1
        try:
            yield self.service_time_s
        finally:
            self.active -= 1
        return [self.forward(event, self.sink)]


class TestReneging:
    def test_impatient_items_renege(self):
        served_sink = Sink("served")
        reneged_counter = Counter("reneged")
        desk = _ImpatientDesk("desk", reneged_counter, patience_s=0.5)
        desk.sink = served_sink
        events = [Event(Instant.Epoch, "Customer", target=desk) for _ in range(3)]
        run_sim([desk, served_sink, reneged_counter], events)
        # First starts immediately (wait 0); the rest are dequeued at t=1.0
        # having waited past their 0.5s patience.
        assert desk.served == 1
        assert desk.reneged == 2
        assert reneged_counter.count == 2
        assert desk.reneging_stats().served == 1

    def test_patient_items_all_served(self):
        served_sink = Sink("served")
        desk = _ImpatientDesk("desk", None, patience_s=100.0)
        desk.sink = served_sink
        events = [Event(Instant.Epoch, "Customer", target=desk) for _ in range(3)]
        run_sim([desk, served_sink], events)
        assert desk.served == 3
        assert desk.reneged == 0


class TestConveyor:
    def test_fixed_transit_delay(self):
        sink = Sink()
        belt = ConveyorBelt("belt", sink, transit_time_s=2.5)
        run_sim([belt, sink], [Event(Instant.Epoch, "Part", target=belt)])
        assert sink.events_received == 1
        assert sink.completion_times[0].to_seconds() == pytest.approx(2.5)
        assert belt.stats().items_transported == 1

    def test_capacity_rejects_overflow(self):
        sink = Sink()
        belt = ConveyorBelt("belt", sink, transit_time_s=1.0, capacity=2)
        events = [Event(Instant.Epoch, "Part", target=belt) for _ in range(3)]
        run_sim([belt, sink], events)
        assert belt.rejected == 1
        assert sink.events_received == 2


class TestInspection:
    def test_all_pass(self):
        passed, failed = Sink("pass"), Sink("fail")
        station = InspectionStation(
            "qa", passed, failed, inspection_time_s=0.1, pass_rate=1.0
        )
        events = [Event(Instant.Epoch, "Part", target=station) for _ in range(5)]
        run_sim([station, passed, failed], events)
        assert passed.events_received == 5
        assert failed.events_received == 0
        assert station.stats().inspected == 5

    def test_all_fail(self):
        passed, failed = Sink("pass"), Sink("fail")
        station = InspectionStation(
            "qa", passed, failed, inspection_time_s=0.1, pass_rate=0.0
        )
        events = [Event(Instant.Epoch, "Part", target=station) for _ in range(5)]
        run_sim([station, passed, failed], events)
        assert failed.events_received == 5

    def test_seeded_mix_reproducible(self):
        def outcome(seed):
            passed, failed = Sink("pass"), Sink("fail")
            station = InspectionStation(
                "qa", passed, failed, inspection_time_s=0.01, pass_rate=0.7, seed=seed
            )
            events = [Event(Instant.Epoch, "Part", target=station) for _ in range(50)]
            run_sim([station, passed, failed], events)
            return passed.events_received

        assert outcome(3) == outcome(3)
        assert 20 < outcome(3) < 50


class TestBatchProcessor:
    def test_flush_on_full_batch(self):
        sink = Sink()
        batcher = BatchProcessor("oven", sink, batch_size=3, process_time_s=2.0)
        events = [Event(Instant.Epoch, "Loaf", target=batcher) for _ in range(3)]
        run_sim([batcher, sink], events)
        assert batcher.batches_processed == 1
        assert batcher.items_processed == 3
        assert all(t.to_seconds() == pytest.approx(2.0) for t in sink.completion_times)

    def test_flush_on_timeout(self):
        sink = Sink()
        batcher = BatchProcessor(
            "oven", sink, batch_size=10, process_time_s=1.0, timeout_s=2.0
        )
        events = [Event(Instant.Epoch, "Loaf", target=batcher) for _ in range(3)]
        run_sim([batcher, sink], events)
        assert batcher.timeouts == 1
        assert batcher.items_processed == 3
        # Timeout at t=2, plus 1s processing.
        assert all(t.to_seconds() == pytest.approx(3.0) for t in sink.completion_times)

    def test_full_batch_cancels_timeout(self):
        sink = Sink()
        batcher = BatchProcessor(
            "oven", sink, batch_size=2, process_time_s=0.5, timeout_s=10.0
        )
        events = [Event(Instant.Epoch, "Loaf", target=batcher) for _ in range(2)]
        sim = run_sim([batcher, sink], events)
        assert batcher.timeouts == 0
        assert batcher.batches_processed == 1
        # The cancelled timeout must not hold the simulation open.
        assert sim.clock.now.to_seconds() < 5.0


class TestShiftSchedule:
    def test_capacity_lookup_and_transitions(self):
        schedule = ShiftSchedule(
            [Shift(0, 8, 2), Shift(8, 16, 5)], default_capacity=1
        )
        assert schedule.capacity_at(0.0) == 2
        assert schedule.capacity_at(8.0) == 5
        assert schedule.capacity_at(20.0) == 1
        assert schedule.transition_times() == [0, 8, 16]
        assert schedule.next_transition_after(8.0) == 16

    def test_shift_opening_drains_queued_work(self):
        """Work arriving while capacity is 0 starts when the shift opens."""
        sink = Sink()
        server = ShiftedServer(
            "desk",
            ShiftSchedule([Shift(5, 100, 1)], default_capacity=0),
            service_time_s=1.0,
            downstream=sink,
        )
        sim = Simulation(entities=[server, sink])
        sim.schedule(server.start_events())
        sim.schedule(
            [
                Event(Instant.Epoch, "Job", target=server),
                Event(Instant.from_seconds(1.0), "Job", target=server),
                keepalive(10.0),
            ]
        )
        sim.run()
        assert server.processed == 2
        done = sorted(t.to_seconds() for t in sink.completion_times)
        assert done == pytest.approx([6.0, 7.0])

    def test_lazy_arming_without_start_events(self):
        sink = Sink()
        server = ShiftedServer(
            "desk",
            ShiftSchedule([Shift(0, 100, 1)], default_capacity=0),
            service_time_s=1.0,
            downstream=sink,
        )
        run_sim([server, sink], [Event(Instant.Epoch, "Job", target=server)])
        assert server.processed == 1


class TestBreakdown:
    def test_cycle_accounting(self):
        workstation = Counter("machine")
        scheduler = BreakdownScheduler(
            "breaker",
            workstation,
            mean_time_to_failure_s=5.0,
            mean_repair_time_s=1.0,
            seed=7,
        )
        sim = Simulation(
            entities=[workstation, scheduler], end_time=Instant.from_seconds(200)
        )
        sim.schedule([scheduler.start_event(), keepalive(200.0)])
        sim.run()
        stats = scheduler.stats()
        assert stats.breakdown_count > 10
        assert stats.total_downtime_s > 0
        assert 0.5 < stats.availability < 1.0

    def test_broken_flag_follows_state(self):
        target = Counter("machine")
        scheduler = BreakdownScheduler("breaker", target, seed=1)
        assert target._broken is False
        sim = Simulation(entities=[target, scheduler], end_time=Instant.from_seconds(500))
        sim.schedule([scheduler.start_event(), keepalive(500.0)])
        sim.run()
        assert target._broken == scheduler.is_down

    def test_seeded_reproducibility(self):
        def count(seed):
            target = Counter("m")
            sched = BreakdownScheduler("b", target, 10.0, 2.0, seed=seed)
            sim = Simulation(entities=[target, sched], end_time=Instant.from_seconds(300))
            sim.schedule([sched.start_event(), keepalive(300.0)])
            sim.run()
            return sched.breakdown_count

        assert count(42) == count(42)


class TestInventory:
    def test_consume_and_fulfill(self):
        fulfilled = Counter("fulfilled")
        buffer = InventoryBuffer("store", initial_stock=10, reorder_point=0, downstream=fulfilled)
        events = [Event(Instant.Epoch, "Demand", target=buffer) for _ in range(4)]
        run_sim([buffer, fulfilled], events)
        assert buffer.stock == 6
        assert fulfilled.count == 4
        assert buffer.stats().fill_rate == 1.0

    def test_stockout_routing(self):
        stockouts = Counter("stockouts")
        buffer = InventoryBuffer(
            "store", initial_stock=1, reorder_point=0, order_quantity=5,
            lead_time_s=100.0, stockout_target=stockouts,
        )
        events = [
            Event(Instant.from_seconds(i * 0.1), "Demand", target=buffer)
            for i in range(3)
        ]
        run_sim([buffer, stockouts], events, end_s=1.0)
        assert buffer.stockouts == 2
        assert stockouts.count == 2
        assert buffer.stats().fill_rate == pytest.approx(1 / 3)

    def test_reorder_replenishes_after_lead_time(self):
        buffer = InventoryBuffer(
            "store", initial_stock=3, reorder_point=2, order_quantity=10, lead_time_s=5.0
        )
        events = [
            Event(Instant.Epoch, "Demand", target=buffer),
            Event(Instant.from_seconds(1.0), "Demand", target=buffer),
        ]
        run_sim([buffer], events)
        # First consume drops stock to 2 <= s, placing one order of 10.
        assert buffer.reorders == 1
        assert buffer.stock == 1 + 10
        assert buffer.items_replenished == 10

    def test_quantity_from_context(self):
        buffer = InventoryBuffer("store", initial_stock=10, reorder_point=0)
        event = Event(Instant.Epoch, "Demand", target=buffer, context={"quantity": 7})
        run_sim([buffer], [event])
        assert buffer.stock == 3


class TestPerishableInventory:
    def test_spoilage_sweep(self):
        waste = Counter("waste")
        inventory = PerishableInventory(
            "fridge",
            initial_stock=10,
            shelf_life_s=5.0,
            spoilage_check_interval_s=2.0,
            reorder_point=0,
            waste_target=waste,
            initial_stock_time_s=0.0,
        )
        sim = Simulation(
            entities=[inventory, waste], end_time=Instant.from_seconds(10)
        )
        sim.schedule([inventory.start_event(), keepalive(10.0)])
        sim.run()
        # The t=6 sweep finds the t=0 batch older than 5s.
        assert inventory.total_spoiled == 10
        assert waste.count == 1
        assert inventory.stock == 0
        assert inventory.stats().waste_rate == 1.0

    def test_fifo_consumption_spares_fresh_stock(self):
        inventory = PerishableInventory(
            "fridge",
            initial_stock=5,
            shelf_life_s=100.0,
            spoilage_check_interval_s=1000.0,
            reorder_point=2,
            order_quantity=5,
            lead_time_s=1.0,
            initial_stock_time_s=0.0,
        )
        events = [
            Event(Instant.from_seconds(i), "Demand", target=inventory, context={})
            for i in range(4)
        ]
        run_sim([inventory], events, end_s=10.0)
        assert inventory.total_consumed == 4
        # Reorder fired when stock hit 2; replenishment of 5 arrived.
        assert inventory.reorders == 1
        assert inventory.stock == 1 + 5

    def test_consume_prefers_oldest_batch(self):
        inventory = PerishableInventory(
            "fridge", initial_stock=3, shelf_life_s=5.0,
            spoilage_check_interval_s=3.0, reorder_point=0, initial_stock_time_s=0.0,
        )
        inventory._batches.append((Instant.from_seconds(2.0), 3))
        sim = Simulation(entities=[inventory], end_time=Instant.from_seconds(7.0))
        sim.schedule([inventory.start_event(), keepalive(7.0)])
        sim.schedule(Event(Instant.from_seconds(1.0), "Demand", target=inventory))
        sim.run()
        # The t=1 consume drains one unit of the t=0 batch (FIFO). At the
        # t=6 sweep, the t=0 leftovers (age 6 >= 5) spoil; the t=2 batch
        # (age 4) survives.
        assert inventory.total_consumed == 1
        assert inventory.total_spoiled == 2
        assert inventory.stock == 3


class TestAppointments:
    def test_arrivals_at_appointment_times(self):
        sink = Sink()
        scheduler = AppointmentScheduler(
            "book", sink, appointments_s=[1.0, 2.0, 3.5], no_show_rate=0.0
        )
        sim = Simulation(entities=[scheduler, sink])
        sim.schedule(scheduler.start_events())
        sim.run()
        assert sink.events_received == 3
        assert [t.to_seconds() for t in sink.completion_times] == pytest.approx(
            [1.0, 2.0, 3.5]
        )

    def test_all_no_shows(self):
        sink = Sink()
        scheduler = AppointmentScheduler(
            "book", sink, appointments_s=[1.0, 2.0], no_show_rate=1.0
        )
        sim = Simulation(entities=[scheduler, sink])
        sim.schedule(scheduler.start_events())
        sim.run()
        assert sink.events_received == 0
        assert scheduler.stats().no_shows == 2


class TestConditionalRouter:
    def test_first_match_wins(self):
        a, b = Counter("a"), Counter("b")
        router = ConditionalRouter(
            "router",
            routes=[
                (lambda e: e.context.get("size", 0) > 10, a),
                (lambda e: True, b),
            ],
        )
        events = [
            Event(Instant.Epoch, "Job", target=router, context={"size": 20}),
            Event(Instant.Epoch, "Job", target=router, context={"size": 5}),
        ]
        run_sim([router, a, b], events)
        assert a.count == 1
        assert b.count == 1
        assert router.stats().by_target == {"a": 1, "b": 1}

    def test_unmatched_drops_without_default(self):
        a = Counter("a")
        router = ConditionalRouter("router", routes=[(lambda e: False, a)])
        run_sim([router, a], [Event(Instant.Epoch, "Job", target=router)])
        assert router.dropped == 1
        assert a.count == 0

    def test_by_context_field(self):
        express, standard = Counter("express"), Counter("standard")
        router = ConditionalRouter.by_context_field(
            "router", "tier", {"gold": express}, default=standard
        )
        events = [
            Event(Instant.Epoch, "Order", target=router, context={"tier": "gold"}),
            Event(Instant.Epoch, "Order", target=router, context={"tier": "basic"}),
        ]
        run_sim([router, express, standard], events)
        assert express.count == 1
        assert standard.count == 1


class TestPooledCycle:
    def test_cycle_timing_and_queueing(self):
        sink = Sink()
        pool = PooledCycleResource("washers", pool_size=2, cycle_time_s=1.0, downstream=sink)
        events = [Event(Instant.Epoch, "Load", target=pool) for _ in range(3)]
        run_sim([pool, sink], events)
        done = sorted(t.to_seconds() for t in sink.completion_times)
        assert done == pytest.approx([1.0, 1.0, 2.0])
        assert pool.completed == 3
        assert pool.available == 2

    def test_bounded_queue_rejects(self):
        sink = Sink()
        pool = PooledCycleResource(
            "washers", pool_size=1, cycle_time_s=1.0, downstream=sink, queue_capacity=1
        )
        events = [Event(Instant.Epoch, "Load", target=pool) for _ in range(4)]
        run_sim([pool, sink], events)
        assert pool.rejected == 2
        assert pool.completed == 2


class TestGateController:
    def test_closed_gate_queues_then_flushes(self):
        sink = Sink()
        gate = GateController(
            "gate", sink, schedule=[(2.0, 4.0)], initially_open=False
        )
        sim = Simulation(entities=[gate, sink])
        sim.schedule(gate.start_events())
        sim.schedule(
            [
                Event(Instant.Epoch, "Car", target=gate),
                Event(Instant.from_seconds(1.0), "Car", target=gate),
                Event(Instant.from_seconds(3.0), "Car", target=gate),
                Event(Instant.from_seconds(5.0), "Car", target=gate),
            ]
        )
        sim.run()
        stats = gate.stats()
        # Two queued pre-open flush at t=2; the t=3 arrival passes through;
        # the t=5 arrival queues against the closed gate.
        assert stats.passed_through == 3
        assert stats.queued_while_closed == 3
        assert gate.queue_depth == 1
        assert sorted(t.to_seconds() for t in sink.completion_times) == pytest.approx(
            [2.0, 2.0, 3.0]
        )

    def test_bounded_queue_rejects_when_closed(self):
        sink = Sink()
        gate = GateController("gate", sink, initially_open=False, queue_capacity=1)
        events = [Event(Instant.Epoch, "Car", target=gate) for _ in range(3)]
        run_sim([gate, sink], events)
        assert gate.rejected == 2


class _FutureWorker(Entity):
    """Resolves ``reply_future`` with its name after a service delay."""

    def __init__(self, name, delay_s):
        super().__init__(name)
        self.delay_s = delay_s

    def handle_event(self, event):
        yield self.delay_s
        event.context["reply_future"].resolve(self.name)
        return None


class TestSplitMerge:
    def test_fan_out_and_merge(self):
        sink = Sink()
        workers = [_FutureWorker("w0", 1.0), _FutureWorker("w1", 3.0)]
        splitter = SplitMerge("split", workers, sink)
        run_sim(
            [splitter, sink, *workers],
            [Event(Instant.Epoch, "Task", target=splitter)],
        )
        assert sink.events_received == 1
        # Merge completes when the slowest branch resolves.
        assert sink.completion_times[0].to_seconds() == pytest.approx(3.0)
        assert splitter.stats().merges_completed == 1

    def test_merged_context_carries_sub_results(self):
        collected = {}

        class Collector(Entity):
            def handle_event(self, event):
                collected["sub_results"] = event.context.get("sub_results")
                return None

        collector = Collector("collector")
        workers = [_FutureWorker("w0", 0.5), _FutureWorker("w1", 0.1)]
        splitter = SplitMerge("split", workers, collector)
        run_sim(
            [splitter, collector, *workers],
            [Event(Instant.Epoch, "Task", target=splitter)],
        )
        assert collected["sub_results"] == ["w0", "w1"]


class TestPreemptibleResource:
    def test_immediate_grant_and_release(self):
        resource = PreemptibleResource("crane", capacity=2)
        future = resource.acquire(1, priority=1.0)
        assert future.is_resolved
        grant = future._value
        assert resource.available == 1
        grant.release()
        assert resource.available == 2
        grant.release()  # idempotent
        assert resource.stats().releases == 1

    def test_preemption_evicts_weakest_holder(self):
        resource = PreemptibleResource("crane", capacity=1)
        preempted = []
        low = resource.acquire(1, priority=5.0, on_preempt=lambda: preempted.append("low"))
        assert low.is_resolved
        high = resource.acquire(1, priority=1.0, preempt=True)
        assert high.is_resolved
        assert preempted == ["low"]
        assert low._value.preempted
        assert resource.preemptions == 1

    def test_no_preempt_queues_instead(self):
        resource = PreemptibleResource("crane", capacity=1)
        holder = resource.acquire(1, priority=5.0)
        waiter = resource.acquire(1, priority=1.0, preempt=False)
        assert not waiter.is_resolved
        assert resource.contentions == 1
        holder._value.release()
        assert waiter.is_resolved

    def test_equal_priority_cannot_preempt(self):
        resource = PreemptibleResource("crane", capacity=1)
        first = resource.acquire(1, priority=2.0)
        second = resource.acquire(1, priority=2.0, preempt=True)
        assert first.is_resolved
        assert not second.is_resolved
        assert resource.preemptions == 0

    def test_waiters_wake_in_priority_order(self):
        resource = PreemptibleResource("crane", capacity=1)
        holder = resource.acquire(1, priority=0.0)
        low = resource.acquire(1, priority=9.0, preempt=False)
        high = resource.acquire(1, priority=1.0, preempt=False)
        holder._value.release()
        assert high.is_resolved
        assert not low.is_resolved

    def test_generator_integration(self):
        """Preemption mid-service: the preempted job observes its grant."""
        log = []

        class CraneUser(Entity):
            def __init__(self, name, resource, priority, hold_s):
                super().__init__(name)
                self.resource = resource
                self.priority = priority
                self.hold_s = hold_s

            def handle_event(self, event):
                grant = yield self.resource.acquire(
                    1, priority=self.priority,
                    on_preempt=lambda: log.append(f"{self.name}-preempted"),
                )
                yield self.hold_s
                if not grant.preempted:
                    grant.release()
                    log.append(f"{self.name}-done")
                return None

        resource = PreemptibleResource("crane", capacity=1)
        routine = CraneUser("routine", resource, priority=5.0, hold_s=10.0)
        urgent = CraneUser("urgent", resource, priority=1.0, hold_s=1.0)
        run_sim(
            [resource, routine, urgent],
            [
                Event(Instant.Epoch, "Job", target=routine),
                Event(Instant.from_seconds(2.0), "Job", target=urgent),
            ],
        )
        assert log == ["routine-preempted", "urgent-done"]
