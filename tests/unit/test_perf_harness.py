"""Smoke tests for the perf harness: scenarios run at tiny scale, the
report renders, and checkpoints round-trip."""

import json

from tests.perf.runner import (
    PerfResult,
    load_reference,
    print_report,
    run_scenario,
)
from tests.perf.scenarios import SCENARIOS


def test_all_scenarios_run_at_tiny_scale(tmp_path):
    results = []
    for name, scenario in SCENARIOS.items():
        result = run_scenario(scenario, scale=0.01)
        assert result.name == name
        assert result.wall_clock_s >= 0
        results.append(result)
    # Speed scenarios actually processed work.
    by_name = {r.name: r for r in results}
    assert by_name["throughput"].events_processed > 1000
    assert by_name["large_heap"].events_processed == 1000
    print_report(results, baseline=None, reference=load_reference())


def test_reference_numbers_present():
    reference = load_reference()
    assert reference is not None
    assert reference["throughput"]["events_per_second"] == 134580


def test_checkpoint_roundtrip(tmp_path, monkeypatch):
    import tests.perf.runner as runner

    monkeypatch.setattr(runner, "DATA_DIR", tmp_path)
    results = [
        PerfResult(
            name="throughput",
            events_processed=1000,
            wall_clock_s=0.01,
            events_per_second=100000.0,
            peak_memory_mb=1.0,
        )
    ]
    path = runner.save_checkpoint(results)
    assert path.exists()
    data = runner.load_checkpoint(path)
    assert data["results"]["throughput"]["events_per_second"] == 100000.0
    assert path in runner.list_checkpoints()
    payload = json.loads(path.read_text())
    assert "system" in payload and "git_hash" in payload
