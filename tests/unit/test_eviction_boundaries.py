"""Boundary conditions of every cache-eviction policy.

The consolidated datastore tests cover the happy paths; these target the
edges where eviction policies classically go wrong: empty evict, single
key, re-insert of an evicted key, remove-then-evict bookkeeping, tie
breaks, segment-bound demotions (SLRU/2Q), hand wraparound (CLOCK), and
expiry boundaries (TTL).

Reference analogue: the per-policy cases in
``happysimulator/tests/unit/test_eviction_policies.py``.
"""

from __future__ import annotations

import pytest

from happysim_tpu.components.datastore.eviction_policies import (
    ClockEviction,
    FIFOEviction,
    LFUEviction,
    LRUEviction,
    RandomEviction,
    SampledLRUEviction,
    SLRUEviction,
    TTLEviction,
    TwoQueueEviction,
)

ALL_POLICIES = [
    LRUEviction,
    LFUEviction,
    FIFOEviction,
    lambda: RandomEviction(seed=7),
    SLRUEviction,
    lambda: SampledLRUEviction(sample_size=3, seed=7),
    ClockEviction,
    TwoQueueEviction,
    lambda: TTLEviction(ttl=10.0, clock_func=lambda: 0.0),
]

IDS = [
    "lru", "lfu", "fifo", "random", "slru", "sampled_lru", "clock", "2q", "ttl",
]


@pytest.mark.parametrize("factory", ALL_POLICIES, ids=IDS)
class TestCommonBoundaries:
    def test_evict_on_empty_returns_none(self, factory):
        assert factory().evict() is None

    def test_single_key_evicts_then_empty(self, factory):
        policy = factory()
        policy.on_insert("only")
        assert policy.evict() == "only"
        assert policy.evict() is None

    def test_evicted_key_is_forgotten(self, factory):
        """After eviction the policy holds no record: a later evict must
        never return the same key twice."""
        policy = factory()
        for key in ("a", "b", "c"):
            policy.on_insert(key)
        victims = [policy.evict() for _ in range(3)]
        assert sorted(victims) == ["a", "b", "c"]
        assert policy.evict() is None

    def test_remove_makes_key_unevictable(self, factory):
        policy = factory()
        policy.on_insert("a")
        policy.on_insert("b")
        policy.on_remove("a")
        assert policy.evict() == "b"
        assert policy.evict() is None

    def test_remove_unknown_key_is_noop(self, factory):
        policy = factory()
        policy.on_insert("a")
        policy.on_remove("ghost")
        assert policy.evict() == "a"

    def test_access_unknown_key_is_noop(self, factory):
        policy = factory()
        policy.on_access("ghost")
        assert policy.evict() is None

    def test_reinsert_after_eviction_is_fresh(self, factory):
        policy = factory()
        policy.on_insert("a")
        policy.evict()
        policy.on_insert("a")
        assert policy.evict() == "a"

    def test_clear_empties_all_bookkeeping(self, factory):
        policy = factory()
        for key in ("a", "b"):
            policy.on_insert(key)
        policy.on_access("a")
        policy.clear()
        assert policy.evict() is None

    def test_duplicate_insert_does_not_double_track(self, factory):
        policy = factory()
        policy.on_insert("a")
        policy.on_insert("a")
        assert policy.evict() == "a"
        assert policy.evict() is None


class TestLRUOrder:
    def test_access_refreshes_recency(self):
        policy = LRUEviction()
        for key in ("a", "b", "c"):
            policy.on_insert(key)
        policy.on_access("a")  # a becomes most recent
        assert policy.evict() == "b"
        assert policy.evict() == "c"
        assert policy.evict() == "a"

    def test_reinsert_refreshes_recency(self):
        policy = LRUEviction()
        policy.on_insert("a")
        policy.on_insert("b")
        policy.on_insert("a")  # upsert counts as a touch
        assert policy.evict() == "b"


class TestLFUTies:
    def test_frequency_orders_victims(self):
        policy = LFUEviction()
        for key in ("a", "b"):
            policy.on_insert(key)
        policy.on_access("a")
        assert policy.evict() == "b"

    def test_insertion_order_breaks_frequency_ties(self):
        policy = LFUEviction()
        for key in ("x", "y", "z"):
            policy.on_insert(key)  # all count 0
        assert policy.evict() == "x"
        assert policy.evict() == "y"

    def test_evicted_key_restarts_at_zero(self):
        policy = LFUEviction()
        policy.on_insert("a")
        for _ in range(5):
            policy.on_access("a")
        policy.on_insert("b")
        assert policy.evict() == "b"  # b is colder
        policy.on_insert("b")
        policy.on_access("b")
        policy.on_insert("c")
        assert policy.evict() == "c"


class TestFIFOOrder:
    def test_access_does_not_refresh(self):
        policy = FIFOEviction()
        policy.on_insert("a")
        policy.on_insert("b")
        policy.on_access("a")  # FIFO ignores touches
        assert policy.evict() == "a"


class TestTTLBoundaries:
    def test_exactly_at_ttl_is_not_expired(self):
        now = {"t": 0.0}
        policy = TTLEviction(ttl=10.0, clock_func=lambda: now["t"])
        policy.on_insert("a")
        now["t"] = 10.0  # age == ttl: strictly-greater contract
        assert not policy.is_expired("a")
        now["t"] = 10.0000001
        assert policy.is_expired("a")

    def test_expired_keys_evict_before_fresh_ones(self):
        now = {"t": 0.0}
        policy = TTLEviction(ttl=5.0, clock_func=lambda: now["t"])
        policy.on_insert("old")
        now["t"] = 6.0
        policy.on_insert("fresh")
        assert policy.evict() == "old"

    def test_no_expired_falls_back_to_insertion_order(self):
        policy = TTLEviction(ttl=100.0, clock_func=lambda: 0.0)
        policy.on_insert("first")
        policy.on_insert("second")
        assert policy.evict() == "first"

    def test_get_expired_keys_lists_all(self):
        now = {"t": 0.0}
        policy = TTLEviction(ttl=1.0, clock_func=lambda: now["t"])
        policy.on_insert("a")
        policy.on_insert("b")
        now["t"] = 2.0
        policy.on_insert("c")
        assert sorted(policy.get_expired_keys()) == ["a", "b"]


class TestSLRUSegments:
    def test_one_touch_keys_never_displace_working_set(self):
        policy = SLRUEviction(protected_ratio=0.5)
        policy.on_insert("hot")
        policy.on_access("hot")  # promoted to protected
        for i in range(5):  # a scan of one-touch keys
            policy.on_insert(f"scan{i}")
        victims = [policy.evict() for _ in range(5)]
        assert "hot" not in victims

    def test_promotion_demotes_protected_lru_at_bound(self):
        policy = SLRUEviction(protected_ratio=0.5)
        for key in ("a", "b", "c", "d"):
            policy.on_insert(key)
        policy.on_access("a")  # protected: [a]
        policy.on_access("b")  # max_protected = 2 -> protected: [a, b]
        policy.on_access("c")  # over bound: a demotes to probationary
        assert policy.protected_size <= 2
        # a went back to probationary, so it is evictable before b/c.
        victims = [policy.evict(), policy.evict()]
        assert "a" in victims

    def test_protected_exhausts_after_probationary(self):
        policy = SLRUEviction()
        policy.on_insert("p")
        policy.on_access("p")
        assert policy.probationary_size == 0
        assert policy.evict() == "p"  # falls back to protected


class TestClockHand:
    def test_second_chance_spares_referenced_key(self):
        policy = ClockEviction()
        for key in ("a", "b", "c"):
            policy.on_insert(key)
        # All ref bits are set on insert; first sweep clears them, so the
        # first victim is the first unreferenced key the hand meets.
        first = policy.evict()
        policy.on_access("b") if first != "b" else policy.on_access("c")
        second = policy.evict()
        assert second != first
        assert policy.size == 1

    def test_hand_stays_valid_after_remove(self):
        policy = ClockEviction()
        for key in ("a", "b", "c", "d"):
            policy.on_insert(key)
        policy.evict()
        policy.on_remove("c") if policy.size and "c" in policy._ref_bits else None
        # Whatever remains must still evict cleanly to empty.
        drained = []
        while policy.size:
            drained.append(policy.evict())
        assert len(drained) == len(set(drained))
        assert policy.evict() is None

    def test_all_referenced_still_terminates(self):
        policy = ClockEviction()
        for key in ("a", "b"):
            policy.on_insert(key)
        policy.on_access("a")
        policy.on_access("b")
        assert policy.evict() in ("a", "b")


class TestTwoQueue:
    def test_one_hit_wonders_wash_out_of_kin(self):
        policy = TwoQueueEviction(kin_ratio=0.5)
        policy.on_insert("hot")
        policy.on_access("hot")  # promoted to Am
        for i in range(4):
            policy.on_insert(f"cold{i}")
        victims = [policy.evict() for _ in range(4)]
        assert "hot" not in victims

    def test_promotion_requires_second_touch(self):
        policy = TwoQueueEviction(kin_ratio=0.25)
        policy.on_insert("once")
        policy.on_insert("twice")
        policy.on_access("twice")
        assert policy.evict() == "once"  # still in Kin; "twice" is in Am

    def test_am_lru_order(self):
        policy = TwoQueueEviction(kin_ratio=0.25)
        for key in ("a", "b"):
            policy.on_insert(key)
            policy.on_access(key)  # both in Am
        policy.on_access("a")  # a most recent
        assert policy.evict() == "b"


class TestSampledLRU:
    def test_small_population_degenerates_to_exact_lru(self):
        policy = SampledLRUEviction(sample_size=10, seed=1)
        for key in ("a", "b", "c"):
            policy.on_insert(key)
        policy.on_access("a")
        # Sample covers the whole population: exact LRU victim.
        assert policy.evict() == "b"

    def test_seeded_runs_reproduce(self):
        def run():
            policy = SampledLRUEviction(sample_size=2, seed=42)
            for i in range(10):
                policy.on_insert(f"k{i}")
            return [policy.evict() for _ in range(10)]

        assert run() == run()


class TestRandomEviction:
    def test_seeded_runs_reproduce(self):
        def run():
            policy = RandomEviction(seed=5)
            for i in range(8):
                policy.on_insert(f"k{i}")
            return [policy.evict() for _ in range(8)]

        assert run() == run()

    def test_every_key_eventually_evicted_once(self):
        policy = RandomEviction(seed=11)
        keys = {f"k{i}" for i in range(6)}
        for key in keys:
            policy.on_insert(key)
        assert {policy.evict() for _ in range(6)} == keys
