"""Parallel runtime: equivalence oracles, links, determinism, runner.

Mirrors the reference's key patterns
(``tests/integration/test_parallel_simulation.py:99,254,295``):
single-partition ≡ plain Simulation, deterministic re-runs, and generator
continuity across windows.
"""

import pytest

from happysim_tpu import (
    ConstantLatency,
    Duration,
    Entity,
    Event,
    Instant,
    Server,
    Simulation,
    Sink,
    Source,
)
from happysim_tpu.parallel import (
    ParallelRunner,
    ParallelSimulation,
    PartitionLink,
    PartitionValidationError,
    RunConfig,
    SimulationPartition,
)


class Relay(Entity):
    """Forwards everything to a (possibly remote) target."""

    def __init__(self, name, target):
        super().__init__(name)
        self.target = target
        self.events_received = 0

    def handle_event(self, event):
        self.events_received += 1
        return [self.forward(event, self.target)]


def build_mm1(seed: int = 0, rate: float = 50.0) -> Simulation:
    """Top-level so ProcessPoolExecutor can pickle it."""
    sink = Sink()
    server = Server(
        "server", service_time=ConstantLatency(0.01), downstream=sink
    )
    source = Source.poisson(rate=rate, target=server, stop_after=5.0, seed=seed)
    sim = Simulation(sources=[source], entities=[server, sink], end_time=Instant.from_seconds(20))
    sim.harvest_artifacts = lambda: {"received": sink.events_received}
    return sim


class TestSinglePartitionEquivalence:
    def _world(self):
        sink = Sink()
        server = Server("server", service_time=ConstantLatency(0.02), downstream=sink)
        source = Source.constant(rate=20.0, target=server, stop_after=2.0)
        return sink, server, source

    def test_matches_plain_simulation(self):
        sink_a, server_a, source_a = self._world()
        plain = Simulation(
            sources=[source_a], entities=[server_a, sink_a], end_time=Instant.from_seconds(10)
        )
        plain.run()

        sink_b, server_b, source_b = self._world()
        with pytest.warns(UserWarning):
            parallel = ParallelSimulation(
                [
                    SimulationPartition(
                        "only", entities=[server_b, sink_b], sources=[source_b]
                    )
                ],
                end_time=Instant.from_seconds(10),
            )
        parallel.run()

        assert sink_b.events_received == sink_a.events_received == 40
        assert sink_b.latencies_s == sink_a.latencies_s


class TestCoordinatedPartitions:
    def _linked_world(self, loss=0.0, seed=None):
        sink = Sink("remote-sink")
        relay_target = sink
        relay = Relay("relay", relay_target)
        source = Source.constant(rate=10.0, target=relay, stop_after=1.95)
        part_a = SimulationPartition("A", entities=[relay], sources=[source])
        part_b = SimulationPartition("B", entities=[sink])
        link = PartitionLink(
            "A", "B", min_latency=Duration.from_seconds(0.1), packet_loss=loss, seed=seed
        )
        return sink, relay, ParallelSimulation(
            [part_a, part_b], links=[link], end_time=Instant.from_seconds(10)
        )

    def test_cross_partition_events_arrive_with_link_latency(self):
        sink, relay, parallel = self._linked_world()
        summary = parallel.run()
        assert relay.events_received == 19
        assert sink.events_received == 19
        assert summary.cross_partition_events == 19
        # Arrival time = send time + link latency (0.1s).
        first = min(t.to_seconds() for t in sink.completion_times)
        assert first == pytest.approx(0.2)  # sent at 0.1, +0.1 link

    def test_deterministic_rerun(self):
        sink1, _, p1 = self._linked_world(loss=0.3, seed=7)
        p1.run()
        sink2, _, p2 = self._linked_world(loss=0.3, seed=7)
        p2.run()
        assert sink1.events_received == sink2.events_received
        assert [t.nanoseconds for t in sink1.completion_times] == [
            t.nanoseconds for t in sink2.completion_times
        ]

    def test_packet_loss_drops(self):
        sink, _, parallel = self._linked_world(loss=0.5, seed=3)
        summary = parallel.run()
        assert 0 < sink.events_received < 19
        assert summary.dropped_events == 19 - sink.events_received

    def test_generator_spans_windows(self):
        """A generator process sleeping longer than the window survives it."""
        done = []

        class Sleeper(Entity):
            def handle_event(self, event):
                yield 0.55  # > 5 windows of 0.1
                done.append(self.now.to_seconds())

        sleeper = Sleeper("sleeper")
        sink = Sink()
        relay = Relay("relay", sink)
        part_a = SimulationPartition("A", entities=[sleeper, relay])
        part_b = SimulationPartition("B", entities=[sink])
        link = PartitionLink("A", "B", min_latency=Duration.from_seconds(0.1))
        parallel = ParallelSimulation(
            [part_a, part_b], links=[link], end_time=Instant.from_seconds(2)
        )
        parallel._runtimes[0]._ctx.run(
            parallel._runtimes[0].sim.schedule,
            Event(Instant.Epoch, "go", target=sleeper),
        )
        parallel.run()
        assert done == [0.55]

    def test_window_larger_than_min_latency_rejected(self):
        sink = Sink()
        part_a = SimulationPartition("A", entities=[Relay("r", sink)])
        part_b = SimulationPartition("B", entities=[sink])
        with pytest.raises(ValueError, match="exceeds minimum link latency"):
            ParallelSimulation(
                [part_a, part_b],
                links=[PartitionLink("A", "B", min_latency=Duration.from_seconds(0.05))],
                end_time=Instant.from_seconds(1),
                window=0.1,
            )

    def test_undeclared_cross_reference_rejected(self):
        sink = Sink()
        relay = Relay("relay", sink)  # references B's sink
        part_a = SimulationPartition("A", entities=[relay])
        part_b = SimulationPartition("B", entities=[sink])
        with pytest.raises(PartitionValidationError, match="no link"):
            ParallelSimulation([part_a, part_b], end_time=Instant.from_seconds(1))

    def test_duplicate_entity_rejected(self):
        sink = Sink()
        with pytest.raises(PartitionValidationError, match="appears in both"):
            ParallelSimulation(
                [
                    SimulationPartition("A", entities=[sink]),
                    SimulationPartition("B", entities=[sink]),
                ],
                end_time=Instant.from_seconds(1),
            )


class TestParallelRunner:
    def test_inline_replicas(self):
        runner = ParallelRunner(backend="inline")
        results = runner.run_replicas(build_mm1, n_replicas=4, base_seed=100)
        assert len(results) == 4
        assert all(r.summary.events_processed > 0 for r in results)
        # Different seeds -> different arrival streams.
        counts = {r.artifacts["received"] for r in results}
        assert len(counts) > 1

    def test_same_seed_reproduces(self):
        runner = ParallelRunner(backend="inline")
        a = runner.run_replicas(build_mm1, n_replicas=1, base_seed=42)[0]
        b = runner.run_replicas(build_mm1, n_replicas=1, base_seed=42)[0]
        assert a.artifacts == b.artifacts

    def test_thread_backend(self):
        runner = ParallelRunner(backend="thread", max_workers=4)
        results = runner.run_replicas(build_mm1, n_replicas=4, base_seed=0)
        assert len(results) == 4

    def test_process_backend(self):
        runner = ParallelRunner(backend="process", max_workers=2)
        results = runner.run_sweep(
            [
                RunConfig("lo", build_mm1, seed=1, params={"rate": 20.0}),
                RunConfig("hi", build_mm1, seed=1, params={"rate": 80.0}),
            ]
        )
        assert results[0].name == "lo"
        assert results[1].artifacts["received"] > results[0].artifacts["received"]
