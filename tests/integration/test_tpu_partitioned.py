"""Entity-sharded SPMD execution: ppermute outbox exchange over the
partition mesh, validated against Jackson-network closed forms and the
host executor (SURVEY §2.5 / §7 step 8 — the last parallel-mode row)."""

import numpy as np
import pytest

from happysim_tpu.tpu.model import EnsembleModel
from happysim_tpu.tpu.partitioned import partition_mesh, run_partitioned

LAM, MU, HOP_LATENCY = 5.0, 20.0, 0.05


def ring_model(horizon_s=20.0, hop_probability=0.5):
    """Each partition: source -> server -> (q: neighbor | 1-q: sink)."""
    model = EnsembleModel(horizon_s=horizon_s)
    src = model.source(rate=LAM)
    srv = model.server(service_mean=1.0 / MU, queue_capacity=256)
    snk = model.sink()
    remote = model.remote(ingress=srv, latency_s=HOP_LATENCY)
    router = model.router(policy="random")
    model.connect(src, srv)
    model.connect(srv, router)
    # Random over [sink, remote] = hop_probability 0.5 in two targets.
    model.connect(router, snk)
    model.connect(router, remote)
    return model


@pytest.fixture(scope="module")
def mesh():
    import jax

    return partition_mesh(jax.devices("cpu")[:8])


class TestRingJacksonOracle:
    def test_latency_matches_product_form(self, mesh):
        """Jackson ring: effective lambda = lam/(1-q) = 10; per-visit
        sojourn 1/(mu - lam_eff) = 0.1s; mean visits 2; mean hops 1 ->
        E[latency] = 0.2 + 0.05 = 0.25s."""
        result = run_partitioned(
            ring_model(horizon_s=30.0), window_s=HOP_LATENCY, mesh=mesh,
            n_replicas=16, seed=0,
        )
        assert result.remote_dropped == 0
        assert result.sink_mean_latency_s[0] == pytest.approx(0.25, rel=0.1)

    def test_flow_conservation(self, mesh):
        result = run_partitioned(
            ring_model(), window_s=HOP_LATENCY, mesh=mesh, n_replicas=8, seed=1
        )
        # Every completion either sank or hopped; nothing vanished.
        completed = result.server_completed[0]
        assert result.sink_count[0] + result.remote_sent == completed
        assert result.transit_dropped == 0
        assert result.truncated_windows == 0
        # ~half the completions hop.
        assert result.remote_sent / completed == pytest.approx(0.5, abs=0.05)

    def test_budget_exhaustion_detected(self, mesh):
        result = run_partitioned(
            ring_model(horizon_s=10.0), window_s=HOP_LATENCY, mesh=mesh,
            n_replicas=2, seed=9, max_events_per_window=2,
        )
        # A 2-event budget can't keep up with ~0.5 arrivals + service per
        # window: the overrun is REPORTED, not silently absorbed.
        assert result.truncated_windows > 0

    def test_partitions_balanced(self, mesh):
        result = run_partitioned(
            ring_model(), window_s=HOP_LATENCY, mesh=mesh, n_replicas=8, seed=2
        )
        counts = result.per_partition_sink_count[:, 0]
        assert counts.min() > 0.6 * counts.max()

    def test_deterministic(self, mesh):
        a = run_partitioned(
            ring_model(), window_s=HOP_LATENCY, mesh=mesh, n_replicas=4, seed=3
        )
        b = run_partitioned(
            ring_model(), window_s=HOP_LATENCY, mesh=mesh, n_replicas=4, seed=3
        )
        assert a.sink_count == b.sink_count
        assert a.remote_sent == b.remote_sent
        assert a.sink_mean_latency_s == b.sink_mean_latency_s


class TestHostEquivalence:
    def test_matches_host_ring(self, mesh):
        """The same 8-server ring on the host executor (ConveyorBelt as
        the inter-partition link) agrees on mean sojourn."""
        from happysim_tpu import (
            ConveyorBelt,
            ExponentialLatency,
            Instant,
            RandomRouter,
            Server,
            Simulation,
            Sink,
            Source,
        )

        n = 8
        sink = Sink("sink")
        servers = [
            Server(
                f"srv{i}",
                service_time=ExponentialLatency(1.0 / MU, seed=50 + i),
                queue_capacity=256,
            )
            for i in range(n)
        ]
        for i, server in enumerate(servers):
            link = ConveyorBelt(
                f"link{i}", servers[(i + 1) % n], transit_time_s=HOP_LATENCY
            )
            server.downstream = RandomRouter(
                f"router{i}", targets=[sink, link], seed=80 + i
            )
        links = [s.downstream.targets[1] for s in servers]
        routers = [s.downstream for s in servers]
        sources = [
            Source.poisson(rate=LAM, target=servers[i], seed=10 + i, name=f"src{i}")
            for i in range(n)
        ]
        Simulation(
            sources=sources,
            entities=[*servers, *routers, *links, sink],
            end_time=Instant.from_seconds(300.0),
        ).run()
        host_mean = sink.latency_stats().mean_s

        result = run_partitioned(
            ring_model(horizon_s=30.0), window_s=HOP_LATENCY, mesh=mesh,
            n_replicas=16, seed=4,
        )
        assert result.sink_mean_latency_s[0] == pytest.approx(host_mean, rel=0.15)


class TestContracts:
    def test_window_must_respect_min_latency(self, mesh):
        with pytest.raises(ValueError, match="conservative-window"):
            run_partitioned(ring_model(), window_s=HOP_LATENCY * 2, mesh=mesh)

    def test_run_ensemble_rejects_remotes(self):
        from happysim_tpu.tpu.engine import run_ensemble

        with pytest.raises(ValueError, match="run_partitioned"):
            run_ensemble(ring_model(), n_replicas=8)

    def test_partitioned_requires_remotes(self, mesh):
        from happysim_tpu.tpu.model import EnsembleModel

        model = EnsembleModel(horizon_s=5.0)
        src = model.source(rate=1.0)
        snk = model.sink()
        model.connect(src, snk)
        with pytest.raises(ValueError, match="remote"):
            run_partitioned(model, window_s=0.05, mesh=mesh)

    def test_outbox_overflow_counted(self, mesh):
        result = run_partitioned(
            ring_model(horizon_s=10.0), window_s=HOP_LATENCY, mesh=mesh,
            n_replicas=2, seed=5, outbox_capacity=1,
        )
        # Multiple hops per 50ms window at lam_eff=10/s overflow a 1-slot
        # outbox sometimes; the loss is counted, not silent.
        assert result.remote_dropped > 0
        assert result.remote_sent > 0