"""TPU brownout windows vs the host executor's PauseNode faults.

A server with outage window [20, 40) drops exactly the arrivals landing in
the window. The host twin is a paused pass-through relay in front of the
same server (PauseNode drops deliveries in-window; in-flight work
finishes) — deterministic constant arrivals/service make the comparison
exact.
"""

import pytest

from happysim_tpu import (
    ConstantLatency,
    FaultSchedule,
    Instant,
    PauseNode,
    Server,
    Simulation,
    Sink,
    Source,
)
from happysim_tpu.core.entity import Entity
from happysim_tpu.tpu.engine import run_ensemble
from happysim_tpu.tpu.model import EnsembleModel

RATE = 10.0
HORIZON = 100.0
OUT = (20.0, 40.0)


@pytest.fixture(scope="module")
def mesh():
    import jax

    from happysim_tpu.tpu.mesh import replica_mesh

    return replica_mesh(jax.devices("cpu")[:8])


class Relay(Entity):
    """Pass-through hop (the PauseNode target)."""

    def __init__(self, name, downstream):
        super().__init__(name)
        self.downstream = downstream

    def handle_event(self, event):
        return [self.forward(event, self.downstream)]

    def downstream_entities(self):
        return [self.downstream]


def run_host():
    sink = Sink("sink")
    server = Server(
        "srv", service_time=ConstantLatency(0.05), downstream=sink, queue_capacity=256
    )
    relay = Relay("relay", server)
    source = Source.constant(rate=RATE, target=relay, stop_after=HORIZON)
    faults = FaultSchedule()
    faults.add(PauseNode("relay", start=OUT[0], end=OUT[1]))
    sim = Simulation(
        sources=[source],
        entities=[relay, server, sink],
        fault_schedule=faults,
        end_time=Instant.from_seconds(HORIZON + 10),
    )
    sim.run()
    return sink.events_received, server.requests_completed


def run_tpu(mesh):
    model = EnsembleModel(horizon_s=HORIZON + 10)
    src = model.source(rate=RATE, kind="constant", stop_after_s=HORIZON)
    srv = model.server(
        concurrency=1, service_mean=0.05, service="constant",
        queue_capacity=256, outage=OUT,
    )
    snk = model.sink()
    model.connect(src, srv)
    model.connect(srv, snk)
    return run_ensemble(model, n_replicas=64, seed=1, mesh=mesh)


class TestOutageWindow:
    def test_drops_match_host_pause(self, mesh):
        host_delivered, host_completed = run_host()
        result = run_tpu(mesh)
        tpu_delivered = result.sink_count[0] / result.n_replicas
        tpu_outage_dropped = result.server_outage_dropped[0] / result.n_replicas
        # 20s of a 10/s deterministic stream falls in the window.
        assert tpu_outage_dropped == pytest.approx(200, abs=2)
        # Loss counters are disjoint: queue-full drops never fired here.
        assert result.server_dropped[0] == 0
        assert tpu_delivered == pytest.approx(host_delivered, abs=2)
        assert result.server_completed[0] / result.n_replicas == pytest.approx(
            host_completed, abs=2
        )

    def test_no_window_no_outage_drops(self, mesh):
        model = EnsembleModel(horizon_s=20.0)
        src = model.source(rate=RATE, kind="poisson")
        srv = model.server(concurrency=1, service_mean=0.05)
        snk = model.sink()
        model.connect(src, srv)
        model.connect(srv, snk)
        result = run_ensemble(model, n_replicas=64, seed=2, mesh=mesh)
        assert result.server_outage_dropped[0] == 0

    def test_outage_validation(self):
        model = EnsembleModel()
        with pytest.raises(ValueError, match="outage window"):
            model.server(outage=(5.0, 5.0))
        with pytest.raises(ValueError, match="start must be >= 0"):
            model.server(outage=(-1.0, 5.0))

    def test_recovery_resumes_throughput(self, mesh):
        """Deliveries stop during the window and resume after it."""
        result = run_tpu(mesh)
        # Total conservation: delivered + outage-dropped = offered.
        offered = RATE * HORIZON
        per_rep = (
            result.sink_count[0] + result.server_outage_dropped[0]
        ) / result.n_replicas
        assert per_rep == pytest.approx(offered, abs=3)
