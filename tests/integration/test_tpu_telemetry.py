"""Device-side windowed telemetry: the observation-only contract.

Three properties anchor the subsystem (ISSUE 3 acceptance criteria):

1. EXACT MERGE — a telemetry-enabled model's per-window counter series
   sum exactly to the whole-run ``EnsembleResult`` counters and
   ``sink_hist`` (integer scatter-adds partition the same events the
   whole-run accumulators see).
2. OBSERVATION ONLY — telemetry adds no RNG draws and no dynamics, so
   the simulated trajectory is bit-identical to the same model without
   a spec (on the event scan), and a telemetry-free model traces to the
   exact same program as before the subsystem existed.
3. DURABILITY — the buffers ride the scan carry, so mid-run checkpoint
   + resume reproduces the uninterrupted run's series exactly, and a
   spec mismatch at resume is rejected like ``macro_block``.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from happysim_tpu.tpu import (
    EnsembleModel,
    FaultSpec,
    mm1_model,
    run_ensemble,
    run_partitioned,
)
from happysim_tpu.tpu.chain import fast_plan
from happysim_tpu.tpu.engine import _Compiled, model_fingerprint


def _mm1(telemetry_window=None, **model_kwargs):
    model = mm1_model(
        lam=8.0, mu=10.0, horizon_s=12.0, warmup_s=2.0, **model_kwargs
    )
    if telemetry_window is not None:
        model.telemetry(window_s=telemetry_window)
    return model


def _chaos_model(telemetry_window=None):
    """Every accounting site live at once: limiter admission, transit
    latency, deadline retries, stochastic outage faults with backoff
    retries, packet loss."""
    model = EnsembleModel(horizon_s=20.0)
    src = model.source(rate=6.0)
    lim = model.limiter(refill_rate=5.0, capacity=4.0)
    srv = model.server(
        concurrency=1,
        service_mean=0.12,
        queue_capacity=4,
        deadline_s=1.5,
        max_retries=2,
        fault=FaultSpec(rate=0.2, mean_duration_s=1.0, mode="outage"),
        retry_backoff_s=0.05,
        retry_jitter=0.5,
    )
    snk = model.sink()
    model.connect(src, lim)
    model.connect(lim, srv, latency_s=0.01)
    model.connect(srv, snk, loss_p=0.05)
    if telemetry_window is not None:
        model.telemetry(window_s=telemetry_window)
    return model


SIM_FIELDS_EXCLUDED = {
    "wall_seconds",
    "events_per_second",
    "timeseries",
    "compile_seconds",
    # resumed runs pay a carry-redistribution transfer; uninterrupted twins
    # report 0.0 (timing provenance, not simulation state)
    "redistribution_seconds",
    # Engine-path provenance: the two runs may take different engine
    # routes — the SIMULATION fields are what must match.
    "engine_path",
    "kernel_decline",
    # block-occupancy provenance (engine_report observability, not state)
    "macro_block",
    "max_blocks",
    "blocks_total",
    "block_occupancy",
    "padded_replicas",
}


def assert_simulation_identical(a, b):
    """Every simulation-output field bit-identical (timing + the series
    themselves excluded — telemetry must not change the simulation)."""
    for field in dataclasses.fields(a):
        if field.name in SIM_FIELDS_EXCLUDED:
            continue
        left, right = getattr(a, field.name), getattr(b, field.name)
        if isinstance(left, np.ndarray):
            assert np.array_equal(left, right), field.name
        else:
            assert left == right, f"{field.name}: {left!r} != {right!r}"


class TestExactMerge:
    def test_mm1_window_sums_equal_whole_run(self):
        result = run_ensemble(
            _mm1(telemetry_window=1.5), n_replicas=32, seed=11, max_events=480
        )
        ts = result.timeseries
        assert ts is not None and ts.n_windows == 8
        assert ts.sink_count.sum(axis=0).tolist() == result.sink_count
        assert np.array_equal(ts.sink_hist.sum(axis=0), result.sink_hist)
        assert ts.server_completed.sum(axis=0).tolist() == result.server_completed
        # Float integrals re-associate but must agree tightly.
        denominator = result.n_replicas * ts.measured_len_s
        whole_depth = result.server_mean_queue_len[0] * denominator.sum()
        windowed_depth = (
            np.asarray(ts.server_mean_queue_len)[:, 0] * denominator
        ).sum()
        assert windowed_depth == pytest.approx(whole_depth, rel=1e-5)
        whole_busy = result.server_utilization[0] * denominator.sum()
        windowed_busy = (
            np.asarray(ts.server_utilization)[:, 0] * denominator
        ).sum()
        assert windowed_busy == pytest.approx(whole_busy, rel=1e-5)

    def test_chaos_counters_all_partition_exactly(self):
        result = run_ensemble(_chaos_model(telemetry_window=2.0), n_replicas=64, seed=7)
        ts = result.timeseries
        pairs = [
            (ts.sink_count, result.sink_count),
            (ts.server_completed, result.server_completed),
            (ts.server_dropped, result.server_dropped),
            (ts.server_timed_out, result.server_timed_out),
            (ts.server_retried, result.server_retried),
            (ts.server_fault_dropped, result.server_fault_dropped),
            (ts.server_fault_retried, result.server_fault_retried),
            (ts.limiter_admitted, result.limiter_admitted),
            (ts.limiter_dropped, result.limiter_dropped),
            (ts.transit_dropped, result.transit_dropped),
        ]
        for series, whole in pairs:
            assert series.sum(axis=0).tolist() == whole
        assert int(ts.network_lost.sum()) == result.network_lost
        assert np.array_equal(ts.sink_hist.sum(axis=0), result.sink_hist)
        # Something actually happened on every counter family this model
        # exercises, or the test proves nothing.
        assert result.network_lost > 0
        assert result.server_fault_dropped[0] > 0
        assert result.server_fault_retried[0] > 0
        assert result.limiter_dropped[0] > 0

    def test_fault_occupancy_tracks_duty_cycle(self):
        from happysim_tpu.tpu.faults import duty_cycle

        result = run_ensemble(_chaos_model(telemetry_window=2.0), n_replicas=256, seed=5)
        occupancy = np.asarray(result.timeseries.fault_occupancy)[:, 0]
        expected = duty_cycle(0.2, 1.0)
        # Early windows: renewal process not yet truncated by max_windows;
        # 256 replicas x 2s windows gives a loose-but-real gate.
        assert occupancy[:5].mean() == pytest.approx(expected, rel=0.5)
        assert (occupancy >= 0.0).all() and (occupancy <= 1.0).all()

    def test_spread_percentiles_bracket_the_mean(self):
        result = run_ensemble(
            _mm1(telemetry_window=1.5), n_replicas=64, seed=2, max_events=960
        )
        ts = result.timeseries
        busy = slice(2, ts.n_windows)  # post-warmup windows
        assert (
            ts.replica_throughput_p10[busy, 0]
            <= ts.replica_throughput_mean[busy, 0]
        ).all()
        assert (
            ts.replica_throughput_mean[busy, 0]
            <= ts.replica_throughput_p90[busy, 0]
        ).all()
        # Mean per-replica rate times replicas times window length must
        # rebuild the aggregate counts.
        rebuilt = (
            ts.replica_throughput_mean[:, 0]
            * result.n_replicas
            * ts.window_len_s
        )
        np.testing.assert_allclose(rebuilt, ts.sink_count[:, 0], rtol=1e-6)


class TestRouterTopologies:
    """Sink deliveries with TRACED sink indices (router choices) must
    window correctly, including the mixed sink/server feedback shape
    whose sink edge carries the only latency in the model (the shape
    that exposed the has_transit router gap fixed in this PR)."""

    @staticmethod
    def _feedback_model(telemetry: bool):
        model = EnsembleModel(horizon_s=10.0)
        src = model.source(rate=6.0)
        srv = model.server(service_mean=0.05, queue_capacity=32)
        snk = model.sink()
        rtr = model.router(policy="random")
        model.connect(src, srv)
        model.connect(srv, rtr)
        model.connect(rtr, snk, latency_s=0.02)  # only latency edge
        model.connect(rtr, srv)  # latency-free feedback to the server
        if telemetry:
            model.telemetry(window_s=1.0)
        return model

    def test_mixed_feedback_router_with_sink_edge_latency(self):
        result = run_ensemble(
            self._feedback_model(True), n_replicas=16, seed=4, max_events=2000
        )
        base = run_ensemble(
            self._feedback_model(False), n_replicas=16, seed=4, max_events=2000
        )
        ts = result.timeseries
        assert ts.sink_count.sum(axis=0).tolist() == result.sink_count
        assert np.array_equal(ts.sink_hist.sum(axis=0), result.sink_hist)
        assert_simulation_identical(result, base)

    def test_two_sink_fanout_windows_each_sink(self):
        def build(telemetry: bool):
            model = EnsembleModel(horizon_s=8.0)
            src = model.source(rate=5.0)
            sink_a, sink_b = model.sink(), model.sink()
            rtr = model.router(policy="round_robin")
            model.connect(src, rtr)
            model.connect(rtr, sink_a)
            model.connect(rtr, sink_b, latency_s=0.01)
            if telemetry:
                model.telemetry(window_s=1.0)
            return model

        result = run_ensemble(build(True), n_replicas=16, seed=9, max_events=400)
        base = run_ensemble(build(False), n_replicas=16, seed=9, max_events=400)
        ts = result.timeseries
        assert ts.sink_count.shape == (8, 2)
        assert ts.sink_count.sum(axis=0).tolist() == result.sink_count
        assert np.array_equal(ts.sink_hist.sum(axis=0), result.sink_hist)
        assert_simulation_identical(result, base)


class TestObservationOnly:
    def test_simulation_bit_identical_with_and_without_telemetry(self):
        with_tel = run_ensemble(
            _mm1(telemetry_window=1.5), n_replicas=32, seed=11, max_events=480
        )
        without = run_ensemble(_mm1(), n_replicas=32, seed=11, max_events=480)
        assert with_tel.timeseries is not None and without.timeseries is None
        assert_simulation_identical(with_tel, without)

    def test_chaos_simulation_bit_identical(self):
        with_tel = run_ensemble(_chaos_model(telemetry_window=2.0), n_replicas=32, seed=7)
        without = run_ensemble(_chaos_model(), n_replicas=32, seed=7)
        assert_simulation_identical(with_tel, without)

    def test_telemetry_free_model_traces_identical_program(self):
        """A model that never had a spec and one whose spec was cleared
        must produce the same jaxpr, with no telemetry buffers in the
        carry — the compile-time gate leaves zero residue."""

        def step_jaxpr(model):
            compiled = _Compiled(model)
            key = jax.random.PRNGKey(0)
            params = {
                "src_rate": jnp.full((compiled.nS,), 8.0),
                "srv_mean": jnp.full((compiled.nV,), 0.1),
            }
            state = compiled.init_state(key, params)
            step = compiled.make_step(float(model.horizon_s), external_u=True)
            return str(
                jax.make_jaxpr(step)(
                    (state, params), jnp.full((compiled.n_draws,), 0.5)
                )
            )

        never = _mm1()
        cleared = _mm1(telemetry_window=1.0)
        cleared.telemetry_spec = None
        enabled = _mm1(telemetry_window=1.0)
        assert step_jaxpr(never) == step_jaxpr(cleared)
        assert step_jaxpr(never) != step_jaxpr(enabled)
        free_state = _Compiled(never).init_state(
            jax.random.PRNGKey(0),
            {"src_rate": jnp.full((1,), 8.0), "srv_mean": jnp.full((1,), 0.1)},
        )
        assert not any(key.startswith("tel_") for key in free_state)

    def test_telemetry_free_fingerprint_unchanged(self):
        """Telemetry joins the model fingerprint only when present, so
        existing telemetry-free checkpoints stay resumable."""
        assert model_fingerprint(_mm1()) != model_fingerprint(
            _mm1(telemetry_window=1.0)
        )
        cleared = _mm1(telemetry_window=1.0)
        cleared.telemetry_spec = None
        assert model_fingerprint(_mm1()) == model_fingerprint(cleared)


class TestExecutorRouting:
    def test_chain_fast_path_declines_telemetry(self):
        chain_eligible = mm1_model(lam=8.0, mu=10.0, horizon_s=10.0)
        assert fast_plan(chain_eligible) is not None
        chain_eligible.telemetry(window_s=1.0)
        assert fast_plan(chain_eligible) is None
        # And run_ensemble still produces the series via the event scan.
        result = run_ensemble(chain_eligible, n_replicas=8, seed=0)
        assert result.timeseries is not None
        assert result.timeseries.sink_count.sum(axis=0).tolist() == result.sink_count

    def test_partitioned_rejects_telemetry(self):
        model = EnsembleModel(horizon_s=2.0)
        src = model.source(rate=5.0)
        srv = model.server(service_mean=0.05)
        snk = model.sink()
        model.connect(src, srv)
        model.connect(srv, snk)
        model.remote(ingress=srv, latency_s=0.5)
        model.telemetry(window_s=0.5)
        with pytest.raises(ValueError, match="telemetry"):
            run_partitioned(model, window_s=0.5)

    def test_metric_subset_allocates_only_requested_buffers(self):
        model = _mm1()
        model.telemetry(window_s=1.5, metrics=("latency",))
        compiled = _Compiled(model)
        state = compiled.init_state(
            jax.random.PRNGKey(0),
            {"src_rate": jnp.full((1,), 8.0), "srv_mean": jnp.full((1,), 0.1)},
        )
        tel_keys = {key for key in state if key.startswith("tel_")}
        assert tel_keys == {"tel_sink_sum", "tel_sink_hist"}
        result = run_ensemble(model, n_replicas=8, seed=0, max_events=200)
        ts = result.timeseries
        assert ts.sink_p99_s is not None and ts.sink_count is None
        assert ts.server_mean_queue_len is None and ts.server_completed is None


class TestCheckpointDurability:
    KW = dict(n_replicas=16, seed=3, max_events=400)

    def test_mid_run_resume_reproduces_series_exactly(self):
        baseline = run_ensemble(_mm1(telemetry_window=1.5), **self.KW)
        snapshots = []
        checkpointed = run_ensemble(
            _mm1(telemetry_window=1.5),
            **self.KW,
            checkpoint_every_s=0.0,
            checkpoint_callback=snapshots.append,
        )
        assert checkpointed.timeseries == baseline.timeseries
        assert snapshots and all(
            0 < snap.chunk_index < snap.n_chunks for snap in snapshots
        )
        middle = snapshots[len(snapshots) // 2]
        assert middle.telemetry.startswith("window_s=1.5;")
        assert any(key.startswith("tel_") for key in middle.state)
        resumed = run_ensemble(
            _mm1(telemetry_window=1.5), **self.KW, resume_from=middle
        )
        assert resumed.timeseries == baseline.timeseries
        assert_simulation_identical(resumed, baseline)

    def test_npz_round_trip_preserves_buffers(self, tmp_path):
        from happysim_tpu.tpu import EnsembleCheckpoint

        snapshots = []
        baseline = run_ensemble(
            _mm1(telemetry_window=1.5),
            **self.KW,
            checkpoint_every_s=0.0,
            checkpoint_callback=snapshots.append,
        )
        middle = snapshots[len(snapshots) // 2]
        path = str(tmp_path / "telemetry-checkpoint")
        middle.save(path)
        loaded = EnsembleCheckpoint.load(path)
        assert loaded.telemetry == middle.telemetry
        resumed = run_ensemble(
            _mm1(telemetry_window=1.5), **self.KW, resume_from=loaded
        )
        assert resumed.timeseries == baseline.timeseries

    def test_resume_rejects_spec_mismatch(self):
        snapshots = []
        run_ensemble(
            _mm1(telemetry_window=1.5),
            **self.KW,
            checkpoint_every_s=0.0,
            checkpoint_callback=snapshots.append,
        )
        middle = snapshots[len(snapshots) // 2]
        with pytest.raises(ValueError, match="telemetry|fingerprint"):
            run_ensemble(
                _mm1(telemetry_window=3.0), **self.KW, resume_from=middle
            )
        with pytest.raises(ValueError, match="telemetry|fingerprint"):
            run_ensemble(_mm1(), **self.KW, resume_from=middle)

    def test_legacy_telemetry_free_checkpoint_still_resumes(self):
        """Pre-telemetry checkpoints load with telemetry="" and resume
        into telemetry-free runs unchanged."""
        snapshots = []
        baseline = run_ensemble(
            _mm1(),
            **self.KW,
            checkpoint_every_s=0.0,
            checkpoint_callback=snapshots.append,
        )
        legacy = dataclasses.replace(
            snapshots[len(snapshots) // 2], telemetry=""
        )
        resumed = run_ensemble(_mm1(), **self.KW, resume_from=legacy)
        assert_simulation_identical(resumed, baseline)


class TestShardingInvariance:
    def test_series_identical_across_mesh_layouts(self, cpu_mesh):
        """Same seed on a 1-device and an 8-device mesh: the windowed
        buffers shard on the replica axis like every other state leaf,
        so the series must be bit-identical (the engine's sharding
        oracle, extended to telemetry)."""
        kwargs = dict(n_replicas=16, seed=3, max_events=400)
        single = run_ensemble(_mm1(telemetry_window=1.5), **kwargs)
        sharded = run_ensemble(
            _mm1(telemetry_window=1.5), **kwargs, mesh=cpu_mesh
        )
        assert sharded.timeseries == single.timeseries
        assert_simulation_identical(sharded, single)


class TestInstrumentationBridge:
    def test_to_data_feeds_existing_tooling(self):
        from happysim_tpu.instrumentation.data import Data

        result = run_ensemble(
            _mm1(telemetry_window=1.5), n_replicas=16, seed=3, max_events=400
        )
        datasets = result.timeseries.to_data()
        p99 = datasets["sink[0].p99_s"]
        assert isinstance(p99, Data) and len(p99) == 8
        np.testing.assert_allclose(
            p99.times_s, result.timeseries.window_start_s
        )
        # The existing bucketing/statistics pipeline consumes it as-is.
        assert p99.max() >= p99.mean() >= 0.0
        assert len(p99.bucket(3.0)) >= 2

    def test_to_dataframe_schema(self):
        pandas = pytest.importorskip("pandas")

        result = run_ensemble(
            _mm1(telemetry_window=1.5), n_replicas=16, seed=3, max_events=400
        )
        frame = result.timeseries.to_dataframe()
        assert isinstance(frame, pandas.DataFrame)
        assert len(frame) == 8
        for column in (
            "window_start_s",
            "sink[0].count",
            "sink[0].p99_s",
            "server[0].mean_queue_len",
            "server[0].utilization",
            "server[0].completed",
        ):
            assert column in frame.columns
        assert frame["sink[0].count"].sum() == result.sink_count[0]
