"""E2E milestone: M/M/1 through Source → Server → Sink matches theory.

Mirrors the reference's queue-theory oracle
(``/root/reference/examples/queuing/m_m_1_queue.py:66-78``): with λ arrivals
and service rate μ, ρ = λ/μ, E[wait in queue] = ρ/(μ−λ) − 1/μ, E[sojourn] =
1/(μ−λ). This is the correctness baseline the TPU executor is also validated
against (tests/integration/test_tpu_mm1.py).
"""

import pytest

from happysim_tpu import (
    ExponentialLatency,
    Instant,
    Probe,
    Server,
    Simulation,
    Sink,
    Source,
)


def run_mm1(lam=8.0, mu=10.0, horizon_s=400.0, seed=42):
    sink = Sink()
    server = Server(
        "server",
        concurrency=1,
        service_time=ExponentialLatency(1.0 / mu, seed=seed + 1),
        downstream=sink,
    )
    source = Source.poisson(rate=lam, target=server, stop_after=horizon_s, seed=seed)
    sim = Simulation(
        sources=[source],
        entities=[server, sink],
        end_time=Instant.from_seconds(horizon_s * 2),  # let queue drain
    )
    summary = sim.run()
    return sim, summary, sink, server


class TestMM1:
    def test_sojourn_time_matches_theory(self):
        lam, mu = 8.0, 10.0
        _, _, sink, server = run_mm1(lam, mu)
        # E[T] = 1/(mu - lam) = 0.5s
        expected = 1.0 / (mu - lam)
        observed = sum(sink.latencies_s) / len(sink.latencies_s)
        assert observed == pytest.approx(expected, rel=0.15)

    def test_all_requests_complete(self):
        _, _, sink, server = run_mm1(horizon_s=50.0)
        assert server.requests_completed == sink.events_received
        assert sink.events_received > 300  # ~8/s * 50s

    def test_utilization_matches_rho(self):
        lam, mu = 8.0, 10.0
        _, summary, sink, server = run_mm1(lam, mu)
        busy_fraction = server.busy_seconds / max(t.to_seconds() for t in sink.completion_times)
        assert busy_fraction == pytest.approx(lam / mu, rel=0.1)

    def test_probe_queue_depth(self):
        sink = Sink()
        server = Server(
            "server",
            service_time=ExponentialLatency(0.095, seed=2),
            downstream=sink,
        )
        source = Source.poisson(rate=8.0, target=server, stop_after=100.0, seed=3)
        probe = Probe.on(server, "queue_depth", interval_s=0.1)
        sim = Simulation(
            sources=[source],
            entities=[server, sink],
            probes=[probe],
            end_time=Instant.from_seconds(150),
        )
        sim.run()
        assert probe.data.count() > 900
        # Mean queue length for M/M/1: rho^2/(1-rho); rho=0.76 → ~2.4.
        # Loose bound: positive and below 4x theory.
        rho = 8.0 * 0.095
        theory = rho * rho / (1 - rho)
        assert 0 < probe.data.mean() < theory * 4


class TestMMC:
    def test_mmc_multiserver_faster_than_mm1(self):
        lam, mu = 16.0, 10.0  # needs c >= 2
        sink = Sink()
        server = Server(
            "mmc",
            concurrency=3,
            service_time=ExponentialLatency(1.0 / mu, seed=11),
            downstream=sink,
        )
        source = Source.poisson(rate=lam, target=server, stop_after=200.0, seed=12)
        sim = Simulation(
            sources=[source],
            entities=[server, sink],
            end_time=Instant.from_seconds(400),
        )
        sim.run()
        assert sink.events_received > 2800
        mean_latency = sum(sink.latencies_s) / len(sink.latencies_s)
        # With c=3, rho = 16/30 ≈ 0.53 → sojourn close to service mean 0.1
        assert mean_latency < 0.2
