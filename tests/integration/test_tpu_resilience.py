"""The vectorized resilience layer, end to end (ISSUE 15).

Two layers of coverage:

1. Mechanism: breaker state machines trip/short-circuit/re-close, load
   shedding rejects at the admission hop, retry budgets suppress
   launches (``srv_budget_dropped``, never parked transit jobs), the
   new state leaves checkpoint round-trip, and a resilience-free model
   traces to the IDENTICAL jaxpr (the compile-time-gating contract the
   telemetry and chaos layers already honor).

2. Scenario: the two ROADMAP-item-4 metastability scenarios —
   retry-storm collapse (a correlated outage ends but goodput never
   recovers without a retry budget; with budgets + breakers it recovers
   to >= 90% of pre-outage goodput) and the breaker-protected cascade
   (a downstream brownout trips the breaker, which sheds and then
   re-closes through half-open probes). These are the scenario class
   the pure-Python reference fundamentally cannot reach: one compiled
   launch Monte-Carlos the hysteresis over every replica.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from happysim_tpu.tpu import run_ensemble
from happysim_tpu.tpu.engine import _Compiled
from happysim_tpu.tpu.mesh import replica_mesh
from happysim_tpu.tpu.model import EnsembleModel, FaultSpec


def _mesh():
    return replica_mesh(jax.devices("cpu")[:1])


# ---------------------------------------------------------------------------
# Scenario 1: retry-storm collapse (metastable failure reproduced + defended)
# ---------------------------------------------------------------------------

MU = 50.0
LAM = 32.0  # rho = 0.64: comfortably stable — the collapse is NOT overload
HORIZON = 12.0
OUTAGE = (2.0, 4.0)  # correlated outage window (identical in every replica)


def _storm_model(defended: bool) -> EnsembleModel:
    """M/M/1 at rho=0.64 with deadline retries and a pinned outage.

    The metastable mechanism: during the outage, rejected arrivals park
    as backoff retries; the post-outage herd pushes queue wait past the
    deadline, so EVERY completion expires and retries — sustained
    demand (1 + max_retries) x lambda = 2.56 mu > mu keeps the queue
    saturated and goodput at zero long after the outage ended, even
    though the base load is stable. The defense caps retry launches at
    ratio x requests (plus breakers failing fast during the dark
    window), so post-outage demand stays under mu and the queue drains.
    """
    model = EnsembleModel(horizon_s=HORIZON, transit_capacity=64)
    src = model.source(rate=LAM)
    srv = model.server(
        service_mean=1.0 / MU,
        queue_capacity=512,
        deadline_s=0.25,
        max_retries=3,
        retry_backoff_s=0.5,
        fault=FaultSpec(windows=(OUTAGE,)),
    )
    snk = model.sink()
    model.connect(src, srv)
    model.connect(srv, snk)
    model.telemetry(window_s=1.0, metrics=("throughput", "rates"))
    if defended:
        model.circuit_breaker(
            failure_threshold=5, window_s=1.0, cooldown_s=0.5, half_open_probes=2
        )
        model.retry_budget(ratio=0.1, min_per_s=0.5, burst=4.0)
    return model


def _goodput_windows(result) -> np.ndarray:
    return result.timeseries.sink_count[:, 0].astype(np.float64)


def _run_storm(defended: bool):
    return run_ensemble(
        _storm_model(defended),
        n_replicas=32,
        seed=7,
        mesh=_mesh(),
        max_events=6144,
    )


class TestRetryStormCollapse:
    @pytest.fixture(scope="class")
    def undefended(self):
        return _run_storm(False)

    @pytest.fixture(scope="class")
    def defended(self):
        return _run_storm(True)

    def test_undefended_goodput_stays_collapsed(self, undefended):
        """The metastability pin: the outage ends at t=4 but goodput in
        the LAST three windows (t in [9, 12) — four windows of slack
        after the outage) never recovers. The run is not truncated, so
        the collapse is the dynamics, not an exhausted event budget."""
        assert undefended.truncated_replicas == 0
        windows = _goodput_windows(undefended)
        pre = windows[:2].mean()
        post = windows[-3:].mean()
        assert pre > 0.8 * LAM * 32  # healthy pre-outage goodput
        assert post < 0.1 * pre, (
            f"expected metastable collapse, got post/pre = {post / pre:.3f}"
        )
        # The storm signature: retries dwarf the offered load.
        assert undefended.server_retried[0] > 32 * LAM * HORIZON * 0.5

    def test_defended_goodput_recovers(self, defended):
        """Budgets + breakers on: >= 90% of pre-outage goodput in the
        tail windows (the acceptance-criteria bound)."""
        assert defended.truncated_replicas == 0
        windows = _goodput_windows(defended)
        pre = windows[:2].mean()
        post = windows[-3:].mean()
        assert post >= 0.9 * pre, (
            f"expected recovery >= 0.9, got {post / pre:.3f}"
        )

    def test_defenses_actually_fired(self, defended):
        """The recovery must be attributable: budget suppressions and
        breaker trips both nonzero, and the budget drops appear in the
        windowed series summing to the whole-run counter."""
        assert sum(defended.server_budget_dropped) > 0
        assert sum(defended.breaker_tripped) > 0
        assert sum(defended.server_breaker_dropped) > 0
        series = defended.timeseries
        np.testing.assert_array_equal(
            series.server_budget_dropped.sum(axis=0),
            np.asarray(defended.server_budget_dropped),
        )
        np.testing.assert_array_equal(
            series.breaker_tripped.sum(axis=0),
            np.asarray(defended.breaker_tripped),
        )

    def test_budget_drops_are_not_parked_transit_jobs(self, defended, undefended):
        """Budget-suppressed retries become srv_budget_dropped, not
        transit registrations: the defended run's transit pressure is
        BELOW the undefended run's (which actually overflowed its
        registers during the storm)."""
        assert sum(defended.transit_dropped) <= sum(undefended.transit_dropped)
        assert sum(defended.server_fault_retried) < sum(
            undefended.server_fault_retried
        )

    def test_resilience_reaches_report_and_summary(self, defended, undefended):
        report = defended.engine_report()["resilience"]
        assert report["circuit_breaker"] and report["retry_budget"]
        assert not report["load_shed"]
        assert report["breaker_tripped_total"] == sum(defended.breaker_tripped)
        assert report["budget_dropped_total"] == sum(
            defended.server_budget_dropped
        )
        assert defended.resilience_features == ("circuit_breaker", "retry_budget")
        resilience_entities = [
            e for e in defended.summary().entities if e.kind == "Resilience"
        ]
        assert len(resilience_entities) == 1
        extra = resilience_entities[0].extra
        assert "circuit_breaker" in extra["features"]
        assert extra["total_budget_dropped"] == sum(
            defended.server_budget_dropped
        )
        # The undefended run declares no defenses: no Resilience entity,
        # per-feature report all off.
        off = undefended.engine_report()["resilience"]
        assert not (off["circuit_breaker"] or off["load_shed"] or off["retry_budget"])
        assert not any(
            e.kind == "Resilience" for e in undefended.summary().entities
        )


# ---------------------------------------------------------------------------
# Scenario 2: breaker-protected cascade (trip -> shed -> half-open -> close)
# ---------------------------------------------------------------------------


def _cascade_model() -> EnsembleModel:
    """source -> A -> B -> sink; B browns out on [3, 4): B's breaker
    trips on the brownout drops, short-circuits the upstream flow while
    dark (fail-fast instead of feeding a dead hop), and re-closes
    through half-open probes once the window ends."""
    model = EnsembleModel(horizon_s=10.0)
    src = model.source(rate=20.0)
    first = model.server(service_mean=1.0 / MU, queue_capacity=128)
    second = model.server(
        service_mean=1.0 / MU, queue_capacity=128, outage=(3.0, 4.0)
    )
    snk = model.sink()
    model.connect(src, first)
    model.connect(first, second)
    model.connect(second, snk)
    model.telemetry(window_s=1.0, metrics=("throughput", "rates"))
    model.circuit_breaker(
        failure_threshold=4, window_s=0.5, cooldown_s=0.4, half_open_probes=1
    )
    return model


class TestBreakerProtectedCascade:
    @pytest.fixture(scope="class")
    def result(self):
        return run_ensemble(
            _cascade_model(), n_replicas=32, seed=11, mesh=_mesh(), max_events=2048
        )

    def test_downstream_brownout_trips_the_breaker(self, result):
        # Only B (server 1) observes failures; A's breaker stays closed.
        assert result.breaker_tripped[1] > 0
        assert result.breaker_tripped[0] == 0
        assert result.server_breaker_dropped[1] > 0
        assert result.server_breaker_dropped[0] == 0
        # Fail-fast: the breaker absorbed most of the dark window — the
        # brownout ledger itself stays capped near threshold x trips
        # (only arrivals the breaker ADMITTED can be outage drops).
        assert result.server_outage_dropped[1] <= (
            result.breaker_tripped[1] * 4 + 4
        )

    def test_breaker_recloses_and_goodput_recovers(self, result):
        series = result.timeseries
        open_frac = series.breaker_open_fraction[:, 1]
        # Open time concentrates in the brownout windows [3, 5)...
        assert open_frac[3] > 0.2
        # ...and the breaker is fully re-closed well before the end.
        assert open_frac[-1] == 0.0
        assert open_frac[-2] == 0.0
        windows = series.sink_count[:, 0].astype(np.float64)
        pre = windows[:3].mean()
        post = windows[-3:].mean()
        assert post >= 0.9 * pre
        # Whole-run open fraction is the windowed integral re-expressed.
        np.testing.assert_allclose(
            result.breaker_open_fraction[1],
            float(
                (open_frac * series.window_len_s).sum() / result.horizon_s
            ),
            rtol=1e-6,
        )


# ---------------------------------------------------------------------------
# Mechanism tier
# ---------------------------------------------------------------------------


def _shed_model(policy: str, **kwargs) -> EnsembleModel:
    model = EnsembleModel(horizon_s=4.0)
    src = model.source(rate=40.0)
    srv = model.server(service_mean=0.1, concurrency=2, queue_capacity=16)
    snk = model.sink()
    model.connect(src, srv)
    model.connect(srv, snk)
    model.load_shed(policy=policy, **kwargs)
    return model


class TestLoadShed:
    def test_queue_depth_shed_caps_the_queue(self):
        result = run_ensemble(
            _shed_model("queue_depth", threshold=4),
            n_replicas=16,
            seed=3,
            mesh=_mesh(),
            max_events=1024,
        )
        assert sum(result.server_shed_dropped) > 0
        # Shedding at depth 4 (queue cap 16) means queue-full drops
        # cannot happen: the shed fires strictly first.
        assert sum(result.server_dropped) == 0

    def test_utilization_shed(self):
        result = run_ensemble(
            _shed_model("utilization", threshold=1.0),
            n_replicas=16,
            seed=3,
            mesh=_mesh(),
            max_events=1024,
        )
        # threshold=1.0 is "no queueing" admission: every arrival that
        # found all slots busy was shed, so no job ever waited.
        assert sum(result.server_shed_dropped) > 0
        assert result.server_mean_wait_s[0] == 0.0

    def test_priority_fraction_is_exempt(self):
        full = run_ensemble(
            _shed_model("queue_depth", threshold=2),
            n_replicas=16,
            seed=3,
            mesh=_mesh(),
            max_events=1024,
        )
        exempt = run_ensemble(
            _shed_model("queue_depth", threshold=2, priority_fraction=0.5),
            n_replicas=16,
            seed=3,
            mesh=_mesh(),
            max_events=1024,
        )
        # Exempting half the traffic sheds strictly less.
        assert 0 < sum(exempt.server_shed_dropped) < sum(
            full.server_shed_dropped
        )


class TestHedgeBudget:
    def _hedge_model(self, budget: bool) -> EnsembleModel:
        model = EnsembleModel(horizon_s=6.0)
        src = model.source(rate=20.0)
        srv = model.server(
            service_mean=0.1, queue_capacity=64, hedge_delay_s=0.05
        )
        snk = model.sink()
        model.connect(src, srv)
        model.connect(srv, snk)
        if budget:
            # ratio=0 + slow floor refill: the initial burst drains and
            # most hedges are then suppressed.
            model.retry_budget(ratio=0.0, min_per_s=0.5, burst=2.0)
        return model

    def test_budget_suppressed_hedges_are_booked(self):
        kwargs = dict(n_replicas=16, seed=9, mesh=_mesh(), max_events=512)
        free = run_ensemble(self._hedge_model(False), **kwargs)
        capped = run_ensemble(self._hedge_model(True), **kwargs)
        # The budget suppressed launches, and every suppression was
        # booked — hedges forgone show up in srv_budget_dropped instead
        # of silently vanishing.
        assert sum(capped.server_hedged) < sum(free.server_hedged)
        assert sum(capped.server_budget_dropped) > 0
        # Floor refill only: launches are bounded by burst + accrual.
        assert sum(capped.server_hedged) <= 16 * (2 + 0.5 * 6.0) + 16


class TestCompileTimeGating:
    def _plain_model(self):
        model = EnsembleModel(horizon_s=4.0)
        src = model.source(rate=6.0)
        srv = model.server(service_mean=0.05, queue_capacity=8)
        snk = model.sink()
        model.connect(src, srv)
        model.connect(srv, snk)
        return model

    def _step_jaxpr(self, model) -> str:
        compiled = _Compiled(model)
        step = compiled.make_step(float(model.horizon_s), external_u=True)
        key = jnp.zeros((2,), jnp.uint32)
        params = {
            "src_rate": jnp.ones((compiled.nS,), jnp.float32),
            "srv_mean": jnp.ones((compiled.nV,), jnp.float32),
        }
        state = compiled.init_state(key, params)
        u = jnp.full((compiled.n_draws,), 0.5, jnp.float32)
        return str(
            jax.make_jaxpr(lambda s, u_row: step((s, params), u_row))(state, u)
        )

    def test_resilience_free_model_traces_to_identical_jaxpr(self):
        """The acceptance-criteria gating assertion: a model without
        resilience specs compiles to the exact program it compiled to
        before the layer existed (same discipline as telemetry)."""
        assert self._step_jaxpr(self._plain_model()) == self._step_jaxpr(
            self._plain_model()
        )
        # And the resilience-free state carries none of the new leaves.
        compiled = _Compiled(self._plain_model())
        state = compiled.init_state(
            jnp.zeros((2,), jnp.uint32),
            {"src_rate": jnp.ones((1,)), "srv_mean": jnp.ones((1,))},
        )
        assert not any(k.startswith(("brk_", "bud_")) for k in state)
        assert "srv_shed_dropped" not in state

    def test_resilience_state_leaves_checkpoint_roundtrip(self, tmp_path):
        """Full-stack checkpoint: snapshot mid-run, save to npz, resume,
        land on the uninterrupted run's exact counters."""
        def build():
            model = _storm_model(True)
            model.load_shed(policy="queue_depth", threshold=400)
            return model

        kwargs = dict(n_replicas=8, seed=5, mesh=_mesh(), max_events=2048)
        snapshots = []
        full = run_ensemble(
            build(),
            checkpoint_every_s=0.0,
            checkpoint_callback=snapshots.append,
            **kwargs,
        )
        assert snapshots
        for leaf in (
            "brk_state", "brk_fail_t", "brk_fail_idx", "brk_open_t",
            "brk_probes", "brk_tripped", "brk_open_time",
            "srv_breaker_dropped", "srv_shed_dropped",
            "bud_tokens", "bud_last", "srv_budget_dropped",
        ):
            assert leaf in snapshots[0].state, leaf
        path = str(tmp_path / "resilience-ck")
        snapshots[0].save(path)
        from happysim_tpu.tpu import EnsembleCheckpoint

        resumed = run_ensemble(
            build(),
            resume_from=EnsembleCheckpoint.load(path),
            checkpoint_callback=lambda snap: None,
            **kwargs,
        )
        assert resumed.sink_count == full.sink_count
        assert resumed.breaker_tripped == full.breaker_tripped
        assert resumed.server_breaker_dropped == full.server_breaker_dropped
        assert resumed.server_budget_dropped == full.server_budget_dropped
        assert resumed.server_shed_dropped == full.server_shed_dropped
        assert resumed.breaker_open_fraction == full.breaker_open_fraction

    def test_resilience_declines_the_chain_fast_path(self):
        """A resilient model must run the event scan (the closed form
        cannot price breaker windows / shed gates / budget coupling)."""
        from happysim_tpu.tpu.chain import fast_plan
        from happysim_tpu.tpu.model import mm1_model

        base = mm1_model(lam=4.0, mu=9.0, horizon_s=4.0)
        assert fast_plan(base) is not None
        for install in (
            lambda m: (
                setattr(m.servers[0], "deadline_s", 0.5),
                m.circuit_breaker(),
            ),
            lambda m: m.load_shed(policy="queue_depth", threshold=4),
            lambda m: (
                setattr(m.servers[0], "deadline_s", 0.5),
                setattr(m.servers[0], "max_retries", 1),
                m.retry_budget(ratio=0.1),
            ),
        ):
            model = mm1_model(lam=4.0, mu=9.0, horizon_s=4.0)
            install(model)
            assert fast_plan(model) is None

    def test_partitioned_rejects_resilience_by_name(self):
        from happysim_tpu.tpu.partitioned import run_partitioned

        model = EnsembleModel(horizon_s=2.0)
        src = model.source(rate=4.0)
        srv = model.server(service_mean=0.05, deadline_s=0.5, max_retries=1)
        snk = model.sink()
        model.remote(ingress=srv, latency_s=0.5)
        model.connect(src, srv)
        model.connect(srv, snk)
        model.retry_budget(ratio=0.2)
        with pytest.raises(ValueError) as excinfo:
            run_partitioned(model, window_s=0.25)
        message = str(excinfo.value)
        assert "retry_budget" in message
        assert "run_ensemble" in message
