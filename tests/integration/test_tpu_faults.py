"""Device-side chaos engineering vs the host fault twins.

The acceptance contract for the TPU fault subsystem (tpu/faults.py):

1. On an IDENTICAL deterministic schedule, the TPU path and the host
   event loop agree exactly on per-replica drop counts and within 1% on
   mean latency (outage windows ≙ PauseNode; service inflation ≙ a
   windowed InjectLatency-style distribution).
2. With stochastic faults across >= 4096 replicas, the ensemble drop
   count matches the configured rate/duration analytically within 3
   sigma (exponential gaps + exponential durations form a two-state
   Markov chain with closed-form occupation-time moments).
3. The chain fast path provably declines every faulted model (see also
   test_tpu_chain.TestPlan::test_fault_backoff_hedge_loss_disqualify)
   — the scan's accounting, which the closed form cannot produce, shows
   up in the results.
4. Client resilience semantics (retry/backoff budgets, hedging, packet
   loss) obey their analytic contracts.
"""

from __future__ import annotations

import math

import pytest

from happysim_tpu import (
    ConstantLatency,
    FaultSchedule,
    Instant,
    PauseNode,
    Server,
    Simulation,
    Sink,
    Source,
)
from happysim_tpu.core.entity import Entity
from happysim_tpu.core.temporal import Duration
from happysim_tpu.distributions.latency_distribution import LatencyDistribution
from happysim_tpu.tpu.engine import run_ensemble
from happysim_tpu.tpu.faults import duty_cycle
from happysim_tpu.tpu.model import EnsembleModel, FaultSpec

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module")
def mesh():
    import jax

    from happysim_tpu.tpu.mesh import replica_mesh

    return replica_mesh(jax.devices("cpu")[:8])


class Relay(Entity):
    """Pass-through hop (the PauseNode target)."""

    def __init__(self, name, downstream):
        super().__init__(name)
        self.downstream = downstream

    def handle_event(self, event):
        return [self.forward(event, self.downstream)]

    def downstream_entities(self):
        return [self.downstream]


class WindowedInflation(LatencyDistribution):
    """Constant service time, multiplied by ``factor`` inside [start, end)
    — the host twin of FaultSpec(mode="degrade", latency_factor=...)."""

    def __init__(self, base_s: float, factor: float, start: float, end: float):
        self.base_s = base_s
        self.factor = factor
        self.window = (start, end)

    def get_latency(self, time: Instant) -> Duration:
        t = time.to_seconds()
        scale = self.factor if self.window[0] <= t < self.window[1] else 1.0
        return Duration.from_seconds(self.base_s * scale)

    def mean(self) -> Duration:
        return Duration.from_seconds(self.base_s)


class TestDeterministicCrossValidation:
    """Pinned FaultSpec.windows vs the host loop, same schedule."""

    RATE = 10.0
    HORIZON = 100.0
    # Window edges sit mid-gap between the 0.1 s-spaced deterministic
    # arrivals, so float32 time accumulation on the device can never
    # flip an arrival across a boundary the float64 host loop kept.
    WINDOW = (20.05, 40.05)

    def test_outage_drops_match_host_pause_exactly(self, mesh):
        sink = Sink("sink")
        server = Server(
            "srv", service_time=ConstantLatency(0.05), downstream=sink,
            queue_capacity=256,
        )
        relay = Relay("relay", server)
        source = Source.constant(rate=self.RATE, target=relay, stop_after=self.HORIZON)
        faults = FaultSchedule()
        faults.add(PauseNode("relay", start=self.WINDOW[0], end=self.WINDOW[1]))
        sim = Simulation(
            sources=[source],
            entities=[relay, server, sink],
            fault_schedule=faults,
            end_time=Instant.from_seconds(self.HORIZON + 10),
        )
        sim.run()

        model = EnsembleModel(horizon_s=self.HORIZON + 10)
        src = model.source(rate=self.RATE, kind="constant", stop_after_s=self.HORIZON)
        srv = model.server(
            concurrency=1, service_mean=0.05, service="constant",
            queue_capacity=256,
            fault=FaultSpec(windows=(self.WINDOW,), mode="outage"),
        )
        model.connect(src, srv)
        model.connect(srv, model.sink())
        result = run_ensemble(model, n_replicas=64, seed=1, mesh=mesh)

        # Every replica runs the identical deterministic schedule, so the
        # aggregate must be an exact per-replica multiple.
        assert result.server_fault_dropped[0] % result.n_replicas == 0
        per_replica_dropped = result.server_fault_dropped[0] // result.n_replicas
        window_span = self.WINDOW[1] - self.WINDOW[0]
        assert per_replica_dropped == pytest.approx(
            self.RATE * window_span, abs=2
        )
        # Host twin: drops = offered - delivered (PauseNode swallows the
        # in-window deliveries before the server sees them).
        host_offered = int(self.RATE * self.HORIZON)
        host_dropped = host_offered - sink.events_received
        assert per_replica_dropped == host_dropped
        assert result.sink_count[0] // result.n_replicas == sink.events_received
        # Static-outage and queue-full counters stay disjoint from the
        # stochastic-fault ledger.
        assert result.server_outage_dropped[0] == 0
        assert result.server_dropped[0] == 0
        # Mean latency parity (trivially the constant service here, but
        # asserted against the host number, not the constant).
        host_mean = sink.latency_stats().mean_s
        assert result.sink_mean_latency_s[0] == pytest.approx(host_mean, rel=0.01)

    def test_latency_inflation_matches_host_within_1pct(self, mesh):
        """Degrade-mode service inflation vs a host windowed distribution.

        rate 10/s, base service 0.05 s, inflation 3x over [20, 40):
        in-window the server needs 0.15 s per 0.1 s arrival gap, so a
        queue builds and drains — mean latency is dominated by the fault
        dynamics, and both paths are deterministic.
        """
        base, factor = 0.05, 3.0
        sink = Sink("sink")
        server = Server(
            "srv",
            service_time=WindowedInflation(base, factor, *self.WINDOW),
            downstream=sink,
            queue_capacity=1024,
        )
        source = Source.constant(rate=self.RATE, target=server, stop_after=self.HORIZON)
        sim = Simulation(
            sources=[source],
            entities=[server, sink],
            end_time=Instant.from_seconds(self.HORIZON + 10),
        )
        sim.run()
        host_mean = sink.latency_stats().mean_s
        assert host_mean > base * 1.5  # the fault actually dominated

        model = EnsembleModel(horizon_s=self.HORIZON + 10)
        src = model.source(rate=self.RATE, kind="constant", stop_after_s=self.HORIZON)
        srv = model.server(
            concurrency=1, service_mean=base, service="constant",
            queue_capacity=1024,
            fault=FaultSpec(
                windows=(self.WINDOW,), mode="degrade", latency_factor=factor
            ),
        )
        model.connect(src, srv)
        model.connect(srv, model.sink())
        result = run_ensemble(model, n_replicas=64, seed=2, mesh=mesh)

        assert result.sink_count[0] // result.n_replicas == sink.events_received
        assert result.sink_mean_latency_s[0] == pytest.approx(host_mean, rel=0.01)
        # Degrade mode never rejects work.
        assert result.server_fault_dropped[0] == 0


class TestStochasticEnsemble:
    def test_drop_rate_matches_duty_cycle_within_3_sigma(self, mesh):
        """>= 4096 replicas, each with its own Exp-gap/Exp-duration fault
        timeline: total fault drops vs the two-state-Markov closed form.

        Up->down rate r, down->up rate m: stationary dark fraction
        d = r/(r+m) (== duty_cycle), startup correction for a process
        born "up", occupation-time variance 2rm/(r+m)^3 per second.
        """
        r_up, m_down = 0.2, 1.0  # mean up 5 s, mean dark 1 s
        lam, horizon, replicas = 4.0, 30.0, 4096
        model = EnsembleModel(horizon_s=horizon)
        src = model.source(rate=lam, kind="poisson")
        srv = model.server(
            service_mean=0.02, queue_capacity=512,
            fault=FaultSpec(
                rate=r_up, mean_duration_s=1.0 / m_down, max_windows=24
            ),
        )
        model.connect(src, srv)
        model.connect(srv, model.sink())
        result = run_ensemble(model, n_replicas=replicas, seed=3, mesh=mesh)

        d = duty_cycle(r_up, 1.0 / m_down)
        assert d == pytest.approx(r_up / (r_up + m_down))
        rate_sum = r_up + m_down
        expected_dark = d * horizon - d / rate_sum * (
            1.0 - math.exp(-rate_sum * horizon)
        )
        var_dark = 2.0 * r_up * m_down / rate_sum**3 * horizon
        mean_drops = replicas * lam * expected_dark
        # Poisson thinning over a random dark time: Var = lam^2 Var[T] +
        # lam E[T] per replica.
        sigma = math.sqrt(replicas * (lam**2 * var_dark + lam * expected_dark))
        drops = result.server_fault_dropped[0]
        assert abs(drops - mean_drops) < 3.0 * sigma, (
            drops, mean_drops, sigma
        )
        # Replica independence sanity: the same model without faults
        # delivers everything.
        assert result.truncated_replicas == 0

    def test_correlated_trigger_darkens_only_subscribers(self, mesh):
        model = EnsembleModel(horizon_s=60.0)
        model.correlated_outages(rate=0.1, mean_duration_s=2.0, trigger_p=1.0)
        src = model.source(rate=6.0)
        subscribed = model.server(
            service_mean=0.05, queue_capacity=256,
            fault=FaultSpec(correlated=True),
        )
        bystander = model.server(service_mean=0.05, queue_capacity=256)
        router = model.router(policy="round_robin")
        sink = model.sink()
        model.connect(src, router)
        model.connect(router, subscribed)
        model.connect(router, bystander)
        model.connect(subscribed, sink)
        model.connect(bystander, sink)
        result = run_ensemble(model, n_replicas=256, seed=4, mesh=mesh)
        assert result.server_fault_dropped[0] > 0
        assert result.server_fault_dropped[1] == 0

    def test_correlated_trigger_hits_all_subscribers_together(self, mesh):
        """Both subscribers share ONE trigger per replica: their drop
        counts agree far more tightly than independent schedules would
        (round-robin halves the stream symmetrically)."""
        model = EnsembleModel(horizon_s=60.0)
        model.correlated_outages(rate=0.1, mean_duration_s=2.0, trigger_p=0.5)
        src = model.source(rate=8.0, kind="constant")
        a = model.server(
            service_mean=0.05, queue_capacity=256, fault=FaultSpec(correlated=True)
        )
        b = model.server(
            service_mean=0.05, queue_capacity=256, fault=FaultSpec(correlated=True)
        )
        router = model.router(policy="round_robin")
        sink = model.sink()
        model.connect(src, router)
        model.connect(router, a)
        model.connect(router, b)
        model.connect(a, sink)
        model.connect(b, sink)
        result = run_ensemble(model, n_replicas=256, seed=5, mesh=mesh)
        drops = result.server_fault_dropped
        assert drops[0] > 0 and drops[1] > 0
        # Same windows, alternating deterministic arrivals: the split can
        # differ by at most one arrival per window edge.
        assert abs(drops[0] - drops[1]) / max(drops) < 0.05


class TestCapacityDegrade:
    """mode='degrade' with capacity_factor: the cap is on the ACTIVE job
    count (host twin ReduceCapacity), not on which slots are used."""

    def test_capacity_factor_halves_throughput_and_utilization(self, mesh):
        """Full-horizon window, concurrency 4 at factor 0.5: the server
        runs exactly like a 2-slot server under saturating load."""
        horizon, service = 20.0, 0.1
        model = EnsembleModel(horizon_s=horizon)
        src = model.source(rate=40.0, kind="constant", stop_after_s=horizon)
        srv = model.server(
            concurrency=4, service_mean=service, service="constant",
            queue_capacity=1024,
            fault=FaultSpec(
                windows=((0.0, horizon + 1.0),), mode="degrade",
                capacity_factor=0.5,
            ),
        )
        model.connect(src, srv)
        model.connect(srv, model.sink())
        result = run_ensemble(model, n_replicas=32, seed=12, mesh=mesh)
        # 2 usable slots x 1/0.1 per-slot rate = 20/s against 40/s offered.
        completed = result.server_completed[0] / result.n_replicas
        assert completed == pytest.approx(2.0 / service * horizon, rel=0.03)
        # Busy integral sees 2-of-4 slots occupied the whole run.
        assert result.server_utilization[0] == pytest.approx(0.5, rel=0.05)
        # Degrade mode rejects nothing; excess work queues.
        assert result.server_fault_dropped[0] == 0
        assert result.server_mean_queue_len[0] > 10.0

    def test_capacity_factor_zero_freezes_starts_in_window(self, mesh):
        """factor 0.0 over [5, 10): nothing STARTS in-window (running
        work finishes), the backlog queues and drains afterwards —
        nothing is lost."""
        horizon, rate = 30.0, 8.0
        window = (5.05, 10.05)
        model = EnsembleModel(horizon_s=horizon)
        src = model.source(rate=rate, kind="constant", stop_after_s=20.0)
        srv = model.server(
            concurrency=2, service_mean=0.05, service="constant",
            queue_capacity=1024,
            fault=FaultSpec(windows=(window,), mode="degrade", capacity_factor=0.0),
        )
        model.connect(src, srv)
        model.connect(srv, model.sink())
        result = run_ensemble(model, n_replicas=32, seed=13, mesh=mesh)
        offered = int(rate * 20.0)
        # Conservation: the frozen window only delays work.
        assert result.sink_count[0] / result.n_replicas == pytest.approx(
            offered, abs=2
        )
        assert result.server_fault_dropped[0] == 0
        assert result.server_dropped[0] == 0
        # The ~40 in-window arrivals all waited: mean wait well above the
        # no-fault twin's (which is ~0 at this load).
        assert result.server_mean_wait_s[0] > 0.2


class TestResilience:
    def test_retry_budget_accounting_is_exact(self, mesh):
        """A full-horizon outage rejects every attempt: each arrival
        spends its entire budget (max_retries parks) then drops once."""
        horizon, rate, retries = 30.0, 10.0, 2
        model = EnsembleModel(horizon_s=horizon)
        src = model.source(rate=rate, kind="constant", stop_after_s=horizon - 2.0)
        srv = model.server(
            service_mean=0.05, queue_capacity=256,
            fault=FaultSpec(windows=((0.0, horizon + 1.0),), mode="outage"),
            retry_backoff_s=0.01, max_retries=retries,
        )
        model.connect(src, srv)
        model.connect(srv, model.sink())
        result = run_ensemble(model, n_replicas=32, seed=6, mesh=mesh)
        assert result.sink_count[0] == 0
        assert result.server_fault_dropped[0] > 0
        assert result.server_fault_retried[0] == retries * result.server_fault_dropped[0]
        assert result.truncated_replicas == 0

    def test_backoff_retry_recovers_window_rejections(self, mesh):
        """With a finite window, client retries carry rejected arrivals
        past the outage: deliveries strictly beat the no-retry twin."""
        def build(with_retries: bool):
            model = EnsembleModel(horizon_s=60.0)
            src = model.source(rate=8.0, kind="constant", stop_after_s=50.0)
            kwargs = dict(retry_backoff_s=0.5, max_retries=4) if with_retries else {}
            srv = model.server(
                service_mean=0.02, queue_capacity=512,
                fault=FaultSpec(windows=((10.0, 12.0), (30.0, 33.0))),
                **kwargs,
            )
            model.connect(src, srv)
            model.connect(srv, model.sink())
            return model

        retrying = run_ensemble(build(True), n_replicas=64, seed=7, mesh=mesh)
        dropping = run_ensemble(build(False), n_replicas=64, seed=7, mesh=mesh)
        assert retrying.sink_count[0] > dropping.sink_count[0]
        assert retrying.server_fault_dropped[0] < dropping.server_fault_dropped[0]
        # backoff 0.5 * 2^a clears the 2 s window within the budget; the
        # 3 s window needs the later attempts too.
        assert retrying.server_fault_retried[0] > 0

    def test_hedging_cuts_the_tail(self, mesh):
        """Hedged M/M/1: effective service min(S1, d + S2) thins the
        exponential tail, so p99 drops while the mean barely moves."""
        def build(hedge):
            model = EnsembleModel(horizon_s=40.0, warmup_s=5.0)
            src = model.source(rate=4.0)
            srv = model.server(
                service_mean=0.1, queue_capacity=512,
                hedge_delay_s=0.2 if hedge else None,
            )
            model.connect(src, srv)
            model.connect(srv, model.sink())
            return model

        hedged = run_ensemble(build(True), n_replicas=512, seed=8, mesh=mesh)
        plain = run_ensemble(build(False), n_replicas=512, seed=8, mesh=mesh)
        assert hedged.sink_p99_s[0] < plain.sink_p99_s[0]
        assert hedged.server_hedge_wins[0] <= hedged.server_hedged[0]
        # P(S > d) = exp(-d/mean) = exp(-2) of starts launch a hedge.
        starts = hedged.server_completed[0]
        frac = hedged.server_hedged[0] / starts
        assert frac == pytest.approx(math.exp(-2.0), rel=0.1)
        assert plain.server_hedged == [0]

    def test_packet_loss_rate_within_3_sigma(self, mesh):
        p, rate, stop = 0.2, 10.0, 28.0
        model = EnsembleModel(horizon_s=30.0)
        src = model.source(rate=rate, kind="constant", stop_after_s=stop)
        srv = model.server(service_mean=0.001, service="constant", queue_capacity=256)
        model.connect(src, srv, loss_p=p)
        model.connect(srv, model.sink())
        result = run_ensemble(model, n_replicas=256, seed=9, mesh=mesh)
        # Conservation pins the crossing count exactly (service drains
        # well before the horizon): every crossing either vanished or
        # reached the sink. The loss count is then Binomial(crossings, p).
        crossings = result.network_lost + result.sink_count[0]
        # ~rate*stop per replica (the final tick can round off the stop).
        assert crossings / result.n_replicas == pytest.approx(rate * stop, abs=2)
        expected = crossings * p
        sigma = math.sqrt(crossings * p * (1.0 - p))
        assert abs(result.network_lost - expected) < 3.0 * sigma

    def test_loss_window_bounds_the_bernoulli(self, mesh):
        # Window edges mid-gap between the 0.1 s-spaced arrivals: exactly
        # 50 in-window crossings per replica, immune to float32 rounding.
        p, rate, window = 0.5, 10.0, (5.05, 10.05)
        model = EnsembleModel(horizon_s=30.0)
        src = model.source(rate=rate, kind="constant", stop_after_s=28.0)
        srv = model.server(service_mean=0.001, service="constant", queue_capacity=256)
        model.connect(src, srv, loss_p=p, loss_window=window)
        model.connect(srv, model.sink())
        result = run_ensemble(model, n_replicas=256, seed=10, mesh=mesh)
        in_window = 256 * int(rate * (window[1] - window[0]))
        expected = in_window * p
        sigma = math.sqrt(in_window * p * (1.0 - p))
        assert abs(result.network_lost - expected) < 3.0 * sigma


class TestScanFallback:
    def test_faulted_chain_shape_runs_on_the_event_scan(self, mesh):
        """An otherwise chain-eligible M/M/1 with a fault spec must fall
        back: the fault ledger (which the closed form cannot produce) is
        populated and the analytic M/M/1 mean still holds outside the
        windows' influence at low duty."""
        from happysim_tpu.tpu.chain import fast_plan

        model = EnsembleModel(horizon_s=40.0, warmup_s=10.0)
        src = model.source(rate=8.0)
        srv = model.server(
            service_mean=0.05, queue_capacity=512,
            fault=FaultSpec(rate=0.05, mean_duration_s=0.5),
        )
        model.connect(src, srv)
        model.connect(srv, model.sink())
        assert fast_plan(model) is None
        result = run_ensemble(model, n_replicas=128, seed=11, mesh=mesh)
        assert result.server_fault_dropped[0] > 0
        assert result.simulated_events > 0


class TestRetryCounterDiscipline:
    """Retry counters must only book retries that actually re-arrived:
    a retry that found every transit register occupied vanishes into
    tr_dropped and must NOT count as retried (the has_room discipline
    of the legacy immediate re-enqueue path, applied to backoff)."""

    def test_transit_overflow_not_counted_as_fault_retried(self, mesh):
        # Deterministic: constant arrivals at t=1..10 all inside the
        # pinned outage window; backoff 1000s (jitter 0) parks retries
        # far past the horizon, so the 2 transit registers never free —
        # exactly 2 retries park per replica, the other 8 overflow.
        model = EnsembleModel(horizon_s=10.0, transit_capacity=2)
        src = model.source(rate=1.0, kind="constant")
        srv = model.server(
            concurrency=1,
            service_mean=0.05,
            fault=FaultSpec(windows=((0.0, 100.0),), mode="outage"),
            retry_backoff_s=1000.0,
            max_retries=5,
        )
        model.connect(src, srv)
        model.connect(srv, model.sink())
        result = run_ensemble(model, n_replicas=8, seed=0, mesh=mesh)

        n = result.n_replicas
        assert result.server_fault_retried[0] == 2 * n, (
            "fault_retried must count only PARKED retries (2 transit "
            "slots), not every rejection"
        )
        assert result.transit_dropped[0] == 8 * n
        # Rejections with retry budget left are never terminal drops.
        assert result.server_fault_dropped[0] == 0
        assert result.sink_count[0] == 0
        assert result.truncated_replicas == 0
