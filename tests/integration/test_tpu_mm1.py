"""TPU ensemble executor vs analytic theory and vs the Python host executor.

The cross-backend equivalence oracle (SURVEY.md §4): because the two
backends use different RNGs (Python `random` vs threefry), parity is
statistical — both must agree with the analytic M/M/1 law and with each
other within Monte-Carlo tolerance.
"""

import jax
import jax.numpy as jnp
import pytest

from happysim_tpu import ExponentialLatency, Instant, Server, Simulation, Sink, Source
from happysim_tpu.tpu import run_mm1_ensemble
from happysim_tpu.tpu.mesh import replica_mesh, replica_sharding
from happysim_tpu.tpu.mm1 import _mm1_stats


class TestAnalytic:
    def test_mean_wait_within_one_percent(self, cpu_mesh):
        result = run_mm1_ensemble(
            lam=8.0, mu=10.0, n_replicas=8192, n_customers=4096, seed=0, mesh=cpu_mesh
        )
        assert result.wait_error_rel < 0.01
        assert result.analytic_wait_s == pytest.approx(0.4)

    def test_sojourn_includes_service(self, cpu_mesh):
        result = run_mm1_ensemble(
            lam=8.0, mu=10.0, n_replicas=4096, n_customers=4096, seed=1, mesh=cpu_mesh
        )
        # E[T] = Wq + 1/mu = 0.5
        assert result.mean_sojourn_s == pytest.approx(0.5, rel=0.03)

    def test_different_utilization(self, cpu_mesh):
        result = run_mm1_ensemble(
            lam=5.0, mu=10.0, n_replicas=4096, n_customers=2048, seed=2, mesh=cpu_mesh
        )
        # rho=0.5 -> Wq = 0.1
        assert result.mean_wait_s == pytest.approx(0.1, rel=0.05)

    def test_unstable_queue_rejected(self, cpu_mesh):
        with pytest.raises(ValueError):
            run_mm1_ensemble(lam=10.0, mu=10.0, mesh=cpu_mesh)

    def test_replicas_padded_to_mesh(self, cpu_mesh):
        result = run_mm1_ensemble(
            lam=8.0, mu=10.0, n_replicas=1001, n_customers=128, seed=3, mesh=cpu_mesh
        )
        assert result.n_replicas % 8 == 0
        assert result.n_replicas >= 1001


class TestShardingInvariance:
    def test_single_vs_eight_device_mesh_same_result(self, cpu_devices):
        """Threefry is counter-based: lane streams are identical regardless
        of mesh layout, so the ensemble mean matches bit-for-bit up to
        reduction order."""
        mesh1 = replica_mesh(cpu_devices[:1])
        mesh8 = replica_mesh(cpu_devices[:8])
        r1 = run_mm1_ensemble(
            lam=8.0, mu=10.0, n_replicas=2048, n_customers=512, seed=7, mesh=mesh1
        )
        r8 = run_mm1_ensemble(
            lam=8.0, mu=10.0, n_replicas=2048, n_customers=512, seed=7, mesh=mesh8
        )
        assert r1.mean_wait_s == pytest.approx(r8.mean_wait_s, rel=1e-5)

    def test_seed_determinism(self, cpu_mesh):
        a = run_mm1_ensemble(n_replicas=1024, n_customers=256, seed=9, mesh=cpu_mesh)
        b = run_mm1_ensemble(n_replicas=1024, n_customers=256, seed=9, mesh=cpu_mesh)
        assert a.mean_wait_s == b.mean_wait_s

    def test_seed_variation(self, cpu_mesh):
        a = run_mm1_ensemble(n_replicas=1024, n_customers=256, seed=1, mesh=cpu_mesh)
        b = run_mm1_ensemble(n_replicas=1024, n_customers=256, seed=2, mesh=cpu_mesh)
        assert a.mean_wait_s != b.mean_wait_s


class TestCrossBackendEquivalence:
    """Python heap executor and XLA ensemble executor agree statistically."""

    def test_mean_queue_wait_matches_host_executor(self, cpu_mesh):
        lam, mu = 8.0, 10.0
        # Host executor: measure queue wait = sojourn - service.
        sink = Sink()
        server = Server(
            "server",
            service_time=ExponentialLatency(1.0 / mu, seed=101),
            downstream=sink,
        )
        source = Source.poisson(rate=lam, target=server, stop_after=500.0, seed=100)
        sim = Simulation(
            sources=[source],
            entities=[server, sink],
            end_time=Instant.from_seconds(1000),
        )
        sim.run()
        host_sojourn = sum(sink.latencies_s) / len(sink.latencies_s)

        tpu = run_mm1_ensemble(
            lam=lam, mu=mu, n_replicas=8192, n_customers=4096, seed=5, mesh=cpu_mesh
        )
        # Both estimate E[T]; host run is a single replica so give it slack.
        assert tpu.mean_sojourn_s == pytest.approx(host_sojourn, rel=0.2)
        # And both near the analytic law.
        assert tpu.mean_sojourn_s == pytest.approx(1.0 / (mu - lam), rel=0.03)
        assert host_sojourn == pytest.approx(1.0 / (mu - lam), rel=0.2)
